"""Headline benchmark: regex scan throughput (GB/s per chip).

Prints exactly ONE JSON line on stdout:
    {"metric": "...", "value": N, "unit": "GB/s", "vs_baseline": N}

Measures the flagship path — the Pallas shift-and literal scan — on a
synthetic ~80-byte-line corpus resident in HBM (the north star's framing:
">= 10 GB/s/chip regex scan over HBM-resident file shards", BASELINE.json).
vs_baseline is value / 10.0, the ratio against that 10 GB/s target (the
reference itself publishes no numbers — BASELINE.md).

Timing uses the slope method: the scan is chained r times inside one jit
(fori_loop) ending in an on-device match-count reduction, and per-pass time
is (t(r2) - t(r1)) / (r2 - r1).  This cancels both dispatch/fetch latency
(substantial through a tunneled device) and the constant overheads, and the
reduction forces full execution.  Falls back to the native CPU scanner (same
tables) if no accelerator is reachable within the watchdog window, so the
bench always emits its line.
"""

from __future__ import annotations

import functools
import json
import sys
import time

import numpy as np

CORPUS_BYTES = 256 * 1024 * 1024
PATTERN = "needle"
TARGET_GBPS = 10.0  # north-star baseline (BASELINE.json)
TPU_WATCHDOG_S = int(__import__("os").environ.get("BENCH_WATCHDOG_S", "900"))


def make_corpus(n: int) -> bytes:
    rng = np.random.default_rng(0)
    data = rng.integers(32, 127, size=n, dtype=np.uint8)
    data[rng.integers(0, n, size=n // 80)] = 0x0A  # ~80-byte lines
    needle = np.frombuffer(PATTERN.encode(), np.uint8)
    for p in rng.integers(0, n - 16, size=1000):
        data[p : p + len(needle)] = needle
    return data.tobytes()


def bench_tpu(data: bytes) -> float:
    import jax
    import jax.numpy as jnp

    from distributed_grep_tpu.models.shift_and import try_compile_shift_and
    from distributed_grep_tpu.ops import layout as layout_mod
    from distributed_grep_tpu.ops import pallas_scan

    model = try_compile_shift_and(PATTERN)
    lay = layout_mod.choose_layout(
        len(data),
        target_lanes=8192,
        min_chunk=512,
        lane_multiple=pallas_scan.LANES_PER_BLOCK,
        chunk_multiple=512,
    )
    arr = layout_mod.to_device_array(data, lay)
    arr3 = arr.reshape(lay.chunk, -1, 128)
    # 512 extra '\n' pad rows: each loop iteration scans a window starting at
    # a DIFFERENT row offset (i-dependent dynamic_slice), so XLA cannot hoist
    # the scan out of the fori_loop as loop-invariant — which it provably did
    # before (5 chained passes timed identical to 1).
    pad = np.full((512,) + arr3.shape[1:], 0x0A, dtype=np.uint8)
    dev = jax.device_put(jnp.asarray(np.concatenate([arr3, pad], axis=0)))
    sym_ranges = tuple(tuple(r) for r in model.sym_ranges)
    lane_blocks = lay.lanes // pallas_scan.LANES_PER_BLOCK

    @functools.partial(jax.jit, static_argnames=("reps",))
    def chained(d, reps):
        def body(i, acc):
            window = jax.lax.dynamic_slice_in_dim(d, (i % 2) * 512, lay.chunk, axis=0)
            words = pallas_scan._shift_and_pallas(
                window,
                sym_ranges=sym_ranges,
                match_bit=int(model.match_bit),
                chunk=lay.chunk,
                lane_blocks=lane_blocks,
                interpret=False,
            )
            return acc + jnp.count_nonzero(words)
        return jax.lax.fori_loop(0, reps, body, jnp.int32(0))

    r1, r2 = 2, 10
    c1 = int(chained(dev, r1))  # compile + warm
    c2 = int(chained(dev, r2))
    # Odd iterations drop each stripe's first 512 bytes (the shifted window),
    # losing ~512/chunk of the 1000 planted needles — counts are near, not
    # exactly, 1000/pass.  Both runs see the same 1:1 full/shifted window mix,
    # so per-pass counts must still agree exactly across rep counts.
    assert c2 * r1 == c1 * r2, f"per-pass count drift: {c1}/{r1} vs {c2}/{r2}"
    assert 900 * r1 <= c1 <= 1100 * r1, f"match count off: {c1} for {r1} passes"

    def timed(reps, iters=3):
        t0 = time.perf_counter()
        for _ in range(iters):
            int(chained(dev, reps))
        return (time.perf_counter() - t0) / iters

    d1, d2 = timed(r1), timed(r2)
    per_pass = (d2 - d1) / (r2 - r1)
    print(f"bench: slope timings {d1=:.4f}s ({r1} passes) {d2=:.4f}s ({r2} passes)",
          file=sys.stderr)
    if per_pass <= 0:
        raise RuntimeError(f"non-positive slope: {d1=:.4f} {d2=:.4f}")
    print(f"bench: tpu pallas shift-and {len(data)/1e9/per_pass:.2f} GB/s "
          f"({per_pass*1e3:.1f} ms/pass, {c1} matches)", file=sys.stderr)
    return len(data) / 1e9 / per_pass


def bench_cpu_fallback(data: bytes) -> float:
    from distributed_grep_tpu.utils import native

    t0 = time.perf_counter()
    hits = native.literal_scan(data, PATTERN.encode())
    dt = time.perf_counter() - t0
    print(f"bench: CPU-fallback native literal scan {len(data)/1e9/dt:.2f} GB/s "
          f"({len(hits)} matches)", file=sys.stderr)
    return len(data) / 1e9 / dt


def _tpu_child() -> int:
    """Runs the accelerator bench in a child process (the parent enforces a
    wall-clock watchdog — a wedged device tunnel blocks inside C where
    signals can't interrupt, so only a process boundary is safe)."""
    import jax

    data = make_corpus(CORPUS_BYTES)
    backend = jax.devices()[0].platform
    print(f"bench: backend={backend}", file=sys.stderr)
    value = bench_tpu(data)
    print(f"RESULT_GBPS {value:.6f}")
    return 0


def main() -> int:
    if "--tpu-child" in sys.argv:
        return _tpu_child()

    import subprocess

    value = None
    metric = "regex_scan_throughput_per_chip_literal"
    try:
        proc = subprocess.run(
            [sys.executable, __file__, "--tpu-child"],
            capture_output=True,
            text=True,
            timeout=TPU_WATCHDOG_S,
        )
        sys.stderr.write(proc.stderr[-2000:])
        for line in proc.stdout.splitlines():
            if line.startswith("RESULT_GBPS "):
                value = float(line.split()[1])
        if proc.returncode != 0 and value is None:
            print(f"bench: accelerator child failed rc={proc.returncode}", file=sys.stderr)
    except subprocess.TimeoutExpired:
        print(f"bench: accelerator child exceeded {TPU_WATCHDOG_S}s watchdog "
              "(wedged device tunnel?); falling back to CPU", file=sys.stderr)

    if value is None:
        metric = "regex_scan_throughput_per_chip_literal_cpu_fallback"
        value = bench_cpu_fallback(make_corpus(CORPUS_BYTES))

    print(json.dumps({
        "metric": metric,
        "value": round(value, 3),
        "unit": "GB/s",
        "vs_baseline": round(value / TARGET_GBPS, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())

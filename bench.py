"""Headline benchmark: regex scan throughput (GB/s per chip).

Prints exactly ONE JSON line on stdout:
    {"metric": "...", "value": N, "unit": "GB/s", "vs_baseline": N}

Measures the flagship path — BASELINE.md config 1: the Pallas shift-and
literal scan with the engine's rare-class device filter, on an
enwik8-shaped words corpus resident in HBM (the north star's framing:
">= 10 GB/s/chip regex scan over HBM-resident file shards", BASELINE.json).
vs_baseline is value / 10.0, the ratio against that 10 GB/s target (the
reference itself publishes no numbers — BASELINE.md).

Timing uses the slope method: the scan is chained r times inside one jit
(fori_loop) ending in an on-device match-count reduction, and per-pass time
is (t(r2) - t(r1)) / (r2 - r1).  This cancels both dispatch/fetch latency
(substantial through a tunneled device) and the constant overheads, and the
reduction forces full execution.  Falls back to the native CPU scanner (same
tables) if no accelerator is reachable within the watchdog window, so the
bench always emits its line.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

# Runnable as `python benchmarks/...` / `python bench.py` from anywhere:
# the repo root joins the FRONT of sys.path unconditionally, so the
# checkout being benchmarked always wins over any installed copy of the
# package.  (Repeated per script by necessity — a shared helper could not
# be imported before the path is fixed.)
_root = Path(__file__).resolve().parent
if not (_root / "distributed_grep_tpu").is_dir():
    _root = _root.parent
if (_root / "distributed_grep_tpu").is_dir():
    sys.path.insert(0, str(_root))
import time

import numpy as np

def _env_int(name: str, default: int, lo: int) -> int:
    """Env override that can never break the one-JSON-line contract: a
    malformed or absurd value silently keeps the default/floor."""
    try:
        v = int(__import__("os").environ.get(name, default))
    except ValueError:
        return default
    return max(lo, v)


CORPUS_BYTES = _env_int("BENCH_CORPUS_BYTES", 64 * 1000 * 1000, lo=10_000)
# default == the baseline_configs suite size:
# BASELINE.md row 1 (218-261 GB/s band) was measured at this working-set size,
# and the rate is size-dependent (~250 at 32 MB, ~175-195 at 256 MB), so the
# headline must match the methodology it is compared against
PATTERN = "volcano"  # BASELINE.md config 1's pattern (the flagship row)
TARGET_GBPS = 10.0  # north-star baseline (BASELINE.json)
TPU_WATCHDOG_S = _env_int("BENCH_WATCHDOG_S", 900, lo=1)
# The axon tunnel drops for multi-minute windows (observed 2026-07-31:
# first fast `Connection Failed` errors, later black-hole hangs).  A
# single-shot bench run that lands in such a window would record the CPU
# fallback — a false ~500x "regression" against the device kernel's real
# rate.  So the accelerator is first health-checked by a cheap probe child
# (tiny device_put round trip), retried across a budget window; the full
# bench child launches AT MOST ONCE, after a probe succeeds.  Fast-error
# outages fall back quicker than the old 900 s single shot; transient
# outages get retried instead of misrecorded; a deterministic bench
# failure on a healthy device still falls through after one attempt.
PROBE_WATCHDOG_S = _env_int("BENCH_PROBE_WATCHDOG_S", 120, lo=1)
PROBE_BUDGET_S = _env_int("BENCH_PROBE_BUDGET_S", 600, lo=0)

# English-like filler (enwik/WET-shaped words+spaces+newlines — the same
# text family as benchmarks/baseline_configs config 1, so the headline and
# the config suite measure the same workload).  PATTERN is deliberately
# absent from the vocabulary; occurrences are injected, keeping the match
# count a calibrated sanity band.
_WORDS = (
    "the of and to in a is that for it as was with be by on not he his but "
    "at are this have from or had they you which one were her all she there "
    "would their we him been has when who will more no if out so said what "
    "up its about into than them can only other new some could time these "
    "two may then do first any my now such like our over man me even most "
    "made after also did many before must through years where much your way "
    "well down should because each just those people how too little state "
    "good very make world still own see men work long get here between both "
    "life being under never day same another know while last might us great "
    "old year off come since against go came right used take three"
).split()


def make_corpus(n: int) -> bytes:
    rng = np.random.default_rng(0)
    out, size = [], 0
    while size < n:
        k = int(rng.integers(3, 24))
        line = b" ".join(
            _WORDS[i].encode() for i in rng.integers(0, len(_WORDS), k)
        )
        out.append(line)
        size += len(line) + 1
    data = np.frombuffer(b"\n".join(out)[:n], dtype=np.uint8).copy()
    needle = np.frombuffer(PATTERN.encode(), np.uint8)
    for p in rng.integers(0, n - 16, size=1000):
        data[p : p + len(needle)] = needle
    return data.tobytes()


def bench_tpu(data: bytes) -> float:
    import statistics

    from distributed_grep_tpu.models.shift_and import (
        filtered_for_device,
        try_compile_shift_and,
    )
    from distributed_grep_tpu.utils.slope import pallas_shift_and_setup, slope_per_pass

    model = try_compile_shift_and(PATTERN)
    # Measure the kernel the ENGINE actually dispatches for this workload:
    # the rare-class device filter (fewer compares — the kernel's ALU
    # bottleneck) when the pattern has rare byte classes, with the host
    # span-confirm pass restoring exactness downstream (ops/engine.py
    # _sa_filtered).  For 'volcano' this is the 3-check filter of
    # BASELINE.md config 1.
    kernel_model = filtered_for_device(model) or model
    print(f"bench: kernel checks {sum(1 for r in kernel_model.sym_ranges if r)}"
          f"/{len(model.sym_ranges)} symbol classes", file=sys.stderr)
    # The 512 '\n' pad rows let each chained pass scan an i-dependent window —
    # required by the slope harness's anti-hoisting scheme (utils/slope.py).
    # Odd windows drop each stripe's first 512 bytes, losing ~512/chunk of
    # the 1000 planted needles, hence the count band below.
    dev, chunk, pad_rows, scan = pallas_shift_and_setup(data, kernel_model)
    # The tunneled device adds ~100 ms of run-to-run jitter.  Two defenses
    # (VERDICT r3 item 5 — BENCH_r03 underquoted the measured kernel 28%):
    # chains long enough that the rep delta dominates the jitter (the
    # harness auto-escalates r2 until it does), and the median of 5
    # INDEPENDENT slope draws (each itself a median of 3 timed sections) —
    # one compile, so later draws cost only their run time.
    draws = []
    for i in range(5):
        per_pass, per_count = slope_per_pass(
            dev, chunk, pad_rows, scan, r1=8, r2=104, count_range=(900, 1100),
            measurements=3,
        )
        print(f"bench: draw {i}: {len(data)/1e9/per_pass:.2f} GB/s "
              f"({per_pass*1e3:.2f} ms/pass, {per_count:.0f} matches/pass)",
              file=sys.stderr)
        draws.append(per_pass)
    per_pass = statistics.median(draws)
    print(f"bench: tpu pallas shift-and {len(data)/1e9/per_pass:.2f} GB/s "
          f"(median of {len(draws)} slope draws)", file=sys.stderr)
    return len(data) / 1e9 / per_pass


def bench_cpu_fallback(data: bytes) -> float:
    from distributed_grep_tpu.utils import native

    t0 = time.perf_counter()
    hits = native.literal_scan(data, PATTERN.encode())
    dt = time.perf_counter() - t0
    print(f"bench: CPU-fallback native literal scan {len(data)/1e9/dt:.2f} GB/s "
          f"({len(hits)} matches)", file=sys.stderr)
    return len(data) / 1e9 / dt


def _probe_child() -> int:
    """Cheap accelerator liveness check in a disposable process: resolve the
    default backend and push one tiny array through it.  devices() alone is
    not enough — a black-holed tunnel can answer discovery from cache while
    real transfers hang (ops/engine.py's deep probe learned the same)."""
    import os

    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    if dev.platform == "cpu":
        # Three distinct situations resolve to a cpu backend: the caller
        # explicitly requested cpu FIRST (deterministic — stop probing);
        # no accelerator plugin is registered at all (deterministic); or a
        # registered accelerator plugin failed to initialize and jax fell
        # back (observed during the tunnel's fast-`Connection Failed`
        # phase — transient, worth retrying).
        plats = [p.strip()
                 for p in os.environ.get("JAX_PLATFORMS", "").split(",")
                 if p.strip()]
        try:  # registered non-cpu backend factories (internal API; any
            # failure to read it counts as "none registered" — fail fast)
            from jax._src import xla_bridge as _xb

            accel = [k for k in _xb._backend_factories
                     if k not in ("cpu", "interpreter")]
        except Exception:
            accel = []
        if (plats and plats[0] == "cpu") or not accel:
            print("PROBE_CPU")
        else:
            print(f"PROBE_FALLBACK_CPU {accel}")
        return 1
    x = jax.device_put(jnp.arange(8, dtype=jnp.int32), dev)
    if int(x.sum()) != 28:
        return 1
    print(f"PROBE_OK {dev.platform}")
    return 0


def _tpu_child() -> int:
    """Runs the accelerator bench in a child process (the parent enforces a
    wall-clock watchdog — a wedged device tunnel blocks inside C where
    signals can't interrupt, so only a process boundary is safe)."""
    import jax

    data = make_corpus(CORPUS_BYTES)
    backend = jax.devices()[0].platform
    print(f"bench: backend={backend}", file=sys.stderr)
    value = bench_tpu(data)
    print(f"RESULT_GBPS {value:.6f}")
    return 0


def _run_child(arg: str, timeout_s: int) -> tuple[str, int] | None:
    """Run this script as a child with `arg`; (stdout, rc), or None on
    watchdog expiry.  A wedged tunnel blocks inside C where signals can't
    interrupt, so only a process boundary is a safe timeout."""
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, __file__, arg],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return None
    sys.stderr.write(proc.stderr[-2000:])
    return proc.stdout, proc.returncode


def main() -> int:
    if "--tpu-child" in sys.argv:
        return _tpu_child()
    if "--probe-child" in sys.argv:
        return _probe_child()

    value = None
    metric = "regex_scan_throughput_per_chip_literal"
    deadline = time.monotonic() + PROBE_BUDGET_S
    attempt = 0
    probed_ok = False
    while True:
        attempt += 1
        out = _run_child("--probe-child", PROBE_WATCHDOG_S)
        if out is None:
            print(f"bench: probe {attempt} hung past {PROBE_WATCHDOG_S}s "
                  "(black-holed device tunnel?)", file=sys.stderr)
        elif "PROBE_OK" in out[0]:
            probed_ok = True
            break
        elif "PROBE_FALLBACK_CPU" in out[0]:
            print(f"bench: probe {attempt}: accelerator plugin fell back to "
                  "cpu (transient init failure?); retrying", file=sys.stderr)
        elif "PROBE_CPU" in out[0]:
            print("bench: cpu backend requested (or no accelerator plugin "
                  "registered); nothing to probe", file=sys.stderr)
            break
        else:
            # jax import failure / crash: deterministic, retrying can't help
            print(f"bench: probe {attempt} failed rc={out[1]}; "
                  "falling back to CPU", file=sys.stderr)
            break
        if time.monotonic() >= deadline:
            print(f"bench: no healthy accelerator within {PROBE_BUDGET_S}s "
                  "probe budget; falling back to CPU", file=sys.stderr)
            break
        time.sleep(20)

    if probed_ok:
        print(f"bench: probe {attempt} ok; running accelerator bench",
              file=sys.stderr)
        bench_out = _run_child("--tpu-child", TPU_WATCHDOG_S)
        if bench_out is None:
            print(f"bench: accelerator child exceeded {TPU_WATCHDOG_S}s "
                  "watchdog (tunnel dropped mid-run?); falling back to CPU",
                  file=sys.stderr)
        else:
            for line in bench_out[0].splitlines():
                if line.startswith("RESULT_GBPS "):
                    value = float(line.split()[1])
            if value is None:
                print(f"bench: accelerator child failed rc={bench_out[1]}; "
                      "falling back to CPU", file=sys.stderr)

    if value is None:
        metric = "regex_scan_throughput_per_chip_literal_cpu_fallback"
        value = bench_cpu_fallback(make_corpus(CORPUS_BYTES))

    print(json.dumps({
        "metric": metric,
        "value": round(value, 3),
        "unit": "GB/s",
        "vs_baseline": round(value / TARGET_GBPS, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())

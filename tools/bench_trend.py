#!/usr/bin/env python3
"""Render the BENCH_r*.json trajectory as one JSON line + a markdown table.

The driver snapshots ``bench.py``'s one-JSON-line contract into
``BENCH_r<NN>.json`` per round ({n, cmd, rc, tail, parsed}); this tool
folds them into the round-over-round throughput trajectory an operator
(or a PR description) wants at a glance:

    python tools/bench_trend.py            # JSON line, then the table
    python tools/bench_trend.py --json     # the JSON line only
    make trend

Per round: the parsed headline GB/s (cpu-fallback rounds flagged — their
numbers are NOT chip numbers), and the per-pass wall parsed from the
bench tail's "N ms/pass" marker when present.  NO gating and no
thresholds on purpose: this box's background load swings ~2x, so the
trajectory is a report, not a check (BASELINE.md's interleaved A/B
medians are the honest comparisons).

When the newest daemon.jsonl under ``--root`` (recursively, mtime
picks) carries ``promoted`` lines, a ``failover`` summary rides along —
the fleet-timeline samples behind ``dgrep_daemon_failover_seconds``.
Same reporting-only stance.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")
_MS_RE = re.compile(r"\(([\d.]+) ms/pass")


def load_rounds(root: Path) -> list[dict]:
    rounds: list[dict] = []
    for path in sorted(root.glob("BENCH_r*.json")):
        m = _ROUND_RE.search(path.name)
        if m is None:
            continue
        try:
            doc = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed") or {}
        tail = doc.get("tail") or ""
        if not parsed:
            # older snapshots: fall back to the last JSON line in the tail
            for line in reversed(tail.splitlines()):
                try:
                    parsed = json.loads(line)
                    break
                except ValueError:
                    continue
        ms = _MS_RE.findall(tail)
        metric = str(parsed.get("metric", ""))
        row = {
            "round": int(m.group(1)),
            "gbps": parsed.get("value"),
            "unit": parsed.get("unit", ""),
            "metric": metric,
            "cpu_fallback": "cpu_fallback" in metric,
            "rc": doc.get("rc"),
        }
        if ms:
            row["ms_per_pass"] = float(ms[-1])
        rounds.append(row)
    return rounds


def failover_samples(root: Path) -> dict | None:
    """Tail the newest daemon.jsonl under ``root`` for failover_s
    samples (the ``promoted`` lines the round-19 histogram observes).
    None when no work root with promotions is around — the trend line
    keeps its pre-round-19 shape."""
    newest = None
    for path in root.rglob("daemon.jsonl"):
        try:
            mt = path.stat().st_mtime
        except OSError:
            continue
        if newest is None or mt > newest[0]:
            newest = (mt, path)
    if newest is None:
        return None
    samples: list[float] = []
    steals = 0
    try:
        for line in newest[1].read_text(encoding="utf-8").splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail — replay-tolerant, like the runtime
            if rec.get("kind") == "lease_steal":
                steals += 1
            elif rec.get("kind") == "promoted":
                f = (rec.get("payload") or {}).get("failover_s")
                if f is not None:
                    samples.append(float(f))
    except OSError:
        return None
    if not samples and not steals:
        return None
    return {
        "source": str(newest[1]),
        "promotions": len(samples),
        "lease_steals": steals,
        "max_failover_s": max(samples, default=None),
        "last_failover_s": samples[-1] if samples else None,
    }


def result_store_footprint(root: Path) -> dict | None:
    """Size up the newest persisted result-cache store under ``root``
    (the round-20 ``<work_root>/results/`` dirs): entry count + bytes.
    Reporting only, like the failover rider — None keeps the trend line
    its pre-round-20 shape when no store exists."""
    newest = None
    for path in root.rglob("results"):
        if not path.is_dir():
            continue
        try:
            mt = path.stat().st_mtime_ns
        except OSError:
            continue
        if newest is None or mt > newest[0]:
            newest = (mt, path)
    if newest is None:
        return None
    entries = 0
    total = 0
    try:
        for e in newest[1].glob("*.res"):
            try:
                total += e.stat().st_size
            except OSError:
                continue
            entries += 1
    except OSError:
        return None
    if not entries:
        return None
    return {
        "source": str(newest[1]),
        "entries": entries,
        "bytes": total,
    }


def markdown_table(rounds: list[dict]) -> str:
    lines = ["| round | GB/s | ms/pass | notes |",
             "| --- | --- | --- | --- |"]
    for r in rounds:
        notes = []
        if r["cpu_fallback"]:
            notes.append("cpu fallback (tunnel down)")
        if r.get("rc"):
            notes.append(f"rc={r['rc']}")
        gbps = "?" if r["gbps"] is None else f"{r['gbps']:g}"
        ms = r.get("ms_per_pass")
        lines.append(
            f"| r{r['round']:02d} | {gbps} | "
            f"{'-' if ms is None else f'{ms:g}'} | {', '.join(notes)} |"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="BENCH_r*.json round-over-round trajectory")
    p.add_argument("--root", default=".",
                   help="directory holding BENCH_r*.json (default: cwd)")
    p.add_argument("--json", action="store_true", dest="json_only",
                   help="print only the one JSON line")
    args = p.parse_args(argv)

    rounds = load_rounds(Path(args.root))
    if not rounds:
        print(f"error: no BENCH_r*.json under {args.root}", file=sys.stderr)
        return 1
    chip = [r for r in rounds if not r["cpu_fallback"]
            and r["gbps"] is not None]
    doc = {
        "rounds": rounds,
        "latest_gbps": rounds[-1]["gbps"],
        "best_chip_gbps": max((r["gbps"] for r in chip), default=None),
    }
    failover = failover_samples(Path(args.root))
    if failover is not None:
        doc["failover"] = failover
    results = result_store_footprint(Path(args.root))
    if results is not None:
        doc["result_store"] = results
    print(json.dumps(doc, sort_keys=True))
    if not args.json_only:
        print(markdown_table(rounds))
    return 0


if __name__ == "__main__":
    sys.exit(main())

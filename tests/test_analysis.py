"""Tier-1 gate + per-rule fixtures for the project invariant checker
(distributed_grep_tpu/analysis/).

Two directions per rule: it must FIRE on a known-bad snippet (no false
negatives — a rule that silently stopped matching is worse than no rule)
and stay SILENT on this repo with an EMPTY baseline (no false positives —
every pre-existing violation was fixed in the PR that landed the
analyzer, not inventoried).

Standalone-runnable:  python -m pytest tests/ -q -m lint
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from distributed_grep_tpu.analysis import RULES, Project, run_analysis
from distributed_grep_tpu.analysis.checker import main as analyze_main
from distributed_grep_tpu.analysis.knobs import KNOBS, knob_docs

pytestmark = pytest.mark.lint


def _mk(root: Path, rel: str, src: str) -> None:
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(src, encoding="utf-8")


def _hits(root: Path, rule: str) -> list:
    return [v for v in run_analysis(root=root, rules=[rule])]


# ------------------------------------------------------------ the tier-1 gate

def test_repo_is_clean_with_empty_baseline():
    """The acceptance invariant: `analyze` exits 0 on the repo with NO
    baseline.  Any new violation fails tier-1 here, with the rule's
    file:line diagnostics in the assertion."""
    violations = run_analysis()
    assert not violations, "\n" + "\n".join(v.render() for v in violations)


def test_cli_analyze_subcommand_green(capsys):
    from distributed_grep_tpu.__main__ import main

    # one full-repo pass through the CLI (--json covers the plain exit-0
    # contract too; a second bare `analyze` run would double the
    # repo-wide analysis cost in the tier-1 suite for no extra signal)
    assert main(["analyze", "--json"]) == 0
    out = capsys.readouterr().out
    doc = json.loads(out)
    assert doc["count"] == 0 and doc["violations"] == []


# ------------------------------------------------------------------ R1 posix

def test_posix_expand_fires_on_raw_user_pattern(tmp_path):
    _mk(tmp_path, "apps/x.py",
        "import re\n"
        "def f(user_pattern):\n"
        "    return re.compile(user_pattern)\n")
    (v,) = _hits(tmp_path, "posix-expand")
    assert v.path == "apps/x.py" and v.line == 3
    assert "expand_posix_classes" in v.message


def test_posix_expand_fires_through_alias_and_search(tmp_path):
    _mk(tmp_path, "ops/x.py",
        "import re as _re\n"
        "def f(p, data):\n"
        "    return _re.search(p, data)\n")
    (v,) = _hits(tmp_path, "posix-expand")
    assert v.line == 3


def test_posix_expand_exempts_hoisted_literal_constant(tmp_path):
    """The wordcount._WORD shape: an app-internal literal hoisted into a
    named constant is still a literal, not a user pattern."""
    _mk(tmp_path, "apps/w.py",
        "import re\n"
        "_WORD = rb'[A-Za-z0-9]+'\n"
        "def f(text):\n"
        "    return re.findall(_WORD, text)\n")
    assert not _hits(tmp_path, "posix-expand")


def test_posix_expand_silent_on_sanitized_and_literal(tmp_path):
    _mk(tmp_path, "apps/ok.py",
        "import re\n"
        "from distributed_grep_tpu.models.dfa import expand_posix_classes\n"
        "WORD = re.compile(rb'[A-Za-z]+')\n"  # app-internal literal
        "def f(p):\n"
        "    return re.compile(expand_posix_classes(p))\n"
        "def g(p, mode):\n"
        "    base = wrap(expand_posix_classes(p), mode)\n"
        "    return re.compile(base)\n"  # sanitized via the assignment
        "def h(lits):\n"
        "    return re.compile(b'|'.join(re.escape(x) for x in lits))\n")
    assert not _hits(tmp_path, "posix-expand")


# ------------------------------------------------------------------ R2 store

def test_store_resolve_fires_on_raw_glob_and_open(tmp_path):
    _mk(tmp_path, "runtime/x.py",
        "import glob\n"
        "def f(d):\n"
        "    a = glob.glob(d + '/mr-out-*')\n"
        "    b = open(f'{d}/mr-0-1')\n"
        "    return a, b\n")
    got = _hits(tmp_path, "store-resolve")
    assert [v.line for v in got] == [3, 4]
    assert all("unit of truth" in v.message for v in got)


def test_store_resolve_exempts_store_py_and_plain_paths(tmp_path):
    _mk(tmp_path, "runtime/store.py",
        "from pathlib import Path\n"
        "def resolve(d):\n"
        "    return sorted(Path(d).glob('mr-out-*'))\n")
    _mk(tmp_path, "runtime/ok.py",
        "def f(p):\n"
        "    return open(p)\n")  # no mr-* literal: not a raw artifact read
    assert not _hits(tmp_path, "store-resolve")


# ---------------------------------------------------------------- R3 unicode

def test_surrogateescape_fires_on_bare_utf8_conversions(tmp_path):
    _mk(tmp_path, "runtime/x.py",
        "def f(p):\n"
        "    return p.encode('utf-8'), p.encode(), b'x'.decode('utf-8')\n")
    got = _hits(tmp_path, "surrogateescape")
    assert len(got) == 3 and all(v.line == 2 for v in got)


def test_surrogateescape_exemptions(tmp_path):
    _mk(tmp_path, "apps/ok.py",
        "import json\n"
        "def f(p, obj):\n"
        "    a = p.encode('utf-8', 'surrogateescape')\n"
        "    b = p.encode('utf-8', errors='surrogateescape')\n"
        "    c = b'x'.decode('utf-8', errors='replace')\n"
        "    d = json.dumps(obj).encode('utf-8')\n"  # ASCII by construction
        "    e = b'ff'.decode('ascii')\n"  # fixed-alphabet codec
        "    return a, b, c, d, e\n")
    _mk(tmp_path, "models/out_of_scope.py",
        "def f(p):\n"
        "    return p.encode('utf-8')\n")  # models/ is not the data plane
    assert not _hits(tmp_path, "surrogateescape")


# ------------------------------------------------------------------ R4 knobs

def test_env_knobs_fires_on_unregistered_and_wrong_owner(tmp_path):
    _mk(tmp_path, "ops/x.py",
        "import os\n"
        "A = os.environ.get('DGREP_BOGUS', '1')\n"
        "B = os.environ.get('DGREP_LOG')\n")
    got = _hits(tmp_path, "env-knobs")
    msgs = "\n".join(v.message for v in got)
    assert "unregistered env knob DGREP_BOGUS" in msgs
    assert "DGREP_LOG read outside its owner" in msgs


def test_env_knobs_fires_on_stale_registry_entry(tmp_path):
    _mk(tmp_path, "utils/logging.py", "x = 1\n")  # owner exists, no read
    got = _hits(tmp_path, "env-knobs")
    assert any("DGREP_LOG is never read" in v.message for v in got)


def test_env_knobs_resolves_module_constant_keys(tmp_path):
    _mk(tmp_path, "utils/spans.py",
        "import os\n"
        "_ENV_VAR = 'DGREP_SPANS'\n"
        "def enabled():\n"
        "    return os.environ.get(_ENV_VAR, '') not in ('', '0')\n")
    assert not _hits(tmp_path, "env-knobs")
    # ...and the same indirect read elsewhere is still caught
    _mk(tmp_path, "runtime/x.py",
        "import os\n"
        "_V = 'DGREP_SPANS'\n"
        "y = os.environ.get(_V)\n")
    got = _hits(tmp_path, "env-knobs")
    assert any("DGREP_SPANS read outside its owner" in v.message
               for v in got)


def test_env_knobs_resolves_function_local_keys(tmp_path):
    """A knob read hidden behind a function-local name is still a read."""
    _mk(tmp_path, "runtime/x.py",
        "import os\n"
        "def f():\n"
        "    var = 'DGREP_TOTALLY_BOGUS'\n"
        "    return os.environ.get(var)\n")
    got = _hits(tmp_path, "env-knobs")
    assert any("DGREP_TOTALLY_BOGUS" in v.message for v in got)


def test_knob_registry_docs_cover_every_knob():
    docs = knob_docs()
    for name, knob in KNOBS.items():
        assert name in docs and knob.owner in docs


# ------------------------------------------------------------------- R5 rpc

_RPC_FIXTURE = """\
from dataclasses import dataclass, field
from typing import Any
@dataclass
class A:
    x: int = 1
    m: dict | None = None
    spans: list = field(default_factory=list)
_ELIDE_DEFAULTS: dict[str, Any] = {'spans': [], 'gone': None, 'x': 5}
"""


def test_rpc_elide_fires_on_missing_drift_and_dead_keys(tmp_path):
    _mk(tmp_path, "runtime/rpc.py", _RPC_FIXTURE)
    msgs = "\n".join(v.message for v in _hits(tmp_path, "rpc-elide"))
    assert "Optional-default field A.m missing" in msgs
    assert "_ELIDE_DEFAULTS['x'] == 5 but A.x defaults to 1" in msgs
    assert "key 'gone' is not a field" in msgs


def test_rpc_elide_silent_on_consistent_schema(tmp_path):
    _mk(tmp_path, "runtime/rpc.py",
        "from dataclasses import dataclass, field\n"
        "from typing import Any\n"
        "@dataclass\n"
        "class A:\n"
        "    x: int = 1\n"
        "    m: dict | None = None\n"
        "    spans: list = field(default_factory=list)\n"
        "_ELIDE_DEFAULTS: dict[str, Any] = {'spans': [], 'm': None}\n")
    assert not _hits(tmp_path, "rpc-elide")


def test_rpc_elide_reply_side_fires_on_unregistered_and_truthy(tmp_path):
    _mk(tmp_path, "runtime/rpc.py",
        "from dataclasses import dataclass\n"
        "from typing import Any\n"
        "_ELIDE_DEFAULTS: dict[str, Any] = {}\n"
        "_REPLY_BASE = ('ok',)\n"
        "_REPLY_ELIDE = ('retries', 'gone')\n"
        "@dataclass\n"
        "class PollReply:\n"
        "    ok: bool = False\n"
        "    retries: int = 3\n"     # truthy default: elision never fires
        "    orphan: str = ''\n")    # declared on neither side
    msgs = "\n".join(v.message for v in _hits(tmp_path, "rpc-elide"))
    assert ("reply field PollReply.orphan is in neither _REPLY_BASE nor "
            "_REPLY_ELIDE") in msgs
    assert ("_REPLY_ELIDE field PollReply.retries defaults to 3 (truthy)"
            ) in msgs
    assert "reply registry key 'gone' is not a field" in msgs


def test_rpc_elide_reply_side_fires_on_missing_registries_and_both(
        tmp_path):
    _mk(tmp_path, "runtime/rpc.py",
        "from dataclasses import dataclass\n"
        "from typing import Any\n"
        "_ELIDE_DEFAULTS: dict[str, Any] = {}\n"
        "@dataclass\n"
        "class PollReply:\n"
        "    ok: bool = False\n")
    msgs = "\n".join(v.message for v in _hits(tmp_path, "rpc-elide"))
    assert ("reply dataclasses present but _REPLY_BASE/_REPLY_ELIDE "
            "tuple literals missing") in msgs
    _mk(tmp_path, "runtime/rpc.py",
        "from dataclasses import dataclass\n"
        "from typing import Any\n"
        "_ELIDE_DEFAULTS: dict[str, Any] = {}\n"
        "_REPLY_BASE = ('ok',)\n"
        "_REPLY_ELIDE = ('ok',)\n"
        "@dataclass\n"
        "class PollReply:\n"
        "    ok: bool = False\n")
    msgs = "\n".join(v.message for v in _hits(tmp_path, "rpc-elide"))
    assert ("registered in BOTH _REPLY_BASE and _REPLY_ELIDE") in msgs


def test_rpc_elide_reply_side_silent_on_partitioned_schema(tmp_path):
    # non-Reply dataclasses need no registries (the old fixtures'
    # shape), and a correct partition is silent
    _mk(tmp_path, "runtime/rpc.py",
        "from dataclasses import dataclass\n"
        "from typing import Any\n"
        "_ELIDE_DEFAULTS: dict[str, Any] = {}\n"
        "_REPLY_BASE = ('ok',)\n"
        "_REPLY_ELIDE = ('extra',)\n"
        "@dataclass\n"
        "class PollReply:\n"
        "    ok: bool = False\n"
        "    extra: str = ''\n")
    assert not _hits(tmp_path, "rpc-elide")


# ---------------------------------------------------------------- R6 mosaic

def test_mosaic_fires_on_narrow_compare_and_bad_unroll(tmp_path):
    _mk(tmp_path, "ops/pallas_x.py",
        "import jax.numpy as jnp\n"
        "def kernel(a, b, run):\n"
        "    m = a.astype(jnp.int8) == b\n"
        "    n = jnp.uint16(3) < b\n"
        "    run(a, unroll=7)\n"
        "    return m, n\n"
        "def unroll_for(model):\n"
        "    return 5 if model else 8\n")
    got = _hits(tmp_path, "mosaic-ceilings")
    msgs = "\n".join(v.message for v in got)
    assert "int8 vector compare" in msgs and "uint16 vector compare" in msgs
    assert "unroll=7 outside the probed set" in msgs
    assert "unroll_for returns 5" in msgs


def test_mosaic_fires_on_fdr_ceiling_breach(tmp_path):
    _mk(tmp_path, "models/fdr.py",
        "MAX_GATHERS = 96\nDOMAINS = (128, 384)\n")
    msgs = "\n".join(v.message for v in _hits(tmp_path, "mosaic-ceilings"))
    assert "MAX_GATHERS=96 exceeds the probed compile ceiling 64" in msgs
    assert "DOMAINS entry 384" in msgs


def test_mosaic_silent_on_widened_compares(tmp_path):
    _mk(tmp_path, "ops/pallas_ok.py",
        "import jax.numpy as jnp\n"
        "def kernel(ref, lo, run):\n"
        "    b = ref.astype(jnp.int32)\n"
        "    m = (b >= lo) & (b == 97)\n"  # i32 compares: the probed floor
        "    run(b, unroll=16)\n"
        "    return m | (b.astype(jnp.uint8) & 1)\n")  # cast OUTSIDE compare
    assert not _hits(tmp_path, "mosaic-ceilings")


# --------------------------------------------------------------- R7 logging

def test_logging_fires_on_print_and_root_logger(tmp_path):
    _mk(tmp_path, "parallel/x.py",
        "import logging\n"
        "log = logging.getLogger('x')\n"
        "def f():\n"
        "    print('hi')\n")
    got = _hits(tmp_path, "logging")
    msgs = "\n".join(v.message for v in got)
    assert "bare print()" in msgs and "root-logger" in msgs \
        and "without get_logger" in msgs


def test_logging_scope_and_get_logger_exemptions(tmp_path):
    _mk(tmp_path, "utils/y.py",
        "from distributed_grep_tpu.utils.logging import get_logger\n"
        "log = get_logger('y')\n")
    _mk(tmp_path, "apps/z.py", "print('cli output is fine here')\n")
    assert not _hits(tmp_path, "logging")


# -------------------------------------------------------------- R8 net-retry

def test_net_retry_fires_on_raw_urlopen_and_socket(tmp_path):
    _mk(tmp_path, "runtime/x.py",
        "import socket\n"
        "import urllib.request\n"
        "def f(url, host):\n"
        "    with urllib.request.urlopen(url, timeout=5) as r:\n"
        "        body = r.read()\n"
        "    c = socket.create_connection((host, 80))\n"
        "    return body, c\n")
    _mk(tmp_path, "__main__.py",
        "import urllib.request\n"
        "def poll(url):\n"
        "    return urllib.request.urlopen(url).read()\n")
    got = _hits(tmp_path, "net-retry")
    assert [(v.path, v.line) for v in got] == [
        ("__main__.py", 3), ("runtime/x.py", 4), ("runtime/x.py", 6),
    ]
    assert all("retry-wrapped transport helpers" in v.message for v in got)


def test_net_retry_silent_on_transport_module_and_out_of_scope(tmp_path):
    # the retry helpers themselves live on raw urlopen — exempt
    _mk(tmp_path, "runtime/http_transport.py",
        "import urllib.request\n"
        "def _request(url):\n"
        "    return urllib.request.urlopen(url).read()\n")
    # benchmarks/apps are out of scope (no control-plane retry contract)
    _mk(tmp_path, "apps/y.py",
        "import urllib.request\n"
        "def fetch(url):\n"
        "    return urllib.request.urlopen(url).read()\n")
    # server-side sockets in runtime/ are not client calls
    _mk(tmp_path, "runtime/server.py",
        "from http.server import ThreadingHTTPServer\n"
        "def serve(handler):\n"
        "    return ThreadingHTTPServer(('127.0.0.1', 0), handler)\n")
    assert not _hits(tmp_path, "net-retry")


def test_net_retry_fires_on_addr_comma_split_outside_transport(tmp_path):
    # round 18: a hand-rolled address-list split forks the failover
    # rotation out of the shared retry loop — flagged everywhere in
    # scope except http_transport itself (where split_addrs lives)
    _mk(tmp_path, "runtime/x.py",
        "def pick(addr):\n"
        "    return addr.split(',')[0]\n")
    _mk(tmp_path, "__main__.py",
        "def first(args):\n"
        "    return args.addr.split(',')[0]\n")
    got = _hits(tmp_path, "net-retry")
    assert [(v.path, v.line) for v in got] == [
        ("__main__.py", 2), ("runtime/x.py", 2),
    ]
    assert all("split_addrs" in v.message for v in got)


def test_net_retry_silent_on_non_addr_splits_and_transport_split(tmp_path):
    # split_addrs' own comma split is exempt with its module
    _mk(tmp_path, "runtime/http_transport.py",
        "def split_addrs(addr):\n"
        "    return [a for a in addr.split(',') if a]\n")
    # comma splits of non-address strings stay silent (Range headers,
    # CSV-ish option parsing)
    _mk(tmp_path, "runtime/y.py",
        "def parse_range(rng):\n"
        "    return rng.split(',')[0]\n"
        "def split_other(addr):\n"
        "    return addr.split(';')\n")
    assert not _hits(tmp_path, "net-retry")


# ------------------------------------------------------ R9 locked-blocking

def test_locked_blocking_fires_in_with_block_and_locked_method(tmp_path):
    _mk(tmp_path, "runtime/x.py",
        "import os\n"
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def f(self, p):\n"
        "        with self._lock:\n"
        "            open(p)\n"  # file open under a hot lock
        "    def _push_locked(self, f):\n"
        "        os.fsync(f.fileno())\n"  # fsync in a _locked method
        "    def g(self):\n"
        "        with self._lock:\n"
        "            self.journal.map_completed(1, 'f', [])\n")  # I/O object
    got = _hits(tmp_path, "locked-blocking")
    assert [v.line for v in got] == [8, 10, 13]
    msgs = "\n".join(v.message for v in got)
    assert "open()" in msgs and "os.fsync()" in msgs
    assert "journal.map_completed() [I/O object]" in msgs
    assert "_locked convention" in got[1].message


def test_locked_blocking_fires_on_sleep_engine_and_socket(tmp_path):
    _mk(tmp_path, "ops/x.py",
        "import time\n"
        "import threading\n"
        "from urllib.request import urlopen\n"
        "_l = threading.Lock()\n"
        "def f(url, pat):\n"
        "    with _l:\n"
        "        time.sleep(1)\n"
        "        urlopen(url)\n"
        "        eng = GrepEngine(pat)\n"
        "    return eng\n")
    got = _hits(tmp_path, "locked-blocking")
    assert [v.line for v in got] == [7, 8, 9]


def test_locked_blocking_nested_compound_reports_once(tmp_path):
    """A blocking call under if/try INSIDE the with reports exactly once
    (no double-walk), and a with-ITEM expression is scanned against the
    locks already held to its left."""
    _mk(tmp_path, "runtime/x.py",
        "import threading\n"
        "_l = threading.Lock()\n"
        "def f(p, cond):\n"
        "    with _l:\n"
        "        if cond:\n"
        "            try:\n"
        "                open(p)\n"
        "            except OSError:\n"
        "                pass\n"
        "def g(p):\n"
        "    with _l, open(p) as fh:\n"  # item opened AFTER _l acquired
        "        return fh\n")
    got = _hits(tmp_path, "locked-blocking")
    assert [v.line for v in got] == [7, 11]


def test_locked_blocking_nested_def_under_lock_is_not_flagged(tmp_path):
    """Defining a closure under a lock runs nothing — its body is its
    own scope (flagged only under its OWN locks / _locked name)."""
    _mk(tmp_path, "runtime/x.py",
        "import threading\n"
        "_l = threading.Lock()\n"
        "def f(p):\n"
        "    with _l:\n"
        "        def cb():\n"
        "            return open(p)\n"
        "        return cb\n")
    assert not _hits(tmp_path, "locked-blocking")


def test_locked_blocking_io_ok_and_staged_flush_stay_silent(tmp_path):
    """The two blessed escapes: a lock DECLARED io_ok (serializing the
    I/O is its purpose) and the staged-flush pattern (stage under the
    lock, write after release)."""
    _mk(tmp_path, "runtime/ok.py",
        "import os\n"
        "import threading\n"
        "from distributed_grep_tpu.utils.lockdep import make_lock\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._flush_lock = make_lock('flush', io_ok=True)\n"
        "        self._pending = []\n"
        "    def commit(self, entry, f):\n"
        "        with self._lock:\n"
        "            self._pending.append(entry)\n"  # staging: no I/O
        "        self._flush(f)\n"
        "    def _flush(self, f):\n"
        "        with self._flush_lock:\n"
        "            with self._lock:\n"
        "                pending, self._pending = self._pending, []\n"
        "            os.fsync(f.fileno())\n"  # under the io_ok lock only
        "    def teardown(self, p):\n"
        "        open(p)\n"  # no lock held: fine
        "    def h(self, s):\n"
        "        with self._lock:\n"
        "            return s.replace('a', 'b')\n")  # str.replace != os.replace
    assert not _hits(tmp_path, "locked-blocking")


def test_locked_blocking_out_of_scope_dirs_are_exempt(tmp_path):
    _mk(tmp_path, "utils/spans_like.py",
        "import threading\n"
        "class L:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def write(self, f):\n"
        "        with self._lock:\n"
        "            open(f)\n")  # utils/ is not in the R9 scope
    assert not _hits(tmp_path, "locked-blocking")


# ----------------------------------------------------------- R10 lock-order

def test_lock_order_fires_on_cross_function_cycle(tmp_path):
    _mk(tmp_path, "runtime/y.py",
        "import threading\n"
        "a = threading.Lock()\n"
        "b = threading.Lock()\n"
        "def f():\n"
        "    with a:\n"
        "        g()\n"  # a -> b via the call edge
        "def g():\n"
        "    with b:\n"
        "        pass\n"
        "def h():\n"
        "    with b:\n"
        "        with a:\n"  # b -> a lexically: cycle
        "            pass\n")
    got = _hits(tmp_path, "lock-order")
    assert len(got) == 1
    assert "lock-order cycle" in got[0].message


def test_lock_order_three_lock_cycle_reports_once(tmp_path):
    """One A->B->C->A cycle is ONE deadlock: dedup keys on the cycle's
    full lock set, not the closing edge (edge-keyed dedup would report
    a 3-cycle three times)."""
    _mk(tmp_path, "runtime/tri.py",
        "import threading\n"
        "a = threading.Lock()\n"
        "b = threading.Lock()\n"
        "c = threading.Lock()\n"
        "def f():\n"
        "    with a:\n"
        "        with b:\n"
        "            pass\n"
        "def g():\n"
        "    with b:\n"
        "        with c:\n"
        "            pass\n"
        "def h():\n"
        "    with c:\n"
        "        with a:\n"
        "            pass\n")
    got = _hits(tmp_path, "lock-order")
    assert len(got) == 1 and "cycle" in got[0].message


def test_lock_order_fires_on_lexical_self_reacquire(tmp_path):
    _mk(tmp_path, "ops/z.py",
        "import threading\n"
        "l = threading.Lock()\n"
        "def f():\n"
        "    with l:\n"
        "        with l:\n"
        "            pass\n")
    (v,) = _hits(tmp_path, "lock-order")
    assert "re-acquired while already held" in v.message


def test_lock_order_cross_module_edge_via_annotation(tmp_path):
    """The service -> scheduler shape: a dataclass field annotation types
    the receiver, the call edge crosses modules, and the REVERSE order
    in the other module closes the cycle."""
    _mk(tmp_path, "runtime/sched.py",
        "import threading\n"
        "class Sched:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def stop(self):\n"
        "        with self._lock:\n"
        "            helper()\n"
        "def helper():\n"
        "    pass\n")
    _mk(tmp_path, "runtime/svc.py",
        "import threading\n"
        "from runtime.sched import Sched\n"
        "class Rec:\n"
        "    scheduler: Sched | None = None\n"
        "class Svc:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def close(self, rec):\n"
        "        with self._lock:\n"
        "            rec.scheduler.stop()\n")
    assert not _hits(tmp_path, "lock-order")  # svc -> sched alone: acyclic
    _mk(tmp_path, "runtime/sched.py",
        "import threading\n"
        "from runtime.svc import Svc\n"
        "class Sched:\n"
        "    svc: Svc | None = None\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def stop(self):\n"
        "        with self._lock:\n"
        "            self.svc.close(None)\n")  # sched -> svc: cycle closes
    got = _hits(tmp_path, "lock-order")
    assert len(got) == 1 and "cycle" in got[0].message


def test_lock_order_conditional_acquire_helper_is_not_a_self_cycle(tmp_path):
    """The `locked=True` re-entry guard shape (service admission check):
    a helper that conditionally takes the SAME lock its caller holds must
    not read as a self-deadlock — call-path self-edges are skipped by
    design (the lexical `with a: with a:` case still reports)."""
    _mk(tmp_path, "runtime/adm.py",
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def check(self, locked=False):\n"
        "        if not locked:\n"
        "            with self._lock:\n"
        "                return self.check(locked=True)\n"
        "        return True\n"
        "    def submit(self):\n"
        "        with self._lock:\n"
        "            self.check(locked=True)\n")
    assert not _hits(tmp_path, "lock-order")


def test_lock_order_make_lock_names_and_condition_alias(tmp_path):
    """make_lock names are the graph nodes, and Condition(self._lock)
    aliases the wrapped lock — `with self._cond:` is the same node as
    `with self._lock:` (no phantom second lock)."""
    _mk(tmp_path, "runtime/named.py",
        "import threading\n"
        "from distributed_grep_tpu.utils.lockdep import make_lock\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = make_lock('svc')\n"
        "        self._cond = threading.Condition(self._lock)\n"
        "    def wake(self):\n"
        "        with self._cond:\n"
        "            pass\n"
        "    def wait_then(self):\n"
        "        with self._lock:\n"
        "            self.wake()\n")  # same node: skipped, not a cycle
    assert not _hits(tmp_path, "lock-order")


# -------------------------------------------------------- R11 shard-map-rep

def test_shard_map_rep_fires_in_pallas_module(tmp_path):
    _mk(tmp_path, "parallel/k.py",
        "from jax.experimental.shard_map import shard_map\n"
        "from distributed_grep_tpu.ops import pallas_scan\n"
        "def go(body, mesh, spec):\n"
        "    return shard_map(body, mesh=mesh, in_specs=spec,\n"
        "                     out_specs=spec)\n")
    (v,) = _hits(tmp_path, "shard-map-rep")
    assert v.line == 4 and "check_rep=False" in v.message


def test_shard_map_rep_fires_on_explicit_true(tmp_path):
    _mk(tmp_path, "parallel/k.py",
        "from jax.experimental.shard_map import shard_map\n"
        "def kernel(x):\n"
        "    return pallas_call(x)\n"  # pallas-touching via the call
        "def go(mesh, spec):\n"
        "    return shard_map(kernel, mesh=mesh, in_specs=spec,\n"
        "                     out_specs=spec, check_rep=True)\n")
    (v,) = _hits(tmp_path, "shard-map-rep")
    assert v.line == 5


def test_shard_map_rep_silent_on_compliant_and_non_pallas(tmp_path):
    # the XLA-core sharded scan: no pallas anywhere -> check_rep may stay
    _mk(tmp_path, "parallel/scan.py",
        "from jax.experimental.shard_map import shard_map\n"
        "def go(body, mesh, spec):\n"
        "    return shard_map(body, mesh=mesh, in_specs=spec,\n"
        "                     out_specs=spec)\n")
    # the kernel module passes check_rep=False as required
    _mk(tmp_path, "parallel/kern.py",
        "from jax.experimental.shard_map import shard_map\n"
        "from distributed_grep_tpu.ops import pallas_scan\n"
        "def go(body, mesh, spec):\n"
        "    return shard_map(body, mesh=mesh, in_specs=spec,\n"
        "                     out_specs=spec, check_rep=False)\n")
    assert not _hits(tmp_path, "shard-map-rep")


# ------------------------------------------------------ R12 metrics-registry

def test_metrics_registry_fires_on_undeclared_and_mismatch(tmp_path):
    _mk(tmp_path, "runtime/x.py",
        "from distributed_grep_tpu.utils import metrics as m\n"
        "c = m.counter('dgrep_bogus_total')\n"  # undeclared series
        "g = m.gauge('dgrep_jobs_submitted_total')\n")  # declared counter
    got = _hits(tmp_path, "metrics-registry")
    msgs = "\n".join(v.message for v in got)
    assert "undeclared metrics series dgrep_bogus_total" in msgs
    assert ("dgrep_jobs_submitted_total created as a gauge but declared "
            "counter") in msgs


def test_metrics_registry_fires_on_stale_declaration(tmp_path):
    # the registry owner exists but no call site creates any series:
    # every declared name is stale (the env-knobs stale-entry shape)
    _mk(tmp_path, "utils/metrics.py", "x = 1\n")
    got = _hits(tmp_path, "metrics-registry")
    msgs = "\n".join(v.message for v in got)
    assert "declared metrics series dgrep_queue_wait_seconds is never " \
           "created" in msgs


def test_metrics_registry_silent_on_declared_and_mini_trees(tmp_path):
    # correct usage: declared name, matching kind — silent even though
    # the mini-tree has no utils/metrics.py (stale check gated on it)
    _mk(tmp_path, "runtime/ok.py",
        "from distributed_grep_tpu.utils import metrics as m\n"
        "h = m.histogram('dgrep_queue_wait_seconds')\n"
        "c = m.counter('dgrep_jobs_done_total')\n")
    # non-series strings through same-named callables stay exempt (the
    # dgrep_ prefix is the series namespace)
    _mk(tmp_path, "apps/other.py",
        "def counter(name):\n"
        "    return name\n"
        "x = counter('not_a_series')\n")
    assert not _hits(tmp_path, "metrics-registry")


# ------------------------------------------------------- R13 event-registry

def test_event_registry_fires_on_undeclared_name_and_kind(tmp_path):
    _mk(tmp_path, "runtime/x.py",
        "from distributed_grep_tpu.utils import spans\n"
        "spans.instant('totally_bogus', cat='engine')\n"  # undeclared
        "with spans.span('resume', cat='service'):\n"     # declared instant
        "    pass\n")
    msgs = "\n".join(v.message for v in _hits(tmp_path, "event-registry"))
    assert "undeclared event name 'totally_bogus'" in msgs
    assert "'resume' emitted as a span but declared instant/daemon" in msgs


def test_event_registry_fires_on_cat_mismatch_and_dict_literal(tmp_path):
    _mk(tmp_path, "runtime/x.py",
        "buf.add({'t': 'instant', 'name': 'index:prune', 'cat': 'map'})\n"
        "buf.add({'t': 'span', 'name': 'nobody:declared'})\n")
    msgs = "\n".join(v.message for v in _hits(tmp_path, "event-registry"))
    assert ("'index:prune' emitted with cat 'map' but declared cat "
            "'engine'") in msgs
    assert "undeclared event name 'nobody:declared'" in msgs


def test_event_registry_fires_on_undeclared_family_fstring(tmp_path):
    # computed names must land in a declared enumerated family
    _mk(tmp_path, "apps/x.py",
        "from distributed_grep_tpu.utils import spans\n"
        "def f(verdict):\n"
        "    spans.instant(f'bogus:{verdict}', cat='engine')\n")
    msgs = "\n".join(v.message for v in _hits(tmp_path, "event-registry"))
    assert "undeclared event family 'bogus:*'" in msgs


def test_event_registry_fires_on_consumer_side_drift(tmp_path):
    # a consumer matching a name no emitter produces is a one-sided
    # rename (explain.py is in the audited consumer set)
    _mk(tmp_path, "runtime/explain.py",
        "def view(events):\n"
        "    return [e for e in events if e.get('name') is not None\n"
        "            and (name := e['name']) and name == 'scan_old_name']\n")
    msgs = "\n".join(v.message for v in _hits(tmp_path, "event-registry"))
    assert "consumer matches undeclared event name 'scan_old_name'" in msgs


def test_event_registry_fires_on_stale_declaration(tmp_path):
    # the emit owner exists but emits nothing: every declared entry is
    # stale (gated on utils/spans.py so the other mini-trees stay silent)
    _mk(tmp_path, "utils/spans.py", "x = 1\n")
    msgs = "\n".join(v.message for v in _hits(tmp_path, "event-registry"))
    assert ("declared event 'scan:*' has no surviving emit site" in msgs)


def test_event_registry_silent_on_declared_and_daemon_emitters(tmp_path):
    # declared names with matching kind/cat — incl. a family f-string,
    # a daemon stage() call, and a non-constant name (dynamic-audit
    # territory, silently skipped like metrics-registry)
    _mk(tmp_path, "runtime/ok.py",
        "from distributed_grep_tpu.utils import spans\n"
        "def f(mode, verdict, daemon_log, anything):\n"
        "    spans.instant(f'cache:{verdict}', cat='engine')\n"
        "    with spans.span('map:read', cat='map'):\n"
        "        pass\n"
        "    daemon_log.stage('lease_steal', prev_epoch=1)\n"
        "    spans.instant(anything)\n")
    assert not _hits(tmp_path, "event-registry")


# ----------------------------------------------------------- SARIF output

def test_sarif_output_shape_and_stability(tmp_path, capsys):
    _mk(tmp_path, "parallel/x.py", "def f():\n    print('x')\n")
    assert analyze_main(["--root", str(tmp_path), "--sarif"]) == 1
    first = capsys.readouterr().out
    doc = json.loads(first)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "distributed-grep-analyze"
    rule_ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
    assert rule_ids == sorted(RULES)  # every rule, stable order
    (res,) = [r for r in run["results"] if r["ruleId"] == "logging"]
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "parallel/x.py"
    assert loc["region"]["startLine"] == 2
    # byte-stable: same tree -> identical SARIF and identical --json
    assert analyze_main(["--root", str(tmp_path), "--sarif"]) == 1
    assert capsys.readouterr().out == first
    assert analyze_main(["--root", str(tmp_path), "--json"]) == 1
    j1 = capsys.readouterr().out
    assert analyze_main(["--root", str(tmp_path), "--json"]) == 1
    assert capsys.readouterr().out == j1


def test_sarif_clean_tree_is_green_with_empty_results(tmp_path, capsys):
    _mk(tmp_path, "apps/ok.py", "x = 1\n")
    assert analyze_main(["--root", str(tmp_path), "--sarif"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["runs"][0]["results"] == []


# --------------------------------------------- suppression + CLI plumbing

def test_pragma_suppresses_named_rule_only(tmp_path):
    _mk(tmp_path, "parallel/x.py",
        "def f():\n"
        "    print('deliberate')  # analyze-ok: logging\n")
    assert not _hits(tmp_path, "logging")
    _mk(tmp_path, "parallel/y.py",
        "def f():\n"
        "    print('deliberate')  # analyze-ok: other-rule\n")
    assert any(v.path == "parallel/y.py"
               for v in _hits(tmp_path, "logging"))


def test_baseline_roundtrip_and_exit_codes(tmp_path, capsys):
    _mk(tmp_path, "parallel/x.py", "def f():\n    print('x')\n")
    root = str(tmp_path)
    assert analyze_main(["--root", root, "--rule", "logging"]) == 1
    base = tmp_path / "baseline.txt"
    assert analyze_main(["--root", root, "--rule", "logging",
                         "--write-baseline", str(base)]) == 0
    capsys.readouterr()
    assert analyze_main(["--root", root, "--rule", "logging",
                         "--baseline", str(base)]) == 0
    assert analyze_main(["--root", root, "--rule", "no-such-rule"]) == 2
    # a typo'd baseline path is a clean usage error, not a traceback
    assert analyze_main(["--root", root,
                         "--baseline", str(tmp_path / "missing.txt")]) == 2
    assert analyze_main(["--list-rules"]) == 0
    assert analyze_main(["--knobs"]) == 0
    out = capsys.readouterr().out
    assert "DGREP_BATCH_BYTES" in out
    assert analyze_main(["--events"]) == 0
    out = capsys.readouterr().out
    assert "scan:*" in out and "lease_steal" in out


def test_json_output_shape(tmp_path, capsys):
    _mk(tmp_path, "parallel/x.py", "def f():\n    print('x')\n")
    assert analyze_main(["--root", str(tmp_path), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["count"] >= 1
    v = doc["violations"][0]
    assert set(v) == {"rule", "path", "line", "message"}


def test_every_rule_has_a_doc_line():
    from distributed_grep_tpu.analysis.rules import RULE_DOCS

    for name in RULES:
        assert RULE_DOCS[name], name


def test_project_tolerates_unparseable_file(tmp_path):
    _mk(tmp_path, "runtime/broken.py", "def f(:\n")
    # non-UTF-8 source: ast.parse raises UnicodeEncodeError on the
    # surrogateescape-decoded text — skipped like a SyntaxError
    (tmp_path / "runtime" / "binary.py").write_bytes(b'print("x\xff")\n')
    assert Project(tmp_path).tree("runtime/broken.py") is None
    assert Project(tmp_path).tree("runtime/binary.py") is None
    assert run_analysis(root=tmp_path) == []


def test_write_baseline_unwritable_path_is_clean_error(tmp_path, capsys):
    _mk(tmp_path, "parallel/x.py", "def f():\n    print('x')\n")
    rc = analyze_main(["--root", str(tmp_path), "--write-baseline",
                       str(tmp_path / "no" / "such" / "dir" / "b.txt")])
    assert rc == 2
    assert "error:" in capsys.readouterr().err

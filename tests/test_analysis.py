"""Tier-1 gate + per-rule fixtures for the project invariant checker
(distributed_grep_tpu/analysis/).

Two directions per rule: it must FIRE on a known-bad snippet (no false
negatives — a rule that silently stopped matching is worse than no rule)
and stay SILENT on this repo with an EMPTY baseline (no false positives —
every pre-existing violation was fixed in the PR that landed the
analyzer, not inventoried).

Standalone-runnable:  python -m pytest tests/ -q -m lint
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from distributed_grep_tpu.analysis import RULES, Project, run_analysis
from distributed_grep_tpu.analysis.checker import main as analyze_main
from distributed_grep_tpu.analysis.knobs import KNOBS, knob_docs

pytestmark = pytest.mark.lint


def _mk(root: Path, rel: str, src: str) -> None:
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(src, encoding="utf-8")


def _hits(root: Path, rule: str) -> list:
    return [v for v in run_analysis(root=root, rules=[rule])]


# ------------------------------------------------------------ the tier-1 gate

def test_repo_is_clean_with_empty_baseline():
    """The acceptance invariant: `analyze` exits 0 on the repo with NO
    baseline.  Any new violation fails tier-1 here, with the rule's
    file:line diagnostics in the assertion."""
    violations = run_analysis()
    assert not violations, "\n" + "\n".join(v.render() for v in violations)


def test_cli_analyze_subcommand_green(capsys):
    from distributed_grep_tpu.__main__ import main

    assert main(["analyze"]) == 0
    assert main(["analyze", "--json"]) == 0
    out = capsys.readouterr().out
    doc = json.loads(out)
    assert doc["count"] == 0 and doc["violations"] == []


# ------------------------------------------------------------------ R1 posix

def test_posix_expand_fires_on_raw_user_pattern(tmp_path):
    _mk(tmp_path, "apps/x.py",
        "import re\n"
        "def f(user_pattern):\n"
        "    return re.compile(user_pattern)\n")
    (v,) = _hits(tmp_path, "posix-expand")
    assert v.path == "apps/x.py" and v.line == 3
    assert "expand_posix_classes" in v.message


def test_posix_expand_fires_through_alias_and_search(tmp_path):
    _mk(tmp_path, "ops/x.py",
        "import re as _re\n"
        "def f(p, data):\n"
        "    return _re.search(p, data)\n")
    (v,) = _hits(tmp_path, "posix-expand")
    assert v.line == 3


def test_posix_expand_exempts_hoisted_literal_constant(tmp_path):
    """The wordcount._WORD shape: an app-internal literal hoisted into a
    named constant is still a literal, not a user pattern."""
    _mk(tmp_path, "apps/w.py",
        "import re\n"
        "_WORD = rb'[A-Za-z0-9]+'\n"
        "def f(text):\n"
        "    return re.findall(_WORD, text)\n")
    assert not _hits(tmp_path, "posix-expand")


def test_posix_expand_silent_on_sanitized_and_literal(tmp_path):
    _mk(tmp_path, "apps/ok.py",
        "import re\n"
        "from distributed_grep_tpu.models.dfa import expand_posix_classes\n"
        "WORD = re.compile(rb'[A-Za-z]+')\n"  # app-internal literal
        "def f(p):\n"
        "    return re.compile(expand_posix_classes(p))\n"
        "def g(p, mode):\n"
        "    base = wrap(expand_posix_classes(p), mode)\n"
        "    return re.compile(base)\n"  # sanitized via the assignment
        "def h(lits):\n"
        "    return re.compile(b'|'.join(re.escape(x) for x in lits))\n")
    assert not _hits(tmp_path, "posix-expand")


# ------------------------------------------------------------------ R2 store

def test_store_resolve_fires_on_raw_glob_and_open(tmp_path):
    _mk(tmp_path, "runtime/x.py",
        "import glob\n"
        "def f(d):\n"
        "    a = glob.glob(d + '/mr-out-*')\n"
        "    b = open(f'{d}/mr-0-1')\n"
        "    return a, b\n")
    got = _hits(tmp_path, "store-resolve")
    assert [v.line for v in got] == [3, 4]
    assert all("unit of truth" in v.message for v in got)


def test_store_resolve_exempts_store_py_and_plain_paths(tmp_path):
    _mk(tmp_path, "runtime/store.py",
        "from pathlib import Path\n"
        "def resolve(d):\n"
        "    return sorted(Path(d).glob('mr-out-*'))\n")
    _mk(tmp_path, "runtime/ok.py",
        "def f(p):\n"
        "    return open(p)\n")  # no mr-* literal: not a raw artifact read
    assert not _hits(tmp_path, "store-resolve")


# ---------------------------------------------------------------- R3 unicode

def test_surrogateescape_fires_on_bare_utf8_conversions(tmp_path):
    _mk(tmp_path, "runtime/x.py",
        "def f(p):\n"
        "    return p.encode('utf-8'), p.encode(), b'x'.decode('utf-8')\n")
    got = _hits(tmp_path, "surrogateescape")
    assert len(got) == 3 and all(v.line == 2 for v in got)


def test_surrogateescape_exemptions(tmp_path):
    _mk(tmp_path, "apps/ok.py",
        "import json\n"
        "def f(p, obj):\n"
        "    a = p.encode('utf-8', 'surrogateescape')\n"
        "    b = p.encode('utf-8', errors='surrogateescape')\n"
        "    c = b'x'.decode('utf-8', errors='replace')\n"
        "    d = json.dumps(obj).encode('utf-8')\n"  # ASCII by construction
        "    e = b'ff'.decode('ascii')\n"  # fixed-alphabet codec
        "    return a, b, c, d, e\n")
    _mk(tmp_path, "models/out_of_scope.py",
        "def f(p):\n"
        "    return p.encode('utf-8')\n")  # models/ is not the data plane
    assert not _hits(tmp_path, "surrogateescape")


# ------------------------------------------------------------------ R4 knobs

def test_env_knobs_fires_on_unregistered_and_wrong_owner(tmp_path):
    _mk(tmp_path, "ops/x.py",
        "import os\n"
        "A = os.environ.get('DGREP_BOGUS', '1')\n"
        "B = os.environ.get('DGREP_LOG')\n")
    got = _hits(tmp_path, "env-knobs")
    msgs = "\n".join(v.message for v in got)
    assert "unregistered env knob DGREP_BOGUS" in msgs
    assert "DGREP_LOG read outside its owner" in msgs


def test_env_knobs_fires_on_stale_registry_entry(tmp_path):
    _mk(tmp_path, "utils/logging.py", "x = 1\n")  # owner exists, no read
    got = _hits(tmp_path, "env-knobs")
    assert any("DGREP_LOG is never read" in v.message for v in got)


def test_env_knobs_resolves_module_constant_keys(tmp_path):
    _mk(tmp_path, "utils/spans.py",
        "import os\n"
        "_ENV_VAR = 'DGREP_SPANS'\n"
        "def enabled():\n"
        "    return os.environ.get(_ENV_VAR, '') not in ('', '0')\n")
    assert not _hits(tmp_path, "env-knobs")
    # ...and the same indirect read elsewhere is still caught
    _mk(tmp_path, "runtime/x.py",
        "import os\n"
        "_V = 'DGREP_SPANS'\n"
        "y = os.environ.get(_V)\n")
    got = _hits(tmp_path, "env-knobs")
    assert any("DGREP_SPANS read outside its owner" in v.message
               for v in got)


def test_env_knobs_resolves_function_local_keys(tmp_path):
    """A knob read hidden behind a function-local name is still a read."""
    _mk(tmp_path, "runtime/x.py",
        "import os\n"
        "def f():\n"
        "    var = 'DGREP_TOTALLY_BOGUS'\n"
        "    return os.environ.get(var)\n")
    got = _hits(tmp_path, "env-knobs")
    assert any("DGREP_TOTALLY_BOGUS" in v.message for v in got)


def test_knob_registry_docs_cover_every_knob():
    docs = knob_docs()
    for name, knob in KNOBS.items():
        assert name in docs and knob.owner in docs


# ------------------------------------------------------------------- R5 rpc

_RPC_FIXTURE = """\
from dataclasses import dataclass, field
from typing import Any
@dataclass
class A:
    x: int = 1
    m: dict | None = None
    spans: list = field(default_factory=list)
_ELIDE_DEFAULTS: dict[str, Any] = {'spans': [], 'gone': None, 'x': 5}
"""


def test_rpc_elide_fires_on_missing_drift_and_dead_keys(tmp_path):
    _mk(tmp_path, "runtime/rpc.py", _RPC_FIXTURE)
    msgs = "\n".join(v.message for v in _hits(tmp_path, "rpc-elide"))
    assert "Optional-default field A.m missing" in msgs
    assert "_ELIDE_DEFAULTS['x'] == 5 but A.x defaults to 1" in msgs
    assert "key 'gone' is not a field" in msgs


def test_rpc_elide_silent_on_consistent_schema(tmp_path):
    _mk(tmp_path, "runtime/rpc.py",
        "from dataclasses import dataclass, field\n"
        "from typing import Any\n"
        "@dataclass\n"
        "class A:\n"
        "    x: int = 1\n"
        "    m: dict | None = None\n"
        "    spans: list = field(default_factory=list)\n"
        "_ELIDE_DEFAULTS: dict[str, Any] = {'spans': [], 'm': None}\n")
    assert not _hits(tmp_path, "rpc-elide")


# ---------------------------------------------------------------- R6 mosaic

def test_mosaic_fires_on_narrow_compare_and_bad_unroll(tmp_path):
    _mk(tmp_path, "ops/pallas_x.py",
        "import jax.numpy as jnp\n"
        "def kernel(a, b, run):\n"
        "    m = a.astype(jnp.int8) == b\n"
        "    n = jnp.uint16(3) < b\n"
        "    run(a, unroll=7)\n"
        "    return m, n\n"
        "def unroll_for(model):\n"
        "    return 5 if model else 8\n")
    got = _hits(tmp_path, "mosaic-ceilings")
    msgs = "\n".join(v.message for v in got)
    assert "int8 vector compare" in msgs and "uint16 vector compare" in msgs
    assert "unroll=7 outside the probed set" in msgs
    assert "unroll_for returns 5" in msgs


def test_mosaic_fires_on_fdr_ceiling_breach(tmp_path):
    _mk(tmp_path, "models/fdr.py",
        "MAX_GATHERS = 96\nDOMAINS = (128, 384)\n")
    msgs = "\n".join(v.message for v in _hits(tmp_path, "mosaic-ceilings"))
    assert "MAX_GATHERS=96 exceeds the probed compile ceiling 64" in msgs
    assert "DOMAINS entry 384" in msgs


def test_mosaic_silent_on_widened_compares(tmp_path):
    _mk(tmp_path, "ops/pallas_ok.py",
        "import jax.numpy as jnp\n"
        "def kernel(ref, lo, run):\n"
        "    b = ref.astype(jnp.int32)\n"
        "    m = (b >= lo) & (b == 97)\n"  # i32 compares: the probed floor
        "    run(b, unroll=16)\n"
        "    return m | (b.astype(jnp.uint8) & 1)\n")  # cast OUTSIDE compare
    assert not _hits(tmp_path, "mosaic-ceilings")


# --------------------------------------------------------------- R7 logging

def test_logging_fires_on_print_and_root_logger(tmp_path):
    _mk(tmp_path, "parallel/x.py",
        "import logging\n"
        "log = logging.getLogger('x')\n"
        "def f():\n"
        "    print('hi')\n")
    got = _hits(tmp_path, "logging")
    msgs = "\n".join(v.message for v in got)
    assert "bare print()" in msgs and "root-logger" in msgs \
        and "without get_logger" in msgs


def test_logging_scope_and_get_logger_exemptions(tmp_path):
    _mk(tmp_path, "utils/y.py",
        "from distributed_grep_tpu.utils.logging import get_logger\n"
        "log = get_logger('y')\n")
    _mk(tmp_path, "apps/z.py", "print('cli output is fine here')\n")
    assert not _hits(tmp_path, "logging")


# -------------------------------------------------------------- R8 net-retry

def test_net_retry_fires_on_raw_urlopen_and_socket(tmp_path):
    _mk(tmp_path, "runtime/x.py",
        "import socket\n"
        "import urllib.request\n"
        "def f(url, host):\n"
        "    with urllib.request.urlopen(url, timeout=5) as r:\n"
        "        body = r.read()\n"
        "    c = socket.create_connection((host, 80))\n"
        "    return body, c\n")
    _mk(tmp_path, "__main__.py",
        "import urllib.request\n"
        "def poll(url):\n"
        "    return urllib.request.urlopen(url).read()\n")
    got = _hits(tmp_path, "net-retry")
    assert [(v.path, v.line) for v in got] == [
        ("__main__.py", 3), ("runtime/x.py", 4), ("runtime/x.py", 6),
    ]
    assert all("retry-wrapped transport helpers" in v.message for v in got)


def test_net_retry_silent_on_transport_module_and_out_of_scope(tmp_path):
    # the retry helpers themselves live on raw urlopen — exempt
    _mk(tmp_path, "runtime/http_transport.py",
        "import urllib.request\n"
        "def _request(url):\n"
        "    return urllib.request.urlopen(url).read()\n")
    # benchmarks/apps are out of scope (no control-plane retry contract)
    _mk(tmp_path, "apps/y.py",
        "import urllib.request\n"
        "def fetch(url):\n"
        "    return urllib.request.urlopen(url).read()\n")
    # server-side sockets in runtime/ are not client calls
    _mk(tmp_path, "runtime/server.py",
        "from http.server import ThreadingHTTPServer\n"
        "def serve(handler):\n"
        "    return ThreadingHTTPServer(('127.0.0.1', 0), handler)\n")
    assert not _hits(tmp_path, "net-retry")


# --------------------------------------------- suppression + CLI plumbing

def test_pragma_suppresses_named_rule_only(tmp_path):
    _mk(tmp_path, "parallel/x.py",
        "def f():\n"
        "    print('deliberate')  # analyze-ok: logging\n")
    assert not _hits(tmp_path, "logging")
    _mk(tmp_path, "parallel/y.py",
        "def f():\n"
        "    print('deliberate')  # analyze-ok: other-rule\n")
    assert any(v.path == "parallel/y.py"
               for v in _hits(tmp_path, "logging"))


def test_baseline_roundtrip_and_exit_codes(tmp_path, capsys):
    _mk(tmp_path, "parallel/x.py", "def f():\n    print('x')\n")
    root = str(tmp_path)
    assert analyze_main(["--root", root, "--rule", "logging"]) == 1
    base = tmp_path / "baseline.txt"
    assert analyze_main(["--root", root, "--rule", "logging",
                         "--write-baseline", str(base)]) == 0
    capsys.readouterr()
    assert analyze_main(["--root", root, "--rule", "logging",
                         "--baseline", str(base)]) == 0
    assert analyze_main(["--root", root, "--rule", "no-such-rule"]) == 2
    # a typo'd baseline path is a clean usage error, not a traceback
    assert analyze_main(["--root", root,
                         "--baseline", str(tmp_path / "missing.txt")]) == 2
    assert analyze_main(["--list-rules"]) == 0
    assert analyze_main(["--knobs"]) == 0
    out = capsys.readouterr().out
    assert "DGREP_BATCH_BYTES" in out


def test_json_output_shape(tmp_path, capsys):
    _mk(tmp_path, "parallel/x.py", "def f():\n    print('x')\n")
    assert analyze_main(["--root", str(tmp_path), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["count"] >= 1
    v = doc["violations"][0]
    assert set(v) == {"rule", "path", "line", "message"}


def test_every_rule_has_a_doc_line():
    from distributed_grep_tpu.analysis.rules import RULE_DOCS

    for name in RULES:
        assert RULE_DOCS[name], name


def test_project_tolerates_unparseable_file(tmp_path):
    _mk(tmp_path, "runtime/broken.py", "def f(:\n")
    # non-UTF-8 source: ast.parse raises UnicodeEncodeError on the
    # surrogateescape-decoded text — skipped like a SyntaxError
    (tmp_path / "runtime" / "binary.py").write_bytes(b'print("x\xff")\n')
    assert Project(tmp_path).tree("runtime/broken.py") is None
    assert Project(tmp_path).tree("runtime/binary.py") is None
    assert run_analysis(root=tmp_path) == []


def test_write_baseline_unwritable_path_is_clean_error(tmp_path, capsys):
    _mk(tmp_path, "parallel/x.py", "def f():\n    print('x')\n")
    rc = analyze_main(["--root", str(tmp_path), "--write-baseline",
                       str(tmp_path / "no" / "such" / "dir" / "b.txt")])
    assert rc == 2
    assert "error:" in capsys.readouterr().err

"""Runtime spine tests: scheduler semantics, full jobs, fault tolerance.

These test the capabilities the reference exhibits (SURVEY.md §4): per-file
map tasks, streaming shuffle, heartbeat-timeout re-execution, idempotent
completion, atomic commits — exactly-once output despite at-least-once
execution.
"""

import threading
import time

import pytest

from distributed_grep_tpu.apps.loader import load_application
from distributed_grep_tpu.runtime import rpc
from distributed_grep_tpu.runtime.job import run_job
from distributed_grep_tpu.runtime.scheduler import Scheduler
from distributed_grep_tpu.runtime.worker import WorkerKilled
from distributed_grep_tpu.utils.config import JobConfig


def make_config(tmp_path, corpus, pattern="hello", **kw):
    defaults = dict(
        input_files=[str(p) for p in corpus.values()],
        application="distributed_grep_tpu.apps.grep",
        app_options={"pattern": pattern},
        n_reduce=4,
        work_dir=str(tmp_path / "job"),
        task_timeout_s=2.0,
        sweep_interval_s=0.1,
    )
    defaults.update(kw)
    return JobConfig(**defaults)


# --------------------------------------------------------------- scheduler

def test_scheduler_map_before_reduce():
    s = Scheduler(files=["f1", "f2"], n_reduce=2, sweep_interval_s=0.05)
    r1 = s.assign_task(rpc.AssignTaskArgs(), timeout=1.0)
    r2 = s.assign_task(rpc.AssignTaskArgs(), timeout=1.0)
    assert {r1.assignment, r2.assignment} == {rpc.Assignment.MAP}
    assert {r1.filename, r2.filename} == {"f1", "f2"}
    assert r1.worker_id != r2.worker_id  # monotonically allocated ids
    # No reduce assignment until the map phase completes (coordinator.go:75).
    r3 = s.assign_task(rpc.AssignTaskArgs(worker_id=r1.worker_id), timeout=0.2)
    assert r3.assignment == "retry"
    s.map_finished(rpc.TaskFinishedArgs(task_id=r1.task_id, produced_parts=[0]))
    s.map_finished(rpc.TaskFinishedArgs(task_id=r2.task_id, produced_parts=[1]))
    r4 = s.assign_task(rpc.AssignTaskArgs(worker_id=r1.worker_id), timeout=1.0)
    assert r4.assignment == rpc.Assignment.REDUCE
    s.stop()


def test_scheduler_idempotent_map_finished():
    s = Scheduler(files=["f1"], n_reduce=2, sweep_interval_s=0.05)
    a = s.assign_task(rpc.AssignTaskArgs(), timeout=1.0)
    s.map_finished(rpc.TaskFinishedArgs(task_id=a.task_id, produced_parts=[0]))
    # Duplicate completion (a timed-out clone finishing late) is absorbed
    # (coordinator.go:131-134): partition list must not double-register.
    s.map_finished(rpc.TaskFinishedArgs(task_id=a.task_id, produced_parts=[0]))
    assert s.reduce_tasks[0].task_files == ["mr-0-0"]
    s.stop()


def test_scheduler_timeout_reenqueues_same_task_id():
    s = Scheduler(files=["f1"], n_reduce=1, task_timeout_s=0.3, sweep_interval_s=0.05)
    a = s.assign_task(rpc.AssignTaskArgs(), timeout=1.0)
    assert a.assignment == rpc.Assignment.MAP
    # Don't complete it; the failure detector must re-enqueue within ~0.5s.
    b = s.assign_task(rpc.AssignTaskArgs(), timeout=3.0)
    assert b.assignment == rpc.Assignment.MAP
    assert b.task_id == a.task_id  # file->task dedup keeps the id (coordinator.go:53-58)
    assert s.map_tasks[a.task_id].attempts == 2
    s.stop()


def test_scheduler_streaming_shuffle_before_map_phase_end():
    """Reducers stream files while maps still run (coordinator.go:159-174)."""
    s = Scheduler(files=["f1", "f2"], n_reduce=1, sweep_interval_s=0.05)
    a1 = s.assign_task(rpc.AssignTaskArgs(), timeout=1.0)
    s.map_finished(rpc.TaskFinishedArgs(task_id=a1.task_id, produced_parts=[0]))
    # Map phase NOT done (f2 outstanding), but partition 0 already has a file.
    r = s.reduce_next_file(rpc.ReduceNextFileArgs(task_id=0, files_processed=0), timeout=1.0)
    assert r.next_file == f"mr-{a1.task_id}-0" and not r.done
    # Next fetch blocks (long-poll) until the second map commits.
    result = {}

    def fetch():
        result["r"] = s.reduce_next_file(
            rpc.ReduceNextFileArgs(task_id=0, files_processed=1), timeout=5.0
        )

    t = threading.Thread(target=fetch)
    t.start()
    time.sleep(0.2)
    a2 = s.assign_task(rpc.AssignTaskArgs(), timeout=1.0)
    s.map_finished(rpc.TaskFinishedArgs(task_id=a2.task_id, produced_parts=[0]))
    t.join(timeout=5.0)
    assert result["r"].next_file == f"mr-{a2.task_id}-0"
    # Cursor exhausted + map phase done -> done=True.
    r3 = s.reduce_next_file(rpc.ReduceNextFileArgs(task_id=0, files_processed=2), timeout=1.0)
    assert r3.done
    s.stop()


def test_scheduler_done_predicate_is_pure():
    s = Scheduler(files=[], n_reduce=1, sweep_interval_s=0.05)
    a = s.assign_task(rpc.AssignTaskArgs(), timeout=1.0)
    assert a.assignment == rpc.Assignment.REDUCE  # zero map tasks: phase trivially done
    s.reduce_finished(rpc.TaskFinishedArgs(task_id=a.task_id))
    assert s.done() and s.done()  # callable repeatedly, no side effects
    s.stop()


# -------------------------------------------------------------- end-to-end

def test_grep_job_end_to_end(tmp_path, corpus):
    cfg = make_config(tmp_path, corpus, pattern="hello")
    res = run_job(cfg, n_workers=3)
    # Oracle: Python re over the same files, reference key format.
    expected = {}
    for name, path in corpus.items():
        for i, line in enumerate(path.read_bytes().split(b"\n"), start=1):
            if b"hello" in line:
                expected[f"{path} (line number #{i})"] = line.decode()
    # Keys contain spaces, so compare whole output lines rather than the
    # first-space-split collate view.
    lines = set()
    for f in res.output_files:
        lines.update(l for l in f.read_text().splitlines() if l)
    expected_lines = {f"{k}\t{v}" for k, v in expected.items()}
    assert lines == expected_lines
    assert res.metrics["counters"]["map_completed"] == 3
    assert res.metrics["counters"]["reduce_completed"] == 4


def test_wordcount_job_end_to_end(tmp_path, corpus):
    cfg = make_config(
        tmp_path, corpus, application="distributed_grep_tpu.apps.wordcount", app_options={}
    )
    res = run_job(cfg, n_workers=2)
    all_text = b" ".join(p.read_bytes() for p in corpus.values())
    import re as _re

    words = [w.lower() for w in _re.findall(r"[A-Za-z]+", all_text.decode())]
    assert res.results["hello"] == str(words.count("hello"))
    assert res.results["fox"] == str(words.count("fox"))


def test_job_fault_injection_worker_death_recovers(tmp_path, corpus):
    """Kill worker 0 mid-map; the job must still finish with correct output
    (at-least-once execution, exactly-once output)."""
    killed = {"n": 0}

    def die_once():
        if killed["n"] == 0:
            killed["n"] += 1
            raise WorkerKilled()

    cfg = make_config(tmp_path, corpus, task_timeout_s=1.0)
    res = run_job(
        cfg,
        n_workers=2,
        fault_hooks_per_worker=[{"before_map_commit": die_once}, {}],
    )
    assert killed["n"] == 1
    assert res.metrics["counters"]["map_completed"] == 3
    # Retry happened for the killed task.
    assert res.metrics["counters"].get("map_retries", 0) >= 1
    lines = set()
    for f in res.output_files:
        lines.update(l for l in f.read_text().splitlines())
    assert any("hello" in l for l in lines)


def test_job_journal_resume_skips_completed_work(tmp_path, corpus):
    """Coordinator crash + restart: journal replay skips finished tasks."""
    cfg = make_config(tmp_path, corpus)
    res1 = run_job(cfg, n_workers=2)
    n_outputs = len(res1.output_files)
    # "Restart": run again with resume=True — journal says everything is done,
    # so no tasks are re-assigned (metrics show zero assignments).
    res2 = run_job(cfg, n_workers=2, resume=True)
    assert res2.metrics["counters"].get("map_assigned", 0) == 0
    assert res2.metrics["counters"].get("reduce_assigned", 0) == 0
    assert len(res2.output_files) == n_outputs
    assert res2.results == res1.results


def test_duplicate_execution_is_idempotent(tmp_path, corpus):
    """Two workers racing the same re-issued task produce identical committed
    files (rename-commit makes duplicate executions safe, worker.go:103)."""
    slow_once = {"done": False}

    def stall():
        if not slow_once["done"]:
            slow_once["done"] = True
            time.sleep(2.5)  # > task_timeout_s: task gets re-issued meanwhile

    cfg = make_config(tmp_path, corpus, task_timeout_s=1.0)
    res = run_job(
        cfg,
        n_workers=2,
        fault_hooks_per_worker=[{"before_map_commit": stall}, {}],
    )
    lines = set()
    for f in res.output_files:
        lines.update(l for l in f.read_text().splitlines() if l)
    expected_lines = set()
    for name, path in corpus.items():
        for i, line in enumerate(path.read_bytes().split(b"\n"), start=1):
            if b"hello" in line:
                expected_lines.add(f"{path} (line number #{i})\t{line.decode()}")
    assert lines == expected_lines


# ---------------------------------------------------- mid-task heartbeats

def test_heartbeat_grace_window():
    """VERDICT r3 item 3: a grace-declared silent phase (cold device
    compile) extends the sweep window ONCE; a plain stamp clears it, so
    steady-state detection keeps the tight task_timeout_s."""
    from distributed_grep_tpu.runtime.types import TaskState

    s = Scheduler(files=["f1"], n_reduce=1, task_timeout_s=0.3,
                  sweep_interval_s=0.05)
    a = s.assign_task(rpc.AssignTaskArgs(), timeout=1.0)
    s.heartbeat("map", a.task_id, grace_s=2.5)
    time.sleep(0.8)  # well past task_timeout_s, inside the declared grace
    assert s.map_tasks[a.task_id].state is TaskState.IN_PROGRESS
    s.heartbeat("map", a.task_id)  # plain stamp: grace cleared
    assert s.map_tasks[a.task_id].grace_s == 0.0
    time.sleep(0.8)  # past the plain window again -> swept
    assert s.map_tasks[a.task_id].state is TaskState.UNASSIGNED
    assert s.map_tasks[a.task_id].attempts == 1  # not yet re-assigned
    # a straggler's late stamp must not resurrect the re-enqueued task
    s.heartbeat("map", a.task_id, grace_s=99.0)
    assert s.map_tasks[a.task_id].grace_s == 0.0
    s.stop()


_SLOW_APP = '''
import time

_progress = None
_mode = "progress"


def set_progress(fn):
    global _progress
    _progress = fn


def configure(mode="progress", **kw):
    global _mode
    _mode = mode


def map_fn(filename, contents):
    if _mode == "grace":
        # one declared silent phase covering the whole slow stretch
        if _progress:
            _progress(grace_s=3.0)
        time.sleep(1.0)
    elif _mode == "hang":
        time.sleep(1.0)  # no progress reported: must be swept + retried
    else:
        for _ in range(10):  # steady progress through a long map
            time.sleep(0.1)
            if _progress:
                _progress()
    return []


def reduce_fn(key, values):
    return ""
'''


@pytest.mark.parametrize("mode", ["progress", "grace"])
def test_slow_map_survives_tight_timeout_via_heartbeats(tmp_path, mode):
    """A 1 s map under a 0.4 s detector window completes in ONE attempt
    when it reports progress (or declares a compile-grace window) — the
    done-criterion for dropping the 120 s device-timeout band-aid."""
    app_py = tmp_path / "slow_app.py"
    app_py.write_text(_SLOW_APP)
    f = tmp_path / "in.txt"
    f.write_text("x\n")
    cfg = JobConfig(
        input_files=[str(f)], application=str(app_py),
        app_options={"mode": mode}, n_reduce=1,
        work_dir=str(tmp_path / "job"),
        task_timeout_s=0.4, sweep_interval_s=0.05,
    )
    res = run_job(cfg, n_workers=1)
    counters = res.metrics["counters"]
    assert counters.get("map_retries", 0) == 0
    assert counters.get("heartbeats", 0) >= 1
    assert counters["map_completed"] == 1


def test_hung_map_still_swept_under_tight_timeout(tmp_path):
    """The converse guard: a map that reports NO progress past the window
    is re-enqueued (heartbeats must not weaken failure detection)."""
    app_py = tmp_path / "slow_app.py"
    app_py.write_text(_SLOW_APP)
    f = tmp_path / "in.txt"
    f.write_text("x\n")
    cfg = JobConfig(
        input_files=[str(f)], application=str(app_py),
        app_options={"mode": "hang"}, n_reduce=1,
        work_dir=str(tmp_path / "job"),
        task_timeout_s=0.4, sweep_interval_s=0.05,
    )
    res = run_job(cfg, n_workers=2)
    counters = res.metrics["counters"]
    assert counters.get("map_retries", 0) >= 1  # swept at ~0.4 s, retried
    assert counters["map_completed"] == 1  # late duplicate absorbed


def test_results_materialize_guard(tmp_path):
    """JobResult.results refuses to materialize past the limit (the
    100 GB-path attractive-nuisance fix); streaming still works."""
    from distributed_grep_tpu.runtime.job import JobResult

    p = tmp_path / "mr-out-0"
    p.write_text("k\tv\n" * 1000)
    res = JobResult(output_files=[p])
    assert res.results == {"k": "v"}
    small = JobResult(output_files=[p])
    small.RESULTS_MATERIALIZE_LIMIT = 100
    with pytest.raises(RuntimeError, match="stream via iter_results"):
        _ = small.results
    assert sum(1 for _ in small.iter_results()) == 1000


def test_progressless_app_survives_via_compute_pump(tmp_path):
    """Apps without set_progress (wordcount-shaped) must not be swept
    mid-compute under a tight window: the worker pumps coarse liveness
    over their compute leg (process-alive semantics — the best available
    signal when the app cannot report progress)."""
    app_py = tmp_path / "mute_app.py"
    app_py.write_text(
        "import time\n"
        "def configure(**kw): pass\n"
        "def map_fn(filename, contents):\n"
        "    time.sleep(1.0)\n"
        "    return []\n"
        "def reduce_fn(key, values):\n"
        "    return ''\n"
    )
    f = tmp_path / "in.txt"
    f.write_text("x\n")
    cfg = JobConfig(
        input_files=[str(f)], application=str(app_py), app_options={},
        n_reduce=1, work_dir=str(tmp_path / "job"),
        task_timeout_s=0.4, sweep_interval_s=0.05,
    )
    res = run_job(cfg, n_workers=1)
    counters = res.metrics["counters"]
    assert counters.get("map_retries", 0) == 0
    assert counters.get("heartbeats", 0) >= 1
    assert counters["map_completed"] == 1


def test_slow_shuffle_leg_survives_tight_timeout(tmp_path, monkeypatch):
    """The map SHUFFLE leg (bucketize + intermediate writes) runs after the
    app's last progress stamp, and on match-dense output it can outlast
    the detector window by itself (observed live: a 549k-record map was
    swept mid-shuffle and re-executed).  The worker pumps coarse liveness
    over it — a slow shuffle must complete in ONE attempt even for
    progress-capable apps (whose compute pump is a nullcontext)."""
    from distributed_grep_tpu.runtime import shuffle as shuffle_mod

    app_py = tmp_path / "emit_app.py"
    app_py.write_text(  # progress-capable, and emits a record so the
        # shuffle leg actually encodes something
        "import time\n"
        "from distributed_grep_tpu.apps.base import KeyValue\n"
        "_p = None\n"
        "def set_progress(fn):\n"
        "    global _p; _p = fn\n"
        "def configure(**kw): pass\n"
        "def map_fn(filename, contents):\n"
        "    if _p: _p()\n"
        "    return [KeyValue(key='k', value='v')]\n"
        "def reduce_fn(key, values):\n"
        "    return values[0]\n"
    )
    f = tmp_path / "in.txt"
    f.write_text("x\n")

    real_encode = shuffle_mod.encode_records
    encoded = []

    def slow_encode(kvs):
        encoded.append(len(kvs))
        time.sleep(1.0)  # slower than the 0.4 s window, like dense output
        return real_encode(kvs)

    monkeypatch.setattr(shuffle_mod, "encode_records", slow_encode)
    # Round 5: small maps on the LOCAL transport skip the shuffle pump
    # (their leg is sub-ms); this test pins the pump itself, so present
    # as a remote-style transport where a slow leg is realistic at any
    # record count (a network push can stall regardless of size).
    from distributed_grep_tpu.runtime.transport import LocalTransport

    monkeypatch.setattr(LocalTransport, "is_local", False)
    cfg = JobConfig(
        input_files=[str(f)], application=str(app_py),
        app_options={}, n_reduce=1,
        work_dir=str(tmp_path / "job"),
        task_timeout_s=0.4, sweep_interval_s=0.05,
    )
    res = run_job(cfg, n_workers=1)
    counters = res.metrics["counters"]
    assert encoded, "the slow shuffle leg never ran — vacuous test"
    assert counters.get("map_retries", 0) == 0
    assert counters["map_completed"] == 1

"""FDR bucketed literal-set filter: model oracle, Pallas kernel (interpret
mode), auto-tuning, and end-to-end exactness through the engine confirm
path.  The filter itself may over-report (bucket superimposition, all-ones
stripe seeds); exactness is asserted where the system promises it — at the
line level after host confirmation — while the model-level tests assert
the filter's contract: candidates are a SUPERSET of true match ends, with
a measured false-positive rate close to the model's prediction."""

from __future__ import annotations

import numpy as np
import pytest

from distributed_grep_tpu.models import fdr as fdr_mod
from distributed_grep_tpu.ops import layout as layout_mod
from distributed_grep_tpu.ops import pallas_fdr

from tests.test_ops import make_text


def _rand_literals(n, lo, hi, seed, alphabet=b"abcdefghijklmnopqrstuvwxyz"):
    rng = np.random.default_rng(seed)
    pats = set()
    while len(pats) < n:
        k = int(rng.integers(lo, hi + 1))
        pats.add(bytes(rng.choice(list(alphabet), size=k).tolist()))
    return sorted(pats)


def _true_ends(patterns, data: bytes, ignore_case=False) -> set[int]:
    hay = data.lower() if ignore_case else data
    ends = set()
    for p in patterns:
        nd = p.lower() if ignore_case else p
        start = 0
        while True:
            i = hay.find(nd, start)
            if i < 0:
                break
            ends.add(i + len(nd))  # i+1 convention: offset of last byte + 1
            start = i + 1
    return ends


# ------------------------------------------------------------------- model

def test_candidates_superset_of_matches():
    pats = _rand_literals(200, 4, 10, seed=1)
    model = fdr_mod.compile_fdr(pats)
    data = make_text(400, inject=[(7, b"xx " + pats[0] + b" yy"),
                                  (200, pats[10] + pats[50]),
                                  (399, b"ends with " + pats[99])])
    cands = set(fdr_mod.reference_candidates_model(model, data).tolist())
    assert _true_ends(pats, data) <= cands


def test_fp_rate_close_to_estimate():
    pats = _rand_literals(300, 5, 9, seed=2)
    model = fdr_mod.compile_fdr(pats, fp_budget_per_byte=1e-3)
    rng = np.random.default_rng(3)
    data = rng.integers(97, 123, size=1 << 20, dtype=np.uint8).tobytes()
    cands = fdr_mod.reference_candidates_model(model, data)
    true = _true_ends(pats, data)
    fp = len(set(cands.tolist()) - true) / len(data)
    # estimate assumes uniform pairs; lowercase text is close enough that
    # the empirical rate should be within ~30x (and under budget x30)
    assert fp <= max(model.fp_per_byte * 30, 3e-3), (fp, model.fp_per_byte)


def test_ignore_case_folding():
    pats = [b"NeedLe", b"VOLCANO"]
    model = fdr_mod.compile_fdr(pats, ignore_case=True)
    data = b"a nEEdle here\nand a volCANO there\n"
    cands = set(fdr_mod.reference_candidates_model(model, data).tolist())
    assert _true_ends(pats, data, ignore_case=True) <= cands


def test_length_stratification_and_short_patterns():
    pats = [b"ab", b"cd", b"needle", b"volcano", b"xy"] + _rand_literals(40, 6, 8, seed=4)
    model = fdr_mod.compile_fdr(pats)
    ms = sorted({b.m for b in model.banks})
    assert ms[0] == 1  # len-2 group got its own window
    data = b"ab here\nneedle there\nxy\n" + make_text(50)
    cands = set(fdr_mod.reference_candidates_model(model, data).tolist())
    assert _true_ends(pats, data) <= cands


def test_rejects_unusable_literals():
    with pytest.raises(fdr_mod.FdrError):
        fdr_mod.compile_fdr([b"a"])  # too short for a pair
    with pytest.raises(fdr_mod.FdrError):
        fdr_mod.compile_fdr([b"has\nnewline"])
    with pytest.raises(fdr_mod.FdrError):
        fdr_mod.compile_fdr([])


def test_big_set_banks_within_budget():
    pats = _rand_literals(2000, 5, 9, seed=5)
    model = fdr_mod.compile_fdr(pats, fp_budget_per_byte=2e-4)
    assert model.n_patterns == 2000
    for b in model.banks:
        assert 1 <= b.m <= fdr_mod.MAX_DEPTHS
        for _, _, d in b.checks:
            assert d in fdr_mod.DOMAINS
        assert b.total_gathers <= fdr_mod.MAX_GATHERS
    # cost search should prefer meeting the budget when feasible
    assert model.fp_per_byte <= 2e-3


def test_clustered_check_cells_never_split():
    """The cell-snapped clustered check assigns each hash cell to exactly
    one bucket, so its bucket densities sum to used_cells/domain <= 1 —
    the property that makes it worth one gather (models/fdr._bucket_of).
    Rank-range assignment (v2) would split ~N_BUCKETS cells and push the
    sum toward 1.25."""
    pats = _rand_literals(3000, 5, 9, seed=11)
    model = fdr_mod.compile_fdr(pats)
    for b in model.banks:
        slot, fam, domain = b.checks[0]
        assert (slot, fam, domain) == (b.m - 1, 0, fdr_mod.CLUSTER_DOMAIN)
        # no cell split: each table cell's mask is a single-bucket bit
        t = b.tables[0]
        nonzero = t[t != 0]
        assert np.all((nonzero & (nonzero - 1)) == 0)
        bits = (t[:, None] >> np.arange(32, dtype=np.uint32)) & 1
        assert float((bits.sum(axis=0) / t.shape[0]).sum()) <= 1.0 + 1e-9


# ------------------------------------------------------------------ kernel

def _kernel_vs_reference(pats, data, **compile_kw):
    model = fdr_mod.compile_fdr(pats, **compile_kw)
    if model.ignore_case:
        data_f = bytes(data).lower()
    else:
        data_f = data
    lay = layout_mod.choose_layout(
        len(data_f), target_lanes=4096, min_chunk=512,
        lane_multiple=4096, chunk_multiple=512,
    )
    arr = layout_mod.to_device_array(data_f, lay)
    for bank in model.banks:
        got = pallas_fdr.fdr_scan(arr, bank, interpret=True)
        # expected: reference per lane-stripe (each lane is its own stripe)
        want = np.zeros((lay.chunk, lay.lanes), dtype=bool)
        for lane in range(lay.lanes):
            stripe = bytes(arr[:, lane])
            ends = fdr_mod.reference_candidates(bank, stripe)
            want[(ends - 1).astype(np.int64), lane] = True
        np.testing.assert_array_equal(
            got, np.packbits(want, axis=1, bitorder="little")
        )


def test_pallas_fdr_interpret_matches_reference():
    pats = _rand_literals(60, 4, 9, seed=6)
    data = make_text(
        120,
        inject=[(3, b"xx " + pats[0] + b" yy"), (60, pats[1] + b" " + pats[2])],
    )
    _kernel_vs_reference(pats, data)


def test_pallas_fdr_interpret_multi_subtable():
    # force a multi-subtable domain (n_sub > 1) via a big enough set
    pats = _rand_literals(5000, 5, 9, seed=7)
    model = fdr_mod.compile_fdr(pats)
    assert any(b.domain >= 256 for b in model.banks)
    data = make_text(60, inject=[(5, pats[3] + b" mid " + pats[4])])
    _kernel_vs_reference(pats, data)


def test_pallas_fdr_short_window_bank():
    _kernel_vs_reference([b"ab", b"zq", b"needle"], make_text(60, inject=[(2, b"zq ab")]))


def test_device_tables_layout():
    pats = _rand_literals(100, 4, 8, seed=8)
    model = fdr_mod.compile_fdr(pats)
    bank = model.banks[0]
    tiles = pallas_fdr.bank_device_tables(bank)
    n_rows = sum(d // 128 for _, _, d in bank.checks)
    assert tiles.shape == (n_rows, 32, 128)
    # per-check subtables stack in plan order; any sublane row holds the
    # broadcast 128-entry slice
    row = 0
    for i, (_, _, d) in enumerate(bank.checks):
        for j in range(d // 128):
            np.testing.assert_array_equal(
                tiles[row, 5],
                bank.tables[i][j * 128 : (j + 1) * 128],
            )
            row += 1


# ----------------------------------------------------- engine (device path)

def test_engine_fdr_end_to_end_interpret(monkeypatch):
    """Full engine path: FDR candidates on the (interpreted) kernel, host
    confirm, boundary stitching — output must equal the oracle exactly."""
    from distributed_grep_tpu.ops import engine as engine_mod
    from distributed_grep_tpu.ops import pallas_scan

    pats = _rand_literals(150, 4, 9, seed=9)
    data = make_text(
        150,
        inject=[(2, b"xx " + pats[0] + b" yy"),
                (75, pats[1] + b" and " + pats[2]),
                (149, b"tail " + pats[3])],
    )
    monkeypatch.setattr(pallas_scan, "available", lambda: True)
    orig = pallas_fdr.fdr_scan_words
    monkeypatch.setattr(
        pallas_fdr, "fdr_scan_words",
        lambda arr, bank, dev_tables=None, interpret=None:
            orig(arr, bank, dev_tables=dev_tables, interpret=True),
    )
    eng = engine_mod.GrepEngine(patterns=[p.decode("latin-1") for p in pats])
    assert eng.mode == "fdr"
    res = eng.scan(data)
    want = fdr_mod.exact_match_lines(pats, data, ignore_case=False)
    assert set(res.matched_lines.tolist()) == want


def test_engine_fdr_ignore_case_interpret(monkeypatch):
    from distributed_grep_tpu.ops import engine as engine_mod
    from distributed_grep_tpu.ops import pallas_scan

    pats = [b"NEEDLE", b"VolCano", b"qq"]
    data = make_text(60, inject=[(5, b"a needle B"), (30, b"VOLCANO qQ")])
    monkeypatch.setattr(pallas_scan, "available", lambda: True)
    orig = pallas_fdr.fdr_scan_words
    monkeypatch.setattr(
        pallas_fdr, "fdr_scan_words",
        lambda arr, bank, dev_tables=None, interpret=None:
            orig(arr, bank, dev_tables=dev_tables, interpret=True),
    )
    eng = engine_mod.GrepEngine(
        patterns=[p.decode() for p in pats], ignore_case=True
    )
    assert eng.mode == "fdr"
    res = eng.scan(data)
    assert set(res.matched_lines.tolist()) == fdr_mod.exact_match_lines(
        pats, data, ignore_case=True
    )


def test_engine_fdr_kernel_failure_falls_back(monkeypatch):
    from distributed_grep_tpu.ops import engine as engine_mod
    from distributed_grep_tpu.ops import pallas_scan

    pats = _rand_literals(50, 4, 8, seed=10)
    data = make_text(60, inject=[(7, pats[0] + b" here")])
    monkeypatch.setattr(pallas_scan, "available", lambda: True)

    def boom(*a, **k):
        raise RuntimeError("mosaic says no")

    monkeypatch.setattr(pallas_fdr, "fdr_scan_words", boom)
    eng = engine_mod.GrepEngine(patterns=[p.decode("latin-1") for p in pats])
    assert eng.mode == "fdr"
    res = eng.scan(data)  # must fall back to exact DFA banks, not raise
    assert eng._fdr_broken
    assert set(res.matched_lines.tolist()) == fdr_mod.exact_match_lines(
        pats, data, ignore_case=False
    )


def test_engine_cpu_backend_ignores_fdr():
    from distributed_grep_tpu.ops import engine as engine_mod

    pats = _rand_literals(20, 4, 8, seed=11)
    data = make_text(40, inject=[(3, pats[0])])
    eng = engine_mod.GrepEngine(
        patterns=[p.decode("latin-1") for p in pats], backend="cpu"
    )
    assert eng.mode == "native" and eng.fdr is None
    res = eng.scan(data)
    assert set(res.matched_lines.tolist()) == fdr_mod.exact_match_lines(
        pats, data, ignore_case=False
    )


def test_too_dense_set_raises():
    # ~16k distinct full-alphabet 2-byte literals saturate every table and
    # hash combination: the model must refuse (engine then keeps the exact
    # DFA banks)
    rng = np.random.default_rng(12)
    pats = {bytes(p.tolist()) for p in rng.integers(1, 256, size=(30000, 2), dtype=np.uint8)}
    pats = sorted(p for p in pats if b"\n" not in p)[:16384]
    with pytest.raises(fdr_mod.FdrError):
        fdr_mod.compile_fdr(pats)


# ---------------------------------------------- literal-set decomposition

def test_alternation_routes_to_pattern_set_engines():
    """Hyperscan-style literal decomposition: a finite-literal-set regex
    compiles to the pattern-set engines (FDR on device), not the NFA."""
    from distributed_grep_tpu.ops.engine import GrepEngine

    eng = GrepEngine("(volcano|anarchism|needle)")
    assert eng.mode in ("fdr", "dfa") and len(eng.tables) >= 1
    assert eng.pattern == "(volcano|anarchism|needle)"
    got = set(eng.scan(b"a volcano\nx\nneedles\nanarchism!\n").matched_lines.tolist())
    assert got == {1, 3, 4}
    # 1-byte members are FDR-ineligible on device: regex paths keep them
    assert GrepEngine("(a|b)").mode == "nfa"
    # class-sequences keep the (faster) single-pass shift-and path
    assert GrepEngine("h[ae]llo").mode == "shift_and"
    # case-insensitive decomposition folds in the set engines, not by
    # enumerating case variants
    ci = GrepEngine("nee(dle|t)", ignore_case=True)
    assert ci.mode in ("fdr", "dfa")
    assert set(ci.scan(b"NEEDLE\nneet\nneat\n").matched_lines.tolist()) == {1, 2}


def test_literal_set_enumeration_caps_and_rejects():
    from distributed_grep_tpu.models.dfa import enumerate_literal_set

    assert enumerate_literal_set("(ab|cd)") == [b"ab", b"cd"]
    assert enumerate_literal_set("x[01][01]") == [b"x00", b"x01", b"x10", b"x11"]
    assert enumerate_literal_set("a+") is None          # unbounded
    assert enumerate_literal_set("^ab") is None         # anchored
    assert enumerate_literal_set("(a|)") is None        # empty member
    assert enumerate_literal_set("[0-9]{4}") is None    # 10^4 > cap
    assert enumerate_literal_set("volcano") == [b"volcano"]


# ------------------------------ FDR-ineligible device-cliff routing (round 3)

def test_fdr_ineligible_set_routes_to_native():
    """A set too dense for the FDR filter must route --backend device to the
    native MT host scanner (exact, ~GB/s) instead of the ~0.1 GB/s XLA
    DFA-bank device path (VERDICT r2 item 5)."""
    from distributed_grep_tpu.ops.engine import GrepEngine
    from distributed_grep_tpu.utils.native import native_available

    if not native_available():
        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(9)
    raw = sorted({bytes(x) for x in rng.integers(0, 256, size=(25000, 3)).tolist()
                  if 10 not in x})
    eng = GrepEngine(patterns=raw, backend="device")
    assert eng.mode == "native"
    data = b"needle xyw\nno hit Q9w\n" + raw[17] + b" yes\nNOPE Q!\n"
    got = set(eng.scan(data).matched_lines.tolist())
    sp = set(raw)
    expected = {
        i for i, l in enumerate(data.split(b"\n")[:-1], 1)
        if any(q in l for q in sp)
    }
    assert got == expected


def test_all_short_pattern_set_routes_to_pairset():
    """1-2-byte sets never reach the FDR compiler; since round 4 the
    structured ones get the exact pairset device kernel (models/pairset)
    instead of the native-host consolation route."""
    from distributed_grep_tpu.ops.engine import GrepEngine

    eng = GrepEngine(patterns=["a", "b"], backend="device")
    assert eng.mode == "pairset"
    got = set(eng.scan(b"xyz\nqab\nccc\nBa\n").matched_lines.tolist())
    assert got == {2, 4}


def test_unfactorizable_short_set_still_routes_to_native():
    """A random dense pair set defeats both pairset orientations (> 32 row
    and column classes); it must keep the native MT route, never the
    device DFA cliff."""
    from distributed_grep_tpu.ops.engine import GrepEngine
    from distributed_grep_tpu.utils.native import native_available

    if not native_available():
        pytest.skip("native lib unavailable")
    rng = np.random.default_rng(8)
    pats = sorted({bytes(rng.integers(32, 127, size=2).tolist())
                   for _ in range(3000)} - {b"\n\n"})
    eng = GrepEngine(patterns=pats, backend="device")
    assert eng.mode == "native"


# ------------------------------------ tuner self-calibration (round 3)

def test_probe_recovers_from_poisoned_confirm_constant(monkeypatch):
    """Inject an absurd priced confirm cost ('confirm is free'); the init
    probe must measure the real cost and retune the plan back toward the
    honestly-priced one (VERDICT r2 item 3 done-criterion)."""
    import distributed_grep_tpu.models.fdr as fdr_mod
    from distributed_grep_tpu.ops.engine import GrepEngine

    rng = np.random.default_rng(5)
    alphabet = list(b"abcdefghijklmnopqrstuvwxyz0123456789")
    pats = sorted({
        bytes(rng.choice(alphabet, size=int(rng.integers(5, 9))).tolist())
        for _ in range(3000)
    })
    spats = [p.decode() for p in pats]

    monkeypatch.setattr(fdr_mod, "CONFIRM_PS_PER_CANDIDATE", 1.0)
    monkeypatch.setenv("DGREP_NO_CALIBRATE", "1")
    eng_bad = GrepEngine(patterns=spats)
    g_bad = sum(b.total_gathers for b in eng_bad.fdr.banks)

    monkeypatch.delenv("DGREP_NO_CALIBRATE")
    # pin the probe's measurement (real timing is load-dependent; the
    # wiring probe->mismatch->retune is what's under test)
    import distributed_grep_tpu.ops.engine as engine_mod  # noqa: F401
    monkeypatch.setattr(fdr_mod, "probe_confirm_ps", lambda cs, **kw: 8600.0)
    eng_fix = GrepEngine(patterns=spats)
    g_fix = sum(b.total_gathers for b in eng_fix.fdr.banks)
    assert eng_fix.calibration["confirm_probe_ps"] == 8600.0
    # probe-calibrated plan buys more device gathers than the 'free
    # confirm' plan, converging toward the honest plan
    assert g_fix > g_bad
    # and it equals a plan compiled directly under the measured pricing
    from dataclasses import replace

    pricing = replace(
        fdr_mod.default_pricing(), confirm_ps_per_candidate=8600.0
    )
    direct = fdr_mod.compile_fdr(spats, pricing=pricing)
    assert [(b.m, b.checks) for b in eng_fix.fdr.banks] == \
        [(b.m, b.checks) for b in direct.banks]


def test_post_scan_retune_from_measured_stats():
    """Stage-2 retune: measured candidate rate and confirm wall far off the
    priced constants must swap in a plan compiled under measured pricing."""
    import os

    import distributed_grep_tpu.models.fdr as fdr_mod
    from distributed_grep_tpu.ops.engine import GrepEngine

    rng = np.random.default_rng(6)
    alphabet = list(b"abcdefghijklmnopqrstuvwxyz0123456789")
    pats = sorted({
        bytes(rng.choice(alphabet, size=int(rng.integers(5, 9))).tolist())
        for _ in range(3000)
    })
    eng = GrepEngine(patterns=[p.decode() for p in pats])
    g0 = sum(b.total_gathers for b in eng.fdr.banks)
    # manufacture evidence: candidates 20x the plan's expectation and a
    # very slow confirm -> the retune must buy more gathers
    n_bytes = 64 * 1024 * 1024
    fake_cands = int(eng.fdr.fp_per_byte * 20 * n_bytes)
    actual_threads = min(8, os.cpu_count() or 1)
    eng.stats = {
        "candidates": fake_cands,
        # 400 ns wall per candidate through the actual fan
        "confirm_seconds": fake_cands * 400e-9,
    }
    eng._maybe_retune_fdr(n_bytes)
    assert eng._fdr_retuned
    g1 = sum(b.total_gathers for b in eng.fdr.banks)
    assert g1 > g0  # slow+dense confirm evidence -> more filtering on device
    assert eng.calibration["measured_fp_bias"] == pytest.approx(20.0, rel=0.01)

    # within-tolerance evidence must NOT retune (runs-once flag aside)
    eng2 = GrepEngine(patterns=[p.decode() for p in pats])
    plan2 = [(b.m, b.checks) for b in eng2.fdr.banks]
    pr = eng2._fdr_pricing
    cands2 = int(eng2.fdr.fp_per_byte * pr.fp_bias * n_bytes)
    eng2.stats = {
        "candidates": cands2,
        "confirm_seconds": cands2 * (pr.confirm_ps_per_candidate / 1e12)
        / actual_threads * actual_threads,
    }
    eng2._maybe_retune_fdr(n_bytes)
    assert [(b.m, b.checks) for b in eng2.fdr.banks] == plan2


def test_scan_stays_exact_after_retune_swap():
    """After the stage-2 retune swaps the FDR plan, the next scan must
    re-upload the new bank tables and stay exact (pins the
    _fdr_dev_tables reset path)."""
    import os

    from distributed_grep_tpu.ops.engine import GrepEngine

    rng = np.random.default_rng(12)
    alphabet = list(b"abcdefghijklmnopqrstuvwxyz0123456789")
    pats = sorted({
        bytes(rng.choice(alphabet, size=int(rng.integers(5, 9))).tolist())
        for _ in range(2000)
    })
    eng = GrepEngine(patterns=[p.decode() for p in pats], interpret=True)
    assert eng.mode == "fdr"
    plan0 = [(b.m, b.checks) for b in eng.fdr.banks]

    n_bytes = 64 * 1024 * 1024
    fake = int(eng.fdr.fp_per_byte * 20 * n_bytes)
    eng.stats = {"candidates": fake, "confirm_seconds": fake * 400e-9}
    eng._maybe_retune_fdr(n_bytes)
    assert eng._fdr_retuned
    assert [(b.m, b.checks) for b in eng.fdr.banks] != plan0  # plan swapped
    assert eng._fdr_dev_tables is None  # tables re-upload lazily

    lines = []
    for i in range(400):
        n = int(rng.integers(0, 50))
        lines.append(bytes(rng.choice(alphabet + [32], size=n).tolist()))
        if i % 37 == 3:
            lines[-1] = b"xx " + pats[int(rng.integers(0, len(pats)))] + b" yy"
    data = b"\n".join(lines) + b"\n"
    expected = {
        i for i, ln in enumerate(data.split(b"\n")[:-1], 1)
        if any(p in ln for p in pats)
    }
    got = set(eng.scan(data).matched_lines.tolist())
    assert got == expected


def test_chip_aware_pricing_buys_more_filtering():
    """VERDICT r3 item 1: the confirm threads are shared across a host's
    active chips, so Pricing.n_chips must shift the tuner toward more
    device gathers / lower candidate rates as the chip count grows —
    monotonically, and with a strict flip by 4 chips on a config-5-shaped
    set."""
    from dataclasses import replace

    pats = _rand_literals(
        3000, 5, 8, seed=21, alphabet=b"abcdefghijklmnopqrstuvwxyz0123456789"
    )
    base = replace(
        fdr_mod.default_pricing(),
        confirm_ps_per_candidate=8600.0, confirm_threads=8, n_chips=1,
    )
    models = {
        nc: fdr_mod.compile_fdr(pats, pricing=replace(base, n_chips=nc))
        for nc in (1, 4)
    }
    g1 = sum(b.total_gathers for b in models[1].banks)
    g4 = sum(b.total_gathers for b in models[4].banks)
    assert g4 > g1  # 4 chips -> confirm share quartered -> buy filtering
    assert models[4].fp_per_byte < models[1].fp_per_byte
    # and the wall model itself scales: same plan, 4x the confirm wall
    pr4 = replace(base, n_chips=4)
    assert pr4.confirm_wall_ps(0.01) == pytest.approx(
        4 * base.confirm_wall_ps(0.01)
    )


def test_engine_mesh_chip_count_pricing(monkeypatch):
    """An engine driving an 8-device mesh must price the FDR confirm leg
    at the 8-chip share from construction (not only after a retune)."""
    from dataclasses import replace

    from distributed_grep_tpu.ops.engine import GrepEngine
    from distributed_grep_tpu.parallel.mesh import make_mesh

    monkeypatch.setenv("DGREP_NO_CALIBRATE", "1")  # pin: no probe swap
    pats = _rand_literals(
        3000, 5, 8, seed=21, alphabet=b"abcdefghijklmnopqrstuvwxyz0123456789"
    )
    spats = [p.decode() for p in pats]
    eng1 = GrepEngine(patterns=spats, interpret=True)
    mesh = make_mesh((8,), ("data",))
    eng8 = GrepEngine(patterns=spats, mesh=mesh, interpret=True)
    assert eng1._fdr_pricing.n_chips == 1
    assert eng8._fdr_pricing.n_chips == 8
    direct = fdr_mod.compile_fdr(
        spats, pricing=replace(fdr_mod.default_pricing(), n_chips=8)
    )
    assert [(b.m, b.checks) for b in eng8.fdr.banks] == \
        [(b.m, b.checks) for b in direct.banks]
    # EP on a 2D mesh: the pattern axis scans concurrently too
    mesh2 = make_mesh((4, 2), ("data", "seq"))
    eng_ep = GrepEngine(
        patterns=spats, mesh=mesh2, mesh_axis="data", pattern_axis="seq",
        interpret=True,
    )
    assert eng_ep._fdr_pricing.n_chips == 8


def test_pipeline_compression_preserves_candidates():
    """Round-4 m-compression: a plan probing only shallow depths drops its
    dead pipeline slots; the candidate stream is unchanged except for
    LESS stripe-head over-report (the all-ones seed shrinks)."""
    words = [b"volcano", b"anarchism", b"needleqq", b"breadth",
             b"journal", b"mineral", b"quantum", b"physics"]
    model = fdr_mod.compile_fdr(words)
    bank = model.banks[0]
    depths = [bank.m - 1 - s for s, _, _ in bank.checks]
    assert bank.m == max(depths) + 1  # compressed to the used depth range
    assert bank.m < min(len(w) for w in words) - 1  # actually shrank
    # reconstruct the uncompressed form and compare candidate streams
    m_old = min(len(w) for w in words) - 1
    checks_old = tuple(
        (m_old - 1 - d, fam, dom)
        for d, (_, fam, dom) in zip(depths, bank.checks)
    )
    b_old = fdr_mod.FdrBank(
        m=m_old, checks=checks_old, tables=bank.tables,
        patterns=bank.patterns, fp_per_byte=bank.fp_per_byte,
    )
    data = make_text(300, inject=[(4, b"xx volcano yy"),
                                  (150, b"physics anarchism"),
                                  (299, b"tail quantum")])
    got = set(fdr_mod.reference_candidates(bank, data).tolist())
    old = set(fdr_mod.reference_candidates(b_old, data).tolist())
    # identical beyond the old seed window; inside it only over-report may
    # differ (compressed seeds fewer positions) — never a lost candidate
    assert {e for e in got if e > m_old} == {e for e in old if e > m_old}
    assert got <= old
    assert _true_ends(words, data) <= got


def test_kernel_failure_mid_multisegment_scan_with_collect_pool(monkeypatch):
    """Round-4 regression: with collects on a pool, a kernel that fails on
    a LATER segment (first consumed inside a collect future) must still
    trip the fallback net and produce the exact result — the failure
    surfaces via future.result() instead of an inline call now."""
    from distributed_grep_tpu.ops import engine as engine_mod
    from distributed_grep_tpu.ops import pallas_scan

    pats = _rand_literals(60, 4, 8, seed=13)
    data = make_text(
        3000,
        inject=[(5, pats[0] + b" head"), (1500, b"mid " + pats[1]),
                (2999, b"tail " + pats[2])],
    )
    monkeypatch.setattr(pallas_scan, "available", lambda: True)
    calls = {"n": 0}
    real = pallas_fdr.fdr_scan_words

    def flaky(arr, bank, **kw):
        calls["n"] += 1
        if calls["n"] >= 3:
            raise RuntimeError("mosaic says no, mid-scan")
        kw["interpret"] = True
        return real(arr, bank, **kw)

    monkeypatch.setattr(pallas_fdr, "fdr_scan_words", flaky)
    eng = engine_mod.GrepEngine(
        patterns=[p.decode("latin-1") for p in pats], segment_bytes=16 * 1024
    )
    assert eng.mode == "fdr"
    assert len(data) // (16 * 1024) >= 4
    res = eng.scan(data)
    assert eng._fdr_broken
    assert set(res.matched_lines.tolist()) == fdr_mod.exact_match_lines(
        pats, data, ignore_case=False
    )


def test_mixed_set_short_members_ride_the_device():
    """A set mixing long literals with 1-byte members: the shorts run the
    exact pairset kernel OR'd into the FDR candidate words (round 4 — the
    old host AC scan serialized the dispatch loop ~40x the device leg),
    and the extended ConfirmSet keeps the union exact."""
    from distributed_grep_tpu.ops import engine as engine_mod

    pats = _rand_literals(60, 4, 8, seed=14) + [b"!", b"~"]
    data = make_text(
        2500,
        inject=[(3, pats[0] + b" head"), (1200, b"bang ! mid"),
                (2499, b"tilde ~ tail " + pats[1])],
    )
    eng = engine_mod.GrepEngine(
        patterns=[p.decode("latin-1") for p in pats], interpret=True,
        segment_bytes=16 * 1024,
    )
    assert eng.mode == "fdr"
    assert eng._fdr_pairset is not None
    res = eng.scan(data)
    assert set(res.matched_lines.tolist()) == fdr_mod.exact_match_lines(
        pats, data, ignore_case=False
    )

    # and through the mesh path (lane-sharded FDR + lane-sharded pairset)
    from distributed_grep_tpu.parallel.mesh import make_mesh

    eng_m = engine_mod.GrepEngine(
        patterns=[p.decode("latin-1") for p in pats], interpret=True,
        mesh=make_mesh((8,), ("data",)),
    )
    assert eng_m.mode == "fdr" and eng_m._fdr_pairset is not None
    res_m = eng_m.scan(data)
    assert set(res_m.matched_lines.tolist()) == fdr_mod.exact_match_lines(
        pats, data, ignore_case=False
    )
    # the MESH kernels actually ran (a silent fallback to the exact host
    # path would still pass the oracle check)
    assert eng_m.stats.get("psum_candidates", 0) >= 1
    assert not eng_m._fdr_broken and not eng_m._pallas_broken
    # and the stats-based retune is disabled for mixed sets (exact pairset
    # matches pollute the candidate-rate measurement)
    eng_m.stats["candidates"] = 10_000_000
    eng_m.stats["confirm_seconds"] = 1.0
    eng_m._maybe_retune_fdr(1 << 26)
    assert not eng_m._fdr_retuned


def test_mixed_set_dense_short_member_routes_to_native():
    """A mixed set whose 1-byte member is expected-dense (' ') must not
    attach the pairset sidecar: every occurrence would become a
    device-reported candidate and the collect path's O(candidates)
    coordinate fetch + confirm would swamp the scan (round-4 review
    finding).  The whole set keeps the loud native route, exact."""
    from distributed_grep_tpu.ops import engine as engine_mod

    pats = _rand_literals(40, 4, 8, seed=77) + [b" "]
    eng = engine_mod.GrepEngine(
        patterns=[p.decode("latin-1") for p in pats], interpret=True,
    )
    assert eng.mode in ("native", "dfa")
    assert eng._fdr_pairset is None and eng.fdr is None
    data = make_text(400, inject=[(3, pats[0] + b"-x"), (200, b"nospacehere")])
    res = eng.scan(data)
    import distributed_grep_tpu.models.fdr as fdr_mod
    assert set(res.matched_lines.tolist()) == fdr_mod.exact_match_lines(
        pats, data, ignore_case=False
    )

"""utils/lockdep.py — the runtime lock-discipline harness.

The acceptance bar (ISSUE 9): the harness demonstrably catches a seeded
A->B/B->A inversion and a blocking-syscall-while-held, honors the io_ok
escape, and costs nothing when off (make_lock hands out raw Locks).  The
suite-level audit itself rides the autouse conftest fixture on the
`service`/`chaos`/`soak_mini` markers; these are the harness's own unit
semantics.
"""

from __future__ import annotations

import os
import threading
import time

import pytest

from distributed_grep_tpu.utils import lockdep


@pytest.fixture(autouse=True)
def _active_harness():
    """Each test runs with a fresh, activated harness and leaves the
    process exactly as found (patched syscalls restored)."""
    lockdep.activate()
    lockdep.reset()
    yield
    lockdep.deactivate()
    lockdep.reset()


def test_make_lock_is_raw_when_off(monkeypatch):
    # guard against an operator shell exporting DGREP_LOCKDEP=1
    monkeypatch.delenv("DGREP_LOCKDEP", raising=False)
    lockdep.deactivate()  # undo the fixture's activation for this test
    try:
        assert not lockdep.active()
        lk = lockdep.make_lock("off-test")
        assert isinstance(lk, type(threading.Lock()))
    finally:
        lockdep.activate()  # restore for the fixture's teardown pairing


def test_seeded_inversion_is_detected():
    """A deliberate A->B then B->A acquisition (sequential — lockdep
    order violations need no actual deadlock to be real) records one
    inversion naming both locks."""
    a = lockdep.make_lock("inv-a")
    b = lockdep.make_lock("inv-b")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    report = lockdep.report()
    assert "inv-a -> inv-b" in report["edges"]
    assert "inv-b -> inv-a" in report["edges"]
    (inv,) = report["inversions"]
    assert set(inv["edge"]) == {"inv-a", "inv-b"}
    assert inv["stack"], "the inversion must carry an acquisition stack"


def test_consistent_order_is_clean():
    a = lockdep.make_lock("ord-a")
    b = lockdep.make_lock("ord-b")
    for _ in range(3):
        with a:
            with b:
                pass
    report = lockdep.report()
    assert report["inversions"] == []
    assert "ord-a -> ord-b" in report["edges"]


def test_cross_thread_inversion_is_detected():
    """The service regime: thread 1 takes A then B, thread 2 takes B
    then A — sequenced so the test cannot deadlock, but the graph sees
    both orders."""
    a = lockdep.make_lock("xt-a")
    b = lockdep.make_lock("xt-b")

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    th = threading.Thread(target=t1)
    th.start()
    th.join()
    th = threading.Thread(target=t2)
    th.start()
    th.join()
    assert len(lockdep.report()["inversions"]) == 1


def test_blocking_syscall_while_held(tmp_path):
    lk = lockdep.make_lock("blk")
    p = tmp_path / "f"
    p.write_text("x")  # outside the lock: not an event
    before = len(lockdep.report()["blocking"])
    with lk:
        time.sleep(0)
    events = lockdep.report()["blocking"][before:]
    assert any(e["lock"] == "blk" and "sleep" in e["call"] for e in events)


def test_fsync_while_held_and_io_ok_escape(tmp_path):
    hot = lockdep.make_lock("hot")
    io = lockdep.make_lock("flush", io_ok=True)
    with open(tmp_path / "f", "w") as f:
        f.write("x")
        f.flush()
        with hot:
            os.fsync(f.fileno())
        with io:
            os.fsync(f.fileno())
    events = lockdep.report()["blocking"]
    assert any(e["lock"] == "hot" and "fsync" in e["call"] for e in events)
    assert not any(e["lock"] == "flush" for e in events)


def test_io_ok_inner_under_hot_outer_still_reports():
    """io_ok exempts the io lock ITSELF, not a hot lock held above it."""
    hot = lockdep.make_lock("outer-hot")
    io = lockdep.make_lock("inner-io", io_ok=True)
    with hot:
        with io:
            time.sleep(0)
    events = lockdep.report()["blocking"]
    assert any(e["lock"] == "outer-hot" for e in events)


def test_condition_wait_releases_the_held_entry():
    """threading.Condition over a tracked lock: wait() releases through
    the wrapper, so a syscall during the wait window on ANOTHER thread's
    behalf is not charged to this thread — and after wait returns the
    lock is held again."""
    lk = lockdep.make_lock("cond-lock")
    cond = threading.Condition(lk)
    hit = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            hit.append(len(getattr(lockdep._tls, "held", [])))

    th = threading.Thread(target=waiter)
    th.start()
    time.sleep(0.1)
    # while the waiter sleeps inside wait(), ITS thread released the lock
    with cond:
        cond.notify_all()
    th.join(timeout=5)
    assert hit == [1]  # re-acquired (tracked) when wait returned
    assert lockdep.report()["blocking"] == []


def test_nonblocking_acquire_failure_records_nothing():
    lk = lockdep.make_lock("nb")
    with lk:
        got = lk.acquire(False)  # Condition._is_owned probe shape
        assert not got
    assert lockdep.report()["inversions"] == []


def test_rlock_reentry_is_one_hold():
    rl = lockdep.make_rlock("re")
    other = lockdep.make_lock("re-other")
    with rl:
        with rl:  # reentrant: NOT a self-deadlock, not an edge
            with other:
                pass
    report = lockdep.report()
    assert report["inversions"] == []
    assert "re -> re-other" in report["edges"]


def test_env_enabled_run_instruments_module_registries():
    """DGREP_LOCKDEP=1 in the environment (the deployment/debug switch)
    must instrument the locks the ops modules construct at IMPORT time —
    model cache, device probe, reader pools, corpus cache — which the
    per-test fixture can never reach (they predate any activate()).
    Run in a subprocess so the import happens under the env var."""
    import subprocess
    import sys

    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "from distributed_grep_tpu.utils import lockdep\n"
        "assert lockdep.active(), 'env var must switch the harness on'\n"
        "from distributed_grep_tpu.ops import engine, layout\n"
        "for lk, io_ok in ((engine._model_cache_lock, True),\n"
        "                  (engine._model_cache_stats_lock, False),\n"
        "                  (engine._device_probe_lock, True),\n"
        "                  (engine._reader_pools_lock, False),\n"
        "                  (layout.corpus_cache()._lock, False)):\n"
        "    assert isinstance(lk, lockdep._TrackedLock), lk\n"
        "    assert lk.io_ok is io_ok, lk\n"
        "with engine._model_cache_lock:\n"
        "    with engine._model_cache_stats_lock:\n"
        "        pass\n"
        "rep = lockdep.report()\n"
        "assert 'model-cache -> model-cache-stats' in rep['edges'], rep\n"
        "print('registries instrumented')\n"
    )
    env = dict(os.environ, DGREP_LOCKDEP="1")
    out = subprocess.run(
        [sys.executable, "-c", code], env=env, cwd="/root/repo",
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "registries instrumented" in out.stdout


def test_env_knob_parser(monkeypatch):
    monkeypatch.delenv("DGREP_LOCKDEP", raising=False)
    assert not lockdep.env_lockdep()
    monkeypatch.setenv("DGREP_LOCKDEP", "1")
    assert lockdep.env_lockdep()
    monkeypatch.setenv("DGREP_LOCKDEP", "false")
    assert not lockdep.env_lockdep()

"""The dynamic half of the event-vocabulary contract (round 20).

The static `event-registry` rule proves what the AST can see: string-
constant emit sites and declared-family f-strings.  Names built at
runtime (helper pass-throughs, computed members) reach the registry only
through `utils/event_audit.py` — hooked into SpanBuffer.add,
EventLog.write_many, and DaemonLog.stage, activated per test by the
conftest `_event_vocab_audit` fixture under the service/obs/follow/
fuse/result/chaos tiers and by `DGREP_EVENT_AUDIT=1` for live daemons.

Standalone-runnable:  python -m pytest tests/test_event_audit.py -q
"""

from __future__ import annotations

import os

import pytest

from distributed_grep_tpu.utils import event_audit, spans

pytestmark = pytest.mark.obs


def test_recorder_flags_undeclared_name_through_span_buffer():
    """The acceptance demonstration: an undeclared name emitted through
    the real SpanBuffer hook produces a finding — exactly what makes the
    conftest fixture fail a test.  (This test carries the `obs` marker,
    so the fixture IS active here: the reset at the end is what keeps
    the deliberate finding from failing this test at teardown.)"""
    assert event_audit.is_active()  # the autouse fixture switched it on
    buf = spans.SpanBuffer()
    buf.add({"t": "instant", "name": "totally_bogus", "ts": 1.0})
    found = event_audit.findings()
    assert len(found) == 1
    assert "undeclared instant event name 'totally_bogus'" in found[0]
    event_audit.reset()
    assert not event_audit.findings()


def test_recorder_flags_kind_mismatch_and_passes_declared():
    assert event_audit.is_active()
    buf = spans.SpanBuffer()
    # declared names at their declared kinds: no findings
    buf.add({"t": "instant", "name": "index:prune", "ts": 1.0})
    buf.add({"t": "span", "name": "map:read", "ts": 1.0, "dur": 0.1})
    buf.add({"t": "instant", "name": "cache:hit", "ts": 1.0})  # family
    assert not event_audit.findings()
    # a declared instant emitted as a span is a kind mismatch
    buf.add({"t": "span", "name": "resume", "ts": 1.0, "dur": 0.1})
    found = event_audit.findings()
    assert len(found) == 1 and "emitted as a span" in found[0]
    event_audit.reset()


def test_recorder_dedups_by_name_and_ignores_non_events():
    assert event_audit.is_active()
    buf = spans.SpanBuffer()
    for _ in range(3):
        buf.add({"t": "instant", "name": "nope", "ts": 1.0})
    assert len(event_audit.findings()) == 1  # one finding per name
    # non-event records (clock observations, cursor lines) pass through
    buf.add({"t": "worker_clock", "offset": 0.5})
    buf.add({"t": "instant", "ts": 1.0})  # nameless: not auditable
    assert len(event_audit.findings()) == 1
    event_audit.reset()


def test_daemon_log_stage_is_audited(tmp_path):
    from distributed_grep_tpu.runtime.daemon_log import DaemonLog

    assert event_audit.is_active()
    dl = DaemonLog(tmp_path)
    try:
        dl.stage("lease_steal", prev_epoch=1)  # declared daemon event
        assert not event_audit.findings()
        dl.stage("made_up_lifecycle")
        found = event_audit.findings()
        assert len(found) == 1
        assert "undeclared daemon event name 'made_up_lifecycle'" in found[0]
    finally:
        event_audit.reset()
        dl.discard()


def test_off_means_off():
    """Deactivated, the hooks are one bool read — nothing records."""
    event_audit.deactivate()
    try:
        buf = spans.SpanBuffer()
        buf.add({"t": "instant", "name": "totally_bogus", "ts": 1.0})
        assert not event_audit.findings()
    finally:
        event_audit.activate()  # restore for the fixture's teardown read
        event_audit.reset()


def test_env_enabled_run_audits_import_time_paths():
    """DGREP_EVENT_AUDIT=1 in the environment (the deployment/debug
    switch) must activate the recorder at import time — the path a live
    daemon uses, which the per-test fixture can never exercise.  Run in
    a subprocess so the module import happens under the env var."""
    import subprocess
    import sys

    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "from distributed_grep_tpu.utils import event_audit, spans\n"
        "assert event_audit.is_active(), 'env var must switch it on'\n"
        "buf = spans.SpanBuffer()\n"
        "buf.add({'t': 'instant', 'name': 'index:prune', 'ts': 1.0})\n"
        "assert not event_audit.findings()\n"
        "buf.add({'t': 'instant', 'name': 'env_bogus', 'ts': 1.0})\n"
        "(f,) = event_audit.findings()\n"
        "assert 'env_bogus' in f, f\n"
        "print('env audit live')\n"
    )
    env = dict(os.environ, DGREP_EVENT_AUDIT="1")
    out = subprocess.run(
        [sys.executable, "-c", code], env=env, cwd="/root/repo",
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "env audit live" in out.stdout
    # env mode logs the finding as a warning (a live daemon has no
    # teardown assert to read findings for it)
    assert "env_bogus" in out.stderr


def test_env_knob_parser(monkeypatch):
    monkeypatch.delenv("DGREP_EVENT_AUDIT", raising=False)
    assert not event_audit.env_event_audit()
    monkeypatch.setenv("DGREP_EVENT_AUDIT", "1")
    assert event_audit.env_event_audit()
    monkeypatch.setenv("DGREP_EVENT_AUDIT", "0")
    assert not event_audit.env_event_audit()

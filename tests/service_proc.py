"""Subprocess ``dgrep serve`` driver for the chaos/soak tiers.

A REAL daemon process (SIGKILL-able — the one death no in-process
simulation can model honestly: no finally blocks, no scheduler stop, no
flushes) plus the minimal HTTP client the tests need.  Lives outside the
test modules so tests/test_chaos.py and tests/test_soak.py share one
spawn recipe (pytest puts tests/ on sys.path; plain ``import
service_proc`` works from any test module).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

REPO = str(Path(__file__).resolve().parents[1])


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http_json(method: str, url: str, body: bytes | None = None,
               timeout: float = 10.0) -> dict:
    req = urllib.request.Request(url, data=body, method=method)
    if body is not None:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


class ServiceProc:
    """One ``dgrep serve`` subprocess bound to a fixed (port, work_root)
    so a SIGKILL + ``start()`` models a daemon crash + restart: same
    address (attached workers' retry loops reconnect), same work root
    (the jobs.jsonl registry + per-job journals drive the resume)."""

    def __init__(self, work_root: Path, port: int | None = None,
                 workers: int = 0, env: dict | None = None,
                 extra_args: list[str] | None = None):
        self.work_root = Path(work_root)
        self.port = port or free_port()
        self.workers = workers
        # e.g. ["--standby"] for the HA tier; a parked standby still
        # answers /status {"service": true, "role": "standby"}, so the
        # start() readiness probe works unchanged
        self.extra_args = list(extra_args or [])
        self.base = f"http://127.0.0.1:{self.port}"
        self.env = {
            "PYTHONPATH": REPO, "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
            "JAX_PLATFORMS": "cpu", "DGREP_LOG": "WARNING",
            "DGREP_NO_CALIBRATE": "1",
            **(env or {}),
        }
        self.proc: subprocess.Popen | None = None
        self._logs: list[Path] = []

    # ------------------------------------------------------------ lifecycle
    def start(self, timeout: float = 60.0) -> "ServiceProc":
        log_path = self.work_root.parent / (
            f"serve-{self.port}-{len(self._logs)}.log"
        )
        self._logs.append(log_path)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "distributed_grep_tpu", "serve",
             "--host", "127.0.0.1", "--port", str(self.port),
             "--work-root", str(self.work_root), "--workers",
             str(self.workers), *self.extra_args],
            stdout=subprocess.DEVNULL,
            stderr=open(log_path, "wb"),
            env=self.env,
        )
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"serve died at startup: {self.tail_log()}"
                )
            try:
                if self.status().get("service"):
                    return self
            except OSError:
                time.sleep(0.1)
        raise TimeoutError(f"serve not ready on {self.base}: "
                           f"{self.tail_log()}")

    def sigkill(self) -> None:
        """The daemon crash: SIGKILL, no shutdown path of any kind runs."""
        assert self.proc is not None
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=30)

    def terminate(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=30)

    def tail_log(self, n: int = 2000) -> str:
        out = []
        for p in self._logs:
            if p.exists():
                out.append(p.read_bytes()[-n:].decode("utf-8", "replace"))
        return "\n---\n".join(out)

    # --------------------------------------------------------------- client
    def status(self, timeout: float = 10.0) -> dict:
        return _http_json("GET", f"{self.base}/status", timeout=timeout)

    def submit(self, config) -> str:
        body = config.to_json().encode("utf-8", "strict")
        return _http_json("POST", f"{self.base}/jobs", body)["job_id"]

    def job_status(self, job_id: str) -> dict:
        return _http_json("GET", f"{self.base}/jobs/{job_id}")

    def job_result(self, job_id: str) -> dict:
        return _http_json("GET", f"{self.base}/jobs/{job_id}/result")

    def wait_job(self, job_id: str, timeout: float = 120.0,
                 poll_s: float = 0.2) -> dict:
        """Poll to a terminal state, riding out daemon-restart windows
        (connection errors while the daemon is down retry until the
        overall deadline)."""
        deadline = time.monotonic() + timeout
        last: dict = {}
        while time.monotonic() < deadline:
            try:
                last = self.job_status(job_id)
            except OSError:
                time.sleep(poll_s)
                continue
            if last.get("state") in ("done", "failed", "cancelled"):
                return last
            time.sleep(poll_s)
        raise TimeoutError(
            f"job {job_id} not terminal after {timeout}s: {last} "
            f"(daemon log: {self.tail_log()})"
        )

"""Device corpus cache (round 7, ops/layout.CorpusCache): warm queries
rescan HBM-resident shards without re-read / re-pack / re-upload.

The contract under test (ISSUE 7): with a byte budget in force, a repeat
``scan_file`` / ``scan_batch`` over UNCHANGED inputs performs zero host
file reads and zero ``to_device_array`` uploads (spy-proven, ``perf``
marker), and its results are bit-identical to the cold scan for every
kernel family.  The content key is a fresh stat (realpath + size +
mtime_ns + inode) revalidated on every hit, so a modified file can never
serve stale bytes; entries LRU-evict under the DGREP_CORPUS_BYTES budget; the
service's persistent workers get cross-job hits (model cache answers
"same pattern", this cache answers "same data").

Standalone: ``python -m pytest tests/test_corpus_cache.py -q`` (CPU-only;
interpret engines drive the production device path, and the autouse
conftest fixture ``_fresh_corpus_cache`` keeps shards from leaking
across tests).
"""

from __future__ import annotations

import builtins
import os

import numpy as np
import pytest

from distributed_grep_tpu.ops import layout
from distributed_grep_tpu.ops.engine import GrepEngine

BUDGET = 1 << 28  # roomy test budget: nothing evicts unless a test asks


@pytest.fixture(autouse=True)
def _no_calibrate(monkeypatch):
    """Deterministic FDR plans (CLAUDE.md: DGREP_NO_CALIBRATE for CI)."""
    monkeypatch.setenv("DGREP_NO_CALIBRATE", "1")


def _corpus_bytes_fixture() -> bytes:
    """Needles for every engine family under test, plus hay."""
    rng = np.random.default_rng(13)
    words = ["hello", "hallo", "helloo", "volcano", "needle", "ab", "zz",
             "q", "the", "quick", "brown", "fox", "of", "and"]
    out = []
    for _ in range(600):
        k = int(rng.integers(1, 8))
        out.append(" ".join(
            words[int(rng.integers(0, len(words)))] for _ in range(k)
        ).encode())
    return b"\n".join(out) + b"\n"


def _fdr_patterns() -> list[str]:
    rng = np.random.default_rng(3)
    pats = {"hello", "volcano", "needle"}
    while len(pats) < 50:
        k = int(rng.integers(4, 9))
        pats.add("".join(chr(c) for c in rng.integers(97, 123, size=k)))
    return sorted(pats)


# the five families ISSUE 7 names; labels follow tests/test_batch.py
ENGINES = [
    ("shift_and", dict(pattern="hello")),
    ("nfa", dict(pattern="h[ae]llo+")),
    ("pairset", dict(patterns=["ab", "zz", "q"])),
    ("dfa_filter", dict(pattern="hello$")),  # '$'-dropped device filter
    ("fdr", dict(patterns=_fdr_patterns())),
]


def _counters() -> dict:
    return layout.corpus_cache_counters()


def _spy_reads_and_uploads(monkeypatch):
    """Record every builtins.open target and every to_device_array call.
    The upload spy patches the layout module attribute — ops/device_scan
    resolves ``layout_mod.to_device_array`` at call time, so the patch is
    seen at the real boundary, not via engine telemetry."""
    opens: list[str] = []
    real_open = builtins.open

    def spy_open(f, *a, **k):
        opens.append(str(f))
        return real_open(f, *a, **k)

    uploads: list[int] = []
    real_tda = layout.to_device_array

    def spy_tda(data, lay, *a, **k):
        uploads.append(len(data))
        return real_tda(data, lay, *a, **k)

    monkeypatch.setattr(builtins, "open", spy_open)
    monkeypatch.setattr(layout, "to_device_array", spy_tda)
    return opens, uploads


# ------------------------------------------------------------- key / knob

def test_file_content_key_is_a_fresh_stat(tmp_path):
    p = tmp_path / "a.txt"
    p.write_bytes(b"hello\n")
    k1 = layout.file_content_key(p)
    assert k1 is not None and k1.identity == ("file", os.path.realpath(p))
    assert k1.n_bytes == 6
    p.write_bytes(b"hello!\n")
    k2 = layout.file_content_key(p)
    assert k2.validators != k1.validators  # size changed
    assert layout.file_content_key(tmp_path / "missing") is None


def test_batch_content_key_requires_every_member(tmp_path):
    p = tmp_path / "a.txt"
    p.write_bytes(b"x\n")
    k = layout.file_content_key(p)
    assert layout.batch_content_key([k, None]) is None
    assert layout.batch_content_key([]) is None
    wk = layout.batch_content_key([k, k])
    assert wk.identity[0] == "pack" and wk.n_bytes == 4


def test_env_corpus_bytes_accessor(monkeypatch):
    monkeypatch.delenv("DGREP_CORPUS_BYTES", raising=False)
    assert layout.env_corpus_bytes() is None
    monkeypatch.setenv("DGREP_CORPUS_BYTES", "notanint")
    assert layout.env_corpus_bytes() is None  # malformed == unset
    monkeypatch.setenv("DGREP_CORPUS_BYTES", "0")
    assert layout.env_corpus_bytes() == 0
    monkeypatch.setenv("DGREP_CORPUS_BYTES", str(1 << 20))
    assert layout.env_corpus_bytes() == 1 << 20


def test_budget_resolution(monkeypatch):
    monkeypatch.delenv("DGREP_CORPUS_BYTES", raising=False)
    # CPU backend default: OFF (CI and plain host runs keep their exact
    # pre-cache behavior)
    assert GrepEngine("x", interpret=True)._corpus_budget() == 0
    # explicit construction arg wins
    assert GrepEngine(
        "x", interpret=True, corpus_bytes=123
    )._corpus_budget() == 123
    # env knob beats the backend default
    monkeypatch.setenv("DGREP_CORPUS_BYTES", "456")
    assert GrepEngine("x", interpret=True)._corpus_budget() == 456


def test_mesh_engines_bypass(monkeypatch):
    """Same verdict as the model cache: a mesh engine's sharded uploads
    are tied to ITS device set — budget answers 0 regardless of knobs."""
    from distributed_grep_tpu.parallel.mesh import make_mesh

    monkeypatch.setenv("DGREP_CORPUS_BYTES", str(BUDGET))
    eng = GrepEngine("hello", interpret=True, mesh=make_mesh((2,), ("data",)))
    assert eng._corpus_budget() == 0


# ------------------------------------------- warm-vs-cold per family

@pytest.mark.parametrize("label,kw", ENGINES, ids=[e[0] for e in ENGINES])
def test_warm_scan_file_bit_identical_per_family(label, kw, tmp_path):
    p = tmp_path / "c.txt"
    p.write_bytes(_corpus_bytes_fixture())
    eng = GrepEngine(interpret=True, corpus_bytes=BUDGET, **kw)

    cold_emitted: list = []
    cold = eng.scan_file(str(p), emit=lambda ln, b: cold_emitted.append((ln, b)))
    c = _counters()
    assert c["corpus_cache_misses"] >= 1, label
    assert c["corpus_cache_bytes_resident"] > 0, label

    warm_emitted: list = []
    warm = eng.scan_file(str(p), emit=lambda ln, b: warm_emitted.append((ln, b)))
    c2 = _counters()
    assert c2["corpus_cache_hits"] >= 1, label

    assert np.array_equal(cold.matched_lines, warm.matched_lines), label
    assert cold.n_matches == warm.n_matches
    assert cold.bytes_scanned == warm.bytes_scanned == len(_corpus_bytes_fixture())
    assert cold_emitted == warm_emitted  # per-line emit, byte-identical
    assert cold.n_matches > 0  # the corpus really exercises this family


@pytest.mark.parametrize("label,kw", ENGINES, ids=[e[0] for e in ENGINES])
def test_warm_scan_batch_bit_identical_per_family(label, kw, tmp_path):
    files = []
    body = _corpus_bytes_fixture()
    for j in range(5):
        q = tmp_path / f"f{j}.txt"
        q.write_bytes(body[j * 512:] or b"hello\n")
        files.append((f"f{j}.txt", str(q)))
    eng = GrepEngine(interpret=True, corpus_bytes=BUDGET,
                     batch_bytes=1 << 22, **kw)
    cold = eng.scan_batch(list(files))
    warm = eng.scan_batch(list(files))
    assert _counters()["corpus_cache_hits"] >= 1, label
    assert [n for n, _ in cold] == [n for n, _ in warm] == [n for n, _ in files]
    for (_, a), (_, b) in zip(cold, warm):
        assert np.array_equal(a.matched_lines, b.matched_lines), label
        assert a.n_matches == b.n_matches
        assert a.bytes_scanned == b.bytes_scanned


def test_no_trailing_newline_file_populates_and_warm_hits(tmp_path):
    """A single-chunk file WITHOUT a trailing newline (common in code
    search) must still populate on the cold scan: scan_file detects
    the whole-file-in-hand case and scans it unsplit instead of
    orphaning the un-terminated tail into the carry (which left the
    key unthreaded on both pieces)."""
    body = _corpus_bytes_fixture() + b"hello tail without newline"
    p = tmp_path / "c.txt"
    p.write_bytes(body)
    eng = GrepEngine("hello", interpret=True, corpus_bytes=BUDGET)
    cold = eng.scan_file(str(p))
    c = _counters()
    assert c["corpus_cache_misses"] >= 1
    assert c["corpus_cache_bytes_resident"] > 0  # populated

    warm = eng.scan_file(str(p))
    assert _counters()["corpus_cache_hits"] >= 1
    oracle = GrepEngine("hello", interpret=True).scan(body)
    for res in (cold, warm):
        assert np.array_equal(res.matched_lines, oracle.matched_lines)
        assert res.n_matches == oracle.n_matches
        assert res.bytes_scanned == len(body)


def test_padded_band_input_is_cache_ineligible(tmp_path):
    """raw <= budget < padded: eligibility is priced on the PADDED
    device bytes UPFRONT (device_scan computes the total from the same
    hoisted lay_kwargs the prepare step uses) — the scan skips the
    cache entirely instead of retaining every built segment and having
    the publish declined, and resident tenants survive untouched."""
    body = _corpus_bytes_fixture()
    a, b = tmp_path / "a.txt", tmp_path / "b.txt"
    a.write_bytes(body)
    b.write_bytes((b"hello padded band filler\n" * 4100)[:100001])  # odd
    eng = GrepEngine("hello", interpret=True, corpus_bytes=BUDGET)
    eng.scan_file(str(a))
    c0 = _counters()

    eng.corpus_bytes = b.stat().st_size  # == raw; padded exceeds it
    res = eng.scan_file(str(b))
    assert res.n_matches > 0
    c1 = _counters()
    assert c1["corpus_cache_evictions"] == 0  # the tenant survived
    assert c1["corpus_cache_misses"] == c0["corpus_cache_misses"]
    assert c1["corpus_cache_bytes_resident"] == c0[
        "corpus_cache_bytes_resident"
    ]

    eng.corpus_bytes = BUDGET
    hits0 = c1.get("corpus_cache_hits", 0)
    eng.scan_file(str(a))  # still warm
    assert _counters()["corpus_cache_hits"] == hits0 + 1


# --------------------------------------------------- spy proofs (perf)

@pytest.mark.perf
def test_warm_scan_file_zero_reads_zero_uploads(tmp_path, monkeypatch):
    """ISSUE 7 acceptance: the repeat scan_file touches neither the
    filesystem (no open of the input) nor the upload boundary (zero
    to_device_array calls) — counted at the real boundaries, not from
    the engine's own telemetry."""
    p = tmp_path / "c.txt"
    p.write_bytes(_corpus_bytes_fixture() * 4)
    eng = GrepEngine("hello", interpret=True, corpus_bytes=BUDGET)
    cold = eng.scan_file(str(p))

    opens, uploads = _spy_reads_and_uploads(monkeypatch)
    warm = eng.scan_file(str(p))
    assert not [f for f in opens if str(tmp_path) in f]  # zero host reads
    assert uploads == []  # zero device uploads
    assert np.array_equal(cold.matched_lines, warm.matched_lines)
    assert cold.n_matches == warm.n_matches > 0


@pytest.mark.perf
def test_warm_scan_batch_window_zero_reads_zero_uploads(tmp_path, monkeypatch):
    """The packed-window variant: the warm window is recognized from its
    FIRST member's path before any member is read — the whole window
    re-scans with zero opens and zero uploads, and the demux still emits
    per-file results."""
    files = []
    for j in range(8):
        q = tmp_path / f"f{j}.txt"
        q.write_bytes(
            b"".join(
                (b"hello line %d\n" % i if i % 5 == 0 else b"hay line %d\n" % i)
                for i in range(60)
            )
        )
        files.append((f"f{j}.txt", str(q)))
    eng = GrepEngine("hello", interpret=True, corpus_bytes=BUDGET,
                     batch_bytes=1 << 20)
    cold = eng.scan_batch(list(files))
    assert dict(eng.stats)["batch_dispatches"] == 1  # one packed window

    opens, uploads = _spy_reads_and_uploads(monkeypatch)
    warm = eng.scan_batch(list(files))
    stats = dict(eng.stats)
    assert not [f for f in opens if str(tmp_path) in f]  # zero member reads
    assert uploads == []  # zero uploads
    assert stats["corpus_cache_hits"] >= 1
    for (na, a), (nb, b) in zip(cold, warm):
        assert na == nb
        assert np.array_equal(a.matched_lines, b.matched_lines)
    assert sum(r.n_matches for _, r in warm) > 0


def test_disabled_budget_never_populates(tmp_path):
    p = tmp_path / "c.txt"
    p.write_bytes(_corpus_bytes_fixture())
    eng = GrepEngine("hello", interpret=True, corpus_bytes=0)
    eng.scan_file(str(p))
    eng.scan_file(str(p))
    assert _counters() == {}  # a disabled cache is a true no-op


# ------------------------------------------------------ invalidation

def test_mtime_change_invalidates_same_size(tmp_path):
    """Same byte count, different content: the mtime_ns component of the
    validator must catch it — stale resident bytes are NEVER served."""
    p = tmp_path / "c.txt"
    body = _corpus_bytes_fixture()
    p.write_bytes(body)
    eng = GrepEngine("hello", interpret=True, corpus_bytes=BUDGET)
    cold = eng.scan_file(str(p))
    assert cold.n_matches > 0

    changed = body.replace(b"hello", b"hxllo", 5)  # same length
    assert len(changed) == len(body)
    p.write_bytes(changed)
    st = p.stat()
    os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns + 1))  # force a tick

    res = eng.scan_file(str(p))
    oracle = GrepEngine("hello", interpret=True).scan(changed)
    assert np.array_equal(res.matched_lines, oracle.matched_lines)
    assert res.n_matches == oracle.n_matches
    # the replaced needles really changed the verdict: stale resident
    # bytes would have reproduced cold's lines exactly
    assert not np.array_equal(res.matched_lines, cold.matched_lines)
    c = _counters()
    assert c["corpus_cache_evictions"] >= 1  # the stale entry died


def test_inode_change_invalidates_same_size_same_mtime(tmp_path):
    """Atomic replacement that preserves BOTH size and mtime (cp -p +
    mv, rsync -t, timestamp-preserving tar extract): the inode component
    of the validator must catch it — size+mtime alone would revalidate
    the stale entry as unchanged and serve old bytes with the file never
    opened."""
    p = tmp_path / "c.txt"
    body = _corpus_bytes_fixture()
    p.write_bytes(body)
    eng = GrepEngine("hello", interpret=True, corpus_bytes=BUDGET)
    cold = eng.scan_file(str(p))
    assert cold.n_matches > 0
    st = p.stat()

    changed = body.replace(b"hello", b"hxllo", 5)  # same length
    assert len(changed) == len(body)
    q = tmp_path / "c.txt.new"
    q.write_bytes(changed)
    os.utime(q, ns=(st.st_atime_ns, st.st_mtime_ns))  # preserve mtime
    os.replace(q, p)  # new inode, same size, same mtime_ns
    assert p.stat().st_mtime_ns == st.st_mtime_ns
    assert p.stat().st_size == st.st_size

    res = eng.scan_file(str(p))
    oracle = GrepEngine("hello", interpret=True).scan(changed)
    assert np.array_equal(res.matched_lines, oracle.matched_lines)
    assert res.n_matches == oracle.n_matches
    assert not np.array_equal(res.matched_lines, cold.matched_lines)
    assert _counters()["corpus_cache_evictions"] >= 1  # stale entry died


def test_size_change_invalidates(tmp_path):
    p = tmp_path / "c.txt"
    body = _corpus_bytes_fixture()
    p.write_bytes(body)
    eng = GrepEngine("hello", interpret=True, corpus_bytes=BUDGET)
    eng.scan_file(str(p))
    p.write_bytes(body + b"one more hello line\n")
    res = eng.scan_file(str(p))
    assert res.bytes_scanned == len(body) + 20
    oracle = GrepEngine("hello", interpret=True).scan(
        body + b"one more hello line\n"
    )
    assert np.array_equal(res.matched_lines, oracle.matched_lines)


def test_batch_member_change_invalidates_window(tmp_path):
    """One modified member breaks the whole packed window's key: fresh
    stats are taken per member on every call, so the warm-window probe
    misses and the files are re-read (correct results, counted miss)."""
    files = []
    for j in range(4):
        q = tmp_path / f"f{j}.txt"
        q.write_bytes(b"hello %d\nworld\n" % j * 30)
        files.append((f"f{j}.txt", str(q)))
    eng = GrepEngine("hello", interpret=True, corpus_bytes=BUDGET,
                     batch_bytes=1 << 20)
    eng.scan_batch(list(files))
    hits0 = _counters().get("corpus_cache_hits", 0)

    q = tmp_path / "f2.txt"
    q.write_bytes(b"no needles at all\n" * 30)
    out = eng.scan_batch(list(files))
    assert _counters().get("corpus_cache_hits", 0) == hits0  # no false hit
    assert out[2][1].n_matches == 0  # the new content, not the cached one
    assert out[0][1].n_matches > 0


def test_shrunk_batch_bytes_governs_warm_windows(tmp_path):
    """Lowering batch_bytes must take effect for already-resident
    windows too (the knob bounds per-dispatch host/device memory): a
    window packed under the old larger cap is NOT re-served — the cold
    path re-packs at the new granularity, results stay exact."""
    files = []
    for j in range(6):
        q = tmp_path / f"f{j}.txt"
        q.write_bytes(b"hello %d\nworld filler line\n" % j * 40)
        files.append((f"f{j}.txt", str(q)))
    eng = GrepEngine("hello", interpret=True, corpus_bytes=BUDGET,
                     batch_bytes=1 << 20)
    cold = eng.scan_batch(list(files))
    assert dict(eng.stats)["batch_dispatches"] == 1  # one big window

    eng.batch_bytes = 2048  # shrink below the resident window's size
    out = eng.scan_batch(list(files))
    stats = dict(eng.stats)
    # re-dispatched at the new granularity (smaller windows and/or solo
    # scans) — NOT one oversized warm window
    assert stats["batch_dispatches"] + stats["solo_dispatches"] > 1
    assert stats["batch_fill_ratio"] <= 1.0  # vs the CURRENT cap
    assert [n for n, _ in out] == [n for n, _ in cold]
    for (_, a), (_, b) in zip(cold, out):
        assert np.array_equal(a.matched_lines, b.matched_lines)


# ------------------------------------------------------ LRU eviction

def test_lru_eviction_under_tiny_budget(tmp_path):
    body = _corpus_bytes_fixture()
    a, b = tmp_path / "a.txt", tmp_path / "b.txt"
    a.write_bytes(body)
    b.write_bytes(body[7:])  # distinct content, ~same size
    eng = GrepEngine("hello", interpret=True, corpus_bytes=BUDGET)
    eng.scan_file(str(a))
    one_entry = _counters()["corpus_cache_bytes_resident"]
    assert one_entry > 0

    eng.corpus_bytes = int(one_entry * 1.5)  # fits ONE entry, not two
    eng.scan_file(str(b))  # inserting b pushes a (LRU) out
    c = _counters()
    assert c["corpus_cache_evictions"] >= 1
    assert c["corpus_cache_bytes_resident"] <= int(one_entry * 1.5)

    hits0 = c.get("corpus_cache_hits", 0)
    eng.scan_file(str(b))  # survivor is warm
    assert _counters()["corpus_cache_hits"] == hits0 + 1
    misses0 = _counters()["corpus_cache_misses"]
    eng.scan_file(str(a))  # evictee is cold again
    assert _counters()["corpus_cache_misses"] == misses0 + 1


def test_input_larger_than_budget_is_cache_ineligible(tmp_path):
    """An input bigger than the whole budget never touches the cache:
    retaining its built segments until scan end would defeat the
    double-buffer's bounded footprint, and publishing would LRU-wipe
    every smaller entry before the oversized newcomer evicts itself —
    so the scan runs exactly as if the cache were off."""
    p = tmp_path / "c.txt"
    p.write_bytes(_corpus_bytes_fixture())
    eng = GrepEngine("hello", interpret=True, corpus_bytes=1)  # 1 byte
    r1 = eng.scan_file(str(p))
    assert _counters() == {}  # no lookup, no put, no counters
    r2 = eng.scan_file(str(p))  # still correct, still uncached
    assert np.array_equal(r1.matched_lines, r2.matched_lines)
    assert _counters() == {}


def test_oversized_input_does_not_wipe_resident_entries(tmp_path):
    """The LRU-wipe scenario pinned directly: a small warm entry must
    SURVIVE a scan of an input larger than the budget."""
    body = _corpus_bytes_fixture()
    small, big = tmp_path / "small.txt", tmp_path / "big.txt"
    small.write_bytes(body)
    big.write_bytes(body * 400)  # ~6 MB, over the 4 MB budget
    # XLA device path (no interpret): same scan_device cache gate,
    # fast enough for a multi-MB corpus in CI
    eng = GrepEngine("hello", backend="device", corpus_bytes=1 << 22)
    eng.scan_file(str(small))
    resident = _counters()["corpus_cache_bytes_resident"]
    assert 0 < resident <= 1 << 22

    assert big.stat().st_size > 1 << 22
    eng.scan_file(str(big))  # cache-ineligible, must not evict anything
    c = _counters()
    assert c["corpus_cache_evictions"] == 0
    assert c["corpus_cache_bytes_resident"] == resident

    hits0 = c.get("corpus_cache_hits", 0)
    eng.scan_file(str(small))  # the small entry is still warm
    assert _counters()["corpus_cache_hits"] == hits0 + 1


def test_cached_window_is_slim_and_reconstructs_members(tmp_path):
    """A cache-resident window must NOT pin the original member blobs
    (they would double its host footprint alongside the packed data);
    member bytes reconstruct as slices of the packed blob, exactly."""
    files = []
    for j in range(4):
        q = tmp_path / f"f{j}.txt"
        # one member missing its trailing newline (synthesized in the
        # packed layout) and one empty (packs to zero bytes)
        body = (b"" if j == 2
                else b"hello %d\nworld" % j + (b"\n" if j % 2 else b""))
        q.write_bytes(body)
        files.append((f"f{j}.txt", str(q)))
    eng = GrepEngine("hello", interpret=True, corpus_bytes=BUDGET,
                     batch_bytes=1 << 20)
    eng.scan_batch(list(files))

    cache = layout.corpus_cache()
    wins = [e for e in cache._entries.values() if e.batch is not None]
    assert wins
    for ent in wins:
        assert ent.batch.blobs is None  # no second host copy pinned
        for nm, blob in zip(ent.batch.names, ent.batch.member_blobs()):
            assert blob == (tmp_path / nm).read_bytes(), nm


def test_put_segments_declines_oversized_variant():
    """The authoritative budget check is on the PADDED device bytes at
    put time (the raw-input gate in device_scan under-counts padding):
    a variant whose own bytes exceed the whole budget is declined
    outright — resident tenants survive, nothing is evicted."""
    from types import SimpleNamespace

    cache = layout.CorpusCache()
    small = layout.CorpusKey(identity=("file", "/a"), validators=((1, 1),))
    cache.put_segments(
        small, ("sig",), b"x",
        [(0, SimpleNamespace(padded=100), np.zeros(100, np.uint8), None)],
        budget=1000,
    )
    big = layout.CorpusKey(identity=("file", "/b"), validators=((2, 2),))
    cache.put_segments(
        big, ("sig",), b"y",
        [(0, SimpleNamespace(padded=2000), np.zeros(2000, np.uint8), None)],
        budget=1000,
    )
    c = cache.counters()
    assert c["corpus_cache_evictions"] == 0  # the small tenant survives
    assert c["corpus_cache_bytes_resident"] == 100
    assert cache.lookup(small) is not None
    assert cache.lookup(big) is None  # the oversized variant never landed


def test_sibling_variant_dropped_before_tenant_eviction():
    """Two layout sigs of the SAME content whose total exceeds the
    budget (alternating engine families over one corpus): the stale
    sibling variant is dropped, not the whole entry — whole-entry LRU
    would wipe the variant just built and thrash to permanent misses —
    and other tenants survive when dropping the sibling suffices."""
    from types import SimpleNamespace

    def seg(n):
        return [(0, SimpleNamespace(padded=n), np.zeros(n, np.uint8), None)]

    cache = layout.CorpusCache()
    tenant = layout.CorpusKey(identity=("file", "/t"), validators=((1, 1),))
    cache.put_segments(tenant, ("sig1",), b"t", seg(300), budget=1000)
    shared = layout.CorpusKey(identity=("file", "/s"), validators=((2, 2),))
    cache.put_segments(shared, ("sig1",), b"s", seg(600), budget=1000)
    cache.put_segments(shared, ("sig2",), b"s", seg(600), budget=1000)

    assert cache.resident_segments(shared, ("sig2",)) is not None  # kept
    assert cache.resident_segments(shared, ("sig1",)) is None  # dropped
    assert cache.lookup(tenant) is not None  # the other tenant survived
    assert cache.counters()["corpus_cache_bytes_resident"] == 900


def test_explicit_device_list_bypasses(monkeypatch):
    """Same verdict as the model cache: resident segments are committed
    to specific devices, so an engine pinned to an explicit devices=
    LIST must not share them — budget answers 0; the symbolic "all"
    stays cacheable."""
    import jax

    monkeypatch.setenv("DGREP_CORPUS_BYTES", str(BUDGET))
    dev = jax.devices("cpu")[0]
    eng = GrepEngine("hello", interpret=True, devices=[dev])
    assert eng._corpus_budget() == 0
    assert GrepEngine(
        "hello", interpret=True, devices="all"
    )._corpus_budget() == BUDGET


# ------------------------------------------------ telemetry contracts

def test_stats_stamped_nonzero_only(tmp_path):
    """Zero-activity engines keep their exact stats shape (same contract
    as compile_cache_*): no corpus_* keys before the cache is touched."""
    eng = GrepEngine("hello", interpret=True)  # budget 0 on cpu
    eng.scan(b"hello\n")
    assert not any(k.startswith("corpus_cache") for k in eng.stats)

    p = tmp_path / "c.txt"
    p.write_bytes(_corpus_bytes_fixture())
    eng2 = GrepEngine("hello", interpret=True, corpus_bytes=BUDGET)
    eng2.scan_file(str(p))
    s = dict(eng2.stats)
    assert s["corpus_cache_misses"] >= 1
    assert s["corpus_cache_bytes_resident"] > 0


def test_host_routed_warm_serve_counts_host_hit(tmp_path):
    """A host-routed engine (backend="cpu" — mode native/re, never
    reaches scan_device) serving warm host bytes must still show up in
    the counters: corpus_cache_host_hits counts the ent.data serve,
    since the resident_segments hit/miss verdict never runs for it.
    Without the counter, /status reads an actively-working cache as
    idle."""
    p = tmp_path / "c.txt"
    body = _corpus_bytes_fixture()
    p.write_bytes(body)
    # populate via a device-path engine (put_segments is the only
    # entry creator)
    GrepEngine("hello", interpret=True, corpus_bytes=BUDGET).scan_file(str(p))
    c0 = _counters()

    host_eng = GrepEngine("hello", backend="cpu", corpus_bytes=BUDGET)
    res = host_eng.scan_file(str(p))
    c1 = _counters()
    assert c1["corpus_cache_host_hits"] == c0.get("corpus_cache_host_hits", 0) + 1
    # the host serve is NOT a resident-segments verdict: neither hit
    # nor miss moved
    assert c1["corpus_cache_hits"] == c0["corpus_cache_hits"]
    assert c1["corpus_cache_misses"] == c0["corpus_cache_misses"]
    oracle = GrepEngine("hello", backend="cpu").scan(body)
    assert np.array_equal(res.matched_lines, oracle.matched_lines)
    assert res.n_matches == oracle.n_matches > 0


def test_counters_never_touched_is_lock_free():
    """engine.scan() polls corpus_cache_counters() once per scan even
    when the cache is off — the never-touched answer must not take the
    process-global lock (worker threads would serialize on it per chunk
    for a disabled feature)."""
    cache = layout.CorpusCache()

    class _Exploding:
        def __enter__(self):
            raise AssertionError("counters() took the lock before first touch")

        def __exit__(self, *a):
            return False

    real_lock = cache._lock
    cache._lock = _Exploding()
    try:
        assert cache.counters() == {}  # lock-free fast path
    finally:
        cache._lock = real_lock
    cache.count_host_hit()  # first touch
    assert cache.counters()["corpus_cache_host_hits"] == 1
    cache.clear()  # reset re-arms the fast path
    assert cache.counters() == {}


def test_worker_piggyback_merges_corpus_counters(tmp_path):
    from distributed_grep_tpu.runtime.worker import _engine_cache_counters

    p = tmp_path / "c.txt"
    p.write_bytes(_corpus_bytes_fixture())
    GrepEngine("hello", interpret=True, corpus_bytes=BUDGET).scan_file(str(p))
    counters = _engine_cache_counters()
    assert counters is not None
    assert counters["corpus_cache_misses"] >= 1
    assert "corpus_cache_bytes_resident" in counters


def test_corpus_span_instants_reach_events_jsonl(tmp_path):
    """The corpus:hit|miss verdict instants ride the span pipeline into
    events.jsonl — i.e. they are visible in trace-export, which renders
    exactly these records."""
    from distributed_grep_tpu.runtime.job import run_job
    from distributed_grep_tpu.utils import spans
    from distributed_grep_tpu.utils.config import JobConfig
    from pathlib import Path

    files = []
    for j in range(4):
        q = tmp_path / f"f{j}.txt"
        q.write_bytes(b"hello %d\nworld\n" % j * 40)
        files.append(str(q))
    base = dict(
        input_files=files,
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": "hello", "backend": "device",
                     "interpret": True, "corpus_bytes": BUDGET},
        batch_bytes=1 << 20,
        n_reduce=2,
        spans=True,
    )
    run_job(JobConfig(work_dir=str(tmp_path / "w1"), job_id="cold",
                      **base), n_workers=1)
    cold_events = spans.EventLog.read(tmp_path / "w1" / "events.jsonl")
    assert any(e.get("name") == "corpus:miss" for e in cold_events)

    run_job(JobConfig(work_dir=str(tmp_path / "w2"), job_id="warm",
                      **base), n_workers=1)
    warm_events = spans.EventLog.read(tmp_path / "w2" / "events.jsonl")
    assert any(e.get("name") == "corpus:hit" for e in warm_events)


# ------------------------------------------- cross-job via the service

@pytest.mark.service
def test_cross_job_warm_hit_through_service(tmp_path, monkeypatch):
    """ISSUE 7 acceptance: two submits of the same query over the same
    inputs through GrepService's persistent shared workers — the second
    job's packed window comes from the resident cache (hits counted in
    the service /status corpus_cache view) and outputs are identical.
    The round-20 RESULT tier would answer the resubmit before any scan
    (no corpus lookup at all) — pin THIS tier with it off, the
    corpus_resident.py base-leg discipline."""
    from distributed_grep_tpu.runtime.service import GrepService, JobState
    from distributed_grep_tpu.utils.config import JobConfig
    from pathlib import Path

    monkeypatch.setenv("DGREP_RESULT_CACHE", "0")

    files = []
    for j in range(6):
        q = tmp_path / f"f{j}.txt"
        q.write_bytes(b"".join(
            (b"hello from f%d line %d\n" % (j, i) if i % 3 == 0
             else b"hay f%d line %d\n" % (j, i))
            for i in range(50)
        ))
        files.append(str(q))

    def cfg() -> JobConfig:
        return JobConfig(
            input_files=list(files),
            application="distributed_grep_tpu.apps.grep_tpu",
            app_options={"pattern": "hello", "backend": "device",
                         "interpret": True, "corpus_bytes": BUDGET},
            batch_bytes=1 << 20,
            n_reduce=2,
        )

    svc = GrepService(work_root=tmp_path / "svc", task_timeout_s=5.0,
                      sweep_interval_s=0.1)
    try:
        svc.start_local_workers(2)
        j1 = svc.submit(cfg())
        assert svc.wait_job(j1, timeout=120), svc.job_status(j1)
        c1 = layout.corpus_cache_counters()
        assert c1.get("corpus_cache_bytes_resident", 0) > 0

        j2 = svc.submit(cfg())
        assert svc.wait_job(j2, timeout=120), svc.job_status(j2)
        c2 = layout.corpus_cache_counters()
        assert c2["corpus_cache_hits"] >= c1.get("corpus_cache_hits", 0) + 1

        r1, r2 = svc.job_result(j1), svc.job_result(j2)
        assert r1["state"] == r2["state"] == JobState.DONE
        got1 = {Path(p).name: Path(p).read_bytes() for p in r1["outputs"]}
        got2 = {Path(p).name: Path(p).read_bytes() for p in r2["outputs"]}
        assert got1 == got2 and any(got1.values())
        # and the service-level status view carries the counters
        assert svc.status()["corpus_cache"]["corpus_cache_hits"] >= 1
    finally:
        svc.stop()

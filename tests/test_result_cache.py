"""Query-result cache (round 20): repeated queries answer from stored
per-split results in O(ms); drifted shards re-scan incrementally.

Pins, in the order ISSUE 18 demands them:

* byte identity across hit / partial / miss vs a cache-off oracle —
  COLLATED record comparison (the cached job's output file layout
  legitimately differs from a scanned job's);
* the full-hit fast path never builds a scheduler and completes on a
  daemon with ZERO workers (the strongest "no scan happened" proof);
* stat-drift never serves stale bytes, including the cp -p + mv
  same-size same-mtime inode replacement;
* append one file of three -> exactly ONE split re-scans (planner
  dispatch proof, `perf` marker);
* entries persist across daemon restart (resume path re-plans with the
  store);
* whole-entry LRU under a tiny DGREP_RESULT_BYTES budget;
* DGREP_RESULT_CACHE=0 is a TRUE no-op (no results/ dir, no /status
  key); and a publish failure mid-job degrades to a partial/miss, never
  to wrong bytes.

Marker `result` (standalone: `pytest -m result`); the service-backed
tests ride the lockdep audit like the `service` suite.
"""

from __future__ import annotations

import os
import shutil
import time
from pathlib import Path

import pytest

from distributed_grep_tpu.runtime import result_cache
from distributed_grep_tpu.runtime.result_cache import (
    ResultKey,
    ResultStore,
    result_key,
)
from distributed_grep_tpu.runtime.service import GrepService
from distributed_grep_tpu.utils.config import JobConfig

pytestmark = pytest.mark.result


# --------------------------------------------------------------- helpers


@pytest.fixture()
def corpus(tmp_path):
    root = tmp_path / "data"
    root.mkdir()
    files = {}
    for name, text in {
        "a.txt": "hello world\nthe quick brown fox\nhello again\n",
        # b.txt keeps a match: a zero-match file's split would be
        # index-PRUNED from the resubmit's plan (the tiers compose),
        # which is correct but makes the reuse counts here input-shaped
        "b.txt": "nothing here\nfox says hello\n\ntrailing line",
        "c.txt": "HELLO uppercase\nhellohello twice\nlast hello\n",
    }.items():
        p = root / name
        p.write_text(text)
        files[name] = p
    return files


def _cfg(corpus, pattern="hello", **app_opts):
    opts = {"pattern": pattern, "backend": "cpu"}
    opts.update(app_opts)
    return JobConfig(
        input_files=[str(p) for p in corpus.values()],
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options=opts,
        n_reduce=3,
    )


def _collate(paths):
    """Merged, sorted record lines — layout-independent comparison
    (cached jobs materialize different file shapes than scanned ones)."""
    lines = []
    for p in paths:
        with open(p, "rb") as f:
            lines.extend(
                ln for ln in f.read().splitlines(keepends=True) if ln.strip()
            )
    return sorted(lines)


def _service(work_root, **kw):
    kw.setdefault("task_timeout_s", 10.0)
    kw.setdefault("sweep_interval_s", 0.1)
    return GrepService(work_root=work_root, **kw)


def _run(svc, config, timeout=60):
    jid = svc.submit(config)
    assert svc.wait_job(jid, timeout=timeout)
    res = svc.job_result(jid)
    assert res["state"] == "done", res
    return jid, res


# --------------------------------------------------- hit / partial / miss


@pytest.mark.perf
def test_hit_partial_miss_byte_identity(tmp_path, corpus):
    svc = _service(tmp_path / "svc")
    svc.start_local_workers(1)
    try:
        # miss: first run scans everything and publishes per split
        j1, r1 = _run(svc, _cfg(corpus))
        rec1 = svc.record(j1)
        assert rec1.result_splits_reused == 0
        n_splits = len(rec1.map_splits)
        assert n_splits == 3

        # full hit: identical resubmit answers from cache — no scheduler
        j2, r2 = _run(svc, _cfg(corpus))
        rec2 = svc.record(j2)
        assert rec2.scheduler is None
        assert rec2.result_splits_reused == n_splits
        assert rec2.result_bytes_unscanned > 0
        assert _collate(r2["outputs"]) == _collate(r1["outputs"])
        # metrics rider (the dgrep submit nonzero-only surface)
        counters = r2["metrics"]["counters"]
        assert counters["result_splits_reused"] == n_splits
        assert counters["result_bytes_unscanned"] > 0
        # GET /jobs/<id> is the submit CLIENT's counter source: a full
        # hit has no scheduler, so job_status must surface the Metrics
        # through the scheduler-less leg or the one-line submit JSON
        # silently drops result_splits_reused (caught by the live drive)
        js = svc.job_status(j2)
        assert js["metrics"]["counters"]["result_splits_reused"] == n_splits

        st = svc.status()
        assert st["result_cache"]["result_hits"] == 1
        assert st["result_cache"]["result_splits_reused"] == n_splits

        # partial hit: append to ONE file -> exactly one split re-scans
        with open(corpus["a.txt"], "a") as f:
            f.write("hello appended\n")
        j3, r3 = _run(svc, _cfg(corpus))
        rec3 = svc.record(j3)
        assert len(rec3.map_splits) == 1  # the dispatch proof
        assert rec3.result_splits_reused == n_splits - 1
        body = b"".join(_collate(r3["outputs"]))
        assert b"appended" in body
        assert svc.status()["result_cache"]["result_partial_hits"] == 1
    finally:
        svc.stop()

    # oracle: cache-off daemon over the (drifted) corpus, byte-identical
    os.environ["DGREP_RESULT_CACHE"] = "0"
    try:
        svc2 = _service(tmp_path / "svc2")
        svc2.start_local_workers(1)
        try:
            _j, r4 = _run(svc2, _cfg(corpus))
            assert _collate(r4["outputs"]) == _collate(r3["outputs"])
        finally:
            svc2.stop()
    finally:
        del os.environ["DGREP_RESULT_CACHE"]


def test_full_hit_zero_workers_and_restart(tmp_path, corpus):
    """Persistence + the strongest no-scan proof in one: prime daemon A,
    stop it, start daemon B over the SAME work root with NO workers —
    the resubmit must complete from the persisted store alone."""
    work_root = tmp_path / "svc"
    svc = _service(work_root)
    svc.start_local_workers(1)
    try:
        _j, r1 = _run(svc, _cfg(corpus))
    finally:
        svc.stop()
    assert (work_root / "results").exists()

    svc2 = _service(work_root)  # no workers attached, resume replays
    try:
        _j, r2 = _run(svc2, _cfg(corpus), timeout=20)
        assert _collate(r2["outputs"]) == _collate(r1["outputs"])
    finally:
        svc2.stop()


def test_inode_drift_never_served(tmp_path, corpus):
    """cp -p + mv: same size, same mtime, new inode, NEW CONTENT — the
    validator tuple's inode member is what catches it."""
    svc = _service(tmp_path / "svc")
    svc.start_local_workers(1)
    try:
        _j, r1 = _run(svc, _cfg(corpus))
        target = corpus["c.txt"]
        st = target.stat()
        clone = target.with_name("c.txt.new")
        # same byte COUNT, different bytes (hello -> hullo kills matches)
        clone.write_bytes(target.read_bytes().replace(b"hello", b"hullo"))
        os.utime(clone, ns=(st.st_atime_ns, st.st_mtime_ns))
        os.replace(clone, target)
        st2 = target.stat()
        assert (st2.st_size, st2.st_mtime_ns) == (st.st_size, st.st_mtime_ns)

        j2, r2 = _run(svc, _cfg(corpus))
        rec2 = svc.record(j2)
        assert len(rec2.map_splits) == 1  # only c.txt re-scanned
        body = b"".join(_collate(r2["outputs"]))
        assert b"hellohello" not in body
        assert _collate(r2["outputs"]) != _collate(r1["outputs"])
    finally:
        svc.stop()


def test_publish_failure_degrades_to_miss(tmp_path, corpus, monkeypatch):
    """A save that dies mid-publish (the SIGKILL-between-publish-and-
    finalize analogue) leaves at most a PREFIX of per-split entries —
    the next submit partial-hits on what landed and re-scans the rest,
    byte-identical either way."""
    saved = []
    orig = ResultStore.save

    def flaky_save(self, key, records):
        if saved:
            return False  # crash after the first split's entry landed
        saved.append(key)
        return orig(self, key, records)

    monkeypatch.setattr(ResultStore, "save", flaky_save)
    svc = _service(tmp_path / "svc")
    svc.start_local_workers(1)
    try:
        _j, r1 = _run(svc, _cfg(corpus))
        monkeypatch.setattr(ResultStore, "save", orig)
        j2, r2 = _run(svc, _cfg(corpus))
        rec2 = svc.record(j2)
        assert rec2.result_splits_reused == 1  # only the landed entry
        assert len(rec2.map_splits) == 2
        assert _collate(r2["outputs"]) == _collate(r1["outputs"])
    finally:
        svc.stop()


def test_alias_named_submit_misses(tmp_path, corpus):
    """Same content through a symlink alias must MISS: cached records
    carry the publishing job's GIVEN path names (fusion's symlinked
    tenants keep per-job names), so a realpath-keyed hit would label
    every line with the other tenant's paths."""
    svc = _service(tmp_path / "svc")
    svc.start_local_workers(1)
    try:
        _j1, _r1 = _run(svc, _cfg(corpus))
        alias_root = tmp_path / "alias"
        alias_root.symlink_to(corpus["a.txt"].parent)
        alias_corpus = {n: alias_root / n for n in corpus}
        j2, r2 = _run(svc, _cfg(alias_corpus))
        rec2 = svc.record(j2)
        assert rec2.result_splits_reused == 0  # a clean miss, not a hit
        body = b"".join(_collate(r2["outputs"]))
        assert b"/alias/" in body  # records carry the ALIAS spellings
        assert b"/data/" not in body
        # the alias job published under ITS names: an alias resubmit hits
        j3, _r3 = _run(svc, _cfg(alias_corpus))
        assert svc.record(j3).result_splits_reused == len(alias_corpus)
    finally:
        svc.stop()


def test_full_hit_fallback_counts_nothing(tmp_path, corpus, monkeypatch):
    """A full hit whose materialization fails falls back to a real scan
    — /status and /metrics must not keep the phantom hit (counters
    stamp only after the cached blobs land)."""
    svc = _service(tmp_path / "svc")
    svc.start_local_workers(1)
    try:
        _j1, r1 = _run(svc, _cfg(corpus))

        def boom(*_a):
            raise OSError("disk full")

        monkeypatch.setattr(GrepService, "_materialize_cached",
                            staticmethod(boom))
        j2, r2 = _run(svc, _cfg(corpus))
        rec2 = svc.record(j2)
        assert rec2.result_splits_reused == 0
        assert _collate(r2["outputs"]) == _collate(r1["outputs"])
        assert "result_cache" not in svc.status()  # no phantom hit
    finally:
        svc.stop()


def test_status_surfaces_evictions_without_hits(tmp_path, corpus,
                                                monkeypatch):
    """Store eviction counters gate on their OWN nonzero-ness: a daemon
    that published (and LRU-evicted) but never hit still reports them."""
    monkeypatch.setenv("DGREP_RESULT_BYTES", "256")
    svc = _service(tmp_path / "svc")
    svc.start_local_workers(1)
    try:
        _run(svc, _cfg(corpus))  # 3 published entries vs a 256 B budget
        st = svc.status()
        assert st["result_cache"]["result_lru_evictions"] >= 1
        assert "result_hits" not in st["result_cache"]
    finally:
        svc.stop()


def test_disabled_is_true_noop(tmp_path, corpus):
    os.environ["DGREP_RESULT_CACHE"] = "0"
    try:
        svc = _service(tmp_path / "svc")
        svc.start_local_workers(1)
        try:
            j1, _r1 = _run(svc, _cfg(corpus))
            _j2, _r2 = _run(svc, _cfg(corpus))
            rec = svc.record(j1)
            assert rec.result_plan is None
            assert not (tmp_path / "svc" / "results").exists()
            assert "result_cache" not in svc.status()
        finally:
            svc.stop()
    finally:
        del os.environ["DGREP_RESULT_CACHE"]


# ----------------------------------------------------------- store units


def _ident_for(path: Path) -> tuple:
    st = path.stat()
    return ((os.path.realpath(path), st.st_size, st.st_mtime_ns,
             st.st_ino),)


def test_store_roundtrip_and_stale_eviction(tmp_path):
    f = tmp_path / "x.txt"
    f.write_text("one\ntwo\n")
    store = ResultStore(tmp_path / "results")
    key = ResultKey(("q",), str(f), _ident_for(f))
    assert store.save(key, b"x.txt\x001\tone\n")
    assert store.load(
        ResultKey(("q",), str(f), _ident_for(f))
    ) == b"x.txt\x001\tone\n"
    # empty blob (zero-match split) is a VALID entry, not a miss
    g = tmp_path / "y.txt"
    g.write_text("nope\n")
    assert store.save(ResultKey(("q",), str(g), _ident_for(g)), b"")
    assert store.load(ResultKey(("q",), str(g), _ident_for(g))) == b""
    # content drift: identity (the paths) is unchanged, so the lookup
    # maps to the SAME stored file — whose validators now disagree with
    # the fresh stat: never served, evicted on the spot
    time.sleep(0.01)
    f.write_text("one\ntwo\nthree\n")
    fresh = ResultKey(("q",), str(f), _ident_for(f))
    assert store.load(fresh) is None
    assert store.stale_evictions == 1
    assert not store._path_for(fresh.identity).exists()


def test_alias_given_names_are_distinct_entries(tmp_path):
    """Same realpath identity, different GIVEN spelling -> different
    store entries (the records inside carry the given names)."""
    f = tmp_path / "real.txt"
    f.write_text("hit\n")
    link = tmp_path / "alias.txt"
    link.symlink_to(f)
    ident = _ident_for(f)
    assert _ident_for(link) == ident  # realpath collapses the alias
    store = ResultStore(tmp_path / "results")
    assert store.save(ResultKey(("q",), str(f), ident), b"real-records")
    assert store.load(ResultKey(("q",), str(link), ident)) is None
    assert store.load(ResultKey(("q",), str(f), ident)) == b"real-records"


def test_bucket_records_duplicate_member_publishes_nothing(tmp_path):
    out = tmp_path / "out-0"
    out.write_bytes(b"a.txt (line number #1)\thit\n")
    # the same file listed twice: attribution is ambiguous and the two
    # same-identity splits would overwrite each other's entry
    assert result_cache.bucket_records(
        [str(out)], ["a.txt", "a.txt"]
    ) is None
    got = result_cache.bucket_records([str(out)], ["a.txt", "b.txt"])
    assert got == [b"a.txt (line number #1)\thit\n", b""]


def test_store_sweeps_torn_tmp_files(tmp_path):
    root = tmp_path / "results"
    root.mkdir()
    torn = root / ".abc.res.123.456.tmp"
    torn.write_bytes(b"torn half-write")
    ResultStore(root)  # construction sweeps crash leftovers
    assert not torn.exists()


def test_store_lru_eviction_and_oversize_decline(tmp_path, monkeypatch):
    f = tmp_path / "x.txt"
    f.write_text("data\n")
    ident = _ident_for(f)
    store = ResultStore(tmp_path / "results")
    monkeypatch.setenv("DGREP_RESULT_BYTES", "4096")
    old = ResultKey(("old",), str(f), ident)
    assert store.save(old, b"a" * 1500)
    time.sleep(0.01)
    assert store.save(ResultKey(("mid",), str(f), ident), b"b" * 1500)
    time.sleep(0.01)
    # third entry overflows the 4096 budget -> oldest-mtime evicted
    assert store.save(ResultKey(("new",), str(f), ident), b"c" * 1500)
    assert store.load(old) is None
    assert store.lru_evictions >= 1
    # an entry larger than the WHOLE budget is declined, evicting nobody
    before = sorted(p.name for p in (tmp_path / "results").glob("*.res"))
    assert not store.save(ResultKey(("huge",), str(f), ident), b"z" * 8192)
    after = sorted(p.name for p in (tmp_path / "results").glob("*.res"))
    assert before == after
    monkeypatch.setenv("DGREP_RESULT_BYTES", "0")
    assert not store.save(ResultKey(("off",), str(f), ident), b"x")


def test_eligibility_boundaries(corpus):
    assert result_key(_cfg(corpus)) is not None
    assert result_key(_cfg(corpus, invert=True)) is None
    assert result_key(_cfg(corpus, count_only=True)) is None
    assert result_key(_cfg(corpus, presence_only=True)) is None
    follow_cfg = _cfg(corpus)
    follow_cfg.follow = True
    assert result_key(follow_cfg) is None
    other_app = _cfg(corpus)
    other_app.application = "some.custom.app"
    assert result_key(other_app) is None


def test_env_knob_parsers(monkeypatch):
    monkeypatch.delenv("DGREP_RESULT_CACHE", raising=False)
    assert result_cache.env_result_cache() is True
    for off in ("0", "false", "no", " NO "):
        monkeypatch.setenv("DGREP_RESULT_CACHE", off)
        assert result_cache.env_result_cache() is False
    monkeypatch.setenv("DGREP_RESULT_CACHE", "1")
    assert result_cache.env_result_cache() is True
    monkeypatch.delenv("DGREP_RESULT_BYTES", raising=False)
    assert result_cache.env_result_bytes() == result_cache.DEFAULT_RESULT_BYTES
    monkeypatch.setenv("DGREP_RESULT_BYTES", "1024")
    assert result_cache.env_result_bytes() == 1024
    monkeypatch.setenv("DGREP_RESULT_BYTES", "-5")
    assert result_cache.env_result_bytes() == 0
    monkeypatch.setenv("DGREP_RESULT_BYTES", "zap")
    assert result_cache.env_result_bytes() == result_cache.DEFAULT_RESULT_BYTES

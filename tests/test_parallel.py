"""Multi-chip tests on the virtual 8-device CPU mesh (SURVEY.md §4)."""

import re

import numpy as np
import pytest

import jax

from distributed_grep_tpu.models.dfa import compile_dfa, reference_scan
from distributed_grep_tpu.ops import layout as layout_mod
from distributed_grep_tpu.ops import lines as lines_mod
from distributed_grep_tpu.parallel.mesh import make_mesh
from distributed_grep_tpu.parallel.sharded_scan import sharded_grep_step


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must fake 8 CPU devices"
    return make_mesh((8,), ("data",))


def make_text(n_lines=400, seed=11, inject=()):
    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n_lines):
        n = int(rng.integers(0, 60))
        lines.append(bytes(rng.choice(list(b"abcdef gh"), size=n).tolist()))
    for pos, text in inject:
        lines[pos] = text
    return b"\n".join(lines) + b"\n"


def test_sharded_scan_matches_host_oracle(mesh8):
    data = make_text(inject=[(7, b"a needle here"), (390, b"needle again")])
    table = compile_dfa("needle")
    lay = layout_mod.choose_layout(len(data), target_lanes=64, min_chunk=8)
    arr = layout_mod.to_device_array(data, lay)
    packed, total, exits, neigh = sharded_grep_step(arr, table, mesh8)
    # Count: device total equals oracle count away from boundaries; boundary
    # misses are possible, so compare via full offsets with stitching below.
    packed_np = np.asarray(packed)
    offsets = lines_mod.match_offsets_from_packed(packed_np, lay)
    nl = lines_mod.newline_index(data)
    device_lines = set(np.unique(lines_mod.line_of_offsets(offsets, nl)).tolist())
    stitched = lines_mod.stitch_lines(
        device_lines,
        data,
        nl,
        lay.stripe_starts().tolist(),
        lambda line: reference_scan(table, line).size > 0,
    )
    expected = {
        i
        for i, line in enumerate(data.split(b"\n"), start=1)
        if re.search(b"needle", line)
    }
    assert stitched == expected
    assert int(total) == offsets.size


def test_sharded_scan_collectives_shapes(mesh8):
    data = make_text(100)
    table = compile_dfa("abc")
    lay = layout_mod.choose_layout(len(data), target_lanes=64, min_chunk=8)
    arr = layout_mod.to_device_array(data, lay)
    packed, total, exits, neigh = sharded_grep_step(arr, table, mesh8)
    assert np.asarray(exits).shape == (lay.lanes,)
    # ppermute ring: every device received exactly one neighbor state
    assert np.asarray(neigh).shape == (8,)
    assert np.asarray(packed).shape == (lay.chunk, lay.lanes // 8)


def test_mesh_helpers():
    m = make_mesh()
    assert m.devices.size == 8
    m2 = make_mesh((4, 2), ("data", "seq"))
    assert m2.shape == {"data": 4, "seq": 2}
    with pytest.raises(ValueError):
        make_mesh((16,), ("data",))


def test_two_axis_mesh_scan():
    """(data, seq) 2D mesh: lanes sharded over the flattened device order —
    scan over the seq axis composed with data axis still yields exact
    results after stitching."""
    mesh = make_mesh((4, 2), ("data", "seq"))
    data = make_text(200, inject=[(50, b"the needle sits here")])
    table = compile_dfa("needle")
    lay = layout_mod.choose_layout(len(data), target_lanes=64, min_chunk=8)
    arr = layout_mod.to_device_array(data, lay)
    # Shard lanes over 'seq' (stripes of one doc across chips), replicate
    # over 'data' — the long-context configuration.
    packed, total, exits, neigh = sharded_grep_step(arr, table, mesh, axis="seq")
    packed_np = np.asarray(packed)
    offsets = lines_mod.match_offsets_from_packed(packed_np, lay)
    nl = lines_mod.newline_index(data)
    device_lines = set(np.unique(lines_mod.line_of_offsets(offsets, nl)).tolist())
    stitched = lines_mod.stitch_lines(
        device_lines, data, nl, lay.stripe_starts().tolist(),
        lambda line: reference_scan(table, line).size > 0,
    )
    expected = {
        i for i, line in enumerate(data.split(b"\n"), start=1) if b"needle" in line
    }
    assert stitched == expected


def test_product_axis_sharding_uses_all_devices():
    """axis=("data","seq"): lanes shard over the 4x2 product — all 8 devices
    hold distinct stripes, psum spans both axes, ring rides 'seq'."""
    mesh = make_mesh((4, 2), ("data", "seq"))
    data = make_text(300, inject=[(13, b"needle one"), (250, b"two needle")])
    table = compile_dfa("needle")
    lay = layout_mod.choose_layout(len(data), target_lanes=64, min_chunk=8)
    arr = layout_mod.to_device_array(data, lay)
    packed, total, exits, neigh = sharded_grep_step(
        arr, table, mesh, axis=("data", "seq")
    )
    shard_shapes = {s.data.shape for s in packed.addressable_shards}
    assert shard_shapes == {(lay.chunk, lay.lanes // 8 // 8)}  # 8-way lane split
    # The ring must wrap over the LINEARIZED product order (data-major),
    # not within each seq group: device d receives device (d-1)%8's last
    # lane's exit state.
    exits_np = np.asarray(exits)
    local = lay.lanes // 8
    last_exit_per_dev = exits_np[local - 1 :: local]
    np.testing.assert_array_equal(np.asarray(neigh), np.roll(last_exit_per_dev, 1))
    offsets = lines_mod.match_offsets_from_packed(np.asarray(packed), lay)
    nl = lines_mod.newline_index(data)
    device_lines = set(np.unique(lines_mod.line_of_offsets(offsets, nl)).tolist())
    stitched = lines_mod.stitch_lines(
        device_lines, data, nl, lay.stripe_starts().tolist(),
        lambda line: reference_scan(table, line).size > 0,
    )
    expected = {
        i for i, line in enumerate(data.split(b"\n"), start=1) if b"needle" in line
    }
    assert stitched == expected
    assert int(total) == offsets.size


# ------------------------------------------------- pattern-parallel (EP) step

def test_pattern_set_step_matches_oracle():
    """Banks shard over 'seq' (pattern axis), lanes over 'data': the OR over
    the pattern axis must equal a single-automaton scan of the whole set."""
    from distributed_grep_tpu.models.aho import compile_aho_corasick_banks
    from distributed_grep_tpu.parallel.sharded_scan import sharded_pattern_set_step

    rng = np.random.default_rng(5)
    pats = sorted(
        {bytes(rng.choice(list(b"abcdefgh"), size=int(rng.integers(3, 7))).tolist())
         for _ in range(40)}
    )
    # one tiny bank per ~8 patterns -> several banks to shard
    tables = []
    for i in range(0, len(pats), 8):
        tables.extend(compile_aho_corasick_banks(pats[i : i + 8]))
    assert len(tables) >= 3
    data = make_text(300, inject=[(5, b"xx " + pats[0] + b" yy"), (250, pats[1])])
    mesh = make_mesh((4, 2), ("data", "seq"))
    lay = layout_mod.choose_layout(len(data), target_lanes=64, min_chunk=8)
    arr = layout_mod.to_device_array(data, lay)
    packed, total = sharded_pattern_set_step(arr, tables, mesh)
    offsets = lines_mod.match_offsets_from_packed(np.asarray(packed), lay)
    assert int(total) >= offsets.size  # total counts padded tail positions too
    nl = lines_mod.newline_index(data)
    device_lines = set(np.unique(lines_mod.line_of_offsets(offsets, nl)).tolist())

    def any_bank(line):
        return any(reference_scan(t, line).size > 0 for t in tables)

    stitched = lines_mod.stitch_lines(
        device_lines, data, nl, lay.stripe_starts().tolist(), any_bank
    )
    expected = {
        i for i, line in enumerate(data.split(b"\n"), 1)
        if any(p in line for p in pats)
    }
    assert stitched == expected


def test_pattern_set_step_bank_padding():
    """Bank count not divisible by the pattern axis: dead padding banks must
    contribute nothing."""
    from distributed_grep_tpu.models.aho import compile_aho_corasick
    from distributed_grep_tpu.parallel.sharded_scan import sharded_pattern_set_step

    tables = [compile_aho_corasick([b"needle"]), compile_aho_corasick([b"volcano"]),
              compile_aho_corasick([b"quartz"])]  # 3 banks over a 2-wide axis
    data = make_text(100, inject=[(3, b"a needle"), (50, b"quartz volcano")])
    mesh = make_mesh((4, 2), ("data", "seq"))
    lay = layout_mod.choose_layout(len(data), target_lanes=64, min_chunk=8)
    arr = layout_mod.to_device_array(data, lay)
    packed, total = sharded_pattern_set_step(arr, tables, mesh)
    offsets = lines_mod.match_offsets_from_packed(np.asarray(packed), lay)
    nl = lines_mod.newline_index(data)
    got = set(np.unique(lines_mod.line_of_offsets(offsets, nl)).tolist())

    def any_bank(line):
        return any(reference_scan(t, line).size > 0 for t in tables)

    got = lines_mod.stitch_lines(got, data, nl, lay.stripe_starts().tolist(), any_bank)
    expected = {
        i for i, line in enumerate(data.split(b"\n"), 1)
        if any(p in line for p in (b"needle", b"volcano", b"quartz"))
    }
    assert got == expected


# ----------------------------- production Pallas kernels under shard_map

def _mesh_layout(data, mesh, axis="data"):
    from distributed_grep_tpu.parallel import sharded_kernels as sk

    mult = sk.mesh_lane_multiple(mesh, axis)
    lay = layout_mod.choose_layout(
        len(data), target_lanes=mult, min_chunk=512,
        lane_multiple=mult, chunk_multiple=512,
    )
    return lay, layout_mod.to_device_array(data, lay)


def test_sharded_shift_and_bit_identical(mesh8):
    """The shift-and Pallas kernel under shard_map must produce the exact
    words a single-device run produces, with the psum count matching."""
    from distributed_grep_tpu.models.shift_and import try_compile_shift_and
    from distributed_grep_tpu.ops import pallas_scan
    from distributed_grep_tpu.parallel import sharded_kernels as sk

    data = make_text(500, inject=[(7, b"a needle here"), (420, b"needle!")])
    model = try_compile_shift_and("needle")
    lay, arr = _mesh_layout(data, mesh8)
    words, total = sk.sharded_shift_and_words(
        arr, model, mesh8, coarse=True, interpret=True
    )
    ref = pallas_scan.shift_and_scan_words(arr, model, interpret=True, coarse=True)
    np.testing.assert_array_equal(np.asarray(words), np.asarray(ref))
    assert int(total) == int(np.count_nonzero(np.asarray(ref)))
    # lanes really shard: every device holds 1/8 of the tile rows
    shard_shapes = {s.data.shape for s in words.addressable_shards}
    assert shard_shapes == {(lay.chunk // 32, lay.lanes // 128 // 8, 128)}


def test_sharded_nfa_bit_identical(mesh8):
    from distributed_grep_tpu.models.nfa import try_compile_glushkov
    from distributed_grep_tpu.ops import pallas_nfa
    from distributed_grep_tpu.parallel import sharded_kernels as sk

    data = make_text(400, inject=[(3, b"neeedle x"), (300, b"nedle")])
    model = try_compile_glushkov("ne+dle")
    assert model is not None
    lay, arr = _mesh_layout(data, mesh8)
    words, total = sk.sharded_nfa_words(arr, model, mesh8, interpret=True)
    ref = pallas_nfa.nfa_scan_words(arr, model, interpret=True)
    np.testing.assert_array_equal(np.asarray(words), np.asarray(ref))
    assert int(total) == int(np.count_nonzero(np.asarray(ref)))


def test_sharded_fdr_bit_identical(mesh8):
    from distributed_grep_tpu.models.fdr import compile_fdr
    from distributed_grep_tpu.ops import pallas_fdr
    from distributed_grep_tpu.parallel import sharded_kernels as sk

    rng = np.random.default_rng(17)
    pats = [b"needle", b"zebra", b"volcano"] + [
        bytes(rng.choice(list(b"abcdefgh"), size=6).tolist()) for _ in range(40)
    ]
    fdr = compile_fdr(pats)
    data = make_text(400, inject=[(11, b"xx needle"), (200, pats[5])])
    lay, arr = _mesh_layout(data, mesh8)
    words, total = sk.sharded_fdr_words(arr, fdr, mesh8, interpret=True)
    ref = None
    for bank in fdr.banks:
        w = pallas_fdr.fdr_scan_words(arr, bank, interpret=True)
        ref = w if ref is None else ref | w
    np.testing.assert_array_equal(np.asarray(words), np.asarray(ref))
    assert int(total) == int(np.count_nonzero(np.asarray(ref)))


def test_engine_mesh_mode_exact(mesh8):
    """GrepEngine(mesh=...) — the production multi-chip mode — must be exact
    vs the line oracle for all three kernel families and record the psum'd
    collective candidate count."""
    from distributed_grep_tpu.ops.engine import GrepEngine

    rng = np.random.default_rng(23)
    lines = []
    for i in range(700):
        n = int(rng.integers(0, 60))
        lines.append(bytes(rng.choice(list(b"abcdefg h"), size=n).tolist()))
        if i % 37 == 5:
            lines[-1] = b"xx needle yy"
        if i % 53 == 9:
            lines[-1] = b"neeeedle and needles"
    data = b"\n".join(lines) + b"\n"

    def oracle(rx):
        return {
            i for i, ln in enumerate(data.split(b"\n")[:-1], 1)
            if re.search(rx, ln)
        }

    engines = {
        "shift_and": GrepEngine("needle", mesh=mesh8, interpret=True),
        "nfa": GrepEngine("ne+dle", mesh=mesh8, interpret=True),
        "fdr": GrepEngine(
            patterns=["needle", "zebra", "volcano", "abcdef", "fedcba",
                      "gabhcd", "hhfgab", "deadbe"],
            mesh=mesh8, interpret=True,
        ),
    }
    rxs = {"shift_and": b"needle", "nfa": b"ne+dle",
           "fdr": b"needle|zebra|volcano|abcdef|fedcba|gabhcd|hhfgab|deadbe"}
    for want_mode, eng in engines.items():
        assert eng.mode == want_mode
        res = eng.scan(data)
        assert set(res.matched_lines.tolist()) == oracle(rxs[want_mode]), want_mode
        assert eng.stats.get("psum_candidates", 0) >= 1, want_mode


def test_engine_mesh_multi_segment(mesh8):
    """Several segments through the mesh path: per-segment shard_map scans
    with psum totals accumulated across segments."""
    from distributed_grep_tpu.ops.engine import GrepEngine

    data = make_text(2000, inject=[(5, b"needle a"), (1990, b"z needle")])
    eng = GrepEngine("needle", mesh=mesh8, interpret=True,
                     segment_bytes=16 * 1024)
    res = eng.scan(data)
    expected = {
        i for i, ln in enumerate(data.split(b"\n")[:-1], 1) if b"needle" in ln
    }
    assert set(res.matched_lines.tolist()) == expected
    assert eng.stats.get("psum_candidates", 0) >= 2


def test_sharded_fdr_pattern_parallel_bit_identical():
    """EP on the production kernel: same-plan FDR banks shard over the
    pattern axis (tables are the sharded operand), candidate words OR over
    ICI — output must be bit-identical to a single-device OR over all
    banks, including zero-table padding banks."""
    from distributed_grep_tpu.models.fdr import FdrModel, compile_fdr
    from distributed_grep_tpu.ops import pallas_fdr
    from distributed_grep_tpu.parallel import sharded_kernels as sk

    rng = np.random.default_rng(7)
    pats = sorted({
        bytes(rng.choice(list(b"abcdefghijklmnop"), size=6).tolist())
        for _ in range(600)
    })
    h1, h2 = pats[::2], pats[1::2]
    m1, m2 = compile_fdr(h1), compile_fdr(h2)
    plans = {(b.m, b.checks) for b in (*m1.banks, *m2.banks)}
    assert len(plans) == 1, "same-distribution halves should share a plan"
    model = FdrModel(banks=list(m1.banks) + list(m2.banks),
                     ignore_case=False, n_patterns=len(pats))

    data = make_text(500, inject=[(7, b"xx " + pats[3]), (420, pats[11])])
    mesh = make_mesh((4, 2), ("data", "seq"))
    lay, arr = _mesh_layout(data, mesh, axis="data")
    words, total = sk.sharded_fdr_pattern_step(
        arr, model, mesh, data_axis="data", pattern_axis="seq",
        interpret=True,
    )
    ref = None
    for bank in model.banks:
        w = pallas_fdr.fdr_scan_words(arr, bank, interpret=True)
        ref = w if ref is None else ref | w
    np.testing.assert_array_equal(np.asarray(words), np.asarray(ref))
    assert int(total) == int(np.count_nonzero(np.asarray(ref)))
    # lanes shard over data only; every device holds 1/4 of the tiles
    shard_shapes = {s.data.shape for s in words.addressable_shards}
    assert shard_shapes == {(lay.chunk // 32, lay.lanes // 128 // 4, 128)}



def test_sharded_approx_bit_identical_and_engine_mesh(mesh8):
    """The approx (agrep) kernel under shard_map: bit-identical words, and
    the engine's mesh mode is exact for max_errors scans."""
    from distributed_grep_tpu.models.approx import line_matches, try_compile_approx
    from distributed_grep_tpu.ops import pallas_approx
    from distributed_grep_tpu.ops.engine import GrepEngine
    from distributed_grep_tpu.parallel import sharded_kernels as sk

    model = try_compile_approx("needle", 1)
    assert model is not None
    data = make_text(400, inject=[(5, b"a needle"), (300, b"nedle x"),
                                  (350, b"nXedle")])
    lay, arr = _mesh_layout(data, mesh8)
    words, total = sk.sharded_approx_words(arr, model, mesh8, interpret=True)
    ref = pallas_approx.approx_scan_words(arr, model, interpret=True)
    np.testing.assert_array_equal(np.asarray(words), np.asarray(ref))
    assert int(total) == int(np.count_nonzero(np.asarray(ref)))

    eng = GrepEngine("needle", max_errors=1, mesh=mesh8, interpret=True)
    res = eng.scan(data)
    want = {
        i for i, ln in enumerate(data.split(b"\n")[:-1], 1)
        if line_matches(model, ln)
    }
    assert set(res.matched_lines.tolist()) == want
    assert eng.stats.get("psum_candidates", 0) >= 1


def test_engine_pattern_axis_ep_exact():
    """GrepEngine(mesh=2D, pattern_axis=...): same-plan FDR banks shard
    over the pattern axis inside the engine — exact output, psum recorded;
    mixed-plan models silently keep the lane-sharded step."""
    from distributed_grep_tpu.models.fdr import FdrModel, compile_fdr
    from distributed_grep_tpu.ops.engine import GrepEngine

    rng = np.random.default_rng(9)
    pats = sorted({
        bytes(rng.choice(list(b"abcdefghijklmnop"), size=6).tolist())
        for _ in range(400)
    })
    data = make_text(700, inject=[(11, b"xx " + pats[5]), (600, pats[17])])
    expected = {
        i for i, ln in enumerate(data.split(b"\n")[:-1], 1)
        if any(p in ln for p in pats)
    }
    mesh = make_mesh((4, 2), ("data", "seq"))
    eng = GrepEngine(
        patterns=[p.decode() for p in pats],
        mesh=mesh, mesh_axis="data", pattern_axis="seq", interpret=True,
    )
    assert eng.mode == "fdr"
    res = eng.scan(data)
    assert set(res.matched_lines.tolist()) == expected
    assert eng.stats.get("psum_candidates", 0) >= 1


def test_engine_mesh_axis_validation(mesh8):
    """Bad axis names fail at construction, not inside the scan's
    kernel-failure net (which would demote the engine silently)."""
    from distributed_grep_tpu.ops.engine import GrepEngine

    with pytest.raises(ValueError, match="mesh_axis"):
        GrepEngine("needle", mesh=mesh8, mesh_axis="bogus")
    mesh2d = make_mesh((4, 2), ("data", "seq"))
    with pytest.raises(ValueError, match="pattern_axis"):
        GrepEngine(patterns=["aa", "bb"], mesh=mesh2d, mesh_axis="data",
                   pattern_axis="typo")
    with pytest.raises(ValueError, match="pattern_axis"):
        GrepEngine(patterns=["aa", "bb"], mesh=mesh2d,
                   mesh_axis=("data", "seq"), pattern_axis="seq")


def test_confirms_overlap_across_device_segments(monkeypatch):
    """VERDICT r3 item 1 done-criterion: with several devices in flight,
    FDR confirms for different segments must run CONCURRENTLY (on the
    collect pool) instead of serializing on the dispatch thread — and the
    result must stay exact while they do."""
    import threading

    from distributed_grep_tpu.ops.engine import GrepEngine

    rng = np.random.default_rng(31)
    alphabet = list(b"abcdefghijklmnopqrstuvwxyz0123456789")
    pats = sorted({
        bytes(rng.choice(alphabet, size=int(rng.integers(5, 9))).tolist())
        for _ in range(200)
    })
    lines = []
    for i in range(4000):
        n = int(rng.integers(0, 50))
        lines.append(bytes(rng.choice(alphabet + [32], size=n).tolist()))
        if i % 41 == 3:
            lines[-1] = b"xx " + pats[int(rng.integers(0, len(pats)))] + b" yy"
    data = b"\n".join(lines) + b"\n"

    monkeypatch.setenv("DGREP_NO_CALIBRATE", "1")
    eng = GrepEngine(
        patterns=[p.decode() for p in pats], devices="all", interpret=True,
        segment_bytes=16 * 1024,
    )
    assert eng.mode == "fdr"
    assert len(data) // (16 * 1024) >= 4  # several segments in flight

    real = eng._fdr_confirm.confirm
    gate = threading.Event()
    lock = threading.Lock()
    calls = [0]

    def slow_confirm(buf, ends, **kw):
        with lock:
            calls[0] += 1
            first = calls[0] == 1
        if first:
            # hold the first confirm open until a second one ENTERS — only
            # possible if confirms run concurrently (the 10 s timeout keeps
            # a serializing regression failing fast instead of hanging)
            gate.wait(timeout=10)
        else:
            gate.set()
        return real(buf, ends, **kw)

    monkeypatch.setattr(eng._fdr_confirm, "confirm", slow_confirm)
    res = eng.scan(data)
    expected = {
        i for i, ln in enumerate(data.split(b"\n")[:-1], 1)
        if any(p in ln for p in pats)
    }
    assert set(res.matched_lines.tolist()) == expected
    assert eng.stats.get("confirm_concurrency_peak", 0) >= 2

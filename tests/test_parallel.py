"""Multi-chip tests on the virtual 8-device CPU mesh (SURVEY.md §4)."""

import re

import numpy as np
import pytest

import jax

from distributed_grep_tpu.models.dfa import compile_dfa, reference_scan
from distributed_grep_tpu.ops import layout as layout_mod
from distributed_grep_tpu.ops import lines as lines_mod
from distributed_grep_tpu.parallel.mesh import make_mesh
from distributed_grep_tpu.parallel.sharded_scan import sharded_grep_step


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must fake 8 CPU devices"
    return make_mesh((8,), ("data",))


def make_text(n_lines=400, seed=11, inject=()):
    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n_lines):
        n = int(rng.integers(0, 60))
        lines.append(bytes(rng.choice(list(b"abcdef gh"), size=n).tolist()))
    for pos, text in inject:
        lines[pos] = text
    return b"\n".join(lines) + b"\n"


def test_sharded_scan_matches_host_oracle(mesh8):
    data = make_text(inject=[(7, b"a needle here"), (390, b"needle again")])
    table = compile_dfa("needle")
    lay = layout_mod.choose_layout(len(data), target_lanes=64, min_chunk=8)
    arr = layout_mod.to_device_array(data, lay)
    packed, total, exits, neigh = sharded_grep_step(arr, table, mesh8)
    # Count: device total equals oracle count away from boundaries; boundary
    # misses are possible, so compare via full offsets with stitching below.
    packed_np = np.asarray(packed)
    offsets = lines_mod.match_offsets_from_packed(packed_np, lay)
    nl = lines_mod.newline_index(data)
    device_lines = set(np.unique(lines_mod.line_of_offsets(offsets, nl)).tolist())
    stitched = lines_mod.stitch_lines(
        device_lines,
        data,
        nl,
        lay.stripe_starts().tolist(),
        lambda line: reference_scan(table, line).size > 0,
    )
    expected = {
        i
        for i, line in enumerate(data.split(b"\n"), start=1)
        if re.search(b"needle", line)
    }
    assert stitched == expected
    assert int(total) == offsets.size


def test_sharded_scan_collectives_shapes(mesh8):
    data = make_text(100)
    table = compile_dfa("abc")
    lay = layout_mod.choose_layout(len(data), target_lanes=64, min_chunk=8)
    arr = layout_mod.to_device_array(data, lay)
    packed, total, exits, neigh = sharded_grep_step(arr, table, mesh8)
    assert np.asarray(exits).shape == (lay.lanes,)
    # ppermute ring: every device received exactly one neighbor state
    assert np.asarray(neigh).shape == (8,)
    assert np.asarray(packed).shape == (lay.chunk, lay.lanes // 8)


def test_mesh_helpers():
    m = make_mesh()
    assert m.devices.size == 8
    m2 = make_mesh((4, 2), ("data", "seq"))
    assert m2.shape == {"data": 4, "seq": 2}
    with pytest.raises(ValueError):
        make_mesh((16,), ("data",))


def test_two_axis_mesh_scan():
    """(data, seq) 2D mesh: lanes sharded over the flattened device order —
    scan over the seq axis composed with data axis still yields exact
    results after stitching."""
    mesh = make_mesh((4, 2), ("data", "seq"))
    data = make_text(200, inject=[(50, b"the needle sits here")])
    table = compile_dfa("needle")
    lay = layout_mod.choose_layout(len(data), target_lanes=64, min_chunk=8)
    arr = layout_mod.to_device_array(data, lay)
    # Shard lanes over 'seq' (stripes of one doc across chips), replicate
    # over 'data' — the long-context configuration.
    packed, total, exits, neigh = sharded_grep_step(arr, table, mesh, axis="seq")
    packed_np = np.asarray(packed)
    offsets = lines_mod.match_offsets_from_packed(packed_np, lay)
    nl = lines_mod.newline_index(data)
    device_lines = set(np.unique(lines_mod.line_of_offsets(offsets, nl)).tolist())
    stitched = lines_mod.stitch_lines(
        device_lines, data, nl, lay.stripe_starts().tolist(),
        lambda line: reference_scan(table, line).size > 0,
    )
    expected = {
        i for i, line in enumerate(data.split(b"\n"), start=1) if b"needle" in line
    }
    assert stitched == expected


def test_product_axis_sharding_uses_all_devices():
    """axis=("data","seq"): lanes shard over the 4x2 product — all 8 devices
    hold distinct stripes, psum spans both axes, ring rides 'seq'."""
    mesh = make_mesh((4, 2), ("data", "seq"))
    data = make_text(300, inject=[(13, b"needle one"), (250, b"two needle")])
    table = compile_dfa("needle")
    lay = layout_mod.choose_layout(len(data), target_lanes=64, min_chunk=8)
    arr = layout_mod.to_device_array(data, lay)
    packed, total, exits, neigh = sharded_grep_step(
        arr, table, mesh, axis=("data", "seq")
    )
    shard_shapes = {s.data.shape for s in packed.addressable_shards}
    assert shard_shapes == {(lay.chunk, lay.lanes // 8 // 8)}  # 8-way lane split
    # The ring must wrap over the LINEARIZED product order (data-major),
    # not within each seq group: device d receives device (d-1)%8's last
    # lane's exit state.
    exits_np = np.asarray(exits)
    local = lay.lanes // 8
    last_exit_per_dev = exits_np[local - 1 :: local]
    np.testing.assert_array_equal(np.asarray(neigh), np.roll(last_exit_per_dev, 1))
    offsets = lines_mod.match_offsets_from_packed(np.asarray(packed), lay)
    nl = lines_mod.newline_index(data)
    device_lines = set(np.unique(lines_mod.line_of_offsets(offsets, nl)).tolist())
    stitched = lines_mod.stitch_lines(
        device_lines, data, nl, lay.stripe_starts().tolist(),
        lambda line: reference_scan(table, line).size > 0,
    )
    expected = {
        i for i, line in enumerate(data.split(b"\n"), start=1) if b"needle" in line
    }
    assert stitched == expected
    assert int(total) == offsets.size


# ------------------------------------------------- pattern-parallel (EP) step

def test_pattern_set_step_matches_oracle():
    """Banks shard over 'seq' (pattern axis), lanes over 'data': the OR over
    the pattern axis must equal a single-automaton scan of the whole set."""
    from distributed_grep_tpu.models.aho import compile_aho_corasick_banks
    from distributed_grep_tpu.parallel.sharded_scan import sharded_pattern_set_step

    rng = np.random.default_rng(5)
    pats = sorted(
        {bytes(rng.choice(list(b"abcdefgh"), size=int(rng.integers(3, 7))).tolist())
         for _ in range(40)}
    )
    # one tiny bank per ~8 patterns -> several banks to shard
    tables = []
    for i in range(0, len(pats), 8):
        tables.extend(compile_aho_corasick_banks(pats[i : i + 8]))
    assert len(tables) >= 3
    data = make_text(300, inject=[(5, b"xx " + pats[0] + b" yy"), (250, pats[1])])
    mesh = make_mesh((4, 2), ("data", "seq"))
    lay = layout_mod.choose_layout(len(data), target_lanes=64, min_chunk=8)
    arr = layout_mod.to_device_array(data, lay)
    packed, total = sharded_pattern_set_step(arr, tables, mesh)
    offsets = lines_mod.match_offsets_from_packed(np.asarray(packed), lay)
    assert int(total) >= offsets.size  # total counts padded tail positions too
    nl = lines_mod.newline_index(data)
    device_lines = set(np.unique(lines_mod.line_of_offsets(offsets, nl)).tolist())

    def any_bank(line):
        return any(reference_scan(t, line).size > 0 for t in tables)

    stitched = lines_mod.stitch_lines(
        device_lines, data, nl, lay.stripe_starts().tolist(), any_bank
    )
    expected = {
        i for i, line in enumerate(data.split(b"\n"), 1)
        if any(p in line for p in pats)
    }
    assert stitched == expected


def test_pattern_set_step_bank_padding():
    """Bank count not divisible by the pattern axis: dead padding banks must
    contribute nothing."""
    from distributed_grep_tpu.models.aho import compile_aho_corasick
    from distributed_grep_tpu.parallel.sharded_scan import sharded_pattern_set_step

    tables = [compile_aho_corasick([b"needle"]), compile_aho_corasick([b"volcano"]),
              compile_aho_corasick([b"quartz"])]  # 3 banks over a 2-wide axis
    data = make_text(100, inject=[(3, b"a needle"), (50, b"quartz volcano")])
    mesh = make_mesh((4, 2), ("data", "seq"))
    lay = layout_mod.choose_layout(len(data), target_lanes=64, min_chunk=8)
    arr = layout_mod.to_device_array(data, lay)
    packed, total = sharded_pattern_set_step(arr, tables, mesh)
    offsets = lines_mod.match_offsets_from_packed(np.asarray(packed), lay)
    nl = lines_mod.newline_index(data)
    got = set(np.unique(lines_mod.line_of_offsets(offsets, nl)).tolist())

    def any_bank(line):
        return any(reference_scan(t, line).size > 0 for t in tables)

    got = lines_mod.stitch_lines(got, data, nl, lay.stripe_starts().tolist(), any_bank)
    expected = {
        i for i, line in enumerate(data.split(b"\n"), 1)
        if any(p in line for p in (b"needle", b"volcano", b"quartz"))
    }
    assert got == expected

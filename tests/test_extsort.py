"""Streaming reduce (runtime/extsort.py): bounded-memory grouping must be
byte-identical to the in-memory sort-merge the reference specifies
(worker.go:146-176), including value-arrival order within a key."""

from __future__ import annotations

import numpy as np

from distributed_grep_tpu.apps.base import KeyValue, group_reduce
from distributed_grep_tpu.runtime.extsort import ExternalReducer
from distributed_grep_tpu.runtime.job import run_job
from distributed_grep_tpu.utils.config import JobConfig


def _records(n, n_keys, seed):
    rng = np.random.default_rng(seed)
    return [
        KeyValue(key=f"k{int(rng.integers(0, n_keys)):05d}", value=f"v{i}")
        for i in range(n)
    ]


def test_spilled_matches_in_memory_order_sensitive():
    """An order-sensitive reduce (join) proves the merge keeps each key's
    values in arrival order across spill boundaries."""
    recs = _records(5000, 40, seed=1)
    join = lambda k, vs: ",".join(vs)  # noqa: E731
    want = group_reduce(recs, join)
    with ExternalReducer(memory_limit_bytes=16 << 10) as r:
        # feed in several batches like the worker does per intermediate file
        for i in range(0, len(recs), 700):
            r.add_many(recs[i : i + 700])
        assert r.spill_count > 1  # the cap actually bit
        got = dict(r.reduce(join))
    assert got == want


def test_no_spill_small_input():
    recs = _records(100, 10, seed=2)
    want = group_reduce(recs, lambda k, vs: str(len(vs)))
    with ExternalReducer(memory_limit_bytes=64 << 20) as r:
        r.add_many(recs)
        assert r.spill_count == 0
        got = dict(r.reduce(lambda k, vs: str(len(vs))))
    assert got == want


def test_keys_stream_sorted():
    with ExternalReducer(memory_limit_bytes=4 << 10) as r:
        r.add_many(_records(3000, 200, seed=3))
        keys = [k for k, _ in r.reduce(lambda k, vs: "x")]
    assert keys == sorted(keys) and len(keys) == len(set(keys))


def test_stream_fn_never_builds_list():
    """reduce_stream_fn receives an iterator; consuming lazily must agree
    with the list-based reduce."""
    recs = _records(4000, 30, seed=4)
    with ExternalReducer(memory_limit_bytes=8 << 10) as r:
        r.add_many(recs)
        got = dict(r.reduce(None, stream_fn=lambda k, vs: str(sum(1 for _ in vs))))
    want = group_reduce(recs, lambda k, vs: str(len(vs)))
    assert got == want


def test_values_with_awkward_bytes_roundtrip():
    """Values containing \\r, tabs, U+2028 and non-ASCII must survive the
    spill wire format exactly."""
    recs = [
        KeyValue("a", "line\rwith\rcr"),
        KeyValue("a", "tab\there"),
        KeyValue("b", "uni sep"),
        KeyValue("b", "café \udcff"),  # surrogateescape byte
    ] * 50
    join = lambda k, vs: "|".join(vs)  # noqa: E731
    with ExternalReducer(memory_limit_bytes=1 << 10) as r:
        r.add_many(recs)
        assert r.spill_count > 0
        got = dict(r.reduce(join))
    assert got == group_reduce(recs, join)


# ------------------------------------------------------------- job level

def test_job_with_tiny_reduce_memory_identical_output(tmp_path, corpus):
    files = [str(p) for p in corpus.values()]

    def run(cap):
        cfg = JobConfig(
            input_files=files,
            application="distributed_grep_tpu.apps.wordcount",
            n_reduce=3,
            work_dir=str(tmp_path / f"wd-{cap}"),
            reduce_memory_bytes=cap,
        )
        return run_job(cfg, n_workers=2)

    small = run(1 << 10)  # a few records per spill
    big = run(256 << 20)
    assert small.results == big.results and small.results
    # identical bytes, not just dicts: outputs are sorted + deterministic
    small_bytes = b"".join(p.read_bytes() for p in small.output_files)
    big_bytes = b"".join(p.read_bytes() for p in big.output_files)
    assert small_bytes == big_bytes
    assert small.metrics["counters"].get("reduce_spills", 0) > 0
    assert big.metrics["counters"].get("reduce_spills", 0) == 0


def test_non_utf8_filename_survives_wire_format(tmp_path):
    """POSIX filenames need not be UTF-8; argv decoding maps raw bytes to
    lone surrogates, which embed in grep keys and must round-trip the
    shuffle + output wire formats (they used to crash encode_records)."""
    import os

    raw = os.fsencode(str(tmp_path)) + b"/bad-\xff-name.txt"
    with open(raw, "wb") as f:
        f.write(b"hello world\nnope\n")
    fname = os.fsdecode(raw)  # contains \udcff
    cfg = JobConfig(
        input_files=[fname],
        app_options={"pattern": "hello"},
        n_reduce=2,
        work_dir=str(tmp_path / "wd"),
    )
    res = run_job(cfg, n_workers=1)
    assert list(res.results.values()) == ["hello world"]
    (key,) = res.results.keys()
    assert key == f"{fname} (line number #1)"

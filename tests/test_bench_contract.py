"""Driver contract for bench.py: exactly ONE JSON line on stdout, with the
required keys, regardless of accelerator health.

The suite runs CPU-only, so this exercises the probe's deterministic
PROBE_CPU short-circuit and the native-scanner fallback — the path the
driver would record if it ran in a device-tunnel outage window.  The
healthy-accelerator path is validated on hardware (BASELINE.md receipts);
the probe/watchdog plumbing is identical either way.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

BENCH = Path(__file__).resolve().parent.parent / "bench.py"


def test_bench_emits_one_json_line_cpu_fallback():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONPATH", None)  # drop the axon sitecustomize (CLAUDE.md)
    env["BENCH_CORPUS_BYTES"] = "2000000"  # keep the fallback scan quick
    proc = subprocess.run(
        [sys.executable, str(BENCH)],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    rec = json.loads(lines[0])
    assert set(rec) == {"metric", "value", "unit", "vs_baseline"}
    assert rec["unit"] == "GB/s"
    assert rec["value"] > 0
    # vs_baseline is computed from the UNROUNDED value, so recomputing from
    # the rounded one can differ in the last digit — tolerance, not equality
    assert abs(rec["vs_baseline"] - rec["value"] / 10.0) < 2e-3
    assert "cpu_fallback" in rec["metric"]  # no accelerator in this env

"""Glushkov bit-parallel NFA model + Pallas kernel vs the DFA oracle.

The DFA compiler (models/dfa.py) shares the parser and Thompson
construction with the Glushkov compiler, so compile_dfa's reference_scan is
the semantic oracle: for every eligible pattern the two engines must agree
on exact match end-offsets, on adversarial texts (stripe boundaries,
anchors, matches at offset 0 / EOF, overlapping matches).
"""

from __future__ import annotations

import numpy as np
import pytest

from distributed_grep_tpu.models import dfa as dfa_mod
from distributed_grep_tpu.models import nfa as nfa_mod
from distributed_grep_tpu.ops import layout as layout_mod
from distributed_grep_tpu.ops import pallas_nfa, scan_jnp

from tests.test_ops import make_text  # shared corpus builder


PATTERNS = [
    "needle",
    "nee(dle|t)",
    "(cat|dog|bird)",
    "colou?r",
    "a[bc]*d",
    "(foo|bar)+baz",
    "x.y",
    "[0-9]{2,4}x",
    "^anchor",
    "^(GET|POST) /cgi",
    "wiki(pedia|media)?",
    "[a-f]{3}",
]

TEXT = (
    b"needle at start\n"
    b"the cat sat on the dog\n"
    b"colour and color and colr\n"
    b"abd abcd abccd abcbcd ad\n"
    b"foobaz barbaz foobarbaz bazfoo\n"
    b"x.y xay xzy x\ny\n"
    b"12x 123x 12345x 1x\n"
    b"anchor here\nnot ^anchor\n"
    b"GET /cgi-bin/query POST /cgi\n"
    b"wiki wikipedia wikimedia wikip\n"
    b"abcdef fade bead\n"
    b"neet needle neets\n"
) * 3


@pytest.mark.parametrize("pattern", PATTERNS)
def test_reference_scan_matches_dfa_oracle(pattern):
    model = nfa_mod.try_compile_glushkov(pattern)
    assert model is not None, pattern
    table = dfa_mod.compile_dfa(pattern)
    got = nfa_mod.scan_reference(model, TEXT)
    want = dfa_mod.reference_scan(table, TEXT)
    np.testing.assert_array_equal(got, want, err_msg=pattern)


@pytest.mark.parametrize("pattern", ["NeEdLe", "[A-F]{3}", "^GeT"])
def test_ignore_case(pattern):
    model = nfa_mod.try_compile_glushkov(pattern, ignore_case=True)
    assert model is not None
    table = dfa_mod.compile_dfa(pattern, ignore_case=True)
    np.testing.assert_array_equal(
        nfa_mod.scan_reference(model, TEXT),
        dfa_mod.reference_scan(table, TEXT),
    )


def test_ineligible_patterns():
    assert nfa_mod.try_compile_glushkov("foo$") is None  # '$' needs lookahead
    assert nfa_mod.try_compile_glushkov("a*") is None  # nullable
    assert nfa_mod.try_compile_glushkov("x|") is None  # nullable branch
    assert nfa_mod.try_compile_glushkov("a{1,200}") is None  # position blowup
    with pytest.raises(dfa_mod.RegexError):
        nfa_mod.try_compile_glushkov("(unbalanced")


def test_chain_specials_split():
    # Pure literal: every position but the last is a chain bit; no specials.
    m = nfa_mod.try_compile_glushkov("needle")
    assert m.n_specials == 0
    assert bin(m.chain_src[0]).count("1") == len("needle") - 1
    # Star introduces a back-edge special.
    m2 = nfa_mod.try_compile_glushkov("ab*c")
    assert m2.n_specials >= 1


def test_long_alternation_spans_two_words():
    words = ["volcano", "anarchy", "physics", "quantum", "needle", "breadth"]
    pattern = "(" + "|".join(words) + ")"
    model = nfa_mod.try_compile_glushkov(pattern)
    assert model is not None and model.n_words == 2
    table = dfa_mod.compile_dfa(pattern)
    data = make_text(4000, inject=[(100, b"a volcano erupts"), (2000, b"quantum needle")])
    np.testing.assert_array_equal(
        nfa_mod.scan_reference(model, data),
        dfa_mod.reference_scan(table, data),
    )


# ----------------------------------------------------------- pallas kernel

def _kernel_vs_dfa(pattern, data, ignore_case=False):
    model = nfa_mod.try_compile_glushkov(pattern, ignore_case=ignore_case)
    assert model is not None and pallas_nfa.eligible(model), pattern
    table = dfa_mod.compile_dfa(pattern, ignore_case=ignore_case)
    lay = layout_mod.choose_layout(
        len(data), target_lanes=4096, min_chunk=512,
        lane_multiple=4096, chunk_multiple=512,
    )
    arr = layout_mod.to_device_array(data, lay)
    got = pallas_nfa.nfa_scan(arr, model, interpret=True)
    want = np.asarray(scan_jnp.dfa_scan(arr, table))
    np.testing.assert_array_equal(got, want, err_msg=pattern)


@pytest.mark.parametrize(
    "pattern",
    ["nee(dle|t)", "colou?r", "a[bc]*d", "^anchor", "[0-9]{2,4}x", "(foo|bar)+baz"],
)
def test_pallas_nfa_interpret_matches_dfa_scan(pattern):
    data = make_text(
        3000,
        inject=[
            (5, b"needle neet colour anchor"),
            (1500, b"abccd 1234x foobarbaz"),
            (2900, b"neet at the end 99x"),
        ],
    )
    _kernel_vs_dfa(pattern, data)


def test_pallas_nfa_two_word_state_interpret():
    words = ["volcano", "anarchy", "physics", "quantum", "needle", "breadth"]
    pattern = "(" + "|".join(words) + ")"
    data = make_text(3000, inject=[(40, b"volcano"), (2000, b"breadth quantum")])
    _kernel_vs_dfa(pattern, data)


def test_pallas_nfa_anchor_at_stripe_boundary():
    # '^foo' where a stripe starts mid-line: the kernel treats stripe start
    # as line start (the host stitcher re-checks those lines); the DFA scan
    # does the same (state 0 at stripe start), so packed bits still agree.
    data = make_text(3000, inject=[(0, b"anchor first"), (1700, b"anchor mid")])
    _kernel_vs_dfa("^anchor", data)


def test_pallas_nfa_ignore_case_interpret():
    data = make_text(2000, inject=[(10, b"NEEDLE NeEt"), (1200, b"needle")])
    _kernel_vs_dfa("nee(dle|t)", data, ignore_case=True)


def test_kernel_cost_and_eligibility():
    m = nfa_mod.try_compile_glushkov("nee(dle|t)")
    assert pallas_nfa.kernel_cost(m) < pallas_nfa.MAX_COST
    # 60 positions with 60 distinct 2-range classes used to blow the
    # per-byte compare budget; the gather-B path (fixed cost per state
    # word) keeps it on the Pallas kernel now.
    import string

    chars = string.ascii_letters + "!#%&,;:@"
    big = nfa_mod.try_compile_glushkov("".join(f"[{c}0-9]" for c in chars[:60]))
    assert big is not None
    assert pallas_nfa.use_gather_b(big) and pallas_nfa.eligible(big)
    assert pallas_nfa._b_cost_gather(big) < pallas_nfa._b_cost_compare(big)


def test_gather_b_mode_picked_and_exact():
    # alternations have many classes -> the gather-B path should win, and
    # its interpret-mode output must stay byte-identical to the DFA scan
    words = ["volcano", "anarchy", "physics", "quantum", "needle", "breadth",
             "zeppelin", "obsidian"]
    pattern = "(" + "|".join(words) + ")"
    model = nfa_mod.try_compile_glushkov(pattern)
    assert pallas_nfa.use_gather_b(model)
    data = make_text(2500, inject=[(10, b"zeppelin obsidian"), (2400, b"quantum")])
    _kernel_vs_dfa(pattern, data)


def test_compare_b_mode_for_small_patterns():
    model = nfa_mod.try_compile_glushkov("colou?r")
    assert not pallas_nfa.use_gather_b(model)


def test_wide_pattern_four_word_state():
    # ~100 Glushkov positions -> 4 uint32 state words; interpret-mode kernel
    # must agree with the DFA oracle byte-for-byte
    words = ["volcano", "anarchism", "philosophy", "wikipedia", "quantum",
             "zeppelin", "obsidian", "telescope", "metabolic", "hurricane",
             "labyrinth", "xylophone"]
    pattern = "(" + "|".join(words) + ")"
    model = nfa_mod.try_compile_glushkov(pattern)
    assert model is not None and model.n_words == 4, model and model.n_pos
    assert pallas_nfa.eligible(model)
    data = make_text(2000, inject=[(3, b"a labyrinth of xylophones"),
                                   (1000, b"metabolic hurricane"),
                                   (1999, b"telescope")])
    _kernel_vs_dfa(pattern, data)


def test_wide_pattern_bounded_repeat():
    # 92 positions compile now (>64); the ~50 optional-tail specials put it
    # over the kernel budget (XLA DFA path), but the model itself must be
    # exact — bit-parallel reference vs the DFA oracle
    model = nfa_mod.try_compile_glushkov("a[bc]{40,90}d")
    assert model is not None and model.n_pos > 64
    assert not pallas_nfa.eligible(model)  # specials-heavy -> XLA path
    table = dfa_mod.compile_dfa("a[bc]{40,90}d")
    data = make_text(500, inject=[(7, b"a" + b"bc" * 30 + b"d"),
                                  (400, b"a" + b"c" * 95 + b"d")])
    np.testing.assert_array_equal(
        nfa_mod.scan_reference(model, data), dfa_mod.reference_scan(table, data)
    )


# ------------------------------- bounded-repeat filter relaxation (round 3)

def test_compile_scan_model_relaxes_config4_shape():
    """The config-4 pattern (33 positions = 2 words exact) must compile to
    a 1-word filter; exact patterns without bounded repeats stay exact."""
    pat = r"get /[a-z0-9/.-]{4,24}\.gif"
    exact = nfa_mod.try_compile_glushkov(pat, ignore_case=True)
    assert exact is not None and exact.n_words == 2
    model, is_filter = nfa_mod.compile_scan_model(pat, ignore_case=True)
    assert is_filter and model.n_words == 1

    model2, f2 = nfa_mod.compile_scan_model("ne+dle")
    assert not f2  # no bounded repeat: exact model
    assert model2 is not None


def test_compile_scan_model_keeps_exact_when_no_word_saving():
    """{m,n} whose relaxation saves no state word keeps the exact model
    (no pointless confirm pass)."""
    model, is_filter = nfa_mod.compile_scan_model("a{1,3}b")
    assert model is not None and not is_filter


def test_filter_is_superset_of_exact():
    """Every exact match offset must appear in the filter's offsets."""
    pat = r"x[ab]{2,40}y"
    exact = nfa_mod.try_compile_glushkov(pat)
    model, is_filter = nfa_mod.compile_scan_model(pat)
    assert is_filter
    data = make_text(
        300,
        inject=[
            (5, b"x" + b"ab" * 3 + b"y"),
            (100, b"x" + b"a" * 60 + b"y end"),  # over the bound: filter-only
            (200, b"xaby xy xab"),
        ],
    )
    ex = set(nfa_mod.scan_reference(exact, data).tolist())
    fi = set(nfa_mod.scan_reference(model, data).tolist())
    assert ex <= fi
    assert len(fi) > len(ex)  # the over-bound line is a false candidate


def test_filter_rescues_over_cap_repeat():
    """Bounded repeat whose exact expansion exceeds MAX_POSITIONS: exact
    compile fails, the filter fits — NFA path instead of the DFA cliff."""
    pat = r"q[ab]{10,200}z"
    assert nfa_mod.try_compile_glushkov(pat) is None
    model, is_filter = nfa_mod.compile_scan_model(pat)
    assert is_filter and model is not None and model.n_words == 1


def test_engine_filter_path_exact():
    """Engine end-to-end with the filter model: false candidates must be
    rejected by the host confirm on both the interpret-Pallas and the XLA
    fallback paths."""
    import re

    from distributed_grep_tpu.ops.engine import GrepEngine

    pat = r"get /[a-z0-9/.-]{4,24}\.gif"
    rx = re.compile(pat.encode(), re.I)
    data = make_text(
        600,
        inject=[
            (3, b'GET /images/logo.gif HTTP/1.0'),
            (90, b'GET /' + b'a/' * 30 + b'x.gif over-bound'),  # false cand
            (300, b'get /ab.gif too-short'),                    # false cand
            (450, b'GET /pix/a1-b.gif ok'),
        ],
    )
    expected = {
        i for i, ln in enumerate(data.split(b"\n")[:-1], 1) if rx.search(ln)
    }
    eng = GrepEngine(pat, ignore_case=True, interpret=True)
    assert eng.mode == "nfa" and eng._nfa_filter
    assert set(eng.scan(data).matched_lines.tolist()) == expected
    eng2 = GrepEngine(pat, ignore_case=True)  # XLA DFA-bank fallback
    assert set(eng2.scan(data).matched_lines.tolist()) == expected


def test_filter_defeat_swaps_to_exact_automaton():
    """A corpus that defeats the relaxed filter's selectivity (every line a
    false candidate) must flip the scan to the exact automaton after the
    first dense segment and still return the exact result."""
    from distributed_grep_tpu.ops.engine import GrepEngine

    pat = r"x[ab]{2,40}y"
    # >SPAN_CONFIRM_LINE_LIMIT lines, all matching the relaxed x[ab]{2,}y
    # but not the exact pattern (runs of 60 'a's)
    bad = b"x" + b"a" * 60 + b"y"
    lines = [bad] * 6000 + [b"x" + b"ab" * 5 + b"y real match"]
    data = b"\n".join(lines) + b"\n"
    eng = GrepEngine(pat, interpret=True, segment_bytes=1 << 20)
    assert eng.mode == "nfa" and eng._nfa_filter
    assert eng.glushkov_exact is not None and eng.glushkov_exact.n_words == 2
    res = eng.scan(data)
    assert set(res.matched_lines.tolist()) == {6001}
    assert eng.stats.get("nfa_filter_defeated") is True
    assert eng.stats.get("candidates", 0) > 4096
    # a fresh scan of a friendly corpus uses the filter again (scan-local)
    good = b"\n".join([b"no match here"] * 50 + [b"xababy hit"]) + b"\n"
    res2 = eng.scan(good)
    assert set(res2.matched_lines.tolist()) == {51}
    assert "nfa_filter_defeated" not in eng.stats


def test_expansion_cap_repeat_rescued_to_device_filter():
    """{m,n} past the DFA expansion cap (512) used to fall to the host re
    loop on --backend device; the relaxed Glushkov filter now runs it on
    the device with re-confirmed candidate lines (round 3)."""
    import re

    from distributed_grep_tpu.ops.engine import GrepEngine

    pat = r"q[ab]{10,900}z"
    rx = re.compile(pat.encode())
    data = make_text(
        400,
        inject=[
            (5, b"q" + b"ab" * 30 + b"z hit"),
            (100, b"q" + b"a" * 950 + b"z over-bound"),  # false candidate
            (300, b"qabz too-short"),
        ],
    )
    want = {i for i, l in enumerate(data.split(b"\n")[:-1], 1) if rx.search(l)}
    eng = GrepEngine(pat, interpret=True)
    assert eng.mode == "nfa" and eng._nfa_filter and not eng.tables
    assert set(eng.scan(data).matched_lines.tolist()) == want
    # no Pallas -> per-line re loop, still exact
    eng2 = GrepEngine(pat)
    assert set(eng2.scan(data).matched_lines.tolist()) == want


# ------------------------------------------- round-5: '$' / over-cap filters

def test_compile_device_filter_drops_end_anchor():
    """'$' accepts have no exact Glushkov form; the device filter drops
    the anchor (language superset at the same end offsets) so everyday
    patterns like 'error$' reach the Pallas path."""
    for pat in ("error$", "abc$|def$", "^end$", "a*b$"):
        assert nfa_mod.try_compile_glushkov(pat) is None, pat
        m = nfa_mod.compile_device_filter(pat)
        assert m is not None, pat
    # no usable filter: nullable bodies (engine short-circuits these)
    for pat in ("x*$", "^$", "(ab)*$"):
        assert nfa_mod.compile_device_filter(pat) is None, pat


def test_compile_device_filter_prefix_truncates_over_cap():
    """>MAX_POSITIONS bodies truncate to a <=32-position required prefix
    (1 state word — the fastest kernel shape)."""
    for pat in ("A" * 200, "x{200}", "[0-9]{150}"):
        assert nfa_mod.try_compile_glushkov(pat) is None, pat
        m = nfa_mod.compile_device_filter(pat)
        assert m is not None and m.n_pos <= 32 and m.n_words == 1, pat
    # optional parts are never partially included: x*y{200} must keep a
    # REQUIRED prefix (y's), not the optional x-run
    m = nfa_mod.compile_device_filter("x*y{200}")
    assert m is not None
    data = b"yyy " + b"y" * 220 + b"\n" + b"x" * 40 + b"\n"
    offs = nfa_mod.scan_reference(m, data)
    nl = data.index(b"\n")
    assert offs.size and offs.max() <= nl + 1  # no hits on the x-only line


def test_device_filter_is_line_superset_of_dfa_oracle():
    """Candidate lines from the filter must cover every exact match line
    (the cand_words confirm contract)."""
    cases = [
        ("error$", [(3, b"an error"), (9, b"error in middle"), (20, b"error")]),
        ("A" * 60, [(5, b"A" * 70), (12, b"A" * 30)]),
        ("[ab]{4,200}c$", [(7, b"abab" * 30 + b"c"), (15, b"ababc x")]),
    ]
    for pat, inject in cases:
        table = dfa_mod.compile_dfa(pat)
        m = nfa_mod.compile_device_filter(pat)
        assert m is not None, pat
        data = make_text(60, inject=inject)
        nl = np.flatnonzero(np.frombuffer(data, np.uint8) == 10)

        def lines_of(offs):
            o = np.asarray(offs, np.int64)
            return set((np.searchsorted(nl, o - 1, side="left") + 1).tolist())

        exact = lines_of(dfa_mod.reference_scan(table, data))
        cand = lines_of(nfa_mod.scan_reference(m, data))
        assert exact <= cand, pat


def test_engine_dollar_anchor_device_path_exact():
    """'error$'-class patterns ride the device NFA filter (round-5: they
    used to route to the native host scanner even on backend=device) and
    the host confirm restores exact '$' semantics."""
    import re

    from distributed_grep_tpu.ops.engine import GrepEngine

    pat = "error$"
    data = make_text(
        500,
        inject=[
            (2, b"an error"),
            (40, b"error in middle not end"),
            (41, b"error"),
            (499, b"tail error"),  # '$' at EOF (no trailing newline context)
        ],
    )
    want = {
        i for i, l in enumerate(data.split(b"\n")[:-1], 1)
        if re.search(rb"error$", l)
    }
    eng = GrepEngine(pat, backend="device", interpret=True)
    assert eng.mode == "nfa" and eng._nfa_filter
    assert eng.glushkov is not None and eng.glushkov_exact is None
    assert set(eng.scan(data).matched_lines.tolist()) == want
    assert eng.stats.get("candidates", 0) >= len(want)


def test_engine_dollar_anchor_dense_confirm_eol():
    """Candidate-dense '$' corpus takes dense_native_confirm, whose
    accept_eol leg (round-5) must not under-report: every line ending in
    the pattern matches, mid-line occurrences do not."""
    from distributed_grep_tpu.ops.engine import GrepEngine

    lines = []
    for i in range(9000):
        if i % 3 == 0:
            lines.append(b"x" * (i % 7) + b" error")
        elif i % 3 == 1:
            lines.append(b"error not at end")
        else:
            lines.append(b"clean")
    data = b"\n".join(lines) + b"\n"
    want = {i + 1 for i in range(9000) if i % 3 == 0}
    eng = GrepEngine("error$", backend="device", interpret=True)
    eng._accel_cached = True
    res = eng.scan(data)
    assert set(res.matched_lines.tolist()) == want
    assert eng.stats.get("candidates", 0) > 4096  # dense path exercised


def test_engine_over_cap_literal_device_path_exact():
    """A >128-char literal (no exact kernel form) scans via the truncated
    prefix filter; confirm rejects lines holding only the prefix."""
    from distributed_grep_tpu.ops.engine import GrepEngine

    lit = bytes(range(65, 91)) * 6  # 156-byte literal A..Z repeated
    pat = lit.decode()
    data = make_text(
        300,
        inject=[(10, lit + b" full hit"), (100, lit[:40] + b" prefix only")],
    )
    eng = GrepEngine(pat, backend="device", interpret=True)
    assert eng.mode == "nfa" and eng._nfa_filter
    assert set(eng.scan(data).matched_lines.tolist()) == {11}


def test_engine_dollar_anchor_mesh_path_exact():
    """The sharded NFA kernel hosts the '$' filter too (mesh engines used
    to stay on the XLA DFA path for these patterns)."""
    import jax
    from jax.sharding import Mesh

    from distributed_grep_tpu.ops.engine import GrepEngine

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("data",))
    data = make_text(400, inject=[(5, b"an error"), (9, b"error mid line")])
    import re

    want = {
        i for i, l in enumerate(data.split(b"\n")[:-1], 1)
        if re.search(rb"error$", l)
    }
    eng = GrepEngine("error$", backend="device", interpret=True, mesh=mesh)
    assert eng.mode == "nfa" and eng._nfa_filter
    assert set(eng.scan(data).matched_lines.tolist()) == want


def test_reference_scan_eol_vectorized_matches_oracle():
    """reference_scan's '$' leg (round-5: second native pass + next-byte
    mask, replacing the per-byte Python walk) vs a re-derived oracle."""
    import re

    for pat, rx in [("error$", rb"error$"), ("[ab]+c$", rb"[ab]+c$")]:
        table = dfa_mod.compile_dfa(pat)
        data = make_text(
            200,
            inject=[
                (0, b"error"),
                (50, b"abc"),
                (51, b"error trailing"),
                (199, b"aac"),
            ],
        )
        got = dfa_mod.reference_scan(table, data)
        want = sorted(
            m.end() for m in re.finditer(rx, data, re.M)
        )
        assert sorted(int(o) for o in got) == want, pat


def test_mid_pattern_anchors_exact_in_dfa():
    """Round 5: mid-pattern '^'/'$' anchors compile into the subset DFA
    via position-gated epsilons (models/dfa ls_eps/eol_eps) — exactly
    the newline-reset scan's semantics — instead of raising into the
    Python-re fallback.  Checked per line vs the re oracle."""
    import re as _re

    data = (b"ac here\nxac\nbc mid\nzbc\nac\nempty\n\nfoo then\nfoo\n"
            b"bar foo\nABCD\nxABCD\n")
    cases = [
        r"(^a|b)c", r"a^b", r"x$y", r"foo(bar$|o)?", r"(foo$|bar)",
        r"a(^|x)c", r"(^|f)oo", r"(^ac|bc$)", r"(a$|b)c", r"(^AB|BC)D",
    ]
    nl = np.flatnonzero(np.frombuffer(data, np.uint8) == 10)
    for pat in cases:
        table = dfa_mod.compile_dfa(pat)
        offs = np.asarray(dfa_mod.reference_scan(table, data), np.int64)
        got = set((np.searchsorted(nl, offs - 1, side="left") + 1).tolist())
        want = {
            i for i, ln in enumerate(data.split(b"\n")[:-1], 1)
            if _re.search(pat.encode(), ln)
        }
        assert got == want, f"{pat!r}: +{got - want} -{want - got}"


def test_mid_pattern_anchors_glushkov_rejects_filter_strips():
    """The bit-parallel Glushkov automaton has no position-gated epsilon,
    so exact compiles of mid-anchor bodies must return None (a silent
    compile would UNDER-approximate — fatal for a filter); the device
    filter path strips the anchors instead (superset) and the candidate
    lines cover every exact match line."""
    for pat in (r"(^a|b)c", r"a(b$|c)d", r"(^ab|cd$)"):
        assert nfa_mod.try_compile_glushkov(pat) is None, pat
        m = nfa_mod.compile_device_filter(pat)
        assert m is not None, pat

    table = dfa_mod.compile_dfa(r"(^ac|bc$)")
    filt = nfa_mod.compile_device_filter(r"(^ac|bc$)")
    data = make_text(80, inject=[(3, b"ac lead"), (9, b"tail bc"),
                                 (14, b"xacx mid decoy"), (21, b"bcx")])
    nl = np.flatnonzero(np.frombuffer(data, np.uint8) == 10)

    def lines_of(offs):
        o = np.asarray(offs, np.int64)
        return set((np.searchsorted(nl, o - 1, side="left") + 1).tolist())

    exact = lines_of(dfa_mod.reference_scan(table, data))
    cand = lines_of(nfa_mod.scan_reference(filt, data))
    assert exact <= cand
    assert exact  # the injections really produced anchored matches


def test_mixed_anchor_chains_match_empty_lines():
    """'$^'-ordered chains hold on EMPTY lines (the position is a line
    start AND an end-of-line simultaneously) — models/dfa marks
    accept_eol on the start state via a mixed non-consuming walk, and
    reference_scan injects the position-0 zero-width accept the native
    byte-walk cannot report (plus drops the trailing-'\\n' phantom).
    Pinned engine-level and oracle-level (round-5 review finding)."""
    import re as _re

    from distributed_grep_tpu.ops.engine import GrepEngine

    datasets = [b"\nab\n\nx\n", b"ab\n\n", b"\n", b"ab\nx\n", b"ab"]
    for pat in (r"$^", r"$(^|b)", r"(a|^)(b|$)", r"^$"):
        for data in datasets:
            want = {i for i, ln in enumerate(data.split(b"\n")[: -1 if data.endswith(b"\n") else None], 1)
                    if _re.search(pat.encode(), ln)}
            got_oracle = dfa_mod.matched_lines(dfa_mod.compile_dfa(pat), data)
            assert got_oracle == want, (
                f"oracle {pat!r} on {data!r}: got {got_oracle} want {want}"
            )
            eng = GrepEngine(pat, backend="cpu")
            got = set(eng.scan(data).matched_lines.tolist())
            assert got == want, (
                f"engine {pat!r} on {data!r} mode={eng.mode}: "
                f"got {got} want {want}"
            )


def test_word_boundary_device_filter_strip_confirm():
    """Round 5: \\b/\\B parse into Anchor nodes; no exact automaton form
    exists (the accept planes carry no next-byte wordness), so the
    device rescue strips them into a filter (superset, same end offsets)
    and re-confirms candidate lines — '\\berror\\b' scans as 'error' on
    the Pallas NFA kernel.  Exact vs the re oracle on both backends."""
    import re as _re

    from distributed_grep_tpu.models.dfa import RegexError
    from distributed_grep_tpu.ops.engine import GrepEngine

    # no exact compile anywhere; the filter strips and compiles
    for pat in (r"\berror\b", r"wordy\B", r"\b[ew]or\w+\b"):
        with pytest.raises(RegexError):
            dfa_mod.compile_dfa(pat)
        assert nfa_mod.try_compile_glushkov(pat) is None, pat
        assert nfa_mod.compile_device_filter(pat) is not None, pat
    # [\b] stays backspace (a Char), like re
    assert isinstance(dfa_mod._Parser(r"[\b]", False).parse(), dfa_mod.Char)

    data = (b"error here\nxerrors\nsuberror\nan error\nerror\nb2c ok\n"
            b"b2cx\nword boundary\nwordy\n" * 40)
    for pat in (r"\berror\b", r"\bword", r"wordy\B", r"\Berror",
                r"b2c\b", r"\b[ew]or\w+\b"):
        want = [i for i, ln in enumerate(data.split(b"\n")[:-1], 1)
                if _re.search(pat.encode(), ln)]
        for kw in (dict(backend="cpu"), dict(interpret=True)):
            eng = GrepEngine(pat, **kw)
            eng._accel_cached = True
            got = eng.scan(data).matched_lines.tolist()
            assert got == want, (
                f"{pat!r} {kw} mode={eng.mode}: "
                f"{sorted(set(got) ^ set(want))[:5]}"
            )
        assert GrepEngine(pat, interpret=True).mode == "nfa", pat


def test_string_anchors_map_to_line_anchors():
    """\\A and \\Z are exact synonyms of '^'/'$' under per-line matching
    (a line-string contains no newline), so they compile into the
    automaton subset instead of deferring to re; \\z stays deferred
    (Python re rejects it — no oracle to agree with)."""
    import re as _re

    from distributed_grep_tpu.ops.engine import GrepEngine

    data = b"foo bar\nxfoo\nbarfoo\nfoo\nmid foo end\n" * 20
    for pat in (r"\Afoo", r"foo\Z", r"(\Afoo|bar\Z)"):
        want = [i for i, ln in enumerate(data.split(b"\n")[:-1], 1)
                if _re.search(pat.encode(), ln)]
        for kw in (dict(backend="cpu"), dict(interpret=True)):
            eng = GrepEngine(pat, **kw)
            eng._accel_cached = True
            assert eng.mode != "re", (pat, kw)
            got = eng.scan(data).matched_lines.tolist()
            assert got == want, (pat, kw, eng.mode)
    # \z defers to the re fallback, which rejects it — the same invalid-
    # pattern error a user gets from re.compile (CLI: exit 2)
    with pytest.raises(_re.error):
        GrepEngine(r"foo\z", backend="cpu")

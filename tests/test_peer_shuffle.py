"""Peer-to-peer shuffle tier (round 16, runtime/peer.py): reducers fetch
map output directly from the worker that produced it; the daemon moves
shuffle METADATA only.

Covers ISSUE 14's acceptance bars:

* a 2-worker HTTP service job with peer shuffle on completes
  byte-identical to the relay path while the daemon's measured shuffle
  data-plane bytes stay at ZERO (counter-proven);
* with peer shuffle off every wire payload keeps its pre-peer shape
  (the DGREP_SERVICE_FUSE=0 byte-identical contract);
* lost peer output (producer gone / checksum mismatch) re-enqueues the
  producing MAP task — the new COMPLETED -> UNASSIGNED transition —
  with quarantine attribution to the vanished producer and journal
  entries unique per (kind, task);
* the declared relay fallback: a dead peer endpoint with a daemon-held
  copy serves through the relay, no re-execution;
* the elastic scale signal (/status "scale") and the drainable local
  pool (scale_local_pool).

Standalone: ``python -m pytest tests/test_peer_shuffle.py -q`` (marker
``service`` — the daemon runtime suite).  CPU-only.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path

import pytest

from distributed_grep_tpu.runtime import rpc
from distributed_grep_tpu.runtime.explain import summarize_events
from distributed_grep_tpu.runtime.http_transport import (
    ServiceHttpTransport,
    client_call,
)
from distributed_grep_tpu.runtime.journal import TaskJournal
from distributed_grep_tpu.runtime.peer import (
    PeerDataServer,
    checksum,
    env_peer_bind,
    env_peer_host,
    env_peer_port,
    env_peer_shuffle,
)
from distributed_grep_tpu.runtime.scheduler import Scheduler, WorkerHealth
from distributed_grep_tpu.runtime.service import GrepService, ServiceServer
from distributed_grep_tpu.runtime.types import TaskState
from distributed_grep_tpu.runtime.worker import WorkerLoop
from distributed_grep_tpu.utils.config import JobConfig

pytestmark = pytest.mark.service


@pytest.fixture(autouse=True)
def _no_calibrate(monkeypatch):
    monkeypatch.setenv("DGREP_NO_CALIBRATE", "1")


def outputs_by_name(paths) -> dict[str, bytes]:
    out = {}
    for p in paths:
        name = Path(p).name.split(".part.")[0]
        out[name] = Path(p).read_bytes()
    return out


def grep_config(corpus, pattern="hello", **kw) -> JobConfig:
    defaults = dict(
        input_files=[str(p) for p in corpus.values()],
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": pattern, "backend": "cpu"},
        n_reduce=2,
        work_dir="ignored",  # the service overrides its copy
    )
    defaults.update(kw)
    return JobConfig(**defaults)


# ------------------------------------------------------------- peer server

def test_peer_server_put_get_and_checksum(tmp_path):
    srv = PeerDataServer().start()
    try:
        size, crc = srv.put("job-1", "mr-0-1", b"hello shuffle\n")
        assert size == len(b"hello shuffle\n")
        assert crc == checksum(b"hello shuffle\n")
        assert srv.get_local("job-1", "mr-0-1") == b"hello shuffle\n"
        assert srv.spool_bytes() == size
        # overwrite (duplicate attempt) keeps accounting exact
        srv.put("job-1", "mr-0-1", b"shorter\n")
        assert srv.spool_bytes() == len(b"shorter\n")
        # the HTTP surface serves the spool
        from distributed_grep_tpu.runtime.http_transport import fetch_peer_data

        assert fetch_peer_data(srv.endpoint, "job-1", "mr-0-1") == b"shorter\n"
        with pytest.raises(RuntimeError):  # 404: honest absence, never a hang
            fetch_peer_data(srv.endpoint, "job-1", "mr-9-9")
    finally:
        srv.close()


def test_peer_server_rejects_traversal(tmp_path):
    srv = PeerDataServer()
    try:
        with pytest.raises(ValueError):
            srv.spool_path("../evil", "mr-0-0")
        with pytest.raises(ValueError):
            srv.spool_path("job-1", ".hidden")
    finally:
        srv.close()


def test_env_knob_accessors(monkeypatch):
    assert env_peer_shuffle() is True
    for off in ("0", "false", "no"):
        monkeypatch.setenv("DGREP_PEER_SHUFFLE", off)
        assert env_peer_shuffle() is False
    monkeypatch.setenv("DGREP_PEER_SHUFFLE", "1")
    assert env_peer_shuffle() is True
    monkeypatch.setenv("DGREP_PEER_PORT", "8125")
    assert env_peer_port() == 8125
    monkeypatch.setenv("DGREP_PEER_PORT", "bogus")
    assert env_peer_port() == 0
    monkeypatch.setenv("DGREP_PEER_PORT", "-1")
    assert env_peer_port() == 0
    monkeypatch.setenv("DGREP_PEER_HOST", "10.0.0.7")
    assert env_peer_host() == "10.0.0.7"


def test_bind_knob_cascade(monkeypatch):
    """Default bind is loopback; an advertised routable name implies the
    wildcard bind (a loopback-bound server can never honor it); an
    explicit DGREP_PEER_BIND wins over both."""
    assert env_peer_bind() == "127.0.0.1"
    monkeypatch.setenv("DGREP_PEER_HOST", "worker-7.cluster")
    assert env_peer_bind() == "0.0.0.0"
    monkeypatch.setenv("DGREP_PEER_BIND", "10.0.0.7")
    assert env_peer_bind() == "10.0.0.7"


def test_server_binds_wildcard_and_advertises_routable_host(monkeypatch):
    """Cross-host deployment shape: DGREP_PEER_HOST makes the server
    LISTEN on the wildcard while ADVERTISING the routable name — peers
    on other hosts can actually connect to what the endpoint says."""
    monkeypatch.setenv("DGREP_PEER_HOST", "127.0.0.1")  # routable-for-test
    srv = PeerDataServer().start()
    try:
        assert srv._httpd.server_address[0] == "0.0.0.0"
        assert srv.endpoint == f"http://127.0.0.1:{srv.port}"
        srv.put("j", "mr-0-0", b"cross-host\n")
        from distributed_grep_tpu.runtime.http_transport import (
            fetch_peer_data,
        )

        assert fetch_peer_data(srv.endpoint, "j", "mr-0-0") == b"cross-host\n"
    finally:
        srv.close()
    # explicit wildcard bind with NO advertise override never
    # advertises the undialable 0.0.0.0
    monkeypatch.delenv("DGREP_PEER_HOST")
    monkeypatch.setenv("DGREP_PEER_BIND", "0.0.0.0")
    srv = PeerDataServer()
    try:
        assert "0.0.0.0" not in srv.endpoint
    finally:
        srv.close()


# ------------------------------------------------------- wire no-op pins

def test_wire_shapes_unchanged_when_off():
    """With peer shuffle off (no peer server, no metadata) every payload
    keeps the exact pre-peer key set — the DGREP_SERVICE_FUSE=0
    byte-identical contract, applied to the new riders."""
    assert rpc.to_dict(rpc.AssignTaskArgs(worker_id=3)) == {"worker_id": 3}
    fin = rpc.to_dict(rpc.TaskFinishedArgs(task_id=1, produced_parts=[0]))
    assert set(fin) == {"task_id", "produced_parts"}
    nxt = rpc.to_dict(rpc.ReduceNextFileArgs(task_id=0, files_processed=2))
    assert set(nxt) == {"task_id", "files_processed"}
    reply = rpc.reply_to_dict(rpc.ReduceNextFileReply(next_file="mr-0-0"))
    # abort joined _REPLY_ELIDE (its docstring always promised "elided
    # when False"); the peer riders stay elided at their defaults too
    assert set(reply) == {"next_file", "done"}
    # ... and the peer riders DO travel when set
    assert rpc.to_dict(
        rpc.AssignTaskArgs(worker_id=3, peer_endpoint="http://h:1")
    )["peer_endpoint"] == "http://h:1"
    r2 = rpc.reply_to_dict(rpc.ReduceNextFileReply(
        next_file="mr-0-0", peer_endpoint="http://h:1", peer_size=4,
        peer_checksum="aa"))
    assert r2["peer_endpoint"] == "http://h:1"
    assert r2["peer_size"] == 4 and r2["peer_checksum"] == "aa"


def test_status_advertises_peer_capability(tmp_path, monkeypatch):
    """Workers gate their peer data plane on the daemon's /status "peer"
    key (run_http_worker): with the knob default-ON, a new worker
    attached to a PRE-peer daemon must not send the unknown
    AssignTaskArgs.peer_endpoint key — cls(**payload) there would
    TypeError on every poll.  Off keeps the pre-peer /status shape."""
    svc = GrepService(work_root=tmp_path / "svc", resume=False)
    try:
        assert svc.status()["peer"] is True
        monkeypatch.setenv("DGREP_PEER_SHUFFLE", "0")
        assert "peer" not in svc.status()
    finally:
        svc.stop()


# --------------------------------------------- service e2e: bytes receipt

def _spin_service(tmp_path, corpus, peer_on: bool, n_workers: int = 2):
    svc = GrepService(work_root=tmp_path / f"svc-{peer_on}", resume=False,
                      task_timeout_s=10.0, sweep_interval_s=0.2)
    server = ServiceServer(svc)
    server.start()
    addr = f"127.0.0.1:{server.port}"
    peers, loops, threads = [], [], []
    for _ in range(n_workers):
        peer = PeerDataServer().start() if peer_on else None
        peers.append(peer)
        loop = WorkerLoop(
            ServiceHttpTransport(addr, rpc_timeout_s=10.0), app=None,
            peer=peer,
        )
        loops.append(loop)
        t = threading.Thread(target=loop.run, daemon=True)
        t.start()
        threads.append(t)
    return svc, server, addr, peers, loops, threads


def _submit_and_wait(addr, cfg, timeout=60.0) -> dict:
    jid = client_call(addr, "POST", "/jobs", cfg.to_json().encode(),
                      timeout=10.0)["job_id"]
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = client_call(addr, "GET", f"/jobs/{jid}", timeout=10.0)
        if st["state"] in ("done", "failed", "cancelled"):
            assert st["state"] == "done", st
            return client_call(addr, "GET", f"/jobs/{jid}/result",
                               timeout=10.0)
        time.sleep(0.05)
    raise AssertionError("job did not finish")


def test_peer_job_byte_identical_with_daemon_bytes_zero(tmp_path, corpus):
    """THE acceptance receipt: peer and relay runs produce byte-identical
    outputs, and with peer shuffle on the daemon's shuffle data plane
    moves ZERO bytes (metadata only)."""
    results = {}
    for peer_on in (True, False):
        svc, server, addr, peers, loops, _threads = _spin_service(
            tmp_path, corpus, peer_on
        )
        try:
            res = _submit_and_wait(addr, grep_config(corpus))
            status = client_call(addr, "GET", "/status", timeout=10.0)
            results[peer_on] = (
                outputs_by_name(res["outputs"]),
                dict(svc._shuffle_stats),
                sum(lp.metrics.counters.get("peer_fetches", 0)
                    for lp in loops),
                status,
            )
        finally:
            svc.stop()
            server.shutdown()
            for p in peers:
                if p is not None:
                    p.close()
    outs_p, stats_p, fetches_p, status_p = results[True]
    outs_r, stats_r, fetches_r, status_r = results[False]
    assert outs_p == outs_r and outs_p  # byte-identical, non-trivial
    assert stats_p["daemon_shuffle_bytes"] == 0  # the P2P receipt
    assert fetches_p > 0
    assert stats_r["daemon_shuffle_bytes"] > 0 and fetches_r == 0
    # /status surfaces the counters (nonzero-only) + worker endpoints
    assert "shuffle" not in status_p  # all-zero: pre-peer shape kept
    assert status_r["shuffle"]["daemon_shuffle_bytes"] > 0
    endpoints = [row.get("data_endpoint")
                 for row in status_p["workers"].values()]
    assert all(e and e.startswith("http://") for e in endpoints)
    assert [r.get("data_endpoint")
            for r in status_r["workers"].values()] == [None, None]


# ------------------------------------------- lost output -> re-execution

def test_scheduler_lost_output_reexecutes_map(tmp_path):
    """The new MapTask transition: a lost-output report moves a COMPLETED
    peer-held map task back to UNASSIGNED (journal entry NOT duplicated),
    charges the vanished producer, gates the reducer's cursor on the
    re-execution, and serves the fresh attempt's metadata afterward."""
    files = [tmp_path / "a.txt", tmp_path / "b.txt"]
    for f in files:
        f.write_text("hello\n")
    journal = TaskJournal(tmp_path / "journal.jsonl")
    health = WorkerHealth(base_s=30.0)
    sched = Scheduler(files=[str(f) for f in files], n_reduce=1,
                      task_timeout_s=30.0, sweep_interval_s=5.0,
                      journal=journal, worker_health=health)
    try:
        # producer (worker 0) completes both maps with peer metadata
        for tid in range(2):
            a = sched.assign_task(rpc.AssignTaskArgs(worker_id=0),
                                  timeout=1.0)
            assert a.assignment == rpc.Assignment.MAP
            fin = rpc.TaskFinishedArgs(
                task_id=a.task_id, worker_id=0, produced_parts=[0],
                peer_endpoint="http://127.0.0.1:1",  # nothing listens here
                peer_parts={"0": [6, checksum(b"hello\n")]},
            )
            sched.map_finished(fin)
        # the reducer is served peer metadata
        r = sched.reduce_next_file(
            rpc.ReduceNextFileArgs(task_id=0, files_processed=0,
                                   epoch=sched.epoch, worker_id=1),
            timeout=0.2,
        )
        assert r.next_file == "mr-0-0"
        assert r.peer_endpoint == "http://127.0.0.1:1"
        assert r.peer_size == 6 and r.peer_checksum == checksum(b"hello\n")
        # the reducer must hold an assignment for the abort-and-requeue
        # half of the report (maps are done, so it gets reduce 0)
        ra = sched.assign_task(rpc.AssignTaskArgs(worker_id=1), timeout=1.0)
        assert ra.assignment == rpc.Assignment.REDUCE and ra.task_id == 0
        # lost-output report: map task 0 re-enqueues, the producer is
        # charged, and the REPORTING attempt is aborted (its worker must
        # be free to run the re-executed map — the small-pool deadlock
        # guard) with its reduce task immediately re-enqueued
        r = sched.reduce_next_file(
            rpc.ReduceNextFileArgs(task_id=0, files_processed=0,
                                   epoch=sched.epoch, worker_id=1,
                                   lost_file="mr-0-0"),
            timeout=0.2,
        )
        assert r.abort
        assert sched.map_tasks[0].state is TaskState.UNASSIGNED
        assert sched.map_tasks[0].peer is None
        assert not sched.map_phase_done()
        assert sched.reduce_tasks[0].state is TaskState.UNASSIGNED
        assert health._fails.get(0) == 1  # one attributed failure
        # a second report for the same task is a no-op (first wins)
        r2 = sched.reduce_next_file(
            rpc.ReduceNextFileArgs(task_id=0, files_processed=0,
                                   epoch=sched.epoch, worker_id=1,
                                   lost_file="mr-0-0"),
            timeout=0.2,
        )
        assert not r2.abort
        assert health._fails.get(0) == 1
        # a surviving worker re-executes; this time the commit is RELAY
        a = sched.assign_task(rpc.AssignTaskArgs(worker_id=2), timeout=1.0)
        assert a.assignment == rpc.Assignment.MAP and a.task_id == 0
        sched.map_finished(rpc.TaskFinishedArgs(
            task_id=0, worker_id=2, produced_parts=[0]))
        assert sched.map_phase_done()
        # the map phase completed TWICE (revocation re-crossed the
        # boundary) but the phase wall observed exactly once — a second
        # sample would include the elapsed reduce time
        from distributed_grep_tpu.runtime import scheduler as sched_mod

        assert sched_mod._H_MAP_PHASE.snapshot()[2] == 1
        r = sched.reduce_next_file(
            rpc.ReduceNextFileArgs(task_id=0, files_processed=0,
                                   epoch=sched.epoch, worker_id=1),
            timeout=0.2,
        )
        assert r.next_file == "mr-0-0" and not r.peer_endpoint
    finally:
        sched.stop()
        sched.close_journal()
    # journal: each (kind, task) at most once despite the re-completion
    entries = TaskJournal.replay(tmp_path / "journal.jsonl")
    seen = [(e["kind"], e["task_id"]) for e in entries]
    assert len(seen) == len(set(seen))
    assert ("map_done", 0) in seen


def test_lost_report_ignores_relay_and_bogus_names(tmp_path):
    """Only PEER-HELD completed outputs are revocable: a report against a
    relay-committed task (daemon holds the bytes — a 404 there is a
    store-layer bug, not a dead worker) or a malformed name is ignored."""
    f = tmp_path / "a.txt"
    f.write_text("hello\n")
    sched = Scheduler(files=[str(f)], n_reduce=1, task_timeout_s=30.0,
                      sweep_interval_s=5.0)
    try:
        a = sched.assign_task(rpc.AssignTaskArgs(worker_id=0), timeout=1.0)
        sched.map_finished(rpc.TaskFinishedArgs(
            task_id=a.task_id, worker_id=0, produced_parts=[0]))
        for bogus in ("mr-0-0", "not-a-name", "mr-99-0"):
            sched.reduce_next_file(
                rpc.ReduceNextFileArgs(task_id=0, files_processed=0,
                                       epoch=sched.epoch,
                                       lost_file=bogus),
                timeout=0.1,
            )
        assert sched.map_tasks[0].state is TaskState.COMPLETED
        assert sched.map_phase_done()
    finally:
        sched.stop()


def test_zombie_lost_report_fenced_by_epoch(tmp_path):
    """A stale-epoch zombie's lost-output report must abort the attempt
    WITHOUT re-enqueueing this incarnation's completed maps."""
    f = tmp_path / "a.txt"
    f.write_text("hello\n")
    sched = Scheduler(files=[str(f)], n_reduce=1, task_timeout_s=30.0,
                      sweep_interval_s=5.0)
    try:
        a = sched.assign_task(rpc.AssignTaskArgs(worker_id=0), timeout=1.0)
        sched.map_finished(rpc.TaskFinishedArgs(
            task_id=a.task_id, worker_id=0, produced_parts=[0],
            peer_endpoint="http://127.0.0.1:1", peer_parts={"0": [1, "x"]}))
        r = sched.reduce_next_file(
            rpc.ReduceNextFileArgs(task_id=0, files_processed=0,
                                   epoch="deadbeefcafe",
                                   lost_file="mr-0-0"),
            timeout=0.1,
        )
        assert r.abort
        assert sched.map_tasks[0].state is TaskState.COMPLETED
    finally:
        sched.stop()


# ---------------------------------------------------- relay fallback leg

class _RelayOnlyTransport:
    """A transport whose daemon holds a relay copy (mixed/migrating
    cluster): peer fetch fails, the declared fallback must serve it."""

    def __init__(self, blobs: dict[str, bytes]):
        self.blobs = blobs

    def read_intermediate(self, name: str) -> bytes:
        return self.blobs[name]


def test_relay_fallback_on_dead_peer(monkeypatch):
    monkeypatch.setenv("DGREP_RPC_RETRIES", "0")  # fail the dead dial fast
    data = b"relay copy\n"
    loop = WorkerLoop(_RelayOnlyTransport({"mr-0-0": data}), app=None)
    reply = rpc.ReduceNextFileReply(
        next_file="mr-0-0", peer_endpoint="http://127.0.0.1:1",
        peer_size=len(data), peer_checksum=checksum(data),
    )
    assert loop._fetch_shuffle(reply) == data
    assert loop.metrics.counters["peer_fetch_failures"] == 1
    assert loop.metrics.counters["relay_fallbacks"] == 1


def test_checksum_mismatch_is_a_declared_failure(monkeypatch):
    """A peer serving WRONG bytes (torn spool, bitrot) must never reach
    the reducer's sink: the crc gate fails the fetch and the relay
    fallback (here: also absent) turns it into a lost-output report."""
    srv = PeerDataServer().start()
    try:
        srv.put("j", "mr-0-0", b"corrupted bytes")

        class _NoRelay:
            def read_intermediate(self, name):
                raise RuntimeError("404")

        loop = WorkerLoop(_NoRelay(), app=None)
        loop._rpc_job_id = "j"
        reply = rpc.ReduceNextFileReply(
            next_file="mr-0-0", peer_endpoint=srv.endpoint,
            peer_size=5, peer_checksum="00000000",  # expect different bytes
        )
        assert loop._fetch_shuffle(reply) is None  # -> lost report
        assert loop.metrics.counters["peer_fetch_failures"] == 1
    finally:
        srv.close()


# ----------------------------------------------------- elastic scaling

def test_scale_advice_and_local_pool(tmp_path, corpus):
    svc = GrepService(work_root=tmp_path / "svc", resume=False,
                      rpc_timeout_s=0.5)
    try:
        # no workers, no jobs: idle with nothing attached -> no advice
        st = svc.status()
        assert "scale" not in st
        # demand with zero workers -> grow
        jid = svc.submit(grep_config(corpus))
        advice = svc.scale_advice()
        assert advice["advice"] == "grow" and advice["pending_tasks"] > 0
        assert svc.status()["scale"]["advice"] == "grow"
        # grow the pool; the job completes
        assert svc.scale_local_pool(2) == 2
        assert svc.local_pool_size() == 2
        assert svc.wait_job(jid, timeout=60)
        # idle with workers attached -> shrink
        deadline = time.monotonic() + 10
        while svc.scale_advice()["advice"] != "shrink":
            assert time.monotonic() < deadline
            time.sleep(0.05)
        # drain to zero: loops exit at their next idle poll
        assert svc.scale_local_pool(0) == -2
        assert svc.local_pool_size() == 0
        for t in svc._local_workers:
            t.join(timeout=10)
        assert not any(t.is_alive() for t in svc._local_workers)
        # drained loops + exited threads are PRUNED at the next scale
        # pass — grow/shrink cycles must not grow the lists (and their
        # retained transports) for the daemon's lifetime
        assert svc.scale_local_pool(1) == 1
        assert len(svc._local_loops) == 1 and len(svc._local_workers) == 1
        svc.scale_local_pool(0)
    finally:
        svc.stop()


def test_scale_advice_ignores_stale_worker_rows(tmp_path, corpus):
    """Worker rows linger for 1 h of silence, but only FRESH rows count
    as capacity: stale rows (drained loops, dead remotes) suppressing
    grow advice would stall recovery exactly when it needs workers."""
    svc = GrepService(work_root=tmp_path / "svc", resume=False,
                      rpc_timeout_s=0.5)
    try:
        svc.submit(grep_config(corpus))
        # six phantom workers, silent for 10 minutes
        with svc._lock:
            for wid in range(100, 106):
                svc.workers[wid] = {"job": None, "task": None,
                                    "seen": time.monotonic() - 600.0}
        advice = svc.scale_advice()
        assert advice["workers_attached"] == 0
        assert advice["advice"] == "grow"
    finally:
        svc.stop()


# ------------------------------------------------------------- explain

def test_explain_summarizes_shuffle_route():
    events = [
        {"t": "instant", "name": "shuffle:peer", "ts": 1.0,
         "args": {"bytes": 100}},
        {"t": "instant", "name": "shuffle:peer", "ts": 2.0,
         "args": {"bytes": 50}},
    ]
    agg = summarize_events(events)
    assert agg["shuffle"] == {
        "peer_fetches": 2, "peer_bytes": 150, "relay_fetches": 0,
        "relay_fallbacks": 0, "lost_outputs": 0, "route": "peer",
    }
    events += [
        {"t": "instant", "name": "shuffle:relay", "ts": 3.0,
         "args": {"fallback": True}},
        {"t": "instant", "name": "map_lost_output", "ts": 4.0},
    ]
    agg = summarize_events(events)
    assert agg["shuffle"]["route"] == "mixed"
    assert agg["shuffle"]["relay_fallbacks"] == 1
    assert agg["shuffle"]["lost_outputs"] == 1
    assert summarize_events([
        {"t": "instant", "name": "shuffle:relay", "ts": 1.0},
    ])["shuffle"]["route"] == "relay"
    assert "shuffle" not in summarize_events([])

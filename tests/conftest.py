"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; the standard JAX trick is to
fake an 8-device mesh on CPU via --xla_force_host_platform_device_count and
test pjit/shard_map logic there (SURVEY.md §4).  Must run before jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Deregister the axon tunnel plugin entirely: its client init can block
# indefinitely when the device tunnel is wedged, hanging every test.  CI is
# CPU-only by design (SURVEY.md §4).  The plain "tpu" factory stays — it is
# never initialized under jax_platforms=cpu, and removing it breaks MLIR
# platform registration (pallas registers tpu lowering rules).
import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
# sitecustomize may have imported jax before this file ran, freezing
# JAX_PLATFORMS at its boot-time value — override through the config API.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def coordinator_port_reader():
    """Returns port_from_stderr(proc, timeout): parse a coordinator
    subprocess's bound port from its stderr via a drain thread —
    readline() in the test thread could block past any deadline, and an
    undrained pipe can stall the coordinator once its ~64 KB buffer
    fills.  Lives in conftest so it needs no cross-test-module import
    (tests/ is not a package)."""
    import queue
    import re
    import threading
    import time

    def port_from_stderr(proc, timeout: float = 15.0):
        q: "queue.Queue[str]" = queue.Queue()

        def drain():
            for line in proc.stderr:  # runs to EOF: the pipe never fills
                q.put(line)

        threading.Thread(target=drain, daemon=True).start()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                line = q.get(timeout=0.2)
            except queue.Empty:
                continue
            m = re.search(r"serving on .*:(\d+)", line)
            if m:
                return int(m.group(1))
        return None

    return port_from_stderr


@pytest.fixture
def workdir(tmp_path):
    from distributed_grep_tpu.utils.io import WorkDir

    return WorkDir(tmp_path / "job")


@pytest.fixture
def corpus(tmp_path):
    """A small multi-file text corpus with known grep-able content."""
    files = {}
    texts = {
        "a.txt": "hello world\nthe quick brown fox\nhello again\n",
        "b.txt": "nothing here\nfox says hello\n\ntrailing line",
        "c.txt": "HELLO uppercase\nhellohello twice on one line\nlast hello",
    }
    for name, text in texts.items():
        p = tmp_path / name
        p.write_text(text)
        files[name] = p
    return files


@pytest.fixture(autouse=True)
def _lockdep_audit(request):
    """The dynamic half of the concurrency-discipline layer (round 11):
    under the `service`, `chaos`, `soak_mini`, `follow`, and `result`
    suites every lock built
    through utils/lockdep.make_lock is instrumented — per-thread
    acquisition stacks, lock-order inversion detection, blocking-syscall-
    while-held detection — and the test FAILS if the run observed either.
    This is the runtime cross-check of the static `locked-blocking` /
    `lock-order` rules: the AST proves what it can see, the harness
    watches what the threads actually did.  Other tests skip activation
    (make_lock hands out raw Locks — zero overhead; suite-wide
    DGREP_LOCKDEP=1 was tried and blew the tier-1 time budget).  Locks
    the ops modules build at IMPORT time are outside this fixture's
    reach — the env-enabled path that covers them is pinned by a
    subprocess test in tests/test_lockdep.py."""
    markers = {m.name for m in request.node.iter_markers()}
    if not markers & {"service", "chaos", "soak_mini", "follow", "result"}:
        yield
        return
    from distributed_grep_tpu.utils import lockdep

    lockdep.activate()
    lockdep.reset()
    try:
        yield
    finally:
        report = lockdep.report()
        lockdep.deactivate()
        lockdep.reset()
    assert not report["inversions"], (
        "lockdep observed a lock-order inversion:\n"
        + "\n".join(str(i) for i in report["inversions"])
    )
    assert not report["blocking"], (
        "lockdep observed a blocking syscall while holding a lock:\n"
        + "\n".join(str(b) for b in report["blocking"])
    )


@pytest.fixture(autouse=True)
def _event_vocab_audit(request):
    """The dynamic half of the event-vocabulary contract (analyze rule
    `event-registry`): under the `service`/`obs`/`follow`/`fuse`/
    `result`/`chaos` suites every span/instant/daemon-event name emitted
    through SpanBuffer/EventLog/DaemonLog is validated against
    analysis/events.py EVENTS and the test FAILS on an undeclared name or
    a kind mismatch — catching dynamically-built names the static AST
    rule cannot resolve (helper pass-throughs, f-string members outside
    the declared family).  Other tests skip activation: the hooks cost
    one module-global bool read when off."""
    markers = {m.name for m in request.node.iter_markers()}
    if not markers & {"service", "obs", "follow", "fuse", "result",
                      "chaos"}:
        yield
        return
    from distributed_grep_tpu.utils import event_audit

    event_audit.activate()
    event_audit.reset()
    try:
        yield
    finally:
        found = event_audit.findings()
        event_audit.deactivate()
        event_audit.reset()
    assert not found, (
        "event audit observed names outside the analysis/events.py "
        "registry:\n" + "\n".join(found)
    )


@pytest.fixture(autouse=True)
def _fresh_device_probe_state():
    """The engine's device-probe verdict is process-global (one backend
    per process in production); tests that exercise demotion would poison
    it for every later test, silently rerouting device-path coverage to
    host — reset per test."""
    from distributed_grep_tpu.ops import engine as _eng

    with _eng._device_probe_lock:
        _eng._device_probe_state.update(verdict=None, at=0.0)
    yield


@pytest.fixture(autouse=True)
def _fresh_model_cache():
    """The cross-job compiled-model cache (ops/engine.cached_engine) is
    process-global by design — in production a service process WANTS
    engines shared across jobs.  Across tests that sharing would leak
    mutated engine state (forced _accel_cached, demotion flags, retuned
    FDR plans) from one test's engine into another's, so each test starts
    and ends with an empty cache."""
    from distributed_grep_tpu.ops import engine as _eng

    _eng.model_cache_clear()
    yield
    _eng.model_cache_clear()


@pytest.fixture(autouse=True)
def _fresh_fusion_counters():
    """The scan-fusion telemetry counters (ops/fuse.py) are
    process-global like the cache counters — zero them per test so one
    test's fused scans never satisfy another's counter assertions."""
    from distributed_grep_tpu.ops import fuse as _fuse

    _fuse.fusion_counters_clear()
    yield
    _fuse.fusion_counters_clear()


@pytest.fixture(autouse=True)
def _fresh_index():
    """The shard-index summary cache, attached store, and telemetry
    counters (distributed_grep_tpu/index) are process-global like the
    corpus cache — cleared per test so one test's summaries (or its
    attached persistence dir) never prune or pollute another's scans."""
    from distributed_grep_tpu.index import summary as _idx

    _idx.clear()
    yield
    _idx.clear()


@pytest.fixture(autouse=True)
def _fresh_follow():
    """The streaming-tier counters (runtime/follow.py) are process-global
    like the fusion counters — zero them per test (sys.modules-gated so
    tests that never touch the tier never import it)."""
    import sys as _sys

    fol = _sys.modules.get("distributed_grep_tpu.runtime.follow")
    if fol is not None:
        fol.follow_counters_clear()
        fol.follow_fused_counters_clear()
    yield
    fol = _sys.modules.get("distributed_grep_tpu.runtime.follow")
    if fol is not None:
        fol.follow_counters_clear()
        fol.follow_fused_counters_clear()


@pytest.fixture(autouse=True)
def _fresh_metrics():
    """The typed-instrument registry (utils/metrics.py round 15) is
    process-global by design — a daemon's /metrics aggregates across its
    whole life.  Across tests that would leak one test's latency
    histograms into another's /status "latency" summary and /metrics
    golden checks, so every instrument is zeroed IN PLACE per test
    (module-level instrument references stay valid)."""
    from distributed_grep_tpu.utils import metrics as _metrics

    _metrics.metrics_reset()
    yield
    _metrics.metrics_reset()


@pytest.fixture(autouse=True)
def _fresh_corpus_cache():
    """The device corpus cache (ops/layout.CorpusCache) is process-global
    by design — the service process WANTS shards shared across jobs.
    Across tests that sharing would serve one test's resident device
    arrays (and host bytes) to another scanning a same-named tmp file,
    so each test starts and ends with an empty cache."""
    from distributed_grep_tpu.ops import layout as _lay

    _lay.corpus_cache_clear()
    yield
    _lay.corpus_cache_clear()


def expand_records(records):
    """Flatten map output to per-record KeyValues: the built-in grep apps
    emit columnar LineBatch objects (round 5, runtime/columnar.py); tests
    asserting record shapes expand them through the semantic equivalence
    (LineBatch.to_keyvalues)."""
    from distributed_grep_tpu.runtime.columnar import LineBatch

    out = []
    for r in records:
        if isinstance(r, LineBatch):
            out.extend(r.to_keyvalues())
        else:
            out.append(r)
    return out

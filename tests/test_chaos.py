"""Chaos tier (round 10): the service daemon under SIGKILL and a hostile
network.

The PR-1 crash matrix (tests/test_store_faults.py) proved the STORAGE
commit protocol; this tier proves the control plane above it:

* ``FaultTransport`` (runtime/fault_transport.py) unit semantics — drops,
  delays, duplicates at the transport boundary;
* duplicate-delivery idempotency end-to-end (every finished RPC + commit
  publication delivered twice -> byte-identical outputs, no duplicate
  journal entries);
* worker quarantine: consecutive attributed timeouts park a worker
  (exponential backoff), re-probation re-admits it, events + counters
  surface the episode;
* the bounded-jittered client retry (``client_call``) surviving RST-ing
  sockets, including the full ``dgrep submit`` poll loop through a flaky
  TCP proxy (the satellite fix: a transient reset used to kill the
  client before its daemon-death JSON fallback could fire);
* the acceptance matrix: daemon SIGKILL mid-stream (2 concurrent jobs +
  1 queued) x {map, reduce phase} x {posix, nonatomic store} x injected
  network faults -> the restarted daemon completes every job
  byte-identical to a fault-free run with zero duplicate journal
  commits.

Standalone:  python -m pytest tests/test_chaos.py -q  (marker ``chaos``)
"""

from __future__ import annotations

import json
import random
import socket
import threading
import time
from pathlib import Path

import pytest

import service_proc
from distributed_grep_tpu.runtime.daemon_log import DaemonLog
from distributed_grep_tpu.runtime.fault_transport import (
    FaultPoint,
    FaultTransport,
    seeded_schedule,
)
from distributed_grep_tpu.runtime.http_transport import (
    ServiceHttpTransport,
    client_call,
)
from distributed_grep_tpu.runtime.job import run_job
from distributed_grep_tpu.runtime.journal import TaskJournal
from distributed_grep_tpu.runtime.scheduler import (
    QUARANTINE_AFTER_FAILURES,
    Scheduler,
    WorkerHealth,
)
from distributed_grep_tpu.runtime import rpc
from distributed_grep_tpu.runtime.service import GrepService, ServiceServer
from distributed_grep_tpu.runtime.worker import WorkerKilled, WorkerLoop
from distributed_grep_tpu.utils.config import JobConfig
from distributed_grep_tpu.utils.io import WorkDir

pytestmark = pytest.mark.chaos


# ------------------------------------------------------- FaultTransport unit

class _FakeTransport:
    def __init__(self):
        self.calls: list[str] = []

    def map_finished(self, args):
        self.calls.append("map_finished")
        return rpc.TaskFinishedReply(ok=True)

    def read_input(self, name):
        self.calls.append(f"read:{name}")
        return b"data"


def test_fault_transport_duplicate_and_passthrough():
    base = _FakeTransport()
    ft = FaultTransport(base, {
        FaultPoint.DUPLICATE: lambda ctx: ctx == "map_finished",
    })
    reply = ft.map_finished(rpc.TaskFinishedArgs(task_id=0))
    assert reply.ok
    assert base.calls == ["map_finished", "map_finished"]  # two deliveries
    assert ft.read_input("f") == b"data"  # un-faulted call passes through


def test_fault_transport_drop_request_never_reaches_base():
    base = _FakeTransport()
    ft = FaultTransport(base, {
        FaultPoint.DROP_REQUEST: lambda ctx: ctx == "map_finished",
    })
    with pytest.raises(ConnectionResetError):
        ft.map_finished(rpc.TaskFinishedArgs(task_id=0))
    assert base.calls == []  # the peer never saw it


def test_fault_transport_drop_reply_applies_server_side():
    base = _FakeTransport()
    ft = FaultTransport(base, {
        FaultPoint.DROP_REPLY: lambda ctx: True,
    })
    with pytest.raises(ConnectionResetError):
        ft.map_finished(rpc.TaskFinishedArgs(task_id=0))
    assert base.calls == ["map_finished"]  # the peer DID act


def test_fault_transport_delay_and_feature_probes():
    base = _FakeTransport()
    slept = time.monotonic()
    ft = FaultTransport(base, {
        FaultPoint.DELAY: lambda ctx: 0.05 if ctx == "read_input" else 0,
    })
    assert ft.read_input("f") == b"data"
    assert time.monotonic() - slept >= 0.05
    # hasattr probes answer the base's truth (worker feature detection)
    assert not hasattr(ft, "read_input_path")
    assert not hasattr(ft, "publish_task_commit")
    with pytest.raises(ValueError):
        FaultTransport(base, {"bogus_point": lambda ctx: 1})


# --------------------------------------- duplicate deliveries, end to end

def grep_config(corpus, pattern="hello", **kw) -> JobConfig:
    defaults = dict(
        input_files=[str(p) for p in corpus.values()],
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": pattern, "backend": "cpu"},
        n_reduce=3,
    )
    defaults.update(kw)
    return JobConfig(**defaults)


def outputs_by_name(paths) -> dict[str, bytes]:
    """name -> bytes, normalized over nonatomic part decoration (the
    resolved winner path is <name>.part.<attempt> there)."""
    out = {}
    for p in paths:
        name = Path(p).name.split(".part.")[0]
        out[name] = Path(p).read_bytes()
    return out


def test_duplicate_deliveries_keep_outputs_exact(tmp_path, corpus):
    """EVERY completion RPC and commit publication delivered twice: the
    idempotent commit layer absorbs all of it — outputs byte-identical
    to a clean run, journal carries each task once."""
    from distributed_grep_tpu.runtime.service import ServiceLocalTransport

    svc = GrepService(work_root=tmp_path / "svc", task_timeout_s=5.0,
                      sweep_interval_s=0.1)
    try:
        jid = svc.submit(grep_config(corpus))
        dup = {"n": 0}

        def dup_hook(ctx: str):
            if ctx in ("map_finished", "reduce_finished",
                       "publish_task_commit", "write_intermediate",
                       "write_output"):
                dup["n"] += 1
                return 1
            return 0

        loop = WorkerLoop(
            FaultTransport(ServiceLocalTransport(svc, rpc_timeout_s=5.0),
                           {FaultPoint.DUPLICATE: dup_hook}),
            app=None,
        )
        t = threading.Thread(target=loop.run, daemon=True)
        t.start()
        assert svc.wait_job(jid, timeout=60), svc.job_status(jid)
        assert dup["n"] > 0  # faults actually fired
        got = outputs_by_name(svc.job_result(jid)["outputs"])
        want = outputs_by_name(run_job(
            grep_config(corpus, work_dir=str(tmp_path / "serial")),
            n_workers=2,
        ).output_files)
        assert got == want
        # journal: each task committed exactly once despite double delivery
        entries = TaskJournal.replay(
            WorkDir(str(tmp_path / "svc" / jid)).journal_path()
        )
        seen = [(e["kind"], e["task_id"]) for e in entries]
        assert len(seen) == len(set(seen))
    finally:
        svc.stop()


# ------------------------------------------------------------- quarantine

def test_worker_quarantine_and_reprobation(tmp_path, monkeypatch):
    """Deterministic quarantine lifecycle at the scheduler: a worker that
    keeps timing out is parked after QUARANTINE_AFTER_FAILURES, its polls
    answer retry + retry_after_s, another worker gets the work, and
    expiry re-probations the flake.  Events + counters cover it."""
    monkeypatch.setenv("DGREP_WORKER_QUARANTINE_S", "0.6")
    from distributed_grep_tpu.utils.spans import EventLog

    ev_path = tmp_path / "events.jsonl"
    event_log = EventLog(ev_path, fresh=True)
    files = [str(tmp_path / "in.txt")]
    Path(files[0]).write_text("hello\n")
    sched = Scheduler(files=files, n_reduce=1, task_timeout_s=0.15,
                      sweep_interval_s=0.05, event_log=event_log)
    try:
        flaky = -1
        for i in range(QUARANTINE_AFTER_FAILURES):
            reply = sched.assign_task(
                rpc.AssignTaskArgs(worker_id=flaky), timeout=2.0
            )
            assert reply.assignment == rpc.Assignment.MAP, (i, reply)
            flaky = reply.worker_id
            # never complete: the sweeper attributes the timeout to us
            deadline = time.monotonic() + 5
            while sched.map_tasks[0].state.value != "unassigned":
                assert time.monotonic() < deadline
                time.sleep(0.02)
        # quarantined now: our poll gets retry + a re-probation hint
        reply = sched.assign_task(
            rpc.AssignTaskArgs(worker_id=flaky), timeout=0.1
        )
        assert reply.assignment == "retry"
        assert reply.retry_after_s > 0
        assert sched.worker_health.quarantine_remaining(flaky) > 0
        assert sched.metrics.counters["workers_quarantined"] == 1
        assert sched.metrics.counters["tasks_requeued"] >= 3
        # another worker gets the task immediately
        reply2 = sched.assign_task(rpc.AssignTaskArgs(worker_id=-1),
                                   timeout=2.0)
        assert reply2.assignment == rpc.Assignment.MAP
        assert reply2.worker_id != flaky
        sched.map_finished(rpc.TaskFinishedArgs(
            task_id=0, worker_id=reply2.worker_id, produced_parts=[0]
        ))
        # /status rows surface the parked worker
        assert "quarantined_s" in sched.worker_status()[str(flaky)]
        # re-probation: after expiry the flake is assignable again
        time.sleep(0.7)
        assert sched.worker_health.quarantine_remaining(flaky) == 0.0
        reply3 = sched.assign_task(
            rpc.AssignTaskArgs(worker_id=flaky), timeout=2.0
        )
        assert reply3.assignment == rpc.Assignment.REDUCE
    finally:
        sched.stop()
        event_log.close()
    names = [json.loads(ln).get("name")
             for ln in ev_path.read_text().splitlines() if ln.strip()]
    assert "quarantine" in names
    # and trace-export renders the instant
    from distributed_grep_tpu.utils.spans import EventLog as EL
    from distributed_grep_tpu.utils.spans import export_chrome_trace

    doc = export_chrome_trace(EL.read(ev_path))
    assert any(e.get("name") == "quarantine" for e in doc["traceEvents"])


def test_quarantine_backoff_doubles_and_success_clears():
    h = WorkerHealth(base_s=10.0)
    for _ in range(QUARANTINE_AFTER_FAILURES - 1):
        assert h.record_failure(7) == 0.0
    assert h.record_failure(7) == 10.0  # episode 1
    h._until.clear()  # expire by hand (no wall-clock wait)
    assert h.record_failure(7) == 20.0  # re-probation failure: episode 2
    h._until.clear()
    h.record_success(7)  # a committed task clears the whole record
    for _ in range(QUARANTINE_AFTER_FAILURES - 1):
        assert h.record_failure(7) == 0.0
    assert h.record_failure(7) == 10.0  # back to episode 1's window


def test_service_status_surfaces_quarantine(tmp_path, corpus):
    """Service-level: a worker going dark under one tenant is parked for
    EVERY tenant (shared WorkerHealth), visible in GET /status."""
    svc = GrepService(work_root=tmp_path / "svc", task_timeout_s=0.15,
                      sweep_interval_s=0.05)
    try:
        jid = svc.submit(grep_config(corpus))
        flaky = -1
        for _ in range(QUARANTINE_AFTER_FAILURES):
            reply = svc.assign_task(rpc.AssignTaskArgs(worker_id=flaky),
                                    timeout=5.0)
            assert reply.assignment == rpc.Assignment.MAP
            flaky = reply.worker_id
            rec = svc.record(jid)
            deadline = time.monotonic() + 5
            while rec.scheduler.map_tasks[reply.task_id].state.value \
                    != "unassigned":
                assert time.monotonic() < deadline
                time.sleep(0.02)
        reply = svc.assign_task(rpc.AssignTaskArgs(worker_id=flaky),
                                timeout=0.1)
        assert reply.assignment == "retry" and reply.retry_after_s > 0
        status = svc.status()
        assert status["workers_quarantined"] >= 1
        assert str(flaky) in status["quarantine"]
        assert status["workers"][str(flaky)].get("quarantined_s", 0) > 0
        assert status["tasks_requeued"] >= QUARANTINE_AFTER_FAILURES
        # healthy workers finish the job while the flake is parked
        svc.start_local_workers(1)
        assert svc.wait_job(jid, timeout=60), svc.job_status(jid)
    finally:
        svc.stop()


def test_zombie_reducer_fenced_by_scheduler_epoch(tmp_path):
    """A reduce attempt that outlives a daemon restart (its transport
    retries reconnect to the NEW incarnation) carries a files_processed
    cursor over the OLD task_files arrival order — the rebuilt scheduler
    must ABORT it, never serve its misindexed cursor (it could commit
    wrong bytes and win attempt resolution)."""
    f = tmp_path / "in.txt"
    f.write_text("hello\n")
    sched = Scheduler(files=[str(f)], n_reduce=1, task_timeout_s=5.0,
                      sweep_interval_s=0.5)
    try:
        # a fetch tagged with another incarnation's epoch: aborted
        r = sched.reduce_next_file(
            rpc.ReduceNextFileArgs(task_id=0, files_processed=1,
                                   epoch="deadbeefcafe"),
            timeout=0.1,
        )
        assert r.abort and not r.done and not r.next_file
        # the current incarnation's epoch (and the legacy empty one) serve
        for ep in (sched.epoch, ""):
            r = sched.reduce_next_file(
                rpc.ReduceNextFileArgs(task_id=0, files_processed=0,
                                       epoch=ep),
                timeout=0.1,
            )
            assert not r.abort
        # assignments carry the epoch the worker must echo
        reply = sched.assign_task(rpc.AssignTaskArgs(worker_id=-1),
                                  timeout=1.0)
        assert reply.assignment == rpc.Assignment.MAP
        assert reply.epoch == sched.epoch
    finally:
        sched.stop()


# ------------------------------------------------- flaky-socket client path

class FlakyProxy:
    """TCP proxy that RST-closes every ``drop_every``-th accepted
    connection (starting with the FIRST) and forwards the rest to the
    upstream port — the transient-reset network a client retry policy
    must survive."""

    def __init__(self, upstream_port: int, drop_every: int = 3,
                 offset: int = 0):
        self.upstream_port = upstream_port
        self.drop_every = drop_every
        self.dropped = 0
        self._n = offset  # offset=1: the FIRST connection passes
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(32)
        self.port = self._srv.getsockname()[1]
        self._stop = False
        self._t = threading.Thread(target=self._accept_loop, daemon=True)
        self._t.start()

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            i = self._n
            self._n += 1
            if i % self.drop_every == 0:
                # SO_LINGER(1, 0): close() sends RST, the hard reset.
                # Count BEFORE closing — the client observes the reset
                # the instant close() runs, and a test asserting on
                # `dropped` right after its exception would race a
                # post-close increment.
                self.dropped += 1
                conn.setsockopt(
                    socket.SOL_SOCKET, socket.SO_LINGER,
                    b"\x01\x00\x00\x00\x00\x00\x00\x00",
                )
                conn.close()
                continue
            threading.Thread(target=self._pump, args=(conn,),
                             daemon=True).start()

    def _pump(self, client):
        try:
            up = socket.create_connection(("127.0.0.1", self.upstream_port))
        except OSError:
            client.close()
            return

        def shuttle(src, dst):
            try:
                while True:
                    block = src.recv(1 << 16)
                    if not block:
                        break
                    dst.sendall(block)
            except OSError:
                pass
            finally:
                try:
                    dst.shutdown(socket.SHUT_WR)
                except OSError:
                    pass

        t = threading.Thread(target=shuttle, args=(up, client), daemon=True)
        t.start()
        shuttle(client, up)
        t.join(timeout=10)
        client.close()
        up.close()

    def close(self):
        self._stop = True
        self._srv.close()


def test_client_call_survives_connection_resets(tmp_path, corpus,
                                                monkeypatch):
    monkeypatch.setenv("DGREP_RPC_BACKOFF_S", "0.05")
    svc = GrepService(work_root=tmp_path / "svc")
    server = ServiceServer(svc)
    server.start()
    proxy = FlakyProxy(server.port, drop_every=2)  # every OTHER conn RSTs
    try:
        # every second call eats a reset first and retries through it
        for _ in range(4):
            status = client_call(f"127.0.0.1:{proxy.port}", "GET", "/status")
            assert status["service"] is True
        assert proxy.dropped >= 2
    finally:
        proxy.close()
        svc.stop()
        server.shutdown()


def test_client_call_single_shot_never_replays(tmp_path, monkeypatch):
    """retry=False (the submit POST): exactly ONE attempt — a retried
    non-idempotent POST would mint a duplicate job after a lost reply."""
    monkeypatch.setenv("DGREP_RPC_BACKOFF_S", "0.05")
    svc = GrepService(work_root=tmp_path / "svc")
    server = ServiceServer(svc)
    server.start()
    proxy = FlakyProxy(server.port, drop_every=1)  # EVERY connection RSTs
    try:
        with pytest.raises(OSError):
            client_call(f"127.0.0.1:{proxy.port}", "POST", "/jobs", b"{}",
                        retry=False)
        assert proxy.dropped == 1  # one attempt, zero replays
    finally:
        proxy.close()
        svc.stop()
        server.shutdown()


def test_cmd_submit_poll_survives_flaky_socket(tmp_path, corpus,
                                               monkeypatch, capsys):
    """The satellite fix end-to-end: `dgrep submit --wait` through a proxy
    that RSTs every third connection completes the job and prints exactly
    ONE JSON line — the old raw-urlopen poll died on the first reset."""
    from distributed_grep_tpu import __main__ as cli

    monkeypatch.setenv("DGREP_RPC_BACKOFF_S", "0.05")
    svc = GrepService(work_root=tmp_path / "svc", task_timeout_s=5.0,
                      sweep_interval_s=0.1)
    server = ServiceServer(svc)
    server.start()
    svc.start_local_workers(2)
    # offset=1: the submit POST itself (first connection) passes — it is
    # deliberately SINGLE-SHOT (a retried non-idempotent POST would mint
    # a duplicate job); every later POLL eats resets and retries through
    proxy = FlakyProxy(server.port, drop_every=3, offset=1)
    try:
        rc = cli.main([
            "submit", "--addr", f"127.0.0.1:{proxy.port}",
            "hello", *[str(p) for p in corpus.values()],
            "--timeout", "60",
        ])
        out = capsys.readouterr().out
        lines = [ln for ln in out.splitlines() if ln.strip()]
        assert rc == 0, out
        assert len(lines) == 1
        doc = json.loads(lines[0])
        assert doc["state"] == "done" and doc["outputs"]
        assert proxy.dropped >= 1  # the flake actually bit
    finally:
        proxy.close()
        svc.stop()
        server.shutdown()


# ------------------------------------------------------ the chaos matrix

def _chaos_hooks(seed: int) -> dict:
    """The matrix's network profile: seeded drops on every call family,
    duplicates on the idempotent completion/commit calls, small delays
    on the data plane."""
    rng = random.Random(seed)

    def drop_request(ctx):
        return rng.random() < 0.04

    def drop_reply(ctx):
        return rng.random() < 0.04

    def duplicate(ctx):
        return ctx in ("map_finished", "reduce_finished",
                       "publish_task_commit", "heartbeat") \
            and rng.random() < 0.15

    def delay(ctx):
        if ctx in ("read_input", "read_intermediate", "write_intermediate"):
            return 0.03 * rng.random()
        return 0

    return {
        FaultPoint.DROP_REQUEST: drop_request,
        FaultPoint.DROP_REPLY: drop_reply,
        FaultPoint.DUPLICATE: duplicate,
        FaultPoint.DELAY: delay,
    }


@pytest.fixture(scope="module")
def matrix_corpus(tmp_path_factory) -> dict[str, Path]:
    """One corpus shared by every matrix case (module-scoped on purpose:
    output bytes embed input paths, so the fault-free oracle runs are
    computed once per (pattern, store) and reused across the phase
    parametrization)."""
    root = tmp_path_factory.mktemp("chaos-corpus")
    files = {}
    for i in range(6):
        p = root / f"in{i}.txt"
        lines = []
        for j in range(400):
            lines.append(
                f"line {j} of file {i}"
                + (" hello" if j % 3 == 0 else "")
                + (" fox" if j % 5 == 0 else "")
            )
        p.write_text("\n".join(lines) + "\n")
        files[p.name] = p
    return files


_ORACLE_CACHE: dict[tuple[str, str], dict[str, bytes]] = {}


@pytest.mark.parametrize("phase,store", [
    ("map", "posix"),
    ("map", "nonatomic"),
    ("reduce", "posix"),
    ("reduce", "nonatomic"),
])
def test_chaos_matrix_daemon_sigkill(tmp_path, monkeypatch, phase, store,
                                     matrix_corpus):
    """Acceptance: daemon SIGKILL mid-stream (2 running + 1 queued) x
    {map, reduce} x {posix, nonatomic} x injected network faults — the
    restarted daemon completes every job byte-identical to a fault-free
    run, with zero duplicate journal commits."""
    monkeypatch.setenv("DGREP_RPC_RETRIES", "10")
    monkeypatch.setenv("DGREP_RPC_BACKOFF_S", "0.2")
    corpus = matrix_corpus
    work_root = tmp_path / "svc-root"
    work_root.mkdir()
    daemon = service_proc.ServiceProc(
        work_root, workers=0,
        env={
            "DGREP_SERVICE_MAX_JOBS": "2",  # 3 submits = 2 running + 1 queued
            "DGREP_WORKER_QUARANTINE_S": "1",
            # fusion OFF: this matrix pins the PRE-fusion daemon's exact
            # crash/restart behavior (the round-13 no-op contract); the
            # fused-attempt death path has its own dedicated case below
            # (test_chaos_worker_killed_mid_fused_attempt) — co-running
            # same-corpus jobs fusing here would add fused-retry timing
            # variance to an already load-sensitive 2 s-timeout matrix
            "DGREP_SERVICE_FUSE": "0",
        },
    ).start()

    stop = threading.Event()

    def worker_main(seed: int) -> None:
        # crashed workers are REPLACED: an injected reset kills the loop
        # like a real network death kills a worker; the next incarnation
        # attaches fresh (new service-allocated id) — which is also what
        # drives quarantine pressure on the ids that died holding tasks
        rng = random.Random(seed)
        while not stop.is_set():
            transport = FaultTransport(
                ServiceHttpTransport(f"127.0.0.1:{daemon.port}",
                                     rpc_timeout_s=15.0),
                _chaos_hooks(rng.randrange(1 << 30)),
            )
            loop = WorkerLoop(transport, app=None)
            try:
                loop.run()
                return  # JOB_DONE: service shut down
            except Exception:  # noqa: BLE001 — worker died; replace it
                time.sleep(0.2)

    threads = [threading.Thread(target=worker_main, args=(seed,),
                                daemon=True) for seed in (11, 23, 47)]
    for t in threads:
        t.start()

    def cfg_for(pattern: str, sub: str) -> JobConfig:
        return grep_config(
            corpus, pattern=pattern, n_reduce=2, store=store,
            task_timeout_s=2.0, sweep_interval_s=0.2,
            work_dir=str(tmp_path / sub),  # service overrides its copy
        )

    patterns = ["hello", "fox", "line"]
    try:
        jids = [daemon.submit(cfg_for(p, f"sub{i}"))
                for i, p in enumerate(patterns)]

        # wait for the kill phase mid-stream, then SIGKILL
        deadline = time.monotonic() + 90
        while True:
            assert time.monotonic() < deadline, daemon.tail_log()
            try:
                st = daemon.job_status(jids[0])
            except OSError:
                time.sleep(0.05)
                continue
            m = st.get("map", {})
            if phase == "map":
                if m.get("completed", 0) >= 1:
                    break  # mid map phase (or later — mid-stream either way)
            else:
                if m and m.get("completed") == m.get("total"):
                    break  # map phase over: reduces in flight
            if st.get("state") == "done":
                break  # too fast to catch — restart still exercises resume
            time.sleep(0.03)
        daemon.sigkill()
        time.sleep(0.5)  # a real crash-restart gap; workers retry through it
        daemon.start()

        results = {}
        for jid in jids:
            st = daemon.wait_job(jid, timeout=150)
            assert st["state"] == "done", (jid, st, daemon.tail_log())
            results[jid] = daemon.job_result(jid)["outputs"]
    finally:
        stop.set()
        # fail the workers' remaining transport calls FAST: the retry
        # schedule is re-read from the env per call, so zeroing it here
        # turns each crashed loop's next call into an immediate exit
        # instead of ~20 s of backoff against a dead daemon (monkeypatch
        # restores the var at teardown)
        monkeypatch.setenv("DGREP_RPC_RETRIES", "0")
        daemon.terminate()
        for t in threads:
            t.join(timeout=10)

    # byte-identical to fault-free serial runs (oracle outputs cached per
    # (pattern, store) — the phase parametrization reuses them)
    for jid, pattern, i in zip(jids, patterns, range(3)):
        key = (pattern, store)
        if key not in _ORACLE_CACHE:
            _ORACLE_CACHE[key] = outputs_by_name(run_job(
                grep_config(corpus, pattern=pattern, n_reduce=2, store=store,
                            work_dir=str(tmp_path / f"oracle{i}")),
                n_workers=2,
            ).output_files)
        assert outputs_by_name(results[jid]) == _ORACLE_CACHE[key], \
            (jid, pattern)

    # zero duplicate journal commits per job, across both daemon lives
    for jid in jids:
        entries = TaskJournal.replay(
            WorkDir(str(work_root / jid)).journal_path()
        )
        seen = [(e["kind"], e["task_id"]) for e in entries]
        assert len(seen) == len(set(seen)), (jid, seen)


# ------------------------------------------- fused-attempt worker death

@pytest.mark.fuse
def test_chaos_worker_killed_mid_fused_attempt(tmp_path, monkeypatch,
                                               corpus):
    """ISSUE 11 chaos bar: a worker dies mid-FUSED-attempt (after the
    shared scan, before any participant's commit — the widest blast
    radius: K claimed tasks, zero commits).  Every participant job must
    finish byte-identical to its fault-free oracle, each job's journal
    holding each (kind, task) at most once; the re-enqueued tasks re-run
    SOLO (claim_map_task gates on attempts == 0), so fusion never
    becomes a correctness dependency."""
    monkeypatch.setenv("DGREP_RPC_RETRIES", "4")
    monkeypatch.setenv("DGREP_RPC_BACKOFF_S", "0.05")
    work_root = tmp_path / "svc-root"
    work_root.mkdir()
    daemon = service_proc.ServiceProc(
        work_root, workers=0,
        env={"DGREP_SERVICE_MAX_JOBS": "3"},
    ).start()

    stop = threading.Event()
    killed = threading.Event()

    def kill_once() -> None:
        if not killed.is_set():
            killed.set()
            raise WorkerKilled("mid-fused-attempt")

    def worker_main(assassin: bool) -> None:
        # the killed incarnation is REPLACED by a clean one, like the
        # matrix's crash-replace loops
        while not stop.is_set():
            hooks = (
                {"before_map_commit": kill_once}
                if assassin and not killed.is_set() else {}
            )
            loop = WorkerLoop(
                ServiceHttpTransport(f"127.0.0.1:{daemon.port}",
                                     rpc_timeout_s=10.0),
                app=None, fault_hooks=hooks,
            )
            try:
                loop.run()
                return  # JOB_DONE: daemon shut down
            except WorkerKilled:
                time.sleep(0.1)
            except Exception:  # noqa: BLE001 — worker died; replace it
                time.sleep(0.2)

    patterns = ["hello", "fox", "line"]
    threads: list[threading.Thread] = []  # bound before any try-exit path
    try:
        jids = [daemon.submit(grep_config(
            corpus, pattern=p, n_reduce=2, task_timeout_s=2.0,
            sweep_interval_s=0.2, work_dir=str(tmp_path / f"sub{i}"),
        )) for i, p in enumerate(patterns)]
        # all three must be RUNNING (fusable) before any worker attaches,
        # or the first assignment has nothing to fuse with
        deadline = time.monotonic() + 30
        while True:
            assert time.monotonic() < deadline, daemon.tail_log()
            sts = [daemon.job_status(j) for j in jids]
            if all(s.get("state") == "running" for s in sts):
                break
            time.sleep(0.05)
        threads = [threading.Thread(target=worker_main, args=(i == 0,),
                                    daemon=True) for i in range(2)]
        for t in threads:
            t.start()
        results = {}
        for jid in jids:
            st = daemon.wait_job(jid, timeout=90)
            assert st["state"] == "done", (jid, st, daemon.tail_log())
            results[jid] = daemon.job_result(jid)["outputs"]
        status = daemon.status()
        assert killed.is_set()  # the kill actually fired mid-attempt
        assert status.get("fusion", {}).get("fused_dispatches", 0) >= 1, \
            status  # fusion actually engaged before the death
    finally:
        stop.set()
        monkeypatch.setenv("DGREP_RPC_RETRIES", "0")
        daemon.terminate()
        for t in threads:
            t.join(timeout=10)

    for jid, pattern, i in zip(jids, patterns, range(3)):
        oracle = outputs_by_name(run_job(
            grep_config(corpus, pattern=pattern, n_reduce=2,
                        work_dir=str(tmp_path / f"oracle{i}")),
            n_workers=2,
        ).output_files)
        assert outputs_by_name(results[jid]) == oracle, (jid, pattern)

    for jid in jids:
        entries = TaskJournal.replay(
            WorkDir(str(work_root / jid)).journal_path()
        )
        seen = [(e["kind"], e["task_id"]) for e in entries]
        assert len(seen) == len(set(seen)), (jid, seen)


# ------------------------------------- peer-shuffle producer death (round 16)

def _peer_chaos_service(tmp_path):
    """In-process service with the chaos-matrix detector cadence: short
    timeouts so a dead producer's held task re-enqueues fast."""
    svc = GrepService(work_root=tmp_path / "svc-root", resume=False,
                      task_timeout_s=2.0, sweep_interval_s=0.2)
    server = ServiceServer(svc)
    server.start()
    return svc, server, f"127.0.0.1:{server.port}"


def test_chaos_producer_killed_between_map_commit_and_reduce_fetch(
        tmp_path, corpus, monkeypatch):
    """ISSUE 14 chaos bar: the PRODUCING worker dies after its map
    commits (output on its spool, metadata registered) and before any
    reducer fetches — the load-bearing P2P fault path.  Surviving
    workers' fetch failures report the outputs lost, the producing map
    tasks re-execute (COMPLETED -> UNASSIGNED), and the job completes
    byte-identical to a fault-free run with journal entries unique per
    (kind, task)."""
    from distributed_grep_tpu.runtime.peer import PeerDataServer

    # dials against the dead producer's endpoint refuse instantly; a
    # full 6-step backoff schedule per fetch only slows the matrix down
    monkeypatch.setenv("DGREP_RPC_RETRIES", "1")
    monkeypatch.setenv("DGREP_RPC_BACKOFF_S", "0.1")

    svc, server, addr = _peer_chaos_service(tmp_path)

    class DieOnReduce(WorkerLoop):
        # the producer's death instant: maps committed (peer-held),
        # first reduce assignment arrives, worker vanishes before any
        # fetch is served
        def _run_reduce(self, a):
            raise WorkerKilled("producer dies before the reduce fetch")

    peer_a = PeerDataServer().start()
    loop_a = DieOnReduce(
        ServiceHttpTransport(addr, rpc_timeout_s=10.0), app=None,
        peer=peer_a,
    )

    def producer_main():
        try:
            loop_a.run()
        except WorkerKilled:
            pass

    t_a = threading.Thread(target=producer_main, daemon=True)
    survivors: list[threading.Thread] = []
    loops_b: list[WorkerLoop] = []
    try:
        cfg = grep_config(corpus, pattern="hello", n_reduce=2,
                          work_dir=str(tmp_path / "sub"))
        jid = svc.submit(cfg)
        t_a.start()
        t_a.join(timeout=60)  # exits at its first reduce assignment
        assert not t_a.is_alive()
        peer_a.close()  # the spool dies with the worker
        # every map completed peer-held before the death
        st = svc.job_status(jid)
        assert st["map"]["completed"] == st["map"]["total"]
        assert st["state"] == "running"
        # survivors (relay data plane — no peer) take over: reducers hit
        # the dead endpoint, report the outputs lost, and re-execute the
        # maps through the relay path
        for _ in range(2):
            loop = WorkerLoop(
                ServiceHttpTransport(addr, rpc_timeout_s=10.0), app=None
            )
            loops_b.append(loop)
            t = threading.Thread(target=loop.run, daemon=True)
            t.start()
            survivors.append(t)
        assert svc.wait_job(jid, timeout=120), svc.job_status(jid)
        outputs = svc.job_result(jid)["outputs"]
    finally:
        svc.stop()
        server.shutdown()
        peer_a.close()
        for t in survivors:
            t.join(timeout=10)

    oracle = outputs_by_name(run_job(
        grep_config(corpus, pattern="hello", n_reduce=2,
                    work_dir=str(tmp_path / "oracle")),
        n_workers=2,
    ).output_files)
    assert outputs_by_name(outputs) == oracle

    # the recovery actually ran through the lost-output path
    rec = svc.record(jid)
    assert rec.metrics.counters.get("maps_lost_output", 0) >= 1
    failures = sum(lp.metrics.counters.get("peer_fetch_failures", 0)
                   for lp in loops_b)
    assert failures >= 1
    # journal: unique per (kind, task) despite the re-executions
    entries = TaskJournal.replay(
        WorkDir(str((tmp_path / "svc-root") / jid)).journal_path()
    )
    seen = [(e["kind"], e["task_id"]) for e in entries]
    assert len(seen) == len(set(seen)), seen


def test_chaos_drop_reply_on_peer_fetch_leg(tmp_path, corpus):
    """A FaultTransport DROP_REPLY on the peer-fetch leg: the fetch
    reaches the (healthy) peer but the reply dies on the wire.  The
    reducer's declared-failure path runs (fetch failure counted, relay
    fallback attempted), the lost-output report re-executes the map, and
    the job completes byte-identical with a unique journal.  The one
    surviving reducer recovers ALONE — the report aborts its own attempt
    so it is free to run the re-executed maps (the small-pool deadlock
    guard)."""
    svc, server, addr = _peer_chaos_service(tmp_path)
    from distributed_grep_tpu.runtime.peer import PeerDataServer

    class DieOnReduce(WorkerLoop):
        # a map-only producer: its task loop dies at the first reduce
        # assignment but its DATA SERVER stays up — every map output is
        # peer-held on a healthy endpoint, so the surviving reducer's
        # fetches MUST cross the wire (no self-serve fast path)
        def _run_reduce(self, a):
            raise WorkerKilled("map-only producer")

    drops = {"left": 2}  # first two peer fetches lose their replies

    def drop_reply(ctx):
        if ctx == "fetch_peer" and drops["left"] > 0:
            drops["left"] -= 1
            return 1
        return 0

    peer_a = PeerDataServer().start()
    loop_a = DieOnReduce(
        ServiceHttpTransport(addr, rpc_timeout_s=10.0), app=None,
        peer=peer_a,
    )
    loop_b = WorkerLoop(
        FaultTransport(
            ServiceHttpTransport(addr, rpc_timeout_s=10.0),
            {FaultPoint.DROP_REPLY: drop_reply},
        ),
        app=None,  # no peer: relay re-commits, peer fetches cross-wire
    )
    loops = [loop_b]
    t_b = None
    try:
        cfg = grep_config(corpus, pattern="fox", n_reduce=2,
                          work_dir=str(tmp_path / "sub"))
        jid = svc.submit(cfg)

        def producer_main():
            try:
                loop_a.run()
            except WorkerKilled:
                pass

        t_a = threading.Thread(target=producer_main, daemon=True)
        t_a.start()
        t_a.join(timeout=60)  # all maps committed peer-held, loop gone
        assert not t_a.is_alive()
        t_b = threading.Thread(target=loop_b.run, daemon=True)
        t_b.start()
        assert svc.wait_job(jid, timeout=120), svc.job_status(jid)
        outputs = svc.job_result(jid)["outputs"]
        rec = svc.record(jid)
    finally:
        svc.stop()
        server.shutdown()
        peer_a.close()
        if t_b is not None:
            t_b.join(timeout=10)

    oracle = outputs_by_name(run_job(
        grep_config(corpus, pattern="fox", n_reduce=2,
                    work_dir=str(tmp_path / "oracle-fox")),
        n_workers=2,
    ).output_files)
    assert outputs_by_name(outputs) == oracle
    assert drops["left"] == 0  # the faults actually fired
    failures = sum(lp.metrics.counters.get("peer_fetch_failures", 0)
                   for lp in loops)
    assert failures >= 1
    # dropped replies forced lost-output re-execution (the daemon held
    # no relay copy), each journaled at most once
    assert rec.metrics.counters.get("maps_lost_output", 0) >= 1
    entries = TaskJournal.replay(
        WorkDir(str((tmp_path / "svc-root") / jid)).journal_path()
    )
    seen = [(e["kind"], e["task_id"]) for e in entries]
    assert len(seen) == len(set(seen)), seen


# --------------------------------------- lease-fenced failover (round 18)

@pytest.mark.parametrize("phase", ["map", "reduce"])
def test_chaos_failover_sigkill_active_with_standby(tmp_path, monkeypatch,
                                                    phase, matrix_corpus):
    """Round-18 acceptance: SIGKILL the ACTIVE daemon mid-{map,reduce}
    with a live --standby watching the same work root.  The standby
    steals the lease after the TTL, promotes through the resume path,
    and finishes the job byte-identical to a fault-free run with journal
    entries unique per (kind, task) across both daemon lives.  Workers
    ride a comma-separated address list: their polls rotate to the
    standby (which parks them with retry + retry_after_s) until the
    promotion, then resume work — no worker restart, no reconfiguration.
    Finally the old active REVIVES as a standby and demotes instead of
    split-braining."""
    monkeypatch.setenv("DGREP_RPC_RETRIES", "10")
    monkeypatch.setenv("DGREP_RPC_BACKOFF_S", "0.2")
    corpus = matrix_corpus
    work_root = tmp_path / "svc-root"
    work_root.mkdir()
    ha_env = {"DGREP_LEASE_TTL_S": "2", "DGREP_SERVICE_FUSE": "0"}
    active = service_proc.ServiceProc(work_root, workers=0,
                                      env=ha_env).start()
    standby = service_proc.ServiceProc(work_root, workers=0, env=ha_env,
                                       extra_args=["--standby"]).start()
    assert active.status().get("role") == "active"
    assert standby.status().get("role") == "standby"
    addrs = f"127.0.0.1:{active.port},127.0.0.1:{standby.port}"

    stop = threading.Event()

    def worker_main() -> None:
        # crash-replace loop on the ADDRESS LIST: a loop that dies in
        # the failover gap reattaches and its rotation finds whichever
        # daemon holds the lease
        while not stop.is_set():
            loop = WorkerLoop(
                ServiceHttpTransport(addrs, rpc_timeout_s=15.0), app=None
            )
            try:
                loop.run()
                return  # JOB_DONE: service shut down
            except Exception:  # noqa: BLE001 — worker died; replace it
                time.sleep(0.2)

    threads = [threading.Thread(target=worker_main, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    try:
        jid = active.submit(grep_config(
            corpus, pattern="hello", n_reduce=2, task_timeout_s=2.0,
            sweep_interval_s=0.2, work_dir=str(tmp_path / "sub"),
        ))
        # catch the kill phase mid-stream (same recipe as the matrix)
        deadline = time.monotonic() + 90
        while True:
            assert time.monotonic() < deadline, active.tail_log()
            try:
                st = active.job_status(jid)
            except OSError:
                time.sleep(0.05)
                continue
            m = st.get("map", {})
            if phase == "map":
                if m.get("completed", 0) >= 1:
                    break
            else:
                if m and m.get("completed") == m.get("total"):
                    break
            if st.get("state") == "done":
                break  # too fast to catch — failover still exercises resume
            time.sleep(0.03)
        active.sigkill()  # no teardown of any kind: the lease goes stale
        # the standby steals the lease after the TTL and promotes
        deadline = time.monotonic() + 60
        while True:
            assert time.monotonic() < deadline, standby.tail_log()
            try:
                if standby.status().get("role") == "active":
                    break
            except OSError:
                pass
            time.sleep(0.2)
        st = standby.wait_job(jid, timeout=150)
        assert st["state"] == "done", (st, standby.tail_log())
        outputs = standby.job_result(jid)["outputs"]

        # the old active revives pointed at the same work root: it must
        # DEMOTE to standby (the lease names a larger epoch), never
        # split-brain a second active
        active.extra_args = ["--standby"]
        active.start()
        assert active.status().get("role") == "standby", active.tail_log()
    finally:
        stop.set()
        monkeypatch.setenv("DGREP_RPC_RETRIES", "0")
        active.terminate()
        standby.terminate()
        for t in threads:
            t.join(timeout=10)

    key = ("hello", "posix")
    if key not in _ORACLE_CACHE:
        _ORACLE_CACHE[key] = outputs_by_name(run_job(
            grep_config(corpus, pattern="hello", n_reduce=2,
                        work_dir=str(tmp_path / "oracle")),
            n_workers=2,
        ).output_files)
    assert outputs_by_name(outputs) == _ORACLE_CACHE[key]
    # journal unique per (kind, task) across BOTH daemon lives
    entries = TaskJournal.replay(WorkDir(str(work_root / jid)).journal_path())
    seen = [(e["kind"], e["task_id"]) for e in entries]
    assert len(seen) == len(set(seen)), seen
    # round 19: the fleet timeline records exactly one steal+promotion
    # pair across both daemon lives — one failover happened, once (the
    # revived old active DEMOTED instead of stealing a third epoch)
    dl_events = DaemonLog.read(work_root)
    steals = [e for e in dl_events if e["kind"] == "lease_steal"]
    promotions = [e for e in dl_events if e["kind"] == "promoted"]
    assert len(steals) == 1 and len(promotions) == 1, \
        [(e["epoch"], e["kind"]) for e in dl_events]
    assert steals[0]["epoch"] == promotions[0]["epoch"]
    assert promotions[0]["payload"]["failover_s"] > 0


def test_chaos_failover_sigkill_active_mid_stream(tmp_path, monkeypatch):
    """Round-18 acceptance, streaming leg: SIGKILL the active while a
    standing query streams a live-append log.  The promoted standby
    resumes the follow job from its durable cursors; a subscriber
    continuing from its last cursor sees the union across both daemon
    lives equal the oracle — no duplicate seq, no lost line — including
    lines appended DURING the outage."""
    monkeypatch.setenv("DGREP_RPC_RETRIES", "10")
    monkeypatch.setenv("DGREP_RPC_BACKOFF_S", "0.2")
    work_root = tmp_path / "svc-root"
    work_root.mkdir()
    ha_env = {"DGREP_LEASE_TTL_S": "2", "DGREP_FOLLOW_POLL_S": "0.05"}
    active = service_proc.ServiceProc(work_root, workers=0,
                                      env=ha_env).start()
    standby = service_proc.ServiceProc(work_root, workers=0, env=ha_env,
                                       extra_args=["--standby"]).start()

    log_path = tmp_path / "app.log"
    log_path.write_bytes(b"hello 0\n")
    n_lines = {"n": 1}
    stop_append = threading.Event()

    def appender() -> None:
        # keeps appending straight through the kill and the outage
        while not stop_append.is_set():
            with open(log_path, "ab") as f:
                f.write(b"hello %d\n" % n_lines["n"])
            n_lines["n"] += 1
            time.sleep(0.02)

    cfg = JobConfig(
        input_files=[str(log_path)],
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": "hello", "backend": "cpu"},
        follow=True, follow_poll_s=0.05,
    )
    at = threading.Thread(target=appender, daemon=True)
    collected: list[dict] = []
    try:
        jid = active.submit(cfg)
        at.start()

        def read_page(proc, cursor: int) -> tuple[list[dict], int]:
            doc = service_proc._http_json(
                "GET",
                f"{proc.base}/jobs/{jid}/stream?cursor={cursor}&timeout=1",
                timeout=10.0,
            )
            assert "dropped" not in doc  # big default ring: nothing shed
            return doc["records"], doc["next"]

        cursor = 0
        deadline = time.monotonic() + 60
        while len(collected) < 10:  # streaming demonstrably live
            assert time.monotonic() < deadline, active.tail_log()
            recs, cursor = read_page(active, cursor)
            collected.extend(recs)
        active.sigkill()  # mid-stream, appender still running
        deadline = time.monotonic() + 60
        while True:
            assert time.monotonic() < deadline, standby.tail_log()
            try:
                if standby.status().get("role") == "active":
                    break
            except OSError:
                pass
            time.sleep(0.2)
        # outage lines + post-promotion lines keep flowing; stop the
        # appender, then drain until the stream catches the final line
        time.sleep(1.0)
        stop_append.set()
        at.join(timeout=10)
        total = n_lines["n"]
        deadline = time.monotonic() + 60
        while not collected or collected[-1]["line"] < total:
            assert time.monotonic() < deadline, (
                len(collected), total, standby.tail_log()
            )
            recs, cursor = read_page(standby, cursor)
            collected.extend(recs)
    finally:
        stop_append.set()
        monkeypatch.setenv("DGREP_RPC_RETRIES", "0")
        active.terminate()
        standby.terminate()

    # union across both lives == the one-shot oracle: every line, once
    assert [(r["line"], r["text"]) for r in collected] == [
        (i + 1, f"hello {i}") for i in range(total)
    ]
    seqs = [r["seq"] for r in collected]
    assert seqs == sorted(set(seqs))  # no duplicate, no regression

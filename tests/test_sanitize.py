"""Sanitizer matrix over the native tier (ISSUE 5 leg 2): the whole
libdgrep surface re-runs under ASan+UBSan and TSan builds — the only race
detection the C++ side has (the MT DFA scanner and the confirm pool are
pthread code reviewed by eyeball until now).

Each case builds the instrumented library (``make -C native sanitize`` /
``tsan``), then runs tests/_native_sanitize_driver.py in a SUBPROCESS with
the sanitizer runtime LD_PRELOADed (a sanitized DSO cannot be dlopen'd
into a plain process otherwise) and ``DGREP_NATIVE_LIB`` selecting the
build — the utils/native.py override this PR adds.  halt-on-error is on,
so any report is a nonzero exit; stderr is additionally screened for
report markers.

Standalone-runnable:  python -m pytest tests/ -q -m sanitize
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.sanitize

REPO = Path(__file__).resolve().parents[1]
NATIVE = REPO / "native"
DRIVER = Path(__file__).parent / "_native_sanitize_driver.py"

_REPORT_MARKERS = (
    "ERROR: AddressSanitizer",
    "runtime error:",  # UBSan
    "WARNING: ThreadSanitizer",
    "ERROR: LeakSanitizer",
)


def _cxx() -> str | None:
    return shutil.which(os.environ.get("CXX", "g++"))


def _runtime_so(name: str) -> str | None:
    """Path of the sanitizer runtime to LD_PRELOAD, via the compiler."""
    cxx = _cxx()
    if cxx is None:
        return None
    out = subprocess.run([cxx, f"-print-file-name={name}"],
                         capture_output=True, text=True).stdout.strip()
    return out if out and "/" in out and Path(out).exists() else None


def _build(target: str, lib: str) -> Path:
    if _cxx() is None or shutil.which("make") is None:
        pytest.skip("no C++ toolchain in this container")
    r = subprocess.run(["make", "-C", str(NATIVE), target],
                       capture_output=True, text=True, timeout=300)
    if r.returncode != 0:
        pytest.skip(f"make {target} failed:\n{r.stdout}\n{r.stderr}")
    return NATIVE / lib


@pytest.fixture(scope="session")
def asan_lib() -> Path:
    if _runtime_so("libasan.so") is None:
        pytest.skip("libasan runtime not found")
    return _build("sanitize", "libdgrep-asan.so")


@pytest.fixture(scope="session")
def tsan_lib() -> Path:
    if _runtime_so("libtsan.so") is None:
        pytest.skip("libtsan runtime not found")
    return _build("tsan", "libdgrep-tsan.so")


def _run_driver(lib: Path, preload: str, mode: str,
                extra_env: dict[str, str]) -> None:
    env = dict(
        os.environ,
        PYTHONPATH=str(REPO),
        LD_PRELOAD=preload,
        DGREP_NATIVE_LIB=str(lib),
        JAX_PLATFORMS="cpu",
        OPENBLAS_NUM_THREADS="1",  # uninstrumented BLAS pool: TSan noise
        **extra_env,
    )
    r = subprocess.run([sys.executable, str(DRIVER), mode],
                       capture_output=True, text=True, env=env, timeout=300)
    output = r.stdout + r.stderr
    assert r.returncode == 0, f"driver {mode} failed under {lib.name}:\n{output}"
    for marker in _REPORT_MARKERS:
        assert marker not in output, f"sanitizer report:\n{output}"
    assert f"{mode} ok" in r.stdout


_ASAN_ENV = {
    # leak detection off: CPython interns/arenas are not leaks; abort (not
    # exit) so a report can never be mistaken for a clean pass
    "ASAN_OPTIONS": "detect_leaks=0:abort_on_error=1",
    "UBSAN_OPTIONS": "halt_on_error=1:print_stacktrace=1",
}
_TSAN_ENV = {
    # report_thread_leaks off: daemon helper threads (the engine's reader
    # pool contract) are by-design never joined
    "TSAN_OPTIONS": "halt_on_error=1:report_thread_leaks=0:exitcode=66",
}


def test_asan_ubsan_surface(asan_lib):
    _run_driver(asan_lib, _runtime_so("libasan.so"), "surface", _ASAN_ENV)


def test_asan_ubsan_threaded_stress(asan_lib):
    _run_driver(asan_lib, _runtime_so("libasan.so"), "stress", _ASAN_ENV)


def test_tsan_surface(tsan_lib):
    _run_driver(tsan_lib, _runtime_so("libtsan.so"), "surface", _TSAN_ENV)


def test_tsan_threaded_stress(tsan_lib):
    """The pthread race matrix: concurrent scans sharing one DFA table and
    one ConfirmSet, each internally fanning out worker threads."""
    _run_driver(tsan_lib, _runtime_so("libtsan.so"), "stress", _TSAN_ENV)


def test_native_lib_override_bad_path_raises():
    """DGREP_NATIVE_LIB pointing nowhere must RAISE (subprocess: the load
    verdict is cached process-wide) — an explicit build selection that
    silently fell back to Python would make this whole matrix vacuous."""
    env = dict(os.environ, PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu",
               DGREP_NATIVE_LIB="/nonexistent/libdgrep-missing.so")
    r = subprocess.run(
        [sys.executable, "-c",
         "from distributed_grep_tpu.utils import native\n"
         "try:\n"
         "    native.native_available()\n"
         "except OSError:\n"
         "    print('RAISED')\n"],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert r.returncode == 0 and "RAISED" in r.stdout, r.stdout + r.stderr


def test_plain_build_still_default():
    """Without the override the ordinary libdgrep.so path stays in force
    (subprocess, again because of the process-wide cache)."""
    env = dict(os.environ, PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu")
    env.pop("DGREP_NATIVE_LIB", None)
    r = subprocess.run(
        [sys.executable, "-c",
         "from distributed_grep_tpu.utils import native\n"
         "print('AVAIL', native.native_available())\n"],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert r.returncode == 0, r.stderr

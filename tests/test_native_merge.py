"""Native columnar merge/print loops (round 6): byte-identity pins.

libdgrep's dgrep_gather_ranges / dgrep_format_batch / dgrep_merge_display
replace the three remaining per-record Python/numpy passes of the
match-dense output path.  Exactness story:

* gather_ranges: pure memcpy — pinned against the numpy cumsum gather.
* format_batch: copies slab bytes verbatim, which equals the Python
  path's decode('utf-8','replace') -> encode ONLY for strictly-valid
  UTF-8 slabs; invalid slabs must take the Python fallback (pinned both
  ways, plus the surrogateescape filename prefix round-trip).
* merge_display: must order by the DECODED path (surrogateescape
  codepoints) like the Python heapq merge — raw byte order diverges
  exactly where a valid multi-byte sequence meets an escaped raw byte —
  and must refuse (fall back) on any non-grep-shaped record.

The e2e test pins the whole route: a job's mr-out files and display
bytes with the native loops == with every native loop disabled, spill
path included.
"""

from __future__ import annotations

import numpy as np
import pytest

from distributed_grep_tpu.runtime import columnar
from distributed_grep_tpu.runtime.columnar import LineBatch
from distributed_grep_tpu.runtime.job import JobResult
from distributed_grep_tpu.utils import native

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="libdgrep unavailable"
)


def _py_gather(arr, starts, ends):
    lens = ends - starts
    offsets = np.zeros(starts.size + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    total = int(offsets[-1])
    if total == 0:
        return b"", offsets
    ne = np.flatnonzero(lens > 0)
    s, l = starts[ne], lens[ne]
    idx = np.ones(total, dtype=np.int64)
    idx[0] = s[0]
    if ne.size > 1:
        heads = offsets[ne[1:]]
        idx[heads] = s[1:] - (s[:-1] + l[:-1] - 1)
    src = np.cumsum(idx)
    return arr[src].tobytes(), offsets


def test_gather_ranges_native_vs_numpy():
    rng = np.random.default_rng(5)
    arr = rng.integers(0, 256, size=65536, dtype=np.uint8)
    starts = np.sort(rng.integers(0, 60000, size=300)).astype(np.int64)
    ends = np.minimum(starts + rng.integers(0, 200, size=300), 65536).astype(
        np.int64
    )
    ends[::7] = starts[::7]  # empty ranges interleaved
    slab, off = columnar.gather_ranges(arr, starts, ends)
    pslab, poff = _py_gather(arr, starts, ends)
    assert slab == pslab and np.array_equal(off, poff)


def _batch(filename, lines, linenos):
    offs = np.zeros(len(lines) + 1, dtype=np.int64)
    np.cumsum([len(ln) for ln in lines], out=offs[1:])
    return LineBatch(
        filename=filename,
        linenos=np.asarray(linenos, dtype=np.int64),
        offsets=offs,
        slab=b"".join(lines),
    )


@pytest.mark.parametrize("filename", [
    "plain.txt",
    "dir/uni-é中.txt",          # multi-byte UTF-8 name
    "raw-\udc80\udcff.bin",              # surrogateescaped raw bytes
])
def test_format_batch_byte_identical(filename):
    b = _batch(filename,
               [b"hello", b"w\xc3\xb6rld", b"", b"a\tb", b"x" * 300],
               [1, 9, 42, 4567, 10 ** 14])
    assert b.format_lines_bytes() == b.format_lines().encode(
        "utf-8", "surrogateescape"
    )


def test_format_batch_invalid_utf8_falls_back_identically():
    # lone continuation, truncated sequence, surrogate encoding, 0xFF —
    # all force the Python utf-8/replace path; output must still equal it
    b = _batch("f", [b"a\x80b", b"\xe2\x82", b"\xed\xa0\x80", b"\xff"],
               [1, 2, 3, 4])
    want = b.format_lines().encode("utf-8", "surrogateescape")
    assert b.format_lines_bytes() == want
    assert b"\xef\xbf\xbd" in want  # the replacement char actually appears


def test_format_batch_per_line_validation_not_whole_slab():
    # round-6 review repro: two individually-invalid lines whose bytes
    # CONCATENATE into valid UTF-8 ('abc\xC3' + '\xA9def' == 'abcédef').
    # The Python path decodes PER LINE (each gets a U+FFFD); whole-slab
    # validation would copy the raw bytes and break byte-identity.
    b = _batch("f", [b"abc\xc3", b"\xa9def"], [1, 2])
    want = b.format_lines().encode("utf-8", "surrogateescape")
    assert b.format_lines_bytes() == want
    assert want.count(b"\xef\xbf\xbd") == 2


def test_format_batch_empty():
    b = _batch("f", [], [])
    assert b.format_lines_bytes() == b"" == b.format_lines().encode()


def _mr_out(recs):
    return b"".join(k + b"\t" + v + b"\n" for k, v in recs)


def _oracle_merge(tmp_path, bufs):
    files = []
    for i, buf in enumerate(bufs):
        p = tmp_path / f"mr-out-{i}"
        p.write_bytes(buf)
        files.append(p)
    res = JobResult(output_files=files, fileline_sorted=True)
    return b"".join(res.iter_display_bytes_sorted())


def test_merge_display_multi_file_and_surrogate_order(tmp_path):
    # '\xc3\xa9' (e-acute, U+00E9) vs raw '\x80' (U+DC80 decoded): byte
    # order says 0x80 < 0xC3, codepoint order says U+00E9 < U+DC80 — the
    # native merge must take the codepoint side, like the Python merge.
    bufs = [
        _mr_out([(b"a.txt (line number #1)", b"x"),
                 (b"a.txt (line number #10)", b"y"),
                 (b"\xc3\xa9.txt (line number #2)", b"acc")]),
        _mr_out([(b"a.txt (line number #2)", b"z"),
                 (b"\x80.txt (line number #1)", b"raw")]),
        b"",
        b"\n",
    ]
    got = native.merge_display(bufs)
    assert got is not None and got == _oracle_merge(tmp_path, bufs)


def test_merge_display_tab_and_notab_values(tmp_path):
    bufs = [
        _mr_out([(b"f (line number #1)", b"v\twith\ttabs"),
                 (b"f (line number #3)", b"")]),
        # record without a '\t' at all (key-only line)
        b"f (line number #2)\n",
    ]
    got = native.merge_display(bufs)
    assert got is not None and got == _oracle_merge(tmp_path, bufs)


def test_merge_display_byte_prefix_is_not_codepoint_prefix(tmp_path):
    # round-6 review repro: b'foo\xC3' decodes to 'foo\udcc3' (U+DCC3)
    # and must sort AFTER b'foo\xC3\xA9' ('fooé', U+00E9) — the naive
    # "shorter byte-prefix first" rule returns the reverse.
    bufs = [
        _mr_out([(b"foo\xc3 (line number #1)", b"short")]),
        _mr_out([(b"foo\xc3\xa9 (line number #1)", b"long")]),
    ]
    got = native.merge_display(bufs)
    assert got is not None and got == _oracle_merge(tmp_path, bufs)
    assert got.index(b"long") < got.index(b"short")


def test_merge_display_unterminated_final_line(tmp_path):
    # output gains a '\n' the input lacked — the capacity math must allow
    # it (len(data) + n_bufs), and bytes must equal the Python merge
    bufs = [b"f (line number #2)\tv\nf (line number #10)\tw"]
    got = native.merge_display(bufs)
    assert got is not None and got == _oracle_merge(tmp_path, bufs)
    assert len(got) == len(bufs[0]) + 1


def test_merge_display_refuses_foreign_records():
    ok = _mr_out([(b"f (line number #1)", b"v")])
    assert native.merge_display([ok, b"wordcount-key\t3\n"]) is None
    assert native.merge_display([b"f (line number #x)\tv\n"]) is None
    assert native.merge_display([b"f (line number #)\tv\n"]) is None
    # 20-digit line number: int64 overflow guard -> Python fallback
    assert native.merge_display(
        [b"f (line number #99999999999999999999)\tv\n"]
    ) is None


def test_display_blocks_sorted_native_equals_fallbacks(tmp_path, monkeypatch):
    rng = np.random.default_rng(9)
    bufs = []
    for i in range(4):
        linenos = np.sort(rng.choice(10 ** 6, size=500, replace=False)) + 1
        recs = [(b"big.txt (line number #%d)" % n,
                 b"line-%d" % n) for n in linenos]
        bufs.append(_mr_out(recs))
    files = []
    for i, buf in enumerate(bufs):
        p = tmp_path / f"mr-out-{i}"
        p.write_bytes(buf)
        files.append(p)
    res = JobResult(output_files=files, fileline_sorted=True)
    got_native = b"".join(res.display_blocks_sorted())
    monkeypatch.setattr(
        "distributed_grep_tpu.utils.native.merge_display", lambda bufs: None
    )
    got_vector = b"".join(res.display_blocks_sorted())  # round-5 numpy pass
    got_stream = b"".join(res.iter_display_bytes_sorted())
    assert got_native == got_vector == got_stream


def test_job_output_native_vs_python_paths_with_spill(tmp_path, monkeypatch):
    """E2E: mr-out files AND display bytes are byte-identical with the
    native loops on vs all off — spill path included (2 MB reduce cap
    forces IdentityCollator spill runs)."""
    from distributed_grep_tpu.runtime.job import run_job
    from distributed_grep_tpu.utils.config import JobConfig

    rng = np.random.default_rng(21)
    data = rng.integers(32, 127, size=6 << 20, dtype=np.uint8)
    data[rng.integers(0, data.size, size=data.size // 60)] = 0x0A
    needle = np.frombuffer(b"the", np.uint8)
    for p in rng.integers(0, data.size - 8, size=30000):
        data[p : p + 3] = needle
    # some non-UTF-8 line content too: the formatter must fall back there
    for p in rng.integers(0, data.size - 8, size=500):
        data[p] = 0xFF
    src = tmp_path / "corpus.bin"
    src.write_bytes(data.tobytes())

    def run(tag):
        wd = tmp_path / f"job-{tag}"
        cfg = JobConfig(
            application="distributed_grep_tpu.apps.grep_tpu",
            input_files=[str(src)],
            work_dir=str(wd), n_reduce=4, journal=False,
            reduce_memory_bytes=128 << 10,  # force spill runs
            app_options={"pattern": "the", "backend": "cpu"},
        )
        res = run_job(cfg, n_workers=2)
        outs = {p.name: p.read_bytes() for p in res.output_files}
        disp = b"".join(res.display_blocks_sorted())
        return outs, disp, res.metrics

    outs_native, disp_native, m = run("native")
    assert m["counters"].get("reduce_spills", 0) > 0, "spill did not engage"

    monkeypatch.setattr(
        "distributed_grep_tpu.utils.native.gather_ranges_native",
        lambda *a, **k: None,
    )
    monkeypatch.setattr(
        "distributed_grep_tpu.utils.native.format_batch",
        lambda *a, **k: None,
    )
    monkeypatch.setattr(
        "distributed_grep_tpu.utils.native.merge_display", lambda bufs: None
    )
    outs_py, disp_py, _ = run("python")
    assert outs_native == outs_py
    assert disp_native == disp_py

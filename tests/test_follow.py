"""Streaming tier (round 17, runtime/follow.py): suffix-boundary
exactness vs the one-shot oracle across kernel families, durable-cursor
restart resume (no duplicate / no lost line), bounded-stream shed, the
service subscription surface, and the stale-prune pin.

Standalone: ``python -m pytest tests/test_follow.py -q`` (CPU-only).
Marker: ``follow``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from distributed_grep_tpu.ops.engine import GrepEngine
from distributed_grep_tpu.runtime.follow import (
    FollowLog,
    FollowRunner,
    FollowScanner,
    StreamRing,
    follow_counters,
)
from distributed_grep_tpu.utils.config import JobConfig

pytestmark = pytest.mark.follow


@pytest.fixture(autouse=True)
def _no_calibrate(monkeypatch):
    monkeypatch.setenv("DGREP_NO_CALIBRATE", "1")


# ---------------------------------------------------------------- oracle
def _oracle(engine_kw: dict, data: bytes) -> list[tuple[int, bytes]]:
    """(line_no, line_bytes) a ONE-SHOT scan of the final file state
    selects — the exactness contract every streamed emission must equal."""
    from distributed_grep_tpu.ops import lines as lines_mod

    eng = GrepEngine(**engine_kw)
    res = eng.scan(data)
    nl = lines_mod.newline_index(data)
    out = []
    for ln in res.matched_lines.tolist():
        s, e = lines_mod.line_span(nl, int(ln), len(data))
        out.append((int(ln), data[s:e]))  # span end excludes the newline
    return out


def _streamed(groups_log: list) -> list[tuple[int, bytes]]:
    out = []
    for _path, records, _cur in groups_log:
        for rec in records:
            if "text" in rec:
                out.append((
                    rec["line"],
                    rec["text"].encode("utf-8", "surrogateescape"),
                ))
    return out


# Append stages exercising every boundary shape the issue names: catch-up
# over existing content, an append SPLITTING a line mid-byte, the append
# completing it (plus whole lines), an append of exactly one line, an
# empty append, and an unterminated tail (finalize).
STAGES = [
    b"hello start\nhallo there\nmiss\n",
    b"partial hel",
    b"lo end\nab zz q volcano needle\n",
    b"hello exactly one helloo line\n",
    b"",
    b"\nends with hello\n",
    b"tail hello no newline",
]


def _fdr_patterns() -> list[str]:
    rng = np.random.default_rng(3)
    pats = {"hello", "volcano", "needle"}
    while len(pats) < 50:
        k = int(rng.integers(4, 9))
        pats.add("".join(chr(c) for c in rng.integers(97, 123, size=k)))
    return sorted(pats)


FAMILIES = [
    ("shift_and", dict(pattern="hello")),
    ("nfa", dict(pattern="h[ae]llo+")),
    ("anchor_start", dict(pattern="^hello")),
    ("anchor_end", dict(pattern="hello$")),
    ("empty_line", dict(pattern="^$")),
    ("pairset", dict(patterns=["ab", "zz", "q"])),
    ("fdr", dict(patterns=_fdr_patterns())),
    ("cpu_native", dict(pattern="hello", backend="cpu")),
    ("cpu_set", dict(patterns=["hello", "needle"], backend="cpu")),
    ("re_fallback", dict(pattern="hello(?! tail)")),
]


@pytest.mark.parametrize("label,kw", FAMILIES, ids=[f[0] for f in FAMILIES])
def test_suffix_exactness_across_families(tmp_path, label, kw):
    """Append boundary exactness: streamed emissions across every wake ==
    the one-shot oracle over the final file bytes, per kernel family."""
    kw = dict(kw)
    if kw.get("backend") != "cpu":
        kw["interpret"] = True  # CI: Pallas interpret IS the device path
    eng = GrepEngine(**kw)
    path = tmp_path / "grow.log"
    path.write_bytes(b"")
    scanner = FollowScanner(eng, [str(path)])
    groups_log: list = []
    for stage in STAGES:
        with open(path, "ab") as f:
            f.write(stage)
        groups_log.extend(scanner.poll_once())
    groups_log.extend(scanner.poll_once(final=True))
    final = b"".join(STAGES)
    assert _streamed(groups_log) == _oracle(kw, final)


def test_line_carry_is_not_emitted_early(tmp_path):
    """The partial tail line never emits before its newline arrives —
    even when the prefix already matches (a half-written 'hello' line
    must not stream, then duplicate once completed)."""
    eng = GrepEngine("hello", backend="cpu")
    path = tmp_path / "carry.log"
    path.write_bytes(b"hello whole\n")
    sc = FollowScanner(eng, [str(path)])
    g1 = sc.poll_once()
    assert [r["line"] for _p, rs, _c in g1 for r in rs] == [1]
    with open(path, "ab") as f:
        f.write(b"hello partial")  # matches already, but incomplete
    assert sc.poll_once() == []  # no complete line: no wake output
    with open(path, "ab") as f:
        f.write(b" now complete\nx\n")
    g2 = sc.poll_once()
    assert [(r["line"], r["text"]) for _p, rs, _c in g2 for r in rs] == [
        (2, "hello partial now complete")
    ]


def test_truncation_and_replacement_full_rescan(tmp_path):
    """Validator-tuple drift: size below the cursor (truncate) and a new
    inode (cp + mv replacement) both reset the cursor — a ``reset``
    record, then emissions byte-identical to a one-shot scan of the NEW
    content."""
    eng = GrepEngine("hello", backend="cpu")
    path = tmp_path / "trunc.log"
    path.write_bytes(b"hello a\nhello b\nhello c\n")
    sc = FollowScanner(eng, [str(path)])
    assert len(_streamed(sc.poll_once())) == 3
    # truncate to SHORTER content (size below the cursor is the signal;
    # an in-place rewrite that grows is indistinguishable from an append
    # by stat alone — the tail -f blind spot, shared deliberately)
    new1 = b"hello cut\nmiss\n"
    path.write_bytes(new1)
    groups = sc.poll_once()
    recs = [r for _p, rs, _c in groups for r in rs]
    assert recs[0] == {"file": str(path), "reset": True}
    assert _streamed(groups) == _oracle(dict(pattern="hello", backend="cpu"),
                                        new1)
    # atomic replacement: same size, fresh inode
    repl = tmp_path / "repl.tmp"
    repl.write_bytes(b"hello replaced content\n")
    os.replace(repl, path)
    groups = sc.poll_once()
    recs = [r for _p, rs, _c in groups for r in rs]
    assert recs[0] == {"file": str(path), "reset": True}
    assert _streamed(groups) == [(1, b"hello replaced content")]


def test_missing_then_created_file(tmp_path):
    """A standing query over a log that does not exist yet (tail -F):
    the cursor waits; creation is just the first growth."""
    eng = GrepEngine("hello", backend="cpu")
    path = tmp_path / "later.log"
    sc = FollowScanner(eng, [str(path)])
    assert sc.poll_once() == []
    path.write_bytes(b"hello now\n")
    assert _streamed(sc.poll_once()) == [(1, b"hello now")]


def test_invert_complement_matches_oracle(tmp_path):
    eng = GrepEngine("hello", backend="cpu")
    path = tmp_path / "inv.log"
    path.write_bytes(b"")
    sc = FollowScanner(eng, [str(path)], invert=True)
    groups_log: list = []
    for stage in STAGES:
        with open(path, "ab") as f:
            f.write(stage)
        groups_log.extend(sc.poll_once())
    groups_log.extend(sc.poll_once(final=True))
    final = b"".join(STAGES)
    from distributed_grep_tpu.ops import lines as lines_mod

    matched = {ln for ln, _ in _oracle(dict(pattern="hello", backend="cpu"),
                                       final)}
    n_lines = lines_mod.count_lines(final)
    want = [ln for ln in range(1, n_lines + 1) if ln not in matched]
    assert [ln for ln, _ in _streamed(groups_log)] == want


def test_count_only_never_materializes_lines(tmp_path):
    """-c standing queries: records carry per-wake count deltas only —
    the match-dense worst case is a bandwidth-bound counter update."""
    eng = GrepEngine("hello", backend="cpu")
    path = tmp_path / "dense.log"
    path.write_bytes(b"hello\n" * 1000)
    sc = FollowScanner(eng, [str(path)], count_only=True)
    groups = sc.poll_once()
    recs = [r for _p, rs, _c in groups for r in rs]
    assert recs == [{"file": str(path), "count": 1000}]
    with open(path, "ab") as f:
        f.write(b"hello\n" * 500 + b"miss\n")
    groups = sc.poll_once()
    recs = [r for _p, rs, _c in groups for r in rs]
    assert recs == [{"file": str(path), "count": 500}]
    assert all("text" not in r and "line" not in r for r in recs)
    assert sc.poll_once() == []  # nothing new: no wake output
    assert sc.cursors[str(path)].emitted == 1500


def test_presence_only_stops_after_first_match(tmp_path):
    eng = GrepEngine("hello", backend="cpu")
    path = tmp_path / "q.log"
    path.write_bytes(b"miss\nhello yes\nhello more\n")
    sc = FollowScanner(eng, [str(path)], count_only=True,
                       presence_only=True)
    groups = sc.poll_once()
    recs = [r for _p, rs, _c in groups for r in rs]
    assert recs == [{"file": str(path), "match": True}]
    with open(path, "ab") as f:
        f.write(b"hello again\n")
    assert sc.poll_once() == []  # settled: no further scans/emits


def test_giant_line_larger_than_wake_cap_does_not_stall(tmp_path,
                                                        monkeypatch):
    """A single line larger than the per-wake read cap must not stall
    the cursor: the suffix read extends until a newline lands, and the
    streamed set still equals the one-shot oracle."""
    from distributed_grep_tpu.runtime import follow as follow_mod

    monkeypatch.setattr(follow_mod, "MAX_WAKE_BYTES", 64)
    eng = GrepEngine("hello", backend="cpu")
    path = tmp_path / "giant.log"
    giant = b"hello " + b"x" * 300  # one 306-byte line vs a 64-byte cap
    path.write_bytes(giant + b"\nhello after\n")
    sc = FollowScanner(eng, [str(path)])
    groups = sc.poll_once()
    assert _streamed(groups) == [(1, giant), (2, b"hello after")]
    # newline-free growth past the cap stays a carry (no emit) ...
    with open(path, "ab") as f:
        f.write(b"hello " + b"y" * 200)
    assert sc.poll_once() == []
    # ... until its newline arrives
    with open(path, "ab") as f:
        f.write(b"tail\n")
    assert _streamed(sc.poll_once()) == [(3, b"hello " + b"y" * 200 + b"tail")]


def test_unterminated_tail_not_rescanned_until_growth(tmp_path,
                                                      monkeypatch):
    """The carry is re-read once after the wake that consumed up to it;
    further no-growth wakes skip the disk entirely (the ``seen`` size
    gate) and the next append still scans exactly."""
    eng = GrepEngine("hello", backend="cpu")
    path = tmp_path / "tail.log"
    path.write_bytes(b"hello a\npartial hel")
    sc = FollowScanner(eng, [str(path)])
    calls = []
    real = eng.scan_file_suffix

    def spy(p, offset, **kw):
        calls.append(offset)
        return real(p, offset, **kw)

    monkeypatch.setattr(eng, "scan_file_suffix", spy)
    assert len(_streamed(sc.poll_once())) == 1  # consumes "hello a\n"
    sc.poll_once()  # tail re-read once: no progress, size remembered
    n = len(calls)
    for _ in range(5):
        assert sc.poll_once() == []
    assert len(calls) == n  # no-growth wakes never hit the disk
    with open(path, "ab") as f:
        f.write(b"lo\n")
    assert _streamed(sc.poll_once()) == [(2, b"partial hello")]


def test_one_bad_file_does_not_discard_other_groups(tmp_path,
                                                    monkeypatch):
    """Per-file fault isolation: a transient read error on one file must
    not lose the other files' already-scanned lines, and the failed
    file's cursor stays put for the next wake."""
    eng = GrepEngine("hello", backend="cpu")
    pa, pb = tmp_path / "a.log", tmp_path / "b.log"
    pa.write_bytes(b"hello A\n")
    pb.write_bytes(b"hello B\n")
    sc = FollowScanner(eng, [str(pa), str(pb)])
    real = eng.scan_file_suffix
    boom = {str(pb)}

    def flaky(p, offset, **kw):
        if str(p) in boom:
            raise OSError("transient")
        return real(p, offset, **kw)

    monkeypatch.setattr(eng, "scan_file_suffix", flaky)
    groups = sc.poll_once()
    assert _streamed(groups) == [(1, b"hello A")]
    assert sc.cursors[str(pb)].offset == 0  # untouched, retried next wake
    boom.clear()
    assert _streamed(sc.poll_once()) == [(1, b"hello B")]


# --------------------------------------------------------- durability
def _mk_cfg(path: str, work_dir: str, **opts) -> JobConfig:
    return JobConfig(
        input_files=[path],
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": "hello", "backend": "cpu", **opts},
        work_dir=work_dir,
        follow=True,
    )


def test_runner_restart_resumes_cursors_no_dup_no_loss(tmp_path):
    """FollowRunner crash/restart (in-process): a second runner over the
    same workdir resumes from the journaled cursors — the union of
    records streamed across both lives equals the oracle exactly, with
    no duplicate and no lost line, and sequence numbers continue."""
    log_path = tmp_path / "app.log"
    log_path.write_bytes(b"hello one\nmiss\n")
    cfg = _mk_cfg(str(log_path), str(tmp_path / "wd"))
    r1 = FollowRunner("job-t", cfg, tmp_path / "wd")
    assert r1.wake_once() == 1
    with open(log_path, "ab") as f:
        f.write(b"hello two\n")
    assert r1.wake_once() == 1
    recs1, _n1, _d1 = r1.ring.read_since(0, timeout=0)
    # simulate a crash: NO close — the fsync'd journal is all that survives
    del r1
    with open(log_path, "ab") as f:
        f.write(b"hello three\nhello four\n")
    r2 = FollowRunner("job-t", cfg, tmp_path / "wd")
    assert r2.resumed
    assert r2.wake_once() == 2
    recs2, _n2, _d2 = r2.ring.read_since(recs1[-1]["seq"], timeout=0)
    seen = recs1 + recs2
    assert [(r["line"], r["text"]) for r in seen] == [
        (1, "hello one"), (3, "hello two"),
        (4, "hello three"), (5, "hello four"),
    ]
    seqs = [r["seq"] for r in seen]
    assert seqs == sorted(set(seqs))  # continuous, no duplicate seq
    r2.close()


def test_follow_log_replay_tolerates_torn_tail(tmp_path):
    """A wake line torn by a crash mid-fsync neither advances the cursor
    nor replays its records (journal-before-publish: nobody ever saw
    them) — the next runner re-scans and re-emits exactly once."""
    log_path = tmp_path / "app.log"
    log_path.write_bytes(b"hello a\nhello b\n")
    cfg = _mk_cfg(str(log_path), str(tmp_path / "wd"))
    r1 = FollowRunner("job-t", cfg, tmp_path / "wd")
    r1.wake_once()
    # tear the last journal line (crash mid-append)
    jp = tmp_path / "wd" / FollowLog.FILENAME
    raw = jp.read_bytes()
    jp.write_bytes(raw[: len(raw) - 9])  # chop inside the last record
    del r1
    r2 = FollowRunner("job-t", cfg, tmp_path / "wd")
    assert not r2.resumed  # the only wake line tore: fresh cursors
    assert r2.wake_once() == 2
    recs, _n, _d = r2.ring.read_since(0, timeout=0)
    assert [(r["line"], r["text"]) for r in recs] == [
        (1, "hello a"), (2, "hello b"),
    ]
    r2.close()


def test_journal_failure_rolls_cursor_back_no_lost_line(tmp_path,
                                                        monkeypatch):
    """A journal write failing mid-wake (disk-full blip) must not lose
    lines LIVE: the un-journaled groups' cursors roll back, nothing was
    published for them, and the next healthy wake re-emits exactly."""
    log_path = tmp_path / "app.log"
    log_path.write_bytes(b"hello one\nhello two\n")
    cfg = _mk_cfg(str(log_path), str(tmp_path / "wd"))
    r = FollowRunner("job-j", cfg, tmp_path / "wd")
    orig = r._log.record_wake

    def failing(*a, **kw):
        raise OSError("disk full")

    monkeypatch.setattr(r._log, "record_wake", failing)
    with pytest.raises(OSError):
        r.wake_once()
    recs, _n, _d = r.ring.read_since(0, timeout=0)
    assert recs == []  # nothing published for the failed journal line
    monkeypatch.setattr(r._log, "record_wake", orig)
    assert r.wake_once() == 2  # cursor rolled back: the wake re-emits
    recs, _n, _d = r.ring.read_since(0, timeout=0)
    assert [(x["line"], x["text"]) for x in recs] == [
        (1, "hello one"), (2, "hello two"),
    ]
    r.close()


def test_journal_landed_but_fsync_failed_retry_no_dup_seq(tmp_path,
                                                          monkeypatch):
    """The write-succeeded/fsync-failed variant: the rollback makes the
    retried wake re-journal the SAME records under the SAME seq0 — two
    identical wake lines on disk.  Replay dedups by seq (first wins), so
    a restarted ring keeps the contiguous-seq invariant and subscribers
    see each line exactly once."""
    log_path = tmp_path / "app.log"
    log_path.write_bytes(b"hello a\nhello b\n")
    cfg = _mk_cfg(str(log_path), str(tmp_path / "wd"))
    r1 = FollowRunner("job-f", cfg, tmp_path / "wd")
    orig = r1._log.record_wake

    def landed_then_failed(*a, **kw):
        orig(*a, **kw)  # the line IS durable ...
        raise OSError("fsync failed")  # ... but the caller must assume not

    monkeypatch.setattr(r1._log, "record_wake", landed_then_failed)
    with pytest.raises(OSError):
        r1.wake_once()
    monkeypatch.setattr(r1._log, "record_wake", orig)
    assert r1.wake_once() == 2  # retry re-journals the same seq0
    del r1
    r2 = FollowRunner("job-f", cfg, tmp_path / "wd")
    recs, _n, _d = r2.ring.read_since(0, timeout=0)
    assert [(x["seq"], x["line"], x["text"]) for x in recs] == [
        (1, 1, "hello a"), (2, 2, "hello b"),
    ]
    r2.close()


def test_torn_journal_line_reopens_before_next_append(tmp_path):
    """A failed wake may leave a torn line mid-file; the next wake must
    reopen the log (truncating the fragment) instead of gluing onto it —
    otherwise replay discards every later line."""
    log_path = tmp_path / "app.log"
    log_path.write_bytes(b"hello a\n")
    cfg = _mk_cfg(str(log_path), str(tmp_path / "wd"))
    r1 = FollowRunner("job-g", cfg, tmp_path / "wd")
    assert r1.wake_once() == 1
    # simulate the torn write the failure path leaves behind
    with open(tmp_path / "wd" / FollowLog.FILENAME, "ab") as f:
        f.write(b'{"kind": "wa')
    r1._log_dirty = True
    with open(log_path, "ab") as f:
        f.write(b"hello b\n")
    assert r1.wake_once() == 1  # reopen truncated the fragment first
    del r1
    r2 = FollowRunner("job-g", cfg, tmp_path / "wd")
    assert r2.resumed
    recs, _n, _d = r2.ring.read_since(0, timeout=0)
    assert [(x["line"], x["text"]) for x in recs] == [
        (1, "hello a"), (2, "hello b"),
    ]
    r2.close()


def test_follow_log_compaction_bounds_disk_and_replay(tmp_path,
                                                      monkeypatch):
    """A long-streaming standing query's wake log compacts at restart:
    disk shrinks to the bounded snapshot, replay memory is capped by
    REPLAY_TAIL_RECORDS, and cursors/seqs/records survive exactly —
    including across a post-compaction append and ANOTHER restart."""
    monkeypatch.setattr(FollowLog, "COMPACT_BYTES", 256)
    monkeypatch.setattr(FollowLog, "REPLAY_TAIL_RECORDS", 4)
    log_path = tmp_path / "app.log"
    log_path.write_bytes(b"")
    cfg = _mk_cfg(str(log_path), str(tmp_path / "wd"))
    r1 = FollowRunner("job-c", cfg, tmp_path / "wd")
    for i in range(10):
        with open(log_path, "ab") as f:
            f.write(b"hello %d\n" % i)
        assert r1.wake_once() == 1
    jp = tmp_path / "wd" / FollowLog.FILENAME
    big = jp.stat().st_size
    assert big > 256
    del r1
    r2 = FollowRunner("job-c", cfg, tmp_path / "wd")  # compacts at init
    assert jp.stat().st_size < big
    assert r2.resumed
    # only the bounded tail is preserved; the reader learns what it lost
    recs, _n, dropped = r2.ring.read_since(0, timeout=0)
    assert dropped == 6 and [x["seq"] for x in recs] == [7, 8, 9, 10]
    # the cursor survived compaction: a new append scans from line 11
    with open(log_path, "ab") as f:
        f.write(b"hello post\n")
    assert r2.wake_once() == 1
    del r2
    r3 = FollowRunner("job-c", cfg, tmp_path / "wd")  # replay compacted+appended
    recs3, _n3, d3 = r3.ring.read_since(7, timeout=0)  # tail cap keeps 8..11
    assert d3 == 0
    assert [(x["seq"], x["line"], x["text"]) for x in recs3] == [
        (8, 8, "hello 7"), (9, 9, "hello 8"),
        (10, 10, "hello 9"), (11, 11, "hello post"),
    ]
    r3.close()


# ------------------------------------------------------------ streaming
def test_stream_ring_sheds_oldest_with_dropped_count():
    ring = StreamRing(cap_bytes=600)
    for i in range(50):
        ring.publish([{"file": "f", "line": i + 1, "text": "x" * 40}])
    recs, nxt, dropped = ring.read_since(0, timeout=0)
    assert recs, "tail must survive"
    first = recs[0]["seq"]
    assert first > 1 and dropped == first - 1  # explicit shed count
    assert nxt == recs[-1]["seq"] == 50
    # a keeping-up consumer sees no drop marker
    recs2, _nxt2, dropped2 = ring.read_since(first, timeout=0)
    assert dropped2 == 0
    assert follow_counters()["stream_dropped_records"] == dropped


def test_stream_ring_longpoll_wakes_on_publish():
    ring = StreamRing(cap_bytes=1 << 20)
    got: list = []

    def reader():
        recs, _n, _d = ring.read_since(0, timeout=5.0)
        got.extend(recs)

    t = threading.Thread(target=reader)
    t.start()
    time.sleep(0.1)
    ring.publish([{"file": "f", "line": 1, "text": "hello"}])
    t.join(timeout=5.0)
    assert [r["seq"] for r in got] == [1]


# ------------------------------------------------------------- service
@pytest.fixture()
def follow_service(tmp_path, monkeypatch):
    monkeypatch.setenv("DGREP_FOLLOW_POLL_S", "0.05")
    from distributed_grep_tpu.runtime.service import GrepService, ServiceServer

    svc = GrepService(work_root=tmp_path / "svc")
    srv = ServiceServer(svc)
    srv.start()
    yield svc, srv, tmp_path
    srv.shutdown()
    svc.stop()


def _http(method: str, url: str, body: bytes | None = None,
          timeout: float = 10.0) -> dict:
    import urllib.request

    req = urllib.request.Request(url, data=body, method=method)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def test_service_follow_stream_and_status(follow_service):
    svc, srv, tmp_path = follow_service
    base = f"http://127.0.0.1:{srv.port}"
    # follow-off wire pin: no standing queries, no "follow" key anywhere
    assert "follow" not in _http("GET", f"{base}/status")
    log_path = tmp_path / "app.log"
    log_path.write_bytes(b"hello one\nmiss\n")
    cfg = _mk_cfg(str(log_path), "ignored")
    jid = _http("POST", f"{base}/jobs",
                cfg.to_json().encode("utf-8"))["job_id"]
    r = _http("GET", f"{base}/jobs/{jid}/stream?cursor=0&timeout=5")
    assert [(x["line"], x["text"]) for x in r["records"]] == [(1, "hello one")]
    with open(log_path, "ab") as f:
        f.write(b"hello two\n")
    r2 = _http("GET",
               f"{base}/jobs/{jid}/stream?cursor={r['next']}&timeout=5")
    assert [(x["line"], x["text"]) for x in r2["records"]] == [
        (3, "hello two")
    ]
    st = _http("GET", f"{base}/status")
    assert st["follow"]["standing"] == 1 and st["follow"]["follow_wakes"] >= 1
    js = _http("GET", f"{base}/jobs/{jid}")
    assert js["follow"]["wakes"] >= 1 and js["state"] == "running"
    # /stream on a batch job answers 409
    import urllib.error

    plain = tmp_path / "plain.txt"
    plain.write_text("hello\n")
    bcfg = JobConfig(input_files=[str(plain)],
                     application="distributed_grep_tpu.apps.grep_tpu",
                     app_options={"pattern": "hello", "backend": "cpu"})
    bjid = _http("POST", f"{base}/jobs",
                 bcfg.to_json().encode("utf-8"))["job_id"]
    with pytest.raises(urllib.error.HTTPError) as ei:
        _http("GET", f"{base}/jobs/{bjid}/stream?cursor=0&timeout=0")
    assert ei.value.code == 409
    # cancel the standing query: stream answers drain + terminal state
    _http("POST", f"{base}/jobs/{jid}/cancel", b"")
    r3 = _http("GET",
               f"{base}/jobs/{jid}/stream?cursor={r['next']}&timeout=0")
    assert r3["state"] == "cancelled"


def test_service_follow_validation(follow_service):
    import urllib.error

    svc, srv, tmp_path = follow_service
    base = f"http://127.0.0.1:{srv.port}"
    log_path = tmp_path / "v.log"
    log_path.write_bytes(b"x\n")
    bad = _mk_cfg(str(log_path), "ignored", word_regexp=True)
    with pytest.raises(urllib.error.HTTPError) as ei:
        _http("POST", f"{base}/jobs", bad.to_json().encode("utf-8"))
    assert ei.value.code == 400
    no_pat = JobConfig(input_files=[str(log_path)],
                       application="distributed_grep_tpu.apps.grep_tpu",
                       app_options={"backend": "cpu"}, follow=True)
    with pytest.raises(urllib.error.HTTPError) as ei:
        _http("POST", f"{base}/jobs", no_pat.to_json().encode("utf-8"))
    assert ei.value.code == 400


def test_stream_on_queued_follow_job_answers_empty_page(tmp_path,
                                                        monkeypatch):
    """A follow job parked in the admission queue has no runner yet:
    /stream answers an empty page with state "queued" (the subscriber
    polls again), never the misleading non-follow 409."""
    monkeypatch.setenv("DGREP_FOLLOW_POLL_S", "0.05")
    from distributed_grep_tpu.runtime.service import GrepService

    svc = GrepService(work_root=tmp_path / "svc", max_jobs=1)
    try:
        log_path = tmp_path / "q.log"
        log_path.write_bytes(b"hello\n")
        first = svc.submit(_mk_cfg(str(log_path), "ignored"))
        queued = svc.submit(_mk_cfg(str(log_path), "ignored"))
        page = svc.job_stream(queued, cursor=0, timeout=0)
        assert page["records"] == [] and page["next"] == 0
        assert str(page["state"]) == "queued"
        # the running one streams normally
        assert svc.job_status(first)["state"] == "running"
    finally:
        svc.stop()


def test_follow_engine_build_failure_fails_job(tmp_path, monkeypatch):
    """A pattern that passes submit validation but cannot compile fails
    the job from the runner thread — the on_fail path runs the close
    flush ON that thread (the current-thread join guard), the job lands
    FAILED with the error, and the stream drains terminal."""
    monkeypatch.setenv("DGREP_FOLLOW_POLL_S", "0.05")
    from distributed_grep_tpu.runtime.service import GrepService

    svc = GrepService(work_root=tmp_path / "svc")
    try:
        log_path = tmp_path / "b.log"
        log_path.write_bytes(b"x\n")
        jid = svc.submit(_mk_cfg(str(log_path), "ignored",
                                 pattern="(unbalanced"))
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            st = svc.job_status(jid)
            if st["state"] == "failed":
                break
            time.sleep(0.05)
        assert st["state"] == "failed" and st["error"]
        page = svc.job_stream(jid, cursor=0, timeout=0)
        assert page["records"] == [] and str(page["state"]) == "failed"
    finally:
        svc.stop()


def test_follow_wire_shape_elides_at_defaults():
    """Round-17 wire pin: a follow-free JobConfig serializes byte-
    identically to the pre-follow dataclass (no new keys), and the
    fields round-trip when set."""
    d = json.loads(JobConfig(input_files=["/x"]).to_json())
    assert "follow" not in d and "follow_poll_s" not in d
    d2 = json.loads(JobConfig(input_files=["/x"], follow=True).to_json())
    assert d2["follow"] is True and "follow_poll_s" not in d2
    cfg = JobConfig.from_json(
        JobConfig(input_files=["/x"], follow=True,
                  follow_poll_s=0.25).to_json()
    )
    assert cfg.follow and cfg.follow_poll_s == 0.25


def test_stale_summary_never_prunes_standing_query(tmp_path):
    """Index-tier pin: a persisted trigram summary built BEFORE an append
    must not hide the appended match — the follow path never consults
    the index at all, and the batch path's fresh-stat revalidation
    treats the append as drift (clean miss)."""
    from distributed_grep_tpu.index import summary as index_summary

    path = tmp_path / "shard.txt"
    path.write_bytes(b"nothing of note here\nmore filler\n")
    store_dir = tmp_path / "index"
    index_summary.attach_store(str(store_dir))
    eng = GrepEngine("zebraword", backend="cpu", corpus_bytes=1 << 20)
    res = eng.scan_file(str(path))
    assert res.n_matches == 0
    # a second scan may now prune via the stored summary — then APPEND
    with open(path, "ab") as f:
        f.write(b"zebraword appears\n")
    sc = FollowScanner(eng, [str(path)])
    sc.cursors[str(path)].offset = 0  # standing query starting at 0
    recs = _streamed(sc.poll_once())
    assert recs == [(3, b"zebraword appears")]
    # batch path agrees after the drift (fresh-stat revalidation)
    res2 = eng.scan_file(str(path))
    assert res2.n_matches == 1


# ------------------------------------------------------------ telemetry
def test_follow_counters_ride_engine_stats_and_are_gated(tmp_path):
    eng = GrepEngine("hello", backend="cpu")
    path = tmp_path / "c.log"
    path.write_bytes(b"hello\n")
    sc = FollowScanner(eng, [str(path)])
    sc.poll_once()
    c = follow_counters()
    assert c["follow_wakes"] == 1 and c["suffix_bytes_scanned"] == 6
    # the next scan's stats tail merges the module counters (engine-stats
    # + heartbeat piggyback surface)
    eng.scan(b"hello again\n")
    assert eng.stats.get("follow_wakes") == 1


# ------------------------------------------------------------ CLI e2e
def test_cli_follow_matches_one_shot_oracle(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("DGREP_FOLLOW_POLL_S", "0.05")
    from distributed_grep_tpu.__main__ import main

    path = tmp_path / "cli.log"
    path.write_bytes(b"hello first\nmiss\n")

    def appender():
        time.sleep(0.15)
        with open(path, "ab") as f:
            f.write(b"hello sec")
        time.sleep(0.15)
        with open(path, "ab") as f:
            f.write(b"ond\nhello tail")

    t = threading.Thread(target=appender)
    t.start()
    rc = main(["grep", "--follow", "--follow-idle-s", "0.8", "hello",
               str(path)])
    t.join()
    out = capsys.readouterr().out
    want = [
        f"{path} (line number #1) hello first",
        f"{path} (line number #3) hello second",
        f"{path} (line number #4) hello tail",
    ]
    assert out.splitlines() == want
    assert rc == 0


def test_cli_follow_count_mode(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("DGREP_FOLLOW_POLL_S", "0.05")
    from distributed_grep_tpu.__main__ import main

    path = tmp_path / "cnt.log"
    path.write_bytes(b"hello\nmiss\nhello\n")
    rc = main(["grep", "--follow", "--follow-idle-s", "0.3", "-c", "hello",
               str(path)])
    out = capsys.readouterr().out
    assert out.strip() == "2"
    assert rc == 0


def test_cli_follow_relative_path_display_matches_one_shot(
        tmp_path, monkeypatch, capsys):
    """The printed filename prefix matches the one-shot run byte for
    byte on a relative-path invocation (both resolve to the absolute
    path — the repo-wide display convention)."""
    monkeypatch.setenv("DGREP_FOLLOW_POLL_S", "0.05")
    monkeypatch.chdir(tmp_path)
    from distributed_grep_tpu.__main__ import main

    Path("rel.log").write_bytes(b"hello rel\n")
    rc = main(["grep", "--follow", "--follow-idle-s", "0.2", "hello",
               "rel.log"])
    follow_out = capsys.readouterr().out
    assert rc == 0
    rc2 = main(["grep", "hello", "rel.log"])
    assert rc2 == 0
    assert follow_out == capsys.readouterr().out
    assert follow_out.startswith(str(tmp_path / "rel.log"))


def test_cli_follow_finalize_drains_past_wake_cap(tmp_path, monkeypatch,
                                                  capsys):
    """The exit finalize loops until nothing drains: a writer that raced
    more than one per-wake read window ahead of the last wake still gets
    every line printed (the one-shot oracle contract holds at exit)."""
    from distributed_grep_tpu.runtime import follow as follow_mod

    monkeypatch.setenv("DGREP_FOLLOW_POLL_S", "0.05")
    monkeypatch.setattr(follow_mod, "MAX_WAKE_BYTES", 64)
    from distributed_grep_tpu.__main__ import main

    path = tmp_path / "burst.log"
    # > 4 windows of matching lines, unterminated tail included
    body = b"".join(b"hello line %02d\n" % i for i in range(20))
    path.write_bytes(body + b"hello tail")
    rc = main(["grep", "--follow", "--follow-idle-s", "0.15", "-h",
               "hello", str(path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert len(out.splitlines()) == 21  # all 20 lines + the tail


def test_cli_stream_and_follow_print_reset_notice(tmp_path, monkeypatch,
                                                  capsys):
    """Truncation mid-follow surfaces as a stderr notice (tail parity) —
    the consumer learns the line numbers restarted for a new file
    generation — while stdout keeps only match lines."""
    monkeypatch.setenv("DGREP_FOLLOW_POLL_S", "0.05")
    from distributed_grep_tpu.__main__ import main

    path = tmp_path / "rot.log"
    path.write_bytes(b"hello old\n")

    def truncator():
        time.sleep(0.2)
        path.write_bytes(b"hello x\n")  # strictly SHORTER: size < cursor

    t = threading.Thread(target=truncator)
    t.start()
    rc = main(["grep", "--follow", "--follow-idle-s", "0.6", "-h",
               "hello", str(path)])
    t.join()
    cap = capsys.readouterr()
    assert rc == 0
    assert cap.out.splitlines() == [
        "(line number #1) hello old", "(line number #1) hello x",
    ]
    assert "truncated or replaced" in cap.err


def test_cli_follow_rejects_unsupported_modes(tmp_path, capsys):
    from distributed_grep_tpu.__main__ import main

    path = tmp_path / "x.log"
    path.write_text("hello\n")
    assert main(["grep", "--follow", "-o", "hello", str(path)]) == 2
    assert main(["grep", "--follow", "-C", "1", "hello", str(path)]) == 2
    assert main(["grep", "--follow", "hello", "-"]) == 2


# ------------------------------------------------------- chaos (restart)
def test_daemon_sigkill_restart_resumes_stream(tmp_path):
    """The round-17 chaos leg: SIGKILL the daemon mid-stream, restart on
    the same work root, and the union of records collected across both
    daemon lives equals the oracle — no duplicate, no lost line."""
    import sys

    sys.path.insert(0, str(Path(__file__).parent))
    import service_proc

    log_path = tmp_path / "app.log"
    log_path.write_bytes(b"")
    proc = service_proc.ServiceProc(
        tmp_path / "root", workers=0,
        env={"DGREP_FOLLOW_POLL_S": "0.05"},
    )
    (tmp_path / "root").mkdir(parents=True, exist_ok=True)
    proc.start()
    collected: dict[int, tuple] = {}
    cursor = 0

    def drain(deadline_s: float = 8.0, want: int = 0) -> None:
        nonlocal cursor
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            try:
                r = service_proc._http_json(
                    "GET",
                    f"{proc.base}/jobs/{jid}/stream"
                    f"?cursor={cursor}&timeout=0.5",
                )
            except OSError:
                time.sleep(0.1)
                continue
            for rec in r["records"]:
                assert rec["seq"] not in collected, "duplicate seq"
                collected[rec["seq"]] = (rec["line"], rec["text"])
            cursor = r["next"]
            if want and len(collected) >= want:
                return
            if not want:
                return
        raise TimeoutError(
            f"stream stuck at {len(collected)}/{want}: {proc.tail_log()}"
        )

    try:
        cfg = _mk_cfg(str(log_path), "ignored")
        jid = proc.submit(cfg)
        with open(log_path, "ab") as f:
            f.write(b"".join(b"hello %d\n" % i for i in range(10)))
        drain(want=10)
        proc.sigkill()
        with open(log_path, "ab") as f:  # appends land while the daemon is down
            f.write(b"".join(b"hello %d\n" % i for i in range(10, 15)))
        proc.start()  # resume: registry replays, cursors reload
        with open(log_path, "ab") as f:
            f.write(b"".join(b"hello %d\n" % i for i in range(15, 20)))
        drain(deadline_s=15.0, want=20)
    finally:
        proc.terminate()
    got = [collected[s] for s in sorted(collected)]
    assert got == [(i + 1, "hello %d" % i) for i in range(20)]


def test_stream_stale_cursor_dropped_across_daemon_restart(tmp_path,
                                                           monkeypatch):
    """Satellite pin (round 18): a subscriber reconnecting with a STALE
    cursor after a ring shed gets the explicit ``dropped`` count — and
    still gets it ACROSS a daemon restart: StreamRing.preload re-seeds
    the replayed journal tail under the same byte cap, so the restarted
    /stream page reports what the bounded ring no longer holds instead
    of silently renumbering or starting empty."""
    monkeypatch.setenv("DGREP_FOLLOW_POLL_S", "0.05")
    monkeypatch.setenv("DGREP_STREAM_BUFFER", "600")
    from distributed_grep_tpu.runtime.service import GrepService

    log_path = tmp_path / "app.log"
    log_path.write_bytes(b"".join(
        b"hello %02d %s\n" % (i, b"x" * 40) for i in range(50)
    ))
    cfg = _mk_cfg(str(log_path), "ignored")
    svc_a = GrepService(work_root=tmp_path / "svc")
    jid = svc_a.submit(cfg)
    deadline = time.monotonic() + 30
    page = {}
    while page.get("next") != 50:
        assert time.monotonic() < deadline, page
        page = svc_a.job_stream(jid, cursor=0, timeout=0.2)
    # same-life shed: the 600-byte ring kept only a tail; a stale
    # cursor=0 reader learns exactly how much it lost
    assert page["dropped"] > 0
    first_live = page["records"][0]["seq"]
    assert page["dropped"] == first_live - 1
    # daemon "crash": stop only the wake loop — no cancel record, the
    # registry row stays RUNNING (the abandoned-not-stopped idiom; a
    # graceful stop would record CANCELLED and nothing would resume)
    svc_a.record(jid).follow.request_stop()
    time.sleep(0.3)

    svc_b = GrepService(work_root=tmp_path / "svc")
    try:
        deadline = time.monotonic() + 30
        page2 = {}
        while page2.get("next") != 50:
            assert time.monotonic() < deadline, page2
            page2 = svc_b.job_stream(jid, cursor=0, timeout=0.2)
        # the stale cursor's explicit dropped count survived the restart
        assert page2["dropped"] > 0
        assert page2["records"][0]["seq"] == page2["dropped"] + 1
        # sequence numbers are the SAME stream, not a renumbering: the
        # retained tail ends at the pre-crash high-water seq
        assert page2["records"][-1]["seq"] == 50
        # a caught-up cursor sees no drop marker after the restart either
        page3 = svc_b.job_stream(jid, cursor=page2["records"][0]["seq"],
                                 timeout=0)
        assert "dropped" not in page3
    finally:
        svc_b.stop()

"""The BASELINE.json config suite must run and agree with the re oracle at
toy size on every config (CPU; the numbers only matter on hardware)."""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "benchmarks"))

import baseline_configs as bc  # noqa: E402


@pytest.mark.parametrize("num", [1, 2, 3, 4])
def test_config_runs_and_checks(num):
    out = bc.run_config(num, size=200_000, backend="device", check=True)
    assert out["check"] == "ok", out
    assert out["matched_lines"] > 0 or num == 3  # sparse injected sets may be small


def test_config_5_banked_ruleset():
    out = bc.run_config(5, size=200_000, backend="device", check=True, n_patterns=300)
    assert out["check"] == "ok", out


def test_config_3_cpu_backend_parity():
    out = bc.run_config(3, size=150_000, backend="cpu", check=True)
    assert out["check"] == "ok", out

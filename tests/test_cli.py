"""CLI-level tests for the grep launcher (``__main__.py``).

The reference's launchers take bare argv and hardcode the rest
(main/coordinator_launch.go:11-23, main/worker_launch.go:11-19); ours parse
real flags, so the flag semantics need their own coverage — particularly
the grep -f byte-exactness contract (patterns are arbitrary bytes split on
'\\n' only) and the -E -f alternation-join restrictions.
"""

import sys

import pytest

from distributed_grep_tpu.__main__ import _has_backref, main


def run_cli(argv, capsys):
    code = main(argv)
    out = capsys.readouterr()
    return code, out.out, out.err


def test_grep_literal(tmp_path, corpus, capsys):
    code, out, _ = run_cli(
        ["grep", "hello", str(corpus["a.txt"]), "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0
    assert "hello world" in out and "hello again" in out
    assert "quick brown" not in out


def test_patterns_file_splits_on_newline_only(tmp_path, corpus, capsys):
    """grep -f splits patterns on \\n only: a literal containing \\r (or \\v,
    \\f, \\x85) must stay one pattern, not fragment into two."""
    target = tmp_path / "crlf.txt"
    target.write_bytes(b"seek\rhere\nplain text\njust seek\n")
    pf = tmp_path / "pats.txt"
    pf.write_bytes(b"seek\rhere\n")  # one pattern with an embedded \r
    code, out, _ = run_cli(
        ["grep", "-f", str(pf), str(target), "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0
    assert "seek\rhere" in out
    # "just seek" matches only if the pattern fragmented at the \r
    assert "just seek" not in out


def test_patterns_file_trailing_newline_not_empty_pattern(tmp_path, corpus, capsys):
    """A pattern file ending in \\n has no empty last pattern (grep semantics:
    an empty pattern would match every line)."""
    pf = tmp_path / "pats.txt"
    pf.write_bytes(b"fox\n")
    code, out, _ = run_cli(
        ["grep", "-f", str(pf), str(corpus["a.txt"]), str(corpus["b.txt"]),
         "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0
    assert "quick brown fox" in out and "fox says hello" in out
    assert "nothing here" not in out  # empty pattern would have matched all


def test_e_f_backreference_rejected(tmp_path, capsys):
    """-E -f lines joined into one alternation renumber capturing groups, so
    a backreference would silently bind to another line's group: reject."""
    target = tmp_path / "t.txt"
    target.write_text("abab\ncdcd\n")
    pf = tmp_path / "pats.txt"
    pf.write_text("(a)b\\1\n(c)d\n")
    code, _, err = run_cli(
        ["grep", "-E", "-f", str(pf), str(target), "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 2
    assert "backreference" in err


def test_e_f_single_backref_line_ok(tmp_path, capsys):
    """One line alone is wrapped only in non-capturing groups — group numbers
    survive, so a single-line backreference still works."""
    target = tmp_path / "t.txt"
    target.write_text("abab\nabcd\n")
    pf = tmp_path / "pats.txt"
    pf.write_text("(ab)\\1\n")
    code, out, _ = run_cli(
        ["grep", "-E", "-f", str(pf), str(target), "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0
    assert "abab" in out and "abcd" not in out


@pytest.mark.parametrize(
    "rx,expect",
    [
        (r"(a)\1", True),
        (r"(?P<x>a)(?P=x)", True),
        (r"a\\1", False),  # escaped backslash then digit — not a backref
        (r"\0", False),  # octal zero, not a backref
        (r"(a)(b)", False),
        (r"(a)\\\1", True),  # escaped backslash, then a real backref
        (r"(a)[\1]", False),  # inside a class: octal escape, not a backref
        (r"[(?P=]", False),  # inside a class: literal characters
        (r"(a)[]\1]", False),  # ']' literal as first member; still in class
        (r"(a)[^]\1]", False),  # same with negation
        (r"(a)[^^]\1", True),  # class closed, then a real backref
        (r"(c)(?(1)z|w)", True),  # conditional group test — number-sensitive
    ],
)
def test_has_backref(rx, expect):
    assert _has_backref(rx) is expect


def test_files_with_matches(tmp_path, corpus, capsys):
    code, out, _ = run_cli(
        ["grep", "-l", "fox", str(corpus["a.txt"]), str(corpus["b.txt"]),
         str(corpus["c.txt"]), "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0
    assert out.splitlines() == [str(corpus["a.txt"]), str(corpus["b.txt"])]


def test_only_matching(tmp_path, corpus, capsys):
    code, out, _ = run_cli(
        ["grep", "-o", "hel+o", str(corpus["a.txt"]), str(corpus["c.txt"]),
         "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0
    lines = out.splitlines()
    # c.txt line 2 has "hellohello": two matches from one line
    assert sum(1 for l in lines if l.endswith(" hello")) == 5
    assert any("(line number #2)" in l for l in lines)


def test_only_matching_literal_set_prefers_longest(tmp_path, capsys):
    t = tmp_path / "t.txt"
    t.write_text("xabcdx\n")
    pf = tmp_path / "p.txt"
    pf.write_bytes(b"abc\nabcd\n")
    code, out, _ = run_cli(
        ["grep", "-o", "-f", str(pf), str(t), "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0
    assert out.splitlines()[0].endswith(" abcd")  # leftmost-longest, like grep -F


def test_context_lines(tmp_path, capsys):
    t = tmp_path / "t.txt"
    t.write_text("l1\nl2\nhit A\nl4\nl5\nl6\nhit B\nl8\n")
    code, out, _ = run_cli(
        ["grep", "-C", "1", "hit", str(t), "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0
    got = [l.split(") ", 1)[-1] if ") " in l else l for l in out.splitlines()]
    # context lines carry a ')-' marker; normalize for comparison
    norm = []
    for l in out.splitlines():
        if l == "--":
            norm.append("--")
        elif ")- " in l:
            norm.append("ctx:" + l.split(")- ", 1)[1])
        else:
            norm.append("hit:" + l.split(") ", 1)[1])
    assert norm == [
        "ctx:l2", "hit:hit A", "ctx:l4", "--", "ctx:l6", "hit:hit B", "ctx:l8",
    ]


def test_context_adjacent_groups_no_separator(tmp_path, capsys):
    t = tmp_path / "t.txt"
    t.write_text("hit1\nmid\nhit2\nx\n")
    code, out, _ = run_cli(
        ["grep", "-C", "1", "hit", str(t), "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0
    assert "--" not in out.splitlines()
    assert len(out.splitlines()) == 4  # hit1, mid(ctx), hit2, x(ctx)


def test_only_matching_with_invert_prints_nothing(tmp_path, corpus, capsys):
    code, out, _ = run_cli(
        ["grep", "-o", "-v", "hello", str(corpus["a.txt"]),
         "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0 and out == ""


def test_context_separator_across_files(tmp_path, capsys):
    a = tmp_path / "a.txt"
    a.write_text("hit a\nx\n")
    b = tmp_path / "b.txt"
    b.write_text("y\nhit b\n")
    code, out, _ = run_cli(
        ["grep", "-C", "1", "hit", str(a), str(b), "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0
    # grep's group separator is global: one '--' between the two files' groups
    assert out.splitlines().count("--") == 1


def test_context_non_utf8_line_round_trips(tmp_path, capsys):
    t = tmp_path / "t.bin"
    t.write_bytes(b"caf\xe9 hit\nplain\n")
    code, out_ctx, _ = run_cli(
        ["grep", "-C", "1", "hit", str(t), "--work-dir", str(tmp_path / "w1")],
        capsys,
    )
    code2, out_plain, _ = run_cli(
        ["grep", "hit", str(t), "--work-dir", str(tmp_path / "w2")],
        capsys,
    )
    assert code == 0 and code2 == 0
    # both modes must print the matched line's bytes identically
    (plain_line,) = [l for l in out_plain.splitlines() if "hit" in l]
    assert plain_line in out_ctx.splitlines()


# ------------------------------------------------- round-2 surface additions

def test_word_regexp(tmp_path, capsys):
    t = tmp_path / "w.txt"
    t.write_text("hell yes\nhello\nshell hell\nx_hell\n")
    code, out, _ = run_cli(
        ["grep", "-w", "hell", str(t), "--work-dir", str(tmp_path / "w")], capsys
    )
    assert code == 0
    lines = {int(l.split("#")[1].split(")")[0]) for l in out.splitlines()}
    assert lines == {1, 3}  # not "hello", not "x_hell" (underscore is a word char)


def test_line_regexp_and_exit_codes(tmp_path, capsys):
    t = tmp_path / "x.txt"
    t.write_text("hello\nhello there\n")
    code, out, _ = run_cli(
        ["grep", "-x", "hello", str(t), "--work-dir", str(tmp_path / "w")], capsys
    )
    assert code == 0 and len(out.splitlines()) == 1
    code, out, _ = run_cli(
        ["grep", "-x", "hell", str(t), "--work-dir", str(tmp_path / "w2")], capsys
    )
    assert code == 1 and out == ""  # no whole-line match -> grep exit 1


def test_word_regexp_pattern_set(tmp_path, capsys):
    t = tmp_path / "s.txt"
    t.write_text("alpha beta\nalphabet soup\nbeta max\n")
    pf = tmp_path / "pats"
    pf.write_text("alpha\nbeta\n")
    code, out, _ = run_cli(
        ["grep", "-w", "-f", str(pf), str(t), "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0
    lines = {int(l.split("#")[1].split(")")[0]) for l in out.splitlines()}
    assert lines == {1, 3}  # "alphabet" is not a word match


def test_max_count_quiet_fixed_strings(tmp_path, capsys):
    t = tmp_path / "m.txt"
    t.write_text("a.b\nxay\na.b again\na.b third\n")
    code, out, _ = run_cli(
        ["grep", "-F", "-m", "2", "a.b", str(t), "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0
    assert len(out.splitlines()) == 2  # -F: '.' literal (no 'xay'); -m 2 caps
    code, out, _ = run_cli(
        ["grep", "-q", "zzz", str(t), "--work-dir", str(tmp_path / "w2")], capsys
    )
    assert code == 1 and out == ""
    code, out, _ = run_cli(
        ["grep", "-q", "xay", str(t), "--work-dir", str(tmp_path / "w3")], capsys
    )
    assert code == 0 and out == ""


def test_multiple_e_patterns_and_files_without_match(tmp_path, capsys):
    a = tmp_path / "a.txt"
    a.write_text("apple pie\n")
    b = tmp_path / "b.txt"
    b.write_text("nothing here\n")
    code, out, _ = run_cli(
        ["grep", "-e", "apple", "-e", "cherry", str(a), str(b),
         "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0 and "apple pie" in out
    code, out, _ = run_cli(
        ["grep", "-L", "apple", str(a), str(b), "--work-dir", str(tmp_path / "w2")],
        capsys,
    )
    assert code == 0 and out.strip() == str(b)


def test_recursive_include_and_dir_error(tmp_path, capsys):
    d = tmp_path / "tree"
    (d / "sub").mkdir(parents=True)
    (d / "sub" / "x.log").write_text("needle deep\n")
    (d / "top.txt").write_text("needle top\n")
    code, out, _ = run_cli(
        ["grep", "-r", "needle", str(d), "--include", "*.log",
         "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0
    assert "x.log" in out and "top.txt" not in out
    # a directory without -r is an error, like grep without -r/-d
    code, _, err = run_cli(
        ["grep", "needle", str(d), "--work-dir", str(tmp_path / "w2")], capsys
    )
    assert code == 2 and "directory" in err


def test_review_fixes_round2_cli(tmp_path, capsys):
    t = tmp_path / "r.txt"
    t.write_text("hell x_hell\nzz\ncat one\ncat two\n")
    # -w -o: only the word-bounded occurrence prints
    code, out, _ = run_cli(
        ["grep", "-w", "-o", "hell", str(t), "--work-dir", str(tmp_path / "w1")],
        capsys,
    )
    assert code == 0 and len(out.splitlines()) == 1
    # -o respects the -m cap
    code, out, _ = run_cli(
        ["grep", "-o", "-m", "1", "cat", str(t), "--work-dir", str(tmp_path / "w2")],
        capsys,
    )
    assert code == 0 and len(out.splitlines()) == 1
    # negative -m is an error like GNU grep
    code, _, err = run_cli(
        ["grep", "-m", "-1", "cat", str(t), "--work-dir", str(tmp_path / "w3")],
        capsys,
    )
    assert code == 2 and "invalid max count" in err
    # -F -e with embedded newline = alternative literals
    code, out, _ = run_cli(
        ["grep", "-F", "-e", "zz\nmissing", str(t),
         "--work-dir", str(tmp_path / "w4")],
        capsys,
    )
    assert code == 0 and len(out.splitlines()) == 1
    # -L exit status follows MATCH presence (GNU grep 3.8, verified
    # differentially in test_fuzz_cli.py): file listed, nothing matched
    # anywhere -> exit 1
    code, out, _ = run_cli(
        ["grep", "-L", "nothinghere", str(t), "--work-dir", str(tmp_path / "w5")],
        capsys,
    )
    assert code == 1 and out.strip() == str(t)
    code, out, _ = run_cli(
        ["grep", "-L", "cat", str(t), "--work-dir", str(tmp_path / "w6")],
        capsys,
    )
    assert code == 0 and out == ""  # matches exist -> 0, nothing listed


def test_byte_offset_no_filename_suppress(tmp_path, capsys):
    t = tmp_path / "bo.txt"
    t.write_text("one hello\nnope\nbye hello\n")
    code, out, _ = run_cli(
        ["grep", "-b", "hello", str(t), "--work-dir", str(tmp_path / "w")], capsys
    )
    assert code == 0
    # offsets match grep -b: line 1 at 0, line 3 at 15
    assert "(byte #0)" in out and "(byte #15)" in out
    code, out, _ = run_cli(
        ["grep", "-h", "hello", str(t), "--work-dir", str(tmp_path / "w2")], capsys
    )
    assert code == 0
    assert str(t) not in out and "(line number #1)" in out
    # -s: missing file message suppressed, remaining files searched,
    # exit 2 records the error (GNU semantics)
    code, out, err = run_cli(
        ["grep", "-s", "hello", str(t), str(tmp_path / "missing.txt"),
         "--work-dir", str(tmp_path / "w3")],
        capsys,
    )
    assert code == 2 and "cannot read" not in err and "one hello" in out
    # without -s the message appears, matches still print
    code, out, err = run_cli(
        ["grep", "hello", str(t), str(tmp_path / "missing.txt"),
         "--work-dir", str(tmp_path / "w4")],
        capsys,
    )
    assert code == 2 and "cannot read" in err and "one hello" in out
    # -q with a match reports 0 even after file errors
    code, out, _ = run_cli(
        ["grep", "-q", "hello", str(t), str(tmp_path / "missing.txt"),
         "--work-dir", str(tmp_path / "w5")],
        capsys,
    )
    assert code == 0


# ------------------------------- streaming collation (round 3)

def test_streaming_collation_bounded_memory(tmp_path):
    """Match-dense job: the sorted collation stream must spill past the
    memory cap (not hold the result set in RAM) and stay byte-identical
    to the in-RAM dict collation."""
    from distributed_grep_tpu.runtime.job import JobResult, grep_key_sort

    out = tmp_path / "mr-out-0"
    # 20k matched lines across 2 files, written in the reduce side's
    # lexicographic key order (NOT numeric order: line 10 < line 9 lex)
    items = []
    for f in ("/tmp/a.txt", "/tmp/b.txt"):
        for ln in range(1, 10_001):
            items.append((f"{f} (line number #{ln})", f"line {ln} of {f}"))
    lex = sorted(items, key=lambda kv: kv[0])
    out.write_text("\n".join(f"{k}\t{v}" for k, v in lex) + "\n")

    res = JobResult(output_files=[out])
    # tiny cap: forces spill runs (ExternalReducer.spill_count exercised
    # indirectly — boundedness is the cap's contract, pinned in
    # test_extsort.py; here we pin ORDER and EXACTNESS of the stream)
    streamed = list(res.iter_results_sorted(memory_bytes=64 * 1024,
                                            spill_dir=str(tmp_path)))
    expected = sorted(items, key=grep_key_sort)
    assert streamed == expected  # numeric (file, line) order, all records


def test_cli_default_output_identical_and_m_cap(tmp_path, capsys):
    """Default-mode CLI output through the streaming path must equal GNU
    grep -n line selection, and -m must cap per file."""
    import subprocess
    import sys

    f1 = tmp_path / "x.txt"
    f1.write_text("".join(
        f"needle line {i}\n" if i % 3 == 0 else f"hay {i}\n"
        for i in range(1, 31)
    ))
    from distributed_grep_tpu.__main__ import main

    rc = main(["grep", "needle", str(f1)])
    out = capsys.readouterr().out
    got = [l for l in out.splitlines() if l]
    oracle = subprocess.run(
        ["grep", "-n", "needle", str(f1)], capture_output=True, text=True
    ).stdout.splitlines()
    assert len(got) == len(oracle)
    for g, o in zip(got, oracle):
        ln, text = o.split(":", 1)
        assert g == f"{f1} (line number #{ln}) {text}"
    assert rc == 0

    rc = main(["grep", "needle", str(f1), "-m", "2"])
    out2 = [l for l in capsys.readouterr().out.splitlines() if l]
    assert len(out2) == 2 and out2 == got[:2]


def test_count_only_fast_path(tmp_path, corpus, capsys):
    """Count queries (-c/-l/-L/-q with no per-line-output mode) ride the
    apps' count_only contract — ONE record per file, key = filename,
    value = selected count — so a match-dense count job skips the
    per-line record pipeline entirely (549k-match 64 MB `-c` measured
    17.5 s -> 1.9 s).  Counts, -m caps, -v, and -q exit codes must be
    identical to the per-line path's."""
    a, b = str(corpus["a.txt"]), str(corpus["b.txt"])
    # -c: per-file counts, argv order
    code, out, _ = run_cli(
        ["grep", "-c", "hello", a, b, "--work-dir", str(tmp_path / "w1")],
        capsys,
    )
    assert code == 0
    assert out.splitlines() == [f"{a}:2", f"{b}:1"]
    # -c -v: inverted counts
    code, out, _ = run_cli(
        ["grep", "-c", "-v", "hello", a, b, "--work-dir", str(tmp_path / "w2")],
        capsys,
    )
    assert code == 0
    assert out.splitlines() == [f"{a}:1", f"{b}:3"]
    # -c -m1: the per-file cap applies to count records too
    code, out, _ = run_cli(
        ["grep", "-c", "-m1", "hello", a, b, "--work-dir", str(tmp_path / "w3")],
        capsys,
    )
    assert code == 0
    assert out.splitlines() == [f"{a}:1", f"{b}:1"]
    # -q: exit 0 iff any line selected, no output
    code, out, _ = run_cli(
        ["grep", "-q", "fox", a, b, "--work-dir", str(tmp_path / "w4")], capsys,
    )
    assert (code, out) == (0, "")
    code, out, _ = run_cli(
        ["grep", "-q", "zebra", a, b, "--work-dir", str(tmp_path / "w5")], capsys,
    )
    assert (code, out) == (1, "")
    # -c with a context flag is NOT count-only (needs line sets) — still exact
    code, out, _ = run_cli(
        ["grep", "-c", "-A1", "hello", a, "--work-dir", str(tmp_path / "w6")],
        capsys,
    )
    assert code == 0 and out.splitlines() == ["2"]


def test_count_only_app_contract(tmp_path):
    """Both apps emit the same count records under count_only (drop-in
    interchangeability, the north-star boundary)."""
    from distributed_grep_tpu.apps import grep as cpu_app
    from distributed_grep_tpu.apps import grep_tpu as tpu_app

    data = b"volcano one\nplain\nvolcano two\n"
    for app in (cpu_app, tpu_app):
        app.configure(pattern="volcano", count_only=True, **(
            {"backend": "cpu"} if app is tpu_app else {}
        ))
        recs = app.map_fn("f.txt", data)
        assert [(r.key, r.value) for r in recs] == [("f.txt", "2")], app.__name__


def test_stdin_input(tmp_path, corpus, capsys, monkeypatch):
    """GNU grep reads standard input when no FILE is given, or for the
    FILE "-"; output shows the "(standard input)" label.  The runtime
    schedules real files, so stdin spools to a temp file under the hood."""
    import io
    import types

    def feed(data: bytes):
        monkeypatch.setattr(
            sys, "stdin", types.SimpleNamespace(buffer=io.BytesIO(data))
        )

    # bare stdin, default print: label + line numbers, grep exit code
    feed(b"one hello\ntwo\nthree hello\n")
    code, out, _ = run_cli(
        ["grep", "hello", "--work-dir", str(tmp_path / "w1")], capsys)
    assert code == 0
    assert out.splitlines() == [
        "(standard input) (line number #1) one hello",
        "(standard input) (line number #3) three hello",
    ]
    # "-" mixed with a real file; -l lists the label
    a = str(corpus["a.txt"])
    feed(b"piped hello\n")
    code, out, _ = run_cli(
        ["grep", "-l", "hello", "-", a, "--work-dir", str(tmp_path / "w2")],
        capsys)
    assert code == 0
    assert out.splitlines() == ["(standard input)", a]
    # -c from bare stdin: bare count, no prefix
    feed(b"x hello\ny\nz hello\n")
    code, out, _ = run_cli(
        ["grep", "-c", "hello", "--work-dir", str(tmp_path / "w3")], capsys)
    assert (code, out.strip()) == (0, "2")
    # no match from stdin: exit 1
    feed(b"nothing\n")
    code, out, _ = run_cli(
        ["grep", "-q", "hello", "--work-dir", str(tmp_path / "w4")], capsys)
    assert (code, out) == (1, "")


def test_exclude_dir_recursive(tmp_path, capsys):
    """grep -r --exclude-dir: directories whose basename matches any glob
    are pruned — descended ones AND explicitly named command-line ones
    (probed against grep 3.8, which skips both)."""
    (tmp_path / "keep").mkdir()
    (tmp_path / ".git").mkdir()
    (tmp_path / "skipme" / "nested").mkdir(parents=True)
    for p in ("keep/k.txt", ".git/g.txt", "skipme/nested/n.txt", "top.txt"):
        (tmp_path / p).write_text("needle\n")
    code, out, _ = run_cli(
        ["grep", "-r", "--exclude-dir=.git", "--exclude-dir", "skip*",
         "-l", "needle", str(tmp_path / "keep"), str(tmp_path),
         "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0
    names = sorted(l.rsplit("/", 1)[-1] for l in out.splitlines())
    assert names == ["k.txt", "k.txt", "top.txt"]
    # a command-line directory matching the glob is itself skipped (GNU)
    code, out, _ = run_cli(
        ["grep", "-r", "--exclude-dir", "skip*", "-l", "needle",
         str(tmp_path / "skipme"), "--work-dir", str(tmp_path / "w2")],
        capsys,
    )
    assert (code, out) == (1, "")


def test_stdin_streaming_early_exit(tmp_path, capsys, monkeypatch):
    """Round 5: stdin as the only input STREAMS — presence queries stop
    at the first settled match without draining the pipe (GNU semantics;
    the round-4 spool read to EOF first).  An endless stream object
    stands in for an unbounded pipe: reading past the match would hang
    or exhaust it."""
    import itertools
    import types

    class EndlessPipe:
        """Yields one matching chunk, then infinite filler chunks; fails
        the test if read more than `limit` times (a drain would)."""

        def __init__(self, first: bytes, limit: int = 5):
            self.chunks = itertools.chain(
                [first], itertools.repeat(b"filler line\n" * 10)
            )
            self.reads = 0
            self.limit = limit

        def read1(self, n: int = -1) -> bytes:
            self.reads += 1
            assert self.reads <= self.limit, "presence query drained the pipe"
            return next(self.chunks)

    pipe = EndlessPipe(b"no\nhas needle here\nmore\n")
    monkeypatch.setattr(sys, "stdin", types.SimpleNamespace(buffer=pipe))
    code = main(["grep", "-q", "needle"])
    assert code == 0 and pipe.reads == 1

    pipe = EndlessPipe(b"x needle\n")
    monkeypatch.setattr(sys, "stdin", types.SimpleNamespace(buffer=pipe))
    code = main(["grep", "-l", "needle", "-"])
    out = capsys.readouterr().out
    assert code == 0 and out.splitlines() == ["(standard input)"]
    assert pipe.reads == 1

    # -L: a match settles the (empty) answer early too
    pipe = EndlessPipe(b"x needle\n")
    monkeypatch.setattr(sys, "stdin", types.SimpleNamespace(buffer=pipe))
    code = main(["grep", "-L", "needle"])
    assert code == 0 and capsys.readouterr().out == ""
    assert pipe.reads == 1

    # -m stops READING at the cap (GNU) — chunk granularity
    pipe = EndlessPipe(b"a needle\nb needle\nc needle\n", limit=2)
    monkeypatch.setattr(sys, "stdin", types.SimpleNamespace(buffer=pipe))
    code = main(["grep", "-m", "2", "needle"])
    out = capsys.readouterr().out
    assert code == 0
    assert out.splitlines() == [
        "(standard input) (line number #1) a needle",
        "(standard input) (line number #2) b needle",
    ]


def test_stdin_streaming_matches_gnu_modes(tmp_path, capsys, monkeypatch):
    """Streamed stdin agrees with GNU for -c/-w/-x/-v/-i and partial
    trailing lines; line numbers accumulate across chunked reads."""
    import io
    import shutil
    import subprocess
    import types

    gnu = shutil.which("grep")

    class TrickleBytesIO(io.BytesIO):
        """Returns at most 7 bytes per read1 — forces carry/chunk logic."""

        def read1(self, n: int = -1) -> bytes:
            return super().read(7)

    data = (
        b"The needle one\nno match\nNEEDLE up\nneedles plural\n"
        b"needle\nlast without newline needle"
    )

    cases = [
        (["-c", "needle"], ["-c", "needle"]),
        (["-w", "needle"], ["-nw", "needle"]),
        (["-x", "needle"], ["-nx", "needle"]),
        (["-v", "-c", "needle"], ["-cv", "needle"]),
        (["-i", "-c", "needle"], ["-ci", "needle"]),
    ]
    for ours_args, gnu_args in cases:
        monkeypatch.setattr(
            sys, "stdin", types.SimpleNamespace(buffer=TrickleBytesIO(data))
        )
        code = main(["grep", *ours_args])
        out = capsys.readouterr().out
        p = subprocess.run([gnu, *gnu_args], input=data,
                           capture_output=True, env={"LC_ALL": "C"})
        assert code == p.returncode, (ours_args, out, p.stdout)
        if "-c" in ours_args or "-cv" in gnu_args[0]:
            assert out.strip() == p.stdout.decode().strip(), ours_args
        else:
            ours_lines = {
                int(l.split("#")[1].split(")")[0])
                for l in out.splitlines()
            }
            gnu_lines = {
                int(l.split(":")[0]) for l in p.stdout.decode().splitlines()
            }
            assert ours_lines == gnu_lines, ours_args


def test_max_count_zero_selects_nothing(tmp_path, corpus, capsys, monkeypatch):
    """GNU -m 0: prints nothing, exits 1 — on files AND streamed stdin
    (probed grep 3.8; round-5 review finding: both paths printed/exited
    wrong when the cap was zero)."""
    import io
    import types

    a = str(corpus["a.txt"])
    code, out, _ = run_cli(
        ["grep", "-m", "0", "hello", a, "--work-dir", str(tmp_path / "w")],
        capsys)
    assert (code, out) == (1, "")
    monkeypatch.setattr(
        sys, "stdin",
        types.SimpleNamespace(buffer=io.BytesIO(b"a hello\n")),
    )
    code, out, _ = run_cli(["grep", "-m", "0", "hello"], capsys)
    assert (code, out) == (1, "")
    monkeypatch.setattr(
        sys, "stdin",
        types.SimpleNamespace(buffer=io.BytesIO(b"a hello\n")),
    )
    code, out, _ = run_cli(["grep", "-c", "-m", "0", "hello"], capsys)
    assert (code, out.strip()) == (1, "0")

"""CLI-level tests for the grep launcher (``__main__.py``).

The reference's launchers take bare argv and hardcode the rest
(main/coordinator_launch.go:11-23, main/worker_launch.go:11-19); ours parse
real flags, so the flag semantics need their own coverage — particularly
the grep -f byte-exactness contract (patterns are arbitrary bytes split on
'\\n' only) and the -E -f alternation-join restrictions.
"""

import sys

import pytest

from distributed_grep_tpu.__main__ import _has_backref, main


def run_cli(argv, capsys):
    code = main(argv)
    out = capsys.readouterr()
    return code, out.out, out.err


def test_grep_literal(tmp_path, corpus, capsys):
    code, out, _ = run_cli(
        ["grep", "hello", str(corpus["a.txt"]), "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0
    assert "hello world" in out and "hello again" in out
    assert "quick brown" not in out


def test_patterns_file_splits_on_newline_only(tmp_path, corpus, capsys):
    """grep -f splits patterns on \\n only: a literal containing \\r (or \\v,
    \\f, \\x85) must stay one pattern, not fragment into two."""
    target = tmp_path / "crlf.txt"
    target.write_bytes(b"seek\rhere\nplain text\njust seek\n")
    pf = tmp_path / "pats.txt"
    pf.write_bytes(b"seek\rhere\n")  # one pattern with an embedded \r
    code, out, _ = run_cli(
        ["grep", "-f", str(pf), str(target), "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0
    assert "seek\rhere" in out
    # "just seek" matches only if the pattern fragmented at the \r
    assert "just seek" not in out


def test_patterns_file_trailing_newline_not_empty_pattern(tmp_path, corpus, capsys):
    """A pattern file ending in \\n has no empty last pattern (grep semantics:
    an empty pattern would match every line)."""
    pf = tmp_path / "pats.txt"
    pf.write_bytes(b"fox\n")
    code, out, _ = run_cli(
        ["grep", "-f", str(pf), str(corpus["a.txt"]), str(corpus["b.txt"]),
         "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0
    assert "quick brown fox" in out and "fox says hello" in out
    assert "nothing here" not in out  # empty pattern would have matched all


def test_e_f_backreference_rejected(tmp_path, capsys):
    """-E -f lines joined into one alternation renumber capturing groups, so
    a backreference would silently bind to another line's group: reject."""
    target = tmp_path / "t.txt"
    target.write_text("abab\ncdcd\n")
    pf = tmp_path / "pats.txt"
    pf.write_text("(a)b\\1\n(c)d\n")
    code, _, err = run_cli(
        ["grep", "-E", "-f", str(pf), str(target), "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 2
    assert "backreference" in err


def test_e_f_single_backref_line_ok(tmp_path, capsys):
    """One line alone is wrapped only in non-capturing groups — group numbers
    survive, so a single-line backreference still works."""
    target = tmp_path / "t.txt"
    target.write_text("abab\nabcd\n")
    pf = tmp_path / "pats.txt"
    pf.write_text("(ab)\\1\n")
    code, out, _ = run_cli(
        ["grep", "-E", "-f", str(pf), str(target), "--work-dir", str(tmp_path / "w")],
        capsys,
    )
    assert code == 0
    assert "abab" in out and "abcd" not in out


@pytest.mark.parametrize(
    "rx,expect",
    [
        (r"(a)\1", True),
        (r"(?P<x>a)(?P=x)", True),
        (r"a\\1", False),  # escaped backslash then digit — not a backref
        (r"\0", False),  # octal zero, not a backref
        (r"(a)(b)", False),
        (r"(a)\\\1", True),  # escaped backslash, then a real backref
        (r"(a)[\1]", False),  # inside a class: octal escape, not a backref
        (r"[(?P=]", False),  # inside a class: literal characters
        (r"(a)[]\1]", False),  # ']' literal as first member; still in class
        (r"(a)[^]\1]", False),  # same with negation
        (r"(a)[^^]\1", True),  # class closed, then a real backref
        (r"(c)(?(1)z|w)", True),  # conditional group test — number-sensitive
    ],
)
def test_has_backref(rx, expect):
    assert _has_backref(rx) is expect

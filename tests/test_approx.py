"""Approximate (agrep <= k errors) matching: model vs an independent DP
edit-distance oracle, XLA core vs model reference, Pallas kernel
(interpret) vs XLA core, and the engine end-to-end including newline
resets and stripe boundaries."""

from __future__ import annotations

import numpy as np
import pytest

from distributed_grep_tpu.models import approx as ax
from distributed_grep_tpu.ops import layout as layout_mod
from distributed_grep_tpu.ops import pallas_approx, scan_jnp
from distributed_grep_tpu.ops.engine import GrepEngine

from tests.test_ops import make_text


# ------------------------------------------------------------------- model

def test_model_vs_dp_oracle_fuzz():
    rng = np.random.default_rng(1)
    for _ in range(200):
        m = int(rng.integers(3, 12))
        k = int(rng.integers(1, min(3, m - 1) + 1))
        pat = "".join(chr(c) for c in rng.integers(97, 103, m))
        model = ax.try_compile_approx(pat, k)
        assert model is not None
        line = bytes(rng.integers(97, 104, int(rng.integers(0, 30))).tolist())
        assert ax.line_matches(model, line) == ax.dp_oracle_line(
            model.base.sym_ranges, line, k
        ), (pat, k, line)


def test_model_class_pattern_and_cases():
    model = ax.try_compile_approx("h[ae]llo", 1)
    cases = [(b"hallo", True), (b"hxllo", True), (b"hxlxo", False),
             (b"xxhelloxx", True), (b"helo", True), (b"heelloo", True),
             (b"hello", True), (b"", False)]
    for line, want in cases:
        assert ax.line_matches(model, line) == want, line


def test_newline_never_spanned():
    model = ax.try_compile_approx("abcd", 1)
    # 'ab\ncd' — an error budget of 1 must not bridge the newline
    assert ax.scan_reference(model, b"ab\ncd").size == 0
    # but each line is scanned independently
    assert ax.scan_reference(model, b"abcd\nabxd\n").size >= 2


def test_compile_bounds():
    assert ax.try_compile_approx("abc", 3) is None  # k >= length
    assert ax.try_compile_approx("abcdef", 4) is None  # k > MAX_ERRORS
    assert ax.try_compile_approx("a(b|c)d", 1) is None  # not shift-and-able
    assert ax.try_compile_approx("abcdef", 2) is not None


# --------------------------------------------------------------- XLA core

def _lay_arr(data):
    lay = layout_mod.choose_layout(
        len(data), target_lanes=4096, min_chunk=512,
        lane_multiple=4096, chunk_multiple=512,
    )
    return lay, layout_mod.to_device_array(data, lay)


def test_xla_core_matches_reference_per_stripe():
    model = ax.try_compile_approx("needle", 1)
    data = make_text(200, inject=[(3, b"a needxe here"), (100, b"nedle and needles"),
                                  (199, b"needle")])
    lay, arr = _lay_arr(data)
    packed = np.asarray(scan_jnp.approx_scan(arr, model))
    want = np.zeros((lay.chunk, lay.lanes), dtype=bool)
    for lane in range(lay.lanes):
        stripe = bytes(arr[:, lane])
        ends = ax.scan_reference(model, stripe)
        want[(ends - 1), lane] = True
    np.testing.assert_array_equal(packed, np.packbits(want, axis=1, bitorder="little"))


# ----------------------------------------------------------- pallas kernel

@pytest.mark.parametrize("pattern,k", [("needle", 1), ("volcano", 2), ("h[ae]llo", 1)])
def test_pallas_interpret_matches_xla(pattern, k):
    model = ax.try_compile_approx(pattern, k)
    assert model is not None and pallas_approx.eligible(model)
    data = make_text(
        120,
        inject=[(5, b"needxe volcxno hxllo"), (60, b"nedle volano hallo"),
                (119, b"the needle")],
    )
    lay, arr = _lay_arr(data)
    got = pallas_approx.approx_scan(arr, model, interpret=True)
    want = np.asarray(scan_jnp.approx_scan(arr, model))
    np.testing.assert_array_equal(got, want)


# ----------------------------------------------------------------- engine

def _oracle_lines(model, data):
    return {
        i for i, line in enumerate(data.split(b"\n"), 1)
        if ax.dp_oracle_line(model.base.sym_ranges, line, model.k)
    }


def test_engine_approx_end_to_end():
    data = make_text(300, inject=[(4, b"a needxe in line"), (150, b"nedle"),
                                  (299, b"needle exact")])
    eng = GrepEngine("needle", max_errors=1)
    assert eng.mode == "approx"
    got = set(eng.scan(data).matched_lines.tolist())
    assert got == _oracle_lines(eng.approx, data)


def test_engine_approx_ignore_case():
    data = b"NEEDLE\nNEDLE\nnothing\nNeEdLx\n"
    eng = GrepEngine("needle", max_errors=1, ignore_case=True)
    got = set(eng.scan(data).matched_lines.tolist())
    assert got == {1, 2, 4}


def test_engine_approx_all_lines_when_k_ge_len():
    eng = GrepEngine("ab", max_errors=2)
    res = eng.scan(b"xx\nyy\n")
    assert res.matched_lines.tolist() == [1, 2]


def test_engine_approx_cpu_backend():
    data = b"needle\nnedle\nno\n"
    eng = GrepEngine("needle", max_errors=1, backend="cpu")
    assert set(eng.scan(data).matched_lines.tolist()) == {1, 2}


def test_engine_approx_rejects():
    with pytest.raises(ValueError):
        GrepEngine("a(b|c)+", max_errors=1)
    with pytest.raises(ValueError):
        GrepEngine(patterns=["ab", "cd"], max_errors=1)
    with pytest.raises(ValueError):
        GrepEngine("abcdef", max_errors=9)

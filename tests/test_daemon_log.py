"""Fleet timeline (round 19, runtime/daemon_log.py + trace-export
--fleet + dgrep explain disruptions).

* ``DaemonLog`` mechanics — staged-flush roundtrip, the round-18 write
  fence DROPPING a deposed daemon's staged batch with the file bytes
  provably unchanged, torn-tail truncation at reopen, ``discard()``;
* the ``DGREP_DAEMON_LOG=0`` no-op pin — a log-free service writes no
  daemon.jsonl and keeps its /status shape;
* an in-process service lifecycle run — start / worker_attach /
  job_terminal / stop land on the timeline, and /status worker rows
  carry ``last_event_age_s`` (the freshness signal ``dgrep top`` and
  the scale advisor now share);
* the ``--fleet`` Chrome-trace golden over a synthetic two-incarnation
  work root — epoch-ordered daemon rows, the promotion-latency span,
  job events merged as their own process;
* ``disruptions_view`` windowing for ``dgrep explain``.

The subprocess SIGKILL-failover daemon.jsonl assertion lives in
tests/test_chaos.py.  Standalone: ``python -m pytest
tests/test_daemon_log.py -q`` (marker ``obs``).
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from distributed_grep_tpu.runtime.daemon_log import (
    FILENAME,
    DaemonLog,
    env_daemon_log,
)
from distributed_grep_tpu.runtime.explain import disruptions_view
from distributed_grep_tpu.runtime.service import GrepService
from distributed_grep_tpu.utils.config import JobConfig
from distributed_grep_tpu.utils.spans import export_fleet_trace

pytestmark = pytest.mark.obs


# ------------------------------------------------------------------ knob

def test_env_knob_parser(monkeypatch):
    monkeypatch.delenv("DGREP_DAEMON_LOG", raising=False)
    assert env_daemon_log() is True
    monkeypatch.setenv("DGREP_DAEMON_LOG", "0")
    assert env_daemon_log() is False
    monkeypatch.setenv("DGREP_DAEMON_LOG", "1")
    assert env_daemon_log() is True


# ------------------------------------------------------------- mechanics

def test_stage_flush_roundtrip_and_epoch_ordering(tmp_path):
    d1 = DaemonLog(tmp_path, epoch=1, role="active")
    d1.append_now("lease_acquire", addr="a:1")
    d1.stage("start", work_root=str(tmp_path))
    d1.stage("job_terminal", job="job-000001", state="done")
    assert d1.flush() is True
    d1.close()
    d2 = DaemonLog(tmp_path, epoch=2, role="active")
    d2.append_now("lease_steal", addr="a:2", prev_epoch=1)
    d2.close()
    events = DaemonLog.read(tmp_path)
    assert [(e["epoch"], e["kind"]) for e in events] == [
        (1, "lease_acquire"), (1, "start"), (1, "job_terminal"),
        (2, "lease_steal"),
    ]
    # identity stamped per record; payload elided when empty
    assert all(e["pid"] and e["role"] == "active" for e in events)
    assert events[2]["payload"] == {"job": "job-000001", "state": "done"}


def test_fence_drops_staged_batch_bytes_unchanged(tmp_path):
    """The tentpole fence pin: a deposed daemon's staged events are
    dropped WHOLE — the durable file never sees a stale interleave."""
    d = DaemonLog(tmp_path, epoch=1, role="active")
    d.append_now("start")
    before = (tmp_path / FILENAME).read_bytes()
    d.stage("lease_lost")
    d.stage("stop")
    assert d.flush(gate=lambda: False) is False
    assert (tmp_path / FILENAME).read_bytes() == before
    # the fenced batch is GONE, not re-staged: a later un-fenced flush
    # must not resurrect it
    assert d.flush() is True
    assert (tmp_path / FILENAME).read_bytes() == before
    d.close()


def test_torn_tail_truncated_on_reopen(tmp_path):
    d = DaemonLog(tmp_path, epoch=1)
    d.append_now("start")
    d.close()
    path = tmp_path / FILENAME
    good = path.read_bytes()
    with path.open("ab") as f:
        f.write(b'{"ts": 1.0, "epoch": 1, "kind": "sto')  # torn mid-write
    assert DaemonLog.read(tmp_path) == [json.loads(good)]
    # reopen truncates the torn tail, then appends cleanly after it
    d2 = DaemonLog(tmp_path, epoch=2)
    d2.append_now("lease_steal", prev_epoch=1)
    d2.close()
    kinds = [e["kind"] for e in DaemonLog.read(tmp_path)]
    assert kinds == ["start", "lease_steal"]


def test_discard_drops_staged_without_flush(tmp_path):
    d = DaemonLog(tmp_path, epoch=1)
    d.append_now("start")
    before = (tmp_path / FILENAME).read_bytes()
    d.stage("lease_lost")
    d.discard()
    assert (tmp_path / FILENAME).read_bytes() == before
    d.discard()  # idempotent (graceful-close-then-demote path)


def test_read_missing_file_answers_empty(tmp_path):
    assert DaemonLog.read(tmp_path) == []


# --------------------------------------------------- service lifecycle

def _tiny_cfg(tmp_path: Path, **kw) -> JobConfig:
    p = tmp_path / "in.txt"
    if not p.exists():
        p.write_text("hello\nmiss\n")
    return JobConfig(
        input_files=[str(p)],
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": "hello", "backend": "cpu"},
        n_reduce=1,
        **kw,
    )


def test_service_lifecycle_lands_on_timeline(tmp_path):
    root = tmp_path / "svc"
    svc = GrepService(work_root=root, daemon_log=DaemonLog(root),
                      task_timeout_s=5.0, sweep_interval_s=0.1)
    try:
        jid = svc.submit(_tiny_cfg(tmp_path))
        svc.start_local_workers(1)
        assert svc.wait_job(jid, timeout=60), svc.job_status(jid)
        # the /status small fix: worker rows expose last_event_age_s
        # (dgrep top and the scale advisor read the same freshness)
        rows = svc.status()["workers"]
        assert rows and all("last_event_age_s" in r for r in rows.values())
        # a quiet job's explain report has NO disruptions key
        assert "disruptions" not in svc.job_explain(jid)
        # a job-tagged disruption lands in the report (the explain
        # satellite's daemon.jsonl sourcing, through the live daemon)
        svc._daemon_log.append_now("map_lost_output", job=jid, task=0)
        assert svc.job_explain(jid)["disruptions"] == {"lost_outputs": 1}
    finally:
        svc.stop()
    events = DaemonLog.read(root)
    kinds = [e["kind"] for e in events]
    assert kinds[0] == "start"
    assert "worker_attach" in kinds
    assert kinds[-1] == "stop"  # graceful stop is the LAST durable line
    terminal = [e for e in events if e["kind"] == "job_terminal"]
    assert [(e["payload"]["job"], e["payload"]["state"])
            for e in terminal] == [(jid, "done")]


def test_daemon_log_off_is_true_noop(tmp_path):
    """No DaemonLog attached = no daemon.jsonl, same /status keys —
    what DGREP_DAEMON_LOG=0 means (the serve path constructs None)."""
    root = tmp_path / "svc"
    svc = GrepService(work_root=root, task_timeout_s=5.0,
                      sweep_interval_s=0.1)
    try:
        jid = svc.submit(_tiny_cfg(tmp_path))
        svc.start_local_workers(1)
        assert svc.wait_job(jid, timeout=60), svc.job_status(jid)
        assert "disruptions" not in svc.job_explain(jid)
        assert "daemon" not in svc.status()
    finally:
        svc.stop()
    assert not (root / FILENAME).exists()


# ------------------------------------------------------- fleet trace

def _two_incarnation_root(tmp_path: Path) -> Path:
    """Synthetic failover: epoch 1 serves and dies (no stop line),
    epoch 2 parks, steals, promotes, serves a job, stops."""
    d1 = DaemonLog(tmp_path, epoch=1, role="active")
    d1.append_now("lease_acquire", addr="h:1")
    d1.stage("start", work_root=str(tmp_path))
    d1.flush()
    d1.close()
    d2 = DaemonLog(tmp_path, epoch=2, role="active")
    d2.stage("standby_park", parked_s=1.5)
    d2.append_now("lease_steal", addr="h:2", prev_epoch=1)
    d2.append_now("promoted", addr="h:2", failover_s=2.25,
                  running=1, queued=0)
    d2.stage("job_terminal", job="job-000001", state="done")
    d2.append_now("stop")
    d2.close()
    return tmp_path


def test_fleet_trace_two_incarnations_golden(tmp_path):
    root = _two_incarnation_root(tmp_path)
    job_events = [
        {"t": "span", "name": "map:compute", "ts": 10.0, "dur": 0.5,
         "worker": 0, "args": {}},
    ]
    doc = export_fleet_trace(DaemonLog.read(root),
                             jobs={"job-000001": job_events})
    evs = doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms"
    # daemon fleet is pid 1 and sorts ABOVE the job processes
    pnames = {e["pid"]: e["args"]["name"] for e in evs
              if e["ph"] == "M" and e["name"] == "process_name"}
    assert pnames[1] == "dgrep daemon fleet"
    assert pnames[2] == "dgrep job job-000001"
    sort_idx = {e["pid"]: e["args"]["sort_index"] for e in evs
                if e["ph"] == "M" and e["name"] == "process_sort_index"}
    assert sort_idx[1] < sort_idx[2]
    # one daemon row per epoch, epoch-ordered top to bottom
    tnames = {e["tid"]: e["args"]["name"] for e in evs
              if e["ph"] == "M" and e["name"] == "thread_name"
              and e["pid"] == 1}
    assert [n for _, n in sorted(tnames.items())] == sorted(tnames.values())
    assert any(n.startswith("daemon epoch 1") for n in tnames.values())
    assert any(n.startswith("daemon epoch 2") for n in tnames.values())
    # lease epochs render as spans; the promotion latency is a span
    # from the steal to the promoted event on epoch 2's row
    spans = {e["name"]: e for e in evs if e["ph"] == "X" and e["pid"] == 1}
    assert "lease epoch 1" in spans and "lease epoch 2" in spans
    promo = spans["promotion"]
    assert promo["args"]["failover_s"] == 2.25
    steal_ts = next(e["ts"] for e in evs if e["ph"] == "i"
                    and e["name"] == "lease_steal")
    assert promo["ts"] == steal_ts and promo["dur"] > 0
    # every daemon event lands as an instant on its epoch's row
    instants = [e["name"] for e in evs if e["ph"] == "i" and e["pid"] == 1]
    assert {"lease_acquire", "start", "standby_park", "lease_steal",
            "promoted", "job_terminal", "stop"} <= set(instants)
    # the job's own events merged under its pid
    assert any(e["ph"] == "X" and e["pid"] == 2
               and e["name"] == "map:compute" for e in evs)
    json.dumps(doc)  # whole doc stays JSON-serializable


# ------------------------------------------------- explain disruptions

def test_disruptions_view_windowing():
    ev = [
        {"ts": 5.0, "epoch": 1, "kind": "start"},
        {"ts": 12.0, "epoch": 1, "kind": "quarantine",
         "payload": {"worker": 0}},
        {"ts": 13.0, "epoch": 1, "kind": "map_lost_output",
         "payload": {"job": "job-000001", "task": 3}},
        {"ts": 13.5, "epoch": 1, "kind": "map_lost_output",
         "payload": {"job": "job-OTHER", "task": 1}},
        {"ts": 14.0, "epoch": 2, "kind": "promoted",
         "payload": {"failover_s": 2.5}},
        {"ts": 15.0, "epoch": 2, "kind": "resume"},
        {"ts": 99.0, "epoch": 2, "kind": "quarantine"},  # after finish
    ]
    view = disruptions_view(ev, "job-000001",
                            submitted_at=10.0, finished_at=20.0)
    assert view == {
        "quarantines": 1, "lost_outputs": 1, "daemon_restarts": 1,
        "failovers": 1, "max_failover_s": 2.5,
    }
    # the boot that ADMITTED the job is not a disruption
    assert "daemon_restarts" not in disruptions_view(
        ev[:1], "job-000001", submitted_at=5.0, finished_at=20.0)
    # job-tagged lost outputs count regardless of window (ids never
    # recycle — the revocation names its tenant directly)
    assert disruptions_view(ev, "job-000001",
                            submitted_at=50.0, finished_at=60.0) == \
        {"lost_outputs": 1}
    # nonzero-only: a quiet window for an untouched job answers {}
    assert disruptions_view(ev, "job-000099",
                            submitted_at=50.0, finished_at=60.0) == {}
    assert disruptions_view([], "job-000001") == {}

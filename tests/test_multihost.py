"""Multi-host glue (parallel/multihost.py): arg/env resolution for
jax.distributed, and a two-process CLI job bound to a non-loopback
interface — the closest this single machine gets to the reference's
actually-deployed two-Raspberry-Pi topology (coordinator.go:316-327).

Real federation cannot run here (this JAX build does not federate CPU
processes — CLAUDE.md); jax.distributed.initialize is therefore recorded,
not executed, and multi-host SPMD logic is validated on the virtual mesh
(tests/test_parallel.py).
"""

from __future__ import annotations

import json
import socket
import subprocess
import sys
import time
from pathlib import Path

import jax
import pytest

from distributed_grep_tpu.parallel import multihost


@pytest.fixture
def record_init(monkeypatch):
    calls = []
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: calls.append(kw)
    )
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "local_device_count", lambda: 4)
    monkeypatch.setattr(jax, "device_count", lambda: 8)
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    return calls


def test_no_address_means_single_process(record_init):
    assert multihost.init_distributed() is False
    assert record_init == []


def test_explicit_args(record_init):
    assert multihost.init_distributed("10.0.0.1:9999", 2, 1) is True
    assert record_init == [
        {"coordinator_address": "10.0.0.1:9999", "num_processes": 2, "process_id": 1}
    ]


def test_env_resolution(record_init, monkeypatch):
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.2:1111")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    monkeypatch.setenv("JAX_PROCESS_ID", "3")
    assert multihost.init_distributed() is True
    assert record_init == [
        {"coordinator_address": "10.0.0.2:1111", "num_processes": 4, "process_id": 3}
    ]


def test_args_override_env(record_init, monkeypatch):
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.2:1111")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    monkeypatch.setenv("JAX_PROCESS_ID", "3")
    assert multihost.init_distributed("10.9.9.9:2222", process_id=0) is True
    assert record_init == [
        {"coordinator_address": "10.9.9.9:2222", "num_processes": 4, "process_id": 0}
    ]


def test_partial_spec_omits_kwargs(record_init):
    """jax.distributed can infer num_processes/process_id on real TPU pods;
    only pass what was configured."""
    assert multihost.init_distributed("10.0.0.1:9999") is True
    assert record_init == [{"coordinator_address": "10.0.0.1:9999"}]


def test_process_id_zero_env(record_init, monkeypatch):
    """'0' from the environment must not be dropped as falsy."""
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.2:1111")
    monkeypatch.setenv("JAX_PROCESS_ID", "0")
    assert multihost.init_distributed() is True
    assert record_init[0]["process_id"] == 0


def test_local_mesh_devices_are_local():
    assert multihost.local_mesh_devices() == jax.local_devices()


def test_http_worker_calls_init_distributed(monkeypatch, tmp_path, corpus):
    """The HTTP worker entry point wires the glue: with the JAX env vars
    set, run_http_worker must call init_distributed before working."""
    from distributed_grep_tpu.runtime import http_transport
    from distributed_grep_tpu.runtime.http_coordinator import CoordinatorServer
    from distributed_grep_tpu.utils.config import JobConfig

    called = []
    monkeypatch.setattr(
        "distributed_grep_tpu.parallel.multihost.init_distributed",
        lambda *a, **k: called.append(True) or False,
    )
    server = CoordinatorServer(JobConfig(
        input_files=[str(p) for p in corpus.values()],
        app_options={"pattern": "hello"},
        n_reduce=2,
        work_dir=str(tmp_path / "job"),
        coordinator_port=0,
    ))
    server.start()
    try:
        http_transport.run_http_worker(f"127.0.0.1:{server.port}")
        assert called == [True]
        assert server.wait_done(timeout=10.0)
    finally:
        server.shutdown(linger_s=0.1)


# ------------------------------------------------- non-loopback two-process

def _primary_ip() -> str | None:
    """The host's non-loopback address, if it has one."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("192.0.2.1", 80))  # no traffic sent (UDP)
            ip = s.getsockname()[0]
        return None if ip.startswith("127.") else ip
    except OSError:
        return None


@pytest.mark.slow
def test_two_process_job_non_loopback(tmp_path, corpus, coordinator_port_reader):
    """Coordinator and worker as separate processes over the host's real
    interface (not loopback), distinct working directories — the deployed
    shape of the reference (2 Raspberry Pis + a host, README.md:5)."""
    ip = _primary_ip()
    if ip is None:
        pytest.skip("host has no non-loopback interface")
    cfg = tmp_path / "job.json"
    cfg.write_text(json.dumps({
        "input_files": [str(p) for p in corpus.values()],
        "application": "distributed_grep_tpu.apps.grep",
        "app_options": {"pattern": "hello"},
        "n_reduce": 2,
        "work_dir": str(tmp_path / "coord-wd"),  # coordinator-private
        "coordinator_host": ip,
        "coordinator_port": 0,
    }))
    import os
    import re as re_mod

    env = {**os.environ, "DGREP_LOG": "INFO",
           # worker-private spool/temp dir — proves no shared filesystem
           "DGREP_SPOOL_DIR": str(tmp_path / "worker-tmp"),
           "TMPDIR": str(tmp_path / "worker-tmp")}
    (tmp_path / "worker-tmp").mkdir()
    coord = subprocess.Popen(
        [sys.executable, "-m", "distributed_grep_tpu", "coordinator",
         "--config", str(cfg)],
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
        env={**env, "PYTHONPATH": ""}, cwd=str(Path(__file__).resolve().parents[1]),
    )
    try:
        port = coordinator_port_reader(coord)
        assert port
        worker = subprocess.run(
            [sys.executable, "-m", "distributed_grep_tpu", "worker",
             "--addr", f"{ip}:{port}"],
            capture_output=True, timeout=120, env=env,
            cwd=str(Path(__file__).resolve().parents[1]),
        )
        assert worker.returncode == 0, worker.stderr[-800:]
        assert coord.wait(timeout=30) == 0
    finally:
        if coord.poll() is None:
            coord.kill()
            coord.wait()
    out = b"".join(
        p.read_bytes() for p in (tmp_path / "coord-wd" / "out").glob("mr-out-*")
    )
    assert b"hello world" in out and b"fox says hello" in out


# ------------------------------------------- multi-host mesh feed (r3 item 2)

class _FakeDev:
    def __init__(self, pid, i):
        self.process_index = pid
        self.id = i

    def __hash__(self):
        return self.id

    def __eq__(self, other):
        return self.id == other.id


class _FakeSharding:
    """A 2-process, 4-device topology: devices 0-1 on process 0, 2-3 on
    process 1; lane axis split 4 ways."""

    def __init__(self):
        self.devs = [_FakeDev(i // 2, i) for i in range(4)]

    def devices_indices_map(self, shape):
        chunk, s, lanes = shape
        per = s // 4
        return {
            d: (slice(None), slice(i * per, (i + 1) * per), slice(None))
            for i, d in enumerate(self.devs)
        }


def test_local_shard_index_map_materializes_only_local_blocks():
    """The multi-host feed contract: a process builds device shards ONLY
    for its own devices (device_put of the full array onto a mesh spanning
    hosts would try to address remote chips)."""
    from distributed_grep_tpu.parallel import sharded_kernels as sk

    sharding = _FakeSharding()
    shape = (512, 8, 128)
    for pid in (0, 1):
        local = sk._local_shard_index_map(sharding, shape, process_index=pid)
        assert {d.id for d in local} == ({0, 1} if pid == 0 else {2, 3})
        for d, idx in local.items():
            lo, hi = idx[1].start, idx[1].stop
            assert hi - lo == 2  # its 2-of-8 lane-block slice, nothing more


def test_multihost_feed_path_bit_identical(monkeypatch):
    """Force the process_count>1 branch on the virtual mesh (all devices
    local, so the shard assembly must reproduce the device_put result
    exactly) — covers _put_spec end-to-end through a real kernel."""
    from distributed_grep_tpu.models.shift_and import try_compile_shift_and
    from distributed_grep_tpu.ops import layout as layout_mod
    from distributed_grep_tpu.parallel import sharded_kernels as sk
    from distributed_grep_tpu.parallel.mesh import make_mesh

    import numpy as np

    mesh8 = make_mesh((8,), ("data",))
    data = (b"a needle in a haystack " * 400 + b"\n") * 8
    model = try_compile_shift_and("needle")
    mult = sk.mesh_lane_multiple(mesh8, "data")
    lay = layout_mod.choose_layout(
        len(data), target_lanes=mult, min_chunk=512,
        lane_multiple=mult, chunk_multiple=512,
    )
    arr = layout_mod.to_device_array(data, lay)
    ref_words, ref_total = sk.sharded_shift_and_words(
        arr, model, mesh8, interpret=True
    )
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    mh_words, mh_total = sk.sharded_shift_and_words(
        arr, model, mesh8, interpret=True
    )
    assert int(mh_total) == int(ref_total)
    assert (np.asarray(mh_words) == np.asarray(ref_words)).all()


def test_engine_mesh_scan_under_forced_multihost(monkeypatch):
    """Whole-engine scan with the multi-process feed branch forced on the
    virtual mesh: segment tiles AND (for FDR) table arrays go through the
    per-process shard assembly, and the output stays oracle-exact."""
    from distributed_grep_tpu.ops.engine import GrepEngine
    from distributed_grep_tpu.parallel.mesh import make_mesh

    mesh8 = make_mesh((8,), ("data",))
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    data = (b"a needle here\n" + b"no hit line\n" * 6) * 300
    eng = GrepEngine("needle", mesh=mesh8, interpret=True)
    assert eng.mode == "shift_and"
    got = set(eng.scan(data).matched_lines.tolist())
    want = {
        i for i, ln in enumerate(data.split(b"\n")[:-1], 1) if b"needle" in ln
    }
    assert got == want
    assert eng.stats.get("psum_candidates", 0) >= 1

    # FDR under the same forced topology: segment tiles AND the EP table
    # stack (pattern_axis on a 2D mesh) go through the per-process shard
    # assembly (_put_spec)
    mesh2d = make_mesh((4, 2), ("data", "seq"))
    fdr_pats = ["needle", "volcano", "abcdef", "fedcba",
                "zzebra", "gabhcd", "hhfgab", "deadbe"]
    eng_fdr = GrepEngine(patterns=fdr_pats, mesh=mesh2d, mesh_axis="data",
                         pattern_axis="seq", interpret=True)
    assert eng_fdr.mode == "fdr"
    got2 = set(eng_fdr.scan(data).matched_lines.tolist())
    sp = {p.encode() for p in fdr_pats}
    want2 = {
        i for i, ln in enumerate(data.split(b"\n")[:-1], 1)
        if any(p in ln for p in sp)
    }
    assert got2 == want2
    assert eng_fdr.stats.get("psum_candidates", 0) >= 1

"""Lease-fenced daemon failover (round 18, runtime/lease.py).

The active/standby control plane's primitives and fences, in-process:

* ``WorkRootLease`` lifecycle — O_EXCL acquire, TTL-gated steal with the
  epoch bumped and a fresh token, loser-detects on concurrent steals,
  deposed ``renew()`` never clobbers the winner, graceful ``release``;
* the daemon-scope WRITE FENCE — a ``GrepService`` whose lease was
  stolen drops its staged registry flush (never interleaves), deposes
  itself, and closes admission;
* single-daemon NO-OP pins — no lease attached means no LEASE file, no
  "role" key in /status, token-free registry submit lines (the PR-15
  wire shapes, unchanged);
* the satellites — submit_token dedup (in-process and across a resume),
  the promoted daemon seeding its worker table from the registry's last
  pre-failover snapshot, and the ``StandbyServer`` park surface;
* client rotation — ``client_call`` over a comma-separated address list
  fails over from a dead address to the live one inside the one shared
  retry loop.

The subprocess SIGKILL-the-active matrix lives in tests/test_chaos.py.
Standalone: ``python -m pytest tests/test_lease.py -q`` (marker
``service``).
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
from dataclasses import replace as dc_replace
from pathlib import Path

import pytest

from distributed_grep_tpu.runtime.http_transport import (
    HttpTransport,
    client_call,
    split_addrs,
)
from distributed_grep_tpu.runtime.lease import (
    WorkRootLease,
    env_lease_renew_s,
    env_lease_ttl_s,
    lease_configured,
)
from distributed_grep_tpu.runtime.service import (
    AdmissionError,
    GrepService,
    ServiceRegistry,
    ServiceServer,
    StandbyServer,
)
from distributed_grep_tpu.utils.config import JobConfig

pytestmark = pytest.mark.service


# ---------------------------------------------------------------- env knobs

def test_lease_env_knob_parsers(monkeypatch):
    monkeypatch.delenv("DGREP_LEASE_TTL_S", raising=False)
    monkeypatch.delenv("DGREP_LEASE_RENEW_S", raising=False)
    assert env_lease_ttl_s() == 10.0
    assert env_lease_renew_s() == pytest.approx(10.0 / 3.0)
    assert lease_configured() is False
    monkeypatch.setenv("DGREP_LEASE_TTL_S", "6")
    assert env_lease_ttl_s() == 6.0
    assert env_lease_renew_s() == pytest.approx(2.0)  # ttl/3 default
    assert lease_configured() is True
    monkeypatch.setenv("DGREP_LEASE_RENEW_S", "0.5")
    assert env_lease_renew_s() == 0.5
    # malformed / non-positive fall back (a zero TTL would make every
    # lease instantly stealable — never what an operator means)
    monkeypatch.setenv("DGREP_LEASE_TTL_S", "banana")
    assert env_lease_ttl_s() == 10.0
    monkeypatch.setenv("DGREP_LEASE_TTL_S", "-3")
    assert env_lease_ttl_s() == 10.0
    monkeypatch.setenv("DGREP_LEASE_RENEW_S", "0")
    monkeypatch.setenv("DGREP_LEASE_TTL_S", "9")
    assert env_lease_renew_s() == pytest.approx(3.0)


# ----------------------------------------------------------- lease lifecycle

def _backdate(work_root: Path, by_s: float) -> None:
    """Age the on-disk lease record: the stamp a stale active leaves."""
    path = work_root / "LEASE"
    doc = json.loads(path.read_text())
    doc["renewed"] -= by_s
    path.write_text(json.dumps(doc, sort_keys=True))


def test_acquire_fresh_then_contender_parks(tmp_path):
    a = WorkRootLease(tmp_path, addr="127.0.0.1:1", ttl_s=60.0)
    assert a.acquire() is True
    assert a.epoch == 1 and a.token
    assert a.verify() is True
    rec = WorkRootLease.read(tmp_path)
    assert rec["addr"] == "127.0.0.1:1" and rec["epoch"] == 1
    # a second daemon against a LIVE lease parks (becomes a standby)
    b = WorkRootLease(tmp_path, ttl_s=60.0)
    assert b.acquire() is False
    assert b.verify() is False
    # the live holder renews
    before = WorkRootLease.read(tmp_path)["renewed"]
    time.sleep(0.01)
    assert a.renew() is True
    assert WorkRootLease.read(tmp_path)["renewed"] > before


def test_steal_after_ttl_deposed_renew_never_clobbers(tmp_path):
    a = WorkRootLease(tmp_path, addr="old", ttl_s=0.5)
    assert a.acquire()
    _backdate(tmp_path, 5.0)
    b = WorkRootLease(tmp_path, addr="new", ttl_s=0.5)
    assert b.acquire() is True
    assert b.epoch == 2  # the steal bumps the epoch past the stale holder
    assert b.token != a.token
    assert WorkRootLease.read(tmp_path)["addr"] == "new"
    # the deposed holder: verify false, renew false WITHOUT writing
    assert a.verify() is False
    on_disk = (tmp_path / "LEASE").read_bytes()
    assert a.renew() is False
    assert (tmp_path / "LEASE").read_bytes() == on_disk  # never clobbered
    # a deposed release is a no-op: the winner's lease file survives
    a.release()
    assert b.verify() is True
    # the winner's release removes it — the graceful-handoff path
    b.release()
    assert not (tmp_path / "LEASE").exists()
    assert b.verify() is False


def test_concurrent_stealers_loser_detects(tmp_path):
    """Two stealers race a stale lease: both replace, the LAST writer
    wins, and the loser's re-read token mismatch demotes it — modeled
    as back-to-back steals (the second lands after the first's re-read,
    the worst interleave the token check must catch)."""
    a = WorkRootLease(tmp_path, ttl_s=0.2)
    assert a.acquire()
    _backdate(tmp_path, 5.0)
    b = WorkRootLease(tmp_path, ttl_s=0.2)
    assert b.acquire() is True and b.epoch == 2
    _backdate(tmp_path, 5.0)  # b goes silent too
    c = WorkRootLease(tmp_path, ttl_s=0.2)
    assert c.acquire() is True and c.epoch == 3
    # b is now the loser: every ownership probe answers deposed
    assert b.verify() is False and b.renew() is False
    assert c.verify() is True
    # epochs strictly order incarnations — a revived deposed holder
    # always sees a larger epoch than its own
    assert WorkRootLease.read(tmp_path)["epoch"] > b.epoch - 1


def test_torn_lease_file_treated_stale(tmp_path):
    (tmp_path / "LEASE").write_bytes(b'{"epoch": 7, "tok')  # torn write
    assert WorkRootLease.read(tmp_path) is None
    b = WorkRootLease(tmp_path, ttl_s=60.0)
    assert b.acquire() is True  # unreadable record never wedges election
    assert b.verify() is True


def test_release_hands_off_without_ttl_wait(tmp_path):
    a = WorkRootLease(tmp_path, ttl_s=3600.0)
    assert a.acquire()
    a.release()
    b = WorkRootLease(tmp_path, ttl_s=3600.0)
    assert b.acquire() is True  # O_EXCL path: no TTL wait after release
    assert b.epoch == 1


def test_renewal_thread_fires_on_lost_once_and_stops(tmp_path):
    a = WorkRootLease(tmp_path, ttl_s=60.0)
    assert a.acquire()
    lost = threading.Event()
    lost_calls = []
    renews = []
    a.start_renewal(
        on_lost=lambda: (lost_calls.append(1), lost.set()),
        on_renew=lambda: renews.append(1),
        interval_s=0.05,
    )
    deadline = time.monotonic() + 5
    while not renews:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    assert not lost.is_set()
    # a stealer replaces the record out from under the renewal thread
    (tmp_path / "LEASE").unlink()
    b = WorkRootLease(tmp_path, ttl_s=60.0)
    assert b.acquire()
    assert lost.wait(timeout=5)
    time.sleep(0.2)  # the loop must have STOPPED: one on_lost, ever
    assert lost_calls == [1]
    assert b.verify() is True  # the winner's record was never touched
    a.stop_renewal()
    b.release()


# ---------------------------------------------------- single-daemon no-op

def _tiny_cfg(tmp_path: Path, **kw) -> JobConfig:
    p = tmp_path / "in.txt"
    if not p.exists():
        p.write_text("hello\nmiss\n")
    return JobConfig(
        input_files=[str(p)],
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": "hello", "backend": "cpu"},
        n_reduce=1,
        **kw,
    )


def test_no_lease_single_daemon_true_noop(tmp_path):
    """The PR-15 shapes, unchanged: a lease-free daemon writes no LEASE
    file, answers /status without a "role" key, and its registry submit
    lines carry no submit_token key (wire-elided when absent)."""
    svc = GrepService(work_root=tmp_path / "svc", task_timeout_s=5.0,
                      sweep_interval_s=0.1)
    try:
        jid = svc.submit(_tiny_cfg(tmp_path))
        svc.start_local_workers(1)
        assert svc.wait_job(jid, timeout=60), svc.job_status(jid)
        status = svc.status()
        assert "role" not in status
        assert not (tmp_path / "svc" / "LEASE").exists()
    finally:
        svc.stop()
    lines = [json.loads(ln) for ln in
             (tmp_path / "svc" / "jobs.jsonl").read_text().splitlines()
             if ln.strip()]
    submits = [e for e in lines if e.get("kind") == "job_submit"]
    assert submits
    for e in submits:
        assert "submit_token" not in (e.get("config") or {})
    assert not any(e.get("kind") == "workers" for e in lines)


# ------------------------------------------------------------ write fence

def test_fence_drops_staged_flush_and_deposes(tmp_path):
    """The tentpole fence: a standby steals the lease while a registry
    batch sits staged — the deposed daemon DROPS the batch (the promoted
    daemon owns those records now; an interleaved stale append would
    become replay's trusted last state), flips to deposed, and closes
    admission."""
    root = tmp_path / "svc"
    lease = WorkRootLease(root, addr="me", ttl_s=0.3)
    root.mkdir()
    assert lease.acquire()
    svc = GrepService(work_root=root, lease=lease,
                      task_timeout_s=5.0, sweep_interval_s=0.1)
    jid = svc.submit(_tiny_cfg(tmp_path))
    assert svc.status()["role"] == "active"
    registry = root / "jobs.jsonl"
    before = registry.read_bytes()
    # the standby steals (the active went silent past the TTL)
    _backdate(root, 5.0)
    thief = WorkRootLease(root, addr="thief", ttl_s=0.3)
    assert thief.acquire()
    # next durable transition: staged, then FENCED at flush time
    svc.cancel(jid)
    assert svc.deposed_event.wait(timeout=5)
    assert registry.read_bytes() == before  # the stale batch never landed
    assert svc.status()["role"] == "deposed"
    with pytest.raises(AdmissionError):
        svc.submit(_tiny_cfg(tmp_path))  # admission closed
    # deposed stop() must not delete the winner's lease file
    svc.stop()
    assert thief.verify() is True
    thief.release()


def test_deposed_submit_rejected_before_durable_register(tmp_path):
    """A submit racing the deposition must not durably register a job
    the promoted active will never learn about — the client's rotation
    re-POSTs against the winner (the submit_token makes that safe)."""
    root = tmp_path / "svc"
    lease = WorkRootLease(root, addr="me", ttl_s=0.3)
    root.mkdir()
    assert lease.acquire()
    svc = GrepService(work_root=root, lease=lease)
    _backdate(root, 5.0)
    thief = WorkRootLease(root, addr="thief", ttl_s=0.3)
    assert thief.acquire()
    before = (root / "jobs.jsonl").read_bytes() \
        if (root / "jobs.jsonl").exists() else b""
    with pytest.raises(AdmissionError):
        svc.submit(_tiny_cfg(tmp_path, submit_token="tok-race"))
    after = (root / "jobs.jsonl").read_bytes() \
        if (root / "jobs.jsonl").exists() else b""
    assert after == before  # no job_submit line from the deposed daemon
    svc.stop()
    thief.release()


# --------------------------------------------------- submit-token satellite

def test_submit_token_dedup_inprocess_and_across_resume(tmp_path):
    cfg = _tiny_cfg(tmp_path, submit_token="tok-abc")
    svc = GrepService(work_root=tmp_path / "svc", task_timeout_s=5.0,
                      sweep_interval_s=0.1)
    jid = svc.submit(cfg)
    assert svc.submit(cfg) == jid  # duplicate delivery: same job
    # distinct tokens mint distinct jobs
    assert svc.submit(dc_replace(cfg, submit_token="tok-xyz")) != jid
    svc.start_local_workers(1)
    assert svc.wait_job(jid, timeout=60), svc.job_status(jid)
    svc.stop()
    # the dedup map survives a restart: rebuilt from registry submit
    # lines, so a re-POST to the PROMOTED daemon lands on the same job
    svc2 = GrepService(work_root=tmp_path / "svc")
    try:
        assert svc2.submit(cfg) == jid
    finally:
        svc2.stop()


# ----------------------------------------------- worker-seeding satellite

def test_promotion_seeds_worker_table_from_snapshot(tmp_path):
    """The promoted daemon adopts the deposed active's last renewal-time
    worker snapshot: scale_advice sees the attached fleet immediately,
    and the id allocator jumps past every seeded id."""
    root = tmp_path / "svc"
    root.mkdir()
    reg = ServiceRegistry(root)
    reg.record_workers({
        "3": {"job": "job-1", "data_endpoint": "http://w3:9"},
        "7": {"job": None},
        "bogus": {"job": None},  # non-numeric ids are skipped, not fatal
    })
    reg.close()
    assert ServiceRegistry.replay_workers(root)["3"]["job"] == "job-1"
    lease = WorkRootLease(root, ttl_s=60.0)
    assert lease.acquire()
    svc = GrepService(work_root=root, lease=lease)
    try:
        assert set(svc.workers) == {3, 7}
        assert svc.workers[3]["data_endpoint"] == "http://w3:9"
        assert svc._next_worker_id >= 8  # fresh ids never collide
        rows = svc.status()["workers"]
        assert set(rows) == {"3", "7"}
    finally:
        svc.stop()
        lease.release()
    # startup compaction dropped the snapshot records: the next
    # promotion seeds nothing (workers re-register on their first poll)
    assert ServiceRegistry.replay_workers(root) == {}
    # lease-FREE construction never seeds, even with a snapshot present
    root2 = tmp_path / "svc2"
    root2.mkdir()
    reg2 = ServiceRegistry(root2)
    reg2.record_workers({"5": {"job": None}})
    reg2.close()
    svc2 = GrepService(work_root=root2)
    try:
        assert svc2.workers == {}
    finally:
        svc2.stop()


def test_lease_renewal_snapshots_worker_rows_change_gated(tmp_path):
    root = tmp_path / "svc"
    lease = WorkRootLease(root, ttl_s=60.0)
    root.mkdir()
    assert lease.acquire()
    svc = GrepService(work_root=root, lease=lease)
    try:
        svc.workers[4] = {"job": None, "task": None,
                          "seen": time.monotonic()}
        svc.lease_renewed()
        rows = ServiceRegistry.replay_workers(root)
        assert set(rows) == {"4"}
        size = (root / "jobs.jsonl").stat().st_size
        svc.lease_renewed()  # unchanged fleet: no second snapshot line
        assert (root / "jobs.jsonl").stat().st_size == size
    finally:
        svc.stop()
        lease.release()


# ------------------------------------------------------- standby surface

def test_standby_server_parks_workers_and_points_at_active(tmp_path):
    lease = WorkRootLease(tmp_path, addr="127.0.0.1:4242", ttl_s=60.0)
    assert lease.acquire()
    standby = StandbyServer(tmp_path, host="127.0.0.1", port=0).start()
    addr = f"127.0.0.1:{standby.port}"
    try:
        st = client_call(addr, "GET", "/status", retry=False)
        assert st == {"service": True, "role": "standby",
                      "active": "127.0.0.1:4242"}
        # assign polls park: retry + retry_after_s, the caller's id echoed
        # (WorkerLoop adopts reply.worker_id unconditionally — a -1 here
        # would un-register a parked worker)
        r = client_call(addr, "POST", "/rpc/AssignTask",
                        json.dumps({"worker_id": 9}).encode(), retry=False)
        assert r["assignment"] == "retry" and r["worker_id"] == 9
        assert r["retry_after_s"] == StandbyServer.PARK_RETRY_S
        # reduce pulls abort cleanly (the zombie fence's answer)
        r = client_call(addr, "POST", "/rpc/ReduceNextFile",
                        json.dumps({"task_id": 0}).encode(), retry=False)
        assert r["abort"] is True
        # submits and data traffic answer 503: rotation finds the active
        with pytest.raises(urllib.error.HTTPError) as ei:
            client_call(addr, "POST", "/jobs", b"{}", retry=False)
        assert ei.value.code == 503
        with pytest.raises(urllib.error.HTTPError) as ei:
            client_call(addr, "GET", "/jobs/job-1", retry=False)
        assert ei.value.code == 503
    finally:
        standby.shutdown()
        lease.release()


# ------------------------------------------------------- client rotation

def test_split_addrs_and_transport_rotation():
    assert split_addrs("a:1, b:2 ,,c:3") == ["a:1", "b:2", "c:3"]
    t = HttpTransport("127.0.0.1:1,127.0.0.1:2")
    assert t.base == "http://127.0.0.1:1"
    t._count_retry()  # a connectivity failure rotates to the next base
    assert t.base == "http://127.0.0.1:2"
    t._count_retry()
    assert t.base == "http://127.0.0.1:1"  # round-robin wraps
    # single-address transports never rotate (the historical behavior)
    s = HttpTransport("127.0.0.1:1")
    s._count_retry()
    assert s.base == "http://127.0.0.1:1"


def test_client_call_rotates_to_live_address(tmp_path, monkeypatch):
    """The failover dial: first address dead (connection refused), the
    shared retry loop rotates to the live standby-list peer and the call
    lands — no new retry machinery, the round-10 loop grew a hook."""
    monkeypatch.setenv("DGREP_RPC_BACKOFF_S", "0.05")
    svc = GrepService(work_root=tmp_path / "svc")
    server = ServiceServer(svc)
    server.start()
    try:
        dead = "127.0.0.1:9"  # discard port: refuses fast
        status = client_call(f"{dead},127.0.0.1:{server.port}",
                             "GET", "/status", timeout=5.0)
        assert status["service"] is True
    finally:
        svc.stop()
        server.shutdown()


def test_client_call_rotates_past_parked_standby(tmp_path, monkeypatch):
    """The OTHER failover dial (caught live by an operator drive): the
    first listed address is a PARKED STANDBY — it ANSWERS, with 503, so
    the connectivity-failure rotation never fires.  A 503 is the one
    status the real daemon never sends (400/404/409/429 are its
    rejections) and the standby registered nothing, so the shared retry
    loop rotates and re-sends: a submit dialed standby-first must land
    on the active, not spin 503s until the client deadline."""
    monkeypatch.setenv("DGREP_RPC_BACKOFF_S", "0.05")
    lease = WorkRootLease(tmp_path / "root", addr="x", ttl_s=60.0)
    (tmp_path / "root").mkdir()
    assert lease.acquire()
    standby = StandbyServer(tmp_path / "root", host="127.0.0.1",
                            port=0).start()
    svc = GrepService(work_root=tmp_path / "svc")
    server = ServiceServer(svc)
    server.start()
    try:
        addrs = f"127.0.0.1:{standby.port},127.0.0.1:{server.port}"
        # a WRITE (the submit POST shape) rotates and is registered
        # exactly once (note /status would NOT rotate: the standby
        # answers it 200 with its own role — deliberately probeable)
        cfg = _tiny_cfg(tmp_path)
        reply = client_call(addrs, "POST", "/jobs",
                            cfg.to_json().encode(), timeout=10.0)
        svc.start_local_workers(1)
        assert svc.wait_job(reply["job_id"], timeout=30)
        # a 503'd READ rotates too: job polls dialed standby-first land
        st = client_call(addrs, "GET", f"/jobs/{reply['job_id']}",
                         timeout=5.0)
        assert st["state"] == "done"
        # single-address 503 keeps the strict no-retry contract
        with pytest.raises(urllib.error.HTTPError) as ei:
            client_call(f"127.0.0.1:{standby.port}", "GET", "/jobs/j",
                        timeout=5.0)
        assert ei.value.code == 503
    finally:
        svc.stop()
        server.shutdown()
        standby.shutdown()
        lease.release()

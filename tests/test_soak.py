"""Large-corpus endurance soak (VERDICT r3 item 8): an env-gated ~10 GB
end-to-end MapReduce job on the cpu backend asserting the three properties
the 100 GB north star needs — flat RSS, exact counts vs `grep -c`, and
journal-resume after a mid-corpus coordinator/worker crash.

Run with:  DGREP_SOAK=10G python -m pytest tests/test_soak.py -x -q -s
(any "<N>G" value scales the corpus; CI skips without the env var).
Measured wall/RSS recorded in BASELINE.md.
"""

from __future__ import annotations

import os
import re
import subprocess
import time

import numpy as np
import pytest

SOAK = os.environ.get("DGREP_SOAK", "")
_m = re.fullmatch(r"(\d+)G", SOAK)
SOAK_GB = int(_m.group(1)) if _m else 0

NEEDLE = b"soaktestneedle"


@pytest.mark.skipif(
    SOAK_GB < 1, reason="soak: set DGREP_SOAK=10G (or <N>G) to run"
)
def test_soak_end_to_end_job_with_resume(tmp_path):
    import resource

    from distributed_grep_tpu.runtime.job import run_job
    from distributed_grep_tpu.runtime.worker import WorkerKilled
    from distributed_grep_tpu.utils.config import JobConfig

    split_bytes = 500 * 1000 * 1000
    n_splits = max(2, (SOAK_GB * 1_000_000_000) // split_bytes)
    rng = np.random.default_rng(0)
    files = []
    t_gen = time.perf_counter()
    for i in range(n_splits):
        p = tmp_path / f"split{i:02d}.bin"
        with open(p, "wb") as f:
            for _ in range(split_bytes // (100 * 1000 * 1000)):
                block = rng.integers(32, 127, size=100_000_000, dtype=np.uint8)
                block[rng.integers(0, block.size, size=block.size // 80)] = 0x0A
                for pos in rng.integers(0, block.size - 64, size=25):
                    block[pos : pos + len(NEEDLE)] = np.frombuffer(NEEDLE, np.uint8)
                f.write(block.tobytes())
        files.append(str(p))
    print(f"\nsoak: generated {n_splits} x {split_bytes//1_000_000} MB "
          f"in {time.perf_counter()-t_gen:.0f}s")

    # oracle: GNU grep -c per split (matching LINES, the job's key unit)
    t_or = time.perf_counter()
    oracle = {}
    for p in files:
        with open(p, "rb") as fh:
            out = subprocess.run(
                ["grep", "-c", "-a", NEEDLE.decode()], stdin=fh,
                capture_output=True, text=True,
            )
        oracle[p] = int(out.stdout.strip() or 0)
    print(f"soak: grep -c oracle in {time.perf_counter()-t_or:.0f}s "
          f"({sum(oracle.values())} matched lines)")

    cfg = JobConfig(
        input_files=files,
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": NEEDLE.decode(), "backend": "cpu"},
        n_reduce=8,
        work_dir=str(tmp_path / "job"),
        task_timeout_s=60.0,
        sweep_interval_s=0.5,
    )

    rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    t_job = time.perf_counter()

    # Phase 1 — crash mid-corpus: the only worker dies after committing
    # about a third of the maps; run_job aborts with work outstanding.
    kill_after = max(1, n_splits // 3)
    done = {"n": 0}

    def die_midway():
        done["n"] += 1
        if done["n"] > kill_after:
            raise WorkerKilled()

    with pytest.raises(RuntimeError, match="all workers exited"):
        run_job(cfg, n_workers=1,
                fault_hooks_per_worker=[{"before_map_finished": die_midway}])

    # Phase 2 — restart with resume: journal replay must skip the
    # committed maps, and the job completes.
    res = run_job(cfg, n_workers=2, resume=True)
    wall = time.perf_counter() - t_job
    assigned = res.metrics["counters"]["map_assigned"]
    assert assigned <= n_splits - kill_after, (
        f"resume re-ran completed work: {assigned} assigned after "
        f"{kill_after} were journaled"
    )

    # exact counts vs grep -c, streamed (never materialize the result set)
    from distributed_grep_tpu.runtime.job import GREP_KEY_RE

    counts = dict.fromkeys(files, 0)
    for key, _v in res.iter_results():
        m = GREP_KEY_RE.match(key)
        assert m and m.group(1) in counts
        counts[m.group(1)] += 1
    assert counts == oracle

    rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    print(f"soak: job (crash+resume) wall {wall:.0f}s, "
          f"RSS growth {(rss1-rss0)/1024:.0f} MB, "
          f"{sum(oracle.values())} lines exact")
    # flat RSS: far below corpus size — two 64 MB stream chunks, the
    # reduce cap, and allocator noise; nowhere near the 10 GB corpus
    assert rss1 - rss0 < 1_500_000  # KB

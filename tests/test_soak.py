"""Large-corpus endurance soak (VERDICT r3 item 8): an env-gated ~10 GB
end-to-end MapReduce job on the cpu backend asserting the three properties
the 100 GB north star needs — flat RSS, exact counts vs `grep -c`, and
journal-resume after a mid-corpus coordinator/worker crash.

Run with:  DGREP_SOAK=10G python -m pytest tests/test_soak.py -x -q -s
(any "<N>G" value scales the corpus; CI skips without the env var).
Measured wall/RSS recorded in BASELINE.md.
"""

from __future__ import annotations

import os
import re
import subprocess
import time

import numpy as np
import pytest

SOAK = os.environ.get("DGREP_SOAK", "")
_m = re.fullmatch(r"(\d+)G", SOAK)
SOAK_GB = int(_m.group(1)) if _m else 0

NEEDLE = b"soaktestneedle"


@pytest.mark.skipif(
    SOAK_GB < 1, reason="soak: set DGREP_SOAK=10G (or <N>G) to run"
)
def test_soak_end_to_end_job_with_resume(tmp_path):
    import resource

    from distributed_grep_tpu.runtime.job import run_job
    from distributed_grep_tpu.runtime.worker import WorkerKilled
    from distributed_grep_tpu.utils.config import JobConfig

    split_bytes = 500 * 1000 * 1000
    n_splits = max(2, (SOAK_GB * 1_000_000_000) // split_bytes)
    rng = np.random.default_rng(0)
    files = []
    t_gen = time.perf_counter()
    for i in range(n_splits):
        p = tmp_path / f"split{i:02d}.bin"
        with open(p, "wb") as f:
            for _ in range(split_bytes // (100 * 1000 * 1000)):
                block = rng.integers(32, 127, size=100_000_000, dtype=np.uint8)
                block[rng.integers(0, block.size, size=block.size // 80)] = 0x0A
                for pos in rng.integers(0, block.size - 64, size=25):
                    block[pos : pos + len(NEEDLE)] = np.frombuffer(NEEDLE, np.uint8)
                f.write(block.tobytes())
        files.append(str(p))
    print(f"\nsoak: generated {n_splits} x {split_bytes//1_000_000} MB "
          f"in {time.perf_counter()-t_gen:.0f}s")

    # oracle: GNU grep -c per split (matching LINES, the job's key unit)
    t_or = time.perf_counter()
    oracle = {}
    for p in files:
        with open(p, "rb") as fh:
            out = subprocess.run(
                ["grep", "-c", "-a", NEEDLE.decode()], stdin=fh,
                capture_output=True, text=True,
            )
        oracle[p] = int(out.stdout.strip() or 0)
    print(f"soak: grep -c oracle in {time.perf_counter()-t_or:.0f}s "
          f"({sum(oracle.values())} matched lines)")

    cfg = JobConfig(
        input_files=files,
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": NEEDLE.decode(), "backend": "cpu"},
        n_reduce=8,
        work_dir=str(tmp_path / "job"),
        task_timeout_s=60.0,
        sweep_interval_s=0.5,
    )

    rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    t_job = time.perf_counter()

    # Phase 1 — crash mid-corpus: the only worker dies after committing
    # about a third of the maps; run_job aborts with work outstanding.
    kill_after = max(1, n_splits // 3)
    done = {"n": 0}

    def die_midway():
        done["n"] += 1
        if done["n"] > kill_after:
            raise WorkerKilled()

    with pytest.raises(RuntimeError, match="all workers exited"):
        run_job(cfg, n_workers=1,
                fault_hooks_per_worker=[{"before_map_finished": die_midway}])

    # Phase 2 — restart with resume: journal replay must skip the
    # committed maps, and the job completes.
    res = run_job(cfg, n_workers=2, resume=True)
    wall = time.perf_counter() - t_job
    assigned = res.metrics["counters"]["map_assigned"]
    assert assigned <= n_splits - kill_after, (
        f"resume re-ran completed work: {assigned} assigned after "
        f"{kill_after} were journaled"
    )

    # exact counts vs grep -c, streamed (never materialize the result set)
    from distributed_grep_tpu.runtime.job import GREP_KEY_RE

    counts = dict.fromkeys(files, 0)
    for key, _v in res.iter_results():
        m = GREP_KEY_RE.match(key)
        assert m and m.group(1) in counts
        counts[m.group(1)] += 1
    assert counts == oracle

    rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    print(f"soak: job (crash+resume) wall {wall:.0f}s, "
          f"RSS growth {(rss1-rss0)/1024:.0f} MB, "
          f"{sum(oracle.values())} lines exact")
    # flat RSS: far below corpus size — two 64 MB stream chunks, the
    # reduce cap, and allocator noise; nowhere near the 10 GB corpus
    assert rss1 - rss0 < 1_500_000  # KB


# ----------------------------------------------------------------- mini-soak
#
# Always-on CI tier (round-6 VERDICT item 8): the rolling-window protocol —
# generator thread writing splits ahead of the scan, reaper deleting each
# split once its map commit hits the journal, mid-run crash + journal
# resume, exact per-split counts — pinned CONTINUOUSLY at a <60 s scale
# (~256 MB, 16 splits, window 4) instead of only at manual
# DGREP_SOAK_ROLLING time.  Runs in the normal suite; also standalone:
#
#     python -m pytest tests/test_soak.py -m soak_mini -q
MINI_SPLIT_BYTES = 16 * 1000 * 1000
MINI_SPLITS = 16
MINI_WINDOW = 4


@pytest.mark.soak_mini
def test_mini_soak_rolling_window(tmp_path):
    import resource
    import shutil
    import threading

    from distributed_grep_tpu.runtime.job import run_job
    from distributed_grep_tpu.runtime.worker import WorkerKilled
    from distributed_grep_tpu.utils.config import JobConfig

    split_bytes, n_splits, window = MINI_SPLIT_BYTES, MINI_SPLITS, MINI_WINDOW
    rng = np.random.default_rng(11)

    template = tmp_path / "template.bin"
    block = rng.integers(32, 127, size=split_bytes, dtype=np.uint8)
    block[rng.integers(0, block.size, size=block.size // 80)] = 0x0A
    template.write_bytes(block.tobytes())

    files = [str(tmp_path / f"mini{i:03d}.bin") for i in range(n_splits)]
    for p in files:  # placeholders: the worker stats the path pre-app
        open(p, "wb").close()

    state = {"generated": 0, "deleted": 0, "stop": False, "gen_error": None}
    cv = threading.Condition()
    oracle: dict[str, int] = {}
    disk_peak = {"bytes": 0}

    def generate() -> None:
        try:
            for i, p in enumerate(files):
                with cv:
                    cv.wait_for(
                        lambda: state["stop"]
                        or state["generated"] - state["deleted"] < window
                    )
                    if state["stop"]:
                        return
                tmp = p + ".tmp"
                shutil.copyfile(template, tmp)
                n_needles = int(rng.integers(3, 40))
                with open(tmp, "r+b") as f:
                    for pos in rng.integers(
                        0, split_bytes - 64, size=n_needles
                    ):
                        f.seek(int(pos))
                        f.write(NEEDLE)
                with open(tmp, "rb") as fh:
                    out = subprocess.run(
                        ["grep", "-c", "-a", NEEDLE.decode()], stdin=fh,
                        capture_output=True, text=True,
                    )
                oracle[p] = int(out.stdout.strip() or 0)
                os.replace(tmp, p)
                open(p + ".ready", "wb").close()
                with cv:
                    state["generated"] = i + 1
                    resident = state["generated"] - state["deleted"]
                    disk_peak["bytes"] = max(
                        disk_peak["bytes"], resident * split_bytes
                    )
                    cv.notify_all()
        except BaseException as e:  # noqa: BLE001 — surfaced by the main thread
            with cv:
                state["gen_error"] = e
                state["stop"] = True
                cv.notify_all()

    from distributed_grep_tpu.utils.io import WorkDir

    journal_path = WorkDir(str(tmp_path / "job")).journal_path()

    def reap() -> None:
        from distributed_grep_tpu.runtime.journal import TaskJournal

        reaped: set[str] = set()
        while True:
            with cv:
                if state["stop"] and state["deleted"] >= state["generated"]:
                    return
            for e in TaskJournal.replay(journal_path):
                if e.get("kind") == "map_done":
                    p = e.get("file")
                    if p and p not in reaped and os.path.exists(p):
                        os.unlink(p)
                        os.path.exists(p + ".ready") and os.unlink(p + ".ready")
                        reaped.add(p)
                        with cv:
                            state["deleted"] = len(reaped)
                            cv.notify_all()
            with cv:
                if state["stop"]:
                    return
            time.sleep(0.2)

    app_py = tmp_path / "mini_rolling_app.py"
    app_py.write_text(
        "import os, time\n"
        "from distributed_grep_tpu.apps import grep_tpu as base\n"
        "configure = base.configure\n"
        "reduce_fn = base.reduce_fn\n"
        "reduce_is_identity = True\n"
        "set_progress = base.set_progress\n"
        "map_fn = base.map_fn\n"
        "def map_path_fn(filename, path):\n"
        "    fn = base._progress_fn()\n"
        "    t0 = time.monotonic()\n"
        "    while not os.path.exists(filename + '.ready'):\n"
        "        if time.monotonic() - t0 > 120:\n"
        "            raise RuntimeError('generator stalled')\n"
        "        fn and fn()\n"
        "        time.sleep(0.1)\n"
        "    return base.map_path_fn(filename, path)\n"
    )
    cfg = JobConfig(
        input_files=files,
        application=str(app_py),
        app_options={"pattern": NEEDLE.decode(), "backend": "cpu"},
        n_reduce=4,
        work_dir=str(tmp_path / "job"),
        task_timeout_s=30.0,
        sweep_interval_s=0.2,
    )

    rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    t_job = time.perf_counter()
    gen_t = threading.Thread(target=generate, name="mini-gen", daemon=True)
    reap_t = threading.Thread(target=reap, name="mini-reap", daemon=True)
    gen_t.start()
    reap_t.start()

    kill_after = max(1, n_splits // 3)
    done = {"n": 0}

    def die_midway():
        done["n"] += 1
        if done["n"] > kill_after:
            raise WorkerKilled()

    try:
        with pytest.raises(RuntimeError, match="all workers exited"):
            run_job(cfg, n_workers=1,
                    fault_hooks_per_worker=[{"before_map_finished": die_midway}])
        res = run_job(cfg, n_workers=2, resume=True)
    finally:
        with cv:
            state["stop"] = True
            cv.notify_all()
    gen_t.join(timeout=30)
    if state["gen_error"] is not None:
        raise state["gen_error"]
    wall = time.perf_counter() - t_job

    assigned = res.metrics["counters"]["map_assigned"]
    assert assigned <= n_splits - kill_after, (
        f"resume re-ran completed work: {assigned} assigned after "
        f"{kill_after} were journaled"
    )

    counts = dict.fromkeys(files, 0)
    from distributed_grep_tpu.runtime.job import GREP_KEY_RE

    for key, _v in res.iter_results():
        m = GREP_KEY_RE.match(key)
        assert m and m.group(1) in counts
        counts[m.group(1)] += 1
    assert counts == oracle
    rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    reap_t.join(timeout=30)
    print(f"\nmini-soak: {n_splits * split_bytes / 1e6:.0f} MB in "
          f"{wall:.0f}s, RSS growth {(rss1-rss0)/1024:.0f} MB, disk peak "
          f"{disk_peak['bytes']/1e6:.0f} MB, "
          f"{sum(oracle.values())} lines exact across {n_splits} splits")
    assert wall < 60, f"mini-soak over its time budget: {wall:.0f}s"
    assert disk_peak["bytes"] <= (window + 1) * split_bytes


@pytest.mark.soak_mini
def test_mini_soak_match_dense_native_records(tmp_path):
    """Round-8 mini-soak leg: a MATCH-DENSE window through the native
    map-record pipeline (DeferredBatch -> dgrep_build_records -> mr-out
    slabs) with a mid-run crash + journal resume — the new record path
    must stay crash/resume-exact.  Counts are pinned per split against a
    generation-time GNU ``grep -c`` oracle; unlike the rolling-window leg
    the corpus here is dense (~1 in 6 lines matches), so the record
    build, partition split, and identity collation all run at real
    volume across BOTH daemon lives.  Budget: < 60 s."""
    import resource

    from distributed_grep_tpu.runtime.job import run_job
    from distributed_grep_tpu.runtime.worker import WorkerKilled
    from distributed_grep_tpu.utils.config import JobConfig

    split_bytes = 3_000_000
    n_splits = 10
    rng = np.random.default_rng(41)
    files = []
    oracle: dict[str, int] = {}
    t_all = time.perf_counter()
    for i in range(n_splits):
        block = rng.integers(97, 123, size=split_bytes, dtype=np.uint8)
        block[rng.integers(0, block.size, size=block.size // 8)] = 0x20
        block[rng.integers(0, block.size, size=block.size // 45)] = 0x0A
        # dense plant: ~1 needle site per ~300 bytes -> ~1 in 6 lines
        for pos in rng.integers(0, block.size - 64, size=block.size // 300):
            block[pos : pos + len(NEEDLE)] = np.frombuffer(NEEDLE, np.uint8)
        p = tmp_path / f"dense{i:02d}.bin"
        p.write_bytes(block.tobytes())
        with open(p, "rb") as fh:
            out = subprocess.run(
                ["grep", "-c", "-a", NEEDLE.decode()], stdin=fh,
                capture_output=True, text=True,
            )
        oracle[str(p)] = int(out.stdout.strip() or 0)
        files.append(str(p))
    assert sum(oracle.values()) > 50_000, "corpus not dense enough to count"

    cfg = JobConfig(
        input_files=files,
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": NEEDLE.decode(), "backend": "cpu"},
        n_reduce=4,
        work_dir=str(tmp_path / "job"),
        task_timeout_s=30.0,
        sweep_interval_s=0.2,
    )

    rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    kill_after = max(1, n_splits // 3)
    done = {"n": 0}

    def die_midway():
        done["n"] += 1
        if done["n"] > kill_after:
            raise WorkerKilled()

    # Phase 1 — crash mid-corpus after ~1/3 of the maps committed.
    with pytest.raises(RuntimeError, match="all workers exited"):
        run_job(cfg, n_workers=1,
                fault_hooks_per_worker=[{"before_map_finished": die_midway}])
    # Phase 2 — journal resume completes only the uncommitted remainder.
    res = run_job(cfg, n_workers=2, resume=True)
    assigned = res.metrics["counters"]["map_assigned"]
    assert assigned <= n_splits - kill_after, (
        f"resume re-ran completed work: {assigned} assigned after "
        f"{kill_after} were journaled"
    )

    from distributed_grep_tpu.runtime.job import GREP_KEY_RE

    counts = dict.fromkeys(files, 0)
    for key, _v in res.iter_results():
        m = GREP_KEY_RE.match(key)
        assert m and m.group(1) in counts
        counts[m.group(1)] += 1
    assert counts == oracle
    rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    wall = time.perf_counter() - t_all
    print(f"\nmini-soak dense: {n_splits * split_bytes / 1e6:.0f} MB, "
          f"{sum(oracle.values())} matched lines exact across a crash+resume "
          f"in {wall:.0f}s, RSS growth {(rss1 - rss0) / 1024:.0f} MB")
    assert wall < 60, f"dense mini-soak over its time budget: {wall:.0f}s"


@pytest.mark.soak_mini
def test_mini_soak_daemon_kill_and_restart(tmp_path):
    """Round-10 mini-soak leg: a REAL ``dgrep serve`` daemon (subprocess,
    its own in-process workers) is SIGKILLed mid-window and restarted
    over the same work root; the registry + per-job journal resume
    completes the job with counts exact against a GNU grep oracle taken
    at generation time.  Budget: < 60 s like the rolling-window leg."""
    import subprocess
    from pathlib import Path

    import service_proc

    from distributed_grep_tpu.utils.config import JobConfig

    t_all = time.perf_counter()
    split_bytes = 1_000_000
    n_splits = 12
    rng = np.random.default_rng(23)
    files = []
    oracle: dict[str, int] = {}
    block = rng.integers(32, 127, size=split_bytes, dtype=np.uint8)
    block[rng.integers(0, block.size, size=block.size // 80)] = 0x0A
    template = block.tobytes()
    for i in range(n_splits):
        p = tmp_path / f"svc{i:02d}.bin"
        data = bytearray(template)
        for pos in rng.integers(0, split_bytes - 64,
                                size=int(rng.integers(3, 30))):
            data[pos: pos + len(NEEDLE)] = NEEDLE
        p.write_bytes(bytes(data))
        out = subprocess.run(
            ["grep", "-c", "-a", NEEDLE.decode()], stdin=open(p, "rb"),
            capture_output=True, text=True,
        )
        oracle[str(p)] = int(out.stdout.strip() or 0)
        files.append(str(p))

    # a grep_tpu wrapper whose maps take a beat: the kill window is then
    # deterministic to catch mid-stream (same trick as the rolling app)
    app_py = tmp_path / "slow_grep_app.py"
    app_py.write_text(
        "import time\n"
        "from distributed_grep_tpu.apps import grep_tpu as base\n"
        "configure = base.configure\n"
        "reduce_fn = base.reduce_fn\n"
        "reduce_is_identity = True\n"
        "set_progress = base.set_progress\n"
        "map_fn = base.map_fn\n"
        "def map_path_fn(filename, path):\n"
        "    time.sleep(0.12)\n"
        "    return base.map_path_fn(filename, path)\n"
    )
    cfg = JobConfig(
        input_files=files,
        application=str(app_py),
        app_options={"pattern": NEEDLE.decode(), "backend": "cpu"},
        n_reduce=4,
        task_timeout_s=30.0,
        sweep_interval_s=0.2,
    )
    work_root = tmp_path / "svc-root"
    work_root.mkdir()
    daemon = service_proc.ServiceProc(work_root, workers=1).start()
    try:
        jid = daemon.submit(cfg)
        # catch the job mid-window: some maps committed, not all
        deadline = time.monotonic() + 45
        while True:
            assert time.monotonic() < deadline, daemon.tail_log()
            st = daemon.job_status(jid)
            done_maps = st.get("map", {}).get("completed", 0)
            if 2 <= done_maps < n_splits:
                break
            assert st.get("state") != "done", "job finished before the kill"
            time.sleep(0.02)
        daemon.sigkill()
        daemon.start()
        st = daemon.wait_job(jid, timeout=60)
        assert st["state"] == "done", (st, daemon.tail_log())
        outputs = daemon.job_result(jid)["outputs"]
    finally:
        daemon.terminate()

    # exact per-split counts vs the generation-time GNU grep oracle: count
    # each split's grep keys ("<path> (line number #N)") in the outputs
    blob = b"".join(Path(p).read_bytes() for p in outputs)
    counts = {
        f: blob.count(f"{f} (line number #".encode())
        for f in files
    }
    assert counts == oracle
    wall = time.perf_counter() - t_all
    print(f"\nmini-soak daemon-kill: {n_splits} splits, "
          f"{sum(oracle.values())} lines exact across a SIGKILL+restart, "
          f"{wall:.0f}s")
    assert wall < 60, f"daemon-kill mini-soak over budget: {wall:.0f}s"


# --------------------------------------------------------------- rolling 100G
ROLL = os.environ.get("DGREP_SOAK_ROLLING", "")
_mr = re.fullmatch(r"(\d+)G", ROLL)
ROLL_GB = int(_mr.group(1)) if _mr else 0


@pytest.mark.skipif(
    ROLL_GB < 1, reason="rolling soak: set DGREP_SOAK_ROLLING=100G to run"
)
def test_soak_rolling_window(tmp_path):
    """The 100 GB north-star corpus on an 80 GB disk (VERDICT r4 item 5):
    ONE job over N splits where a generator thread writes splits ahead of
    the scan and a reaper thread deletes each split once the journal
    records its map as committed — at most WINDOW splits resident on
    disk.  Includes a mid-run crash + journal resume (replay matches by
    task file NAME, scheduler.py:97, so reaped files of completed maps
    never re-read).  Properties asserted: exact per-split counts vs a
    GNU grep oracle taken at generation time, flat RSS, bounded disk."""
    import resource
    import shutil
    import threading

    from distributed_grep_tpu.runtime.job import run_job
    from distributed_grep_tpu.runtime.worker import WorkerKilled
    from distributed_grep_tpu.utils.config import JobConfig

    split_bytes = 500 * 1000 * 1000
    n_splits = max(4, (ROLL_GB * 1_000_000_000) // split_bytes)
    # <= 8 GB of splits resident at full scale; small smoke runs shrink
    # the window so the generator gate and the reaper actually engage
    window = min(16, max(2, n_splits // 2))
    rng = np.random.default_rng(7)

    # One 500 MB random template; each split = copy + fresh needle patch
    # (generation must outrun the scan or the window gate would stall it).
    t0 = time.perf_counter()
    template = tmp_path / "template.bin"
    with open(template, "wb") as f:
        for _ in range(split_bytes // (100 * 1000 * 1000)):
            block = rng.integers(32, 127, size=100_000_000, dtype=np.uint8)
            block[rng.integers(0, block.size, size=block.size // 80)] = 0x0A
            f.write(block.tobytes())
    print(f"\nrolling soak: template in {time.perf_counter()-t0:.0f}s")

    files = [str(tmp_path / f"roll{i:03d}.bin") for i in range(n_splits)]
    for p in files:  # placeholders: the worker stats the path pre-app
        open(p, "wb").close()

    state = {"generated": 0, "deleted": 0, "stop": False, "gen_error": None}
    cv = threading.Condition()
    oracle: dict[str, int] = {}
    disk_peak = {"bytes": 0}

    def generate() -> None:
        try:
            for i, p in enumerate(files):
                with cv:
                    cv.wait_for(
                        lambda: state["stop"]
                        or state["generated"] - state["deleted"] < window
                    )
                    if state["stop"]:
                        return
                tmp = p + ".tmp"
                shutil.copyfile(template, tmp)
                # patch fresh needle sites per split (count exact by
                # construction is NOT assumed — the oracle greps the file)
                n_needles = int(rng.integers(5, 60))
                with open(tmp, "r+b") as f:
                    for pos in rng.integers(
                        0, split_bytes - 64, size=n_needles
                    ):
                        f.seek(int(pos))
                        f.write(NEEDLE)
                out = subprocess.run(
                    ["grep", "-c", "-a", NEEDLE.decode()],
                    stdin=open(tmp, "rb"), capture_output=True, text=True,
                )
                oracle[p] = int(out.stdout.strip() or 0)
                os.replace(tmp, p)  # atomic: placeholder -> real content
                open(p + ".ready", "wb").close()
                with cv:
                    state["generated"] = i + 1
                    resident = state["generated"] - state["deleted"]
                    disk_peak["bytes"] = max(
                        disk_peak["bytes"], resident * split_bytes
                    )
                    cv.notify_all()
        except BaseException as e:  # noqa: BLE001 — surfaced by the main thread
            with cv:
                state["gen_error"] = e
                state["stop"] = True
                cv.notify_all()

    from distributed_grep_tpu.utils.io import WorkDir

    journal_path = WorkDir(str(tmp_path / "job")).journal_path()

    def reap() -> None:
        """Delete splits whose map completion the journal has committed."""
        from distributed_grep_tpu.runtime.journal import TaskJournal

        reaped: set[str] = set()
        while True:
            with cv:
                if state["stop"] and state["deleted"] >= state["generated"]:
                    return
            for e in TaskJournal.replay(journal_path):
                if e.get("kind") == "map_done":
                    p = e.get("file")
                    if p and p not in reaped and os.path.exists(p):
                        os.unlink(p)
                        os.path.exists(p + ".ready") and os.unlink(p + ".ready")
                        reaped.add(p)
                        with cv:
                            state["deleted"] = len(reaped)
                            cv.notify_all()
            with cv:
                if state["stop"]:
                    return
            time.sleep(1.0)

    # The app: grep_tpu, but each map WAITS for its split's .ready marker
    # (the generator may be a step behind), stamping liveness meanwhile.
    app_py = tmp_path / "rolling_app.py"
    app_py.write_text(
        "import os, time\n"
        "from distributed_grep_tpu.apps import grep_tpu as base\n"
        "configure = base.configure\n"
        "reduce_fn = base.reduce_fn\n"
        "reduce_is_identity = True\n"
        "set_progress = base.set_progress\n"
        "map_fn = base.map_fn\n"
        "def map_path_fn(filename, path):\n"
        "    fn = base._progress_fn()\n"
        "    t0 = time.monotonic()\n"
        "    while not os.path.exists(filename + '.ready'):\n"
        "        if time.monotonic() - t0 > 900:\n"
        "            raise RuntimeError('generator stalled')\n"
        "        fn and fn()\n"
        "        time.sleep(0.5)\n"
        "    return base.map_path_fn(filename, path)\n"
    )
    cfg = JobConfig(
        input_files=files,
        application=str(app_py),
        app_options={"pattern": NEEDLE.decode(), "backend": "cpu"},
        n_reduce=8,
        work_dir=str(tmp_path / "job"),
        task_timeout_s=60.0,
        sweep_interval_s=0.5,
    )

    rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    t_job = time.perf_counter()
    gen_t = threading.Thread(target=generate, name="soak-gen", daemon=True)
    reap_t = threading.Thread(target=reap, name="soak-reap", daemon=True)
    gen_t.start()
    reap_t.start()

    # Phase 1 — crash after ~1/3 of the maps committed.
    kill_after = max(1, n_splits // 3)
    done = {"n": 0}

    def die_midway():
        done["n"] += 1
        if done["n"] > kill_after:
            raise WorkerKilled()

    try:
        with pytest.raises(RuntimeError, match="all workers exited"):
            run_job(cfg, n_workers=1,
                    fault_hooks_per_worker=[{"before_map_finished": die_midway}])
        # Phase 2 — resume: replay skips committed (possibly reaped) maps.
        res = run_job(cfg, n_workers=2, resume=True)
    finally:
        with cv:
            state["stop"] = True
            cv.notify_all()
    gen_t.join(timeout=30)
    if state["gen_error"] is not None:
        raise state["gen_error"]
    wall = time.perf_counter() - t_job

    counts = dict.fromkeys(files, 0)
    from distributed_grep_tpu.runtime.job import GREP_KEY_RE

    for key, _v in res.iter_results():
        m = GREP_KEY_RE.match(key)
        assert m and m.group(1) in counts
        counts[m.group(1)] += 1
    assert counts == oracle
    rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    reap_t.join(timeout=30)
    gb = n_splits * split_bytes / 1e9
    print(f"rolling soak: {gb:.0f} GB in {wall:.0f}s "
          f"({gb/wall*1000:.0f} MB/s), RSS growth "
          f"{(rss1-rss0)/1024:.0f} MB, disk peak "
          f"{disk_peak['bytes']/1e9:.1f} GB of splits, "
          f"{sum(oracle.values())} lines exact across {n_splits} splits")
    assert rss1 - rss0 < 1_500_000  # KB — flat RSS
    assert disk_peak["bytes"] <= (window + 1) * split_bytes

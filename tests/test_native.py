"""Native library (libdgrep) vs pure-Python fallback equivalence."""

import numpy as np
import pytest

from distributed_grep_tpu.utils import native


def _python_fnv32a(data: bytes) -> int:
    h = 2166136261
    for b in data:
        h ^= b
        h = (h * 16777619) & 0xFFFFFFFF
    return h & 0x7FFFFFFF


@pytest.mark.parametrize("key", [b"", b"a", b"app", b"hello world", bytes(range(256))])
def test_fnv32a_matches_reference_algorithm(key):
    # Same algorithm as the reference's ihash (worker.go:13-17): FNV-32a
    # masked to non-negative.
    assert native.fnv32a(key) == _python_fnv32a(key)


def test_partition_range():
    for key in ["a", "b", "some key", ""]:
        assert 0 <= native.partition(key, 10) < 10


def test_newline_index():
    data = b"a\nbb\n\nccc"
    np.testing.assert_array_equal(native.newline_index(data), [1, 4, 5])
    assert native.newline_index(b"").size == 0
    assert native.newline_index(b"no newline").size == 0


def test_literal_scan_overlapping():
    # End offsets, overlapping occurrences included.
    np.testing.assert_array_equal(native.literal_scan(b"aaaa", b"aa"), [2, 3, 4])
    np.testing.assert_array_equal(native.literal_scan(b"abcabc", b"abc"), [3, 6])
    assert native.literal_scan(b"abc", b"xyz").size == 0
    assert native.literal_scan(b"abc", b"").size == 0


def test_dfa_scan_and_state_carry():
    # DFA for literal "ab": 0 -(a)-> 1 -(b)-> 2(accept); 2 -(a)-> 1.
    tbl = np.zeros((3, 256), dtype=np.uint16)
    tbl[:, ord("a")] = 1
    tbl[1, ord("b")] = 2
    acc = np.array([0, 0, 1], dtype=np.uint8)
    offsets, final = native.dfa_scan(b"xabxab", tbl, acc)
    np.testing.assert_array_equal(offsets, [3, 6])
    assert final == 2
    # State carry across a chunk boundary: split "ab" across chunks.
    off1, s1 = native.dfa_scan(b"xa", tbl, acc, start_state=0)
    off2, s2 = native.dfa_scan(b"bxab", tbl, acc, start_state=s1)
    assert off1.size == 0
    np.testing.assert_array_equal(off2, [1, 4])  # offsets relative to chunk 2


def test_native_lib_actually_loaded():
    # The toolchain is baked into the image; the native path must be active.
    assert native.native_available()


def test_dfa_scan_mt_matches_sequential():
    from distributed_grep_tpu.models.aho import compile_aho_corasick
    from distributed_grep_tpu.models.dfa import compile_dfa

    rng = np.random.default_rng(7)
    data = bytes(rng.choice(list(b"abcdefg \n"), size=1 << 20).tolist())
    data += b"needle at end"
    for table in (compile_dfa("nee(dle|g)"), compile_aho_corasick([b"needle", b"fgab"])):
        full = table.full_table()
        acc = table.accept.astype(np.uint8)
        seq, _ = native.dfa_scan(data, full, acc, table.start)
        for nt in (2, 3, 8):
            mt = native.dfa_scan_mt(data, full, acc, table.start, n_threads=nt)
            np.testing.assert_array_equal(mt, seq, err_msg=f'n_threads={nt}')


def test_dfa_scan_mt_small_input_falls_through():
    from distributed_grep_tpu.models.dfa import compile_dfa

    t = compile_dfa("ab")
    data = b"xxabyy\nab\n"
    seq, _ = native.dfa_scan(data, t.full_table(), t.accept.astype(np.uint8), t.start)
    mt = native.dfa_scan_mt(data, t.full_table(), t.accept.astype(np.uint8), t.start)
    np.testing.assert_array_equal(mt, seq)


# --- ConfirmSet (FDR candidate confirm, native + fallback) ------------------

def _confirm_oracle(pats, data, ends, ignore_case=False):
    hay = data.lower() if ignore_case else data
    ps = [p.lower() if ignore_case else p for p in pats]
    out = np.zeros(len(ends), dtype=bool)
    for i, e in enumerate(ends):
        out[i] = any(0 < len(p) <= e <= len(hay) and hay[e - len(p):e] == p
                     for p in ps)
    return out


@pytest.mark.parametrize("force_fallback", [False, True])
@pytest.mark.parametrize("ignore_case", [False, True])
def test_confirm_set_matches_oracle(force_fallback, ignore_case):
    rng = np.random.default_rng(13)
    pats = [b"needle", b"XY", b"abc", b"zzz\xffq", b"Q" * 9]
    norm = [p.lower() if ignore_case else p for p in pats]
    data = b"a needle XY\nabczzz\xffq " + b"Q" * 9 + b" nEEdle xy end"
    # use_native=False exercises the pure-Python path (hosts without a
    # C++ toolchain) — the same exactness-critical code with no lib
    cs = native.ConfirmSet(norm, ignore_case=ignore_case,
                           use_native=not force_fallback)
    assert (cs._handle is None) == force_fallback
    ends = np.arange(0, len(data) + 2, dtype=np.uint64)
    got = cs.confirm(data, ends)
    np.testing.assert_array_equal(
        got, _confirm_oracle(pats, data, ends.tolist(), ignore_case)
    )


def test_confirm_set_fallback_equals_native_random():
    rng = np.random.default_rng(14)
    pats = sorted({bytes(rng.integers(1, 256, size=int(rng.integers(2, 10)),
                                      dtype=np.uint8).tolist()).replace(b"\n", b"-")
                   for _ in range(300)})
    data = bytes(rng.integers(0, 256, size=1 << 16, dtype=np.uint8).tolist())
    # plant a few
    arr = np.frombuffer(data, dtype=np.uint8).copy()
    for pos in (100, 5000, 60000):
        p = pats[pos % len(pats)]
        arr[pos:pos + len(p)] = np.frombuffer(p, dtype=np.uint8)
    data = arr.tobytes()
    nat = native.ConfirmSet(pats)
    assert nat._handle is not None
    fb = native.ConfirmSet(pats, use_native=False)
    assert fb._handle is None
    ends = rng.integers(0, len(data) + 1, size=5000).astype(np.uint64)
    np.testing.assert_array_equal(nat.confirm(data, ends), fb.confirm(data, ends))

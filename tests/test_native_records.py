"""Native map-record pipeline (round 8): byte-identity pins.

libdgrep's dgrep_unique_lines / dgrep_line_spans / dgrep_build_records
collapse everything between kernel output and the partitioned mr-out
slabs into one C pass.  Exactness story:

* unique_lines: a linear merge over two sorted arrays — pinned against
  np.unique(searchsorted) on random offsets.
* line_spans: pinned against ops/lines.line_span per line, including the
  no-trailing-newline and no-newline-at-all chunk shapes.
* build_records: partition assignment must be bit-identical to
  utils.native.partition on the formatted key (the reference ihash
  contract runtime/columnar.partitions already pins — extended here to
  the native entry), and the per-partition (linenos, offsets, slab)
  triples must equal the numpy select()/gather chain exactly.
* DeferredBatch: the lazy whole-buffer batch must materialize to the
  eager batch and split identically through both the native and the
  numpy paths; DGREP_NATIVE_RECORDS=0 must silence every native entry.

The e2e test pins the whole route at job scale: mr-out files and display
bytes with the native record entries on == all off, spill path included.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from distributed_grep_tpu.ops.lines import (
    line_of_offsets,
    line_span,
    newline_index,
    unique_match_lines,
)
from distributed_grep_tpu.runtime import shuffle
from distributed_grep_tpu.runtime.columnar import (
    DeferredBatch,
    LineBatch,
    line_spans,
    make_batch_from_lines,
)
from distributed_grep_tpu.utils import native
from distributed_grep_tpu.utils.native import partition

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="libdgrep unavailable"
)


def _text(rng: random.Random, n: int, alphabet=b"abc de\nfgh") -> bytes:
    return bytes(rng.choice(alphabet) for _ in range(n))


def _disable_native_records(monkeypatch):
    """Silence every native record entry (the numpy-fallback tree)."""
    monkeypatch.setattr(
        "distributed_grep_tpu.utils.native.build_records",
        lambda *a, **k: None,
    )
    monkeypatch.setattr(
        "distributed_grep_tpu.utils.native.line_spans_native",
        lambda *a, **k: None,
    )
    monkeypatch.setattr(
        "distributed_grep_tpu.utils.native.unique_lines_native",
        lambda *a, **k: None,
    )


# ------------------------------------------------------------ unique_lines

def test_unique_lines_matches_searchsorted():
    rng = random.Random(3)
    data = _text(rng, 30000)
    nl = newline_index(data)
    for size in (1, 7, 500, 3000):
        ends = np.array(
            sorted(rng.sample(range(1, len(data) + 1), size)), np.int64
        )
        want = np.unique(line_of_offsets(ends, nl))
        got = unique_match_lines(ends, nl)
        assert np.array_equal(got, want)
    assert unique_match_lines(np.zeros(0, np.int64), nl).size == 0


def test_unique_lines_duplicate_offsets_same_line():
    data = b"aaa\nbbb\nccc\n"
    nl = newline_index(data)
    ends = np.array([1, 2, 3, 3, 9, 10], np.int64)  # lines 1,1,1,1,3,3
    assert unique_match_lines(ends, nl).tolist() == [1, 3]


# -------------------------------------------------------------- line_spans

@pytest.mark.parametrize("tail_newline", [True, False])
def test_line_spans_matches_line_span(tail_newline):
    rng = random.Random(5)
    data = _text(rng, 20000)
    data = data + b"\n" if tail_newline else data.rstrip(b"\n") + b"x"
    nl = newline_index(data)
    n_lines = nl.size + (0 if data.endswith(b"\n") else 1)
    lns = np.arange(1, n_lines + 1, dtype=np.int64)
    starts, ends = line_spans(lns, nl, len(data))
    for i, ln in enumerate(lns.tolist()):
        assert (starts[i], ends[i]) == line_span(nl, ln, len(data))


def test_line_spans_no_newline_chunk():
    s, e = line_spans(np.array([1], np.int64), np.zeros(0, np.uint64), 9)
    assert (s[0], e[0]) == (0, 9)


def test_line_spans_native_equals_numpy(monkeypatch):
    rng = random.Random(11)
    data = _text(rng, 8000)
    nl = newline_index(data)
    lns = np.array(sorted(rng.sample(range(1, nl.size), 200)), np.int64)
    got = line_spans(lns, nl, len(data))
    _disable_native_records(monkeypatch)
    want = line_spans(lns, nl, len(data))
    assert np.array_equal(got[0], want[0]) and np.array_equal(got[1], want[1])


# ----------------------------------------------------------- build_records

@pytest.mark.parametrize("fname", [
    "/data/split-03.txt",
    "weird \udcff\udc80 name",        # surrogateescaped raw bytes
    "dir/uni-é中.txt",                 # multi-byte UTF-8
    "",
])
def test_build_records_partition_bit_identical(fname):
    """The shuffle contract, extended to the native entry: the one-pass
    build must route every record exactly like utils.native.partition on
    its formatted key (reference ihash semantics)."""
    rng = random.Random(7)
    data = _text(rng, 16000)
    nl = newline_index(data)
    n_lines = max(2, nl.size)
    for base in (0, 10**13):
        local = np.array(
            sorted(rng.sample(range(1, n_lines), min(250, n_lines - 1))),
            np.int64,
        )
        stored = local + base
        starts, ends = line_spans(local, nl, len(data))
        prefix = (fname + " (line number #").encode("utf-8", "surrogateescape")
        for n_reduce in (1, 4, 97):
            parts = native.build_records(
                np.frombuffer(data, np.uint8), starts, ends, stored,
                prefix, n_reduce,
            )
            assert parts is not None
            seen = 0
            for p, (lns, offs, slab) in parts.items():
                assert offs[0] == 0 and offs[-1] == len(slab)
                seen += lns.size
                for ln in lns.tolist():
                    key = f"{fname} (line number #{ln})"
                    assert partition(key, n_reduce) == p, key
            assert seen == stored.size


def test_split_by_partition_native_equals_numpy(monkeypatch):
    rng = random.Random(9)
    data = _text(rng, 16000)
    arr = np.frombuffer(data, np.uint8)
    nl = newline_index(data)
    local = np.array(sorted(rng.sample(range(1, nl.size), 300)), np.int64)
    eager = make_batch_from_lines("f.txt", local, arr, nl, len(data))
    deferred = DeferredBatch("f.txt", local, arr, nl, len(data))
    got_e = eager.split_by_partition(8)
    got_d = deferred.split_by_partition(8)
    _disable_native_records(monkeypatch)
    want = make_batch_from_lines(
        "f.txt", local, arr, nl, len(data)
    ).split_by_partition(8)
    want_d = DeferredBatch(
        "f.txt", local, arr, nl, len(data)
    ).split_by_partition(8)
    assert set(got_e) == set(want) == set(got_d) == set(want_d)
    for p in want:
        for got in (got_e[p], got_d[p], want_d[p]):
            assert np.array_equal(got.linenos, want[p].linenos)
            assert np.array_equal(got.offsets, want[p].offsets)
            assert got.slab == want[p].slab


def test_build_records_empty_and_malformed():
    arr = np.frombuffer(b"abc\ndef\n", np.uint8)
    assert native.build_records(
        arr, np.zeros(0, np.int64), np.zeros(0, np.int64),
        np.zeros(0, np.int64), b"f (line number #", 4,
    ) == {}
    # out-of-bounds span: refuse (numpy fallback would take over)
    bad = native.build_records(
        arr, np.array([0], np.int64), np.array([99], np.int64),
        np.array([1], np.int64), b"f (line number #", 4,
    )
    assert bad is None


def test_env_kill_switch(monkeypatch):
    """DGREP_NATIVE_RECORDS=0 silences every native record entry — the
    debug kill-switch registered in analysis/knobs.py."""
    monkeypatch.setenv("DGREP_NATIVE_RECORDS", "0")
    assert not native.env_native_records()
    arr = np.frombuffer(b"abc\ndef\n", np.uint8)
    assert native.build_records(
        arr, np.array([0], np.int64), np.array([3], np.int64),
        np.array([1], np.int64), b"f (line number #", 4,
    ) is None
    assert native.line_spans_native(
        np.array([3], np.uint64), np.array([1], np.int64), 8
    ) is None
    assert native.unique_lines_native(
        np.array([3], np.uint64), np.array([1], np.int64)
    ) is None
    monkeypatch.setenv("DGREP_NATIVE_RECORDS", "1")
    assert native.env_native_records()


# ---------------------------------------------------------- DeferredBatch

def test_deferred_batch_materializes_to_eager():
    rng = random.Random(13)
    data = _text(rng, 6000)
    arr = np.frombuffer(data, np.uint8)
    nl = newline_index(data)
    local = np.array(sorted(rng.sample(range(1, nl.size), 80)), np.int64)
    eager = make_batch_from_lines("g", local, arr, nl, len(data),
                                  lineno_base=500)
    deferred = DeferredBatch("g", local, arr, nl, len(data), lineno_base=500)
    assert isinstance(deferred, LineBatch)  # every consumer sees a batch
    assert len(deferred) == len(eager)
    assert np.array_equal(deferred.linenos, eager.linenos)
    assert np.array_equal(deferred.offsets, eager.offsets)  # materializes
    assert deferred.slab == eager.slab
    assert deferred.to_keyvalues() == eager.to_keyvalues()
    assert deferred.format_lines_bytes() == eager.format_lines_bytes()


def test_deferred_batch_through_bucketize_matches_per_record():
    rng = random.Random(17)
    data = _text(rng, 6000)
    arr = np.frombuffer(data, np.uint8)
    nl = newline_index(data)
    local = np.array(sorted(rng.sample(range(1, nl.size), 120)), np.int64)
    deferred = DeferredBatch("/f.txt", local, arr, nl, len(data))
    per_record = shuffle.bucketize(deferred.to_keyvalues(), 5)
    columnar = shuffle.bucketize(
        [DeferredBatch("/f.txt", local, arr, nl, len(data))], 5
    )
    assert set(per_record) == set(columnar)
    for r in per_record:
        expanded = []
        for item in columnar[r]:
            expanded.extend(item.to_keyvalues())
        assert expanded == per_record[r], r


def test_grep_tpu_emits_deferred_and_wire_roundtrips():
    """The whole-bytes map path emits DeferredBatch records whose encoded
    wire form equals the eager batch's (the shuffle writes them through
    encode_records — materialization must be transparent there too)."""
    from distributed_grep_tpu.apps import grep_tpu

    grep_tpu.configure(pattern="fox", backend="cpu")
    data = b"a fox\nno match\nfoxfox\nlast fox"
    records = grep_tpu.map_fn("f.txt", data)
    assert len(records) == 1 and isinstance(records[0], DeferredBatch)
    enc = shuffle.encode_records(records)
    back = shuffle.decode_records(enc)
    assert len(back) == 1
    assert back[0].to_keyvalues() == records[0].to_keyvalues()


# ------------------------------------------------ batched -w/-x confirm

def test_regex_confirm_batched_matches_cpu_app():
    """The batched slab confirm (-w/-x over non-literal patterns) must
    select exactly the lines the CPU app's per-line confirm selects.
    ignore_case defeats the literal fast path, forcing the regex leg."""
    from tests.conftest import expand_records

    from distributed_grep_tpu.apps import grep as grep_cpu
    from distributed_grep_tpu.apps import grep_tpu

    data = (b"the fox\nTHE END\nbreathe\n the \nlast the" + b"\n"
            b"xtheyx\nthe\n")
    for mode in ({"word_regexp": True}, {"line_regexp": True}):
        grep_cpu.configure(pattern="the", ignore_case=True, **mode)
        grep_tpu.configure(pattern="the", ignore_case=True, backend="cpu",
                           **mode)
        assert grep_tpu._confirm is not None and grep_tpu._confirm_lit is None
        want = expand_records(grep_cpu.map_fn("f", data))
        got = expand_records(grep_tpu.map_fn("f", data))
        assert got == want, mode


# ------------------------------------------------------- ephemeral store

def test_non_durable_store_skips_fsync_bytes_identical(tmp_path, monkeypatch):
    """JobConfig.durable=False (the CLI's ephemeral temp workdirs) must
    skip every blob fsync while producing byte-identical outputs; the
    default stays fully durable."""
    import os as _os

    from distributed_grep_tpu.runtime.job import run_job
    from distributed_grep_tpu.utils.config import JobConfig

    calls = {"n": 0}
    real_fsync = _os.fsync

    def counting(fd):
        calls["n"] += 1
        real_fsync(fd)

    monkeypatch.setattr(_os, "fsync", counting)
    src = tmp_path / "in.txt"
    src.write_bytes(b"needle one\nplain\nneedle two\n" * 200)

    def run(durable: bool, tag: str):
        cfg = JobConfig(
            application="distributed_grep_tpu.apps.grep_tpu",
            input_files=[str(src)], work_dir=str(tmp_path / f"job-{tag}"),
            n_reduce=3, journal=False, durable=durable,
            app_options={"pattern": "needle", "backend": "cpu"},
        )
        calls["n"] = 0
        res = run_job(cfg, n_workers=1)
        return {p.name: p.read_bytes() for p in res.output_files}, calls["n"]

    outs_d, fsyncs_d = run(True, "durable")
    outs_e, fsyncs_e = run(False, "ephemeral")
    assert outs_d == outs_e
    assert fsyncs_d > 0 and fsyncs_e == 0


def test_put_from_file_consume_renames_and_copies(tmp_path):
    """consume=True commits by rename when allowed (src disappears) and
    the blob bytes are identical either way; consume=False keeps src."""
    from distributed_grep_tpu.runtime.store import PosixStore

    for durable in (True, False):
        store = PosixStore(durable=durable)
        src = tmp_path / f"spool-{durable}"
        src.write_bytes(b"payload-" + str(durable).encode())
        dst = tmp_path / f"out-{durable}" / "mr-out-0"
        store.put_from_file(dst, src, consume=True)
        assert dst.read_bytes() == b"payload-" + str(durable).encode()
        assert not src.exists()  # renamed, not copied
    store = PosixStore()
    src = tmp_path / "keep"
    src.write_bytes(b"kept")
    dst = tmp_path / "out-keep"
    store.put_from_file(dst, src)
    assert dst.read_bytes() == b"kept" and src.exists()


# ------------------------------------------------------------------- e2e

def test_job_output_native_records_vs_python_paths_with_spill(
    tmp_path, monkeypatch
):
    """E2E: mr-out files AND display bytes are byte-identical with the
    native record pipeline on vs EVERY native loop off — spill/extsort
    path engaged via a tiny reduce cap (the acceptance contract, same
    harness as test_native_merge.py's e2e)."""
    from distributed_grep_tpu.runtime.job import run_job
    from distributed_grep_tpu.utils.config import JobConfig

    rng = np.random.default_rng(33)
    data = rng.integers(32, 127, size=4 << 20, dtype=np.uint8)
    data[rng.integers(0, data.size, size=data.size // 50)] = 0x0A
    needle = np.frombuffer(b"the", np.uint8)
    for p in rng.integers(0, data.size - 8, size=40000):
        data[p : p + 3] = needle
    src = tmp_path / "corpus.bin"
    src.write_bytes(data.tobytes())

    def run(tag):
        wd = tmp_path / f"job-{tag}"
        cfg = JobConfig(
            application="distributed_grep_tpu.apps.grep_tpu",
            input_files=[str(src)],
            work_dir=str(wd), n_reduce=4, journal=False,
            reduce_memory_bytes=128 << 10,  # force spill runs
            app_options={"pattern": "the", "backend": "cpu"},
        )
        res = run_job(cfg, n_workers=2)
        outs = {p.name: p.read_bytes() for p in res.output_files}
        disp = b"".join(res.display_blocks_sorted())
        return outs, disp, res.metrics

    outs_native, disp_native, m = run("native")
    assert m["counters"].get("reduce_spills", 0) > 0, "spill did not engage"

    _disable_native_records(monkeypatch)
    monkeypatch.setattr(
        "distributed_grep_tpu.utils.native.gather_ranges_native",
        lambda *a, **k: None,
    )
    monkeypatch.setattr(
        "distributed_grep_tpu.utils.native.format_batch",
        lambda *a, **k: None,
    )
    monkeypatch.setattr(
        "distributed_grep_tpu.utils.native.merge_display", lambda bufs: None
    )
    outs_py, disp_py, _ = run("python")
    assert outs_native == outs_py
    assert disp_native == disp_py

"""Shard-index tier (distributed_grep_tpu/index): trigram summaries route
queries past shards that cannot match.

The contract under test (ISSUE 12): indexed and DGREP_INDEX=0 outputs are
byte-identical across every kernel family (the summary only ever answers
"cannot match"; a maybe always scans); pruned shards are never opened and
never dispatched (spy-pinned, ``perf`` marker); eligibility boundaries —
empty-match patterns, sub-trigram literals, ignore_case, POSIX classes,
the \\b re-fallback, approx mode — each either prune correctly or fall
through to a full scan, never under-report; summaries persist under the
work root keyed by the content-identity validator tuple, so stat drift
(the cp -p + mv inode case) is a clean miss and a daemon restart serves
them without rebuilding.

Standalone: ``python -m pytest tests/test_index.py -q`` (CPU-only; the
autouse ``_fresh_index`` fixture clears the summary cache per test).
"""

from __future__ import annotations

import builtins
import os
import shutil
import time

import numpy as np
import pytest

from distributed_grep_tpu.index import plan as index_plan
from distributed_grep_tpu.index import summary as index_summary
from distributed_grep_tpu.index.store import IndexStore
from distributed_grep_tpu.ops.engine import GrepEngine

pytestmark = pytest.mark.index


@pytest.fixture(autouse=True)
def _no_calibrate(monkeypatch):
    monkeypatch.setenv("DGREP_NO_CALIBRATE", "1")


@pytest.fixture(autouse=True)
def _engine_store(tmp_path):
    """Engine-level summary BUILDS are gated on a reuse surface (an
    attached store, or corpus-cache opt-in — one-shot CLI jobs build
    nothing); the engine tests here run the store-attached shape, like
    the service's workers.  Runs after conftest's _fresh_index clear."""
    index_summary.attach_store(tmp_path / "idxstore")
    yield


def _fdr_patterns() -> list[str]:
    rng = np.random.default_rng(3)
    pats = {"hello", "volcano", "needle"}
    while len(pats) < 50:
        k = int(rng.integers(4, 9))
        pats.add("".join(chr(c) for c in rng.integers(97, 123, size=k)))
    return sorted(pats)


# the five families the corpus-cache suite pins, reused here
ENGINES = [
    ("shift_and", dict(pattern="hello")),
    ("nfa", dict(pattern="h[ae]llo+")),
    ("pairset", dict(patterns=["ab", "zz", "q"])),  # index-INELIGIBLE set
    ("dfa_filter", dict(pattern="hello$")),
    ("fdr", dict(patterns=_fdr_patterns())),
]


def _corpus_bytes() -> bytes:
    rng = np.random.default_rng(13)
    words = ["hello", "hallo", "helloo", "volcano", "needle", "ab", "zz",
             "q", "the", "quick", "brown", "fox", "of", "and"]
    out = []
    for _ in range(400):
        k = int(rng.integers(1, 8))
        out.append(" ".join(
            words[int(rng.integers(0, len(words)))] for _ in range(k)
        ).encode())
    return b"\n".join(out) + b"\n"


# ------------------------------------------------------------ summary format


def test_native_and_numpy_builds_are_bit_identical(monkeypatch):
    from distributed_grep_tpu.utils import native

    data = (b"The Quick BROWN fox\xff\xfe jumps over\n" * 500
            + b"unterminated tail")
    if native.trigram_summary_available():
        s_native = index_summary.build_summary(data)
        monkeypatch.setattr(native, "trigram_summary_into",
                            lambda d, b: False)
        s_py = index_summary.build_summary(data)
        assert s_native == s_py
    # chunked fallback == one-shot fallback (the 2-byte overlap seam)
    monkeypatch.setattr(native, "trigram_summary_into", lambda d, b: False)
    big = data * 40
    import distributed_grep_tpu.index.summary as S

    whole = S.build_summary(big)
    # shrink the chunk step so the seam logic actually runs
    monkeypatch.setattr(S, "build_summary", S.build_summary)
    bloom = np.zeros(len(whole), dtype=np.uint8)
    step = 1 << 12
    arr = np.frombuffer(big, dtype=np.uint8)
    for pos in range(0, max(len(big) - 2, 0), step):
        piece = S._FOLD[arr[pos:pos + step + 2]].astype(np.uint64)
        if piece.size < 3:
            break
        v = ((piece[:-2] << np.uint64(16)) | (piece[1:-1] << np.uint64(8))
             | piece[2:])
        idx = np.unique(S._bit_indices(v, len(whole) * 8))
        np.bitwise_or.at(
            bloom, (idx >> np.uint64(3)).astype(np.int64),
            (np.uint8(1) << (idx & np.uint64(7)).astype(np.uint8)),
        )
    assert bloom.tobytes() == whole


def test_short_data_yields_all_zero_summary():
    # < 3 bytes: no trigram — the all-zero summary correctly prunes every
    # eligible query (a 2-byte shard cannot contain a 3+-byte literal)
    for blob in (b"", b"a", b"ab"):
        s = index_summary.build_summary(blob)
        assert not any(s)
    req = index_plan.requirements_for_query(pattern="needle")
    assert not req.may_match(index_summary.build_summary(b"ab"))


def test_case_fold_is_index_time_noop():
    s = index_summary.build_summary(b"some NEEDLE text here\n")
    for q, ic in [("needle", False), ("NEEDLE", False), ("NeEdLe", True)]:
        req = index_plan.requirements_for_query(pattern=q, ignore_case=ic)
        assert req.may_match(s), q
    # and a literal genuinely absent prunes regardless of case flags
    req = index_plan.requirements_for_query(pattern="volcano",
                                            ignore_case=True)
    assert not req.may_match(s)


def test_env_summary_bytes_clamps_to_pow2(monkeypatch):
    monkeypatch.delenv("DGREP_INDEX_SUMMARY_BYTES", raising=False)
    assert index_summary.env_summary_bytes() == 16384
    monkeypatch.setenv("DGREP_INDEX_SUMMARY_BYTES", "notanint")
    assert index_summary.env_summary_bytes() == 16384
    monkeypatch.setenv("DGREP_INDEX_SUMMARY_BYTES", "5000")
    assert index_summary.env_summary_bytes() == 4096
    monkeypatch.setenv("DGREP_INDEX_SUMMARY_BYTES", "1")
    assert index_summary.env_summary_bytes() == 1024
    monkeypatch.setenv("DGREP_INDEX_SUMMARY_BYTES", str(1 << 30))
    assert index_summary.env_summary_bytes() == 1 << 20


# -------------------------------------------------------- query eligibility

ELIGIBLE = [
    ("needle", {}, [b"needle"]),
    ("(volcano|needle)", {}, [b"volcano", b"needle"]),
    ("err[0-9]+ors", {}, [b"err"]),  # required factor around a class
    (r"\berror\b", {}, [b"error"]),  # the re-fallback rescue family
    ("hello$", {}, [b"hello"]),  # '$'-dropped device-filter family
    ("^needle", {}, [b"needle"]),
    ("[[:digit:]]+needle", {}, [b"needle"]),  # POSIX class body
    ("a{3,}", {}, [b"aaa"]),  # required repeat of a singleton
    ("NEEDLE", {"ignore_case": True}, [b"NEEDLE"]),
]

INELIGIBLE = [
    ("", {}),  # empty pattern: matches everything
    ("a*", {}),  # nullable: no required bytes
    ("x?y?z?", {}),
    ("ab", {}),  # sub-trigram literal
    ("(foo|ab)", {}),  # one alternative too short unconstrains the Alt
    ("needle", {"max_errors": 1}),  # approx: edits can destroy literals
    ("[0-9]+", {}),  # classes only: no literal run
]


@pytest.mark.parametrize("pat,kw,lits", ELIGIBLE)
def test_eligible_queries_derive_required_literals(pat, kw, lits):
    req = index_plan.requirements_for_query(pattern=pat, **kw)
    assert req is not None and req.literals == lits


@pytest.mark.parametrize("pat,kw", INELIGIBLE)
def test_ineligible_queries_scan_everything(pat, kw):
    assert index_plan.requirements_for_query(pattern=pat, **kw) is None


def test_pattern_set_eligibility_boundaries():
    req = index_plan.requirements_for_query(patterns=["volcano", "needle"])
    assert req.literals == [b"volcano", b"needle"]
    # ANY sub-trigram member makes the whole set ineligible: the summary
    # could never rule that member out, so pruning would under-report
    assert index_plan.requirements_for_query(
        patterns=["volcano", "ab"]) is None
    assert index_plan.requirements_for_query(patterns=[]) is None


def test_cannot_match_verdict_is_sound_fuzz():
    """Whenever the index says "cannot match", a real scan agrees —
    random corpora x random queries, both fire (absent literal prunes)
    and silent (present literal never prunes a matching shard)."""
    import re

    rng = np.random.default_rng(42)
    letters = "abcdefgh"
    for trial in range(40):
        n = int(rng.integers(10, 400))
        corpus = bytes(
            rng.choice([ord(c) for c in letters + "\n "], size=n)
        )
        s = index_summary.build_summary(corpus)
        qlen = int(rng.integers(3, 6))
        q = "".join(letters[int(rng.integers(0, len(letters)))]
                    for _ in range(qlen))
        req = index_plan.requirements_for_query(pattern=q)
        present = q.encode() in corpus
        if not req.may_match(s):
            assert not present, (q, corpus)
        if present:
            assert req.may_match(s), (q, corpus)


# ----------------------------------------------------- engine-level routing


def _spy_opens(monkeypatch):
    opened: list = []
    real_open = builtins.open

    def spy_open(f, *a, **k):
        opened.append(os.fspath(f) if not isinstance(f, int) else f)
        return real_open(f, *a, **k)

    monkeypatch.setattr(builtins, "open", spy_open)
    return opened


@pytest.mark.perf
def test_scan_file_pruned_shard_is_never_opened(tmp_path, monkeypatch):
    p = tmp_path / "shard.txt"
    p.write_bytes(b"nothing of note\nplain filler text\n" * 200)
    eng = GrepEngine("needle", backend="cpu")
    cold = eng.scan_file(p)  # builds + publishes the summary
    assert cold.n_matches == 0
    opened = _spy_opens(monkeypatch)
    scans: list = []
    orig = GrepEngine._scan_impl
    monkeypatch.setattr(
        GrepEngine, "_scan_impl",
        lambda self, *a, **k: (scans.append(1), orig(self, *a, **k))[1],
    )
    res = eng.scan_file(p)
    assert res.n_matches == 0 and res.matched_lines.size == 0
    assert str(p) not in [str(x) for x in opened], "pruned shard was opened"
    assert not scans, "pruned shard was dispatched"
    assert eng.stats["index_shards_pruned"] >= 1
    assert eng.stats["index_bytes_skipped"] >= p.stat().st_size


def test_one_shot_engine_builds_nothing(tmp_path):
    """No store attached, no corpus opt-in (the one-shot CLI shape):
    lookups run, but no summary is ever BUILT — a process that will
    never consult them must not pay the pass."""
    index_summary.clear()  # detach the autouse store
    p = tmp_path / "shard.txt"
    p.write_bytes(b"plain filler\n" * 50)
    eng = GrepEngine("needle", backend="cpu")
    eng.scan_file(p)
    eng.scan_batch([("a", str(p))], index_prune=True)
    assert index_summary.index_counters().get(
        "index_summaries_built", 0) == 0


def test_scan_file_maybe_still_scans(tmp_path):
    p = tmp_path / "shard.txt"
    p.write_bytes(b"the needle is here\nplain filler\n" * 50)
    eng = GrepEngine("needle", backend="cpu")
    assert eng.scan_file(p).n_matches == 50
    res = eng.scan_file(p)  # summary exists, literal present: maybe
    assert res.n_matches == 50
    assert eng.stats.get("index_maybe_scans", 0) >= 1
    assert not eng.stats.get("index_shards_pruned", 0)


@pytest.mark.parametrize("label,kw", ENGINES)
def test_indexed_vs_off_byte_identity_scan_file(label, kw, tmp_path,
                                                monkeypatch):
    """Every kernel family: matched lines with the index warm equal the
    DGREP_INDEX=0 answer — on a corpus its query matches AND one it
    cannot."""
    hit = tmp_path / "hit.txt"
    hit.write_bytes(_corpus_bytes())
    miss = tmp_path / "miss.txt"
    miss.write_bytes(b"xyzzy plugh 12345\n" * 300)
    results = {}
    for mode in ("off", "indexed"):
        if mode == "off":
            monkeypatch.setenv("DGREP_INDEX", "0")
        else:
            monkeypatch.delenv("DGREP_INDEX", raising=False)
        index_summary.clear()  # detaches the store too
        index_summary.attach_store(tmp_path / "idxstore")
        eng = GrepEngine(backend="cpu", **kw)
        per = {}
        for p in (hit, miss):
            a = eng.scan_file(p)
            b = eng.scan_file(p)  # the warm (possibly pruned) pass
            assert a.matched_lines.tolist() == b.matched_lines.tolist()
            per[p.name] = a.matched_lines.tolist()
        results[mode] = per
    assert results["off"] == results["indexed"], label


@pytest.mark.perf
def test_scan_batch_pruned_members_zero_opens_zero_scans(tmp_path,
                                                         monkeypatch):
    paths = []
    for i in range(6):
        p = tmp_path / f"f{i}.txt"
        body = b"plain filler line\n" * 100
        if i == 2:
            body += b"one needle line\n"
        p.write_bytes(body)
        paths.append(p)
    eng = GrepEngine("needle", backend="cpu")
    items = [(p.name, str(p)) for p in paths]
    first = eng.scan_batch(items, index_prune=True)
    assert [r.n_matches for _, r in first] == [0, 0, 1, 0, 0, 0]
    opened = _spy_opens(monkeypatch)
    warm = eng.scan_batch(items, index_prune=True)
    assert [(n, r.n_matches) for n, r in warm] == \
        [(n, r.n_matches) for n, r in first]
    opened_names = {os.path.basename(str(x)) for x in opened}
    # only the maybe shard may be re-opened; all pruned members never are
    assert opened_names <= {"f2.txt"}, opened_names
    assert eng.stats["index_shards_pruned"] >= 5


def test_scan_batch_invert_keeps_reads_exact(tmp_path, monkeypatch):
    """grep -v: the complement needs the file's real lines, so the app
    refuses member pruning (index_prune=False) and outputs stay
    byte-identical to DGREP_INDEX=0."""
    from distributed_grep_tpu.apps import grep_tpu

    paths = []
    for i in range(3):
        p = tmp_path / f"f{i}.txt"
        p.write_bytes(b"alpha\nbeta\n" + (b"needle\n" if i == 1 else b""))
        paths.append(p)
    items = [(p.name, p.read_bytes()) for p in paths]

    def records(env_off: bool):
        if env_off:
            monkeypatch.setenv("DGREP_INDEX", "0")
        else:
            monkeypatch.delenv("DGREP_INDEX", raising=False)
        index_summary.clear()
        grep_tpu._configured_with = None
        grep_tpu.configure(pattern="needle", backend="cpu", invert=True)
        from conftest import expand_records

        out = []
        for _ in range(2):  # cold then (possibly) index-warm
            out = expand_records(grep_tpu.map_batch_fn(list(items)))
        return sorted((kv.key, kv.value) for kv in out)

    assert records(False) == records(True)


# ------------------------------------------------ content drift (cp -p + mv)


def test_stat_drift_evicts_and_never_prunes_stale(tmp_path):
    """The cp -p + mv case: an atomic same-size, mtime-preserving
    replacement changes only the inode — the summary keyed on the old
    stat must be a clean miss, and the new content's matches must
    surface."""
    p = tmp_path / "shard.txt"
    old = b"plain filler text here\n" * 40
    p.write_bytes(old)
    eng = GrepEngine("needle", backend="cpu")
    assert eng.scan_file(p).n_matches == 0  # builds the no-needle summary
    assert eng.scan_file(p).n_matches == 0  # and prunes on it
    assert eng.stats.get("index_shards_pruned", 0) >= 1
    st = p.stat()
    # same SIZE, same MTIME, new INODE, needle present
    new = (b"plain filler text here\n" * 39
           + b"x needle yz\n".ljust(23, b"!"))
    assert len(new) == len(old)
    repl = tmp_path / "shard.txt.new"
    repl.write_bytes(new)
    os.utime(repl, ns=(st.st_atime_ns, st.st_mtime_ns))
    os.replace(repl, p)
    st2 = p.stat()
    assert (st2.st_size, st2.st_mtime_ns) == (st.st_size, st.st_mtime_ns)
    res = eng.scan_file(p)
    assert res.n_matches == 1, "stale summary pruned fresh content"


def test_store_rejects_stale_validators(tmp_path):
    store = IndexStore(tmp_path / "index")
    p = tmp_path / "f.txt"
    p.write_bytes(b"some corpus bytes here\n")
    key = index_summary.file_key(p)
    s = index_summary.build_summary(p.read_bytes())
    store.save(key, s)
    assert store.load(key) == s
    # drift the validators: the stored record must evict, not serve
    time.sleep(0.01)
    p.write_bytes(b"different corpus bytes\n")
    key2 = index_summary.file_key(p)
    assert store.load(key2) is None
    assert store.load(key2) is None  # stays gone (file deleted)


# ------------------------------------------------------------- planner side


def _mk_corpus(tmp_path, n=6, needle_at=2):
    paths = []
    for i in range(n):
        p = tmp_path / f"f{i}.txt"
        body = b"plain filler line\n" * 30
        if i == needle_at:
            body += b"one needle line\n"
        p.write_bytes(body)
        paths.append(str(p))
    return paths


def _publish_all(paths):
    for f in paths:
        with open(f, "rb") as fh:
            index_summary.publish_summary(index_summary.file_key(f),
                                          fh.read())


def test_plan_map_splits_prunes_files(tmp_path):
    from distributed_grep_tpu.runtime.job import plan_map_splits

    paths = _mk_corpus(tmp_path)
    _publish_all(paths)
    req = index_plan.requirements_for_query(pattern="needle")
    pruner = index_plan.SplitPruner(req, IndexStore(tmp_path / "idx"))
    splits = plan_map_splits(paths, batch_bytes=32 << 20, pruner=pruner)
    flat = [f for s in splits for f in (s if isinstance(s, list) else [s])]
    assert flat == [paths[2]]
    assert pruner.shards_pruned == 5 and pruner.maybe_scans == 1
    assert pruner.bytes_skipped == sum(
        os.path.getsize(p) for p in paths if p != paths[2]
    )
    # no summaries -> nothing prunes (silent direction)
    index_summary.clear()
    pruner2 = index_plan.SplitPruner(req, IndexStore(tmp_path / "idx"))
    splits2 = plan_map_splits(paths, batch_bytes=32 << 20, pruner=pruner2)
    flat2 = [f for s in splits2 for f in (s if isinstance(s, list) else [s])]
    assert flat2 == paths and pruner2.shards_pruned == 0


def test_pruner_for_job_gating(tmp_path, monkeypatch):
    from distributed_grep_tpu.utils.config import JobConfig

    def cfg(**opts):
        return JobConfig(
            input_files=["x"],
            application="distributed_grep_tpu.apps.grep_tpu",
            app_options={"pattern": "needle", "backend": "cpu", **opts},
        )

    assert index_plan.pruner_for_job(cfg(), tmp_path) is not None
    # zero-match output is NOT empty for these: planner must not prune
    assert index_plan.pruner_for_job(cfg(invert=True), tmp_path) is None
    assert index_plan.pruner_for_job(cfg(count_only=True), tmp_path) is None
    assert index_plan.pruner_for_job(
        cfg(presence_only=True), tmp_path) is None
    assert index_plan.pruner_for_job(cfg(max_errors=1), tmp_path) is None
    # ineligible query / foreign app / kill-switch
    assert index_plan.pruner_for_job(cfg(pattern="ab"), tmp_path) is None
    foreign = JobConfig(input_files=["x"],
                        application="distributed_grep_tpu.apps.grep",
                        app_options={"pattern": "needle"})
    assert index_plan.pruner_for_job(foreign, tmp_path) is None
    monkeypatch.setenv("DGREP_INDEX", "0")
    assert index_plan.pruner_for_job(cfg(), tmp_path) is None


# ----------------------------------------------------- service end to end


def _run_service_job(svc, files, pattern, **opts):
    import time as _t

    from distributed_grep_tpu.utils.config import JobConfig

    cfg = JobConfig(
        input_files=files,
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": pattern, "backend": "cpu", **opts},
        n_reduce=2, journal=False,
    )
    jid = svc.submit(cfg)
    deadline = _t.monotonic() + 60
    while _t.monotonic() < deadline:
        st = svc.job_status(jid)
        if st["state"] in ("done", "failed", "cancelled"):
            break
        _t.sleep(0.02)
    assert st["state"] == "done", st
    from pathlib import Path

    out = b"".join(
        Path(p).read_bytes() for p in sorted(st.get("outputs", []))
    )
    return st, out


@pytest.mark.service
def test_service_indexed_vs_off_byte_identity_and_restart(tmp_path,
                                                          monkeypatch):
    from distributed_grep_tpu.runtime.service import GrepService

    paths = _mk_corpus(tmp_path, n=8, needle_at=3)

    # result tier off throughout: the warm resubmits below must PLAN
    # (the index prune is what shrinks the warm plan) — the round-20
    # result cache would answer them with no plan at all
    monkeypatch.setenv("DGREP_RESULT_CACHE", "0")
    # DGREP_INDEX=0 oracle (fresh service, no summaries anywhere)
    monkeypatch.setenv("DGREP_INDEX", "0")
    svc0 = GrepService(work_root=tmp_path / "svc0", task_timeout_s=30)
    svc0.start_local_workers(1)
    try:
        _, out_off = _run_service_job(svc0, paths, "needle")
        _, out_off_miss = _run_service_job(svc0, paths, "zzqqxx")
    finally:
        svc0.stop()
    assert "index" not in svc0.status()  # true no-op: no /status key
    monkeypatch.delenv("DGREP_INDEX", raising=False)
    index_summary.clear()

    svc = GrepService(work_root=tmp_path / "svc", task_timeout_s=30)
    svc.start_local_workers(1)
    try:
        st_cold, out_cold = _run_service_job(svc, paths, "needle")
        st_warm, out_warm = _run_service_job(svc, paths, "needle")
        assert out_cold == out_warm == out_off
        assert st_warm["map"]["total"] < st_cold["map"]["total"]
        assert st_warm["metrics"]["counters"]["index_shards_pruned"] == 7
        _, out_miss = _run_service_job(svc, paths, "zzqqxx")
        assert out_miss == out_off_miss
        assert svc.status()["index"]["index_shards_pruned"] >= 7
    finally:
        svc.stop()

    # restart: a NEW daemon + a cold process-side cache must serve the
    # persisted summaries without rebuilding a single one
    index_summary.clear()
    svc2 = GrepService(work_root=tmp_path / "svc")
    svc2.start_local_workers(1)
    try:
        built0 = index_summary.index_counters().get(
            "index_summaries_built", 0)
        st2, out2 = _run_service_job(svc2, paths, "needle")
        assert out2 == out_off
        assert st2["metrics"]["counters"]["index_shards_pruned"] == 7
        assert index_summary.index_counters().get(
            "index_summaries_built", 0) == built0
    finally:
        svc2.stop()


@pytest.mark.service
def test_service_count_mode_not_planner_pruned(tmp_path):
    """grep -c emits a record per file (zero counts included): the
    planner must keep every map task, and outputs must match the
    unindexed daemon exactly."""
    from distributed_grep_tpu.runtime.service import GrepService

    paths = _mk_corpus(tmp_path, n=4, needle_at=1)
    svc = GrepService(work_root=tmp_path / "svc", task_timeout_s=30)
    svc.start_local_workers(1)
    try:
        _run_service_job(svc, paths, "needle")  # builds summaries
        st, out = _run_service_job(svc, paths, "needle", count_only=True)
        assert st["map"]["total"] == len(paths)
        # every file's count record is present, zeros included
        for p in paths:
            assert os.fspath(p).encode() in out
    finally:
        svc.stop()

"""Application-boundary tests: KeyValue, grouping, loader, grep/wordcount apps."""

import pytest

from distributed_grep_tpu.apps import KeyValue, load_application
from distributed_grep_tpu.apps.base import group_reduce
from tests.conftest import expand_records


def test_group_reduce_sort_merge_semantics():
    # Mirrors reduceDistinctKeys (worker.go:22-43): one reduce call per key,
    # values in original order within sorted key runs.
    records = [KeyValue("b", "1"), KeyValue("a", "x"), KeyValue("b", "2"), KeyValue("a", "y")]
    calls = []

    def reducef(key, values):
        calls.append((key, list(values)))
        return ",".join(values)

    out = group_reduce(records, reducef)
    assert out == {"a": "x,y", "b": "1,2"}
    assert calls == [("a", ["x", "y"]), ("b", ["1", "2"])]


def test_load_application_by_module_name():
    app = load_application("distributed_grep_tpu.apps.grep", pattern="fox")
    kvs = expand_records(app.map_fn("f.txt", b"a fox\nno match\nfoxfox"))
    assert [kv.key for kv in kvs] == ["f.txt (line number #1)", "f.txt (line number #3)"]
    assert app.reduce_fn("k", ["v1", "v2"]) == "v1"


def test_load_application_by_path(tmp_path):
    # Reference-style module exposing Map/Reduce names (worker_launch.go:27-31).
    p = tmp_path / "custom_app.py"
    p.write_text(
        "from distributed_grep_tpu.apps.base import KeyValue\n"
        "def Map(filename, contents):\n"
        "    return [KeyValue('n_bytes', str(len(contents)))]\n"
        "def Reduce(key, values):\n"
        "    return str(sum(int(v) for v in values))\n"
    )
    app = load_application(str(p))
    assert app.map_fn("x", b"abcd") == [KeyValue("n_bytes", "4")]
    assert app.reduce_fn("n_bytes", ["4", "6"]) == "10"


def test_load_application_rejects_incomplete_module(tmp_path):
    p = tmp_path / "broken_app.py"
    p.write_text("def Map(f, c): return []\n")  # no Reduce
    with pytest.raises(TypeError):
        load_application(str(p))


def test_grep_app_pattern_plumbing_and_regex():
    app = load_application("distributed_grep_tpu.apps.grep", pattern=r"h[ae]llo")
    kvs = expand_records(app.map_fn("t", b"hallo\nhello\nhullo\n"))
    assert len(kvs) == 2
    # Reconfigure (new job, new pattern) — state must not leak.
    app.configure(pattern="hullo")
    assert len(expand_records(app.map_fn("t", b"hallo\nhello\nhullo\n"))) == 1


def test_grep_app_case_insensitive_and_binary_safe():
    app = load_application("distributed_grep_tpu.apps.grep", pattern="hello", ignore_case=True)
    kvs = expand_records(app.map_fn("t", b"HELLO\nx\xff\xfehello\xff\n"))
    assert len(kvs) == 2
    assert kvs[1].key == "t (line number #2)"


def test_wordcount_app():
    app = load_application("distributed_grep_tpu.apps.wordcount")
    kvs = app.map_fn("t", b"the cat and the hat")
    out = group_reduce(kvs, app.reduce_fn)
    assert out == {"the": "2", "cat": "1", "and": "1", "hat": "1"}


def test_grep_cpu_no_phantom_trailing_line():
    # 'grep -n ""' on a trailing-newline file matches every real line, not a
    # phantom empty line after the final '\n'
    from distributed_grep_tpu.apps import grep as grep_app

    grep_app.configure(pattern="")
    out = expand_records(grep_app.map_fn("f", b"one\ntwo\n"))
    assert [kv.key for kv in out] == [
        "f (line number #1)", "f (line number #2)"
    ]


def test_grep_cpu_pattern_set_uses_ac():
    from distributed_grep_tpu.apps import grep as grep_app

    grep_app.configure(patterns=["needle", "vol.cano"])  # literals, not regex
    out = expand_records(grep_app.map_fn("f", b"a needle\nvolXcano\nvol.cano literal\nnone\n"))
    assert [kv.key for kv in out] == [
        "f (line number #1)", "f (line number #3)"
    ]


def test_grep_invert_both_apps():
    from distributed_grep_tpu.apps import grep as cpu_app
    from distributed_grep_tpu.apps import grep_tpu as tpu_app

    data = b"hello world\nno match here\nhello again\nplain\n"
    cpu_app.configure(pattern="hello", invert=True)
    tpu_app.configure(pattern="hello", invert=True, backend="cpu")
    want = ["f (line number #2)", "f (line number #4)"]
    assert [kv.key for kv in expand_records(cpu_app.map_fn("f", data))] == want
    assert [kv.key for kv in expand_records(tpu_app.map_fn("f", data))] == want


def test_inverted_index_app():
    from distributed_grep_tpu.apps.base import group_reduce
    from distributed_grep_tpu.apps.loader import load_application

    # fresh module instance (the runtime's isolation) — no state leaks
    ii = load_application("distributed_grep_tpu.apps.inverted_index").module
    ii.configure(min_word_len=2)
    recs = ii.map_fn("a.txt", b"the cat sat\nThe dog") + \
        ii.map_fn("b.txt", b"a cat runs")
    out = group_reduce(recs, ii.reduce_fn)
    assert out["cat"] == "2 a.txt,b.txt"
    assert out["dog"] == "1 a.txt"
    assert "a" not in out  # min_word_len filters


def test_inverted_index_through_runtime(tmp_path):
    from distributed_grep_tpu.runtime.job import run_job
    from distributed_grep_tpu.utils.config import JobConfig

    f1, f2 = tmp_path / "x.txt", tmp_path / "y.txt"
    f1.write_bytes(b"alpha beta\n")
    f2.write_bytes(b"beta gamma\n")
    cfg = JobConfig(
        input_files=[str(f1), str(f2)],
        application="distributed_grep_tpu.apps.inverted_index",
        n_reduce=3,
        work_dir=str(tmp_path / "job"),
    )
    res = run_job(cfg, n_workers=2)
    assert res.results["beta"] == f"2 {f1},{f2}"
    assert res.results["alpha"] == f"1 {f1}"


def test_literal_mode_lines_matches_wrapped_regex():
    """The vectorized -w/-x literal confirm (round 5) vs the wrap_mode
    regex oracle, over boundary-adversarial corpora: BOF/EOF occurrences,
    line-edge occurrences, overlapping occurrences, '_' constituents."""
    import re

    import numpy as np

    from distributed_grep_tpu.apps.grep import literal_mode_lines, wrap_mode

    cases = [
        (b"the", b"the\nthe end\nxthe\nthe_y\na the b\n_the\nthe"),
        (b"aa", b"aaa\naa\nb aa c\naaaa\n"),  # overlapping occurrences
        (b"a-b", b"a-b\nxa-b\na-b y\nza-bw\n"),  # non-word pattern edges
        (b"x", b"x"),  # single byte, no trailing newline
        (b"t t", b"t t\na t t b\nt tt\n"),  # literal containing a space
    ]
    for lit, data in cases:
        for mode in ("word", "line"):
            rx = re.compile(wrap_mode(re.escape(lit), mode))
            lines = data.split(b"\n")
            if lines and lines[-1] == b"":
                lines.pop()
            want = sorted(
                i for i, ln in enumerate(lines, 1) if rx.search(ln)
            )
            got = literal_mode_lines(data, lit, mode).tolist()
            assert got == want, (lit, mode, got, want)


def test_grep_tpu_literal_word_fast_path_engages():
    """A case-sensitive single literal with -w must take the vectorized
    confirm (and agree with the regex path's records exactly)."""
    from distributed_grep_tpu.apps import grep_tpu
    from tests.conftest import expand_records

    data = b"the\nother\n a the b\nthe_x\nthe end\n"
    grep_tpu.configure(pattern="the", word_regexp=True, backend="cpu")
    from distributed_grep_tpu.utils.native import native_available

    if native_available():
        assert grep_tpu._confirm_lit == b"the"
    fast = expand_records(grep_tpu.map_fn("f", data))
    # force the regex path and compare; reset the configure memo so the
    # override cannot leak into later tests via the key == memo early-out
    grep_tpu._confirm_lit = None
    grep_tpu._configured_with = None
    slow = expand_records(grep_tpu.map_fn("f", data))
    assert [(kv.key, kv.value) for kv in fast] == \
        [(kv.key, kv.value) for kv in slow]
    assert [kv.key for kv in fast] == [
        "f (line number #1)", "f (line number #3)", "f (line number #5)"
    ]

"""Service metrics tier (round 15): typed instruments, Prometheus
/metrics exposition, rolling-window cache rates, /status latency
summary, and the `dgrep explain` routing report.

Standalone-runnable:  python -m pytest tests/ -q -m metrics
"""

from __future__ import annotations

import json
import threading
import urllib.request

import pytest

from distributed_grep_tpu.utils import metrics as metrics_mod
from distributed_grep_tpu.utils.config import JobConfig

pytestmark = pytest.mark.metrics


# ------------------------------------------------------- histogram math

def test_histogram_bucket_math_and_render():
    h = metrics_mod.Histogram("dgrep_t_seconds", help="T.")
    for v in (0.002, 0.002, 0.01, 0.5, 3.0, 500.0):
        h.observe(v)
    counts, total, count = h.snapshot()
    assert count == 6 and total == pytest.approx(503.514)
    # raw (non-cumulative) landings: 0.002x2 -> le=0.004, 0.01 -> 0.016,
    # 0.5 -> 1.024, 3.0 -> 4.096, 500 -> +Inf
    by_edge = dict(zip(h.buckets, counts))
    assert by_edge[0.004] == 2 and by_edge[0.016] == 1
    assert by_edge[1.024] == 1 and by_edge[4.096] == 1
    assert counts[-1] == 1  # +Inf
    lines = h.render()
    # cumulative exposition contract + the exact terminal lines
    assert 'dgrep_t_seconds_bucket{le="0.004"} 2' in lines
    assert 'dgrep_t_seconds_bucket{le="0.016"} 3' in lines
    assert 'dgrep_t_seconds_bucket{le="+Inf"} 6' in lines
    assert lines[-1] == "dgrep_t_seconds_count 6"


def test_histogram_quantiles():
    h = metrics_mod.Histogram("dgrep_t_seconds")
    assert h.quantile(0.5) is None  # empty
    for _ in range(100):
        h.observe(0.01)  # all land in (0.004, 0.016]
    q = h.quantile(0.5)
    assert 0.004 < q <= 0.016
    # observations past the last finite edge clamp to it
    h2 = metrics_mod.Histogram("dgrep_t_seconds")
    h2.observe(1e9)
    assert h2.quantile(0.99) == h2.buckets[-1]


def test_untouched_instruments_answer_lock_free():
    """The CorpusCache `_touched` convention: instruments that were never
    recorded answer reads without taking their lock (the hot disabled
    path must not serialize on process-global mutexes)."""

    class Exploding:
        def __enter__(self):
            raise AssertionError("lock taken on the untouched path")

        def __exit__(self, *a):
            return False

    c = metrics_mod.MetricCounter("dgrep_x_total")
    c._lock = Exploding()
    assert c.value() == 0.0
    h = metrics_mod.Histogram("dgrep_x_seconds")
    h._lock = Exploding()
    assert h.snapshot()[2] == 0 and h.quantile(0.5) is None


# -------------------------------------------------- exposition (golden)

_GOLDEN_SERIES = {
    "dgrep_g": ("gauge", "A gauge."),
    "dgrep_h_seconds": ("histogram", "A histogram."),
    "dgrep_n_total": ("counter", "A counter."),
}

_GOLDEN = """\
# HELP dgrep_g A gauge.
# TYPE dgrep_g gauge
dgrep_g 2.5
# HELP dgrep_h_seconds A histogram.
# TYPE dgrep_h_seconds histogram
dgrep_h_seconds_bucket{le="0.001"} 0
dgrep_h_seconds_bucket{le="0.004"} 1
dgrep_h_seconds_bucket{le="0.016"} 1
dgrep_h_seconds_bucket{le="0.064"} 1
dgrep_h_seconds_bucket{le="0.256"} 1
dgrep_h_seconds_bucket{le="1.024"} 2
dgrep_h_seconds_bucket{le="4.096"} 2
dgrep_h_seconds_bucket{le="16.384"} 2
dgrep_h_seconds_bucket{le="65.536"} 2
dgrep_h_seconds_bucket{le="262.144"} 2
dgrep_h_seconds_bucket{le="+Inf"} 2
dgrep_h_seconds_sum 1.002
dgrep_h_seconds_count 2
# HELP dgrep_n_total A counter.
# TYPE dgrep_n_total counter
dgrep_n_total 3
"""


def test_prometheus_exposition_golden_and_byte_stable():
    reg = metrics_mod.MetricsRegistry(series=_GOLDEN_SERIES)
    reg.counter("dgrep_n_total").inc(3)
    reg.gauge("dgrep_g").set(2.5)
    h = reg.histogram("dgrep_h_seconds")
    h.observe(0.002)
    h.observe(1.0)
    first = reg.render()
    assert first == _GOLDEN
    assert reg.render() == first  # byte-stable


def test_registry_kind_mismatch_raises():
    reg = metrics_mod.MetricsRegistry(series=_GOLDEN_SERIES)
    reg.counter("dgrep_n_total")
    with pytest.raises(ValueError):
        reg.gauge("dgrep_n_total")
    with pytest.raises(ValueError):
        reg.histogram("dgrep_g")  # declared gauge


def test_reset_zeroes_in_place():
    """Module-level instrument references must survive a reset — the
    conftest isolation fixture zeroes values, never detaches them."""
    reg = metrics_mod.MetricsRegistry(series=_GOLDEN_SERIES)
    c = reg.counter("dgrep_n_total")
    c.inc(7)
    reg.reset()
    assert c.value() == 0.0
    c.inc(1)  # the SAME object still feeds the registry
    assert "dgrep_n_total 1" in reg.render()


def test_instrument_concurrency_stress():
    c = metrics_mod.MetricCounter("dgrep_s_total")
    h = metrics_mod.Histogram("dgrep_s_seconds")

    def work():
        for _ in range(2000):
            c.inc()
            h.observe(0.01)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value() == 16000
    assert h.snapshot()[2] == 16000


# ------------------------------------------------- rolling-window rates

def test_rate_window_expiry():
    w = metrics_mod.RateWindow(window_s=100.0, granularity_s=10.0)
    w.add("hits", 5.0, now=0.0)
    w.add("hits", 3.0, now=50.0)
    assert w.total("hits", now=60.0) == 8.0
    assert w.total("hits", now=105.0) == 3.0  # first bucket aged out
    assert w.total("hits", now=500.0) == 0.0


def test_delta_tracker_baseline_and_deltas():
    t = metrics_mod.CounterDeltaTracker(("hits",), window_s=1000.0)
    t.observe("p", {"hits": 10}, now=0.0)  # first report = baseline
    assert t.window_totals(now=1.0)["hits"] == 0.0
    t.observe("p", {"hits": 16}, now=2.0)
    assert t.window_totals(now=3.0)["hits"] == 6.0
    # a LOWER reading is a stale/out-of-order snapshot (same-token
    # sources are same-process): ignored, baseline stays the running
    # max — lowering it would re-count the gap on the next report
    t.observe("p", {"hits": 2}, now=4.0)
    assert t.window_totals(now=5.0)["hits"] == 6.0
    t.observe("p", {"hits": 14}, now=6.0)  # still below the max: ignored
    assert t.window_totals(now=7.0)["hits"] == 6.0
    t.observe("p", {"hits": 20}, now=8.0)  # past the max: +4 only
    assert t.window_totals(now=9.0)["hits"] == 10.0


def test_delta_tracker_reconnect_same_process_no_double_count():
    """The satellite audit: a worker that reconnects after a daemon
    restart keeps its process-lifetime counters but gets a FRESH
    service-allocated id.  Keyed by the per-process token, the totals
    continue exactly; keyed by id (the no-token fallback), the new id
    re-baselines — either way nothing is double-counted or regressed."""
    t = metrics_mod.CounterDeltaTracker(("hits",), window_s=1000.0)
    # same process token reports under worker id 1, then id 7
    t.observe(42.0, {"hits": 10}, now=0.0)
    t.observe(42.0, {"hits": 15}, now=1.0)   # +5
    t.observe(42.0, {"hits": 18}, now=2.0)   # +3, now under a new id —
    # the SOURCE is the token, so the id change is invisible
    assert t.window_totals(now=3.0)["hits"] == 8.0
    # no-token fallback: id keys.  The reconnected id's first report
    # (full lifetime total 20) must BASELINE, not add 20
    t2 = metrics_mod.CounterDeltaTracker(("hits",), window_s=1000.0)
    t2.observe(1.0, {"hits": 10}, now=0.0)
    t2.observe(1.0, {"hits": 14}, now=1.0)   # +4
    t2.observe(7.0, {"hits": 20}, now=2.0)   # reconnect, fresh id
    assert t2.window_totals(now=3.0)["hits"] == 4.0


def test_service_worker_seen_feeds_rates_and_strips_proc(tmp_path):
    from distributed_grep_tpu.runtime.service import GrepService

    svc = GrepService(work_root=tmp_path / "root")
    try:
        svc._worker_seen(1, metrics={"proc": 42.0, "compile_cache_hits": 10})
        svc._worker_seen(1, metrics={"proc": 42.0, "compile_cache_hits": 15})
        # daemon reallocated the id; same process keeps reporting
        svc._worker_seen(7, metrics={"proc": 42.0, "compile_cache_hits": 18})
        totals = svc._cache_rates.window_totals()
        assert totals["compile_cache_hits"] == 8.0
        # the token is consumed, never stored into the /status rows
        st = svc.status()
        for row in st["workers"].values():
            assert "proc" not in (row.get("metrics") or {})
    finally:
        svc.stop()


def test_env_metrics_window_parser(monkeypatch):
    monkeypatch.delenv("DGREP_METRICS_WINDOW_S", raising=False)
    assert metrics_mod.env_metrics_window_s() == 300.0
    monkeypatch.setenv("DGREP_METRICS_WINDOW_S", "60")
    assert metrics_mod.env_metrics_window_s() == 60.0
    monkeypatch.setenv("DGREP_METRICS_WINDOW_S", "bogus")
    assert metrics_mod.env_metrics_window_s() == 300.0
    monkeypatch.setenv("DGREP_METRICS_WINDOW_S", "-5")
    assert metrics_mod.env_metrics_window_s() == 300.0


# ----------------------------------------- disabled-path no-op pinning

def test_spans_off_payloads_and_status_unchanged(tmp_path):
    """Metrics tier off the wire: spans-off workers piggyback nothing new
    (no 'proc' key can reach the wire), and a daemon that recorded
    nothing keeps the exact pre-metrics /status shape (no 'latency')."""
    from distributed_grep_tpu.runtime import rpc
    from distributed_grep_tpu.runtime.service import GrepService
    from distributed_grep_tpu.runtime.worker import WorkerLoop

    loop = WorkerLoop(transport=object(), app=None, spans_enabled=False)
    args = loop._finished_args(rpc.TaskFinishedArgs(task_id=0))
    assert args.metrics is None
    assert set(rpc.to_dict(args)) == {"task_id", "produced_parts"}
    # spans ON: the proc token rides INSIDE the metrics dict (no new
    # rpc field) and is stripped before any /status row stores it
    loop2 = WorkerLoop(transport=object(), app=None, spans_enabled=True)
    args2 = loop2._finished_args(rpc.TaskFinishedArgs(task_id=0))
    assert args2.metrics["proc"] == metrics_mod.PROC_TOKEN

    svc = GrepService(work_root=tmp_path / "root")
    try:
        st = svc.status()
        assert "latency" not in st
    finally:
        svc.stop()


def test_scheduler_worker_seen_strips_proc():
    from distributed_grep_tpu.runtime.scheduler import Scheduler

    s = Scheduler(files=[], n_reduce=0)
    s._worker_seen(0, metrics={"proc": 1.0, "bytes_scanned": 5})
    assert s.worker_status()["0"]["metrics"] == {"bytes_scanned": 5}
    s.stop()


# -------------------------------------------------- /metrics over HTTP

def _http_get(port: int, path: str):
    req = urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    )
    return req, req.read()


@pytest.mark.service
def test_service_metrics_endpoint_and_latency(tmp_path):
    from distributed_grep_tpu.runtime.service import GrepService, ServiceServer

    corpus = tmp_path / "corpus"
    corpus.mkdir()
    for i in range(3):
        (corpus / f"f{i}.txt").write_text("needle\nhay\n" * 20)
    svc = GrepService(work_root=tmp_path / "root")
    server = ServiceServer(svc)
    server.start()
    try:
        svc.start_local_workers(1)
        resp, body = _http_get(server.port, "/metrics")
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        text = body.decode("utf-8", "strict")
        assert "# TYPE dgrep_queue_wait_seconds histogram" in text
        assert "# TYPE dgrep_queue_depth gauge" in text
        assert "dgrep_jobs_done_total 0" in text
        # idle daemon: consecutive scrapes are byte-identical
        assert _http_get(server.port, "/metrics")[1] == body

        cfg = JobConfig(
            input_files=[str(p) for p in sorted(corpus.iterdir())],
            application="distributed_grep_tpu.apps.grep_tpu",
            app_options={"pattern": "needle", "backend": "cpu"},
            n_reduce=2,
        )
        jid = svc.submit(cfg)
        assert svc.wait_job(jid, timeout=60)
        text = _http_get(server.port, "/metrics")[1].decode("utf-8",
                                                            "strict")
        assert "dgrep_jobs_done_total 1" in text
        assert "dgrep_queue_wait_seconds_count 1" in text
        assert "dgrep_job_e2e_seconds_count 1" in text
        # /status gains the compact latency summary once data exists
        st = svc.status()
        assert st["latency"]["queue_wait_s"]["count"] == 1
        assert st["latency"]["job_e2e_s"]["p95"] >= (
            st["latency"]["job_e2e_s"]["p50"]
        )
    finally:
        server.shutdown()
        svc.stop()


def test_coordinator_metrics_endpoint(tmp_path):
    from distributed_grep_tpu.runtime.http_coordinator import CoordinatorServer

    p = tmp_path / "in.txt"
    p.write_text("needle\n")
    cfg = JobConfig(
        input_files=[str(p)], work_dir=str(tmp_path / "w"),
        application="distributed_grep_tpu.apps.grep",
        app_options={"pattern": "needle"}, n_reduce=1, coordinator_port=0,
    )
    server = CoordinatorServer(cfg)
    server.start()
    try:
        resp, body = _http_get(server.port, "/metrics")
        assert resp.status == 200
        text = body.decode("utf-8", "strict")
        assert "# TYPE dgrep_assign_poll_seconds histogram" in text
        assert "# TYPE dgrep_map_phase_seconds histogram" in text
    finally:
        server.scheduler.stop()
        server._httpd.shutdown()
        server._httpd.server_close()


# ----------------------------------------------------- explain reports

def test_summarize_events_unit():
    from distributed_grep_tpu.runtime import explain as explain_mod

    events = [
        {"t": "span", "name": "scan:fdr", "dur": 0.5,
         "args": {"bytes": 100, "matches": 3, "device_fallback": False}},
        {"t": "span", "name": "scan:re", "dur": 0.1,
         "args": {"bytes": 10, "matches": 1, "device_fallback": False}},
        {"t": "span", "name": "map:read", "dur": 0.2, "args": {}},
        {"t": "instant", "name": "cache:hit"},
        {"t": "instant", "name": "cache:hit"},
        {"t": "instant", "name": "corpus:miss"},
        {"t": "instant", "name": "index:prune", "args": {"bytes": 64}},
        {"t": "instant", "name": "fuse:plan", "args": {"queries": 3}},
        {"t": "instant", "name": "assign_map"},
        {"t": "instant", "name": "task_timeout"},
        {"t": "worker_clock", "worker": 0, "offset_s": 0.1},  # skipped
    ]
    agg = explain_mod.summarize_events(events)
    assert agg["modes"]["fdr"] == {
        "scans": 1, "bytes": 100, "seconds": 0.5, "matches": 3}
    assert agg["model_cache"]["hits"] == 2
    assert agg["corpus_cache"]["misses"] == 1
    assert agg["index"] == {"prunes": 1, "bytes_skipped": 64, "maybes": 0}
    assert agg["fusion"]["fused_plans"] == 1
    assert agg["fusion"]["max_queries"] == 3
    assert agg["stages"]["map:read"]["count"] == 1
    assert agg["tasks"]["map_assigns"] == 1
    assert agg["tasks"]["timeouts"] == 1
    # route verdict: host+device modes mixed
    assert explain_mod._route_verdict(agg["modes"], 0) == "mixed"
    assert explain_mod._route_verdict({"native": {"scans": 1}}, 0) == "host"
    assert explain_mod._route_verdict({"fdr": {"scans": 1}}, 0) == "device"
    assert explain_mod._route_verdict({"fdr": {"scans": 1}}, 2) == "degraded"
    assert explain_mod._route_verdict({}, 0) == "unknown"
    # scan:batch rows are envelopes (the inner engine span carries the
    # real mode): a pure-device batched job must read "device", not
    # "mixed", and batch-only evidence is "unknown"
    assert explain_mod._route_verdict(
        {"batch": {"scans": 2}, "shift_and": {"scans": 2}}, 0) == "device"
    assert explain_mod._route_verdict({"batch": {"scans": 2}}, 0) == "unknown"


@pytest.mark.service
def test_explain_e2e_index_pruned_cache_warm(tmp_path, capsys, monkeypatch):
    """Acceptance e2e: a real service job that was index-pruned and
    model-cache-warm; `dgrep explain` reports the kernel family, the
    host/device route, the prune, and the cache hits — and the /metrics
    rolling-window gauges move.  Result tier OFF: an identical resubmit
    would otherwise answer wholly from the round-20 result cache — no
    scan, nothing for this scan-path report to pin (that route has its
    own pins in tests/test_result_cache.py)."""
    from distributed_grep_tpu.__main__ import main
    from distributed_grep_tpu.runtime.service import GrepService, ServiceServer

    monkeypatch.setenv("DGREP_RESULT_CACHE", "0")
    corpus = tmp_path / "corpus"
    corpus.mkdir()
    files = []
    for i in range(4):
        p = corpus / f"f{i}.txt"
        text = "zebraquagga hit\n" if i == 0 else "plain line\n"
        p.write_text(text * 40)
        files.append(str(p))
    svc = GrepService(work_root=tmp_path / "root", spans=True)
    server = ServiceServer(svc)
    server.start()
    try:
        svc.start_local_workers(2)

        def submit(pattern: str) -> str:
            cfg = JobConfig(
                input_files=files,
                application="distributed_grep_tpu.apps.grep_tpu",
                app_options={"pattern": pattern, "backend": "cpu"},
                n_reduce=2, spans=True,
            )
            jid = svc.submit(cfg)
            assert svc.wait_job(jid, timeout=60)
            return jid

        submit("zebraquagga")   # cold: builds summaries + model
        submit("plain line")    # different model (A/B: defeats the
        # app-level same-config short-circuit on the next submit)
        jid = submit("zebraquagga")  # warm: model-cache hit, pruned plan

        doc = svc.job_explain(jid)
        assert doc["spans"] is True and doc["state"] == "done"
        assert doc["query"]["pattern"] == "zebraquagga"
        assert doc["routing"]["route"] == "host"  # cpu backend
        assert "native" in doc["routing"]["engine_modes"]
        idx = doc["routing"]["index"]
        assert idx["planner_shards_pruned"] == 3
        assert idx["planner_bytes_skipped"] > 0
        assert doc["routing"]["model_cache"]["hits"] >= 1
        assert doc["tasks"]["map_commits"] == 1  # pruned to one shard
        assert doc["timing"]["e2e_s"] > 0

        # rolling-window rates saw the warm hit
        text = svc.metrics_text()
        hits = [ln for ln in text.splitlines()
                if ln.startswith("dgrep_window_model_cache_hits ")]
        assert hits and float(hits[0].split()[1]) >= 1
        pruned = [ln for ln in text.splitlines()
                  if ln.startswith("dgrep_window_index_shards_pruned ")]
        assert pruned and float(pruned[0].split()[1]) >= 1

        # the CLI renders the same report through the HTTP surface
        addr = f"127.0.0.1:{server.port}"
        assert main(["explain", "--addr", addr, jid]) == 0
        cli_doc = json.loads(capsys.readouterr().out)
        assert cli_doc["job_id"] == jid
        assert cli_doc["routing"]["index"]["planner_shards_pruned"] == 3
    finally:
        server.shutdown()
        svc.stop()


def test_explain_local_workdir(tmp_path, capsys):
    from distributed_grep_tpu.__main__ import main
    from distributed_grep_tpu.runtime.job import run_job

    p = tmp_path / "in.txt"
    p.write_text("needle\nhay\n" * 10)
    cfg = JobConfig(
        input_files=[str(p)], work_dir=str(tmp_path / "w"),
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": "needle", "backend": "cpu"},
        n_reduce=1, spans=True,
    )
    run_job(cfg, n_workers=1)
    assert main(["explain", str(tmp_path / "w")]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["routing"]["route"] == "host"
    assert doc["tasks"]["map_commits"] == 1
    # no event log, no --addr: a clean exit-2 diagnostic
    assert main(["explain", str(tmp_path / "nowhere")]) == 2

"""Regression tests for review findings: workdir staleness, journal/file
mismatch, stale queue entries, chunk-halo duplication, app state isolation."""

import threading
import time

from distributed_grep_tpu.apps.loader import load_application
from distributed_grep_tpu.runtime import rpc
from distributed_grep_tpu.runtime.job import run_job
from distributed_grep_tpu.runtime.scheduler import Scheduler
from distributed_grep_tpu.runtime.types import TaskState
from distributed_grep_tpu.utils.config import JobConfig
from distributed_grep_tpu.utils.io import read_chunks


def test_fresh_job_clears_stale_outputs(tmp_path, corpus):
    """A reused work_dir with smaller n_reduce must not leak old mr-out-*."""
    wd = str(tmp_path / "job")
    files = [str(p) for p in corpus.values()]
    cfg1 = JobConfig(input_files=files, app_options={"pattern": "hello"}, n_reduce=8, work_dir=wd)
    res1 = run_job(cfg1, n_workers=2)
    assert res1.results  # job 1 did find matches (results live in the
    # workdir's mr-out files — read before reusing the workdir, like the
    # reference's on-disk outputs)
    cfg2 = JobConfig(input_files=files, app_options={"pattern": "zzz_nomatch"}, n_reduce=2, work_dir=wd)
    res2 = run_job(cfg2, n_workers=2)
    assert res2.results == {}  # nothing matches; stale job-1 outputs must be gone
    assert len(res2.output_files) == 2


def test_journal_replay_rejects_changed_file(tmp_path):
    entries = [{"kind": "map_done", "task_id": 0, "file": "old.txt", "parts": [0]}]
    s = Scheduler(files=["new.txt"], n_reduce=1, sweep_interval_s=0.05, resume_entries=entries)
    # Entry names a different file -> task must still be runnable.
    a = s.assign_task(rpc.AssignTaskArgs(), timeout=1.0)
    assert a.assignment == rpc.Assignment.MAP and a.filename == "new.txt"
    s.stop()


def test_stale_queue_entry_not_reissued_after_completion():
    """Timeout re-enqueues a task; the original worker then completes it.
    The stale queue entry must not regress the task to IN_PROGRESS."""
    s = Scheduler(files=["f1"], n_reduce=1, task_timeout_s=0.2, sweep_interval_s=0.05)
    a = s.assign_task(rpc.AssignTaskArgs(), timeout=1.0)
    time.sleep(0.5)  # let the sweeper re-enqueue it
    s.map_finished(rpc.TaskFinishedArgs(task_id=a.task_id, produced_parts=[0]))
    assert s.map_tasks[a.task_id].state is TaskState.COMPLETED
    # Next assignment must be the reduce task, not the stale map entry.
    b = s.assign_task(rpc.AssignTaskArgs(), timeout=2.0)
    assert b.assignment == rpc.Assignment.REDUCE
    assert s.map_tasks[a.task_id].state is TaskState.COMPLETED
    s.stop()


def test_read_chunks_no_carry_only_tail(tmp_path):
    p = tmp_path / "f"
    p.write_bytes(b"abcd")
    # File length == chunk size: exactly one chunk, no halo-only tail.
    chunks = list(read_chunks(p, chunk_bytes=4, overlap=2))
    assert chunks == [(0, b"abcd")]
    # Two chunks with halo; second begins at offset 2 (halo overlaps).
    p.write_bytes(b"abcdef")
    chunks = list(read_chunks(p, chunk_bytes=4, overlap=2))
    assert chunks == [(0, b"abcd"), (2, b"cdef")]
    # Empty file: nothing.
    p.write_bytes(b"")
    assert list(read_chunks(p, chunk_bytes=4, overlap=2)) == []


def test_app_instances_are_isolated():
    """Two loads of the same app module must not share pattern state."""
    from tests.conftest import expand_records

    a = load_application("distributed_grep_tpu.apps.grep", pattern="aaa")
    b = load_application("distributed_grep_tpu.apps.grep", pattern="bbb")
    assert len(expand_records(a.map_fn("f", b"aaa\nbbb\n"))) == 1
    assert expand_records(a.map_fn("f", b"aaa\nbbb\n"))[0].key.endswith("#1)")
    assert expand_records(b.map_fn("f", b"aaa\nbbb\n"))[0].key.endswith("#2)")


def test_concurrent_jobs_different_patterns(tmp_path, corpus):
    """Two jobs running simultaneously in one process, different patterns."""
    files = [str(p) for p in corpus.values()]
    results = {}

    def job(name, pattern, wd):
        cfg = JobConfig(
            input_files=files, app_options={"pattern": pattern}, n_reduce=2, work_dir=wd
        )
        results[name] = run_job(cfg, n_workers=2)

    t1 = threading.Thread(target=job, args=("fox", "fox", str(tmp_path / "j1")))
    t2 = threading.Thread(target=job, args=("quick", "quick", str(tmp_path / "j2")))
    t1.start(), t2.start()
    t1.join(30), t2.join(30)
    fox_lines = "\n".join(results["fox"].sorted_lines())
    quick_lines = "\n".join(results["quick"].sorted_lines())
    assert "fox" in fox_lines and "quick" not in fox_lines.replace("quick brown", "")
    assert all("quick" in l for l in results["quick"].sorted_lines())


# ----------------------------------------------- round-5 ADVICE regressions

def test_transport_error_classification():
    """Fast `Connection Failed`-phase exceptions from the device transport
    must be classified as transport evidence (retry-window-eligible
    demotion), while generic runtime failures stay per-pattern permanent
    (round-4 ADVICE: a worker degraded during the fast-error phase never
    reclaimed the device after the tunnel healed)."""
    from distributed_grep_tpu.ops.engine import _is_transport_error

    transport = [
        RuntimeError("Connection Failed: tunnel endpoint went away"),
        RuntimeError("UNAVAILABLE: socket closed"),
        RuntimeError("Deadline Exceeded while dispatching"),
        RuntimeError("read: connection reset by peer"),
    ]
    for e in transport:
        assert _is_transport_error(e), e
    non_transport = [
        RuntimeError("Mosaic lowering failed: unsupported op"),
        RuntimeError("INVALID_ARGUMENT: bad dimension"),
        ValueError("connection"),  # not a RuntimeError: not device-layer
    ]
    for e in non_transport:
        assert not _is_transport_error(e), e


def test_transport_demotion_stays_retry_eligible():
    """_mark_device_broken(transport_evidence=True) must NOT set the
    permanent flag (the DEVICE_RETRY_S un-demote path stays open)."""
    from distributed_grep_tpu.ops.engine import GrepEngine

    eng = GrepEngine("needle", interpret=True)
    eng._mark_device_broken(transport_evidence=True)
    assert eng._device_broken and not eng._device_demotion_permanent
    eng2 = GrepEngine("needle", interpret=True)
    eng2._mark_device_broken(transport_evidence=False)
    assert eng2._device_broken and eng2._device_demotion_permanent


def test_progress_grace_capability_probed_from_signature():
    """The compile-grace declaration must be capability-probed from the
    callback signature, not by catching TypeError around the live call —
    a TypeError raised INSIDE a grace-capable callback is a real bug and
    must propagate, not silently degrade to a plain stamp (round-4
    ADVICE)."""
    import pytest

    from distributed_grep_tpu.ops.engine import _accepts_grace_kwarg

    def modern(grace_s=None):
        pass

    def legacy():
        pass

    def kwargs_only(**kw):
        pass

    assert _accepts_grace_kwarg(modern)
    assert not _accepts_grace_kwarg(legacy)
    assert _accepts_grace_kwarg(kwargs_only)

    # integration: a buggy grace-capable callback surfaces its TypeError
    from distributed_grep_tpu.ops.engine import GrepEngine

    calls = {"n": 0}

    def buggy(grace_s=None):
        calls["n"] += 1
        raise TypeError("bug inside callback body")

    eng = GrepEngine("needle", interpret=True)
    eng._accel_cached = True
    data = b"a needle here\nnothing\n" * 50
    with pytest.raises(TypeError, match="bug inside callback body"):
        eng.scan(data, progress=buggy)
    assert calls["n"] >= 1


def test_chip_count_gated_behind_device_verdict(monkeypatch):
    """devices="all" chip counting runs at CONSTRUCTION time (chip-aware
    FDR pricing probes the decomposition under it), and a bare
    jax.local_devices() there hangs in C on a black-holed transport —
    it must consult the shared time-boxed verdict first and price at 1
    chip on a dead device (round-5 review)."""
    from distributed_grep_tpu.ops import engine as engine_mod
    from distributed_grep_tpu.ops.engine import GrepEngine

    eng = GrepEngine("needle", interpret=True)
    eng.devices = "all"
    eng.mesh = None
    # interpret engines skip the wall by design (CPU backend can't
    # wedge) — force the non-interpret path to exercise the gate
    eng._interpret = False

    monkeypatch.setattr(engine_mod.GrepEngine, "_device_responsive",
                        lambda self: False)

    def boom():
        raise AssertionError("jax touched while device verdict is False")

    import jax

    monkeypatch.setattr(jax, "local_devices", boom)
    assert eng._active_chip_count() == 1

    monkeypatch.setattr(engine_mod.GrepEngine, "_device_responsive",
                        lambda self: True)
    monkeypatch.setattr(jax, "local_devices", lambda: [object()] * 4)
    assert eng._active_chip_count() == 4

"""Randomized recall fuzzing: engine vs Python-re oracle across modes.

VERDICT round-1 weak #5: "Hyperscan-equivalent recall" was asserted by
invariants, not measurement.  This suite generates random patterns from the
engine's supported grammar and random corpora (English-like, binary,
needle-injected), then asserts EXACT line agreement between every engine
mode and the per-line ``re`` oracle — the property the whole system
promises.  Failures reproduce from the printed seed.

Modes covered per case: device (XLA scan path on the CPU backend; the
Pallas kernels' correctness is pinned separately by interpret-mode
oracle tests in test_fdr/test_ops/test_nfa) and cpu (native DFA).  A few
interpret-mode Pallas cases run at the end on small corpora (interpret
mode is ~1000x slower than compiled).
"""

from __future__ import annotations

import re

import numpy as np
import pytest

from distributed_grep_tpu.ops.engine import GrepEngine
from tests.conftest import expand_records

# ------------------------------------------------------------ generators

LITERAL_CHARS = "abcdefgh XYZ019.*+?[](){}|^$\\-"


def _gen_literal(rng, n):
    return "".join(
        re.escape(LITERAL_CHARS[int(rng.integers(0, len(LITERAL_CHARS)))])
        for _ in range(n)
    )


def _gen_class(rng):
    choices = ["[a-f]", "[0-9]", "[a-zA-Z]", "[^x]", "[aeiou]", "[b-d1-3]", "."]
    return choices[int(rng.integers(0, len(choices)))]


def _gen_atom(rng, depth):
    r = rng.random()
    if depth <= 0 or r < 0.5:
        return _gen_literal(rng, int(rng.integers(1, 4)))
    if r < 0.7:
        return _gen_class(rng)
    if r < 0.85:
        return "(" + _gen_pattern(rng, depth - 1) + ")"
    return "(?:" + _gen_pattern(rng, depth - 1) + ")"


def _gen_piece(rng, depth):
    atom = _gen_atom(rng, depth)
    r = rng.random()
    if r < 0.6:
        return atom
    if r < 0.7:
        return atom + "?"
    if r < 0.78:
        return atom + "*"
    if r < 0.86:
        return atom + "+"
    lo = int(rng.integers(0, 3))
    hi = lo + int(rng.integers(0, 3))
    return atom + f"{{{lo},{hi}}}"


def _gen_pattern(rng, depth=2):
    n = int(rng.integers(1, 4))
    branches = []
    for _ in range(n):
        k = int(rng.integers(1, 4))
        branches.append("".join(_gen_piece(rng, depth) for _ in range(k)))
    pat = "|".join(branches)
    if rng.random() < 0.15:
        pat = "^(?:" + pat + ")"
    if rng.random() < 0.1:
        pat = "(?:" + pat + ")$"
    return pat


WORDS = b"the fox hello abc abd XYZ 019 aXf b2c aaab ccc dog end".split()


def _gen_corpus(rng, kind: str, size: int, needles: list[bytes]) -> bytes:
    if kind == "words":
        parts = []
        n = 0
        while n < size:
            k = int(rng.integers(2, 9))
            line = b" ".join(WORDS[int(i)] for i in rng.integers(0, len(WORDS), k))
            parts.append(line)
            n += len(line) + 1
        data = b"\n".join(parts)[:size]
    else:  # binary records
        arr = rng.integers(0, 256, size=size, dtype=np.uint8)
        arr[arr == 0x0A] = 0x0B
        arr[rng.integers(0, size, size=max(2, size // 80))] = 0x0A
        data = arr.tobytes()
    if needles:
        arr = np.frombuffer(data, dtype=np.uint8).copy()
        for pos in rng.integers(0, max(1, len(arr) - 64), size=min(8, len(needles) * 2)):
            nd = needles[int(rng.integers(0, len(needles)))]
            nd = nd.replace(b"\n", b"x")
            if len(nd) > len(arr):
                continue
            # sampled bounded-repeat matches can exceed the 64-byte margin
            # the position draw assumes — clamp so the write always fits
            # (a no-op for every draw that fit before)
            pos = min(int(pos), len(arr) - len(nd))
            arr[pos : pos + len(nd)] = np.frombuffer(nd, dtype=np.uint8)
        data = arr.tobytes()
    return data


def _oracle_lines(rx: re.Pattern[bytes], data: bytes) -> set[int]:
    lines = data.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()
    return {i for i, ln in enumerate(lines, 1) if rx.search(ln)}


def _sample_match(rng, pattern: str) -> bytes | None:
    """A byte string matching the pattern, for needle injection (crude:
    try some random expansions via the oracle)."""
    rx = re.compile(pattern.encode("utf-8", "surrogateescape"))
    for _ in range(30):
        cand = bytes(rng.integers(32, 123, size=int(rng.integers(1, 12)),
                                  dtype=np.uint8).tolist())
        m = rx.search(cand)
        if m and m.group(0):
            return m.group(0)
    return None


# ----------------------------------------------------------------- fuzz

@pytest.mark.parametrize("seed", range(30))
def test_fuzz_regex_modes_agree_with_re(seed):
    rng = np.random.default_rng(1000 + seed)
    pattern = _gen_pattern(rng)
    rx = re.compile(pattern.encode("utf-8", "surrogateescape"))
    needle = _sample_match(rng, pattern)
    kind = "words" if seed % 2 else "binary"
    data = _gen_corpus(rng, kind, 64 << 10, [needle] if needle else [])
    want = _oracle_lines(rx, data)
    for backend in ("device", "cpu"):
        eng = GrepEngine(pattern, backend=backend)
        got = set(eng.scan(data).matched_lines.tolist())
        assert got == want, (
            f"seed={seed} backend={backend} mode={eng.mode} pattern={pattern!r}: "
            f"+{sorted(got - want)[:5]} -{sorted(want - got)[:5]}"
        )


@pytest.mark.parametrize("seed", range(10))
def test_fuzz_ignore_case(seed):
    rng = np.random.default_rng(2000 + seed)
    pattern = _gen_pattern(rng)
    rx = re.compile(pattern.encode("utf-8", "surrogateescape"), re.IGNORECASE)
    data = _gen_corpus(rng, "words", 32 << 10, [])
    want = _oracle_lines(rx, data)
    for backend in ("device", "cpu"):
        eng = GrepEngine(pattern, backend=backend, ignore_case=True)
        got = set(eng.scan(data).matched_lines.tolist())
        assert got == want, f"seed={seed} backend={backend} pattern={pattern!r}"


@pytest.mark.parametrize("seed", range(10))
def test_fuzz_literal_sets(seed):
    """Random literal sets (incl. bytes that look like regex metachars and
    high bytes) vs substring oracle — the grep -F -f path (AC banks on cpu,
    FDR compile + DFA fallback on the CPU device backend)."""
    rng = np.random.default_rng(3000 + seed)
    n = int(rng.integers(2, 120))
    pats = []
    for _ in range(n):
        k = int(rng.integers(1, 9))
        pats.append(bytes(int(b) for b in rng.integers(1, 256, size=k)
                          ).replace(b"\n", b"*"))
    pats = sorted(set(pats))
    data = _gen_corpus(rng, "binary", 48 << 10, pats[:10])
    lines = data.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()
    want = {i for i, ln in enumerate(lines, 1) if any(p in ln for p in pats)}
    for backend in ("device", "cpu"):
        # surrogateescape mirrors the CLI's -f handling: arbitrary pattern
        # bytes round-trip str<->bytes exactly (CLAUDE.md invariant)
        eng = GrepEngine(
            patterns=[p.decode("utf-8", "surrogateescape") for p in pats],
            backend=backend,
        )
        got = set(eng.scan(data).matched_lines.tolist())
        assert got == want, f"seed={seed} backend={backend} mode={eng.mode} n={n}"


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_approx(seed):
    """agrep mode vs the reference recurrence oracle."""
    from distributed_grep_tpu.models.approx import line_matches, try_compile_approx

    rng = np.random.default_rng(4000 + seed)
    plen = int(rng.integers(3, 12))
    pattern = "".join(chr(c) for c in rng.integers(97, 110, size=plen))
    k = int(rng.integers(1, min(3, plen - 1) + 1))
    model = try_compile_approx(pattern, k)
    assert model is not None
    data = _gen_corpus(rng, "words", 24 << 10, [pattern.encode()])
    lines = data.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()
    want = {i for i, ln in enumerate(lines, 1) if line_matches(model, ln)}
    eng = GrepEngine(pattern, max_errors=k)
    got = set(eng.scan(data).matched_lines.tolist())
    assert got == want, f"seed={seed} pattern={pattern!r} k={k}"


@pytest.mark.parametrize("seed", range(3))
def test_fuzz_fdr_kernel_interpret(seed, monkeypatch):
    """A few interpret-mode Pallas FDR cases on small corpora: the real
    kernel code path (not the XLA fallback), exact after confirm."""
    from distributed_grep_tpu.ops import pallas_fdr, pallas_scan

    rng = np.random.default_rng(5000 + seed)
    pats = []
    for _ in range(int(rng.integers(40, 200))):
        k = int(rng.integers(2, 9))
        pats.append(bytes(int(b) for b in rng.integers(97, 123, size=k)))
    pats = sorted(set(pats))
    data = _gen_corpus(rng, "words", 6 << 10, pats[:6])
    monkeypatch.setattr(pallas_scan, "available", lambda: True)
    orig = pallas_fdr.fdr_scan_words
    monkeypatch.setattr(
        pallas_fdr, "fdr_scan_words",
        lambda arr, bank, dev_tables=None, interpret=None:
            orig(arr, bank, dev_tables=dev_tables, interpret=True),
    )
    eng = GrepEngine(patterns=[p.decode() for p in pats])
    assert eng.mode == "fdr"
    got = set(eng.scan(data).matched_lines.tolist())
    lines = data.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()
    want = {i for i, ln in enumerate(lines, 1) if any(p in ln for p in pats)}
    assert got == want, f"seed={seed} n={len(pats)}"


@pytest.mark.parametrize("seed", range(10))
def test_fuzz_literal_decomposition(seed):
    """Random alternations of literals / small class products: the engine
    routes these to the pattern-set engines (literal decomposition); output
    must stay exactly the re oracle's."""
    rng = np.random.default_rng(6000 + seed)
    n = int(rng.integers(2, 9))
    branches = []
    for _ in range(n):
        if rng.random() < 0.3:
            branches.append(
                _gen_literal(rng, int(rng.integers(1, 3)))
                + _gen_class(rng).replace(".", "[ab]").replace("[^x]", "[xy]")
                + _gen_literal(rng, int(rng.integers(1, 3)))
            )
        else:
            branches.append(_gen_literal(rng, int(rng.integers(2, 8))))
    pattern = "(" + "|".join(branches) + ")"
    rx = re.compile(pattern.encode("utf-8", "surrogateescape"))
    needle = _sample_match(rng, pattern)
    data = _gen_corpus(rng, "words" if seed % 2 else "binary", 48 << 10,
                       [needle] if needle else [])
    want = _oracle_lines(rx, data)
    from distributed_grep_tpu.models.dfa import enumerate_literal_set

    lits = enumerate_literal_set(pattern)
    for backend in ("device", "cpu"):
        eng = GrepEngine(pattern, backend=backend)
        if (backend == "device" and lits is not None and len(lits) >= 2
                and all(len(x) >= 2 for x in lits)):
            # the decomposition route must actually engage (non-vacuous;
            # the cpu backend renames every table mode to "native");
            # all-1-2-byte sets land on the exact pairset kernel (round 4)
            assert eng.mode in ("fdr", "dfa", "pairset"), (eng.mode, pattern)
        got = set(eng.scan(data).matched_lines.tolist())
        assert got == want, (
            f"seed={seed} backend={backend} mode={eng.mode} pattern={pattern!r}"
        )


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_word_line_modes(seed):
    """grep -w / -x through the apps vs a wrapped-re oracle."""
    from distributed_grep_tpu.apps import grep as grep_app
    from distributed_grep_tpu.apps import grep_tpu as grep_tpu_app

    rng = np.random.default_rng(7000 + seed)
    pattern = _gen_literal(rng, int(rng.integers(2, 6)))
    data = _gen_corpus(rng, "words", 32 << 10, [pattern.encode()])
    # guaranteed TRUE positives: a whole-line occurrence (-x hit) and a
    # space-delimited word occurrence (-w hit) — the random injections
    # above glue the needle mid-text, which -w/-x almost always reject,
    # so the family used to assert mostly-empty result sets (round-5
    # campaign finding: only 10/250 seeds drew any selected line)
    raw = re.sub(  # proper unescape: '\\X' -> 'X' (keeps literal '\\')
        rb"\\(.)", rb"\1", pattern.encode("utf-8", "surrogateescape")
    )
    data = data + b"\n" + raw + b"\nxx " + raw + b" yy\n"
    mode_kw = {"word_regexp": True} if seed % 2 else {"line_regexp": True}
    wrapped = grep_app.wrap_mode(
        pattern.encode("utf-8", "surrogateescape"),
        "word" if seed % 2 else "line",
    )
    rx = re.compile(wrapped)
    want = _oracle_lines(rx, data)
    for app in (grep_app, grep_tpu_app):
        kw = dict(mode_kw)
        if app is grep_tpu_app:
            kw["backend"] = "cpu"
        app.configure(pattern=pattern, **kw)
        got = {
            int(kv.key.rsplit("#", 1)[1].rstrip(")"))
            for kv in expand_records(app.map_fn("f", data))
        }
        assert got == want, f"seed={seed} app={app.__name__} pattern={pattern!r}"


ESCAPE_ATOMS = [
    r"\d", r"\w", r"\s", r"\D", r"\W", r"\S", r"\.", r"\*", r"\+", r"\?",
    r"\x41", r"\x7a", r"[\b]", r"[\d]", r"[\w\s]", r"[^\d]", r"[\101]",
    r"[\60-\71]", r"\t", r"\r", r"a", r"Z", r"0", r"-", r"_",
    r"\011", r"\0", r"[\011]", r"[\0a]",
]


@pytest.mark.parametrize("seed", range(12))
def test_fuzz_escape_semantics(seed):
    """Escape-heavy patterns vs re: every construct the parser ACCEPTS must
    match re's byte semantics exactly (the \\b-as-literal bug class); what
    it rejects must land on the exact re fallback."""
    from distributed_grep_tpu.models.dfa import RegexError, compile_dfa

    rng = np.random.default_rng(8000 + seed)
    pattern = "".join(
        ESCAPE_ATOMS[int(rng.integers(0, len(ESCAPE_ATOMS)))]
        for _ in range(int(rng.integers(2, 6)))
    )
    try:
        re.compile(pattern.encode())
    except re.error:
        pytest.skip("re itself rejects this combination")
    try:
        compile_dfa(pattern)
    except RegexError:
        # rejected constructs ride the re fallback — engine must agree too
        pass
    rx = re.compile(pattern.encode("utf-8", "surrogateescape"))
    data = _gen_corpus(rng, "binary", 24 << 10, [])
    want = _oracle_lines(rx, data)
    for backend in ("device", "cpu"):
        eng = GrepEngine(pattern, backend=backend)
        got = set(eng.scan(data).matched_lines.tolist())
        assert got == want, (
            f"seed={seed} backend={backend} mode={eng.mode} pattern={pattern!r}: "
            f"+{sorted(got - want)[:5]} -{sorted(want - got)[:5]}"
        )


# -------------------- interpret-mode Pallas kernels, every seed (round 3)

@pytest.mark.parametrize("seed", range(30))
def test_fuzz_pallas_kernels_every_seed(seed):
    """Every regex fuzz seed ALSO runs through interpret-mode Pallas for
    the mode the engine would really use on a TPU (shift-and coarse spans,
    NFA exact/filter, FDR filter; dfa/re modes have no kernel and skip).
    The engine's interpret=True flag drives the same gates a real TPU run
    takes (VERDICT r2 item 8).  Corpus is a smaller slice (interpret mode
    is ~1000x slower than compiled)."""
    rng = np.random.default_rng(1000 + seed)  # SAME stream as the XLA test
    pattern = _gen_pattern(rng)
    rx = re.compile(pattern.encode("utf-8", "surrogateescape"))
    needle = _sample_match(rng, pattern)
    kind = "words" if seed % 2 else "binary"
    data = _gen_corpus(rng, kind, 12 << 10, [needle] if needle else [])
    eng = GrepEngine(pattern, interpret=True, target_lanes=4096,
                     segment_bytes=1 << 20)
    if eng.mode not in ("shift_and", "nfa", "fdr"):
        pytest.skip(f"mode {eng.mode} has no Pallas kernel")
    want = _oracle_lines(rx, data)
    got = set(eng.scan(data).matched_lines.tolist())
    assert got == want, (
        f"seed={seed} mode={eng.mode} pattern={pattern!r}: "
        f"+{sorted(got - want)[:5]} -{sorted(want - got)[:5]}"
    )


@pytest.mark.parametrize("seed", range(10))
def test_fuzz_pallas_literal_sets_every_seed(seed):
    """Every literal-set fuzz seed through the interpret-mode FDR kernel
    (or shift-and for sets the decomposition collapses)."""
    rng = np.random.default_rng(3000 + seed)  # SAME stream as the XLA test
    n = int(rng.integers(2, 120))
    pats = []
    for _ in range(n):
        k = int(rng.integers(1, 9))
        pats.append(bytes(int(b) for b in rng.integers(1, 256, size=k)
                          ).replace(b"\n", b"*"))
    pats = sorted(set(pats))
    data = _gen_corpus(rng, "binary", 12 << 10, pats[:10])
    lines = data.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()
    want = {i for i, ln in enumerate(lines, 1) if any(p in ln for p in pats)}
    eng = GrepEngine(
        patterns=[p.decode("utf-8", "surrogateescape") for p in pats],
        interpret=True, target_lanes=4096, segment_bytes=1 << 20,
    )
    got = set(eng.scan(data).matched_lines.tolist())
    assert got == want, f"seed={seed} mode={eng.mode} n={n}"


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_pallas_approx_every_seed(seed):
    """Every approx fuzz seed through the interpret-mode approx kernel."""
    from distributed_grep_tpu.models.approx import line_matches, try_compile_approx

    rng = np.random.default_rng(4000 + seed)  # SAME stream as the XLA test
    plen = int(rng.integers(3, 12))
    pattern = "".join(chr(c) for c in rng.integers(97, 110, size=plen))
    k = int(rng.integers(1, min(3, plen - 1) + 1))
    model = try_compile_approx(pattern, k)
    assert model is not None
    data = _gen_corpus(rng, "words", 8 << 10, [pattern.encode()])
    lines = data.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()
    want = {i for i, ln in enumerate(lines, 1) if line_matches(model, ln)}
    eng = GrepEngine(pattern, max_errors=k, interpret=True,
                     target_lanes=4096, segment_bytes=1 << 20)
    got = set(eng.scan(data).matched_lines.tolist())
    assert got == want, f"seed={seed} pattern={pattern!r} k={k} mode={eng.mode}"


# ----------------------- bounded-repeat relaxation fuzz (round 3)

@pytest.mark.parametrize("seed", range(12))
def test_fuzz_bounded_repeat_relaxation(seed):
    """Patterns with large {m,n} repeats — the shapes that trigger the
    word-saving filter relaxation (and, past the 512-copy cap, the
    DFA-less rescue).  Engine output must stay exactly re's on both
    backends and on the interpret-Pallas path."""
    rng = np.random.default_rng(16000 + seed)
    cls = ["[ab]", "[a-f]", "[a-z0-9]", "x", "[^q]"][int(rng.integers(0, 5))]
    lo = int(rng.integers(0, 12))
    hi = lo + int(rng.integers(8, 120)) if seed % 3 else lo + int(rng.integers(300, 700))
    head = _gen_literal(rng, int(rng.integers(1, 4)))
    tail = _gen_literal(rng, int(rng.integers(1, 4)))
    pattern = f"{head}{cls}{{{lo},{hi}}}{tail}"
    rx = re.compile(pattern.encode("utf-8", "surrogateescape"))
    # corpus: random lines plus injected exact matches, over-bound runs
    # (false candidates for the relaxed filter), and under-bound runs
    inner = {"[ab]": b"ab", "[a-f]": b"cd", "[a-z0-9]": b"m3",
             "x": b"xx", "[^q]": b"zx"}[cls]
    fill = (inner * ((hi + 2) // 2))
    h = head.encode().replace(b"\\", b"")
    t = tail.encode().replace(b"\\", b"")
    injections = [
        h + fill[: max(lo, 1)] + t,              # near the low bound
        h + fill[: (lo + min(hi, lo + 20)) // 2] + t,  # mid
    ]
    if hi + 40 <= 256:  # _gen_corpus injects near line ends; keep it short
        injections.append(h + fill[: hi + 40] + t)  # over the bound
    data = _gen_corpus(rng, "words", 24 << 10, injections)
    want = _oracle_lines(rx, data)
    for kw in ({"backend": "device"}, {"backend": "cpu"}, {"interpret": True}):
        eng = GrepEngine(pattern, **kw)
        got = set(eng.scan(data).matched_lines.tolist())
        assert got == want, (
            f"seed={seed} {kw} mode={eng.mode} filt={eng._nfa_filter} "
            f"pattern={pattern!r}: +{sorted(got - want)[:4]} "
            f"-{sorted(want - got)[:4]}"
        )


@pytest.mark.parametrize("seed", range(15))
def test_fuzz_filter_superset_invariant(seed):
    """Property: the relaxed scan model's match offsets are a SUPERSET of
    the exact automaton's, on random repeat-bearing patterns (the
    soundness invariant behind the cand_words confirm path)."""
    from distributed_grep_tpu.models import nfa as nfa_mod

    rng = np.random.default_rng(17000 + seed)
    # unanchored body (an appended repeat would make a drawn anchor
    # mid-pattern) + a bounded repeat so relaxation has work to do
    k = int(rng.integers(1, 4))
    pattern = "".join(_gen_piece(rng, 1) for _ in range(k))
    pattern += ["a{2,40}", "[ab]{3,50}b", "(ab){2,30}"][int(rng.integers(0, 3))]
    from distributed_grep_tpu.models.dfa import RegexError

    try:
        exact = nfa_mod.try_compile_glushkov(pattern)
        model, is_filter = nfa_mod.compile_scan_model(pattern)
    except RegexError:
        pytest.skip("appended repeat made a drawn anchor mid-pattern")
    if exact is None or model is None or not is_filter:
        pytest.skip("no exact/filter pair for this draw")
    data = _gen_corpus(rng, "words", 16 << 10, [])
    ex = set(nfa_mod.scan_reference(exact, data).tolist())
    fi = set(nfa_mod.scan_reference(model, data).tolist())
    assert ex <= fi, f"seed={seed} pattern={pattern!r} missing {sorted(ex - fi)[:5]}"


@pytest.mark.parametrize("seed", range(15))
def test_fuzz_dollar_anchor_device_filter(seed):
    """Round-5 family: '$'-anchored and over-cap patterns ride the device
    NFA filter (compile_device_filter) with host-confirmed lines — fuzzed
    vs the re oracle on both backends, with needles injected at line ENDS
    (the position '$' actually tests) and as mid-line decoys."""
    rng = np.random.default_rng(9000 + seed)
    variant = seed % 5
    if variant == 3:  # over-cap literal (prefix-truncated filter)
        pattern = _gen_literal(rng, int(rng.integers(130, 240)))
    elif variant == 4:  # over-cap literal + '$'
        pattern = _gen_literal(rng, int(rng.integers(130, 200))) + "$"
    else:
        base = _gen_pattern(rng).rstrip("$").lstrip("^")
        if not base:
            base = _gen_literal(rng, 3)
        pattern = {
            0: lambda: f"(?:{base})$",
            1: lambda: f"^(?:{base})$",
            2: lambda: f"(?:{base})$|{_gen_literal(rng, 2)}",
        }[variant]()
    try:
        rx = re.compile(pattern.encode("utf-8", "surrogateescape"))
    except re.error:
        pytest.skip("generator drew an invalid wrapper combination")
    try:
        # the anchor-stripped sampling pattern may be syntactically
        # mangled (e.g. '\$' losing its '$') — sample opportunistically
        needle = _sample_match(rng, pattern.replace("$", "").replace("^", "")
                               if variant < 3 else pattern.rstrip("$"))
    except re.error:
        needle = None
    data = _gen_corpus(rng, "words" if seed % 2 else "binary", 48 << 10, [])
    if needle:
        nd = needle.replace(b"\n", b"x")
        # end-of-line injections (true '$' hits) + mid-line decoys
        lines = data.split(b"\n")
        for _ in range(4):
            i = int(rng.integers(0, len(lines)))
            lines[i] = lines[i] + nd
        for _ in range(4):
            i = int(rng.integers(0, len(lines)))
            lines[i] = nd + b" trailing decoy"
        data = b"\n".join(lines)
    want = _oracle_lines(rx, data)
    for backend in ("device", "cpu"):
        eng = GrepEngine(pattern, backend=backend)
        got = set(eng.scan(data).matched_lines.tolist())
        assert got == want, (
            f"seed={seed} variant={variant} backend={backend} "
            f"mode={eng.mode} pattern={pattern!r}: "
            f"+{sorted(got - want)[:5]} -{sorted(want - got)[:5]}"
        )


@pytest.mark.parametrize("seed", range(15))
def test_fuzz_mid_anchor_subset(seed):
    """Round-5 family: MID-pattern anchors — '(^a|b)c', 'a(b$|c)' — are
    in the subset compiler now (models/dfa.py ls_eps/eol_eps edges), so
    these patterns scan linearly on the DFA/native path and ride the
    anchor-stripped device filter (models/nfa._strip_anchors) instead of
    falling back to Python re.  Fuzzed vs the re oracle on both
    backends, with line-start/line-end needle injections (the positions
    the anchors actually gate) plus mid-line decoys the confirm must
    reject."""
    rng = np.random.default_rng(11_000 + seed)
    a = _gen_literal(rng, int(rng.integers(2, 5)))
    b = _gen_literal(rng, int(rng.integers(2, 5)))
    c = _gen_literal(rng, int(rng.integers(1, 4)))
    variant = seed % 5
    pattern = {
        0: lambda: f"(^{a}|{b}){c}",
        1: lambda: f"{a}({b}$|{c})",
        2: lambda: f"(^{a}|{b}$|{c})",
        3: lambda: f"(^{a}|{b})({c}$|{a})",
        4: lambda: f"{a}^{b}",  # never matches — per-line semantics
    }[variant]()
    rx = re.compile(pattern.encode())
    data = _gen_corpus(rng, "words" if seed % 2 else "binary", 48 << 10, [])
    lines = data.split(b"\n")
    for _ in range(4):  # line-START hits/decoys for the '^' branches
        i = int(rng.integers(0, len(lines)))
        lines[i] = (a + c).encode() + b" " + lines[i]
    for _ in range(4):  # line-END hits for the '$' branches
        i = int(rng.integers(0, len(lines)))
        lines[i] = lines[i] + b" " + (a + b).encode()
    for _ in range(4):  # mid-line decoys: same bytes, anchors must veto
        i = int(rng.integers(0, len(lines)))
        lines[i] = lines[i][:1] + (a + c + a + b).encode() + lines[i][1:]
    data = b"\n".join(lines)
    want = _oracle_lines(rx, data)
    for backend in ("device", "cpu"):
        eng = GrepEngine(pattern, backend=backend)
        got = set(eng.scan(data).matched_lines.tolist())
        assert eng.mode != "re", (
            f"seed={seed} pattern={pattern!r} fell back to Python re"
        )
        assert got == want, (
            f"seed={seed} variant={variant} backend={backend} "
            f"mode={eng.mode} pattern={pattern!r}: "
            f"+{sorted(got - want)[:5]} -{sorted(want - got)[:5]}"
        )


@pytest.mark.parametrize("seed", range(16))
def test_fuzz_word_boundary_filter(seed):
    """Round-5 family: \\b/\\B word boundaries — stripped for the device
    filter (superset at the same end offsets), candidate lines
    re-confirmed with the original semantics.  Injections place needles
    with word and non-word neighbors on both sides so the confirm has
    true positives AND boundary-violating decoys on every draw."""
    rng = np.random.default_rng(12_000 + seed)
    w = _gen_literal(rng, int(rng.integers(3, 7)))
    variant = seed % 4
    pattern = {
        0: lambda: rf"\b{w}\b",
        1: lambda: rf"\b{w}",
        2: lambda: rf"{w}\B",
        3: lambda: rf"\B{w}\b",
    }[variant]()
    rx = re.compile(pattern.encode())
    # corpus kind decorrelated from the variant cycle (seed % 4) so every
    # variant runs on BOTH corpus kinds across the seed range
    data = _gen_corpus(rng, "words" if (seed // 4) % 2 else "binary",
                       48 << 10, [])
    lines = data.split(b"\n")
    wb = w.encode()
    for dec in (b" %s " % wb, b"x%s" % wb, b"%sx" % wb, b"9%s_" % wb,
                b".%s." % wb, wb):
        for _ in range(3):
            i = int(rng.integers(0, len(lines)))
            lines[i] = lines[i] + b" " + dec
    data = b"\n".join(lines)
    want = _oracle_lines(rx, data)
    for backend in ("device", "cpu"):
        eng = GrepEngine(pattern, backend=backend)
        got = set(eng.scan(data).matched_lines.tolist())
        if backend == "device":
            assert eng.mode == "nfa", (
                f"seed={seed} pattern={pattern!r} missed the filter rescue"
            )
        assert got == want, (
            f"seed={seed} variant={variant} backend={backend} "
            f"mode={eng.mode} pattern={pattern!r}: "
            f"+{sorted(got - want)[:5]} -{sorted(want - got)[:5]}"
        )


@pytest.mark.parametrize("seed", range(12))
def test_fuzz_posix_classes(seed):
    """Round-5 family: POSIX bracket classes in random combination with
    literals/ranges/negation, on both backends.  Oracle = Python re of
    the EXPANDED pattern (models/dfa.expand_posix_classes — itself
    pinned against GNU by test_fuzz_cli_posix_classes and the edge-shape
    unit tests)."""
    from distributed_grep_tpu.models.dfa import expand_posix_classes

    rng = np.random.default_rng(13_000 + seed)
    names = ["digit", "alpha", "upper", "lower", "alnum", "punct",
             "space", "xdigit", "blank", "graph"]

    def piece():
        nm = names[int(rng.integers(0, len(names)))]
        r = rng.random()
        if r < 0.4:
            body = f"[:{nm}:]"
        elif r < 0.6:
            body = f"[:{nm}:]{_gen_literal(rng, 1)}"
        elif r < 0.8:
            body = f"^[:{nm}:]"  # negated (falls through to repetition)
        else:
            body = f"[:{nm}:]_-"
        rep = {0: "", 1: "+", 2: "?", 3: "{1,3}"}[int(rng.integers(0, 4))]
        return f"[{body}]{rep}"

    pattern = _gen_literal(rng, int(rng.integers(0, 3))) + "".join(
        piece() for _ in range(int(rng.integers(1, 4)))
    )
    rx = re.compile(expand_posix_classes(pattern).encode())
    data = _gen_corpus(rng, "words" if (seed // 4) % 2 else "binary",
                       48 << 10, [])
    want = _oracle_lines(rx, data)
    for backend in ("device", "cpu"):
        eng = GrepEngine(pattern, backend=backend)
        got = set(eng.scan(data).matched_lines.tolist())
        assert got == want, (
            f"seed={seed} backend={backend} mode={eng.mode} "
            f"pattern={pattern!r}: "
            f"+{sorted(got - want)[:5]} -{sorted(want - got)[:5]}"
        )

"""CLI-level differential fuzz vs the system GNU grep binary.

The engine-level fuzz (test_fuzz_recall.py) pins line SELECTION against a
re oracle; this suite pins the whole CLI surface — flag parsing, per-file
prefixes, -m capping, -o match extraction, -b byte offsets, exit codes —
against real GNU grep over random corpora and flag combos.  Every failure
reproduces from the printed seed.

Output normalization: our format is `<path> (line number #N) [(byte #K)]
<text>`; GNU's is `path:N[:K]:text` (with -n/-b).  Both sides parse into
tuples before comparison.
"""

from __future__ import annotations

import re
import shutil
import subprocess

import numpy as np
import pytest

from distributed_grep_tpu.__main__ import main

GNU_GREP = shutil.which("grep")
pytestmark = pytest.mark.skipif(GNU_GREP is None, reason="no system grep")

WORDS = ["the", "fox", "Fox", "hello", "foo", "foobar", "barfoo", "x", "dog",
         "a.b", "end", "foofoo"]

OUR_LINE = re.compile(r"^(?P<path>.*) \(line number #(?P<ln>\d+)\)"
                      r"( \(byte #(?P<boff>\d+)\))? (?P<text>.*)$")


def _make_files(rng, tmp_path, n_files=2):
    paths = []
    for fi in range(n_files):
        lines = []
        for _ in range(int(rng.integers(30, 120))):
            k = int(rng.integers(0, 8))
            lines.append(" ".join(
                WORDS[int(i)] for i in rng.integers(0, len(WORDS), k)
            ))
        p = tmp_path / f"f{fi}.txt"
        p.write_text("\n".join(lines) + "\n")
        paths.append(str(p))
    return paths


def _run_ours(argv, capsys):
    rc = main(argv)
    out = capsys.readouterr().out
    return rc, [l for l in out.split("\n") if l]


def _run_gnu(argv):
    p = subprocess.run([GNU_GREP, *argv], capture_output=True, text=True,
                       env={"LC_ALL": "C"})
    return p.returncode, [l for l in p.stdout.split("\n") if l]


def _parse_ours(lines, with_boff=False):
    out = []
    for l in lines:
        m = OUR_LINE.match(l)
        assert m, f"unparseable CLI line: {l!r}"
        rec = [m.group("path"), int(m.group("ln")), m.group("text")]
        if with_boff:
            rec.insert(2, int(m.group("boff")))
        out.append(tuple(rec))
    return out


def _parse_gnu(lines, paths, n_fields):
    """Split GNU `path:field:...:text` lines.  Path may contain ':' so
    match against the known path list first."""
    out = []
    for l in lines:
        for p in paths:
            if l.startswith(p + ":"):
                rest = l[len(p) + 1:]
                break
        else:
            raise AssertionError(f"no known path prefix: {l!r}")
        parts = rest.split(":", n_fields)
        out.append((p, *[int(x) for x in parts[:-1]], parts[-1]))
    return out


FLAG_SETS = [
    ([], []),
    (["-v"], ["-v"]),
    (["-w"], ["-w"]),
    (["-x"], ["-x"]),
    (["-i"], ["-i"]),
    (["-i", "-v"], ["-i", "-v"]),
    (["-m", "2"], ["-m", "2"]),
]


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_cli_selection_flags(seed, tmp_path, capsys):
    """Default-print selection across flag combos: (path, line, text)
    streams must match GNU grep -n exactly, including order."""
    rng = np.random.default_rng(11000 + seed)
    paths = _make_files(rng, tmp_path)
    pattern = WORDS[int(rng.integers(0, len(WORDS)))]
    ours_f, gnu_f = FLAG_SETS[seed % len(FLAG_SETS)]
    rc, out = _run_ours(["grep", pattern, *paths, *ours_f], capsys)
    grc, gout = _run_gnu(["-n", *gnu_f, pattern, *paths])
    got = _parse_ours(out)
    want = _parse_gnu(gout, paths, 2)
    assert got == want, (
        f"seed={seed} flags={ours_f} pattern={pattern!r}: "
        f"ours={got[:3]} gnu={want[:3]}"
    )
    assert rc == grc, f"seed={seed} flags={ours_f}: rc {rc} vs {grc}"


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_cli_count_and_list(seed, tmp_path, capsys):
    rng = np.random.default_rng(12000 + seed)
    paths = _make_files(rng, tmp_path, n_files=3)
    pattern = WORDS[int(rng.integers(0, len(WORDS)))]
    rc, out = _run_ours(["grep", pattern, *paths, "-c"], capsys)
    grc, gout = _run_gnu(["-c", pattern, *paths])
    assert out == gout, f"seed={seed} -c: {out} vs {gout}"
    assert rc == grc
    for flag in ("-l", "-L"):
        rc, out = _run_ours(["grep", pattern, *paths, flag], capsys)
        grc, gout = _run_gnu([flag, pattern, *paths])
        assert out == gout, f"seed={seed} {flag}: {out} vs {gout}"
        assert rc == grc, f"seed={seed} {flag}: rc {rc} vs {grc}"
    # count_only modifier combos (-v/-i/-m/-w reshape the selected-line
    # set BEFORE the per-file count record is emitted; a 120-seed sweep
    # of these ran clean 2026-07-31).  All combos every seed — a drawn
    # subset under FIXED seeds would deterministically never run some
    # (round-4 review finding), and each run is milliseconds
    for flags in (["-c", "-v"], ["-c", "-i"], ["-c", "-m", "2"],
                  ["-c", "-w"], ["-l", "-v"], ["-q"], ["-q", "-v"]):
        rc, out = _run_ours(["grep", pattern, *paths, *flags], capsys)
        grc, gout = _run_gnu([*flags, pattern, *paths])
        assert out == gout, f"seed={seed} {flags}: {out} vs {gout}"
        assert rc == grc, f"seed={seed} {flags}: rc {rc} vs {grc}"


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_cli_only_matching(seed, tmp_path, capsys):
    """-o: per-match extraction (multiset + order per line) vs grep -on."""
    rng = np.random.default_rng(13000 + seed)
    paths = _make_files(rng, tmp_path)
    pattern = ["foo", "fox", "o", "foofoo"][seed % 4]
    rc, out = _run_ours(["grep", pattern, *paths, "-o"], capsys)
    grc, gout = _run_gnu(["-o", "-n", pattern, *paths])
    got = _parse_ours(out)
    want = _parse_gnu(gout, paths, 2)
    assert got == want, f"seed={seed} -o pattern={pattern!r}"
    assert rc == grc


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_cli_byte_offsets(seed, tmp_path, capsys):
    """-b (line-start offsets) and -o -b (match offsets) vs GNU."""
    rng = np.random.default_rng(14000 + seed)
    paths = _make_files(rng, tmp_path)
    pattern = WORDS[int(rng.integers(0, len(WORDS)))]
    rc, out = _run_ours(["grep", pattern, *paths, "-b"], capsys)
    grc, gout = _run_gnu(["-b", "-n", pattern, *paths])
    got = _parse_ours(out, with_boff=True)
    want = [(p, ln, b, t) for p, ln, b, t in _parse_gnu(gout, paths, 3)]
    assert got == want, f"seed={seed} -b pattern={pattern!r}"
    assert rc == grc

    rc, out = _run_ours(["grep", pattern, *paths, "-o", "-b"], capsys)
    grc, gout = _run_gnu(["-o", "-b", "-n", pattern, *paths])
    got = _parse_ours(out, with_boff=True)
    want = _parse_gnu(gout, paths, 3)
    assert got == want, f"seed={seed} -o -b pattern={pattern!r}"


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_cli_ere_patterns(seed, tmp_path, capsys):
    """Random simple ERE alternations through -E vs GNU grep -E -n."""
    rng = np.random.default_rng(15000 + seed)
    paths = _make_files(rng, tmp_path)
    k = int(rng.integers(2, 5))
    pattern = "|".join(WORDS[int(i)] for i in rng.integers(0, len(WORDS), k))
    rc, out = _run_ours(["grep", "-E", pattern, *paths], capsys)
    grc, gout = _run_gnu(["-E", "-n", pattern, *paths])
    got = _parse_ours(out)
    want = _parse_gnu(gout, paths, 2)
    assert got == want, f"seed={seed} -E pattern={pattern!r}"
    assert rc == grc


def test_byte_offset_with_context_matches_gnu(tmp_path, capsys):
    """-b with -A/-C (round-3 polish: was rejected): line-start offsets on
    matches AND context lines, ':' vs '-' separators mirrored."""
    p = tmp_path / "c.txt"
    p.write_text("foo\nbar\nfoo2\nbaz\nqux\nfoo3\n")
    rc, out = _run_ours(["grep", "foo", str(p), "-b", "-A", "1"], capsys)
    grc, gout = _run_gnu(["-b", "-n", "-A", "1", "foo", str(p)])
    ours = []
    for l in out:
        if l == "--":
            ours.append("--")
            continue
        m = re.match(r"^.* \(line number #(\d+)\)(-?) \(byte #(\d+)\)-? (.*)$", l)
        assert m, l
        ours.append((int(m.group(1)), m.group(2) == "-", int(m.group(3)),
                     m.group(4)))
    want = []
    for l in gout:
        if l == "--":
            want.append("--")
            continue
        m = re.match(r"^(\d+)([:-])(\d+)[:-](.*)$", l)
        assert m, l
        want.append((int(m.group(1)), m.group(2) == "-", int(m.group(3)),
                     m.group(4)))
    assert ours == want
    assert rc == grc


def test_include_applies_without_recursive(tmp_path, capsys):
    """--include filters explicitly listed files like GNU grep (round-3
    polish: it used to be silently ignored without -r)."""
    c = tmp_path / "a.c"
    c.write_text("foo\n")
    t = tmp_path / "a.txt"
    t.write_text("foo\n")
    rc, out = _run_ours(
        ["grep", "foo", str(c), str(t), "--include", "*.c"], capsys)
    grc, gout = _run_gnu(["-n", "--include", "*.c", "foo", str(c), str(t)])
    assert _parse_ours(out) == _parse_gnu(gout, [str(c)], 2)
    assert rc == grc == 0
    # everything filtered out -> no matches, exit 1 like GNU
    rc, out = _run_ours(
        ["grep", "foo", str(t), "--include", "*.c"], capsys)
    grc, gout = _run_gnu(["--include", "*.c", "foo", str(t)])
    assert out == gout == []
    assert rc == grc == 1
    # same under -r (explicit file filtered): silent exit 1, not error 2
    rc, out = _run_ours(
        ["grep", "-r", "foo", str(t), "--include", "*.c"], capsys)
    grc, gout = _run_gnu(["-r", "--include", "*.c", "foo", str(t)])
    assert out == gout == []
    assert rc == grc == 1


def test_recursive_skips_unreadable_files(tmp_path, capsys):
    """-r over a tree with an unreadable file: skip it with a message and
    exit 2, like explicit unreadable arguments (ADVICE r2)."""
    import os

    d = tmp_path / "tree"
    d.mkdir()
    (d / "ok.txt").write_text("needle here\n")
    blocked = d / "blocked.txt"
    blocked.write_text("needle too\n")
    os.chmod(blocked, 0)
    if os.access(str(blocked), os.R_OK):
        pytest.skip("running as privileged user; chmod 0 still readable")
    try:
        rc = main(["grep", "-r", "needle", str(d)])
        cap = capsys.readouterr()
        out = [l for l in cap.out.split("\n") if l]
        assert rc == 2  # file errors force exit 2 (matches still printed)
        assert len(out) == 1 and "ok.txt" in out[0]
        assert "cannot read" in cap.err and "blocked.txt" in cap.err
        # -s suppresses the message but keeps the exit code
        rc2 = main(["grep", "-r", "-s", "needle", str(d)])
        cap2 = capsys.readouterr()
        assert rc2 == 2 and "cannot read" not in cap2.err
    finally:
        os.chmod(blocked, 0o644)


def test_exclude_glob_matches_gnu(tmp_path, capsys):
    """--exclude skips basename-matching files, beats --include, applies to
    explicit files — all probed GNU 3.8 semantics."""
    c = tmp_path / "a.c"
    c.write_text("foo\n")
    t = tmp_path / "a.txt"
    t.write_text("foo\n")
    rc, out = _run_ours(
        ["grep", "foo", str(c), str(t), "--exclude", "*.txt"], capsys)
    grc, gout = _run_gnu(["-n", "--exclude", "*.txt", "foo", str(c), str(t)])
    assert _parse_ours(out) == _parse_gnu(gout, [str(c)], 2)
    assert rc == grc == 0
    # exclude beats include
    rc, out = _run_ours(
        ["grep", "-r", "foo", str(tmp_path), "--include", "*.c",
         "--exclude", "a*"], capsys)
    grc, gout = _run_gnu(["-r", "--include", "*.c", "--exclude", "a*",
                          "foo", str(tmp_path)])
    assert out == gout == []
    assert rc == grc == 1


def test_exclude_dir_slash_glob_matches_gnu(tmp_path, capsys):
    """--exclude-dir globs containing '/' never match (GNU compares
    directory BASENAMES, which contain no '/'): probed grep 3.8 excludes
    nothing for 'build/sub', './build' and '*/sub' alike.  Pinned
    differentially so a GNU behavior change would surface here
    (round-4 ADVICE follow-up)."""
    (tmp_path / "build" / "sub").mkdir(parents=True)
    (tmp_path / "other" / "build").mkdir(parents=True)
    (tmp_path / "build" / "sub" / "f.txt").write_text("foo\n")
    (tmp_path / "other" / "build" / "g.txt").write_text("foo\n")
    (tmp_path / "top.txt").write_text("foo\n")
    for glob in ("build/sub", "./build", "*/sub"):
        rc, out = _run_ours(
            ["grep", "-r", "--exclude-dir", glob, "-l", "foo",
             str(tmp_path)], capsys)
        grc, gout = _run_gnu(
            ["-r", "--exclude-dir", glob, "-l", "foo", str(tmp_path)])
        assert rc == grc == 0, glob
        assert sorted(out) == sorted(gout), glob
    # control: the plain basename glob DOES prune both build dirs
    rc, out = _run_ours(
        ["grep", "-r", "--exclude-dir", "build", "-l", "foo",
         str(tmp_path)], capsys)
    grc, gout = _run_gnu(
        ["-r", "--exclude-dir", "build", "-l", "foo", str(tmp_path)])
    assert rc == grc == 0
    assert sorted(out) == sorted(gout) == [str(tmp_path / "top.txt")]


def test_include_exclude_order_semantics(tmp_path, capsys):
    """GNU treats --include/--exclude as one ordered list: the LAST
    matching glob decides, and unmatched files default to included iff the
    list starts with an exclude — probed grep 3.8 semantics."""
    c = tmp_path / "a.c"
    c.write_text("foo\n")
    t = tmp_path / "a.txt"
    t.write_text("foo\n")
    cases = [
        ["--exclude", "*.txt", "--include", "*.txt"],  # include wins on .txt;
                                                       # unmatched .c default-in
        ["--include", "*.txt", "--exclude", "*.txt"],  # exclude wins; .c
                                                       # default-out
        ["--exclude", "*.c", "--include", "a.*"],      # both match include last
        ["--include", "a.*", "--exclude", "*.c"],      # .c excluded last
    ]
    for flags in cases:
        rc, out = _run_ours(["grep", "-r", "foo", str(tmp_path), *flags],
                            capsys)
        grc, gout = _run_gnu(["-r", "-n", *flags, "foo", str(tmp_path)])
        assert sorted(out) == sorted(
            f"{p} (line number #{ln}) {txt}"
            for p, ln, txt in _parse_gnu(gout, [str(c), str(t)], 2)
        ), flags
        assert rc == grc, flags


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_cli_short_pattern_sets(seed, tmp_path, capsys):
    """grep -f with 1-2-char literal sets (the round-4 pairset family)
    differential vs GNU grep -F -f: stream, order, counts, exit codes."""
    rng = np.random.default_rng(15000 + seed)
    paths = _make_files(rng, tmp_path)
    alphabet = "abcdefghijklmnopqrstuvwxyz"
    pats = sorted({
        "".join(alphabet[int(i)] for i in
                rng.integers(0, len(alphabet), int(rng.integers(1, 3))))
        for _ in range(int(rng.integers(2, 10)))
    })
    pf = tmp_path / "pats.txt"
    pf.write_text("\n".join(pats) + "\n")
    flags = ["-i"] if seed % 2 else []
    rc, out = _run_ours(["grep", "-f", str(pf), *paths, *flags], capsys)
    grc, gout = _run_gnu(["-n", "-F", "-f", str(pf), *flags, *paths])
    assert _parse_ours(out) == _parse_gnu(gout, paths, 2), \
        f"seed={seed} pats={pats}"
    assert rc == grc


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_cli_posix_classes(seed, tmp_path, capsys):
    """POSIX bracket classes ([[:digit:]] etc., round 5) vs GNU grep -E:
    Python re cannot oracle these (it misparses [:name:] as member
    chars), so GNU itself is the oracle — selection, -c, and -w across
    positive and negated classes, plus the unknown-name error."""
    rng = np.random.default_rng(17_000 + seed)
    paths = _make_files(rng, tmp_path)
    cls = ["digit", "alpha", "upper", "lower", "alnum", "punct",
           "space", "xdigit"][int(rng.integers(0, 8))]
    pattern = {
        0: lambda: f"[[:{cls}:]]+",
        1: lambda: f"[^[:{cls}:]]",
        2: lambda: f"[[:{cls}:]_-]+",
        3: lambda: f"x[[:{cls}:]]",
    }[seed % 4]()
    rc, out = _run_ours(["grep", "-E", pattern, *paths], capsys)
    grc, gout = _run_gnu(["-E", "-n", pattern, *paths])
    got = _parse_ours(out)
    want = _parse_gnu(gout, paths, 2)
    assert got == want, f"seed={seed} pattern={pattern!r}"
    assert rc == grc
    # -w wraps the confirm regex around the expanded class
    rc, out = _run_ours(["grep", "-E", "-c", "-w", pattern, *paths], capsys)
    grc, gout = _run_gnu(["-E", "-c", "-w", pattern, *paths])
    assert sorted(out) == sorted(gout), f"seed={seed} -c -w {pattern!r}"
    assert rc == grc


def test_cli_posix_class_unknown_name_errors(tmp_path, capsys):
    """[[:junk:]] is an invalid-pattern error (exit 2), like GNU."""
    f = tmp_path / "a.txt"
    f.write_text("abc\n")
    rc, _ = _run_ours(["grep", "-E", "[[:junk:]]", str(f)], capsys)
    grc, _ = _run_gnu(["-E", "[[:junk:]]", str(f)])
    assert rc == grc == 2


def test_recursive_symlink_semantics_match_gnu(tmp_path, capsys):
    """-r skips symlinked files and dirs met during descent; -R follows
    both (with directory-cycle pruning); a command-line symlink dir is
    followed by both — GNU-verified semantics.  Compared on RESOLVED
    (path, line) sets: our display normalizes to absolute resolved
    paths, GNU prints traversal paths.  The set comparison alone would
    mask per-route duplicates (two routes to one file resolve to
    identical lines) — the multiset check below closes that hole: every
    real file is scanned and printed exactly once under -R."""
    import os
    from pathlib import Path

    d = tmp_path / "d"
    (d / "sub").mkdir(parents=True)
    (d / "a.txt").write_text("hit one\n")
    (tmp_path / "real.txt").write_text("hit two\n")
    os.symlink("../real.txt", d / "link.txt")
    other = tmp_path / "other"
    other.mkdir()
    (other / "b.txt").write_text("hit three\n")
    os.symlink("../other", d / "linkdir")
    os.symlink(".", d / "sub" / "self")  # cycle: -R must terminate
    # a file reachable BOTH directly and via a sibling file symlink: GNU
    # prints each route under its traversal path; our resolved display
    # must print the real file once (ADVICE round-5 medium)
    os.symlink("a.txt", d / "alias.txt")
    # ...but HARD links are distinct files at distinct resolved paths:
    # both must print, like GNU (dedup is per resolved path, not inode)
    os.link(d / "a.txt", d / "hard.txt")

    def resolved(pairs):
        return {(str(Path(p).resolve()), ln) for p, ln, _ in pairs}

    for flag in ("-r", "-R"):
        rc, out = _run_ours(["grep", flag, "hit", str(d)], capsys)
        grc, gout = _run_gnu([flag, "-n", "hit", str(d)])
        parsed = _parse_ours(out)
        # no duplicate (path, line) records — a resolved-set comparison
        # cannot see these, so assert on the multiset directly
        keys = [(str(Path(p).resolve()), ln) for p, ln, _ in parsed]
        assert len(keys) == len(set(keys)), f"{flag}: duplicate output lines"
        got = resolved(parsed)
        want = set()
        for line in gout:  # tmp_path contains no ':', split is safe
            p, ln, _text = line.split(":", 2)
            want.add((str(Path(p).resolve()), int(ln)))
        assert got == want, f"{flag}: {got ^ want}"
        assert rc == grc == 0


def test_dereference_recursive_dangling_symlink_exits_2(tmp_path, capsys):
    """-R reports dangling symlinks met during descent and exits 2, like
    GNU; plain -r skips them silently (they're symlinked files)."""
    import os

    d = tmp_path / "d"
    d.mkdir()
    (d / "a.txt").write_text("hit\n")
    os.symlink("no-such-target", d / "dangle")
    # -c, not -q: GNU -q exits 0 the moment any match exists, even when
    # an error was also detected (and so do we — probed both)
    rc, _ = _run_ours(["grep", "-R", "-c", "hit", str(d)], capsys)
    grc, _ = _run_gnu(["-R", "-c", "hit", str(d)])
    assert rc == grc == 2
    rc, _ = _run_ours(["grep", "-r", "-c", "hit", str(d)], capsys)
    grc, _ = _run_gnu(["-r", "-c", "hit", str(d)])
    assert rc == grc == 0

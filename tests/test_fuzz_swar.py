"""SWAR packed shift-and kernel: bit-exactness fuzz family (round 6).

The packed kernel (ops/pallas_scan.swar_shift_and_scan_words — 4 stripes
per u32 lane element, one byte-plane automaton per stripe) claims BIT-EXACT
candidate words vs the unpacked coarse kernel, via the exact packed
zero-byte class detect (not classic Mycroft, whose borrows over-report).
This family pins that claim the fuzz-harness way: random eligible
patterns x random corpora (binary corpora included — bytes 0x00/0x7F/
0x80/0xFF sit exactly on the detect's borrow/sign borders), comparing

  1. kernel words: packed byte-plane flags == unpacked coarse word flags
     per stripe, bit for bit;
  2. engine lines: final matched_lines with DGREP_SWAR=1 == DGREP_SWAR=0
     (the whole route: packed layout choice, span decode, line confirm).

Failures reproduce from the printed seed.  Standalone:

    python -m pytest tests/test_fuzz_swar.py -m swar -q

Interpret mode is slow, so draws are few and small; the kernel-level
check runs at the minimum packed layout (16384 lanes x 512 chunk = 8 MB).
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from distributed_grep_tpu.models.shift_and import (
    filtered_for_device,
    swar_values,
    try_compile_shift_and,
)
from distributed_grep_tpu.ops import layout as layout_mod
from distributed_grep_tpu.ops import pallas_scan

pytestmark = pytest.mark.swar

ALPHABET = "etaoin srhld.u01"  # common prose bytes + space/digits/punct


def _gen_pattern(rng) -> tuple[str, bool]:
    n = int(rng.integers(1, 9))  # SWAR_MAX_SYMBOLS = 8
    pat = "".join(ALPHABET[int(rng.integers(0, len(ALPHABET)))]
                  for _ in range(n)).replace(".", "x")
    return pat, bool(rng.integers(0, 2))


def _corpus(rng, n: int, binary: bool, needles: list[bytes]) -> bytes:
    if binary:
        data = rng.integers(0, 256, size=n, dtype=np.uint8)
    else:
        data = rng.integers(32, 127, size=n, dtype=np.uint8)
    data[rng.integers(0, n, size=max(1, n // 80))] = 0x0A
    for lit in needles:
        nd = np.frombuffer(lit, np.uint8)
        if nd.size == 0 or nd.size + 1 >= n:
            continue
        for p in rng.integers(0, n - nd.size - 1, size=200):
            data[p : p + nd.size] = nd
    return data.tobytes()


def _stripe_flags_unpacked(arr, model, lay):
    wu = np.asarray(pallas_scan.shift_and_scan_words(
        arr, model, interpret=True, coarse=True
    ))
    return wu.reshape(lay.chunk // 32, lay.lanes) != 0


def _stripe_flags_packed(arr, model, lay):
    wp = np.asarray(pallas_scan.swar_shift_and_scan_words(
        arr, model, interpret=True
    ))
    wpf = wp.reshape(lay.chunk // 32, lay.lanes // 4)
    out = np.zeros((lay.chunk // 32, lay.lanes), dtype=bool)
    for k in range(4):
        out[:, k::4] = ((wpf >> np.uint32(8 * k)) & np.uint32(0xFF)) != 0
    return out


@pytest.mark.parametrize("seed", [3001, 3002, 3003])
def test_fuzz_swar_kernel_words_bit_exact(seed):
    rng = np.random.default_rng(seed)
    pat, ic = _gen_pattern(rng)
    model = try_compile_shift_and(pat, ignore_case=ic)
    assert model is not None and swar_values(model) is not None, (seed, pat)
    binary = bool(rng.integers(0, 2))
    data = _corpus(rng, 16384 * 512, binary,
                   [pat.encode(), pat.upper().encode()])
    lay = layout_mod.choose_layout(
        len(data), target_lanes=16384, min_chunk=512,
        lane_multiple=pallas_scan.SWAR_LANES_PER_BLOCK, chunk_multiple=512,
    )
    arr = layout_mod.to_device_array(data, lay)
    for name, m in [("full", model), ("filtered", filtered_for_device(model))]:
        if m is None or swar_values(m) is None:
            continue
        fu = _stripe_flags_unpacked(arr, m, lay)
        fp = _stripe_flags_packed(arr, m, lay)
        assert np.array_equal(fu, fp), (
            f"seed={seed} pat={pat!r} ic={ic} binary={binary} {name}: "
            f"packed {int(fp.sum())} vs unpacked {int(fu.sum())} spans"
        )


@pytest.mark.parametrize("seed", [3101, 3102])
def test_fuzz_swar_engine_lines_identical(seed, monkeypatch):
    from distributed_grep_tpu.ops.engine import GrepEngine

    rng = np.random.default_rng(seed)
    pat, ic = _gen_pattern(rng)
    assert swar_values(try_compile_shift_and(pat, ignore_case=ic)) is not None
    data = _corpus(rng, 1 << 20, bool(rng.integers(0, 2)),
                   [pat.encode()])
    monkeypatch.setenv("DGREP_SWAR", "1")
    e1 = GrepEngine(pat, ignore_case=ic, interpret=True)
    a = e1.scan(data).matched_lines
    assert e1.stats.get("swar") == 1, "SWAR route did not engage"
    monkeypatch.setenv("DGREP_SWAR", "0")
    b = GrepEngine(pat, ignore_case=ic, interpret=True).scan(data).matched_lines
    assert np.array_equal(a, b), (
        f"seed={seed} pat={pat!r} ic={ic}: {a.size} vs {b.size} lines"
    )


def test_swar_eligibility_boundaries():
    """The gate itself: ranges, length 9, value budget -> ineligible;
    wildcarded filter models and length-8 match-bit-0x80 -> eligible."""
    assert swar_values(try_compile_shift_and("function")) is not None  # len 8
    assert swar_values(try_compile_shift_and("functions")) is None  # len 9
    assert swar_values(try_compile_shift_and("h[ae]llo")) is not None  # 2 vals
    assert swar_values(try_compile_shift_and("h[a-e]llo")) is None  # range
    m = try_compile_shift_and("volcano", ignore_case=True)
    assert m is not None and swar_values(m) is not None  # 14 values
    m8 = try_compile_shift_and("function", ignore_case=True)
    assert m8 is not None and swar_values(m8) is not None  # 16 == budget
    mo = try_compile_shift_and("[abc][abc][abc][abc][abc][abc]")
    assert mo is not None and swar_values(mo) is None  # 18 values > 16

"""CPU grep vs TPU grep: drop-in interchangeability behind the app boundary.

The north star pins this: both apps produce identical records for identical
jobs (BASELINE.json north_star; SURVEY.md §1 plugin boundary).
"""

import pytest

from distributed_grep_tpu.apps.loader import load_application
from tests.conftest import expand_records
from distributed_grep_tpu.runtime.job import run_job
from distributed_grep_tpu.utils.config import JobConfig


@pytest.mark.parametrize("pattern", ["hello", "h[ae]llo", "(fox|hello)", "^the", r"a\nb"])
def test_cpu_and_tpu_apps_emit_identical_records(pattern):
    cpu = load_application("distributed_grep_tpu.apps.grep", pattern=pattern)
    tpu = load_application("distributed_grep_tpu.apps.grep_tpu", pattern=pattern)
    data = (
        b"hello world\nthe quick brown fox\nhallo again\nHELLO up\n"
        b"the end\nno match here\nfox hello the"
    )
    assert expand_records(cpu.map_fn("f.txt", data)) == \
        expand_records(tpu.map_fn("f.txt", data))


def test_tpu_app_case_insensitive():
    cpu = load_application("distributed_grep_tpu.apps.grep", pattern="hello", ignore_case=True)
    tpu = load_application("distributed_grep_tpu.apps.grep_tpu", pattern="hello", ignore_case=True)
    data = b"HELLO\nx\nHeLLo there\n"
    assert expand_records(cpu.map_fn("f", data)) == \
        expand_records(tpu.map_fn("f", data))


def test_tpu_app_multi_pattern_set():
    tpu = load_application(
        "distributed_grep_tpu.apps.grep_tpu", patterns=["fox", "hello"]
    )
    data = b"a fox\nnothing\nhello\n"
    keys = [kv.key for kv in expand_records(tpu.map_fn("f", data))]
    assert keys == ["f (line number #1)", "f (line number #3)"]


def test_full_job_with_tpu_app(tmp_path, corpus):
    cfg = JobConfig(
        input_files=[str(p) for p in corpus.values()],
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": "hello"},
        n_reduce=3,
        work_dir=str(tmp_path / "job"),
    )
    res_tpu = run_job(cfg, n_workers=2)
    cfg2 = JobConfig(
        input_files=cfg.input_files,
        application="distributed_grep_tpu.apps.grep",
        app_options={"pattern": "hello"},
        n_reduce=3,
        work_dir=str(tmp_path / "job2"),
    )
    res_cpu = run_job(cfg2, n_workers=2)
    assert res_tpu.results == res_cpu.results
    assert res_tpu.results  # non-empty


def test_app_mesh_shape_option(tmp_path):
    """mesh_shape/mesh_axes/pattern_axis flow from app_options through
    configure into the engine's mesh mode — a full job on the virtual mesh
    stays exact (wires the JobConfig.mesh_shape knob end-to-end)."""
    from distributed_grep_tpu.runtime.job import run_job
    from distributed_grep_tpu.utils.config import JobConfig

    f = tmp_path / "in.txt"
    f.write_text("hay\nxx needle yy\nzz\nneedle end\nnothing\n")
    cfg = JobConfig(
        input_files=[str(f)],
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": "needle", "interpret": True},
        mesh_shape=(4, 2),
        mesh_axes=("data", "seq"),
        n_reduce=2,
        work_dir=str(tmp_path / "w"),
    )
    assert cfg.effective_app_options()["mesh_shape"] == [4, 2]
    res = run_job(cfg, n_workers=2)
    keys = sorted(res.results)
    assert [k.rsplit("#", 1)[1].rstrip(")") for k in keys] == ["2", "4"]


def test_progress_wiring_and_compile_grace(tmp_path):
    """The worker's progress callback reaches the engine (stamps per scan/
    chunk), and a device scan declares a compile-grace window per FRESH
    kernel/layout shape — first scan, and again when a differently-sized
    split jit-specializes anew — while warm-shape scans stamp plainly
    (VERDICT r3 item 3 wiring + the round-4 per-shape review finding)."""
    from distributed_grep_tpu.apps.loader import load_application

    f = tmp_path / "f.txt"
    f.write_bytes(b"hello a\nxx\nhello b\n" * 100)

    app = load_application(
        "distributed_grep_tpu.apps.grep_tpu", pattern="hello", backend="cpu"
    )
    calls: list[float] = []
    assert app.set_progress(lambda grace_s=0.0: calls.append(grace_s))
    app.map_path_fn(str(f), str(f))
    assert calls and set(calls) == {0.0}  # cpu path: plain stamps only

    app_dev = load_application(
        "distributed_grep_tpu.apps.grep_tpu", pattern="hello", backend="device"
    )
    calls_dev: list[float] = []
    app_dev.set_progress(lambda grace_s=0.0: calls_dev.append(grace_s))
    app_dev.map_path_fn(str(f), str(f))
    assert calls_dev and calls_dev[0] > 0  # cold compile: grace declared
    calls_dev.clear()
    app_dev.map_path_fn(str(f), str(f))
    assert calls_dev and set(calls_dev) == {0.0}  # warm cache: plain stamps
    # a differently-sized split -> a new padded layout -> fresh jit
    # specialization: grace is re-declared for the new shape
    g = tmp_path / "g.txt"
    g.write_bytes(b"hello c\nword word word\n" * 20000)
    calls_dev.clear()
    app_dev.map_path_fn(str(g), str(g))
    assert calls_dev and any(c > 0 for c in calls_dev)
    calls_dev.clear()
    app_dev.map_path_fn(str(g), str(g))  # now warm too
    assert calls_dev and set(calls_dev) == {0.0}
    app_dev.set_progress(None)
    app.set_progress(None)

"""Distributed control/data plane tests: HTTP long-poll protocol, star-topology
data plane, multi-process localhost jobs, worker-death recovery over HTTP.

The reference's own topology (coordinator + workers over RPC/SFTP,
SURVEY.md §4) degenerates to localhost multi-process — that's what these run.
"""

import json
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from distributed_grep_tpu.apps.loader import load_application
from distributed_grep_tpu.runtime.http_coordinator import CoordinatorServer
from distributed_grep_tpu.runtime.http_transport import CoordinatorGone, HttpTransport
from distributed_grep_tpu.runtime.worker import WorkerKilled, WorkerLoop
from distributed_grep_tpu.utils.config import JobConfig


def make_server(tmp_path, corpus, pattern="hello", **kw):
    defaults = dict(
        input_files=[str(p) for p in corpus.values()],
        app_options={"pattern": pattern},
        n_reduce=3,
        work_dir=str(tmp_path / "job"),
        coordinator_port=0,  # ephemeral
        task_timeout_s=2.0,
        sweep_interval_s=0.1,
    )
    defaults.update(kw)
    server = CoordinatorServer(JobConfig(**defaults))
    server.start()
    return server


def expected_grep_lines(corpus, pattern=b"hello"):
    out = set()
    for path in corpus.values():
        for i, line in enumerate(path.read_bytes().split(b"\n"), start=1):
            if pattern in line:
                out.add(f"{path} (line number #{i})\t{line.decode()}")
    return out


def output_lines(workdir_root):
    lines = set()
    for f in sorted(Path(workdir_root).glob("out/mr-out-*")):
        lines.update(l for l in f.read_text().splitlines() if l)
    return lines


def test_http_end_to_end(tmp_path, corpus):
    server = make_server(tmp_path, corpus)
    addr = f"127.0.0.1:{server.port}"
    app = load_application("distributed_grep_tpu.apps.grep", pattern="hello")

    def worker():
        WorkerLoop(HttpTransport(addr), app).run()

    threads = [threading.Thread(target=worker) for _ in range(2)]
    for t in threads:
        t.start()
    assert server.wait_done(timeout=30.0)
    for t in threads:
        t.join(timeout=10.0)
    assert output_lines(tmp_path / "job") == expected_grep_lines(corpus)
    status = server.status()
    assert status["done"] and status["map"]["completed"] == 3
    server.shutdown(linger_s=0.1)


def test_http_worker_death_recovery(tmp_path, corpus):
    """Worker dies after reading its input; a second worker finishes the job
    after the task timeout re-enqueue — over the real HTTP protocol."""
    server = make_server(tmp_path, corpus, task_timeout_s=1.0)
    addr = f"127.0.0.1:{server.port}"
    app = load_application("distributed_grep_tpu.apps.grep", pattern="hello")

    def dying_worker():
        loop = WorkerLoop(
            HttpTransport(addr),
            app,
            fault_hooks={"after_map_read": _raise_killed},
        )
        try:
            loop.run()
        except WorkerKilled:
            pass

    t1 = threading.Thread(target=dying_worker)
    t1.start()
    t1.join(timeout=10.0)
    # Job not done; the healthy worker arrives late (elastic join) and finishes.
    assert not server.scheduler.done()
    t2 = threading.Thread(target=lambda: WorkerLoop(HttpTransport(addr), app).run())
    t2.start()
    assert server.wait_done(timeout=30.0)
    t2.join(timeout=10.0)
    assert output_lines(tmp_path / "job") == expected_grep_lines(corpus)
    assert server.metrics.counters.get("map_retries", 0) >= 1
    server.shutdown(linger_s=0.1)


def _raise_killed():
    raise WorkerKilled()


def test_http_data_plane_rejects_traversal(tmp_path, corpus):
    server = make_server(tmp_path, corpus)
    t = HttpTransport(f"127.0.0.1:{server.port}")
    with pytest.raises(RuntimeError):
        t.write_intermediate("../escape", b"x")
    with pytest.raises(RuntimeError):
        t.read_intermediate("..%2F..%2Fetc%2Fpasswd")
    server.shutdown(linger_s=0.1)


def test_http_input_endpoint_allowlist(tmp_path, corpus):
    """GET /data/input/ serves only the job's input splits — never arbitrary
    coordinator-host files like /etc/passwd."""
    server = make_server(tmp_path, corpus)
    t = HttpTransport(f"127.0.0.1:{server.port}")
    legit = server.config.input_files[0]
    assert t.read_input(legit) == Path(legit).read_bytes()
    with pytest.raises(RuntimeError) as e:
        t.read_input("/etc/passwd")
    assert "403" in str(e.value)
    server.shutdown(linger_s=0.1)


def test_http_config_bootstrap(tmp_path, corpus):
    server = make_server(tmp_path, corpus, pattern="fox")
    t = HttpTransport(f"127.0.0.1:{server.port}")
    cfg = t.fetch_config()
    assert cfg.app_options["pattern"] == "fox"
    assert cfg.n_reduce == 3
    server.shutdown(linger_s=0.1)


def test_coordinator_gone_raises_after_budget(monkeypatch):
    monkeypatch.setenv("DGREP_RPC_RETRIES", "2")
    monkeypatch.setenv("DGREP_RPC_BACKOFF_S", "0.05")
    # Nothing listens on this port.
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
    t = HttpTransport(f"127.0.0.1:{dead_port}")
    with pytest.raises(CoordinatorGone):
        t.fetch_status()
    assert t.retry_count == 2  # every scheduled retry was spent


@pytest.mark.slow
def test_multiprocess_cli_job(tmp_path, corpus):
    """Real processes: coordinator + 2 workers via the CLI, localhost."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    cfg = JobConfig(
        input_files=[str(p) for p in corpus.values()],
        app_options={"pattern": "hello"},
        n_reduce=3,
        work_dir=str(tmp_path / "job"),
        coordinator_port=port,
        task_timeout_s=3.0,
    )
    cfg_path = tmp_path / "job.json"
    cfg_path.write_text(cfg.to_json())
    repo = str(Path(__file__).resolve().parents[1])
    env = {"PYTHONPATH": repo, "PATH": "/usr/bin:/bin", "DGREP_LOG": "WARNING",
           "JAX_PLATFORMS": "cpu"}
    coord = subprocess.Popen(
        [sys.executable, "-m", "distributed_grep_tpu", "coordinator", "--config", str(cfg_path)],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=env,
        text=True,
    )
    workers = []
    try:
        for _ in range(2):
            workers.append(
                subprocess.Popen(
                    [sys.executable, "-m", "distributed_grep_tpu", "worker",
                     "--addr", f"127.0.0.1:{port}"],
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                    env=env,
                )
            )
        out, err = coord.communicate(timeout=90)
        assert coord.returncode == 0, f"coordinator failed: {err[-2000:]}"
        outputs = json.loads(out.strip().splitlines()[-1])["outputs"]
        assert len(outputs) == 3
        assert output_lines(tmp_path / "job") == expected_grep_lines(corpus)
        for w in workers:
            w.wait(timeout=30)
    finally:
        for p in [coord, *workers]:
            if p.poll() is None:
                p.kill()


def test_http_read_input_path_spools_to_temp(tmp_path, corpus):
    server = make_server(tmp_path, corpus)
    try:
        t = HttpTransport(f"127.0.0.1:{server.port}")
        fname = server.config.input_files[0]
        path, is_temp = t.read_input_path(fname)
        assert is_temp
        try:
            assert path.read_bytes() == Path(fname).read_bytes()
        finally:
            path.unlink()
    finally:
        server.shutdown()


def test_http_streaming_app_end_to_end(tmp_path, corpus):
    """grep_tpu's map_path_fn over the HTTP transport: the worker spools
    each split to disk and streams it — output identical to the whole-bytes
    CPU app."""
    server = make_server(
        tmp_path, corpus,
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": "hello", "backend": "cpu"},
    )
    try:
        addr = f"127.0.0.1:{server.port}"
        app = load_application("distributed_grep_tpu.apps.grep_tpu")
        assert app.map_path_fn is not None  # loader must expose streaming entry
        t = HttpTransport(addr)

        def no_whole_read(filename):  # streaming must never load whole bytes
            raise AssertionError("read_input called on the streaming path")

        t.read_input = no_whole_read
        WorkerLoop(t, app).run()
        assert output_lines(server.config.work_dir) == expected_grep_lines(corpus)
    finally:
        server.shutdown()


# ------------------------------------------------- streaming data plane

def test_data_plane_streams_in_small_blocks(tmp_path, corpus, monkeypatch):
    """With the block size shrunk to 512 bytes, a split far larger than one
    block must flow GET + PUT end-to-end — proving neither side depends on
    whole-file buffering."""
    from distributed_grep_tpu.runtime import http_coordinator

    monkeypatch.setattr(http_coordinator, "BLOCK_BYTES", 512)
    big = tmp_path / "big.txt"
    big.write_bytes(b"".join(
        (f"line {i} " + ("hello " if i % 97 == 0 else "x " * 20)).encode() + b"\n"
        for i in range(20_000)
    ))
    corpus = {"big.txt": big}
    server = make_server(tmp_path, corpus)
    addr = f"127.0.0.1:{server.port}"
    app = load_application("distributed_grep_tpu.apps.grep", pattern="hello")
    t = threading.Thread(target=lambda: WorkerLoop(HttpTransport(addr), app).run())
    t.start()
    assert server.wait_done(timeout=30.0)
    t.join(timeout=10.0)
    assert output_lines(tmp_path / "job") == expected_grep_lines(corpus)
    server.shutdown(linger_s=0.1)


def test_input_get_supports_range_resume(tmp_path, corpus):
    """The coordinator serves 'bytes=N-' prefix ranges with 206 — what the
    worker's spool resume sends after a death mid-download."""
    import urllib.request

    server = make_server(tmp_path, corpus)
    path = str(next(iter(corpus.values())))
    whole = Path(path).read_bytes()
    url = f"http://127.0.0.1:{server.port}/data/input/" + urllib.parse.quote(
        path, safe="")
    req = urllib.request.Request(url)
    req.add_header("Range", "bytes=7-")
    with urllib.request.urlopen(req) as resp:
        assert resp.status == 206
        assert resp.headers["Content-Range"] == f"bytes 7-{len(whole)-1}/{len(whole)}"
        assert resp.read() == whole[7:]
    # fancier ranges fall back to a full 200
    req = urllib.request.Request(url)
    req.add_header("Range", "bytes=3-5")
    with urllib.request.urlopen(req) as resp:
        assert resp.status == 200
        assert resp.read() == whole
    server.shutdown(linger_s=0.1)


@pytest.mark.slow
def test_coordinator_rss_flat_on_large_split(tmp_path, coordinator_port_reader):
    """VERDICT round-1 weak #4: a split bigger than any in-memory buffer
    must flow through a coordinator subprocess without its peak RSS growing
    by anything near the split size."""
    size = 150 * 1024 * 1024
    big = tmp_path / "big.bin"
    with open(big, "wb") as f:
        line = b"x" * 199 + b"\n"
        for _ in range(size // len(line)):
            f.write(line)
        f.write(b"the needle is here\n")
    cfg = tmp_path / "job.json"
    cfg.write_text(json.dumps({
        "input_files": [str(big)],
        "application": "distributed_grep_tpu.apps.grep_tpu",
        "app_options": {"pattern": "needle", "backend": "cpu"},
        "n_reduce": 2,
        "work_dir": str(tmp_path / "wd"),
        "coordinator_port": 0,
    }))
    # port 0: parse the actual port from the coordinator's log line
    import os
    import re as re_mod
    import signal

    env = {**os.environ, "DGREP_LOG": "INFO"}
    # The machine-wide PYTHONPATH includes an axon sitecustomize that
    # imports jax (+~130 MB) into EVERY python process; the coordinator
    # never uses it — measure the coordinator without that noise (the
    # worker keeps the normal env).
    coord_env = {**env, "PYTHONPATH": ""}
    coord = subprocess.Popen(
        [sys.executable, "-m", "distributed_grep_tpu", "coordinator",
         "--config", str(cfg)],
        stderr=subprocess.PIPE, stdout=subprocess.DEVNULL, env=coord_env, text=True,
    )
    try:
        port = coordinator_port_reader(coord)
        assert port, "coordinator never announced its port"
        # Sample the coordinator's peak RSS CONCURRENTLY with the job:
        # VmHWM is monotone, but a zombie's /proc status drops the Vm*
        # lines — on a slow box the coordinator's ~2 s shutdown linger
        # can elapse before a post-hoc read, so sampling only after the
        # worker exits races process teardown.  Sandboxed kernels
        # (gVisor) expose no VmHWM at all — there the max over VmRSS
        # samples stands in for the high-water mark, plenty for a
        # bound set ~40 MB under the split size.
        import threading

        samples: list[int] = []
        done = threading.Event()

        def _sample_hwm() -> None:
            while not done.is_set():
                try:
                    with open(f"/proc/{coord.pid}/status") as f:
                        rss = None
                        for ln in f:
                            if ln.startswith("VmHWM"):
                                samples.append(int(ln.split()[1]))
                                rss = None
                                break
                            if ln.startswith("VmRSS"):
                                rss = int(ln.split()[1])
                        if rss is not None:
                            samples.append(rss)
                except OSError:
                    pass
                # 50 Hz: the VmRSS fallback is peak-LOSSY (a transient
                # spike between samples is missed) — a tight interval
                # plus the 40 MB assertion margin keeps a whole-split
                # (150 MB) buffering regression detectable; on kernels
                # with VmHWM the monotone high-water mark wins anyway
                done.wait(0.02)

        sampler = threading.Thread(target=_sample_hwm, daemon=True)
        sampler.start()
        try:
            worker = subprocess.run(
                [sys.executable, "-m", "distributed_grep_tpu", "worker",
                 "--addr", f"127.0.0.1:{port}"],
                capture_output=True, timeout=240, env=env,
            )
        finally:
            done.set()
            sampler.join(timeout=5)
        hwm_kb = max(samples) if samples else None
        assert coord.wait(timeout=60) == 0, worker.stderr[-500:]
    finally:
        if coord.poll() is None:
            coord.send_signal(signal.SIGKILL)
        coord.wait()
    out = b"".join(p.read_bytes() for p in (tmp_path / "wd" / "out").glob("mr-out-*"))
    assert b"needle is here" in out
    assert hwm_kb is not None and hwm_kb < 110 * 1024, f"coordinator VmHWM {hwm_kb} kB"


def test_http_coordinator_crash_resume(tmp_path, corpus):
    """Coordinator crash + restart with --resume over the HTTP plane: the
    journal replay skips committed map work and a fresh worker finishes the
    job.  The reference loses the whole job on a coordinator crash
    (SURVEY.md §5 checkpoint/resume); this is the distributed-mode half of
    the in-process resume test in test_runtime.py."""
    server1 = make_server(tmp_path, corpus)
    addr = f"127.0.0.1:{server1.port}"
    app = load_application("distributed_grep_tpu.apps.grep", pattern="hello")

    # one worker that dies right after its first successful map commit
    committed = {"n": 0}

    def die_after_first_commit():
        committed["n"] += 1
        if committed["n"] >= 2:  # first call = task 1's commit done
            raise WorkerKilled()

    def dying_worker():
        loop = WorkerLoop(
            HttpTransport(addr), app,
            fault_hooks={"before_map_finished": die_after_first_commit},
        )
        try:
            loop.run()
        except WorkerKilled:
            pass

    t1 = threading.Thread(target=dying_worker)
    t1.start()
    t1.join(timeout=15.0)
    status1 = server1.status()
    assert not status1["done"]
    n_committed = status1["map"]["completed"]
    assert n_committed >= 1
    # crash: tear the server down with the job incomplete (journal persists)
    server1.shutdown(linger_s=0.0)

    # restart on the same work dir with resume (the exact same config the
    # journal was written under): replay skips committed maps
    cfg = server1.config
    server2 = CoordinatorServer(cfg, resume=True)
    server2.start()
    status2 = server2.status()
    assert status2["map"]["completed"] == n_committed  # replayed, not re-run
    t2 = threading.Thread(
        target=lambda: WorkerLoop(
            HttpTransport(f"127.0.0.1:{server2.port}"), app
        ).run()
    )
    t2.start()
    assert server2.wait_done(timeout=30.0)
    t2.join(timeout=10.0)
    # the resumed run assigned only the REMAINING maps (>=: a timeout
    # sweep on a loaded CI box may legitimately re-assign one)
    assigned = server2.scheduler.metrics.counters.get("map_assigned", 0)
    assert len(cfg.input_files) - n_committed <= assigned < 2 * len(cfg.input_files)
    assert server2.status()["map"]["completed"] == len(cfg.input_files)
    assert output_lines(tmp_path / "job") == expected_grep_lines(corpus)
    server2.shutdown(linger_s=0.1)


def test_http_worker_slots_parallel(tmp_path, corpus):
    """--slots N: one worker process runs N task loops sharing the
    transport (the multi-chip-per-host slot analogue); job completes with
    oracle output."""
    from distributed_grep_tpu.runtime.http_transport import run_http_worker

    server = make_server(tmp_path, corpus)
    addr = f"127.0.0.1:{server.port}"
    t = threading.Thread(target=lambda: run_http_worker(addr=addr, n_parallel=3))
    t.start()
    assert server.wait_done(timeout=30.0)
    t.join(timeout=15.0)
    assert output_lines(tmp_path / "job") == expected_grep_lines(corpus)
    server.shutdown(linger_s=0.1)


def test_multiprocess_device_backend_mesh_job(tmp_path, corpus):
    """Real worker processes running the DEVICE engine (interpret-mode
    Pallas kernels on an 8-virtual-device mesh) under the HTTP runtime —
    the full distributed TPU-path wiring: config -> worker jax init ->
    engine mesh mode -> exact collated output."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    cfg = JobConfig(
        input_files=[str(p) for p in corpus.values()],
        app_options={"pattern": "hello", "backend": "device",
                     "interpret": True},
        mesh_shape=(4, 2),
        mesh_axes=("data", "seq"),
        n_reduce=2,
        work_dir=str(tmp_path / "job"),
        coordinator_port=port,
        task_timeout_s=60.0,  # first interpret compile in the worker is slow
    )
    cfg_path = tmp_path / "job.json"
    cfg_path.write_text(cfg.to_json())
    repo = str(Path(__file__).resolve().parents[1])
    env = {"PYTHONPATH": repo, "PATH": "/usr/bin:/bin", "DGREP_LOG": "WARNING",
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    coord = subprocess.Popen(
        [sys.executable, "-m", "distributed_grep_tpu", "coordinator",
         "--config", str(cfg_path)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True,
    )
    worker = None
    try:
        worker = subprocess.Popen(
            [sys.executable, "-m", "distributed_grep_tpu", "worker",
             "--addr", f"127.0.0.1:{port}"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env,
        )
        out, err = coord.communicate(timeout=180)
        assert coord.returncode == 0, f"coordinator failed: {err[-2000:]}"
        assert output_lines(tmp_path / "job") == expected_grep_lines(corpus)
        worker.wait(timeout=30)
    finally:
        for p in [coord, worker]:
            if p is not None and p.poll() is None:
                p.kill()


def test_status_cli_verb(tmp_path, corpus):
    """`status --addr` pretty-prints a running coordinator's /status JSON
    (operator surface); unreachable coordinators exit 2 with a clean
    message, like the other CLI error paths."""
    import json as _json
    import subprocess
    import sys as _sys

    server = make_server(tmp_path, corpus)
    try:
        out = subprocess.run(
            [_sys.executable, "-m", "distributed_grep_tpu", "status",
             "--addr", f"127.0.0.1:{server.port}"],
            capture_output=True, text=True, timeout=30,
        )
        assert out.returncode == 0, out.stderr
        st = _json.loads(out.stdout)
        assert {"map", "reduce", "done", "metrics"} <= set(st)
    finally:
        server.shutdown(linger_s=0.1)
    bad = subprocess.run(
        [_sys.executable, "-m", "distributed_grep_tpu", "status",
         "--addr", "127.0.0.1:1", "--timeout", "1"],
        capture_output=True, text=True, timeout=30,
    )
    assert bad.returncode == 2 and "cannot reach" in bad.stderr

"""Performance-contract proofs (pytest marker ``perf``, CPU-runnable,
standalone like ``faults``/``obs``): N small files must produce FAR fewer
than N device dispatches on the batched path — the whole point of
cross-file batching (ISSUE 3 acceptance: dispatch count ≪ file count).

Dispatches are counted at the real boundary — ops/device_scan.scan_device,
the one entry every device-path scan funnels through — on a CPU-interpret
engine (the production Pallas kernel path, interpret mode), not from the
engine's own telemetry, so the assertion cannot be satisfied by a
miscounting counter.

Standalone: ``python -m pytest tests/test_perf.py -q``.
"""

from __future__ import annotations

import numpy as np
import pytest

from distributed_grep_tpu.ops import device_scan
from distributed_grep_tpu.ops.engine import GrepEngine

pytestmark = pytest.mark.perf

N_FILES = 64


def _small_files() -> list[tuple[str, bytes]]:
    rng = np.random.default_rng(11)
    words = [b"the", b"volcano", b"of", b"needle", b"and", b"hello"]
    out = []
    for i in range(N_FILES):
        lines = []
        for _ in range(40):
            k = int(rng.integers(2, 6))
            lines.append(b" ".join(
                words[int(rng.integers(0, len(words)))] for _ in range(k)
            ))
        out.append((f"f{i:03d}", b"\n".join(lines) + b"\n"))
    return out


def _counting(monkeypatch):
    """Wrap the real scan_device with a call counter (the engine resolves
    it from the module at each call, so the patch is seen)."""
    calls: list[int] = []
    orig = device_scan.scan_device

    def counted(eng, data, progress=None, **kw):
        calls.append(len(data))
        return orig(eng, data, progress=progress, **kw)

    monkeypatch.setattr(device_scan, "scan_device", counted)
    return calls


def test_batched_dispatch_count_far_below_file_count(monkeypatch):
    calls = _counting(monkeypatch)
    eng = GrepEngine("hello", interpret=True, batch_bytes=1 << 20)
    got = eng.scan_batch(_small_files())
    stats = dict(eng.stats)
    n_dispatches = len(calls)
    # the contract: dispatches ≪ files (here: everything packs into ONE)
    assert n_dispatches * 8 <= N_FILES, (n_dispatches, N_FILES)
    assert stats["batched_files"] == N_FILES
    assert stats["batch_dispatches"] == n_dispatches
    assert stats["dispatches_saved"] == N_FILES - n_dispatches
    # and the packed dispatch actually scanned everything
    assert sum(calls) == sum(
        len(b) + (0 if b.endswith(b"\n") else 1) for _, b in _small_files()
    )
    assert sum(r.n_matches for _, r in got) > 0


def test_unbatched_baseline_pays_one_dispatch_per_file(monkeypatch):
    """The counter-factual the batched path is measured against: per-file
    scan() on the same interpret engine dispatches once per file."""
    calls = _counting(monkeypatch)
    files = _small_files()[:8]  # 8 files suffice to pin the 1:1 shape
    eng = GrepEngine("hello", interpret=True)
    for _, blob in files:
        eng.scan(blob)
    assert len(calls) == len(files)


def test_batched_results_equal_per_file_on_interpret_engine():
    files = _small_files()
    eng = GrepEngine("hello", interpret=True, batch_bytes=1 << 20)
    got = eng.scan_batch(files)
    blobs = dict(files)
    for name, res in got:
        solo = eng.scan(blobs[name])
        assert np.array_equal(res.matched_lines, solo.matched_lines), name

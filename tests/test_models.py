"""Pattern-model tests: DFA compiler vs Python re, shift-and, Aho-Corasick.

The contract under test is grep's: for every line, "does any match occur in
this line" must agree with Python re.search on that line (SURVEY.md §4:
regex kernel vs a reference oracle on adversarial inputs).
"""

import re

import numpy as np
import pytest

from distributed_grep_tpu.models.aho import compile_aho_corasick
from distributed_grep_tpu.models.dfa import (
    NewlineInPattern,
    RegexError,
    TooManyStates,
    compile_dfa,
    matched_lines,
    reference_scan,
)
from distributed_grep_tpu.models.shift_and import scan_reference, try_compile_shift_and

TEXT = (
    b"hello world\n"
    b"the quick brown fox jumps\n"
    b"HELLO SHOUTING\n"
    b"hallo hullo hella\n"
    b"abc123 def456\n"
    b"  indented line\n"
    b"\n"
    b"x" * 300 + b"needle" + b"y" * 50 + b"\n"
    b"ends with dollar\n"
    b"no trailing newline"
)


def oracle_lines(pattern: str, data: bytes, flags=0) -> set[int]:
    out = set()
    for i, line in enumerate(data.split(b"\n"), start=1):
        if re.search(pattern.encode(), line, flags):
            out.add(i)
    return out


PATTERNS = [
    "hello",
    "h[ae]llo",
    "h.llo",
    "hel+o",
    "hel*o",
    "hells?",
    "(hello|fox|needle)",
    "[0-9]+",
    r"\d{3}",
    r"[a-z]{2}\d",
    "qu..k",
    "^hello",
    "^the",
    "dollar$",
    "^HELLO.*$",
    "x{10,20}needle",
    r"\w+\s\w+",
    "h(el){2}a",
    "nee(dle|ble)",
    "[^a-z ]+",
]


@pytest.mark.parametrize("pattern", PATTERNS)
def test_dfa_matches_re_oracle_per_line(pattern):
    table = compile_dfa(pattern)
    assert matched_lines(table, TEXT) == oracle_lines(pattern, TEXT)


@pytest.mark.parametrize("pattern", ["hello", "h[ae]llo", "[0-9]+", "^the"])
def test_dfa_case_insensitive(pattern):
    table = compile_dfa(pattern, ignore_case=True)
    assert matched_lines(table, TEXT) == oracle_lines(pattern, TEXT, re.IGNORECASE)


def test_dfa_random_fuzz_vs_re():
    rng = np.random.default_rng(42)
    alphabet = b"abcdef\n \t"
    data = bytes(rng.choice(list(alphabet), size=4096).tolist())
    for pattern in ["ab", "a[bc]d", "a.*f", "(ab|cd)+", "a{2,4}b", "^a", "f$", r"\w\s\w"]:
        table = compile_dfa(pattern)
        assert matched_lines(table, data) == oracle_lines(pattern, data), pattern


def test_dfa_binary_bytes():
    data = b"\x00\x01hello\xff\xfe\nz\x80hello\n"
    table = compile_dfa("hello")
    assert matched_lines(table, data) == {1, 2}
    table = compile_dfa(r"[\x00-\x08]")
    assert matched_lines(table, data) == {1}


def test_dfa_match_offsets_exact():
    table = compile_dfa("ab")
    offsets = reference_scan(table, b"xabxxab")
    np.testing.assert_array_equal(offsets, [3, 7])


def test_dfa_eol_empty_input_no_phantom_line():
    """ADVICE round-5 low: empty input has ZERO lines, so '^$'-style
    zero-width EOL accepts must not report a phantom line-1 match (GNU
    reports no match on an empty file).  Library callers (the CLI
    short-circuits empty inputs) and oracle uses hit this path."""
    for pattern in ("^$", "$", "x$|^$"):
        table = compile_dfa(pattern)
        assert reference_scan(table, b"").size == 0, pattern
        assert matched_lines(table, b"") == set(), pattern
    # ...while an actual empty first line still matches (the n > 0 arm)
    table = compile_dfa("^$")
    assert matched_lines(table, b"\nabc\n") == {1}
    assert matched_lines(table, b"\n") == {1}


def test_dfa_rejects_newline_patterns():
    with pytest.raises(NewlineInPattern):
        compile_dfa(r"a\nb")


def test_dfa_syntax_errors():
    for bad in ["h[", "(a", "a)", "*a", "a{3,1}", "a\\"]:
        with pytest.raises(RegexError):
            compile_dfa(bad)


def test_dfa_state_cap():
    with pytest.raises(TooManyStates):
        compile_dfa("a{400}b{400}", max_states=16)


def test_dfa_byte_classes_are_compressed():
    table = compile_dfa("hello")
    # distinct symbols: h e l o + newline + everything-else = 6 classes
    assert table.n_classes <= 8
    assert table.trans.shape == (table.n_states, table.n_classes)
    # newline column resets every state to start
    nl_cls = table.byte_to_cls[0x0A]
    assert (table.trans[:, nl_cls] == table.start).all()


# ----------------------------------------------------------------- shift-and

def test_shift_and_eligibility():
    assert try_compile_shift_and("hello") is not None
    assert try_compile_shift_and("h[ae]llo") is not None
    assert try_compile_shift_and("h.llo") is not None
    assert try_compile_shift_and("hel+o") is None  # repeat -> DFA
    assert try_compile_shift_and("(a|b)") is None  # alternation -> DFA
    assert try_compile_shift_and("^x") is None  # anchor -> DFA
    assert try_compile_shift_and("a" * 33) is None  # too long
    assert try_compile_shift_and("h[") is None  # syntax error -> let DFA raise


def test_shift_and_scan_matches_dfa():
    for pattern in ["hello", "h[ae]llo", "qu..k", "needle"]:
        model = try_compile_shift_and(pattern)
        table = compile_dfa(pattern)
        np.testing.assert_array_equal(
            scan_reference(model, TEXT), reference_scan(table, TEXT), err_msg=pattern
        )


def test_shift_and_case_insensitive():
    model = try_compile_shift_and("hello", ignore_case=True)
    hits = scan_reference(model, b"HELLO hello HeLLo")
    assert len(hits) == 3


# -------------------------------------------------------------- aho-corasick

def test_aho_basic_multi_pattern():
    table = compile_aho_corasick(["he", "she", "his", "hers"])
    data = b"ushers\nhis house\nnothing\n"
    assert matched_lines(table, data) == {1, 2}
    offsets = reference_scan(table, b"ushers")
    # matches: she@4, he@4, hers@6 -> end offsets {4, 6}
    np.testing.assert_array_equal(offsets, [4, 6])


def test_aho_overlapping_and_substring_patterns():
    table = compile_aho_corasick(["ab", "abc", "bc"])
    offsets = reference_scan(table, b"zabcz")
    np.testing.assert_array_equal(offsets, [3, 4])


def test_aho_vs_re_oracle_on_text():
    pats = ["hello", "fox", "needle", "456", "SHOUT"]
    table = compile_aho_corasick(pats)
    expected = set()
    for p in pats:
        expected |= oracle_lines(re.escape(p), TEXT)
    assert matched_lines(table, TEXT) == expected


def test_aho_ignore_case():
    table = compile_aho_corasick(["hello"], ignore_case=True)
    assert matched_lines(table, TEXT) == oracle_lines("hello", TEXT, re.IGNORECASE)


def test_aho_scales_to_1k_literals():
    rng = np.random.default_rng(7)
    pats = ["".join(chr(c) for c in rng.integers(97, 123, size=8)) for _ in range(1000)]
    table = compile_aho_corasick(pats)
    assert table.n_states > 1000
    data = ("xx" + pats[17] + "yy\n" + "zz\n" + pats[999]).encode()
    assert matched_lines(table, data) == {1, 3}


def test_aho_full_alphabet_binary_patterns():
    # Full-alphabet binary ruleset: 256 byte classes, every class index must
    # survive the table dtypes end to end (config-5 shape at toy size).
    pats = [bytes([b]) for b in range(256) if b != 0x0A]
    table = compile_aho_corasick(pats)
    assert table.n_classes >= 256
    assert int(table.byte_to_cls.max()) < table.n_classes
    data = bytes([0, 65, 0x0A, 255, 254, 0x0A])
    assert matched_lines(table, data) == {1, 2}


def test_aho_banks_split_and_union():
    from distributed_grep_tpu.models.aho import compile_aho_corasick_banks

    pats = ["alpha", "bravo", "charlie", "delta", "echo", "foxtrot"]
    banks = compile_aho_corasick_banks(pats, max_states_per_bank=16)
    assert len(banks) >= 2  # forced split
    data = b"xx alpha\nnothing\nfoxtrot here\ndelta\n"
    got = set()
    for t in banks:
        got |= matched_lines(t, data)
    assert got == {1, 3, 4}


def test_aho_bank_single_when_capacity_allows():
    from distributed_grep_tpu.models.aho import compile_aho_corasick_banks

    banks = compile_aho_corasick_banks(["he", "she"], max_states_per_bank=1 << 16)
    assert len(banks) == 1


def test_backrefs_and_assertions_reject_to_re_fallback():
    """\\1..\\9 and \\b-style assertions are beyond any finite automaton:
    the parser must RAISE (routing the engine to its host re fallback),
    never silently treat them as literal digits/letters — r'\\bword\\b'
    used to scan for 'bwordb'."""
    import pytest

    from distributed_grep_tpu.models.dfa import RegexError, compile_dfa
    from distributed_grep_tpu.ops.engine import GrepEngine

    # round 5: \b/\B parse into Anchor nodes (device filter+confirm) and
    # \A/\Z map to the line anchors — only backrefs and \z/\G still
    # reject at parse; \b-containing patterns reject at NFA build (no
    # exact table form), never scanning for literal 'bwordb'
    for pat in (r"(ab)\1", r"\bword\b", r"x\Bd"):
        with pytest.raises(RegexError):
            compile_dfa(pat)
    compile_dfa(r"a\Z")  # == 'a$' under per-line semantics
    eng = GrepEngine(r"\bword\b", backend="cpu")
    assert eng.mode == "re"
    assert eng.scan(b"a word x\nwords\nbwordb\n").matched_lines.tolist() == [1]
    eng2 = GrepEngine(r"(ab)\1", backend="cpu")
    assert eng2.scan(b"abab\nabcd\n").matched_lines.tolist() == [1]


def test_negated_class_ignore_case_excludes_both_cases():
    """[^x] under -i must reject 'x' AND 'X' (re/grep semantics): the
    parser folds class members BEFORE complementing — folding after
    re-adds the excluded letter via its case partner (round-4 wide-fuzz
    find, seed 1111; the bad mask was shared by every engine path)."""
    import re

    from distributed_grep_tpu.ops.engine import GrepEngine

    for pat, data in (
        (r"[^x]$", b"fox\n"), (r"a[^x]", b"aX\n"), (r"[^x]", b"X\n"),
        (r"[^a-c]", b"B\n"), (r"[^a-c]", b"d\n"), (r"[^\d]", b"5\n"),
        # literal-set decomposition route (enumerate_literal_set parses
        # case-sensitively; negated classes must still fold-then-complement
        # or the per-member downstream fold re-adds the excluded letter)
        (r"([^x]|zz)", b"x\n"), (r"(q[^x]|qq)", b"qX\n"),
        (r"([^x]|zz)", b"a\n"),
    ):
        want = bool(re.search(pat.encode(), data.rstrip(b"\n"), re.IGNORECASE))
        for backend in ("cpu", "device"):
            eng = GrepEngine(pat, backend=backend, ignore_case=True)
            got = bool(eng.scan(data).matched_lines.size)
            assert got == want, (pat, data, backend, eng.mode)


def test_nullable_at_eol_matches_empty_lines_exactly():
    """Patterns whose empty match is valid at '$' ('^$', '^ *$', 'x?$')
    must match empty lines — including an empty FIRST line, which no
    byte-level scan position covers — and must not report a phantom line
    past the final newline (round-4 wide-fuzz find, seed 3116; the
    engine post-processes both edges for every backend)."""
    import re

    from distributed_grep_tpu.ops.engine import GrepEngine

    cases = [
        (r"^$", b"a\n\nbb\n\n\ncc\n"), (r"^$", b"\nx\n"),
        (r"^ *$", b"a\n  \n\nz"), (r"x?$", b"a\n\nbb\n"),
        (r"(a|b?)$", b"\n\n"), (r"a$", b"a\n\na\n"), (r"^$", b"\n"),
        (r"^$", b""),
    ]
    for pat, data in cases:
        rx = re.compile(pat.encode())
        lines = data.split(b"\n")[:-1] if data.endswith(b"\n") else data.split(b"\n")
        want = [i for i, ln in enumerate(lines, 1) if rx.search(ln)] if data else []
        for backend in ("cpu", "device"):
            eng = GrepEngine(pat, backend=backend)
            got = sorted(eng.scan(data).matched_lines.tolist())
            assert got == want, (pat, data, backend, eng.mode, got, want)


def test_posix_bracket_classes_compile_and_match():
    """POSIX bracket classes compile into the automaton subset (Python
    re cannot host them, so there is no fallback to hide behind) and the
    expander produces a re-compatible equivalent for confirm/-o/fallback
    consumers — both agree on a digit/punct/space-rich corpus."""
    import re

    from distributed_grep_tpu.models.dfa import (
        RegexError,
        compile_dfa,
        expand_posix_classes,
        matched_lines,
    )

    data = (b"abc123\nonlyletters\n456\nUPPER low\nmix3d!\n  \tws\n"
            b"punct,;.\nDEAD beef 99\nx9\n")
    pats = ["[[:digit:]]+", "[^[:alpha:]]", "[[:upper:]][[:lower:]]+",
            "^[[:space:]]+", "[[:punct:]]", "[[:alnum:]_]+",
            "[[:xdigit:]]{2}", "x[[:digit:]]"]
    for pat in pats:
        table = compile_dfa(pat)
        got = matched_lines(table, data)
        rx = re.compile(expand_posix_classes(pat).encode())
        want = {i for i, ln in enumerate(data.split(b"\n")[:-1], 1)
                if rx.search(ln)}
        assert got == want, f"{pat!r}: {got ^ want}"
    # expansion is type-preserving and leaves non-class text alone
    assert expand_posix_classes("foo[:x]") == "foo[:x]"
    assert isinstance(expand_posix_classes(b"[[:digit:]]"), bytes)
    # unknown names reject (GNU: "Unknown character class name")
    import pytest as _pytest

    with _pytest.raises(RegexError):
        compile_dfa("[[:junk:]]")
    with _pytest.raises(RegexError):
        expand_posix_classes("[[:junk:]]")


def test_posix_bracket_class_edge_shapes_match_gnu():
    """GNU-verified edge shapes (round-5 review): unterminated '[:',
    the single-bracket [:name:] form, and POSIX classes as range
    endpoints all reject (GNU exit 2), while trailing/leading literal
    dashes next to a class stay valid members."""
    from distributed_grep_tpu.models.dfa import (
        RegexError,
        compile_dfa,
        expand_posix_classes,
        matched_lines,
    )

    rejects = ["[[:d]", "[[:]]", "[:digit:]", "[:junk:]",
               "[[:digit:]-z]", "[a-[:digit:]]"]
    for pat in rejects:
        with pytest.raises(RegexError):
            compile_dfa(pat)
        with pytest.raises(RegexError):
            expand_posix_classes(pat)
    data = b"abc123\n:digt stuff\nxy-z\n"
    valid = {  # pattern -> GNU-verified matched lines on `data`
        "[:a]": {1, 2},         # literal members {':','a'}
        "[a:b]": {1, 2},
        "[[:digit:]-]": {1, 3},  # trailing '-' literal
        "[-[:digit:]]": {1, 3},  # leading '-' literal
    }
    for pat, want in valid.items():
        assert matched_lines(compile_dfa(pat), data) == want, pat
        import re as _re

        rx = _re.compile(expand_posix_classes(pat).encode())
        got = {i for i, ln in enumerate(data.split(b"\n")[:-1], 1)
               if rx.search(ln)}
        assert got == want, f"expander {pat!r}"


def test_posix_collating_and_negated_single_bracket_match_gnu():
    """Round-5 review follow-ups, all GNU-verified: trivial C-locale
    collating forms [.c.] / [=c=] equal the character (and work as
    range endpoints); longer collating names reject ("Invalid
    collation character"); the negated single-bracket form [^:alpha:]
    rejects like the plain one.  The in-class escape dialect stays
    re-style ([a\\-[:digit:]] is an escaped dash member here, a
    range-to-class error in GNU, whose in-class backslash is literal —
    a documented pre-existing dialect choice; parser and expander now
    agree with EACH OTHER on every such input)."""
    import re as _re

    from distributed_grep_tpu.models.dfa import (
        RegexError,
        compile_dfa,
        expand_posix_classes,
        matched_lines,
    )

    data = b"abc123\n:digt stuff\nxy-z\n"
    valid = {
        "[[.x.]]": {3},
        "[[=x=]]": {3},
        "[[.x.]-z]": {3},        # collating symbol as range start
        "[a-[.z.]]": {1, 2, 3},  # ...and as range end
    }
    for pat, want in valid.items():
        assert matched_lines(compile_dfa(pat), data) == want, pat
        rx = _re.compile(expand_posix_classes(pat).encode())
        got = {i for i, ln in enumerate(data.split(b"\n")[:-1], 1)
               if rx.search(ln)}
        assert got == want, f"expander {pat!r}"
    for pat in ("[[.space.]]", "[[.xy.]]", "[[..]]", "[^:alpha:]",
                r"[\^-[:digit:]]"):
        with pytest.raises(RegexError):
            compile_dfa(pat)
        with pytest.raises(RegexError):
            expand_posix_classes(pat)
    # parser/expander agreement on the re-style escaped-dash dialect
    pat = r"[a\-[:digit:]]"
    assert matched_lines(compile_dfa(pat), data) == {1, 3}
    rx = _re.compile(expand_posix_classes(pat).encode())
    assert {i for i, ln in enumerate(data.split(b"\n")[:-1], 1)
            if rx.search(ln)} == {1, 3}

"""utils/trace: jax.profiler integration (SURVEY.md §5 tracing subsystem)."""

import contextlib

from distributed_grep_tpu.utils import trace


def test_disabled_by_default(monkeypatch):
    monkeypatch.delenv("DGREP_TRACE_DIR", raising=False)
    assert not trace.enabled()
    # annotate must be a cheap nullcontext when off
    assert isinstance(trace.annotate("x"), contextlib.nullcontext)
    with trace.job_trace():
        pass
    with trace.step_trace("scan", 0):
        pass


def test_job_trace_writes_profile(tmp_path, monkeypatch):
    d = tmp_path / "trace"
    monkeypatch.setenv("DGREP_TRACE_DIR", str(d))
    assert trace.enabled() and trace.trace_dir() == str(d)

    import jax.numpy as jnp

    with trace.job_trace():
        with trace.annotate("compute"):
            jnp.arange(8).sum().block_until_ready()
        with trace.step_trace("scan", 1):
            jnp.arange(8).prod().block_until_ready()

    # jax.profiler.trace writes plugins/profile/<run>/... under the dir
    assert d.exists() and any(d.rglob("*.xplane.pb"))


def test_job_runs_traced(tmp_path, monkeypatch):
    """End-to-end: a tiny job under tracing produces identical output."""
    from distributed_grep_tpu.runtime.job import run_job
    from distributed_grep_tpu.utils.config import JobConfig

    (tmp_path / "in.txt").write_bytes(b"needle one\nhay\nneedle two\n")
    cfg = dict(
        input_files=[str(tmp_path / "in.txt")],
        n_reduce=2,
        work_dir=str(tmp_path / "work"),
        application="distributed_grep_tpu.apps.grep",
        app_options={"pattern": "needle"},
    )
    plain = run_job(JobConfig(**cfg), n_workers=2)

    monkeypatch.setenv("DGREP_TRACE_DIR", str(tmp_path / "trace"))
    traced = run_job(JobConfig(**cfg), n_workers=2)
    assert traced.results == plain.results
    assert (tmp_path / "trace").exists()

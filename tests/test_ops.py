"""Ops tests: layout, XLA engines, Pallas kernel (interpret mode), stitching,
and the full GrepEngine vs the re oracle — including boundary-spanning
matches and anchored patterns across stripe boundaries (SURVEY.md §4)."""

import re

import os

import numpy as np
import pytest

from distributed_grep_tpu.models.dfa import compile_dfa
from distributed_grep_tpu.models.shift_and import try_compile_shift_and
from distributed_grep_tpu.ops import layout as layout_mod
from distributed_grep_tpu.ops import lines as lines_mod
from distributed_grep_tpu.ops import pallas_scan, scan_jnp
from distributed_grep_tpu.ops.engine import GrepEngine


def oracle_lines(pattern: str, data: bytes, flags=0) -> set[int]:
    return {
        i
        for i, line in enumerate(data.split(b"\n"), start=1)
        if re.search(pattern.encode(), line, flags)
    }


def make_text(n_lines=200, seed=3, inject=()):
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n_lines):
        n = int(rng.integers(0, 80))
        lines.append(bytes(rng.choice(list(b"abcdefgh XYZ.,"), size=n).tolist()))
    for pos, text in inject:
        lines[pos] = text
    return b"\n".join(lines) + b"\n"


# ------------------------------------------------------------------- layout

def test_layout_roundtrip():
    data = bytes(range(256)) * 10
    lay = layout_mod.choose_layout(len(data), target_lanes=16, min_chunk=8)
    arr = layout_mod.to_device_array(data, lay)
    assert arr.shape == (lay.chunk, lay.lanes)
    # arr[c, l] = data[l*chunk + c] for real offsets, NL padding beyond
    for l in (0, lay.lanes - 1):
        for c in (0, lay.chunk - 1):
            off = lay.offset_of(c, l)
            expect = data[off] if off < len(data) else 0x0A
            assert arr[c, l] == expect


def test_layout_multiples():
    lay = layout_mod.choose_layout(10_000, lane_multiple=4096, chunk_multiple=512, min_chunk=512)
    assert lay.lanes % 4096 == 0 and lay.chunk % 512 == 0
    assert lay.padded >= 10_000


# -------------------------------------------------------------- XLA engines

def scan_to_lines(packed, lay, data):
    offsets = lines_mod.match_offsets_from_packed(packed, lay)
    nl = lines_mod.newline_index(data)
    return set(np.unique(lines_mod.line_of_offsets(offsets, nl)).tolist()), offsets


@pytest.mark.parametrize("pattern", ["hello", "h[ae]llo", "[0-9]+", "qu..k"])
def test_dfa_scan_single_lane_exact(pattern):
    """One lane = no boundaries: device offsets must equal the host oracle."""
    data = make_text(50, inject=[(5, b"say hello world"), (9, b"hallo 123 hello")])
    table = compile_dfa(pattern)
    lay = layout_mod.choose_layout(len(data), target_lanes=8, min_chunk=len(data) + 8)
    arr = layout_mod.to_device_array(data, lay)
    packed = scan_jnp.dfa_scan(arr, table)
    from distributed_grep_tpu.models.dfa import reference_scan

    got_lines, offsets = scan_to_lines(packed, lay, data)
    np.testing.assert_array_equal(offsets, reference_scan(table, data))
    assert got_lines == oracle_lines(pattern, data)


def test_shift_and_scan_matches_dfa_scan():
    data = make_text(100, inject=[(3, b"needle in haystack"), (97, b"a needle again")])
    model = try_compile_shift_and("needle")
    table = compile_dfa("needle")
    lay = layout_mod.choose_layout(len(data), target_lanes=8, min_chunk=len(data) + 8)
    arr = layout_mod.to_device_array(data, lay)
    np.testing.assert_array_equal(
        scan_jnp.shift_and_scan(arr, model), scan_jnp.dfa_scan(arr, table)
    )


# ---------------------------------------------------------------- stitching

def test_boundary_spanning_match_is_stitched():
    """Place a match exactly across a stripe boundary; the engine must find it."""
    # lanes=2: boundary at chunk. Build data so 'needle' straddles it.
    filler = b"x" * 95 + b"\n"
    data = filler * 10 + b"nee" + b"dle" + b"y" * 90 + b"\n" + filler * 9
    eng = GrepEngine("needle", target_lanes=2, segment_bytes=1 << 20)
    # force a layout where the boundary falls inside 'needle'
    got = set(eng.scan(data).matched_lines.tolist())
    assert got == oracle_lines("needle", data)


@pytest.mark.parametrize("pattern", ["^hello", "world$", "^only.*line$"])
def test_anchored_patterns_across_boundaries(pattern):
    data = make_text(
        300,
        inject=[
            (0, b"hello starts the file"),
            (150, b"hello mid file"),
            (151, b"ends with world"),
            (152, b"only one matching line"),
            (299, b"hello at end or world"),
        ],
    )
    eng = GrepEngine(pattern, target_lanes=16)
    got = set(eng.scan(data).matched_lines.tolist())
    assert got == oracle_lines(pattern, data), pattern


def test_multi_segment_document():
    data = make_text(500, inject=[(250, b"the needle spans segments maybe")])
    eng = GrepEngine("needle", target_lanes=8, segment_bytes=4096)
    got = set(eng.scan(data).matched_lines.tolist())
    assert got == oracle_lines("needle", data)


# ------------------------------------------------------------------- engine

@pytest.mark.parametrize(
    "pattern", ["hello", "h[ae]llo", "(fox|needle)", "[0-9]{2,4}", "^XYZ", r"\w+$"]
)
def test_engine_vs_oracle(pattern):
    data = make_text(
        400,
        inject=[
            (10, b"hello world"),
            (20, b"hallo 1234"),
            (30, b"the fox and the needle"),
            (40, b"XYZ leads here"),
        ],
    )
    eng = GrepEngine(pattern, target_lanes=32)
    got = set(eng.scan(data).matched_lines.tolist())
    assert got == oracle_lines(pattern, data), pattern


def test_engine_empty_matching_pattern_matches_all_lines():
    data = b"a\n\nbb\n"
    eng = GrepEngine("x*")
    got = eng.scan(data).matched_lines.tolist()
    assert got == [1, 2, 3]


def test_engine_cpu_backend_and_re_fallback():
    data = b"aaa\nbbb\nccc"
    cpu = GrepEngine("b+", backend="cpu")
    assert cpu.mode == "native"
    assert cpu.scan(data).matched_lines.tolist() == [2]
    # newline-consuming pattern -> host re fallback
    fb = GrepEngine(r"a\nb")
    assert fb.mode == "re"
    assert fb.scan(data).matched_lines.tolist() == []


def test_engine_empty_document():
    eng = GrepEngine("x")
    res = eng.scan(b"")
    assert res.matched_lines.size == 0 and res.n_matches == 0


def test_engine_pattern_set_banked_device_scan():
    # Force the pattern set across several automaton banks and check the
    # device path unions per-bank matches exactly (config-5 shape at toy size).
    pats = ["alpha", "bravo", "charlie", "delta", "echo", "foxtrot", "golf"]
    data = make_text(
        120,
        inject=[(5, b"xx alpha yy"), (30, b"golf and echo"), (77, b"charlie!")],
    )
    eng = GrepEngine(patterns=pats, target_lanes=16, max_states_per_bank=16)
    assert len(eng.tables) >= 2
    expected = set()
    for p in pats:
        expected |= oracle_lines(p, data)
    assert set(eng.scan(data).matched_lines.tolist()) == expected
    # native backend takes the same banked union path
    cpu = GrepEngine(patterns=pats, backend="cpu", max_states_per_bank=16)
    assert set(cpu.scan(data).matched_lines.tolist()) == expected


# --------------------------------------------------------------- stride DFA

@pytest.mark.parametrize("pattern", ["hello", "h[ae]llo", "(fox|needle)", "ab+a"])
@pytest.mark.parametrize("k", [2, 4])
def test_stride_scan_matches_per_byte_scan(pattern, k):
    from distributed_grep_tpu.models.dfa import build_stride_table

    data = make_text(
        150, inject=[(3, b"hello fox"), (80, b"needle hallo abba abbba")]
    )
    table = compile_dfa(pattern)
    lay = layout_mod.choose_layout(len(data), target_lanes=32, min_chunk=16)
    assert lay.chunk % k == 0
    arr = layout_mod.to_device_array(data, lay)
    st = build_stride_table(table, k)
    got = np.asarray(scan_jnp.dfa_scan_stride(arr, st))
    want = np.asarray(scan_jnp.dfa_scan(arr, table))
    np.testing.assert_array_equal(got, want, err_msg=f"{pattern} k={k}")


def test_stride_preserves_midstride_newline_attribution():
    # A match ending immediately before a '\n' that sits INSIDE a stride must
    # keep its exact offset (line attribution depends on it).
    from distributed_grep_tpu.models.dfa import build_stride_table

    data = b"xxab\nyyyy\nzzab\nqqqq\n" * 8
    table = compile_dfa("ab")
    lay = layout_mod.choose_layout(len(data), target_lanes=8, min_chunk=8)
    arr = layout_mod.to_device_array(data, lay)
    st = build_stride_table(table, 4)
    packed = np.asarray(scan_jnp.dfa_scan_stride(arr, st))
    offsets = lines_mod.match_offsets_from_packed(packed, lay)
    ref = np.asarray(scan_jnp.dfa_scan(arr, table))
    ref_offsets = lines_mod.match_offsets_from_packed(ref, lay)
    np.testing.assert_array_equal(offsets, ref_offsets)


def test_choose_stride_rules():
    from distributed_grep_tpu.models.dfa import choose_stride

    assert choose_stride(compile_dfa("hello")) in (2, 4)
    assert choose_stride(compile_dfa("hel+o$")) == 1  # '$' needs next-byte
    # huge class count (full alphabet AC bank) -> budget forces stride 1
    from distributed_grep_tpu.models.aho import compile_aho_corasick

    pats = [bytes([b, b]) for b in range(1, 256) if b != 0x0A]
    assert choose_stride(compile_aho_corasick(pats), max_cols=1 << 6) == 1


def test_engine_uses_stride_and_matches_oracle():
    data = make_text(300, inject=[(20, b"the fox ran"), (222, b"a needle!")])
    eng = GrepEngine("(fox|needle)", target_lanes=32)
    kinds = [kind for kind, _ in eng._device_tables()]
    assert kinds == ["stride"]
    assert set(eng.scan(data).matched_lines.tolist()) == oracle_lines(
        "(fox|needle)", data
    )


# ----------------------------------------------------------- pallas kernel

def test_pallas_shift_and_interpret_matches_jnp():
    data = make_text(
        2000, inject=[(5, b"needle one"), (1500, b"and a needle late in the doc")]
    )
    model = try_compile_shift_and("needle")
    lay = layout_mod.choose_layout(
        len(data), target_lanes=4096, min_chunk=512, lane_multiple=4096, chunk_multiple=512
    )
    arr = layout_mod.to_device_array(data, lay)
    got = pallas_scan.shift_and_scan(arr, model, interpret=True)
    want = scan_jnp.shift_and_scan(arr, model)
    np.testing.assert_array_equal(got, want)


def test_pallas_class_pattern_interpret():
    data = make_text(1200, inject=[(7, b"say hallo please"), (900, b"or hello there")])
    model = try_compile_shift_and("h[ae]llo")
    assert pallas_scan.eligible(model)
    lay = layout_mod.choose_layout(
        len(data), target_lanes=4096, min_chunk=512, lane_multiple=4096, chunk_multiple=512
    )
    arr = layout_mod.to_device_array(data, lay)
    got = pallas_scan.shift_and_scan(arr, model, interpret=True)
    want = scan_jnp.shift_and_scan(arr, model)
    np.testing.assert_array_equal(got, want)


def test_pallas_coarse_words_span_contract():
    """Coarse packing contract: a word is nonzero IFF some true match ends
    inside its 32-byte span (no span-level false positives or negatives)."""
    import jax.numpy as jnp

    data = make_text(
        3000, inject=[(3, b"needle a"), (700, b"needleneedle"), (2999, b"needle")]
    )
    model = try_compile_shift_and("needle")
    lay = layout_mod.choose_layout(
        len(data), target_lanes=4096, min_chunk=512, lane_multiple=4096, chunk_multiple=512
    )
    arr = layout_mod.to_device_array(data, lay)
    words = np.asarray(
        pallas_scan.shift_and_scan_words(arr, model, interpret=True, coarse=True)
    )
    # expected spans from the exact per-lane oracle
    from distributed_grep_tpu.models.dfa import compile_dfa, reference_scan

    table = compile_dfa("needle")
    nonzero = set()
    S = lay.lanes // 128
    for lane in range(lay.lanes):
        stripe = bytes(arr[:, lane])
        for off in reference_scan(table, stripe):
            w = (int(off) - 1) // 32
            s_idx = (lane // 4096) * 32 + (lane % 4096) // 128
            nonzero.add((w, s_idx, lane % 128))
    got = {tuple(map(int, c)) for c in np.argwhere(words != 0)}
    assert got == nonzero


def test_pallas_coarse_span_decode():
    """span_starts_from_sparse_words maps nonzero coarse words back to
    document span starts covering every true match end."""
    from distributed_grep_tpu.ops import scan_jnp as sj
    from distributed_grep_tpu.ops import sparse as sparse_mod

    data = make_text(2500, inject=[(11, b"needle x"), (2400, b"tail needle")])
    model = try_compile_shift_and("needle")
    lay = layout_mod.choose_layout(
        len(data), target_lanes=4096, min_chunk=512, lane_multiple=4096, chunk_multiple=512
    )
    arr = layout_mod.to_device_array(data, lay)
    words = pallas_scan.shift_and_scan_words(arr, model, interpret=True, coarse=True)
    idx, _ = sj.sparse_nonzero(words)
    starts = sparse_mod.span_starts_from_sparse_words(np.asarray(idx), lay)
    # every true end offset must fall in some reported span
    true_ends = []
    pos = 0
    while True:
        i = data.find(b"needle", pos)
        if i < 0:
            break
        true_ends.append(i + len(b"needle"))
        pos = i + 1
    spans = [(int(s), int(s) + 32) for s in starts]
    for e in true_ends:
        assert any(a < e <= b for a, b in spans), (e, spans[:5])


def test_engine_shift_and_coarse_interpret(monkeypatch):
    """Engine end-to-end on the coarse pallas path (interpret mode):
    span candidates + host line confirm must be exact."""
    from distributed_grep_tpu.ops import engine as engine_mod

    data = make_text(
        800, inject=[(2, b"xx needle yy"), (400, b"needleneedle"), (799, b"needle")]
    )
    monkeypatch.setattr(pallas_scan, "available", lambda: True)
    orig = pallas_scan.shift_and_scan_words
    monkeypatch.setattr(
        pallas_scan, "shift_and_scan_words",
        lambda arr, model, interpret=None, coarse=False:
            orig(arr, model, interpret=True, coarse=coarse),
    )
    eng = engine_mod.GrepEngine("needle")
    assert eng.mode == "shift_and"
    res = eng.scan(data)
    assert set(res.matched_lines.tolist()) == oracle_lines("needle", data)


def test_engine_shift_and_coarse_dense_native_rescan(monkeypatch):
    """Dense patterns trip the native-rescan path (SPAN_CONFIRM_LINE_LIMIT):
    one C DFA pass over the segment instead of per-line Python confirm —
    output must stay exact."""
    from distributed_grep_tpu.ops import engine as engine_mod

    data = make_text(300, inject=[(5, b"the fox ran")])  # 'e' is everywhere
    monkeypatch.setattr(engine_mod, "SPAN_CONFIRM_LINE_LIMIT", 3)
    monkeypatch.setattr(pallas_scan, "available", lambda: True)
    orig = pallas_scan.shift_and_scan_words
    monkeypatch.setattr(
        pallas_scan, "shift_and_scan_words",
        lambda arr, model, interpret=None, coarse=False:
            orig(arr, model, interpret=True, coarse=coarse),
    )
    eng = engine_mod.GrepEngine("e")
    assert eng.mode == "shift_and"
    res = eng.scan(data)
    assert set(res.matched_lines.tolist()) == oracle_lines("e", data)


# ------------------------------------------------- multi-device round-robin

def test_engine_multi_device_segments():
    """Segments round-robin across all 8 virtual devices; results must be
    identical to single-device scanning, including cross-segment matches."""
    import jax

    data = make_text(800, inject=[(3, b"a needle"), (400, b"needle mid"),
                                  (799, b"needle end")])
    kw = dict(segment_bytes=4096, target_lanes=16)
    multi = GrepEngine("needle", devices="all", **kw)
    single = GrepEngine("needle", **kw)
    assert len(jax.local_devices()) == 8
    rm, rs = multi.scan(data), single.scan(data)
    np.testing.assert_array_equal(rm.matched_lines, rs.matched_lines)
    assert rm.n_matches == rs.n_matches


def test_engine_multi_device_dfa_banks(monkeypatch):
    # '$' accepts now ride the device NFA filter (round 5) and otherwise
    # route native; pin BOTH rescues off so the XLA DFA-bank device path
    # keeps multi-device round-robin coverage.
    monkeypatch.setattr(
        "distributed_grep_tpu.utils.native.native_available", lambda: False
    )
    monkeypatch.setattr(
        "distributed_grep_tpu.models.nfa.compile_device_filter",
        lambda *a, **k: None,
    )
    data = make_text(400, inject=[(5, b"needle here or neet")])
    kw = dict(segment_bytes=4096, target_lanes=16)
    multi = GrepEngine("nee(dle|t)$", devices="all", **kw)
    assert multi.mode == "dfa"  # '$' accept -> DFA path with bank tables
    single = GrepEngine("nee(dle|t)$", **kw)
    np.testing.assert_array_equal(
        multi.scan(data).matched_lines, single.scan(data).matched_lines
    )


def test_anchored_eol_device_path_boundaries(monkeypatch):
    """The XLA DFA device path ('$' accepts) stays pinned for stripe and
    segment boundary behavior even though native routing normally takes
    these patterns (review follow-up: the anchored-pattern tests above
    now exercise the native route on hosts with the lib)."""
    monkeypatch.setattr(
        "distributed_grep_tpu.utils.native.native_available", lambda: False
    )
    monkeypatch.setattr(  # round 5: '$' normally rides the NFA filter now
        "distributed_grep_tpu.models.nfa.compile_device_filter",
        lambda *a, **k: None,
    )
    data = make_text(
        300,
        inject=[(0, b"ends with world"), (150, b"world"), (299, b"world")],
    )
    for pattern in ["world$", r"\w+$"]:
        eng = GrepEngine(pattern, target_lanes=16, segment_bytes=4096)
        assert eng.mode == "dfa", pattern
        got = set(eng.scan(data).matched_lines.tolist())
        assert got == oracle_lines(pattern, data), pattern


def test_engine_dfa_only_pattern_routes_native(monkeypatch):
    """Single patterns outside the device kernel subset with NO usable
    device filter route loudly to the native host scanner instead of the
    ~0.1 GB/s XLA DFA device path — the same policy as FDR-ineligible
    sets.  (Round 5: '$' accepts and long literals normally ride the NFA
    filter first; the native route is the no-filter fallback.)"""
    from distributed_grep_tpu.utils.native import native_available

    if not native_available():
        pytest.skip("native lib unavailable")
    monkeypatch.setattr(
        "distributed_grep_tpu.models.nfa.compile_device_filter",
        lambda *a, **k: None,
    )
    data = make_text(300, inject=[(5, b"ends with world"), (200, b"world")])
    for pattern in ["world$", "x" * 200]:
        eng = GrepEngine(pattern, backend="device")
        assert eng.mode == "native", pattern
        assert set(eng.scan(data).matched_lines.tolist()) == \
            oracle_lines(pattern, data), pattern


def test_grep_tpu_app_devices_all():
    from distributed_grep_tpu.apps import grep_tpu

    from tests.conftest import expand_records

    grep_tpu.configure(pattern="needle", devices="all")
    out = expand_records(grep_tpu.map_fn("f", b"a needle\nnothing\n"))
    assert [kv.key for kv in out] == ["f (line number #1)"]


# ------------------------------------------------------ streaming scan_file

def test_scan_file_matches_scan(tmp_path):
    data = make_text(600, inject=[(0, b"needle first"), (299, b"mid needle"),
                                  (599, b"needle last")])
    p = tmp_path / "doc.txt"
    p.write_bytes(data)
    eng = GrepEngine("needle", segment_bytes=4096, target_lanes=16)
    whole = eng.scan(data)
    got_lines = []
    chunked = eng.scan_file(p, chunk_bytes=1000,
                            emit=lambda ln, b: got_lines.append((ln, b)))
    np.testing.assert_array_equal(chunked.matched_lines, whole.matched_lines)
    assert chunked.bytes_scanned == len(data)
    # emit delivered exact global line numbers + exact line text
    all_lines = data.split(b"\n")
    for ln, b in got_lines:
        assert all_lines[ln - 1] == b
    assert [ln for ln, _ in got_lines] == sorted(whole.matched_lines.tolist())


def test_scan_file_line_longer_than_chunk(tmp_path):
    long_line = b"x" * 5000 + b" needle " + b"y" * 3000
    data = b"short\n" + long_line + b"\nneedle tail\n"
    p = tmp_path / "doc.txt"
    p.write_bytes(data)
    eng = GrepEngine("needle", target_lanes=16)
    res = eng.scan_file(p, chunk_bytes=512)
    assert res.matched_lines.tolist() == [2, 3]


def test_grep_tpu_map_path_fn_matches_map_fn(tmp_path):
    from distributed_grep_tpu.apps import grep_tpu

    data = make_text(300, inject=[(5, b"a needle"), (250, b"needle b")])
    p = tmp_path / "doc.txt"
    p.write_bytes(data)
    grep_tpu.configure(pattern="needle", segment_bytes=4096, target_lanes=16)
    from tests.conftest import expand_records

    want = expand_records(grep_tpu.map_fn(str(p), data))
    got = expand_records(grep_tpu.map_path_fn(str(p), str(p)))
    assert got == want
    # invert falls back to whole-bytes and still agrees
    grep_tpu.configure(pattern="needle", invert=True, segment_bytes=4096,
                       target_lanes=16)
    assert expand_records(grep_tpu.map_path_fn(str(p), str(p))) == \
        expand_records(grep_tpu.map_fn(str(p), data))


def test_scan_re_no_phantom_trailing_line():
    # re-fallback engine (newline-consuming pattern) with an empty-matching
    # regex must not count the segment after a trailing '\n' as a line
    eng = GrepEngine("(a\nb)?")
    assert eng.mode == "re"
    assert eng.scan(b"one\ntwo\n").matched_lines.tolist() == [1, 2]


# ------------------------------------------------- rare-class device filter

def test_filtered_for_device_picks_rare_classes():
    from distributed_grep_tpu.models.shift_and import (
        filtered_for_device, try_compile_shift_and,
    )

    model = try_compile_shift_and("volcano")
    filt = filtered_for_device(model)
    assert filt is not None
    checked = [j for j, r in enumerate(filt.sym_ranges) if r]
    dropped = [j for j, r in enumerate(filt.sym_ranges) if not r]
    assert dropped, "some class must be dropped for a 6-class literal"
    # 'v' (rarest) must be checked; 'o'/'a' (common) should be wildcards
    assert 0 in checked  # position of 'v'
    assert 1 in dropped or 4 in dropped  # 'o' or 'a'
    # wildcard positions match every byte in the b_table
    for j in dropped:
        assert np.all(filt.b_table >> np.uint32(j) & 1 == 1)
    # length/match-bit semantics unchanged
    assert filt.length == model.length and filt.match_bit == model.match_bit


def test_filtered_kernel_superset_and_engine_exact(monkeypatch):
    """Filtered kernel candidates are a superset of true matches; the
    engine path (span confirm) stays line-exact."""
    from distributed_grep_tpu.models.shift_and import (
        filtered_for_device, scan_reference, try_compile_shift_and,
    )
    from distributed_grep_tpu.ops import pallas_scan

    model = try_compile_shift_and("volcano")
    filt = filtered_for_device(model)
    data = make_text(200, inject=[(3, b"a volcano erupts"), (150, b"volcanovolcano")])
    # reference-level: filtered match ends must be a superset
    full_ends = set(scan_reference(model, data).tolist())
    filt_ends = set(scan_reference(filt, data).tolist())
    assert full_ends <= filt_ends
    # engine-level exactness with the pallas interpret path forced on
    from distributed_grep_tpu.ops.engine import GrepEngine

    monkeypatch.setattr(pallas_scan, "available", lambda: True)
    orig = pallas_scan.shift_and_scan_words
    monkeypatch.setattr(
        pallas_scan, "shift_and_scan_words",
        lambda arr, m, interpret=None, coarse=False:
            orig(arr, m, interpret=True, coarse=coarse),
    )
    eng = GrepEngine("volcano", backend="device")
    assert eng._sa_filtered is not None
    got = set(eng.scan(data).matched_lines.tolist())
    want = {i for i, line in enumerate(data.split(b"\n"), 1) if b"volcano" in line}
    assert got == want


def test_scan_file_pattern_set(tmp_path):
    """Streaming scan_file with a literal SET (AC/FDR engines) must equal
    the whole-file scan — pattern sets are first-class on the long-context
    path too."""
    from distributed_grep_tpu.ops.engine import GrepEngine

    rng = np.random.default_rng(5)
    pats = [bytes(rng.integers(97, 123, size=int(rng.integers(4, 8))).tolist())
            for _ in range(50)]
    data = make_text(600, inject=[(5, pats[0] + b" x " + pats[1]),
                                  (300, pats[2] * 2),
                                  (599, b"tail " + pats[3])])
    p = tmp_path / "set.txt"
    p.write_bytes(data)
    eng = GrepEngine(None, patterns=[x.decode() for x in pats])
    whole = eng.scan(data)
    emitted = []
    chunked = eng.scan_file(p, chunk_bytes=2048,
                            emit=lambda ln, line: emitted.append(ln))
    assert chunked.matched_lines.tolist() == whole.matched_lines.tolist()
    assert emitted == whole.matched_lines.tolist()


@pytest.mark.skipif(
    not os.environ.get("DGREP_SOAK"),
    reason="soak: set DGREP_SOAK=1 to stream a ~1 GB corpus",
)
def test_soak_streaming_gigabyte(tmp_path):
    """100 GB-readiness demonstrator at 1 GB scale: scan_file streams a
    corpus much larger than its chunk budget with bounded RSS and exact
    match accounting vs a memmem oracle."""
    import resource

    from distributed_grep_tpu.ops.engine import GrepEngine

    p = tmp_path / "big.bin"
    rng = np.random.default_rng(0)
    needle = b"soaktestneedle"
    with open(p, "wb") as f:
        for _ in range(16):  # 16 x 64 MB = 1 GB
            block = rng.integers(32, 127, size=64_000_000, dtype=np.uint8)
            block[rng.integers(0, block.size, size=block.size // 80)] = 0x0A
            arr = block
            for pos in rng.integers(0, arr.size - 64, size=40):
                arr[pos : pos + len(needle)] = np.frombuffer(needle, np.uint8)
            f.write(arr.tobytes())
    data_oracle_count = 0
    with open(p, "rb") as f:
        prev_tail = b""
        while True:
            blk = f.read(1 << 26)
            if not blk:
                break
            buf = prev_tail + blk
            data_oracle_count += buf.count(needle)
            prev_tail = buf[-(len(needle) - 1):]
    rss_before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    eng = GrepEngine(needle.decode(), backend="cpu", segment_bytes=32 << 20)
    res = eng.scan_file(p, chunk_bytes=32 << 20)
    rss_after = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    # stats[end_offsets] counts occurrences (n_matches is the exact
    # matched-LINE count since round 3); the needle has no self-overlap,
    # so the chunk-wise bytes.count above is an exact occurrence oracle
    assert eng.stats["end_offsets"] == data_oracle_count
    assert res.n_matches == res.matched_lines.size
    # memory stayed bounded: well under half the corpus (chunk is 32 MB;
    # allow slack for allocator noise and the oracle pass above)
    assert rss_after - rss_before < 400_000  # KB


def test_n_matches_equals_matched_lines_across_modes():
    """Round-3 invariant: n_matches is the exact matched-line count on
    EVERY mode/backend — cross-mode numbers are comparable (VERDICT r2
    item 9)."""
    data = make_text(900, inject=[(3, b"a needle b needle"),  # 2 hits, 1 line
                                  (400, b"needle"), (871, b"xx needle")])
    expected = sum(1 for l in data.split(b"\n") if b"needle" in l)
    engines = {
        "shift_and": GrepEngine("needle", segment_bytes=8192, target_lanes=16),
        "shift_and_pallas": GrepEngine("needle", interpret=True),
        "native": GrepEngine("needle", backend="cpu"),
        "fdr": GrepEngine(patterns=["needle", "zebraqq"], interpret=True),
        "dfa_set": GrepEngine(patterns=["needle", "zebraqq"]),
    }
    for name, eng in engines.items():
        res = eng.scan(data)
        assert res.n_matches == res.matched_lines.size, name
        assert res.n_matches == expected, name
    # occurrence telemetry still available where computed exactly
    assert engines["native"].stats["end_offsets"] == data.count(b"needle")


def test_pallas_kernel_failure_falls_back(monkeypatch):
    """A runtime Pallas kernel failure in a non-FDR mode must flip the
    engine to its non-Pallas fallback and rescan exactly (round 3 — the
    net used to protect only FDR)."""
    from distributed_grep_tpu.ops import pallas_scan

    data = make_text(300, inject=[(3, b"a needle"), (200, b"needle b")])
    expected = {
        i for i, ln in enumerate(data.split(b"\n")[:-1], 1) if b"needle" in ln
    }

    def boom(*a, **kw):
        raise RuntimeError("synthetic Mosaic failure")

    monkeypatch.setattr(pallas_scan, "shift_and_scan_words", boom)
    eng = GrepEngine("needle", interpret=True)
    assert eng.mode == "shift_and"
    res = eng.scan(data)
    assert set(res.matched_lines.tolist()) == expected
    assert eng._pallas_broken  # flipped; later scans skip the kernel
    res2 = eng.scan(data)
    assert set(res2.matched_lines.tolist()) == expected


def test_scan_file_pipelined_read_exact_and_stats(tmp_path):
    """VERDICT r3 item 4: the read-ahead thread must leave scan_file
    byte-exact across many chunk boundaries and record the residual
    read stall."""
    from distributed_grep_tpu.ops.engine import GrepEngine

    data = b"".join(
        (b"hello %d\n" % i if i % 3 == 0 else b"line %d\n" % i)
        for i in range(5000)
    )
    f = tmp_path / "f.txt"
    f.write_bytes(data)
    eng = GrepEngine("hello", backend="cpu")
    res = eng.scan_file(str(f), chunk_bytes=4096)  # ~12 chunks
    want = [i + 1 for i in range(5000) if i % 3 == 0]
    assert res.matched_lines.tolist() == want
    assert res.n_matches == len(want)
    assert res.bytes_scanned == len(data)
    assert eng.stats["read_wait_seconds"] >= 0.0


def test_host_scan_chunked_progress_and_exact():
    """Host-routed modes (native memmem, table walk, re fallback) stamp
    progress per newline-aligned piece on large in-memory splits — these
    paths previously emitted no heartbeats at all, so a multi-GB
    whole-bytes map was swept and re-executed forever (round-4 review) —
    and the chunked result is identical to the unchunked scan."""
    data = (b"alpha volcano beta\n" + b"filler line one\n" * 11) * 900
    for pattern in [
        "volcano",          # native memmem route
        "vol[cd]ano",       # native DFA table walk
        r"vol(ca)\1no|volcano",  # backreference -> host re fallback
    ]:
        eng = GrepEngine(pattern, backend="cpu")
        assert eng.mode in ("native", "re")
        ref = eng.scan(data)
        assert ref.n_matches == 900
        stamps: list = []
        eng._HOST_CHUNK = 1 << 15  # shrink pieces so the test corpus chunks
        res = eng.scan(data, progress=lambda grace_s=0.0: stamps.append(grace_s))
        assert res.matched_lines.tolist() == ref.matched_lines.tolist()
        assert res.n_matches == ref.n_matches
        assert len(stamps) >= 4 and set(stamps) == {0.0}, pattern


def test_host_scan_chunked_nullable_eol_exact():
    """The chunked host path composes with scan()'s nullable-at-$ empty-line
    post-processing (the per-piece newline stash must not poison the
    full-buffer recompute)."""
    blk = b"\nx q\n\nq tail\nnoq\n" * 2000
    eng = GrepEngine("q*$", backend="cpu")
    ref = eng.scan(blk)
    eng._HOST_CHUNK = 1 << 13
    stamps: list = []
    res = eng.scan(blk, progress=lambda grace_s=0.0: stamps.append(grace_s))
    assert res.matched_lines.tolist() == ref.matched_lines.tolist()
    assert len(stamps) >= 3


def test_choose_layout_quantized_shapes_bounded():
    """quantize_chunk bounds the number of distinct padded shapes a sweep
    of arbitrary sizes can produce (each distinct shape = one jit compile),
    keeps full 64 MB segments on the grid unchanged, and never pads a tail
    by more than ~1/8 + one chunk_multiple."""
    full = layout_mod.choose_layout(
        64 << 20, min_chunk=512, chunk_multiple=512, quantize_chunk=True)
    assert (full.lanes, full.chunk) == (1024, 65536)  # same as unquantized
    rng = np.random.default_rng(7)
    shapes = set()
    for n in rng.integers(1, 64 << 20, size=500).tolist():
        lay = layout_mod.choose_layout(
            int(n), min_chunk=512, chunk_multiple=512, quantize_chunk=True)
        assert lay.padded >= n
        assert lay.padded <= (n * 9) // 8 + lay.lanes * 512 + lay.lanes
        shapes.add((lay.lanes, lay.chunk))
    assert len(shapes) <= 60  # vs ~hundreds at 512-byte chunk steps


def test_concurrent_scans_nullable_eol_thread_safe():
    """One engine is scanned concurrently by worker slots sharing the app
    module; the nullable-at-$ newline-index stash must be per-thread — a
    shared slot would let thread A consume thread B's index whenever the
    two splits happen to be the same byte length (same-size splits are the
    COMMON case), silently mis-numbering lines."""
    import sys
    import threading

    N = 400
    a = b"\nq z\n" * N      # 2N lines, N of them empty
    b = b"aaaq\n" * N       # N lines, none empty — same byte length
    assert len(a) == len(b)
    eng = GrepEngine("q*$", backend="cpu")  # nullable at EOL: all lines match
    errs: list = []
    go = threading.Barrier(2)

    def scan_loop(data, want_lines):
        go.wait()
        try:
            for _ in range(120):
                res = eng.scan(data)
                if res.n_matches != want_lines:
                    errs.append((want_lines, res.n_matches))
                    return
                # per-thread stats: this thread must see ITS scan's numbers
                if int(eng.stats.get("end_offsets", -1)) < 0:
                    errs.append(("stats", dict(eng.stats)))
                    return
        except Exception as e:  # a crash is as much a failure as a miscount
            errs.append(("raised", repr(e)))

    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-5)  # shake the interleaving
    try:
        ts = [threading.Thread(target=scan_loop, args=(a, 2 * N)),
              threading.Thread(target=scan_loop, args=(b, N))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        sys.setswitchinterval(old)
    assert not errs, errs


def test_small_input_routes_host_on_accelerator():
    """On a real accelerator backend, a sub-threshold input scans on the
    EXACT host engines instead of paying a per-scan device round trip
    (~ms on PCIe, ~100 ms through a tunnel) — the grep -r many-small-files
    regime.  Simulated here by forcing the cached accelerator probe."""
    data = make_text(400, inject=[(7, b"xx volcano yy"), (300, b"volcano")])
    want = sorted(oracle_lines("volcano", data))

    eng = GrepEngine("volcano", backend="device")
    eng._accel_cached = True  # pretend jax.default_backend() is a TPU
    res = eng.scan(data)
    assert res.matched_lines.tolist() == want
    # host-native route: no device telemetry was populated
    assert "scan_wall_seconds" not in eng.stats
    assert eng.stats.get("end_offsets", 0) >= len(want)

    # the DFA-less NFA rescue has no tables: the re loop is the host route
    eng2 = GrepEngine("a[^q]{2,700}z", backend="device")
    assert eng2.mode == "nfa" and not eng2.tables
    eng2._accel_cached = True
    res2 = eng2.scan(b"a!!z ok\nnope\nabcz\n" * 50)
    assert res2.n_matches == 100
    assert "scan_wall_seconds" not in eng2.stats

    # device_min_bytes=0 disables the gate: the device path runs (XLA on
    # the CPU "device" here) and populates its telemetry
    eng3 = GrepEngine("volcano", backend="device", device_min_bytes=0)
    eng3._accel_cached = True
    res3 = eng3.scan(data)
    assert res3.matched_lines.tolist() == want
    assert "scan_wall_seconds" in eng3.stats

    # interpret-mode engines (CI kernel coverage) are never rerouted
    eng4 = GrepEngine("volcano", backend="device", interpret=True)
    eng4._accel_cached = True
    res4 = eng4.scan(data)
    assert res4.matched_lines.tolist() == want
    assert "scan_wall_seconds" in eng4.stats

    # mesh engines are never rerouted either: the sharded path IS their
    # purpose, and dryrun_multichip asserts its psum telemetry on tiny
    # shapes (driver contract)
    from distributed_grep_tpu.parallel.mesh import make_mesh

    eng5 = GrepEngine("volcano", backend="device",
                      mesh=make_mesh((8,), ("data",)))
    eng5._accel_cached = True
    res5 = eng5.scan(data)
    assert res5.matched_lines.tolist() == want
    assert "scan_wall_seconds" in eng5.stats


def test_total_device_failure_degrades_to_host(monkeypatch):
    """When EVERY device route fails (dead device link mid-job — observed
    live when the tunneled chip's transport dropped), the engine degrades
    to the exact host scanners for the rest of its life instead of
    crashing the map task; later scans skip the device entirely."""
    data = make_text(300, inject=[(5, b"xx volcano yy"), (99, b"volcano")])
    want = sorted(oracle_lines("volcano", data))
    eng = GrepEngine("volcano", backend="device", interpret=True)

    calls = {"n": 0}

    def boom(*a, **k):
        calls["n"] += 1
        raise RuntimeError("device link down")

    monkeypatch.setattr(pallas_scan, "shift_and_scan_words", boom)
    monkeypatch.setattr(scan_jnp, "shift_and_scan", boom)
    res = eng.scan(data)
    assert res.matched_lines.tolist() == want
    assert eng._device_broken and calls["n"] == 2  # pallas, then XLA
    res2 = eng.scan(data)
    assert res2.matched_lines.tolist() == want
    assert calls["n"] == 2  # second scan never touched the device


def test_unresponsive_device_routes_host(monkeypatch):
    """A wedged device transport hangs jax's first touch in C (no
    exception); the time-boxed first-touch probe detects it and routes
    the engine to the exact host scanners (live-verified against a
    dropped tunnel: the job completed exactly in probe-wall time
    instead of hanging forever)."""
    data = make_text(300, inject=[(5, b"xx volcano yy"), (80, b"volcano")])
    want = sorted(oracle_lines("volcano", data))
    eng = GrepEngine("volcano", backend="device")
    monkeypatch.setattr(eng, "_device_responsive", lambda: False)
    res = eng.scan(data)
    assert res.matched_lines.tolist() == want
    assert eng._device_broken
    assert eng.stats.get("device_fallback") is True
    # interpret engines never pay the probe (their CPU backend can't wedge)
    eng2 = GrepEngine("volcano", backend="device", interpret=True)
    assert eng2._device_responsive() is True


def test_mid_scan_device_stall_degrades_to_host(monkeypatch):
    """A device that black-holes MID-scan (healthy first touch, then the
    transport hangs instead of erroring — the tunnel outage's second
    phase) trips the DEVICE_STALL_S wall on the collect wait and degrades
    to the exact host engines; the hung collect worker is a DAEMON
    thread (engine._DaemonPool), so it cannot block process exit —
    pinned separately by test_stalled_collect_does_not_block_exit."""
    import time as _t

    from distributed_grep_tpu.ops import engine as engine_mod

    data = make_text(2000, inject=[(5, b"xx volcano yy"), (1500, b"volcano")])
    want = sorted(oracle_lines("volcano", data))
    # several segments so the collect pool exists and the bounded wait runs
    eng = GrepEngine("volcano", backend="device", interpret=True,
                     segment_bytes=1 << 14, target_lanes=8)
    monkeypatch.setattr(engine_mod, "DEVICE_STALL_S", 0.3)

    real = scan_jnp.sparse_nonzero

    def hang(payload):
        _t.sleep(60.0)  # "indefinite": only the stall wall can save us
        return real(payload)

    monkeypatch.setattr(scan_jnp, "sparse_nonzero", hang)
    t0 = _t.monotonic()
    res = eng.scan(data)
    wall = _t.monotonic() - t0
    assert res.matched_lines.tolist() == want
    assert eng._device_broken
    assert eng.stats.get("device_fallback") is True
    # interpret-mode dispatch dominates the wall; the proof is that we
    # did NOT sit out the 60 s hang (nor the shutdown join on it)
    assert wall < 30
    monkeypatch.setattr(scan_jnp, "sparse_nonzero", real)
    res2 = eng.scan(data)  # stays on host, no device wait at all
    assert res2.matched_lines.tolist() == want


def test_stalled_collect_does_not_block_exit():
    """Interpreter exit must not join a collect worker blocked in a dead
    device transport: stdlib executor workers are non-daemon and joined
    by threading._shutdown at exit (verified: registry surgery does NOT
    avoid that join), so the engine uses daemon workers.  A subprocess
    triggers the stall degrade with a worker still sleeping 120 s and
    must exit promptly with the exact result."""
    import subprocess
    import sys as _sys
    import time as _t

    code = r"""
import os, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax, jax._src.xla_bridge as _xb
_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")
from distributed_grep_tpu.ops import engine as engine_mod, scan_jnp
from distributed_grep_tpu.ops.engine import GrepEngine
engine_mod.DEVICE_STALL_S = 0.3
real = scan_jnp.sparse_nonzero
def hang(payload):
    time.sleep(120.0)
    return real(payload)
scan_jnp.sparse_nonzero = hang
data = (b"filler line\n" * 50 + b"xx volcano yy\n") * 40
eng = GrepEngine("volcano", backend="device", interpret=True,
                 segment_bytes=1 << 13, target_lanes=8)
res = eng.scan(data)
assert eng._device_broken
print("N", res.n_matches, flush=True)
"""
    t0 = _t.monotonic()
    out = subprocess.run(
        [_sys.executable, "-c", code], capture_output=True, text=True,
        timeout=90, env={**os.environ, "PYTHONPATH": ""},
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    wall = _t.monotonic() - t0
    assert out.returncode == 0, out.stderr[-2000:]
    assert "N 40" in out.stdout
    assert wall < 60  # exited without joining the 120 s-sleeping worker


def test_redos_pattern_immune():
    """Catastrophic-backtracking patterns (the (a+)+b ReDoS classic) are
    linear for the engine's automata scan on every backend — the same
    pattern hangs a backtracking matcher exponentially (observed live: a
    fuzz draw's nested quantifiers hung the Python `re` oracle >50 min
    while the engine scanned 64 KB in 0.16 s).  No `re` call here, by
    construction."""
    import time as _t

    evil = "(a+)+b"
    data = (b"a" * 46 + b"\n") * 400 + b"aaab tail\n" + (b"a" * 46 + b"\n") * 400
    for backend in ("cpu", "device"):
        eng = GrepEngine(evil, backend=backend)
        # enforce the automata route: a regression to the re fallback
        # would HANG here for hours instead of failing
        assert eng.mode in ("nfa", "native"), eng.mode
        t0 = _t.monotonic()
        res = eng.scan(data)
        assert _t.monotonic() - t0 < 20  # linear, not exponential
        assert res.matched_lines.tolist() == [401], backend


def test_deterministic_device_failure_is_permanent_and_local(monkeypatch):
    """A generic exhausted-routes failure may be a per-pattern defect on a
    HEALTHY device: it must demote only its own engine — permanently —
    without poisoning the process-global probe verdict.  Otherwise one bad
    pattern demotes every new engine in the process, then flip-flops each
    retry window (deep probe succeeds, the engine un-demotes, fails
    deterministically again, re-poisons — round-4 review finding)."""
    import time as _t

    from distributed_grep_tpu.ops import engine as engine_mod

    data = make_text(300, inject=[(5, b"xx volcano yy"), (99, b"volcano")])
    want = sorted(oracle_lines("volcano", data))
    monkeypatch.setattr(engine_mod, "_probe_device_blocking", lambda: True)

    calls = {"n": 0}

    def boom(*a, **k):
        calls["n"] += 1
        raise RuntimeError("per-pattern defect")

    monkeypatch.setattr(pallas_scan, "shift_and_scan_words", boom)
    monkeypatch.setattr(scan_jnp, "shift_and_scan", boom)
    eng = GrepEngine("volcano", backend="device")
    res = eng.scan(data)
    assert res.matched_lines.tolist() == want
    assert eng._device_broken and eng._device_demotion_permanent
    with engine_mod._device_probe_lock:
        # the shared verdict was NOT poisoned by the generic failure
        assert engine_mod._device_probe_state["verdict"] is not False

    # an unrelated engine in the same process keeps its device path
    # (NFA mode — the booms above patch only the shift-and kernels)
    eng2 = GrepEngine("volc+ano", backend="device", interpret=True)
    assert eng2.mode == "nfa", eng2.mode
    res2 = eng2.scan(data)
    assert res2.matched_lines.tolist() == want
    assert not eng2._device_broken

    # elapsed retry window + responsive device: the deterministic demotion
    # does NOT un-demote (no flip-flop), and never touches the device again
    with engine_mod._device_probe_lock:
        engine_mod._device_probe_state.update(
            verdict=False, at=_t.monotonic() - engine_mod.DEVICE_RETRY_S - 1
        )
    n = calls["n"]
    res3 = eng.scan(data)
    assert res3.matched_lines.tolist() == want
    assert eng._device_broken and calls["n"] == n


def test_degraded_engine_retries_device_after_window(monkeypatch):
    """A host-degraded engine wins the device back once the shared probe
    verdict turns True again (deep re-probe at most once per
    DEVICE_RETRY_S window, process-wide) — kernel-level flags reset too,
    since their failures were co-temporal with the outage."""
    import time as _t

    from distributed_grep_tpu.ops import engine as engine_mod

    data = make_text(300, inject=[(5, b"xx volcano yy")])
    want = sorted(oracle_lines("volcano", data))
    probes = {"n": 0}

    def dead_probe():
        probes["n"] += 1
        return False

    monkeypatch.setattr(engine_mod, "_probe_device_blocking", dead_probe)
    eng = GrepEngine("volcano", backend="device")
    res = eng.scan(data)
    assert eng._device_broken and res.matched_lines.tolist() == want
    assert probes["n"] == 1

    # inside the window: the cached False answers instantly, no re-probe
    res2 = eng.scan(data)
    assert eng._device_broken and "device_fallback" in eng.stats
    assert probes["n"] == 1

    # window elapsed, still dead: exactly ONE shared re-probe fires
    with engine_mod._device_probe_lock:
        engine_mod._device_probe_state["at"] = (
            _t.monotonic() - engine_mod.DEVICE_RETRY_S - 1
        )
    res3 = eng.scan(data)
    assert eng._device_broken and res3.matched_lines.tolist() == want
    assert probes["n"] == 2

    # window elapsed and the device recovered: back on the device path
    monkeypatch.setattr(
        engine_mod, "_probe_device_blocking", lambda: True
    )
    with engine_mod._device_probe_lock:
        engine_mod._device_probe_state["at"] = (
            _t.monotonic() - engine_mod.DEVICE_RETRY_S - 1
        )
    res4 = eng.scan(data)
    assert not eng._device_broken
    assert res4.matched_lines.tolist() == want
    assert "scan_wall_seconds" in eng.stats  # the device path ran


def test_scan_file_stop_after_match(tmp_path):
    """GNU grep -q/-l stop reading at the first match; the streaming scan
    honors that at chunk granularity (presence_only app contract): a
    match in the first chunk must end the read there, and the default
    full scan must be unaffected."""
    p = tmp_path / "big.txt"
    with open(p, "wb") as f:
        f.write(b"hit early\n")
        for _ in range(200):
            f.write(b"filler line of no consequence\n" * 100)
    size = p.stat().st_size
    eng = GrepEngine("early", backend="cpu")
    res = eng.scan_file(str(p), chunk_bytes=1 << 16, stop_after_match=True)
    assert res.n_matches == 1 and res.matched_lines.tolist() == [1]
    assert res.bytes_scanned < size // 4  # stopped after the first chunk
    full = eng.scan_file(str(p), chunk_bytes=1 << 16)
    assert full.bytes_scanned == size and full.n_matches == 1


def test_scan_file_stop_predicate_confirmed_presence(tmp_path):
    """-w/-x presence: the engine's own match bit is pre-confirm, so the
    caller's stop predicate — not stop_after_match — decides when
    truthiness is settled.  A chunk full of UNconfirmed candidates must
    not end the stream; the first confirmed line must."""
    p = tmp_path / "big.txt"
    with open(p, "wb") as f:
        # the pattern only ever appears INSIDE a longer word -> every
        # engine candidate fails the -w confirm
        f.write(b"xxwordxmatchyy unconfirmed\n" * 50)
        f.write(b"a xwordxmatch9 b\n")
        f.write(b"filler\n" * 5000)
        f.write(b"tail candidate xxwordxmatch0\n")
    import re as _re

    from distributed_grep_tpu.apps.grep import wrap_mode
    confirm = _re.compile(wrap_mode(rb"wordxmatch", "word"))
    eng = GrepEngine("wordxmatch", backend="cpu")
    hits = []

    def emit(ln, line):
        if confirm.search(line):
            hits.append(ln)

    res = eng.scan_file(str(p), chunk_bytes=1 << 10, emit=emit,
                        stop=lambda: len(hits) > 0)
    # candidates existed from chunk 1, but nothing ever confirms -> the
    # stream must have run to the LAST candidate without stopping early
    assert hits == [] and res.bytes_scanned == p.stat().st_size

    p2 = tmp_path / "big2.txt"
    with open(p2, "wb") as f:
        f.write(b"the wordxmatch stands alone here\n")  # space-bounded:
        f.write(b"filler\n" * 5000)                     # confirms under -w
    hits2 = []

    def emit2(ln, line):
        if confirm.search(line):
            hits2.append(ln)

    res2 = eng.scan_file(str(p2), chunk_bytes=1 << 10, emit=emit2,
                         stop=lambda: len(hits2) > 0)
    assert hits2 == [1]
    assert res2.bytes_scanned < p2.stat().st_size // 4  # stopped early

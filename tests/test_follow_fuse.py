"""Fused follow tier (round 21, runtime/follow.py FollowGroup*): one
suffix scan per (file, wake) serves K standing queries.  Pins fused ==
solo byte identity across query families (anchors, ^$, re-fallback,
pattern sets, ignore_case), counter flatness in K, join-mid-stream
catch-up, leave/cancel shrink, truncation demotion isolation, per-member
journal-fault demotion, the DGREP_FOLLOW_FUSE=0 true-no-op pin, the
/status group rows + dgrep top rendering, fuse:wake explain routing, and
the SIGKILL-mid-wake daemon-restart chaos leg.

Standalone: ``python -m pytest tests/test_follow_fuse.py -q`` (CPU-only).
Marker: ``follow`` (rides the round-17 tier's marker).
"""

from __future__ import annotations

import json
import time
from pathlib import Path

import pytest

from distributed_grep_tpu.ops.engine import GrepEngine
from distributed_grep_tpu.runtime.follow import (
    FollowGroupRegistry,
    FollowRunner,
    follow_counters,
    follow_counters_clear,
    follow_fused_counters,
    follow_fused_counters_clear,
)
from distributed_grep_tpu.utils.config import JobConfig

pytestmark = pytest.mark.follow


@pytest.fixture(autouse=True)
def _no_calibrate(monkeypatch):
    monkeypatch.setenv("DGREP_NO_CALIBRATE", "1")


# ---------------------------------------------------------------- helpers
def _mk_cfg(path, work_dir: str, **opts) -> JobConfig:
    app_options = {"backend": "cpu", **opts}
    if "pattern" not in app_options and "patterns" not in app_options:
        app_options["pattern"] = "hello"
    files = path if isinstance(path, list) else [str(path)]
    return JobConfig(
        input_files=[str(f) for f in files],
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options=app_options,
        work_dir=work_dir,
        follow=True,
    )


def _mk_runner(tmp_path, tag: str, log_path, reg=None, **opts):
    wd = tmp_path / f"wd-{tag}"
    cfg = _mk_cfg(log_path, str(wd), **opts)
    return FollowRunner(f"job-{tag}", cfg, wd, groups=reg)


def _records(runner) -> list[dict]:
    recs, _next, _dropped = runner.ring.read_since(0, timeout=0)
    return recs


def _lt(recs: list[dict]) -> list[tuple[int, str]]:
    return [(r["line"], r["text"]) for r in recs if "text" in r]


def _oracle(opts: dict, data: bytes) -> list[tuple[int, str]]:
    """(line, text) a one-shot scan over the final bytes selects — the
    contract each tenant's stream must equal regardless of routing."""
    from distributed_grep_tpu.ops import lines as lines_mod

    kw = {"backend": "cpu", "ignore_case": bool(opts.get("ignore_case"))}
    if opts.get("patterns"):
        kw["patterns"] = list(opts["patterns"])
    else:
        kw["pattern"] = opts["pattern"]
    eng = GrepEngine(**kw)
    res = eng.scan(data)
    nl = lines_mod.newline_index(data)
    out = []
    for ln in res.matched_lines.tolist():
        s, e = lines_mod.line_span(nl, int(ln), len(data))
        out.append((int(ln), data[s:e].decode("utf-8", "surrogateescape")))
    return out


# Append stages exercising the boundary shapes round 17 pinned: catch-up
# over existing content, a mid-line split + its completion, an exact-line
# append, an empty append, an empty LINE, and an unterminated tail.
STAGES = [
    b"hello start\nhallo there\nmiss\n",
    b"partial hel",
    b"lo end\nab zz q volcano needle\n",
    b"hello exactly one helloo line\n",
    b"",
    b"\nends with HELLO\n",
    b"tail hello no newline",
]

QUERIES = [
    ("literal", {"pattern": "hello"}),
    ("nfa", {"pattern": "h[ae]llo+"}),
    ("anchor_start", {"pattern": "^hello"}),
    ("anchor_end", {"pattern": "hello$"}),
    ("empty_line", {"pattern": "^$"}),
    ("pairset", {"patterns": ["ab", "zz", "q"]}),
    ("set", {"patterns": ["hello", "needle"]}),
    ("re_fallback", {"pattern": "hello(?! tail)"}),
    ("ignore_case", {"pattern": "HELLO", "ignore_case": True}),
]


@pytest.mark.parametrize("label,opts", QUERIES, ids=[q[0] for q in QUERIES])
def test_fused_equals_solo_and_oracle(tmp_path, label, opts):
    """The load-bearing identity: a tenant inside a fused group streams
    byte-identically to its own solo runner AND to the one-shot oracle,
    for every union-hostable query shape — the co-tenant's query never
    bleeds into the confirm."""
    co = {"pattern": "volcano"}
    solo_log = tmp_path / "solo.log"
    fused_log = tmp_path / "fused.log"
    solo_log.write_bytes(b"")
    fused_log.write_bytes(b"")

    solo = [_mk_runner(tmp_path, f"s{i}", solo_log, None, **o)
            for i, o in enumerate((opts, co))]
    reg = FollowGroupRegistry(start_threads=False, auto_solo=False)
    fused = [_mk_runner(tmp_path, f"f{i}", fused_log, reg, **o)
             for i, o in enumerate((opts, co))]
    for r in fused:
        assert reg.adopt(r)
    (group,) = reg._groups.values()

    for stage in STAGES:
        for p in (solo_log, fused_log):
            with open(p, "ab") as f:
                f.write(stage)
        for r in solo:
            r.wake_once()
        group.wake_once()

    final = b"".join(STAGES)
    terminated = final[: final.rfind(b"\n") + 1]
    for s, f, o in zip(solo, fused, (opts, co)):
        assert _lt(_records(f)) == _lt(_records(s)) == _oracle(o, terminated)
        assert f.fused
    for r in solo + fused:
        r.close()


def test_counters_flat_in_k(tmp_path):
    """The perf contract the benchmark receipts: K fused tenants cost ONE
    wake + one suffix read per (file, wake) — base counters flat in K,
    the saved counter pricing the (K-1) avoided re-scans — while K solo
    runners pay K of everything."""
    K = 4
    log = tmp_path / "app.log"
    log.write_bytes(b"")
    reg = FollowGroupRegistry(start_threads=False, auto_solo=False)
    runners = [
        _mk_runner(tmp_path, f"k{i}", log, reg, pattern=f"t{i}mark")
        for i in range(K)
    ]
    for r in runners:
        assert reg.adopt(r)
    (group,) = reg._groups.values()
    stages = [b"".join(b"t%dmark line %d\n" % (i, s) for i in range(K))
              for s in range(3)]
    for stage in stages:
        with open(log, "ab") as f:
            f.write(stage)
        group.wake_once()
    total = sum(len(s) for s in stages)
    base = follow_counters()
    assert base["follow_wakes"] == 3  # one per group wake, NOT K
    assert base["suffix_bytes_scanned"] == total  # each byte read ONCE
    fstats = follow_fused_counters()
    assert fstats["follow_fused_queries"] == K
    assert fstats["follow_fused_wakes"] == 3
    assert fstats["follow_suffix_bytes_saved"] == total * (K - 1)
    row = group.status()
    assert row["members"] == K and row["files"] == 1
    assert row["wakes"] == 3 and row["wake_lag_s"] >= 0.0
    for r in runners:
        assert _lt(_records(r)) == [(s * K + i + 1, f"t{i}mark line {s}")
                                    for i, s in [(int(r.job_id[5:]), st)
                                                 for st in range(3)]]
        r.close()

    # the solo control: K independent runners re-read everything K times
    follow_counters_clear()
    follow_fused_counters_clear()
    log2 = tmp_path / "solo.log"
    log2.write_bytes(b"")
    solos = [_mk_runner(tmp_path, f"q{i}", log2, None, pattern=f"t{i}mark")
             for i in range(K)]
    for stage in stages:
        with open(log2, "ab") as f:
            f.write(stage)
        for r in solos:
            r.wake_once()
    base = follow_counters()
    assert base["follow_wakes"] == 3 * K
    assert base["suffix_bytes_scanned"] == total * K
    assert follow_fused_counters() == {}
    for r in solos:
        r.close()


def test_join_mid_stream_catches_up_then_fuses(tmp_path):
    """A tenant joining a live group solo-catches-up from its durable
    cursor to the group cursor on the group thread, then fuses: its
    stream equals the oracle over everything, the incumbent sees no
    duplicate, and subsequent appends ride the shared scan."""
    log = tmp_path / "app.log"
    log.write_bytes(b"hello one\nmiss\n")
    reg = FollowGroupRegistry(start_threads=False, auto_solo=False)
    r1 = _mk_runner(tmp_path, "a", log, reg, pattern="hello")
    assert reg.adopt(r1)
    (group,) = reg._groups.values()
    group.wake_once()
    with open(log, "ab") as f:
        f.write(b"hello two\n")
    group.wake_once()
    assert _lt(_records(r1)) == [(1, "hello one"), (3, "hello two")]

    r2 = _mk_runner(tmp_path, "b", log, reg, pattern="hello")
    assert reg.adopt(r2)
    assert not r2.fused  # catching up until its cursors align
    group.wake_once()  # catch-up: r2 replays 0 -> group cursor, solo path
    assert _lt(_records(r2)) == [(1, "hello one"), (3, "hello two")]

    with open(log, "ab") as f:
        f.write(b"hello three\n")
    group.wake_once()  # aligned now: r2 fuses, then rides the shared scan
    assert r2.fused
    want = [(1, "hello one"), (3, "hello two"), (4, "hello three")]
    assert _lt(_records(r1)) == want
    assert _lt(_records(r2)) == want  # no dup from the catch-up boundary
    # the group consumed "hello three\n" once for both
    assert follow_fused_counters()["follow_suffix_bytes_saved"] == len(
        b"hello three\n")
    for r in (r1, r2):
        r.close()


def test_leave_shrinks_group_and_last_close_retires_it(tmp_path):
    log = tmp_path / "app.log"
    log.write_bytes(b"hello a\n")
    reg = FollowGroupRegistry(start_threads=False, auto_solo=False)
    rs = [_mk_runner(tmp_path, f"m{i}", log, reg, pattern="hello")
          for i in range(3)]
    for r in rs:
        assert reg.adopt(r)
    (group,) = reg._groups.values()
    group.wake_once()
    rs[1].close()  # cancel mid-stream: discard detaches under the wake lock
    assert len(group.members()) == 2
    with open(log, "ab") as f:
        f.write(b"hello b\n")
    group.wake_once()
    want = [(1, "hello a"), (2, "hello b")]
    assert _lt(_records(rs[0])) == _lt(_records(rs[2])) == want
    assert _lt(_records(rs[1])) == [(1, "hello a")]  # stopped at leave
    rs[0].close()
    rs[2].close()
    assert reg._groups == {}  # last member's discard retired the group


def test_truncation_demotes_group_and_members_stay_exact(tmp_path):
    """Truncation/replacement falls the watching group's members back to
    their solo runners — each re-detects the reset against its OWN
    durable cursor and re-emits exactly (the solo-tested reset path) —
    while an unrelated group on another file keeps fusing untouched."""
    loga = tmp_path / "a.log"
    logb = tmp_path / "b.log"
    loga.write_bytes(b"hello a1\nhello a2\n")
    logb.write_bytes(b"hello b1\n")
    reg = FollowGroupRegistry(start_threads=False, auto_solo=False)
    ra = [_mk_runner(tmp_path, f"a{i}", loga, reg, pattern="hello")
          for i in range(2)]
    rb = [_mk_runner(tmp_path, f"b{i}", logb, reg, pattern="hello")
          for i in range(2)]
    for r in ra + rb:
        assert reg.adopt(r)
    assert len(reg._groups) == 2
    ga = next(g for g in reg._groups.values()
              if str(loga) in next(iter(g.cursors)))
    gb = next(g for g in reg._groups.values() if g is not ga)
    ga.wake_once()
    gb.wake_once()

    new = b"hello cut\n"  # strictly shorter: size below the group cursor
    loga.write_bytes(new)
    ga.wake_once()  # detects truncation: demotes ALL of group A to solo
    assert all(not r.fused for r in ra)
    assert len(reg._groups) == 1 and gb.key in reg._groups
    for r in ra:  # drive the solo runners (auto_solo=False left them idle)
        r.wake_once()
        recs = _records(r)
        assert {"file": str(loga), "reset": True} in [
            {k: v for k, v in x.items() if k != "seq"} for x in recs
        ]
        assert _lt(recs) == [(1, "hello a1"), (2, "hello a2"),
                             (1, "hello cut")]
    # the OTHER group never noticed: still fused, still shared-scanning
    with open(logb, "ab") as f:
        f.write(b"hello b2\n")
    gb.wake_once()
    for r in rb:
        assert r.fused
        assert _lt(_records(r)) == [(1, "hello b1"), (2, "hello b2")]
    for r in ra + rb:
        r.close()


def test_commit_failure_demotes_only_that_member(tmp_path, monkeypatch):
    """A journal fault on ONE member's fused commit rolls that member's
    cursor back and demotes it alone; the co-tenant keeps fusing.  The
    demoted runner's next solo wake re-emits exactly once — fusion is
    never a correctness dependency."""
    log = tmp_path / "app.log"
    log.write_bytes(b"hello x\n")
    reg = FollowGroupRegistry(start_threads=False, auto_solo=False)
    r1 = _mk_runner(tmp_path, "ok", log, reg, pattern="hello")
    r2 = _mk_runner(tmp_path, "bad", log, reg, pattern="hello")
    assert reg.adopt(r1) and reg.adopt(r2)
    (group,) = reg._groups.values()

    orig = r2._log.record_wake

    def failing(*a, **kw):
        raise OSError("disk full")

    monkeypatch.setattr(r2._log, "record_wake", failing)
    group.wake_once()
    assert _lt(_records(r1)) == [(1, "hello x")]  # co-tenant unaffected
    assert _records(r2) == []  # nothing published for the failed journal
    assert r1.fused and not r2.fused
    assert [m.runner for m in group.members()] == [r1]

    monkeypatch.setattr(r2._log, "record_wake", orig)
    assert r2.wake_once() == 1  # rolled-back cursor: solo re-emits once
    assert _lt(_records(r2)) == [(1, "hello x")]
    with open(log, "ab") as f:
        f.write(b"hello y\n")
    group.wake_once()
    r2.wake_once()
    assert _lt(_records(r1)) == _lt(_records(r2)) == [
        (1, "hello x"), (2, "hello y")
    ]
    r1.close()
    r2.close()


# ------------------------------------------------------------- service
def _drain(svc, jid, want: int, deadline_s: float = 15.0) -> list[dict]:
    out: list[dict] = []
    cursor = 0
    deadline = time.monotonic() + deadline_s
    while len(out) < want:
        assert time.monotonic() < deadline, (jid, out)
        page = svc.job_stream(jid, cursor=cursor, timeout=0.5)
        out.extend(page["records"])
        cursor = page["next"]
    return out


def test_service_fuses_and_status_exposes_groups(tmp_path, monkeypatch):
    monkeypatch.setenv("DGREP_FOLLOW_POLL_S", "0.05")
    from distributed_grep_tpu.runtime.service import GrepService

    log = tmp_path / "app.log"
    log.write_bytes(b"hello t0x\nhello t1x\n")
    svc = GrepService(work_root=tmp_path / "svc")
    try:
        jids = [svc.submit(_mk_cfg(log, "ignored", pattern=f"t{k}x"))
                for k in range(2)]
        pages = [_drain(svc, jid, 1) for jid in jids]
        for k, recs in enumerate(pages):
            assert _lt(recs) == [(k + 1, f"hello t{k}x")]
        st = svc.status()
        fol = st["follow"]
        assert fol["follow_fused_queries"] == 2
        (row,) = fol["groups"]
        assert row["members"] == 2 and sorted(row["jobs"]) == sorted(jids)
        assert "wake_lag_s" in row and row["wake_lag_s"] >= 0.0
        # dgrep top renders the group row (the round-21 small fix)
        from distributed_grep_tpu.__main__ import _render_top

        text = _render_top({"x": st}, "x", {})
        assert "group [" in text and "wake_lag_s=" in text
        # runner rows carry the fused flag (a joiner flips it one wake
        # after its catch-up aligns — poll briefly)
        deadline = time.monotonic() + 10.0
        while not all(svc.job_status(j)["follow"].get("fused")
                      for j in jids):
            assert time.monotonic() < deadline
            time.sleep(0.05)
    finally:
        svc.stop()


def test_follow_fuse_off_is_true_noop(tmp_path, monkeypatch):
    """DGREP_FOLLOW_FUSE=0 pin: no group registry is ever built, runners
    ride the solo path, /status keeps the round-17 follow view byte
    shape (no fused keys, no groups key), and the streamed records equal
    the fused daemon's."""
    monkeypatch.setenv("DGREP_FOLLOW_POLL_S", "0.05")
    from distributed_grep_tpu.runtime.service import GrepService

    log = tmp_path / "app.log"
    log.write_bytes(b"hello t0x\nhello t1x\n")

    monkeypatch.setenv("DGREP_FOLLOW_FUSE", "0")
    svc = GrepService(work_root=tmp_path / "svc-off")
    try:
        jids = [svc.submit(_mk_cfg(log, "ignored", pattern=f"t{k}x"))
                for k in range(2)]
        off_pages = [_lt(_drain(svc, jid, 1)) for jid in jids]
        assert svc._follow_groups is None  # never constructed
        fol = svc.status()["follow"]
        assert "groups" not in fol
        assert not any(k.startswith("follow_fused") for k in fol)
        assert follow_fused_counters() == {}
        assert not any(svc.job_status(j)["follow"].get("fused")
                       for j in jids)
    finally:
        svc.stop()

    monkeypatch.setenv("DGREP_FOLLOW_FUSE", "1")
    svc2 = GrepService(work_root=tmp_path / "svc-on")
    try:
        jids = [svc2.submit(_mk_cfg(log, "ignored", pattern=f"t{k}x"))
                for k in range(2)]
        on_pages = [_lt(_drain(svc2, jid, 1)) for jid in jids]
        assert on_pages == off_pages  # identical streams either way
    finally:
        svc2.stop()


def test_ineligible_configs_stay_solo(tmp_path):
    """Group-ineligible shapes never adopt: count/presence modes (no
    fusion_key), approx, and two spellings of one file — each runs the
    pre-round-21 solo runner."""
    log = tmp_path / "app.log"
    log.write_bytes(b"hello\n")
    reg = FollowGroupRegistry(start_threads=False, auto_solo=False)
    shapes = [
        {"pattern": "hello", "count_only": True},
        {"pattern": "hello", "max_errors": 1},
        {"pattern": ""},
    ]
    for i, opts in enumerate(shapes):
        r = _mk_runner(tmp_path, f"i{i}", log, reg, **opts)
        assert not reg.adopt(r)
        r.close()
    alias = tmp_path / "app.log"
    dup = _mk_runner(tmp_path, "dup", [log, alias], reg, pattern="hello")
    assert not reg.adopt(dup)
    dup.close()
    assert reg._groups == {}


def test_fuse_wake_instants_feed_explain_route(tmp_path):
    """Satellite: fused wakes write ``fuse:wake`` into each member's
    events.jsonl; dgrep explain's follow section reads them into the
    fused/solo/mixed route verdict."""
    from distributed_grep_tpu.runtime.explain import summarize_events
    from distributed_grep_tpu.utils import spans as spans_mod

    log = tmp_path / "app.log"
    log.write_bytes(b"hello e\n")
    reg = FollowGroupRegistry(start_threads=False, auto_solo=False)
    runners = []
    for i in range(2):
        wd = tmp_path / f"wd-e{i}"
        wd.mkdir()
        ev = spans_mod.EventLog(wd / spans_mod.EventLog.FILENAME, fresh=True)
        cfg = _mk_cfg(log, str(wd), pattern="hello")
        r = FollowRunner(f"job-e{i}", cfg, wd, event_log=ev, groups=reg)
        assert reg.adopt(r)
        runners.append((r, ev, wd))
    (group,) = reg._groups.values()
    group.wake_once()
    for r, ev, wd in runners:
        r.close()
        ev.close()
        events = [json.loads(ln) for ln in
                  (wd / spans_mod.EventLog.FILENAME).read_text().splitlines()]
        assert any(e.get("name") == "fuse:wake" and e.get("job") == r.job_id
                   for e in events)
        rep = summarize_events(events)
        assert rep["follow"]["route"] == "fused"
        assert rep["follow"]["fused_wakes"] == 1
        assert rep["follow"]["records"] == 1


def test_fused_counters_ride_engine_stats_tail(tmp_path):
    """Telemetry contract: the fused counters merge into engine.stats
    after a scan (heartbeat piggyback surface), nonzero-only."""
    log = tmp_path / "app.log"
    log.write_bytes(b"hello s\n")
    reg = FollowGroupRegistry(start_threads=False, auto_solo=False)
    rs = [_mk_runner(tmp_path, f"t{i}", log, reg, pattern="hello")
          for i in range(2)]
    for r in rs:
        assert reg.adopt(r)
    (group,) = reg._groups.values()
    group.wake_once()
    eng = GrepEngine("hello", backend="cpu")
    eng.scan(b"hello again\n")
    assert eng.stats.get("follow_fused_queries") == 2
    assert eng.stats.get("follow_fused_wakes") == 1
    for r in rs:
        r.close()


# ------------------------------------------------------- chaos (restart)
def test_daemon_sigkill_mid_wake_resumes_every_member(tmp_path):
    """The round-21 chaos leg: SIGKILL the daemon while a fused group
    streams K tenants, append during the outage, restart on the same
    work root — every member's durable cursor resumes; the union of
    records across both daemon lives equals each tenant's oracle with
    no duplicate seq and no lost line."""
    import sys

    sys.path.insert(0, str(Path(__file__).parent))
    import service_proc

    K = 2
    log = tmp_path / "app.log"
    log.write_bytes(b"")
    proc = service_proc.ServiceProc(
        tmp_path / "root", workers=0,
        env={"DGREP_FOLLOW_POLL_S": "0.05"},
    )
    (tmp_path / "root").mkdir(parents=True, exist_ok=True)
    proc.start()
    collected: list[dict[int, tuple]] = [{} for _ in range(K)]
    cursors = [0] * K

    def drain(k: int, want: int, deadline_s: float = 15.0) -> None:
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            try:
                r = service_proc._http_json(
                    "GET",
                    f"{proc.base}/jobs/{jids[k]}/stream"
                    f"?cursor={cursors[k]}&timeout=0.5",
                )
            except OSError:
                time.sleep(0.1)
                continue
            for rec in r["records"]:
                assert rec["seq"] not in collected[k], "duplicate seq"
                collected[k][rec["seq"]] = (rec["line"], rec["text"])
            cursors[k] = r["next"]
            if len(collected[k]) >= want:
                return
        raise TimeoutError(
            f"tenant {k} stuck at {len(collected[k])}/{want}: "
            f"{proc.tail_log()}"
        )

    def append(lo: int, hi: int) -> None:
        with open(log, "ab") as f:
            f.write(b"".join(
                b"hello t%dx line %d\n" % (i % K, i) for i in range(lo, hi)
            ))

    try:
        jids = [proc.submit(_mk_cfg(log, "ignored", pattern=f"t{k}x"))
                for k in range(K)]
        append(0, 10)
        for k in range(K):
            drain(k, 5)
        st = service_proc._http_json("GET", f"{proc.base}/status")
        assert st["follow"]["follow_fused_queries"] == K  # it WAS fused
        proc.sigkill()
        append(10, 14)  # lands while the daemon is down
        proc.start()  # resume: cursors reload per member, group re-forms
        append(14, 20)
        for k in range(K):
            drain(k, 10, deadline_s=20.0)
    finally:
        proc.terminate()
    for k in range(K):
        got = [collected[k][s] for s in sorted(collected[k])]
        want = [(i + 1, "hello t%dx line %d" % (i % K, i))
                for i in range(20) if i % K == k]
        assert got == want

"""Observability suite: the span pipeline (utils/spans.py), Metrics under
concurrency, per-worker /status liveness, events.jsonl persistence, the
trace-export renderer, and the no-print/no-root-logger lint over runtime
modules.

Standalone-runnable (like the `faults` matrix):

    python -m pytest tests/ -q -m obs
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from pathlib import Path

import pytest

from distributed_grep_tpu.runtime import rpc
from distributed_grep_tpu.utils import spans
from distributed_grep_tpu.utils.config import JobConfig
from distributed_grep_tpu.utils.metrics import Metrics

pytestmark = pytest.mark.obs


# ------------------------------------------------------- Metrics concurrency

def test_metrics_concurrent_exact():
    """Parallel inc/observe/record_scan from worker-slot threads: snapshot
    totals are exact (no lost updates, no torn reads)."""
    m = Metrics()
    N_THREADS, N_OPS = 8, 500
    snapshots: list[dict] = []

    def pound(idx: int) -> None:
        for i in range(N_OPS):
            m.inc("ops")
            m.inc("weighted", 2.5)
            m.observe("lat", 0.001)
            m.record_scan(1000, 0.0001)
            if i % 100 == 0:  # concurrent readers must not corrupt state
                snapshots.append(m.snapshot())

    threads = [threading.Thread(target=pound, args=(i,)) for i in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    snap = m.snapshot()
    total = N_THREADS * N_OPS
    assert snap["counters"]["ops"] == total
    assert snap["counters"]["weighted"] == pytest.approx(2.5 * total)
    assert snap["counters"]["bytes_scanned"] == 1000 * total
    assert snap["timings"]["lat"]["count"] == total
    assert snap["timings"]["lat"]["total_s"] == pytest.approx(0.001 * total)
    assert snap["throughput_GBps"] > 0
    assert snapshots  # the concurrent readers actually ran
    pb = m.piggyback()
    assert pb["ops"] == total and pb["gbps"] > 0


# --------------------------------------------------------------- span buffer

def test_span_buffer_bounded_and_drop_reporting():
    buf = spans.SpanBuffer(cap=4)
    # declared instant names (analysis/events.py) — the event-vocabulary
    # audit fixture validates everything that passes through the buffer
    names = ["cache:hit", "cache:miss", "cache:off", "corpus:hit",
             "corpus:miss", "index:prune", "index:maybe"]
    for i, n in enumerate(names):
        buf.add({"t": "instant", "name": n, "ts": float(i)})
    assert len(buf) == 4 and buf.dropped == 3
    first = buf.drain(limit=2)
    assert [r["name"] for r in first] == ["cache:hit", "cache:miss"]
    rest = buf.drain()
    # the drop count is reported once, when the buffer fully drains
    assert rest[-1]["name"] == "spans_dropped"
    assert rest[-1]["args"]["count"] == 3
    assert buf.dropped == 0 and buf.drain() == []


def test_span_context_tags_and_nesting():
    buf = spans.SpanBuffer()
    assert not spans.active()
    with spans.task_context(buf, job="j", worker=3, task=7, attempt="a1",
                            kind="map"):
        assert spans.active()
        with spans.span("map:read", cat="map", detail=1):
            pass
        spans.instant("index:maybe", cat="engine")
    assert not spans.active()
    recs = buf.drain()
    assert [r["name"] for r in recs] == ["map:read", "index:maybe"]
    for r in recs:
        assert (r["job"], r["worker"], r["task"], r["attempt"]) == ("j", 3, 7, "a1")
    assert recs[0]["t"] == "span" and "dur" in recs[0]
    assert recs[1]["t"] == "instant"


def test_span_emitters_noop_outside_context():
    """Disabled pipeline: emitters return immediately and buffer nothing."""
    spans.instant("nobody-home")
    spans.scan_record("native", 10, 0.1)
    with spans.span("nothing"):
        pass
    cm = spans.span("x")
    assert isinstance(cm, contextlib.AbstractContextManager)


# ------------------------------------------------------- engine scan records

def _scan_records(engine, data: bytes) -> list[dict]:
    buf = spans.SpanBuffer()
    with spans.task_context(buf, job="j", worker=0, task=0, attempt="a",
                            kind="map"):
        engine.scan(data)
    return [r for r in buf.drain() if r["name"].startswith("scan:")]


def test_engine_scan_record_host_path():
    from distributed_grep_tpu.ops.engine import GrepEngine

    eng = GrepEngine("needle", backend="cpu")
    recs = _scan_records(eng, b"hay\nneedle here\nhay\n" * 10)
    assert len(recs) == 1
    args = recs[0]["args"]
    assert args["mode"] == eng.mode
    assert args["bytes"] == len(b"hay\nneedle here\nhay\n" * 10)
    assert args["device_fallback"] is False  # flag present on the host path
    assert args["matches"] == 10
    assert recs[0]["cat"] == "engine" and recs[0]["dur"] >= 0


def test_engine_scan_record_device_path():
    from distributed_grep_tpu.ops.engine import GrepEngine

    eng = GrepEngine("needle", backend="device")
    data = b"hay\nneedle here\nhay\n" * 50
    recs = _scan_records(eng, data)
    assert len(recs) == 1
    args = recs[0]["args"]
    assert args["mode"] == eng.mode and eng.mode in ("shift_and", "nfa", "dfa")
    assert args["bytes"] == len(data)
    assert "device_fallback" in args  # flag present on the device path too
    assert args["matches"] == 50


def test_engine_scan_no_record_without_context():
    from distributed_grep_tpu.ops.engine import GrepEngine

    eng = GrepEngine("needle", backend="cpu")
    res = eng.scan(b"needle\n")  # must not raise, must not need a buffer
    assert res.n_matches == 1


def test_span_batch_retry_dedup(tmp_path):
    """A transport-level RPC retry reships the same (worker, seq) batch;
    the scheduler persists it exactly once (events.jsonl must cover each
    attempt once, not once per retry)."""
    from distributed_grep_tpu.runtime.scheduler import Scheduler

    buf = spans.SpanBuffer()
    buf.add({"t": "instant", "name": "device_demoted", "ts": 1.0,
             "worker": 0})
    seq, batch = buf.drain_batch()
    assert seq == 1 and len(batch) == 1
    assert buf.drain_batch() == (-1, [])  # empty drain allocates no seq

    log_path = tmp_path / "events.jsonl"
    s = Scheduler(files=["a"], n_reduce=1, event_log=spans.EventLog(log_path))
    try:
        args = rpc.HeartbeatArgs(task_type="map", task_id=0, worker_id=0,
                                 spans=batch, spans_seq=seq, sent_at=1.0)
        s.heartbeat("map", 0, args=args)
        s.heartbeat("map", 0, args=args)  # the retry: identical batch
        events = [e for e in spans.EventLog.read(log_path)
                  if e.get("name") == "device_demoted"]
        assert len(events) == 1
    finally:
        s.stop()


# ------------------------------------------------------------ clock sync

def test_clock_sync_rtt_midpoint():
    cs = spans.ClockSync()
    # worker clock 5 s behind the coordinator; 200 ms round trip ->
    # the request transit is priced at rtt/2
    off = cs.observe(1, sent_at=100.0, recv_at=105.1, rtt_s=0.2)
    assert off == pytest.approx(5.0)
    # EWMA folds later observations in instead of jumping
    off2 = cs.observe(1, sent_at=200.0, recv_at=205.2, rtt_s=0.2)
    assert 5.0 < off2 < 5.1
    # no send timestamp (old worker / piggyback off): no estimate
    assert cs.observe(1, sent_at=0.0, recv_at=1.0, rtt_s=0.1) is None
    assert cs.observe(-1, sent_at=1.0, recv_at=1.0, rtt_s=0.1) is None


# --------------------------------------------------- disabled = true no-op

def test_disabled_rpc_payloads_unchanged():
    """Span-disabled runs put NOTHING extra on the wire: serialized args
    keep exactly the pre-span key set (old coordinators interop)."""
    hb = rpc.to_dict(rpc.HeartbeatArgs(task_type="map", task_id=1,
                                       worker_id=0, grace_s=2.0))
    assert set(hb) == {"task_type", "task_id", "worker_id", "grace_s"}
    fin = rpc.to_dict(rpc.TaskFinishedArgs(task_id=1, worker_id=0,
                                           produced_parts=[0, 1]))
    assert set(fin) == {"task_id", "worker_id", "produced_parts"}
    # and the piggybacked forms do serialize when populated
    hb2 = rpc.to_dict(rpc.HeartbeatArgs(
        task_type="map", task_id=1, spans=[{"t": "instant"}],
        metrics={"bytes_scanned": 5}, sent_at=1.0, rtt_s=0.1,
    ))
    assert {"spans", "metrics", "sent_at", "rtt_s"} <= set(hb2)
    # old-coordinator round trip: a default-shaped payload reconstructs
    assert rpc.from_dict("HeartbeatArgs", hb).grace_s == 2.0


def test_disabled_job_writes_no_event_log(tmp_path, monkeypatch):
    from distributed_grep_tpu.runtime.job import run_job

    monkeypatch.delenv("DGREP_SPANS", raising=False)
    monkeypatch.delenv("DGREP_TRACE_DIR", raising=False)
    (tmp_path / "in.txt").write_bytes(b"needle\nhay\n")
    cfg = JobConfig(
        input_files=[str(tmp_path / "in.txt")],
        n_reduce=2,
        work_dir=str(tmp_path / "work"),
        application="distributed_grep_tpu.apps.grep",
        app_options={"pattern": "needle"},
    )
    res = run_job(cfg, n_workers=2)
    assert res.results  # job actually ran
    assert not (tmp_path / "work" / "events.jsonl").exists()
    # trace.annotate stays a cheap nullcontext alongside (satellite #4)
    from distributed_grep_tpu.utils import trace

    assert isinstance(trace.annotate("x"), contextlib.nullcontext)


# ------------------------------------------------- local job, end to end

def test_local_job_spans_end_to_end(tmp_path):
    from distributed_grep_tpu.runtime.job import run_job

    (tmp_path / "in.txt").write_bytes(b"needle one\nhay\nneedle two\n" * 20)
    cfg = JobConfig(
        input_files=[str(tmp_path / "in.txt")],
        n_reduce=2,
        work_dir=str(tmp_path / "work"),
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": "needle", "backend": "cpu"},
        spans=True,
        job_id="local-e2e",
    )
    res = run_job(cfg, n_workers=2)
    assert res.results
    log_path = tmp_path / "work" / "events.jsonl"
    assert log_path.exists()
    events = spans.EventLog.read(log_path)
    names = [e.get("name") for e in events]
    # coordinator decisions
    assert "assign_map" in names and "map_committed" in names
    assert "assign_reduce" in names and "reduce_committed" in names
    # worker task/phase spans, tagged with the causal ids
    task_spans = [e for e in events if e.get("name") == "map:task"]
    assert task_spans
    for e in task_spans:
        assert e["job"] == "local-e2e" and e["kind"] == "map"
        assert isinstance(e["worker"], int) and e["worker"] >= 0
        assert e["attempt"] and "dur" in e
    assert any(e.get("name") == "reduce:task" for e in events)
    # engine per-scan telemetry promoted from engine.stats
    scans = [e for e in events if str(e.get("name", "")).startswith("scan:")]
    assert scans and all("device_fallback" in s["args"] for s in scans)


# --------------------------------- HTTP job + killed worker (acceptance)

def _run_http_spans_job(tmp_path, corpus):
    """One HTTP job with the span pipeline on: worker 0 dies after reading
    its first split (the SIGKILL stand-in the suite uses, WorkerKilled);
    the surviving worker re-executes it after the timeout sweep."""
    from distributed_grep_tpu.apps.loader import load_application
    from distributed_grep_tpu.runtime.http_coordinator import CoordinatorServer
    from distributed_grep_tpu.runtime.http_transport import HttpTransport
    from distributed_grep_tpu.runtime.worker import WorkerKilled, WorkerLoop

    cfg = JobConfig(
        input_files=[str(p) for p in corpus.values()],
        application="distributed_grep_tpu.apps.grep",
        app_options={"pattern": "hello"},
        n_reduce=2,
        work_dir=str(tmp_path / "job"),
        coordinator_port=0,
        task_timeout_s=1.0,
        sweep_interval_s=0.1,
        spans=True,
        job_id="http-e2e",
    )
    server = CoordinatorServer(cfg)
    server.start()
    addr = f"127.0.0.1:{server.port}"
    app = load_application("distributed_grep_tpu.apps.grep", pattern="hello")

    def dying():
        loop = WorkerLoop(HttpTransport(addr), app, spans_enabled=True,
                          job_id="http-e2e",
                          fault_hooks={"after_map_read": _raise_killed})
        with contextlib.suppress(WorkerKilled):
            loop.run()

    t1 = threading.Thread(target=dying)
    t1.start()
    t1.join(timeout=10.0)
    assert not server.scheduler.done()

    survivor = WorkerLoop(HttpTransport(addr), app, spans_enabled=True,
                          job_id="http-e2e")
    t2 = threading.Thread(target=survivor.run)
    t2.start()
    assert server.wait_done(timeout=30.0)
    status = server.status()  # before shutdown: "during the run" surface
    t2.join(timeout=10.0)
    server.shutdown(linger_s=0.1)
    return server, status, survivor


def _raise_killed():
    from distributed_grep_tpu.runtime.worker import WorkerKilled

    raise WorkerKilled()


def test_http_job_spans_killed_worker_acceptance(tmp_path, corpus):
    server, status, survivor = _run_http_spans_job(tmp_path, corpus)
    events = spans.EventLog.read(Path(server.config.work_dir) / "events.jsonl")

    # every task attempt is covered: the coordinator's assign events carry
    # (task, worker, attempt) — including the killed attempt 1 and its
    # re-execution as attempt 2 after the timeout sweep
    assigns = [e for e in events if e.get("name") == "assign_map"]
    n_maps = len(server.scheduler.map_tasks)
    assert len(assigns) > n_maps  # more assignments than tasks = a retry
    retried = [e for e in assigns if e["args"]["attempt"] >= 2]
    assert retried
    assert any(e.get("name") == "task_timeout" for e in events)

    # the re-executed attempt's spans landed on the SURVIVING worker's row
    retried_task = retried[0]["args"]["task"]
    retask = [e for e in events if e.get("name") == "map:task"
              and e.get("task") == retried_task]
    assert retask and retask[-1]["worker"] == survivor.worker_id

    # per-worker aggregates shipped via heartbeat/finished piggyback
    w = status["workers"][str(survivor.worker_id)]
    assert w["metrics"]["bytes_scanned"] > 0
    assert w["metrics"]["gbps"] > 0
    assert w["last_heartbeat_age_s"] >= 0

    # trace-export: valid Chrome trace_event JSON, re-executed attempt on
    # the surviving worker's row
    from distributed_grep_tpu.__main__ import main

    out = tmp_path / "trace.json"
    rc = main(["trace-export", server.config.work_dir, "-o", str(out)])
    assert rc == 0
    doc = json.load(open(out))
    evs = doc["traceEvents"]
    assert evs
    for ev in evs:
        assert ev["ph"] in ("X", "i", "M")
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        if ev["ph"] != "M":
            assert isinstance(ev["ts"], float) and ev["ts"] > 0
    names = {(ev["name"], ev["tid"]) for ev in evs}
    # coordinator row (tid 0) holds the scheduling decisions
    assert ("assign_map", 0) in names and ("task_timeout", 0) in names
    # the re-executed map task renders on the survivor's row
    survivor_tid = survivor.worker_id + 1
    retask_evs = [ev for ev in evs if ev["name"] == "map:task"
                  and ev["args"].get("task") == retried_task]
    assert retask_evs and retask_evs[-1]["tid"] == survivor_tid
    # row names are declared via metadata events
    thread_names = {ev["args"]["name"] for ev in evs
                    if ev["ph"] == "M" and ev["name"] == "thread_name"}
    assert "coordinator" in thread_names
    assert f"worker {survivor.worker_id}" in thread_names


@pytest.mark.slow
def test_http_job_spans_sigkill_worker_subprocess(tmp_path):
    """The literal SIGKILL variant: a real worker subprocess is SIGKILLed
    mid-map; the surviving in-process worker re-executes after the timeout
    sweep, and events.jsonl + trace-export cover both attempts."""
    import os
    import signal
    import subprocess
    import sys

    from distributed_grep_tpu.apps.loader import load_application
    from distributed_grep_tpu.runtime.http_coordinator import CoordinatorServer
    from distributed_grep_tpu.runtime.http_transport import HttpTransport
    from distributed_grep_tpu.runtime.types import TaskState
    from distributed_grep_tpu.runtime.worker import WorkerLoop

    # task 0 is a wide-window split (a ~16 MB re-loop map runs ~100+ ms),
    # so the SIGKILL lands mid-task with high probability
    big = tmp_path / "big.txt"
    big.write_bytes((b"x" * 120 + b"\n") * 140_000 + b"hello tail\n")
    small = tmp_path / "small.txt"
    small.write_bytes(b"hello small\nnothing\n")
    cfg = JobConfig(
        input_files=[str(big), str(small)],
        application="distributed_grep_tpu.apps.grep",
        app_options={"pattern": "hello"},
        n_reduce=2,
        work_dir=str(tmp_path / "job"),
        coordinator_port=0,
        task_timeout_s=2.0,
        sweep_interval_s=0.1,
        spans=True,
        job_id="sigkill-e2e",
    )
    server = CoordinatorServer(cfg)
    server.start()
    addr = f"127.0.0.1:{server.port}"
    repo = str(Path(__file__).resolve().parents[1])
    env = {"PYTHONPATH": repo, "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
           "DGREP_LOG": "WARNING", "JAX_PLATFORMS": "cpu"}
    w1 = subprocess.Popen(
        [sys.executable, "-m", "distributed_grep_tpu", "worker",
         "--addr", addr],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env,
    )
    caught = False
    try:
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline:
            with server.scheduler._lock:
                caught = any(t.state is TaskState.IN_PROGRESS
                             for t in server.scheduler.map_tasks)
                done = server.scheduler._done_locked()
            if caught or done:
                break
            time.sleep(0.005)
        if caught:
            os.kill(w1.pid, signal.SIGKILL)
    finally:
        if w1.poll() is None and not caught:
            w1.kill()
    if not caught:
        server.shutdown(linger_s=0.0)
        pytest.skip("never caught the worker subprocess mid-task")
    w1.wait(timeout=30)

    app = load_application("distributed_grep_tpu.apps.grep", pattern="hello")
    survivor = WorkerLoop(HttpTransport(addr), app, spans_enabled=True,
                          job_id="sigkill-e2e")
    t = threading.Thread(target=survivor.run)
    t.start()
    assert server.wait_done(timeout=180.0)
    t.join(timeout=15.0)
    if not server.metrics.counters.get("map_retries", 0):
        server.shutdown(linger_s=0.0)
        pytest.skip("SIGKILL landed after the map committed — no retry")
    server.shutdown(linger_s=0.1)

    events = spans.EventLog.read(Path(cfg.work_dir) / "events.jsonl")
    assigns = [e for e in events if e.get("name") == "assign_map"]
    retried = [e for e in assigns if e["args"]["attempt"] >= 2]
    assert retried and any(e.get("name") == "task_timeout" for e in events)
    retried_task = retried[0]["args"]["task"]
    retask = [e for e in events if e.get("name") == "map:task"
              and e.get("task") == retried_task]
    assert retask and retask[-1]["worker"] == survivor.worker_id
    doc = spans.export_chrome_trace(events)
    tids = {ev["tid"] for ev in doc["traceEvents"]
            if ev["ph"] == "X" and ev["name"] == "map:task"
            and ev["args"].get("task") == retried_task}
    assert (survivor.worker_id + 1) in tids


def test_trace_export_cli_missing_log(tmp_path):
    from distributed_grep_tpu.__main__ import main

    assert main(["trace-export", str(tmp_path)]) == 2


# --------------------------------------------------- /status liveness

def test_status_inflight_and_worker_liveness(tmp_path, corpus):
    """GET /status surfaces stragglers before the sweeper fires: heartbeat
    age and any declared grace window per in-flight task, plus per-worker
    last-heartbeat age."""
    from distributed_grep_tpu.runtime.http_coordinator import CoordinatorServer
    from distributed_grep_tpu.runtime.http_transport import HttpTransport

    cfg = JobConfig(
        input_files=[str(p) for p in corpus.values()],
        app_options={"pattern": "hello"},
        n_reduce=2,
        work_dir=str(tmp_path / "job"),
        coordinator_port=0,
        task_timeout_s=60.0,  # nothing must time out under us
    )
    server = CoordinatorServer(cfg)
    server.start()
    try:
        t = HttpTransport(f"127.0.0.1:{server.port}")
        a = t.assign_task(rpc.AssignTaskArgs())
        assert a.assignment == rpc.Assignment.MAP
        t.heartbeat(rpc.HeartbeatArgs(task_type="map", task_id=a.task_id,
                                      worker_id=a.worker_id, grace_s=30.0))
        time.sleep(0.05)
        status = t.fetch_status()
        inflight = status["in_flight"]
        assert len(inflight) == 1
        row = inflight[0]
        assert row["type"] == "map" and row["task_id"] == a.task_id
        assert row["attempts"] == 1 and row["heartbeat_age_s"] >= 0
        assert row["grace_s"] == 30.0 and row["grace_remaining_s"] > 0
        w = status["workers"][str(a.worker_id)]
        assert w["last_heartbeat_age_s"] >= 0
        assert w["task"] == f"map:{a.task_id}"
    finally:
        server.shutdown(linger_s=0.0)


# ------------------------------------------------------- logging lint

def test_runtime_modules_use_structured_logging():
    """One source of truth: the grep-based lint this test used to carry
    moved into the invariant checker (analysis/rules.py rule `logging`,
    AST-walked — prints inside nested expressions are caught too); this
    is now a thin `analyze --rule logging` invocation so the obs suite
    keeps failing loudly on control-plane print()/root-logger use."""
    from distributed_grep_tpu.analysis import run_analysis

    violations = run_analysis(rules=["logging"])
    assert not violations, "\n".join(v.render() for v in violations)

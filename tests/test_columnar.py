"""Columnar match-dense pipeline (runtime/columnar.py, round 5).

The LineBatch path must be SEMANTICALLY INVISIBLE: identical records,
identical shuffle partitioning (bit-identical FNV per key), identical
mr-out text, identical CLI output — just without a Python object per
matched line.  Oracles: the per-record implementations they replace.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from distributed_grep_tpu.apps.base import KeyValue
from distributed_grep_tpu.runtime import shuffle
from distributed_grep_tpu.runtime.columnar import (
    MARKER,
    IdentityCollator,
    LineBatch,
    decode_batch_at,
    encode_batch,
    gather_ranges,
    make_batch_from_lines,
)
from distributed_grep_tpu.utils.native import partition


def _random_batch(rng: random.Random, fname: str, n: int) -> LineBatch:
    linenos = np.array(
        sorted(rng.sample(range(1, max(2, n * 17)), n)), dtype=np.int64
    )
    texts = [
        bytes(rng.randrange(32, 127) for _ in range(rng.randrange(0, 40)))
        for _ in range(n)
    ]
    lens = np.fromiter((len(t) for t in texts), dtype=np.int64, count=n)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    return LineBatch(fname, linenos, offsets, b"".join(texts))


def test_gather_ranges_fuzz_vs_naive():
    rng = random.Random(11)
    for _ in range(200):
        n = rng.randrange(0, 1500)
        raw = bytes(rng.randrange(256) for _ in range(n))
        arr = np.frombuffer(raw, np.uint8)
        m = rng.randrange(0, 40)
        starts = np.array([rng.randrange(0, n + 1) for _ in range(m)],
                          np.int64)
        ends = np.minimum(
            n, starts + np.array([rng.randrange(0, 25) for _ in range(m)])
        )
        slab, off = gather_ranges(arr, starts, ends)
        want = b"".join(raw[a:b] for a, b in zip(starts, ends))
        assert slab == want
        assert off[-1] == len(want)


def test_vectorized_fnv_bit_identical_to_partition():
    """The shuffle contract: batch partitioning must reproduce the
    per-record FNV-32a routing exactly (reference ihash semantics)."""
    rng = random.Random(5)
    for fname in ["/data/split-03.txt", "weird \udcff\udc80 name", "", "a b"]:
        linenos = np.array(
            sorted(rng.sample(range(1, 10**8), 300)), np.int64
        )
        b = LineBatch(fname, linenos, np.arange(301, dtype=np.int64),
                      b"y" * 300)
        for n_reduce in (1, 3, 8, 97):
            got = b.partitions(n_reduce).tolist()
            want = [
                partition(f"{fname} (line number #{int(n)})", n_reduce)
                for n in linenos
            ]
            assert got == want, (fname, n_reduce)


def test_split_by_partition_matches_per_record_bucketize():
    rng = random.Random(7)
    batch = _random_batch(rng, "/f.txt", 400)
    per_record = shuffle.bucketize(batch.to_keyvalues(), 5)
    columnar = shuffle.bucketize([batch], 5)
    assert set(per_record) == set(columnar)
    for r in per_record:
        expanded = []
        for item in columnar[r]:
            expanded.extend(item.to_keyvalues())
        assert expanded == per_record[r], r


def test_wire_roundtrip_mixed_records():
    rng = random.Random(3)
    b1 = _random_batch(rng, "/a", 50)
    b2 = _random_batch(rng, "/b \udcfe", 1)
    records = [
        KeyValue("k1", "v1"),
        b1,
        KeyValue("k2", "line with \t tab and \\n"),
        b2,
        KeyValue("k3", ""),
    ]
    data = shuffle.encode_records(records)
    back = shuffle.decode_records(data)
    assert [type(r).__name__ for r in back] == [
        "KeyValue", "LineBatch", "KeyValue", "LineBatch", "KeyValue"
    ]
    assert back[0] == records[0] and back[2] == records[2]
    assert back[1].to_keyvalues() == b1.to_keyvalues()
    assert back[3].to_keyvalues() == b2.to_keyvalues()


def test_wire_marker_in_value_and_slab_is_not_a_boundary():
    """Adversarial: a grep'd line may CONTAIN the block marker text — in
    a JSONL value and inside a batch slab.  Neither may be parsed as a
    block boundary."""
    evil = MARKER.decode() + '{"file": "x", "n": 9, "slab": 9}'
    kv = KeyValue("k", evil)
    slab_line = (MARKER + b' {"n": 1}').decode()
    texts = [slab_line.encode(), b"plain"]
    lens = np.array([len(t) for t in texts], np.int64)
    off = np.zeros(3, np.int64)
    np.cumsum(lens, out=off[1:])
    batch = LineBatch("/f", np.array([4, 9], np.int64), off, b"".join(texts))
    data = shuffle.encode_records([kv, batch, kv])
    back = shuffle.decode_records(data)
    assert [type(r).__name__ for r in back] == [
        "KeyValue", "LineBatch", "KeyValue"
    ]
    assert back[0].value == evil and back[2].value == evil
    assert back[1].line_bytes(0).decode() == slab_line


def test_batch_free_encoding_unchanged():
    """A record list with no batches must encode byte-identically to the
    round-4 JSONL wire (resume/journal compatibility)."""
    import json

    records = [KeyValue("a", "1"), KeyValue("b \udcff", "x\ty")]
    want = "".join(
        json.dumps([kv.key, kv.value], ensure_ascii=False) + "\n"
        for kv in records
    ).encode("utf-8", "surrogateescape")
    assert shuffle.encode_records(records) == want


def test_make_batch_from_lines_matches_line_span():
    from distributed_grep_tpu.ops.lines import line_span, newline_index

    cases = [
        b"one\ntwo\nthree\n",
        b"no trailing newline",
        b"\n\nempty heads\n\n",
        b"single\n",
    ]
    for data in cases:
        nl = newline_index(data)
        n_lines = data.count(b"\n") + (
            0 if not data or data.endswith(b"\n") else 1
        )
        lns = np.arange(1, n_lines + 1, dtype=np.int64)
        b = make_batch_from_lines(
            "/f", lns, np.frombuffer(data, np.uint8), nl, len(data)
        )
        for i, ln in enumerate(lns.tolist()):
            s, e = line_span(nl, ln, len(data))
            assert b.line_bytes(i) == data[s:e], (data, ln)


def test_make_batch_lineno_base_shifts_only_stored_numbers():
    data = b"aa\nbb\ncc\n"
    from distributed_grep_tpu.ops.lines import newline_index

    b = make_batch_from_lines(
        "/f", np.array([2], np.int64), np.frombuffer(data, np.uint8),
        newline_index(data), len(data), lineno_base=100,
    )
    assert b.linenos.tolist() == [102]
    assert b.line_bytes(0) == b"bb"


def test_identity_collator_orders_and_spills(tmp_path):
    """Batches + loose KeyValues from many 'map tasks' come out in
    (file, line) order with bounded memory (forced spills)."""
    rng = random.Random(13)
    items = []
    want = []
    for fi in range(3):
        fname = f"/data/split-{fi}"
        all_lines = sorted(rng.sample(range(1, 5000), 600))
        for c in range(0, 600, 150):  # 4 chunk batches per file
            chunk = np.array(all_lines[c : c + 150], np.int64)
            texts = [f"t{fi}-{int(n)}".encode() for n in chunk]
            lens = np.array([len(t) for t in texts], np.int64)
            off = np.zeros(lens.size + 1, np.int64)
            np.cumsum(lens, out=off[1:])
            items.append(LineBatch(fname, chunk, off, b"".join(texts)))
        want.extend(
            (fname, int(n), f"t{fi}-{int(n)}") for n in all_lines
        )
    rng.shuffle(items)
    coll = IdentityCollator(memory_limit_bytes=8 << 10,
                           spill_dir=str(tmp_path))
    with coll:
        coll.add_many(items)
        coll.add_many([KeyValue("/data/split-1 (line number #0)", "kv")])
        assert coll.spill_count > 0  # the cap actually forced spills
        out = "".join(coll.iter_output_chunks())
    lines = out.splitlines()
    got = []
    for line in lines:
        k, _, v = line.partition("\t")
        f, _, rest = k.partition(" (line number #")
        got.append((f, int(rest[:-1]), v))
    want_all = sorted(want + [("/data/split-1", 0, "kv")])
    assert got == sorted(got) == want_all


def test_full_job_columnar_output_matches_per_record_oracle(tmp_path):
    """End to end: a grep job through the columnar pipeline produces the
    same results dict as expanding map output per record, and the mr-out
    files are already in display order (fileline_sorted merge)."""
    from distributed_grep_tpu.runtime.job import grep_key_sort, run_job
    from distributed_grep_tpu.utils.config import JobConfig

    rng = random.Random(21)
    files = []
    for fi in range(3):
        p = tmp_path / f"in-{fi}.txt"
        lines = []
        for i in range(400):
            lines.append(
                "needle %d-%d" % (fi, i) if rng.random() < 0.5
                else "nothing %d" % i
            )
        p.write_text("\n".join(lines) + "\n")
        files.append(str(p))
    cfg = JobConfig(
        input_files=files,
        application="distributed_grep_tpu.apps.grep",
        app_options={"pattern": "needle"},
        n_reduce=4,
        work_dir=str(tmp_path / "job"),
    )
    res = run_job(cfg, n_workers=2)
    assert res.fileline_sorted
    # every output file individually in (file, line) order
    for path in res.output_files:
        keys = [grep_key_sort((k, v)) for k, v in res._iter_file(path)]
        assert keys == sorted(keys), path
    # global sorted stream == sorted(all records)
    merged = list(res.iter_results_sorted())
    assert merged == sorted(merged, key=grep_key_sort)
    # records match a direct per-record oracle
    import re

    want = {}
    for f in files:
        data = open(f, "rb").read()
        for i, line in enumerate(data.split(b"\n")[:-1], 1):
            if re.search(b"needle", line):
                want[f"{f} (line number #{i})"] = line.decode()
    assert dict(merged) == want
    # display-bytes stream agrees with the (key, value) stream
    display = list(res.iter_display_bytes_sorted())
    assert display == [
        f"{k} {v}\n".encode("utf-8", "surrogateescape") for k, v in merged
    ]


def test_collator_used_only_for_identity_apps(tmp_path):
    """wordcount (real reduce) must keep the generic external-sort path —
    its records aggregate per key, which the identity collator does not."""
    from distributed_grep_tpu.runtime.job import run_job
    from distributed_grep_tpu.utils.config import JobConfig

    p = tmp_path / "in.txt"
    p.write_text("the cat and the hat and the bat\n")
    cfg = JobConfig(
        input_files=[str(p)],
        application="distributed_grep_tpu.apps.wordcount",
        app_options={},
        n_reduce=2,
        work_dir=str(tmp_path / "job"),
    )
    res = run_job(cfg, n_workers=1)
    assert not res.fileline_sorted
    assert res.results["the"] == "3" and res.results["and"] == "2"


def test_parse_grep_key_bytes_parity_with_regex():
    """The bytes-mode key parser must accept EXACTLY what GREP_KEY_RE
    accepts (round-5 review: int() alone would take '+5' / '1_0')."""
    from distributed_grep_tpu.runtime.job import (
        GREP_KEY_RE,
        parse_grep_key_bytes,
    )

    cases = [
        "f (line number #5)", "s (line number #+5)",
        "u (line number #1_0)", "x (line number # 5)",
        "y (line number #)", "no marker",
        "a (line number #3) (line number #7)",
        "t (line number #5) extra", "weird) (line number #9)",
        " (line number #1)", "p (line number #007)",
    ]
    for k in cases:
        m = GREP_KEY_RE.match(k)
        want = (m.group(1).encode(), int(m.group(2))) if m else None
        assert parse_grep_key_bytes(k.encode()) == want, k


def test_e2e_spilling_collator_output_identical(tmp_path):
    """A grep job forced into heavy IdentityCollator spilling must write
    byte-identical mr-out content to the no-spill run (e2e guard on the
    spill wire + merge; pinned at 64 MB scale in BASELINE.md)."""
    from distributed_grep_tpu.runtime.job import run_job
    from distributed_grep_tpu.utils.config import JobConfig

    rng = random.Random(31)
    p = tmp_path / "in.txt"
    with open(p, "w") as f:
        for i in range(20000):
            f.write(
                ("needle %d x\n" % i) if rng.random() < 0.6
                else ("nothing %d\n" % i)
            )

    def job(tag, mem):
        cfg = JobConfig(
            input_files=[str(p)],
            application="distributed_grep_tpu.apps.grep",
            app_options={"pattern": "needle"},
            n_reduce=4,
            work_dir=str(tmp_path / f"job-{tag}"),
            reduce_memory_bytes=mem,
        )
        res = run_job(cfg, n_workers=2)
        spills = res.metrics["counters"].get("reduce_spills", 0)
        out = b"".join(
            open(q, "rb").read() for q in sorted(res.output_files)
        )
        return spills, out

    s_big, out_big = job("big", 128 << 20)
    s_tiny, out_tiny = job("tiny", 64 << 10)  # 64 KB cap: heavy spilling
    assert s_big == 0 and s_tiny > 0, (s_big, s_tiny)
    assert out_big == out_tiny


def test_display_blocks_sorted_vector_path_matches_generator(tmp_path):
    """The vectorized single-path display merge must produce byte-exact
    generator output — including a path that CONTAINS the key marker,
    values holding tabs/marker text, and >9-digit-free mixed widths —
    and must FALL BACK (not corrupt) for multi-file jobs."""
    from distributed_grep_tpu.runtime.job import JobResult, run_job
    from distributed_grep_tpu.utils.config import JobConfig

    rng = random.Random(17)
    evil_name = "in (line number #7) weird.txt"
    p = tmp_path / evil_name
    lines = []
    for i in range(3000):
        if rng.random() < 0.5:
            lines.append("needle\tvalue with (line number #5) text %d" % i)
        else:
            lines.append("nothing %d" % i)
    p.write_text("\n".join(lines) + "\n")
    cfg = JobConfig(
        input_files=[str(p)],
        application="distributed_grep_tpu.apps.grep",
        app_options={"pattern": "needle"},
        n_reduce=5,
        work_dir=str(tmp_path / "job"),
    )
    res = run_job(cfg, n_workers=2)
    want = b"".join(res.iter_display_bytes_sorted())
    got = b"".join(res.display_blocks_sorted())
    assert got == want
    blocks = list(res.display_blocks_sorted())
    assert len(blocks) == 1, "single-path job should take the vector path"

    # multi-file job: paths differ -> prefix check fails -> generator path
    q = tmp_path / "other.txt"
    q.write_text("a needle\n")
    cfg2 = JobConfig(
        input_files=[str(p), str(q)],
        application="distributed_grep_tpu.apps.grep",
        app_options={"pattern": "needle"},
        n_reduce=3,
        work_dir=str(tmp_path / "job2"),
    )
    res2 = run_job(cfg2, n_workers=2)
    assert b"".join(res2.display_blocks_sorted()) == \
        b"".join(res2.iter_display_bytes_sorted())

    # over-cap totals keep the streaming path (no materialization)
    old_cap = JobResult.DISPLAY_VECTOR_CAP
    try:
        JobResult.DISPLAY_VECTOR_CAP = 1  # force fallback
        assert b"".join(res.display_blocks_sorted()) == want
    finally:
        JobResult.DISPLAY_VECTOR_CAP = old_cap

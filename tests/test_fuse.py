"""Cross-tenant scan fusion (ISSUE 11): one dispatch, K exact queries.

Pytest marker ``fuse``, standalone-runnable like ``perf``/``service``:

    python -m pytest tests/test_fuse.py -q

Pins the acceptance bars:
* fused-vs-solo BYTE identity across kernel families (shift_and / nfa /
  fdr / pairset / dfa-filter '$' / the \\b re-fallback leg), including
  ignore_case mixes and candidate-free queries, for scan AND the
  batched/window path;
* the dispatch-count proof (``perf`` style: a scan_device spy at the
  real boundary): K=4 co-running service jobs over one shared corpus
  run 1 device dispatch per split, not 4, and the fusion counters agree;
* DGREP_SERVICE_FUSE=0 is a true no-op (no fused planning, no new wire
  keys, byte-identical outputs);
* the solo fallback: a broken fused leg still finishes every
  participant byte-identical (fusion is never a correctness dependency).
"""

from __future__ import annotations

import time
from pathlib import Path

import numpy as np
import pytest

from distributed_grep_tpu.ops import device_scan
from distributed_grep_tpu.ops import fuse as fuse_mod
from distributed_grep_tpu.ops.engine import GrepEngine
from distributed_grep_tpu.runtime import fusion as fusion_mod
from distributed_grep_tpu.runtime import rpc
from distributed_grep_tpu.runtime.job import run_job
from distributed_grep_tpu.runtime.scheduler import Scheduler
from distributed_grep_tpu.runtime.service import GrepService
from distributed_grep_tpu.utils.config import JobConfig

pytestmark = pytest.mark.fuse


def _doc() -> bytes:
    lines = []
    for j in range(120):
        lines.append(
            f"line {j} "
            + ("hello " if j % 3 == 0 else "")
            + ("NEEDLE " if j % 7 == 0 else "")
            + ("error" if j % 5 == 0 else "tail")
        )
    lines.append("")  # an empty line (nullable-pattern edge)
    lines.append("last line without newline")
    return ("\n".join(lines)).encode()


# Specs chosen so the SOLO engines cover every kernel family:
# shift_and (literal), nfa (alternation+repeat), fdr (many >=2-byte
# literals), pairset (all 1-2 byte members), the '$' dfa-filter leg, the
# \b re-fallback leg, an ignore_case member, and a candidate-free query.
_SPECS = [
    ("hello", None, False),                              # shift_and
    ("(needle|err+or)", None, True),                     # nfa, ignore_case
    (None, ("hello", "needle", "line 11", "tail"), False),   # fdr set
    (None, ("he", "ta", "x"), False),                    # pairset set
    ("error$", None, False),                             # '$' device filter
    (r"\bhello\b", None, False),                         # re-fallback leg
    ("zz-never-there", None, False),                     # candidate-free
]


def _solo(spec, **kw) -> GrepEngine:
    pat, pats, ic = spec
    return GrepEngine(pat, patterns=list(pats) if pats else None,
                      ignore_case=ic, **kw)


def test_fused_vs_solo_identity_across_families():
    data = _doc()
    # the union rides the device (interpret) kernel path; the solo
    # oracles are the exact host engines — device-vs-host solo identity
    # is pinned elsewhere (test_parallel/test_ops), so fused == cpu-solo
    # pins fused == solo for every backend
    fs = fuse_mod.FusedScanner(_SPECS, interpret=True)
    fused = fs.scan(data)
    for spec, fr in zip(_SPECS, fused):
        sr = _solo(spec, backend="cpu").scan(data)
        assert np.array_equal(sr.matched_lines, fr.matched_lines), (
            spec, sr.matched_lines, fr.matched_lines,
        )
        assert fr.n_matches == fr.matched_lines.size
        assert fr.bytes_scanned == len(data)
    cc = fuse_mod.fusion_counters()
    assert cc["fused_queries"] == len(_SPECS)
    assert cc["fused_dispatches"] >= 1
    assert cc["fusion_bytes_saved"] == (len(_SPECS) - 1) * len(data)


def test_fused_all_sets_union_is_a_set_engine():
    """All-literal-set tenants merge into ONE pattern-set union (the
    FDR/AC machinery is already a multi-literal engine) — no regex
    escape round trip involved."""
    specs = [
        (None, ("hello", "needle"), False),
        (None, ("tail", "line 7"), True),
    ]
    args = fuse_mod.union_engine_args(
        [fuse_mod.QuerySpec.normalize(s) for s in specs]
    )
    assert args.get("patterns") == ["hello", "needle", "tail", "line 7"]
    assert args["ignore_case"] is True
    data = _doc()
    fused = fuse_mod.FusedScanner(specs, backend="cpu").scan(data)
    for spec, fr in zip(specs, fused):
        sr = _solo(spec, backend="cpu").scan(data)
        assert np.array_equal(sr.matched_lines, fr.matched_lines), spec


def test_fused_scan_batch_window_identity(tmp_path):
    """The batched/window path: mixed small files (packed into shared
    windows), an empty file, and a no-trailing-newline file — per-file
    fused results equal per-file solo scans, bit for bit."""
    blobs = {
        "a.txt": b"hello world\nno match here\nNEEDLE found\n",
        "b.txt": b"",
        "c.txt": b"error\nhello error",  # no trailing newline
        "d.txt": _doc(),
    }
    items = []
    for name, b in blobs.items():
        p = tmp_path / name
        p.write_bytes(b)
        items.append((name, str(p)))
    fs = fuse_mod.FusedScanner(_SPECS, interpret=True, batch_bytes=1 << 20)
    outs = fs.scan_batch(items)
    assert len(outs) == len(_SPECS)
    for spec, per_file in zip(_SPECS, outs):
        solo = _solo(spec, backend="cpu")
        assert [n for n, _ in per_file] == list(blobs)
        for (name, fr) in per_file:
            sr = solo.scan(blobs[name])
            assert np.array_equal(sr.matched_lines, fr.matched_lines), (
                spec, name,
            )


def test_unfusable_specs_raise_fuse_error():
    with pytest.raises(fuse_mod.FuseError):
        fuse_mod.QuerySpec.normalize(("", None, False))
    with pytest.raises(fuse_mod.FuseError):
        fuse_mod.QuerySpec.normalize((None, ("ok", ""), False))
    # backreference-bearing regexes cannot join an alternation (their
    # groups would repoint) — the union builder refuses them even for
    # direct API users, not just through the service planner
    with pytest.raises(fuse_mod.FuseError):
        fuse_mod.FusedScanner([(r"(a)b\1", None, False),
                               ("hello", None, False)], backend="cpu")
    # service-side mirror: unfusable queries get no fusion key at all
    assert fusion_mod.query_spec({"pattern": ""}) is None
    assert fusion_mod.query_spec({"pattern": r"(a)\1"}) is None
    assert fusion_mod.query_spec({"pattern": "x", "max_errors": 1}) is None
    assert fusion_mod.query_spec({"pattern": "hello"}) == (
        "hello", None, False,
    )


def test_claim_map_task_first_attempts_only(tmp_path):
    sched = Scheduler(files=["f1", "f2"], n_reduce=1, task_timeout_s=30.0)
    try:
        info = sched.claim_map_task(1, worker_id=7)
        assert info is not None and info["task_id"] == 1
        assert info["epoch"] == sched.epoch
        # already claimed -> not idle -> no double assignment
        assert sched.claim_map_task(1, worker_id=8) is None
        # a retried task (attempts > 0) never re-fuses: simulate timeout
        t = sched.map_tasks[1]
        from distributed_grep_tpu.runtime.types import TaskState

        t.state = TaskState.UNASSIGNED
        assert t.attempts == 1
        assert sched.claim_map_task(1, worker_id=9) is None
        assert sched.claim_map_task(99, worker_id=9) is None  # bad id
    finally:
        sched.stop()


# --------------------------------------------------------------- service

def _mk_corpus(tmp_path, n_files=2, n_lines=400) -> list[str]:
    files = []
    for i in range(n_files):
        p = tmp_path / f"in{i}.txt"
        p.write_text("".join(
            f"line {j} of {i} {'hello' if j % 3 == 0 else ''}"
            f"{' fox' if j % 5 == 0 else ''}\n" for j in range(n_lines)
        ))
        files.append(str(p))
    return files


def _cfg(files, pattern, work_dir, **app_extra) -> JobConfig:
    return JobConfig(
        input_files=files,
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": pattern, **app_extra},
        n_reduce=2,
        work_dir=work_dir,
        task_timeout_s=30.0,
        sweep_interval_s=0.2,
    )


def _wait_running(svc: GrepService, jids, timeout=10.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(svc.record(j).scheduler is not None for j in jids):
            return
        time.sleep(0.02)
    raise AssertionError("jobs did not all start")


def _outputs(paths) -> dict[str, bytes]:
    return {Path(p).name: Path(p).read_bytes() for p in paths}


@pytest.mark.service
@pytest.mark.perf
def test_service_dispatch_count_k4_one_per_split(tmp_path, monkeypatch):
    """The acceptance dispatch proof: K=4 co-running jobs over one
    shared corpus execute 1 device dispatch per split, counted at the
    REAL boundary (ops/device_scan.scan_device, the one entry every
    device-path scan funnels through) — and the fusion counters agree."""
    calls: list[int] = []
    orig = device_scan.scan_device

    def counted(eng, data, progress=None, **kw):
        calls.append(len(data))
        return orig(eng, data, progress=progress, **kw)

    monkeypatch.setattr(device_scan, "scan_device", counted)
    files = _mk_corpus(tmp_path, n_files=2)
    pats = ["hello", "fox", "line 1", "of 0"]
    svc = GrepService(work_root=tmp_path / "svc")
    try:
        jids = [
            svc.submit(_cfg(files, p, str(tmp_path / f"w{i}"),
                            backend="device", interpret=True))
            for i, p in enumerate(pats)
        ]
        _wait_running(svc, jids)
        svc.start_local_workers(1)
        for j in jids:
            assert svc.wait_job(j, timeout=120), svc.job_status(j)
        st = svc.status()
    finally:
        svc.stop()
    n_splits = len(files)  # no batching configured: one task per file
    # THE bar: 1 device dispatch per split, not K per split
    assert len(calls) == n_splits, (len(calls), n_splits)
    assert st["fusion"]["fused_dispatches"] == n_splits
    assert st["fusion"]["fused_jobs"] == len(pats) * n_splits
    cc = fuse_mod.fusion_counters()
    assert cc["fused_dispatches"] == n_splits
    assert cc["fused_dispatches_saved"] == (len(pats) - 1) * n_splits
    assert cc["fused_queries"] == len(pats) * n_splits


@pytest.mark.service
def test_service_fused_outputs_identical_and_spans(tmp_path):
    """Fused service outputs are byte-identical to solo oracles; the
    fuse:plan / fuse:split instants land in EACH participant's
    events.jsonl (spans.split_by_job routing)."""
    import json

    files = _mk_corpus(tmp_path, n_files=2, n_lines=200)
    pats = ["hello", "fox"]
    svc = GrepService(work_root=tmp_path / "svc", spans=True)
    try:
        jids = [svc.submit(_cfg(files, p, str(tmp_path / f"w{i}"),
                                backend="cpu"))
                for i, p in enumerate(pats)]
        _wait_running(svc, jids)
        svc.start_local_workers(1)
        for j in jids:
            assert svc.wait_job(j, timeout=60)
        st = svc.status()
        assert st["fusion"]["fused_dispatches"] >= 1
        outs = {j: _outputs(svc.record(j).outputs) for j in jids}
        for j in jids:
            events = [
                json.loads(ln) for ln in
                (svc.work_root / j / "events.jsonl").read_text().splitlines()
            ]
            names = {e.get("name") for e in events}
            assert "fuse:plan" in names, (j, sorted(names))
            assert "fuse:split" in names, (j, sorted(names))
    finally:
        svc.stop()
    for i, (j, p) in enumerate(zip(jids, pats)):
        oracle = run_job(
            _cfg(files, p, str(tmp_path / f"oracle{i}"), backend="cpu"),
            n_workers=2,
        )
        assert outs[j] == _outputs(oracle.output_files), (j, p)


@pytest.mark.service
def test_fusion_disabled_is_a_noop(tmp_path, monkeypatch):
    """DGREP_SERVICE_FUSE=0: no planning (no stats, no fusion_key, no
    /status key), the fused reply field never reaches the wire, and
    outputs match the solo oracles exactly."""
    monkeypatch.setenv("DGREP_SERVICE_FUSE", "0")
    # wire shape: a default reply serializes WITHOUT the new key
    assert "fused" not in rpc.reply_to_dict(rpc.AssignTaskReply())
    assert "fused" not in rpc.to_dict(rpc.AssignTaskReply())
    files = _mk_corpus(tmp_path, n_files=2, n_lines=120)
    pats = ["hello", "fox"]
    svc = GrepService(work_root=tmp_path / "svc")
    try:
        jids = [svc.submit(_cfg(files, p, str(tmp_path / f"w{i}"),
                                backend="cpu"))
                for i, p in enumerate(pats)]
        for j in jids:
            assert svc.record(j).fusion_key is None
        _wait_running(svc, jids)
        svc.start_local_workers(1)
        for j in jids:
            assert svc.wait_job(j, timeout=60)
        st = svc.status()
        assert "fusion" not in st
        outs = {j: _outputs(svc.record(j).outputs) for j in jids}
    finally:
        svc.stop()
    assert not fuse_mod.fusion_counters()
    for i, (j, p) in enumerate(zip(jids, pats)):
        oracle = run_job(
            _cfg(files, p, str(tmp_path / f"oracle-off{i}"), backend="cpu"),
            n_workers=2,
        )
        assert outs[j] == _outputs(oracle.output_files), (j, p)


@pytest.mark.service
def test_submit_pattern_set_parity(tmp_path, capsys):
    """ISSUE 11 satellite: `dgrep submit -F -e A -e B` (and -f/-E) plumb
    pattern SETS into the submitted JobConfig the same way the local CLI
    path does — the service runs the multi-pattern job and its outputs
    match the local run_job oracle."""
    import json

    from distributed_grep_tpu import __main__ as cli
    from distributed_grep_tpu.runtime.service import ServiceServer

    files = _mk_corpus(tmp_path, n_files=2, n_lines=80)
    svc = GrepService(work_root=tmp_path / "svc")
    server = ServiceServer(svc)
    server.start()
    try:
        svc.start_local_workers(1)
        rc = cli.main([
            "submit", "--addr", f"127.0.0.1:{server.port}",
            "-F", "-e", "hello", "-e", "fox", *files,
            "--timeout", "60",
        ])
        out = capsys.readouterr().out.strip().splitlines()
        assert rc == 0, out
        doc = json.loads(out[-1])
        assert doc["state"] == "done" and doc["outputs"]
        got = _outputs(doc["outputs"])
    finally:
        server.shutdown()
        svc.stop()
    oracle = run_job(
        JobConfig(
            input_files=files,
            application="distributed_grep_tpu.apps.grep_tpu",
            app_options={"patterns": ["hello", "fox"], "backend": "cpu"},
            n_reduce=10,
            work_dir=str(tmp_path / "oracle"),
        ),
        n_workers=2,
    )
    assert got == _outputs(oracle.output_files)


@pytest.mark.service
def test_fused_leg_failure_falls_back_to_solo(tmp_path, monkeypatch):
    """Fusion is a fast path, never a correctness dependency: with the
    union scanner broken outright, the fused attempt's solo fallback
    still finishes every participant byte-identical to its oracle."""

    def boom(*a, **kw):
        raise fuse_mod.FuseError("injected: union scanner down")

    monkeypatch.setattr(fuse_mod, "FusedScanner", boom)
    files = _mk_corpus(tmp_path, n_files=2, n_lines=120)
    pats = ["hello", "fox"]
    svc = GrepService(work_root=tmp_path / "svc")
    try:
        jids = [svc.submit(_cfg(files, p, str(tmp_path / f"w{i}"),
                                backend="cpu"))
                for i, p in enumerate(pats)]
        _wait_running(svc, jids)
        svc.start_local_workers(1)
        for j in jids:
            assert svc.wait_job(j, timeout=60)
        # planning DID fuse (the daemon's counters moved) …
        assert svc.status()["fusion"]["fused_dispatches"] >= 1
        outs = {j: _outputs(svc.record(j).outputs) for j in jids}
    finally:
        svc.stop()
    # … and the fallback still produced exact per-tenant outputs
    for i, (j, p) in enumerate(zip(jids, pats)):
        oracle = run_job(
            _cfg(files, p, str(tmp_path / f"oracle-fb{i}"), backend="cpu"),
            n_workers=2,
        )
        assert outs[j] == _outputs(oracle.output_files), (j, p)

"""Cross-file device batching (round 6): packed scans are bit-identical to
per-file scans across every kernel family.

The contract under test (ops/layout.py BatchPacker + GrepEngine.scan_batch):
many small newline-terminated blobs pack into ONE scan buffer, the scan
runs once, and the demux maps packed line numbers back to per-file lines.
Exactness rides the invariants the repo already pins — every DFA '\\n'
column is the start state (file boundaries are line starts), the approx
recurrence resets at '\\n', and the filter families' host confirm/stitch
pass operates per line — so each family's per-file verdicts must equal a
plain per-file scan() exactly, anchors, missing trailing newlines, empty
files and segment-boundary-spanning batches included.

Standalone: ``python -m pytest tests/test_batch.py -q`` (CPU-only).
"""

from __future__ import annotations

import numpy as np
import pytest

from distributed_grep_tpu.ops.engine import GrepEngine, ScanResult
from distributed_grep_tpu.ops.layout import BatchPacker, packed_size


@pytest.fixture(autouse=True)
def _no_calibrate(monkeypatch):
    """Deterministic FDR plans (CLAUDE.md: DGREP_NO_CALIBRATE for CI)."""
    monkeypatch.setenv("DGREP_NO_CALIBRATE", "1")


def _blobs() -> dict[str, bytes]:
    """Edge-case corpus: trailing-newline-less files, empty files, files of
    only empty lines, needles for every engine family."""
    rng = np.random.default_rng(7)
    words = ["hello", "hallo", "helloo", "world", "fox", "ab", "zz", "q",
             "volcano", "volcXno", "needle", "the", "of", "and"]

    def text(n_lines: int, seed_words=words) -> bytes:
        out = []
        for _ in range(n_lines):
            k = int(rng.integers(1, 8))
            out.append(" ".join(
                seed_words[int(rng.integers(0, len(seed_words)))]
                for _ in range(k)
            ).encode())
        return b"\n".join(out) + b"\n"

    return {
        "plain": text(40),
        "no_trailing_nl": b"first hello line\nsecond line\nlast hello",
        "empty": b"",
        "only_newlines": b"\n\nhello\n\n",
        "match_first_byte": b"hello starts this file\nand more\n",
        "match_last_line": text(10) + b"ends with hello",
        "no_match": b"nothing to see\nin this file\n",
        "dense": b"hello\n" * 200,
        "anchored": b"hello\nxhello\nhello tail\nworld hello\n",
    }


ENGINES = [
    ("shift_and", dict(pattern="hello")),
    ("nfa", dict(pattern="h[ae]llo+")),
    ("anchor_start", dict(pattern="^hello")),
    ("anchor_end", dict(pattern="hello$")),
    ("empty_line", dict(pattern="^$")),
    ("approx_k1", dict(pattern="volcano", max_errors=1)),
    ("pairset", dict(patterns=["ab", "zz", "q"])),
    ("cpu_native", dict(pattern="hello", backend="cpu")),
    ("cpu_set", dict(patterns=["hello", "needle", "volcano"], backend="cpu")),
    ("re_fallback", dict(pattern="hello(?! tail)")),
]


def _fdr_patterns() -> list[str]:
    rng = np.random.default_rng(3)
    pats = {"hello", "volcano", "needle"}
    while len(pats) < 50:
        k = int(rng.integers(4, 9))
        pats.add("".join(chr(c) for c in rng.integers(97, 123, size=k)))
    return sorted(pats)


ENGINES.append(("fdr", dict(patterns=_fdr_patterns())))


def _assert_batch_matches_per_file(eng: GrepEngine, blobs: dict[str, bytes]):
    got = eng.scan_batch(list(blobs.items()))
    stats = dict(eng.stats)  # snapshot BEFORE the verify scans reset it
    assert [name for name, _ in got] == list(blobs)  # input order kept
    for name, res in got:
        solo = eng.scan(blobs[name])
        assert np.array_equal(res.matched_lines, solo.matched_lines), (
            name, res.matched_lines, solo.matched_lines
        )
        assert res.n_matches == solo.n_matches == res.matched_lines.size
        assert res.bytes_scanned == len(blobs[name])
    return stats


@pytest.mark.parametrize("label,kw", ENGINES, ids=[e[0] for e in ENGINES])
def test_packed_batch_bit_identical_per_family(label, kw):
    kw = dict(kw)
    if kw.get("backend") != "cpu":
        kw["interpret"] = True  # CI: Pallas interpret = the device path
    eng = GrepEngine(batch_bytes=1 << 20, **kw)
    _assert_batch_matches_per_file(eng, _blobs())


def test_batch_spanning_segment_boundary():
    """A packed buffer larger than segment_bytes crosses segment (and
    stripe) boundaries mid-batch; the stitch pass must keep every file
    exact."""
    blobs = {
        f"f{i:02d}": (b"filler line with hello inside\n" * 400
                      + (b"tail hello" if i % 3 else b""))
        for i in range(12)
    }  # ~12 KB each, ~145 KB packed >> 64 KB segments
    eng = GrepEngine("hello$", interpret=True, segment_bytes=1 << 16,
                     batch_bytes=1 << 20)
    stats = _assert_batch_matches_per_file(eng, blobs)
    assert stats["batch_dispatches"] == 1
    assert stats["batched_files"] == 12


def test_large_inputs_scan_solo_order_preserved():
    big = b"hello big\n" * 2000  # 20 KB >= device_min_bytes below
    blobs = [("s1", b"small hello\n"), ("s2", b"more hello\n"), ("big", big)]
    eng = GrepEngine("hello", backend="cpu", batch_bytes=1 << 20,
                     device_min_bytes=1 << 14)
    seen = []
    got = eng.scan_batch(blobs, emit=lambda n, d, r: seen.append((n, d)))
    assert [n for n, _ in got] == ["s1", "s2", "big"]
    assert seen == [(n, d) for n, d in blobs]  # emit gets ORIGINAL blobs
    st = eng.stats
    assert st["solo_dispatches"] == 1  # the big input
    assert st["batched_files"] == 2 and st["batch_dispatches"] == 1
    assert st["dispatches_saved"] == 1

    # a large input BETWEEN smalls flushes the pending batch first (order
    # preservation): the stranded singles scan solo, never packed
    eng2 = GrepEngine("hello", backend="cpu", batch_bytes=1 << 20,
                      device_min_bytes=1 << 14)
    got2 = eng2.scan_batch(
        [("s1", b"small hello\n"), ("big", big), ("s2", b"more hello\n")]
    )
    assert [n for n, _ in got2] == ["s1", "big", "s2"]
    assert eng2.stats["solo_dispatches"] == 3
    assert eng2.stats["batched_files"] == 0


def test_scan_batch_accepts_paths(tmp_path):
    p1 = tmp_path / "a.txt"
    p1.write_bytes(b"hello from disk\nno match\n")
    p2 = tmp_path / "b.txt"
    p2.write_bytes(b"nothing")
    eng = GrepEngine("hello", backend="cpu")
    got = dict(eng.scan_batch([("a", p1), ("b", str(p2))]))
    assert got["a"].matched_lines.tolist() == [1]
    assert got["b"].n_matches == 0


def test_batch_bytes_zero_disables_packing():
    eng = GrepEngine("hello", backend="cpu", batch_bytes=0)
    got = eng.scan_batch([("a", b"hello\n"), ("b", b"hello\n")])
    assert [r.n_matches for _, r in got] == [1, 1]
    assert eng.stats["batched_files"] == 0
    assert eng.stats["solo_dispatches"] == 2


# ------------------------------------------------------------- packer unit
def test_packer_tables_and_demux():
    p = BatchPacker(1 << 20)
    blobs = [b"a\nbb\n", b"no newline", b"", b"\n\n", b"z\n"]
    for i, b in enumerate(blobs):
        assert p.fits(b)
        p.add(i, b)
    batch = p.pack()
    assert p.pack() is None  # packer reset
    # synthesized terminator only where needed; empty blob adds nothing
    assert batch.data == b"a\nbb\n" + b"no newline\n" + b"\n\n" + b"z\n"
    assert batch.byte_starts.tolist() == [0, 5, 16, 16, 18, 20]
    # grep -n line counts: 2, 1, 0, 2, 1
    assert batch.line_starts.tolist() == [0, 2, 3, 3, 5, 6]
    per = batch.demux(np.asarray([1, 3, 4, 5, 6], dtype=np.int64))
    assert [x.tolist() for x in per] == [[1], [1], [], [1, 2], [1]]


def test_packed_size():
    assert packed_size(b"") == 0
    assert packed_size(b"x") == 2
    assert packed_size(b"x\n") == 2


def test_packer_fits_respects_cap_but_never_splits():
    p = BatchPacker(8)
    assert p.fits(b"0123456789abcdef")  # first blob always joins
    p.add("big", b"0123456789abcdef")
    assert not p.fits(b"x")
    assert len(p.pack()) == 1


# -------------------------------------------------------- runtime plumbing
def test_plan_map_splits_groups_small_consecutive(tmp_path):
    from distributed_grep_tpu.runtime.job import plan_map_splits

    paths = []
    for i, size in enumerate([100, 200, 5000, 100, 100]):
        f = tmp_path / f"f{i}"
        f.write_bytes(b"x" * size)
        paths.append(str(f))
    splits = plan_map_splits(paths, batch_bytes=1 << 20, small_bytes=1000)
    assert splits == [[paths[0], paths[1]], paths[2], [paths[3], paths[4]]]
    # capacity closes groups
    splits = plan_map_splits(paths, batch_bytes=250, small_bytes=1000)
    assert splits == [paths[0], paths[1], paths[2], [paths[3], paths[4]]]
    # disabled -> identity
    assert plan_map_splits(paths, batch_bytes=0) == paths


def test_scheduler_batched_split_assignment_and_journal(tmp_path):
    from distributed_grep_tpu.runtime import rpc
    from distributed_grep_tpu.runtime.journal import TaskJournal
    from distributed_grep_tpu.runtime.scheduler import Scheduler
    from distributed_grep_tpu.runtime.types import TaskState

    jpath = tmp_path / "journal.jsonl"
    journal = TaskJournal(jpath)
    sched = Scheduler(
        files=[["a", "b"], "c"], n_reduce=1, journal=journal,
    )
    try:
        reply = sched.assign_task(rpc.AssignTaskArgs(), timeout=1.0)
        assert reply.assignment == rpc.Assignment.MAP
        assert reply.filenames == ["a", "b"]
        sched.map_finished(rpc.TaskFinishedArgs(
            task_id=reply.task_id, worker_id=reply.worker_id,
            produced_parts=[0],
        ))
        reply2 = sched.assign_task(rpc.AssignTaskArgs(), timeout=1.0)
        assert reply2.filenames == [] and reply2.filename == "c"
    finally:
        sched.stop()
        journal.close()
    entries = TaskJournal.replay(jpath)
    batch_entries = [e for e in entries if e.get("files")]
    assert batch_entries and batch_entries[0]["files"] == ["a", "b"]

    # replay: same plan resumes COMPLETED; a re-planned member list re-runs
    sched2 = Scheduler(files=[["a", "b"], "c"], n_reduce=1,
                       resume_entries=entries)
    try:
        assert sched2.map_tasks[0].state is TaskState.COMPLETED
    finally:
        sched2.stop()
    sched3 = Scheduler(files=[["a", "x"], "c"], n_reduce=1,
                       resume_entries=entries)
    try:
        assert sched3.map_tasks[0].state is TaskState.UNASSIGNED
    finally:
        sched3.stop()


def test_map_batch_fn_records_match_per_file(tmp_path):
    """grep_tpu.map_batch_fn emits the SAME records as per-file map_fn —
    per-file line numbers verified through expand_records."""
    from conftest import expand_records

    from distributed_grep_tpu.apps.loader import load_application

    app = load_application(
        "distributed_grep_tpu.apps.grep_tpu",
        pattern="hello", backend="cpu",
    )
    items = [(name, blob) for name, blob in _blobs().items()]
    batched = expand_records(app.map_batch_fn(items))
    per_file = expand_records(
        [r for name, blob in items for r in app.map_fn(name, blob)]
    )
    assert [(r.key, r.value) for r in batched] == \
        [(r.key, r.value) for r in per_file]
    assert batched  # the corpus does contain matches


def test_job_batched_output_identical_and_fewer_tasks(tmp_path, monkeypatch):
    from distributed_grep_tpu.runtime.job import run_job
    from distributed_grep_tpu.utils.config import JobConfig

    monkeypatch.delenv("DGREP_BATCH_BYTES", raising=False)
    files = []
    for i in range(12):
        f = tmp_path / f"in{i:02d}.txt"
        f.write_bytes(
            b"line one\n" + (b"hello %d\n" % i) * (i % 4)
            + (b"tail hello" if i % 2 else b"")
        )
        files.append(str(f))

    def cfg(work, batch):
        return JobConfig(
            input_files=files,
            application="distributed_grep_tpu.apps.grep_tpu",
            app_options={"pattern": "hello", "backend": "cpu"},
            work_dir=str(tmp_path / work), n_reduce=3,
            batch_bytes=batch,
        )

    res_plain = run_job(cfg("plain", 0), n_workers=2)
    res_batch = run_job(cfg("batched", 1 << 20), n_workers=2)
    assert res_batch.sorted_lines() == res_plain.sorted_lines()
    assert res_plain.metrics["counters"]["map_tasks"] == 12
    assert res_batch.metrics["counters"]["map_tasks"] < 12


def test_job_batched_app_without_map_batch_fn(tmp_path, monkeypatch):
    """Apps lacking map_batch_fn (the CPU reference-mirror grep app) get
    map_fn per member inside the one batched task — same records, fewer
    tasks."""
    from distributed_grep_tpu.runtime.job import run_job
    from distributed_grep_tpu.utils.config import JobConfig

    monkeypatch.delenv("DGREP_BATCH_BYTES", raising=False)
    files = []
    for i in range(6):
        f = tmp_path / f"in{i}.txt"
        f.write_text(f"hello {i}\nnope\n")
        files.append(str(f))

    def cfg(work, batch):
        return JobConfig(
            input_files=files,
            application="distributed_grep_tpu.apps.grep",
            app_options={"pattern": "hello"},
            work_dir=str(tmp_path / work), n_reduce=2, batch_bytes=batch,
        )

    res_plain = run_job(cfg("plain", 0), n_workers=2)
    res_batch = run_job(cfg("batched", 1 << 20), n_workers=2)
    assert res_batch.sorted_lines() == res_plain.sorted_lines()
    assert res_batch.metrics["counters"]["map_tasks"] == 1


def test_cli_recursive_batched_equals_unbatched(tmp_path, capsys, monkeypatch):
    from distributed_grep_tpu.__main__ import main

    d = tmp_path / "tree"
    (d / "sub").mkdir(parents=True)
    (d / "a.txt").write_text("hello a\nnothing\n")
    (d / "sub" / "b.txt").write_text("nothing\nhello b")
    (d / "c.txt").write_text("no match here\n")

    monkeypatch.setenv("DGREP_BATCH_BYTES", "0")
    assert main(["grep", "-r", "hello", str(d)]) == 0
    unbatched = capsys.readouterr().out
    monkeypatch.delenv("DGREP_BATCH_BYTES")
    assert main(["grep", "-r", "hello", str(d)]) == 0
    batched = capsys.readouterr().out
    assert batched == unbatched
    assert "hello a" in batched and "hello b" in batched


def test_scan_batch_emits_batch_span():
    from distributed_grep_tpu.utils import spans as spans_mod

    buf = spans_mod.SpanBuffer()
    eng = GrepEngine("hello", backend="cpu", batch_bytes=1 << 20)
    with spans_mod.task_context(buf, job="j", worker=0, task=1, attempt="a"):
        eng.scan_batch([("a", b"hello\n"), ("b", b"world\n")])
    recs = buf.drain(limit=buf.cap)
    batch_spans = [r for r in recs if r.get("name") == "scan:batch"]
    assert len(batch_spans) == 1
    args = batch_spans[0]["args"]
    assert args["files"] == 2 and args["matches"] == 1
    assert 0 < args["fill_ratio"] <= 1


def test_scan_result_type_stability():
    eng = GrepEngine("hello", backend="cpu")
    for _, res in eng.scan_batch([("a", b"hello\n"), ("e", b"")]):
        assert isinstance(res, ScanResult)
        assert res.matched_lines.dtype == np.int64

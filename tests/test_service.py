"""Grep-as-a-service suite: the persistent multi-tenant coordinator
(runtime/service.py) and the cross-job compiled-model cache
(ops/engine.cached_engine).

Covers ISSUE 6's acceptance bars end to end:

* warm resubmit of an identical pattern registers compile_cache_hits and
  SKIPS engine reconstruction (GrepEngine.__init__ spy);
* two jobs submitted concurrently over SHARED workers produce outputs
  byte-identical to the same jobs run serially via run_job;
* a worker killed mid-job-A while job-B runs re-executes only A's attempt
  (B finishes with zero retries) — the faults-matrix pattern, multi-tenant;
* cancel leaves the other job's result intact;
* admission control (queue depth / running cap) rejects loudly;
* the one-shot serve_coordinator / cmd_coordinator stdout contract is
  unperturbed by the service layer (back-compat pin; bench.py's own
  one-JSON-line contract is pinned by tests/test_bench_contract.py).

Standalone: ``python -m pytest tests/test_service.py -q``.  CPU-only; the
grep engines run their native/host paths (backend "cpu").
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from distributed_grep_tpu.ops import engine as engine_mod
from distributed_grep_tpu.runtime.job import run_job
from distributed_grep_tpu.runtime.service import (
    AdmissionError,
    GrepService,
    JobState,
    ServiceServer,
)
from distributed_grep_tpu.utils.config import JobConfig

pytestmark = pytest.mark.service


@pytest.fixture(autouse=True)
def _fresh_cache(monkeypatch):
    """Each test starts with an empty compiled-model cache and zeroed
    counters, and never self-calibrates (deterministic, device-free)."""
    monkeypatch.setenv("DGREP_NO_CALIBRATE", "1")
    engine_mod.model_cache_clear()
    yield
    engine_mod.model_cache_clear()


@pytest.fixture
def service(tmp_path):
    svc = GrepService(
        work_root=tmp_path / "svc",
        task_timeout_s=5.0,
        sweep_interval_s=0.1,
    )
    yield svc
    svc.stop()


def grep_config(corpus, pattern="hello", **kw) -> JobConfig:
    defaults = dict(
        input_files=[str(p) for p in corpus.values()],
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={"pattern": pattern, "backend": "cpu"},
        n_reduce=3,
    )
    defaults.update(kw)
    return JobConfig(**defaults)


def outputs_by_name(paths) -> dict[str, bytes]:
    return {Path(p).name: Path(p).read_bytes() for p in paths}


# --------------------------------------------------------- model cache unit

def test_cached_engine_hit_returns_same_object():
    e1, v1 = engine_mod.cached_engine("needle", ignore_case=False, backend="cpu")
    e2, v2 = engine_mod.cached_engine("needle", ignore_case=False, backend="cpu")
    e3, v3 = engine_mod.cached_engine("other", ignore_case=False, backend="cpu")
    assert (v1, v2, v3) == ("miss", "hit", "miss")
    assert e1 is e2 and e1 is not e3
    c = engine_mod.model_cache_counters()
    assert c["compile_cache_hits"] == 1 and c["compile_cache_misses"] == 2


def test_cached_engine_disabled_by_env(monkeypatch):
    monkeypatch.setenv("DGREP_MODEL_CACHE", "0")
    e1, v1 = engine_mod.cached_engine("needle", backend="cpu")
    e2, v2 = engine_mod.cached_engine("needle", backend="cpu")
    assert v1 == v2 == "off" and e1 is not e2
    assert engine_mod.model_cache_counters() == {}  # untouched


def test_cached_engine_lru_eviction(monkeypatch):
    monkeypatch.setenv("DGREP_MODEL_CACHE", "2")
    engine_mod.cached_engine("p1", backend="cpu")
    engine_mod.cached_engine("p2", backend="cpu")
    engine_mod.cached_engine("p3", backend="cpu")  # evicts p1 (LRU)
    c = engine_mod.model_cache_counters()
    assert c["compile_cache_evictions"] == 1
    _, v = engine_mod.cached_engine("p3", backend="cpu")
    assert v == "hit"
    _, v = engine_mod.cached_engine("p1", backend="cpu")
    assert v == "miss"  # was evicted


def test_cached_engine_unhashable_args_bypass():
    class Opaque:  # an options object with no stable identity key
        __hash__ = None

    e, v = engine_mod.cached_engine("needle", backend="cpu",
                                    device_min_bytes=1 << 20)
    assert v == "miss"
    e2, v2 = engine_mod.cached_engine("needle", backend="cpu",
                                      devices=[Opaque()])
    assert v2 == "off" and e2 is not e


def test_cached_engine_mesh_and_device_list_bypass():
    """REAL meshes must bypass explicitly: jax.sharding.Mesh hashes by
    VALUE, so the unhashability guard alone would cache-share one
    tenant's mesh engine (mutated _accel_cached/demotion state and all)
    with the next — the off verdict must come from the mesh key itself.
    Explicit device LISTS likewise; symbolic devices='all' (the grep_tpu
    default) stays cacheable."""
    from distributed_grep_tpu.parallel.mesh import make_mesh

    mesh = make_mesh((2,), ("data",))
    e, v = engine_mod.cached_engine("needle", backend="device", mesh=mesh,
                                    interpret=True)
    assert v == "off"
    e2, v2 = engine_mod.cached_engine("needle", backend="device", mesh=mesh,
                                      interpret=True)
    assert v2 == "off" and e2 is not e
    assert engine_mod.model_cache_counters() == {}  # never touched
    import jax

    _, v3 = engine_mod.cached_engine("needle", backend="cpu",
                                     devices=jax.local_devices()[:1])
    assert v3 == "off"
    _, v4 = engine_mod.cached_engine("needle", backend="cpu", devices="all")
    assert v4 == "miss"  # the symbolic form is a stable key


def test_invalidate_cached_engine_counts_eviction():
    e, _ = engine_mod.cached_engine("needle", backend="cpu")
    engine_mod.invalidate_cached_engine(e)
    c = engine_mod.model_cache_counters()
    assert c["compile_cache_evictions"] == 1
    _, v = engine_mod.cached_engine("needle", backend="cpu")
    assert v == "miss"  # invalidation forced a rebuild


def test_cache_counters_stamped_into_engine_stats():
    e, _ = engine_mod.cached_engine("needle", backend="cpu")
    engine_mod.cached_engine("needle", backend="cpu")  # a hit
    e.scan(b"a needle in a haystack\n")
    assert e.stats["compile_cache_hits"] == 1
    assert e.stats["compile_cache_misses"] == 1


# ------------------------------------------------------- service end to end

def test_late_reduce_attempt_on_terminal_job_aborts_not_done(
        tmp_path, corpus, service):
    """A duplicate reduce attempt that outlives its job's finalize must
    be ABORTED, never told done: done=True let a late attempt (timeout
    churn spawns several) treat its PARTIAL shuffle cursor as complete
    and rename a short output over the finalized job's committed file
    (posix rename-last-wins — caught by the chaos matrix as a rare
    byte-identity failure)."""
    from distributed_grep_tpu.runtime import rpc as rpc_mod

    service.start_local_workers(2)
    jid = service.submit(grep_config(corpus))
    assert service.wait_job(jid, timeout=60), service.job_status(jid)
    reply = service.reduce_next_file(
        rpc_mod.ReduceNextFileArgs(task_id=0, files_processed=1,
                                   job_id=jid, worker_id=99),
        timeout=0.1,
    )
    assert getattr(reply, "abort", False) and not reply.done
    # unknown/evicted job ids abort the attempt too
    reply2 = service.reduce_next_file(
        rpc_mod.ReduceNextFileArgs(task_id=0, files_processed=0,
                                   job_id="job-999", worker_id=99),
        timeout=0.1,
    )
    assert getattr(reply2, "abort", False) and not reply2.done


def test_service_single_job_matches_run_job(tmp_path, corpus, service):
    service.start_local_workers(2)
    jid = service.submit(grep_config(corpus))
    assert service.wait_job(jid, timeout=60), service.job_status(jid)
    res = service.job_result(jid)
    assert res["state"] == JobState.DONE

    oracle = run_job(
        grep_config(corpus, work_dir=str(tmp_path / "serial")), n_workers=2
    )
    assert outputs_by_name(res["outputs"]) == outputs_by_name(
        oracle.output_files
    )


def test_warm_resubmit_hits_cache_and_skips_rebuild(tmp_path, corpus,
                                                    service, monkeypatch):
    """ISSUE 6 acceptance: the SECOND submit of an identical pattern (after
    an intervening different pattern, so the app-level same-config
    short-circuit cannot answer) registers >= 1 compile_cache_hits and
    constructs NO new engine.  Result tier off: the round-20 result
    cache would answer the resubmit without any engine touch at all
    (its own pins live in tests/test_result_cache.py)."""
    service._result_store = None
    constructions = []
    orig_init = engine_mod.GrepEngine.__init__

    def spying_init(self, *a, **kw):
        constructions.append(a)
        return orig_init(self, *a, **kw)

    monkeypatch.setattr(engine_mod.GrepEngine, "__init__", spying_init)
    service.start_local_workers(1)  # ONE worker: no sibling warms the key
    j1 = service.submit(grep_config(corpus, pattern="hello"))
    assert service.wait_job(j1, timeout=60)
    j2 = service.submit(grep_config(corpus, pattern="fox"))
    assert service.wait_job(j2, timeout=60)
    built_before = len(constructions)
    hits_before = engine_mod.model_cache_counters().get(
        "compile_cache_hits", 0
    )
    # warm resubmit of the first pattern
    j3 = service.submit(grep_config(corpus, pattern="hello"))
    assert service.wait_job(j3, timeout=60)
    assert service.job_result(j3)["state"] == JobState.DONE
    assert len(constructions) == built_before  # model rebuild skipped
    hits = engine_mod.model_cache_counters()["compile_cache_hits"]
    assert hits >= hits_before + 1
    # identical outputs cold vs warm
    assert outputs_by_name(service.job_result(j1)["outputs"]) == \
        outputs_by_name(service.job_result(j3)["outputs"])


def test_concurrent_jobs_byte_identical_to_serial(tmp_path, corpus, service):
    """ISSUE 6 acceptance: two jobs submitted concurrently to one daemon
    over SHARED workers produce outputs byte-identical to the same jobs
    run serially via run_job."""
    service.start_local_workers(2)
    cfg_a = grep_config(corpus, pattern="hello")
    cfg_b = grep_config(corpus, pattern="fox", n_reduce=2)
    ja = service.submit(cfg_a)
    jb = service.submit(cfg_b)
    assert service.wait_job(ja, timeout=60), service.job_status(ja)
    assert service.wait_job(jb, timeout=60), service.job_status(jb)
    got_a = outputs_by_name(service.job_result(ja)["outputs"])
    got_b = outputs_by_name(service.job_result(jb)["outputs"])

    want_a = outputs_by_name(run_job(
        grep_config(corpus, pattern="hello",
                    work_dir=str(tmp_path / "sa")), n_workers=2
    ).output_files)
    want_b = outputs_by_name(run_job(
        grep_config(corpus, pattern="fox", n_reduce=2,
                    work_dir=str(tmp_path / "sb")), n_workers=2
    ).output_files)
    assert got_a == want_a
    assert got_b == want_b


def test_worker_kill_mid_job_a_reexecutes_only_a(tmp_path, corpus):
    """ISSUE 6 acceptance (faults-style, multi-tenant): SIGKILL-shaped
    worker death mid-job-A while job-B is running re-executes only A's
    attempt — B completes with zero retries and both outputs stay exact."""
    from distributed_grep_tpu.runtime.worker import WorkerKilled

    svc = GrepService(
        work_root=tmp_path / "svc",
        task_timeout_s=2.0,
        sweep_interval_s=0.1,
    )
    try:
        # the FIRST worker (whichever one) to read a map split of job A
        # (job ids are deterministic: job-1 = first submit) dies there —
        # keyed on the current THREAD's loop so the hook sees the job of
        # the worker actually running it
        from distributed_grep_tpu.runtime import worker as worker_mod

        loops_by_thread: dict[str, object] = {}
        kill_lock = threading.Lock()
        killed = {"n": 0}

        def die_on_job_a_map():
            loop = loops_by_thread.get(threading.current_thread().name)
            if loop is None or loop._rpc_job_id != "job-1":
                return
            with kill_lock:
                if killed["n"]:
                    return
                killed["n"] += 1
            raise WorkerKilled()

        orig_run = worker_mod.WorkerLoop.run

        def capturing_run(self):
            loops_by_thread[threading.current_thread().name] = self
            return orig_run(self)

        worker_mod.WorkerLoop.run, saved = capturing_run, orig_run
        try:
            svc.start_local_workers(
                2, fault_hooks_per_worker=[
                    {"after_map_read": die_on_job_a_map},
                    {"after_map_read": die_on_job_a_map},
                ]
            )
        finally:
            worker_mod.WorkerLoop.run = saved
        ja = svc.submit(grep_config(corpus, pattern="hello"))
        jb = svc.submit(grep_config(corpus, pattern="fox"))
        assert ja == "job-1"
        assert svc.wait_job(ja, timeout=60), svc.job_status(ja)
        assert svc.wait_job(jb, timeout=60), svc.job_status(jb)
        assert killed["n"] == 1  # the fault actually fired

        rec_a, rec_b = svc.record(ja), svc.record(jb)
        assert rec_a.metrics.counters.get("map_retries", 0) >= 1
        assert rec_b.metrics.counters.get("map_retries", 0) == 0
        assert rec_b.metrics.counters.get("reduce_retries", 0) == 0

        # both jobs' outputs byte-identical to serial runs
        for jid, pat, sub in ((ja, "hello", "sa"), (jb, "fox", "sb")):
            want = outputs_by_name(run_job(
                grep_config(corpus, pattern=pat,
                            work_dir=str(tmp_path / sub)), n_workers=2
            ).output_files)
            assert outputs_by_name(svc.job_result(jid)["outputs"]) == want
    finally:
        svc.stop()


def test_cancel_leaves_other_job_intact(tmp_path, corpus, service):
    # cancel job A before any worker exists (deterministically un-started
    # work), then attach workers: B must complete exactly, A stays
    # cancelled with no result.
    ja = service.submit(grep_config(corpus, pattern="hello"))
    jb = service.submit(grep_config(corpus, pattern="fox"))
    assert service.cancel(ja) == JobState.CANCELLED
    service.start_local_workers(2)
    assert service.wait_job(jb, timeout=60), service.job_status(jb)
    assert service.job_status(ja)["state"] == JobState.CANCELLED
    with pytest.raises(RuntimeError):
        service.job_result(ja)
    want = outputs_by_name(run_job(
        grep_config(corpus, pattern="fox",
                    work_dir=str(tmp_path / "sb")), n_workers=2
    ).output_files)
    assert outputs_by_name(service.job_result(jb)["outputs"]) == want


def test_admission_control_rejects_beyond_queue(tmp_path, corpus):
    svc = GrepService(work_root=tmp_path / "svc", max_jobs=1, queue_depth=1)
    try:
        # no workers attached: jobs stay running/queued
        svc.submit(grep_config(corpus))          # running slot
        svc.submit(grep_config(corpus))          # queued slot
        with pytest.raises(AdmissionError):
            svc.submit(grep_config(corpus))      # over the queue cap
    finally:
        svc.stop()


def test_submit_rejects_unreadable_inputs(tmp_path, corpus, service):
    cfg = grep_config(corpus)
    cfg.input_files = [str(tmp_path / "no-such-file.txt")]
    with pytest.raises(ValueError):
        service.submit(cfg)


def test_env_knob_accessors(monkeypatch):
    from distributed_grep_tpu.runtime.service import (
        env_service_max_jobs,
        env_service_queue,
    )

    monkeypatch.setenv("DGREP_SERVICE_MAX_JOBS", "7")
    monkeypatch.setenv("DGREP_SERVICE_QUEUE", "3")
    assert env_service_max_jobs() == 7
    assert env_service_queue() == 3
    monkeypatch.setenv("DGREP_SERVICE_MAX_JOBS", "bogus")
    assert env_service_max_jobs(5) == 5  # malformed keeps the default
    monkeypatch.setenv("DGREP_MODEL_CACHE", "bogus")
    assert engine_mod.env_model_cache_entries(9) == 9


# ------------------------------------------------------------- HTTP surface

def test_http_api_submit_status_result_and_telemetry(tmp_path, corpus,
                                                     monkeypatch):
    """The full HTTP surface: POST /jobs -> GET /jobs/<id> -> result;
    service /status exposes queue/jobs/workers with piggybacked
    compile_cache_* counters; per-job events.jsonl carries the
    cache:hit|miss instants and trace-export renders them.  Result tier
    off: the resubmit must SCAN for compile_cache_hits to register."""
    monkeypatch.setenv("DGREP_RESULT_CACHE", "0")
    svc = GrepService(
        work_root=tmp_path / "svc", spans=True,
        task_timeout_s=5.0, sweep_interval_s=0.1,
    )
    server = ServiceServer(svc)
    server.start()
    base = f"http://127.0.0.1:{server.port}"

    def call(method, path, body=None):
        req = urllib.request.Request(f"{base}{path}", data=body, method=method)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    try:
        svc.start_local_workers(1)
        cfg = grep_config(corpus, spans=True)
        jid = call("POST", "/jobs", cfg.to_json().encode())["job_id"]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = call("GET", f"/jobs/{jid}")
            if st["state"] in (JobState.DONE, JobState.FAILED):
                break
            time.sleep(0.1)
        assert st["state"] == JobState.DONE, st
        assert st["map"]["completed"] == st["map"]["total"] == len(corpus)
        res = call("GET", f"/jobs/{jid}/result")
        assert res["outputs"]
        # warm resubmit over HTTP: different pattern in between, then the
        # original again -> >= 1 cache hit visible in /status
        j2 = call("POST", "/jobs",
                  grep_config(corpus, pattern="fox").to_json().encode())
        j3 = call("POST", "/jobs", cfg.to_json().encode())
        for j in (j2["job_id"], j3["job_id"]):
            assert svc.wait_job(j, timeout=60)
        status = call("GET", "/status")
        assert status["service"] is True
        assert status["compile_cache"]["compile_cache_hits"] >= 1
        rows = list(status["workers"].values())
        assert rows and any(
            "compile_cache_hits" in (r.get("metrics") or {}) for r in rows
        )
        # cache instants on the span pipeline, through trace-export
        ev_path = tmp_path / "svc" / jid / "events.jsonl"
        assert ev_path.exists()
        names = {
            json.loads(line).get("name")
            for line in ev_path.read_text().splitlines() if line.strip()
        }
        assert "cache:miss" in names or "cache:hit" in names
        from distributed_grep_tpu.utils.spans import (
            EventLog,
            export_chrome_trace,
        )

        doc = export_chrome_trace(EventLog.read(ev_path))
        assert any(
            e.get("name", "").startswith("cache:")
            for e in doc["traceEvents"]
        )
        # unknown job and not-done result answer 404/409
        with pytest.raises(urllib.error.HTTPError) as ei:
            call("GET", "/jobs/job-999")
        assert ei.value.code == 404
    finally:
        svc.stop()
        server.shutdown()


def test_http_admission_answers_429(tmp_path, corpus):
    svc = GrepService(work_root=tmp_path / "svc", max_jobs=1, queue_depth=0)
    server = ServiceServer(svc)
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        body = grep_config(corpus).to_json().encode()

        def post():
            req = urllib.request.Request(f"{base}/jobs", data=body,
                                         method="POST")
            req.add_header("Content-Type", "application/json")
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())

        post()  # fills the single running slot (no workers attached)
        with pytest.raises(urllib.error.HTTPError) as ei:
            post()
        assert ei.value.code == 429
        # malformed config answers 400
        req = urllib.request.Request(f"{base}/jobs", data=b'{"n_reduce": 0}',
                                     method="POST")
        req.add_header("Content-Type", "application/json")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 400
    finally:
        svc.stop()
        server.shutdown()


def test_http_worker_attach_serves_service_jobs(tmp_path, corpus):
    """A stock `dgrep worker`-shaped attach (run_http_worker) detects the
    service daemon, scopes its data plane per job, and completes jobs."""
    from distributed_grep_tpu.runtime.http_transport import run_http_worker

    svc = GrepService(
        work_root=tmp_path / "svc", task_timeout_s=5.0, sweep_interval_s=0.1
    )
    server = ServiceServer(svc)
    server.start()
    try:
        t = threading.Thread(
            target=lambda: run_http_worker(
                addr=f"127.0.0.1:{server.port}", n_parallel=1
            ),
            daemon=True,
        )
        t.start()
        jid = svc.submit(grep_config(corpus))
        assert svc.wait_job(jid, timeout=60), svc.job_status(jid)
        want = outputs_by_name(run_job(
            grep_config(corpus, work_dir=str(tmp_path / "serial")),
            n_workers=2,
        ).output_files)
        assert outputs_by_name(svc.job_result(jid)["outputs"]) == want
    finally:
        svc.stop()
        server.shutdown()
        t.join(timeout=15)


# --------------------------------------------- crash recovery (round 10)

def test_service_restart_preserves_history_and_id_counter(tmp_path, corpus):
    """A restarted daemon reloads terminal jobs from jobs.jsonl (results
    still answerable) and continues the job-id counter — old work dirs
    are never clobbered by a new incarnation's ids."""
    svc = GrepService(work_root=tmp_path / "svc", task_timeout_s=5.0,
                      sweep_interval_s=0.1)
    svc.start_local_workers(2)
    j1 = svc.submit(grep_config(corpus))
    assert svc.wait_job(j1, timeout=60), svc.job_status(j1)
    outputs = outputs_by_name(svc.job_result(j1)["outputs"])
    svc.stop()

    svc2 = GrepService(work_root=tmp_path / "svc", task_timeout_s=5.0,
                       sweep_interval_s=0.1)
    try:
        assert svc2.job_status(j1)["state"] == JobState.DONE
        assert outputs_by_name(svc2.job_result(j1)["outputs"]) == outputs
        j2 = svc2.submit(grep_config(corpus, pattern="fox"))
        assert j2 == "job-2"  # counter resumed past the registry's max
        svc2.start_local_workers(1)
        assert svc2.wait_job(j2, timeout=60), svc2.job_status(j2)
    finally:
        svc2.stop()


def test_service_restart_resumes_mid_job_from_journal(tmp_path, corpus):
    """Daemon death mid-job: a new service over the same work root
    resumes the RUNNING job from its journal — completed maps replay as
    done (not re-assigned), the rest run, outputs stay exact.  The first
    service is ABANDONED, not stopped: a SIGKILL runs no teardown, so
    neither does this test."""
    from distributed_grep_tpu.runtime.worker import WorkerKilled

    svc_a = GrepService(work_root=tmp_path / "svc", task_timeout_s=5.0,
                        sweep_interval_s=0.1)
    killed = {"n": 0}

    def die_on_third_read():
        killed["n"] += 1
        if killed["n"] >= 3:
            raise WorkerKilled()

    svc_a.start_local_workers(
        1, fault_hooks_per_worker=[{"after_map_read": die_on_third_read}]
    )
    j1 = svc_a.submit(grep_config(corpus))  # 3 files -> 3 map tasks
    rec_a = svc_a.record(j1)
    deadline = time.monotonic() + 30
    while rec_a.metrics.counters.get("map_completed", 0) < 2 \
            or killed["n"] < 3:
        assert time.monotonic() < deadline, rec_a.metrics.counters
        time.sleep(0.05)
    # svc_a is now abandoned mid-job (its only worker is dead)

    svc_b = GrepService(work_root=tmp_path / "svc", task_timeout_s=5.0,
                        sweep_interval_s=0.1)
    try:
        assert svc_b.job_status(j1)["state"] == JobState.RUNNING
        svc_b.start_local_workers(2)
        assert svc_b.wait_job(j1, timeout=60), svc_b.job_status(j1)
        # journal replay skipped the two committed maps
        assert svc_b.record(j1).metrics.counters.get("map_assigned", 0) <= 1
        want = outputs_by_name(run_job(
            grep_config(corpus, work_dir=str(tmp_path / "serial")),
            n_workers=2,
        ).output_files)
        assert outputs_by_name(svc_b.job_result(j1)["outputs"]) == want
    finally:
        svc_b.stop()


def test_service_restart_readmits_queued_jobs(tmp_path, corpus):
    """Queued (never-started) jobs survive a daemon death: the submit
    record alone re-admits them at restart."""
    svc_a = GrepService(work_root=tmp_path / "svc", max_jobs=1,
                        task_timeout_s=5.0, sweep_interval_s=0.1)
    j1 = svc_a.submit(grep_config(corpus))              # running slot
    j2 = svc_a.submit(grep_config(corpus, pattern="fox"))  # queued
    assert svc_a.job_status(j2)["state"] == JobState.QUEUED
    # abandon svc_a (no workers ever attached; nothing ran)

    svc_b = GrepService(work_root=tmp_path / "svc", task_timeout_s=5.0,
                        sweep_interval_s=0.1)
    try:
        assert svc_b.job_status(j1)["state"] == JobState.RUNNING
        # default max_jobs has free slots: the re-admitted job starts
        assert svc_b.job_status(j2)["state"] in (JobState.RUNNING,
                                                 JobState.QUEUED)
        svc_b.start_local_workers(2)
        for jid, pat, sub in ((j1, "hello", "sa"), (j2, "fox", "sb")):
            assert svc_b.wait_job(jid, timeout=60), svc_b.job_status(jid)
            want = outputs_by_name(run_job(
                grep_config(corpus, pattern=pat,
                            work_dir=str(tmp_path / sub)), n_workers=2
            ).output_files)
            assert outputs_by_name(svc_b.job_result(jid)["outputs"]) == want
    finally:
        svc_b.stop()


def test_service_resume_disabled_still_advances_ids(tmp_path, corpus):
    svc_a = GrepService(work_root=tmp_path / "svc")
    j1 = svc_a.submit(grep_config(corpus))
    svc_b = GrepService(work_root=tmp_path / "svc", resume=False)
    try:
        with pytest.raises(KeyError):
            svc_b.record(j1)  # not re-admitted
        j2 = svc_b.submit(grep_config(corpus, pattern="fox"))
        assert j2 == "job-2"  # but the id space is never reused
    finally:
        svc_b.stop()


def test_registry_compaction_bounds_history_and_retires_ids(tmp_path,
                                                            corpus):
    """The registry is append-only over an unbounded job stream: startup
    trims the reload to the newest terminal records, rewrites the file
    compacted, and the id_floor record keeps every dropped job's id
    retired — old work dirs are never re-minted."""
    from distributed_grep_tpu.runtime.service import (
        _MAX_TERMINAL_RECORDS,
        ServiceRegistry,
    )

    root = tmp_path / "svc"
    root.mkdir()
    reg = ServiceRegistry(root)
    cfg = grep_config(corpus)
    n_hist = _MAX_TERMINAL_RECORDS + 40
    for i in range(1, n_hist + 1):
        jid = f"job-{i}"
        reg.record_submit(jid, cfg)
        reg.record_state(jid, JobState.DONE, outputs=[])
    reg.close()
    size_before = (root / ServiceRegistry.FILENAME).stat().st_size

    svc = GrepService(work_root=root)
    try:
        # in-memory reload bounded like the live table
        terminal = [r for r in svc._jobs.values()
                    if r.state == JobState.DONE]
        assert len(terminal) == _MAX_TERMINAL_RECORDS
        # the file itself was compacted
        assert (root / ServiceRegistry.FILENAME).stat().st_size \
            < size_before
        # dropped ids stay retired: the next mint continues past ALL of
        # the history, including the trimmed-away jobs
        jid = svc.submit(grep_config(corpus))
        assert jid == f"job-{n_hist + 1}"
        jobs, floor = ServiceRegistry.replay(root)
        assert floor >= n_hist + 2
        assert "job-1" not in jobs  # trimmed out of the file
    finally:
        svc.stop()


def test_resume_fails_job_whose_inputs_vanished(tmp_path, corpus):
    """An input deleted during the outage must FAIL the resumed job, not
    re-enqueue its map forever (plan_map_splits shrugs stat failures off,
    so resume re-runs submit's readability validation)."""
    svc_a = GrepService(work_root=tmp_path / "svc")
    j1 = svc_a.submit(grep_config(corpus))
    Path(svc_a.record(j1).config.input_files[0]).unlink()
    # abandon svc_a (daemon crash); restart over the same root
    svc_b = GrepService(work_root=tmp_path / "svc")
    try:
        st = svc_b.job_status(j1)
        assert st["state"] == JobState.FAILED
        assert "unreadable" in st["error"]
    finally:
        svc_b.stop()


def test_resume_env_knob_accessor(monkeypatch):
    from distributed_grep_tpu.runtime.service import env_service_resume

    assert env_service_resume() is True
    monkeypatch.setenv("DGREP_SERVICE_RESUME", "0")
    assert env_service_resume() is False
    monkeypatch.setenv("DGREP_SERVICE_RESUME", "false")
    assert env_service_resume() is False
    monkeypatch.setenv("DGREP_SERVICE_RESUME", "1")
    assert env_service_resume() is True


# ------------------------------------------------------- back-compat pins

def test_one_shot_serve_coordinator_contract_unperturbed(tmp_path, corpus):
    """The single-job coordinator entry point still returns the status
    dict with committed "outputs" — the service layer must not perturb
    the one-shot path (run alongside an HTTP worker thread)."""
    import socket

    from distributed_grep_tpu.apps.loader import load_application
    from distributed_grep_tpu.runtime.http_coordinator import serve_coordinator
    from distributed_grep_tpu.runtime.http_transport import HttpTransport
    from distributed_grep_tpu.runtime.worker import WorkerLoop

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    cfg = JobConfig(
        input_files=[str(p) for p in corpus.values()],
        app_options={"pattern": "hello"},
        n_reduce=3,
        work_dir=str(tmp_path / "job"),
        coordinator_port=port,
    )
    app = load_application("distributed_grep_tpu.apps.grep", pattern="hello")
    result: dict = {}

    def serve():
        result.update(serve_coordinator(cfg))

    ct = threading.Thread(target=serve)
    ct.start()
    time.sleep(0.3)
    wt = threading.Thread(
        target=lambda: WorkerLoop(
            HttpTransport(f"127.0.0.1:{port}"), app
        ).run()
    )
    wt.start()
    ct.join(timeout=60)
    wt.join(timeout=15)
    assert not ct.is_alive()
    assert len(result["outputs"]) == 3
    assert result["done"] is True


def test_cmd_coordinator_stdout_one_json_line(tmp_path, corpus, capsys,
                                              monkeypatch):
    """cmd_coordinator prints EXACTLY one JSON line naming the outputs
    (scripts and the multi-process tests parse it)."""
    from distributed_grep_tpu import __main__ as cli
    from distributed_grep_tpu.runtime import http_coordinator as hc

    cfg_path = tmp_path / "job.json"
    cfg_path.write_text(JobConfig(
        input_files=[str(p) for p in corpus.values()],
        work_dir=str(tmp_path / "job"),
    ).to_json())
    monkeypatch.setattr(
        hc, "serve_coordinator",
        lambda config, resume=False: {"outputs": ["a", "b"], "done": True},
    )
    rc = cli.main(["coordinator", "--config", str(cfg_path)])
    assert rc == 0
    out = capsys.readouterr().out
    lines = [ln for ln in out.splitlines() if ln.strip()]
    assert len(lines) == 1
    assert json.loads(lines[0]) == {"outputs": ["a", "b"]}


def test_quarantine_expiry_reprobation_streak_resumes():
    """Satellite pin (round 18): quarantine EXPIRY is re-probation, not
    absolution — the failure streak resumes at threshold-1, so ONE more
    attributed timeout after a real wall-clock expiry re-quarantines the
    worker immediately, with the window doubled (episode 2); a committed
    task is what clears the whole record."""
    from distributed_grep_tpu.runtime.scheduler import (
        QUARANTINE_AFTER_FAILURES,
        WorkerHealth,
    )

    h = WorkerHealth(base_s=0.1)
    for i in range(QUARANTINE_AFTER_FAILURES - 1):
        assert h.record_failure(5) == 0.0, i  # probation: no window yet
    assert h.record_failure(5) == pytest.approx(0.1)  # episode 1
    assert h.quarantine_remaining(5) > 0
    time.sleep(0.15)  # REAL expiry — no by-hand state surgery
    assert h.quarantine_remaining(5) == 0.0  # assignable again
    # one more timeout: straight back in, doubled window — no second
    # run-up of QUARANTINE_AFTER_FAILURES consecutive failures needed
    assert h.record_failure(5) == pytest.approx(0.2)
    time.sleep(0.25)
    assert h.quarantine_remaining(5) == 0.0
    h.record_success(5)  # a committed task clears streak AND episodes
    for _ in range(QUARANTINE_AFTER_FAILURES - 1):
        assert h.record_failure(5) == 0.0
    assert h.record_failure(5) == pytest.approx(0.1)  # episode 1 again

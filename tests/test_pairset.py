"""Exact short-literal-set engine (models/pairset.py + ops/pallas_pairset):
model factorization, kernel-vs-oracle exactness (interpret mode), engine
end-to-end, and the sharded mesh form.  The pairset path's contract is
stronger than the filter engines': device words are EXACT match ends (no
confirm pass), with under-report confined to stripe heads (stitched)."""

from __future__ import annotations

import numpy as np
import pytest

from distributed_grep_tpu.models import pairset as ps
from distributed_grep_tpu.ops import layout as layout_mod


def _corpus(rng, n, pats, plant=200):
    data = bytearray(rng.integers(32, 127, size=n, dtype=np.uint8).tobytes()
                     .replace(b"\n", b" "))
    for p in rng.integers(16, n - 16, size=plant):
        pat = pats[int(rng.integers(0, len(pats)))]
        data[p : p + len(pat)] = pat
    # sprinkle newlines for line structure (never inside planted pats? a
    # clobbered plant is fine — the oracle sees the same bytes)
    for p in rng.integers(0, n, size=n // 90):
        data[p] = 0x0A
    return bytes(data)


# ------------------------------------------------------------------- model

def test_factorization_exact_on_pair_matrix():
    rng = np.random.default_rng(0)
    # structured set: products of two small groups + singles
    pats = [bytes([a, b]) for a in b"abcde" for b in b"XYZ"] + [b"q", b"7"]
    m = ps.compile_pairset(pats)
    assert m.n_classes <= 32
    # oracle the factorization against brute force membership
    for _ in range(2000):
        b0, b1 = int(rng.integers(0, 256)), int(rng.integers(0, 256))
        want = bytes([b0, b1]) in set(pats) or bytes([b1]) in set(pats)
        if m.transposed:
            got = bool((m.words[b0] >> m.rowcls[b1]) & 1)
        else:
            got = bool((m.words[b1] >> m.rowcls[b0]) & 1)
        assert got == want, (b0, b1)


def test_transpose_orientation_rescues_column_structure():
    # >32 distinct ROW patterns (each b0 pairs with a distinct subset of 6
    # second bytes) but only ~7 distinct COLUMN patterns: the row
    # orientation fails, the transpose factorizes
    b1s = b"uvwxyz"
    pats = []
    for i in range(40):
        for j in range(6):
            if (i + 1) >> j & 1:
                pats.append(bytes([100 + i, b1s[j]]))
    m = ps.compile_pairset(pats)
    assert m.transposed
    sp = set(pats)
    for p in pats:
        assert (m.words[p[0]] >> m.rowcls[p[1]]) & 1
    # and a non-member pair stays False
    assert not (m.words[100] >> m.rowcls[ord("u")]) & 1 or \
        bytes([100, ord("u")]) in sp


def test_rejects_unrepresentable_and_bad_literals():
    rng = np.random.default_rng(2)
    dense = sorted({bytes(rng.integers(32, 127, size=2).tolist())
                    for _ in range(3000)})
    with pytest.raises(ps.PairsetError):
        ps.compile_pairset(dense)
    with pytest.raises(ps.PairsetError):
        ps.compile_pairset([b"abc"])  # too long
    with pytest.raises(ps.PairsetError):
        ps.compile_pairset([b"a\nb"[1:3]])  # contains newline
    with pytest.raises(ps.PairsetError):
        ps.compile_pairset([])


def test_ignore_case_folds_members_and_oracle():
    m = ps.compile_pairset(["AB", "c"], ignore_case=True)
    ends = ps.reference_ends(m, b"xAbY cC")
    # 'Ab' folds to 'ab' (end 3); 'c'/'C' both match (ends 6, 7)
    assert ends.tolist() == [3, 6, 7]


# ------------------------------------------------------------------ kernel

def _scan_offsets(data, model, lay):
    from distributed_grep_tpu.ops import pallas_pairset, scan_jnp
    from distributed_grep_tpu.ops import sparse as sparse_mod

    arr = layout_mod.to_device_array(data, lay)
    words = pallas_pairset.pairset_scan_words(arr, model, interpret=True)
    idx, vals = scan_jnp.sparse_nonzero(words)
    return np.unique(sparse_mod.offsets_from_sparse_words(
        np.asarray(idx), np.asarray(vals), lay
    ))


@pytest.mark.parametrize("ignore_case", [False, True])
def test_kernel_matches_stripe_oracle(ignore_case):
    rng = np.random.default_rng(3)
    pats = [b"ab", b"zq", b"9!", b"x", bytes([200, 13])]
    if ignore_case:
        pats = [b"AB", b"zQ", b"9!", b"X", bytes([200, 13])]
    m = ps.compile_pairset(pats, ignore_case=ignore_case)
    data = _corpus(rng, 3_000_000, [p.lower() if ignore_case else p
                                    for p in pats])
    lay = layout_mod.choose_layout(
        len(data), target_lanes=4096, min_chunk=512,
        lane_multiple=4096, chunk_multiple=512,
    )
    got = _scan_offsets(data, m, lay)
    want = []
    for s0 in [0] + lay.stripe_starts().tolist():
        s1 = min(s0 + lay.chunk, len(data))
        want.extend((ps.reference_ends(m, data[s0:s1]) + s0).tolist())
    want = np.unique(np.asarray(want, dtype=np.int64))
    assert np.array_equal(got, want)


# ------------------------------------------------------------------ engine

def test_engine_pairset_end_to_end_exact():
    from distributed_grep_tpu.ops.engine import GrepEngine

    rng = np.random.default_rng(4)
    pats = [b"ab", b"cd", b"Zq", b"!", b"9"]
    eng = GrepEngine(patterns=[p.decode() for p in pats], interpret=True)
    assert eng.mode == "pairset"
    data = _corpus(rng, 400_000, pats)
    got = set(eng.scan(data).matched_lines.tolist())
    assert got == ps.exact_match_lines(eng.pairset, data)
    # stats carry exact end offsets, no candidates (nothing to confirm)
    assert eng.stats["end_offsets"] >= 1
    assert eng.stats.get("candidates", 0) == 0


def test_engine_pairset_ignore_case_exact():
    from distributed_grep_tpu.ops.engine import GrepEngine

    rng = np.random.default_rng(5)
    pats = [b"AB", b"cD", b"Q"]
    eng = GrepEngine(patterns=[p.decode() for p in pats], ignore_case=True,
                     interpret=True)
    assert eng.mode == "pairset"
    data = _corpus(rng, 200_000, [p.lower() for p in pats])
    got = set(eng.scan(data).matched_lines.tolist())
    assert got == ps.exact_match_lines(eng.pairset, data)


def test_engine_pairset_multi_segment_streams():
    from distributed_grep_tpu.ops.engine import GrepEngine

    rng = np.random.default_rng(6)
    pats = [b"ab", b"x"]
    eng = GrepEngine(patterns=["ab", "x"], interpret=True,
                     segment_bytes=64 * 1024)
    data = _corpus(rng, 300_000, pats)
    got = set(eng.scan(data).matched_lines.tolist())
    assert got == ps.exact_match_lines(eng.pairset, data)


def test_engine_pairset_cpu_fallback_matches():
    """Without a kernel backend the same engine answers from the host
    path, identically."""
    from distributed_grep_tpu.ops.engine import GrepEngine

    rng = np.random.default_rng(7)
    pats = [b"ab", b"x"]
    data = _corpus(rng, 100_000, pats)
    dev = GrepEngine(patterns=["ab", "x"], interpret=True)
    host = GrepEngine(patterns=["ab", "x"], backend="cpu")
    assert dev.mode == "pairset" and host.mode == "native"
    assert dev.scan(data).matched_lines.tolist() == \
        host.scan(data).matched_lines.tolist()


# -------------------------------------------------------------------- mesh

def test_sharded_pairset_bit_identical_and_engine_mesh():
    import jax

    from distributed_grep_tpu.ops import pallas_pairset
    from distributed_grep_tpu.ops.engine import GrepEngine
    from distributed_grep_tpu.parallel import sharded_kernels as sk
    from distributed_grep_tpu.parallel.mesh import make_mesh

    mesh8 = make_mesh((8,), ("data",))
    rng = np.random.default_rng(8)
    pats = [b"ab", b"zq", b"x"]
    m = ps.compile_pairset(pats)
    mult = sk.mesh_lane_multiple(mesh8, "data")
    data = _corpus(rng, 2 * mult * 512, pats)
    lay = layout_mod.choose_layout(
        len(data), target_lanes=mult, min_chunk=512,
        lane_multiple=mult, chunk_multiple=512,
    )
    arr = layout_mod.to_device_array(data, lay)
    words, total = sk.sharded_pairset_words(arr, m, mesh8, interpret=True)
    ref = pallas_pairset.pairset_scan_words(arr, m, interpret=True)
    assert (np.asarray(words) == np.asarray(ref)).all()
    assert int(total) == int(np.count_nonzero(np.asarray(ref)))
    jax.block_until_ready(words)

    eng = GrepEngine(patterns=["ab", "zq", "x"], mesh=mesh8, interpret=True)
    assert eng.mode == "pairset"
    got = set(eng.scan(data).matched_lines.tolist())
    assert got == ps.exact_match_lines(eng.pairset, data)
    assert eng.stats.get("psum_candidates", 0) >= 1


@pytest.mark.parametrize("seed", range(6))
def test_pairset_fuzz_engine_vs_oracle(seed):
    """Random structured short sets (second bytes from <= 5 values keeps
    the row partition within 32 classes by construction) through the full
    engine in interpret mode — exact vs the line oracle every draw."""
    from distributed_grep_tpu.ops.engine import GrepEngine

    rng = np.random.default_rng(100 + seed)
    ic = bool(seed % 2)
    cols = rng.choice(
        [c for c in range(33, 127) if c != 0x0A], size=5, replace=False
    )
    pats = sorted({
        bytes([int(rng.integers(33, 127)), int(cols[rng.integers(0, 5)])])
        for _ in range(int(rng.integers(3, 40)))
    } | {bytes([int(cols[0])])})
    eng = GrepEngine(patterns=pats, ignore_case=ic, interpret=True,
                     segment_bytes=1 << 17)
    model = ps.compile_pairset(pats, ignore_case=ic)
    # A draw whose whole-set density is over the ceiling legitimately
    # takes the round-4 density gate OFF the pure pairset mode: either to
    # the native route, or — when the 2-byte members are FDR-hostable and
    # the 1-byte members alone price under the ceiling — to the FDR
    # filter with the pairset sidecar.  The oracle check below holds
    # either way.
    from distributed_grep_tpu.models.fdr import FP_CEILING_PER_BYTE

    if ps.expected_match_density(pats, ignore_case=ic) > FP_CEILING_PER_BYTE:
        assert eng.mode in ("native", "dfa", "fdr"), (eng.mode, pats)
    else:
        assert eng.mode == "pairset", [p for p in pats]
    data = _corpus(rng, 300_000, model.patterns)
    got = set(eng.scan(data).matched_lines.tolist())
    assert got == ps.exact_match_lines(model, data), (seed, pats)



# ------------------------------------------------------- density gate (r4)

def test_expected_match_density_models_text_and_binary():
    """The estimator takes the max over the uniform-floored and the
    prose-conditional priors: ' ' is dense under the text model (~16% of
    prose bytes) even though the floored prior dilutes it below the
    ceiling; a rare digraph is ~0 under both."""
    assert ps.expected_match_density([" "]) > 0.15
    assert ps.expected_match_density(["zq"]) < 1e-4
    # ignore_case folds uppercase mass into the folded member
    assert (ps.expected_match_density(["a"], ignore_case=True)
            > ps.expected_match_density(["a"]))


def test_dense_short_set_routes_to_native_not_pairset():
    """A short set with an over-ceiling expected match density (' ' is
    ~16% of prose bytes) must not ride the device kernel: the O(matches)
    sparse coordinate fetch would swamp the scan it feeds (round-4 review
    finding).  It keeps the loud native-host route and stays exact."""
    from distributed_grep_tpu.ops.engine import GrepEngine

    eng = GrepEngine(patterns=[" ", "ab"], interpret=True)
    assert eng.mode in ("native", "dfa")
    got = set(eng.scan(b"a b\nxyz\nqab\ncc c\n").matched_lines.tolist())
    assert got == {1, 3, 4}

"""Crash/fault-injection matrix over the blob-store commit layer.

Exercises runtime/store.py end-to-end: for every CrashPoint × {map commit,
reduce commit} × {PosixStore, NonAtomicStore}, a worker is killed at that
exact instruction of the commit protocol and the job must still finish with
byte-identical output — via a surviving worker (sweeper re-issue) or via a
coordinator restart replaying an idempotent journal.  Also pins the
non-atomic resolution invariants directly (torn parts/records invisible,
duplicate attempts resolve to exactly one winner) and the journal's
torn-tail handling.

Standalone: ``python -m pytest tests/test_store_faults.py -q``.  CPU-only
and device-free by construction — the plain grep app never touches a
backend, and DGREP_NO_CALIBRATE keeps any engine construction inert.
"""

import io
import threading
import time

import pytest

from distributed_grep_tpu.apps.loader import load_application
from distributed_grep_tpu.runtime.job import run_job
from distributed_grep_tpu.runtime.journal import TaskJournal
from distributed_grep_tpu.runtime.scheduler import Scheduler
from distributed_grep_tpu.runtime.store import (
    CrashPoint,
    FaultStore,
    NonAtomicStore,
    decode_record,
    encode_record,
    make_store,
)
from distributed_grep_tpu.runtime.transport import LocalTransport
from distributed_grep_tpu.runtime.worker import WorkerKilled, WorkerLoop
from distributed_grep_tpu.utils.config import JobConfig
from distributed_grep_tpu.utils.io import WorkDir

pytestmark = pytest.mark.faults

STORES = ["posix", "nonatomic"]


@pytest.fixture(autouse=True)
def _no_calibrate(monkeypatch):
    """Deterministic, device-free matrix runs: no engine self-calibration
    probes.  Scoped per test (an import-time os.environ write would leak
    into every other module collected in the same pytest process)."""
    monkeypatch.setenv("DGREP_NO_CALIBRATE", "1")


def make_config(tmp_path, corpus, sub="job", **kw):
    defaults = dict(
        input_files=[str(p) for p in corpus.values()],
        application="distributed_grep_tpu.apps.grep",
        app_options={"pattern": "hello"},
        n_reduce=3,
        work_dir=str(tmp_path / sub),
        task_timeout_s=0.5,
        sweep_interval_s=0.05,
    )
    defaults.update(kw)
    return JobConfig(**defaults)


def output_bytes(res) -> list[bytes]:
    return [p.read_bytes() for p in res.output_files]


def clean_output(tmp_path, corpus, store) -> list[bytes]:
    """Baseline: the byte-exact outputs of an uninjected run on this store."""
    res = run_job(make_config(tmp_path, corpus, sub=f"clean-{store}",
                              store=store), n_workers=2)
    out = output_bytes(res)
    assert out and any(b"hello" in b for b in out)
    return out


# ------------------------------------------------------------- store units

def test_config_store_names_match_registry():
    """utils/config validates store names from a literal (importing the
    runtime package per JobConfig would be absurdly heavy); this pins the
    literal to the factory registry so they cannot drift."""
    from distributed_grep_tpu.runtime.store import STORES
    from distributed_grep_tpu.utils.config import STORE_NAMES

    assert STORE_NAMES == frozenset(STORES)
    with pytest.raises(ValueError, match="store must be one of"):
        JobConfig(store="bogus")


def test_record_roundtrip_and_torn_detection():
    payload = {"parts": [0, 2], "kind": "map", "task_id": 3}
    data = encode_record(payload)
    assert decode_record(data) == payload
    # every strict prefix is detectably torn — never half-truth
    for cut in range(len(data)):
        assert decode_record(data[:cut]) is None
    assert decode_record(data[:-2] + b"x\n") is None  # bit-flipped crc


@pytest.mark.parametrize("store_name", STORES)
def test_store_put_get_visibility(tmp_path, store_name):
    store = make_store(store_name)
    p = tmp_path / "blob"
    assert not store.exists(p)
    with pytest.raises(FileNotFoundError):
        store.get(p)
    store.put(p, b"payload")
    assert store.exists(p)
    assert store.get(p) == b"payload"
    got = store.resolve(p)
    assert got is not None and got.read_bytes() == b"payload"
    assert store.list_committed(tmp_path, "blob*") == [got]
    # streaming variants commit the same way
    store.put_from_stream(tmp_path / "s", io.BytesIO(b"abcdef"), 6, chunk_bytes=2)
    assert store.get(tmp_path / "s") == b"abcdef"
    src = tmp_path / "src.local"
    src.write_bytes(b"xyz" * 100)
    store.put_from_file(tmp_path / "f", src, chunk_bytes=7)
    assert store.get(tmp_path / "f") == b"xyz" * 100


def test_nonatomic_torn_part_and_record_are_invisible(tmp_path):
    store = NonAtomicStore()
    p = tmp_path / "mr-0-0"
    # torn part: bytes staged, crash before the commit record
    (tmp_path / "mr-0-0.part.aaaa").write_bytes(b"half a blo")
    assert not store.exists(p)
    # torn commit record: marker half-written
    store.put(p, b"full contents")
    winner = store.resolve(p)
    marker = next(tmp_path.glob("mr-0-0.commit.*"))
    torn = tmp_path / "mr-0-0.commit.zzzz"
    torn.write_bytes(marker.read_bytes()[: marker.stat().st_size // 2])
    assert store.resolve(p) == winner  # torn marker never wins
    # record whose part vanished must not win either
    ghost = decode_record(marker.read_bytes())
    ghost2 = dict(ghost, attempt="0000")  # sorts before every hex uuid
    (tmp_path / "mr-0-0.commit.0000").write_bytes(encode_record(ghost2))
    assert store.resolve(p) == winner


def test_nonatomic_duplicate_attempts_one_winner(tmp_path):
    store = NonAtomicStore()
    p = tmp_path / "mr-out-1"
    store.put(p, b"attempt output\n")
    store.put(p, b"attempt output\n")  # re-executed straggler, same bytes
    assert len(list(tmp_path.glob("mr-out-1.part.*"))) == 2
    assert len(list(tmp_path.glob("mr-out-1.commit.*"))) == 2
    assert store.list_committed(tmp_path, "mr-out-*") == [store.resolve(p)]
    assert store.get(p) == b"attempt output\n"


@pytest.mark.parametrize("store_name", STORES)
def test_task_commit_winner_is_deterministic(tmp_path, store_name):
    store = make_store(store_name)
    store.commit_task(tmp_path, "map", 7, "bbbb", {"parts": [1]})
    store.commit_task(tmp_path, "map", 7, "aaaa", {"parts": [1]})
    rec = store.resolve_task_commit(tmp_path, "map", 7)
    assert rec["attempt"] == "aaaa" and rec["parts"] == [1]
    assert store.resolve_task_commit(tmp_path, "map", 77) is None


# ------------------------------------------------------------ crash matrix

def _kill_once(match):
    """A CrashPoint hook that raises WorkerKilled the first time ctx
    matches; returns (hook, fired) — fired["n"] proves injection ran."""
    fired = {"n": 0}

    def hook(ctx):
        if fired["n"] == 0 and match(ctx):
            fired["n"] += 1
            raise WorkerKilled(f"injected at {ctx}")

    return hook, fired


def _tear_once(match):
    """TORN_COMMIT_RECORD hooks signal by RETURN (FaultStore writes the
    half record and raises itself)."""
    fired = {"n": 0}

    def hook(ctx):
        if fired["n"] == 0 and match(ctx):
            fired["n"] += 1
            return True
        return False

    return hook, fired


def _phase_match(phase, point):
    if point == CrashPoint.AFTER_TEMP_WRITE:
        # ctx is the blob name: map blobs "mr-<t>-<r>", reduce "mr-out-<r>"
        if phase == "map":
            return lambda ctx: ctx.startswith("mr-") and not ctx.startswith("mr-out-")
        return lambda ctx: ctx.startswith("mr-out-")
    return lambda ctx: ctx.startswith(f"{phase}-")


@pytest.mark.parametrize("store_name", STORES)
@pytest.mark.parametrize("phase", ["map", "reduce"])
@pytest.mark.parametrize("point", CrashPoint.ALL)
def test_crash_matrix_surviving_worker(tmp_path, corpus, store_name, phase, point):
    """A worker dies at every commit-protocol instruction; the surviving
    worker (after the sweeper re-issue) completes the job with output
    byte-identical to an uninjected run — no duplicate, torn, or phantom
    mr-* content on either store."""
    expected = clean_output(tmp_path, corpus, store_name)
    maker = _tear_once if point == CrashPoint.TORN_COMMIT_RECORD else _kill_once
    hook, fired = maker(_phase_match(phase, point))
    res = run_job(
        make_config(tmp_path, corpus, store=store_name),
        n_workers=2,
        store_faults_per_worker=[{point: hook}, {}],
    )
    assert fired["n"] == 1, "injection never fired"
    assert output_bytes(res) == expected


@pytest.mark.parametrize("store_name", STORES)
@pytest.mark.parametrize("point", CrashPoint.ALL)
def test_crash_matrix_coordinator_restart(tmp_path, corpus, store_name, point):
    """The lone worker dies at each crash point, taking the job down; a
    restarted coordinator (journal replay + commit records) finishes it.
    A third run replays to a no-op — replay is idempotent."""
    expected = clean_output(tmp_path, corpus, store_name)
    maker = _tear_once if point == CrashPoint.TORN_COMMIT_RECORD else _kill_once
    hook, fired = maker(lambda ctx: True)  # first commit-path call of any task
    cfg = make_config(tmp_path, corpus, store=store_name)
    with pytest.raises(RuntimeError, match="all workers exited"):
        run_job(cfg, n_workers=1, store_faults_per_worker=[{point: hook}])
    assert fired["n"] == 1
    res = run_job(cfg, n_workers=1, resume=True)
    assert output_bytes(res) == expected
    res2 = run_job(cfg, n_workers=1, resume=True)
    assert res2.metrics["counters"].get("map_assigned", 0) == 0
    assert res2.metrics["counters"].get("reduce_assigned", 0) == 0
    assert output_bytes(res2) == expected


# -------------------------------------------- duplicate-completion races

def test_sweeper_reissue_both_attempts_commit_exactly_once(tmp_path, corpus):
    """The satellite race the old suite never covered: a straggler stalls
    mid-commit, the sweeper re-issues, BOTH attempts then commit on disk.
    The store must resolve exactly one winner per blob and per task, the
    scheduler must not double-register, and the output must equal a clean
    run's bytes."""
    workdir = WorkDir(tmp_path / "job", store=make_store("nonatomic"))
    app = load_application("distributed_grep_tpu.apps.grep", pattern="hello")
    files = [str(p) for p in corpus.values()]
    sched = Scheduler(
        files=files, n_reduce=3, task_timeout_s=0.5, sweep_interval_s=0.05,
        app_options={"pattern": "hello"},
        commit_resolver=workdir.resolve_task_commit,
    )
    stalled = {"n": 0, "ctx": None}

    def stall(ctx):
        if ctx.startswith("map-") and stalled["n"] == 0:
            stalled["n"] += 1
            stalled["ctx"] = ctx
            time.sleep(1.2)  # past the sweep window: task re-issued meanwhile

    w0 = WorkerLoop(
        LocalTransport(sched, workdir, store=FaultStore(
            workdir.store, {CrashPoint.BEFORE_COMMIT_RECORD: stall})),
        app,
    )
    w1 = WorkerLoop(LocalTransport(sched, workdir), app)
    threads = [threading.Thread(target=w.run, daemon=True) for w in (w0, w1)]
    for t in threads:
        t.start()
    assert sched.wait_done(timeout=30.0)
    sched.stop()
    for t in threads:
        t.join(timeout=10.0)

    assert stalled["n"] == 1
    tid = int(stalled["ctx"].split("-", 1)[1])
    # the race actually happened: both attempts published task records...
    assert len(list(workdir.commits_dir().glob(f"map-{tid}.*"))) == 2
    # ...but exactly one resolves as truth
    rec = workdir.resolve_task_commit("map", tid)
    assert rec is not None and rec["task_id"] == tid
    # no double-registration in the streaming-shuffle feed
    for rt in sched.reduce_tasks:
        assert len(rt.task_files) == len(set(rt.task_files))
    # each blob of the raced task: two committed attempts, one winner
    for r in rec["parts"]:
        p = workdir.intermediate_path(tid, r)
        assert len(list(p.parent.glob(f"{p.name}.commit.*"))) == 2
        assert workdir.store.resolve(p) is not None
    # and the job's bytes equal an uninjected run's
    from distributed_grep_tpu.runtime.job import JobResult

    expected = clean_output(tmp_path, corpus, "nonatomic")
    got = [p.read_bytes() for p in workdir.list_outputs()]
    assert got == expected
    assert JobResult(output_files=workdir.list_outputs()).results


def test_duplicate_on_disk_commits_register_winning_record_parts(tmp_path):
    """map_finished registers the WINNING commit record's parts, not the
    RPC args — a late straggler RPC carrying a different parts list can
    never register blobs its winning attempt did not commit."""
    from distributed_grep_tpu.runtime import rpc

    workdir = WorkDir(tmp_path / "job", store=make_store("nonatomic"))
    sched = Scheduler(
        files=["f1"], n_reduce=3, sweep_interval_s=0.05,
        commit_resolver=workdir.resolve_task_commit,
    )
    a = sched.assign_task(rpc.AssignTaskArgs(), timeout=1.0)
    workdir.store.commit_task(workdir.commits_dir(), "map", a.task_id,
                              "aaaa", {"parts": [0, 1]})
    # straggler RPC lies about parts (e.g. raced re-execution under a
    # different app config); the record is the unit of truth
    sched.map_finished(rpc.TaskFinishedArgs(task_id=a.task_id,
                                            produced_parts=[0, 1, 2]))
    assert sched.reduce_tasks[0].task_files == [f"mr-{a.task_id}-0"]
    assert sched.reduce_tasks[1].task_files == [f"mr-{a.task_id}-1"]
    assert sched.reduce_tasks[2].task_files == []
    sched.stop()


def test_malformed_commit_record_degrades_to_rpc_parts(tmp_path):
    """The data plane accepts any small JSON body as a commit record; one
    missing "parts" must degrade to RPC-args registration, not wedge the
    task with a KeyError inside the scheduler lock."""
    from distributed_grep_tpu.runtime import rpc

    workdir = WorkDir(tmp_path / "job", store=make_store("nonatomic"))
    sched = Scheduler(
        files=["f1"], n_reduce=2, sweep_interval_s=0.05,
        commit_resolver=workdir.resolve_task_commit,
    )
    a = sched.assign_task(rpc.AssignTaskArgs(), timeout=1.0)
    workdir.store.commit_task(workdir.commits_dir(), "map", a.task_id, "aaaa", {})
    sched.map_finished(rpc.TaskFinishedArgs(task_id=a.task_id, produced_parts=[1]))
    assert sched.reduce_tasks[1].task_files == [f"mr-{a.task_id}-1"]
    assert sched.reduce_tasks[0].task_files == []
    sched.stop()


# ------------------------------------------------------- journal tearing

def test_journal_torn_tail_excluded_and_truncated(tmp_path):
    """Satellite: a torn tail is reported (not silently swallowed),
    excluded from replay, and truncated on reopen so the next append
    starts on a clean line."""
    path = tmp_path / "tasks.jsonl"
    j = TaskJournal(path)
    j.map_completed(0, "f", [0])
    j.map_completed(1, "g", [1])
    j.close()
    clean = path.read_bytes()
    # crash mid-append: half a record, no terminating newline
    path.write_bytes(clean + b'{"kind": "reduce_do')
    assert [e["task_id"] for e in TaskJournal.replay(path)] == [0, 1]
    j2 = TaskJournal(path)  # reopen truncates the torn tail
    assert path.stat().st_size == len(clean)
    j2.reduce_completed(2)
    j2.close()
    kinds = [e["kind"] for e in TaskJournal.replay(path)]
    assert kinds == ["map_done", "map_done", "reduce_done"]


def test_journal_unterminated_tail_is_torn_even_if_it_parses(tmp_path):
    """record() always newline-terminates, so an unterminated tail is a
    partial write BY DEFINITION — even when the prefix happens to parse
    (task_id 12 torn to 1 must not replay as task 1 done)."""
    path = tmp_path / "tasks.jsonl"
    j = TaskJournal(path)
    j.map_completed(0, "f", [0])
    j.close()
    clean = path.read_bytes()
    path.write_bytes(clean + b'{"kind": "reduce_done", "task_id": 1}')
    entries = TaskJournal.replay(path)
    assert [e["kind"] for e in entries] == ["map_done"]
    TaskJournal(path).close()
    assert path.read_bytes() == clean


def test_journal_append_crash_replay_idempotent(tmp_path, corpus):
    """Coordinator dies mid-journal-append (torn tail): the restarted run
    re-executes only the un-journaled work and the final output matches."""
    expected = clean_output(tmp_path, corpus, "posix")
    cfg = make_config(tmp_path, corpus, store="posix")
    run_job(cfg, n_workers=2)
    jpath = WorkDir(cfg.work_dir).journal_path()
    data = jpath.read_bytes()
    lines = data.splitlines(keepends=True)
    # tear the last entry in half — as if the fsync'd append died mid-write
    jpath.write_bytes(b"".join(lines[:-1]) + lines[-1][: len(lines[-1]) // 2])
    res = run_job(cfg, n_workers=2, resume=True)
    assert output_bytes(res) == expected
    # exactly the torn task re-ran; everything journaled was skipped
    total = len(corpus) + cfg.n_reduce
    redone = (res.metrics["counters"].get("map_assigned", 0)
              + res.metrics["counters"].get("reduce_assigned", 0))
    assert 1 <= redone < total


# ------------------------------------------------------------- http plane

def test_http_job_on_nonatomic_store(tmp_path, corpus):
    """The HTTP data plane routes PUTs and commit records through the
    coordinator's store: a full job on NonAtomicStore over real HTTP."""
    from distributed_grep_tpu.runtime.http_coordinator import CoordinatorServer
    from distributed_grep_tpu.runtime.http_transport import HttpTransport

    cfg = make_config(tmp_path, corpus, store="nonatomic",
                      coordinator_port=0, task_timeout_s=5.0)
    server = CoordinatorServer(cfg)
    server.start()
    app = load_application("distributed_grep_tpu.apps.grep", pattern="hello")
    addr = f"127.0.0.1:{server.port}"
    threads = [
        threading.Thread(
            target=WorkerLoop(HttpTransport(addr, rpc_timeout_s=10.0), app).run,
            daemon=True,
        )
        for _ in range(2)
    ]
    for t in threads:
        t.start()
    assert server.wait_done(timeout=30.0)
    for t in threads:
        t.join(timeout=10.0)
    outs = server.workdir.list_outputs()
    assert outs and all(".part." in p.name for p in outs)
    expected = clean_output(tmp_path, corpus, "nonatomic")
    assert [p.read_bytes() for p in outs] == expected
    # commit records made it through the data plane
    assert server.workdir.resolve_task_commit("map", 0) is not None
    server.shutdown(linger_s=0.1)


# ---------------------------------------------- posix behavior preserved

def test_posix_store_outputs_are_plain_files(tmp_path, corpus):
    """PosixStore keeps the exact on-disk shape the runtime always had:
    mr-out-<r> files, no part/marker decorations (behavior-preserving
    refactor guarantee)."""
    res = run_job(make_config(tmp_path, corpus, store="posix"), n_workers=2)
    assert all(p.name.startswith("mr-out-") and ".part." not in p.name
               for p in res.output_files)
    inter = WorkDir(make_config(tmp_path, corpus).work_dir).root / "intermediate"
    assert all(".commit." not in p.name for p in inter.iterdir())

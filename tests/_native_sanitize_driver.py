"""Native-surface driver for the sanitizer builds (tests/test_sanitize.py).

Run as a SUBPROCESS with the sanitizer runtime LD_PRELOADed and
``DGREP_NATIVE_LIB`` pointing at ``libdgrep-asan.so`` / ``libdgrep-tsan.so``
(utils/native.py loads exactly that build, and raises instead of silently
degrading to the Python fallbacks).  Exercises every exported entry point
against independent pure-Python oracles — including the buffer-regrow
retry loops, the ignore_case fold, the short-pattern chain, and the
threaded paths (MT DFA scan, the confirm pool) — then a threaded stress
that shares one ConfirmSet / one DFA table across concurrent scans (the
race surface TSan watches; the library's scan entry points are read-only
by contract).

    python tests/_native_sanitize_driver.py surface   # full sweep
    python tests/_native_sanitize_driver.py stress    # threaded stress

Exit 0 = every check passed and no sanitizer report fired (the builds run
with halt-on-error, so a report is a nonzero exit).
"""

from __future__ import annotations

import os
import random
import sys
import threading

import numpy as np

from distributed_grep_tpu.utils import native


def check(cond: bool, what: str) -> None:
    if not cond:
        print(f"FAIL: {what}", file=sys.stderr)
        sys.exit(1)


def py_fnv32a(data: bytes) -> int:
    h = 2166136261
    for b in data:
        h ^= b
        h = (h * 16777619) & 0xFFFFFFFF
    return h & 0x7FFFFFFF


def py_dfa(data: bytes, table: np.ndarray, accept: np.ndarray,
           start: int = 0) -> list[int]:
    s = start
    out = []
    for i, b in enumerate(data):
        s = int(table[s, b])
        if accept[s]:
            out.append(i + 1)
    return out


def literal_dfa(needle: bytes) -> tuple[np.ndarray, np.ndarray]:
    """KMP-style literal DFA with the '\\n'-resets-to-start invariant."""
    m = len(needle)
    table = np.zeros((m + 1, 256), dtype=np.uint16)
    accept = np.zeros(m + 1, dtype=np.uint8)
    accept[m] = 1
    fail = [0] * (m + 1)
    for s in range(m + 1):
        for c in range(256):
            if s < m and c == needle[s]:
                table[s, c] = s + 1
            elif s == 0:
                table[s, c] = 0
            else:
                table[s, c] = table[fail[s], c]
        if s < m:
            fail[s + 1] = int(table[fail[s], needle[s]])
    table[:, 0x0A] = table[0, 0x0A]  # newline reset (the MT-scan contract)
    return table, accept


def py_trigram(blob: bytes, m: int) -> np.ndarray:
    """Pure-numpy oracle of dgrep_trigram_summary (the shard-index bloom:
    case-folded 24-bit trigram codes, one 64-bit Fibonacci mix, two bit
    probes from the low/high halves)."""
    bloom = np.zeros(m, dtype=np.uint8)
    if len(blob) < 3:
        return bloom
    fold = np.arange(256, dtype=np.uint8)
    fold[ord("A"):ord("Z") + 1] += 32
    f = fold[np.frombuffer(blob, np.uint8)].astype(np.uint64)
    v = (f[:-2] << np.uint64(16)) | (f[1:-1] << np.uint64(8)) | f[2:]
    h = v * np.uint64(0x9E3779B97F4A7C15)
    mask = np.uint64(m * 8 - 1)
    idx = np.unique(
        np.concatenate([h & mask, (h >> np.uint64(32)) & mask])
    )
    np.bitwise_or.at(
        bloom, (idx >> np.uint64(3)).astype(np.int64),
        np.uint8(1) << (idx & np.uint64(7)).astype(np.uint8),
    )
    return bloom


def surface() -> None:
    rng = random.Random(7)
    data = bytes(rng.choice(b"abcnedle\n") for _ in range(200_000))

    # --- fnv32a / partition (incl. non-UTF-8 surrogateescape keys) ---------
    for key in (b"", b"k", b"hello world", b"\xff\xfe\x00raw", "unié",
                "sur" + "\udcff"):
        kb = key.encode("utf-8", "surrogateescape") if isinstance(key, str) \
            else key
        check(native.fnv32a(key) == py_fnv32a(kb), f"fnv32a {key!r}")
        check(0 <= native.partition(key, 7) < 7, f"partition {key!r}")

    # --- newline_index -----------------------------------------------------
    nl = native.newline_index(data)
    expect = np.flatnonzero(np.frombuffer(data, np.uint8) == 0x0A)
    check(np.array_equal(nl, expect.astype(np.uint64)), "newline_index")
    check(native.newline_index(b"").size == 0, "newline_index empty")

    # --- literal_scan (overlaps + regrow: 'aa' in 'aaaa...' doubles) -------
    hay = b"aa" * 8000 + data
    ends = native.literal_scan(hay, b"aa")
    py_ends, start = [], 0
    while True:
        i = hay.find(b"aa", start)
        if i < 0:
            break
        py_ends.append(i + 2)
        start = i + 1
    check(ends.tolist() == py_ends, "literal_scan overlapping + regrow")
    check(native.literal_scan(hay, b"").size == 0, "literal_scan empty")
    check(native.literal_scan(b"ab", b"abc").size == 0, "needle > hay")

    # --- dfa_scan / dfa_scan_mt (forced threads; bit-identity) -------------
    table, accept = literal_dfa(b"nedle")
    offs, final = native.dfa_scan(data, table, accept)
    check(offs.tolist() == py_dfa(data, table, accept), "dfa_scan")
    check(0 <= final < table.shape[0], "dfa_scan final state")
    mt = native.dfa_scan_mt(data, table, accept, n_threads=4)
    check(mt.tolist() == offs.tolist(), "dfa_scan_mt == sequential")

    # --- ConfirmSet: folds, shorts, regrow-sized candidate sets ------------
    pats = [b"nedle", b"ab", b"z", b"needle", b"\xff\xferaw"]
    for ci in (False, True):
        norm = [p.lower() if ci else p for p in pats]
        cs = native.ConfirmSet(norm, ignore_case=ci)
        ref = native.ConfirmSet(norm, ignore_case=ci, use_native=False)
        cand = np.arange(0, len(data), 3, dtype=np.uint64)
        got = cs.confirm(data, cand, n_threads=4)
        want = ref.confirm(data, cand)
        check(np.array_equal(got, want), f"ConfirmSet ci={ci}")
        del cs, ref  # dgrep_confirm_free under the sanitizer

    # --- gather_ranges -----------------------------------------------------
    arr = np.frombuffer(data, np.uint8)
    starts = np.asarray([0, 10, 5, 199_990, 7, 7], dtype=np.int64)
    stops = np.asarray([5, 20, 5, 200_000, 6, 107], dtype=np.int64)
    lens = np.maximum(stops - starts, 0)
    offsets = np.zeros(len(lens) + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    got_b = native.gather_ranges_native(arr, starts, stops, offsets,
                                        int(offsets[-1]))
    want_b = b"".join(data[a:b] for a, b in zip(starts.tolist(),
                                                stops.tolist()) if b > a)
    check(got_b == want_b, "gather_ranges")

    # --- format_batch (valid UTF-8 + the -2 refusal path) ------------------
    lines3 = [b"line one", b"line two!", b"\xc3\xa9 accents"]
    packed = b"".join(lines3)
    po = np.zeros(len(lines3) + 1, dtype=np.int64)
    np.cumsum([len(ln) for ln in lines3], out=po[1:])
    linenos = np.asarray([3, 11, 222], dtype=np.int64)
    prefix = "f\udcffile (line number #".encode("utf-8", "surrogateescape")
    got_f = native.format_batch(prefix, linenos, po, packed)
    want_f = b"".join(
        prefix + str(n).encode() + b")\t" + packed[po[i]:po[i + 1]] + b"\n"
        for i, n in enumerate(linenos.tolist())
    )
    check(got_f == want_f, "format_batch bytes")
    bad = native.format_batch(b"p (line number #", linenos[:1],
                              np.asarray([0, 2], dtype=np.int64), b"\xff\xff")
    check(bad is None, "format_batch refuses non-UTF-8 slab")

    # --- unique_lines / line_spans / build_records (record pipeline) -------
    nl_i = native.newline_index(data)
    ends_c = np.asarray(
        sorted(rng.sample(range(1, len(data) + 1), 4000)), dtype=np.int64
    )
    want_u = np.unique(
        np.searchsorted(nl_i.astype(np.int64), ends_c - 1, side="right") + 1
    )
    got_u = native.unique_lines_native(nl_i, ends_c)
    check(got_u is not None and np.array_equal(got_u, want_u), "unique_lines")
    check(native.unique_lines_native(nl_i, np.zeros(0, np.int64)).size == 0,
          "unique_lines empty")

    n_lines = int(nl_i.size) + (0 if data.endswith(b"\n") else 1)
    lns = np.asarray(
        sorted(rng.sample(range(1, n_lines + 1), 3000)), dtype=np.int64
    )
    sp = native.line_spans_native(nl_i, lns, len(data))
    check(sp is not None, "line_spans available")
    st, en = sp
    nl64 = nl_i.astype(np.int64)
    for i in (0, 1, len(lns) // 2, len(lns) - 1):
        ln = int(lns[i])
        w_s = 0 if ln == 1 else int(nl64[ln - 2]) + 1
        w_e = int(nl64[ln - 1]) if ln - 1 < nl64.size else len(data)
        check((int(st[i]), int(en[i])) == (w_s, w_e), f"line_spans ln={ln}")
    sp0 = native.line_spans_native(np.zeros(0, np.uint64),
                                   np.asarray([1], np.int64), 5)
    check(sp0 is not None and (int(sp0[0][0]), int(sp0[1][0])) == (0, 5),
          "line_spans no-newline chunk")

    arr_u8 = np.frombuffer(data, np.uint8)
    prefix = "f\udcffile (line number #".encode("utf-8", "surrogateescape")
    for n_reduce in (1, 7):
        parts = native.build_records(arr_u8, st, en, lns + 10**12,
                                     prefix, n_reduce)
        check(parts is not None, "build_records available")
        total = 0
        for p, (pl, po, slab) in parts.items():
            check(0 <= p < n_reduce, "build_records partition range")
            check(int(po[0]) == 0 and int(po[-1]) == len(slab),
                  "build_records offsets")
            total += int(pl.size)
            for j in range(min(5, int(pl.size))):
                key = prefix + str(int(pl[j])).encode() + b")"
                check(native.fnv32a(key) % n_reduce == p,
                      "build_records partition == fnv32a")
                line = slab[int(po[j]):int(po[j + 1])]
                ln = int(pl[j] - 10**12)
                w_s = 0 if ln == 1 else int(nl64[ln - 2]) + 1
                w_e = int(nl64[ln - 1]) if ln - 1 < nl64.size else len(data)
                check(line == data[w_s:w_e], "build_records slab bytes")
        check(total == lns.size, "build_records record count")
    check(native.build_records(
        arr_u8, np.asarray([0], np.int64),
        np.asarray([len(data) + 9], np.int64),
        np.asarray([1], np.int64), prefix, 4) is None,
        "build_records refuses out-of-bounds span")

    # --- trigram_summary (shard index: native == numpy oracle) -------------
    for blob in (b"", b"a", b"ab", b"abc", data[:100_000],
                 b"MiXeD CaSe needle\xff\xfe\n" * 50):
        for m in (1024, 16384):
            bloom = np.zeros(m, dtype=np.uint8)
            check(native.trigram_summary_into(blob, bloom),
                  "trigram_summary available")
            check(np.array_equal(bloom, py_trigram(blob, m)),
                  f"trigram_summary bits (len={len(blob)}, m={m})")

    # --- merge_display (k-way, codepoint path order, tie-break) ------------
    def rec(path: bytes, n: int, text: bytes) -> bytes:
        return path + b" (line number #" + str(n).encode() + b")\t" + text

    b1 = b"\n".join([rec(b"a.txt", 1, b"x"), rec(b"b.txt", 9, b"y")]) + b"\n"
    b2 = b"\n".join([rec(b"a.txt", 2, b"z"), rec(b"b.txt", 9, b"w")])  # no \n
    got_m = native.merge_display([b1, b2])
    want_m = (rec(b"a.txt", 1, b"x").replace(b"\t", b" ") + b"\n"
              + rec(b"a.txt", 2, b"z").replace(b"\t", b" ") + b"\n"
              + rec(b"b.txt", 9, b"y").replace(b"\t", b" ") + b"\n"
              + rec(b"b.txt", 9, b"w").replace(b"\t", b" ") + b"\n")
    check(got_m == want_m, "merge_display order + tab->space + final NL")
    # surrogateescape codepoint order: raw byte 0xFF sorts AFTER valid é
    b3 = rec(b"f\xff.t", 1, b"raw") + b"\n"
    b4 = rec(b"f\xc3\xa9.t", 1, b"acc") + b"\n"
    got_o = native.merge_display([b3, b4])
    check(got_o is not None and got_o.index(b"acc") < got_o.index(b"raw"),
          "merge_display surrogateescape codepoint order")
    check(native.merge_display([b"not a grep key\n"]) is None,
          "merge_display refuses non-grep-shaped")

    print("surface ok")


def stress() -> None:
    """Shared-state threaded stress: one DFA table + one ConfirmSet used
    by concurrent scans, plus each scan internally fanning out threads —
    the pthread surface TSan instruments."""
    rng = random.Random(11)
    data = bytes(rng.choice(b"xyneedle\n") for _ in range(400_000))
    table, accept = literal_dfa(b"needle")
    pats = [b"needle", b"ne", b"edle", b"x"]
    cs = native.ConfirmSet(pats)
    seq = native.dfa_scan_mt(data, table, accept, n_threads=1).tolist()
    cand = np.arange(0, len(data), 2, dtype=np.uint64)
    want_mask = cs.confirm(data, cand, n_threads=1)
    # shared inputs for the record-pipeline stress: concurrent worker
    # slots share one engine, so concurrent build_records over the SAME
    # data/nl arrays is the production shape (entries are read-only)
    nl_i = native.newline_index(data)
    n_lines = int(nl_i.size) + (0 if data.endswith(b"\n") else 1)
    lns = np.arange(1, n_lines + 1, 3, dtype=np.int64)
    sp = native.line_spans_native(nl_i, lns, len(data))
    arr_u8 = np.frombuffer(data, np.uint8)
    prefix = b"s (line number #"
    want_parts = native.build_records(arr_u8, sp[0], sp[1], lns, prefix, 5)
    errors: list[str] = []

    # trigram-summary stress inputs: concurrent builds over the SAME
    # shared corpus bytes into private blooms (the production shape —
    # worker threads summarize shared read-only buffers; the bloom each
    # writes is its own)
    want_tg = py_trigram(data, 4096)

    def pound(idx: int) -> None:
        for _ in range(6):
            got = native.dfa_scan_mt(data, table, accept, n_threads=4)
            if got.tolist() != seq:
                errors.append(f"thread {idx}: dfa_scan_mt diverged")
                return
            bloom = np.zeros(4096, dtype=np.uint8)
            if not native.trigram_summary_into(data, bloom) or \
                    not np.array_equal(bloom, want_tg):
                errors.append(f"thread {idx}: trigram_summary diverged")
                return
            mask = cs.confirm(data, cand, n_threads=4)
            if not np.array_equal(mask, want_mask):
                errors.append(f"thread {idx}: confirm diverged")
                return
            got_sp = native.line_spans_native(nl_i, lns, len(data))
            parts = native.build_records(
                arr_u8, got_sp[0], got_sp[1], lns, prefix, 5
            )
            if set(parts) != set(want_parts) or any(
                parts[p][2] != want_parts[p][2]
                or not np.array_equal(parts[p][0], want_parts[p][0])
                for p in parts
            ):
                errors.append(f"thread {idx}: build_records diverged")
                return

    threads = [threading.Thread(target=pound, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    check(not errors, "; ".join(errors) or "stress")
    print("stress ok")


if __name__ == "__main__":
    check(os.environ.get("DGREP_NATIVE_LIB", "") != "",
          "driver needs DGREP_NATIVE_LIB")
    check(native.native_available(), "native library failed to load")
    mode = sys.argv[1] if len(sys.argv) > 1 else "surface"
    {"surface": surface, "stress": stress}[mode]()

"""Runtime lock-discipline harness — the dynamic half of the
concurrency-discipline layer (round 11).

The static rules (``analysis/rules.py`` ``locked-blocking`` /
``lock-order``) prove what the AST can see; THIS module watches what the
threads actually do.  The five lock registries (service, scheduler,
model cache, corpus cache, span pipeline) construct their locks through
``make_lock(name)`` — a plain ``threading.Lock`` when the harness is off
(zero overhead, the production default), an instrumented wrapper when it
is on (``DGREP_LOCKDEP=1`` or an ``activate()`` from the test fixture).
The wrapper records, per thread, the stack of held locks and:

* **lock-order inversions** — every first-seen (held -> acquired) pair
  becomes an edge in a process-global order graph; an edge that closes a
  cycle is recorded with both acquisition stacks.  Edges are keyed by
  the lock NAME (the lock class), not the instance, so two service
  incarnations share one discipline.
* **blocking-syscall-while-held** — while active, ``os.fsync`` /
  ``os.replace`` / ``os.rename`` / ``time.sleep`` / ``builtins.open`` /
  ``socket.create_connection`` are wrapped; a call on a thread holding
  any instrumented lock not declared ``io_ok`` is recorded.  ``io_ok``
  is the blessed escape for locks whose PURPOSE is serializing I/O (the
  registry/journal/start flush locks, the model-cache compile lock, the
  device-probe lock) — the same declaration the static rule reads.

The harness never raises into instrumented code: findings accumulate in
``report()`` and the suite fixture (tests/conftest.py) asserts they are
empty after every ``service`` / ``chaos`` / ``soak_mini`` test.

Condition compatibility: ``threading.Condition(make_lock(...))`` works —
Condition aliases the wrapped ``acquire``/``release``, so the held-stack
stays exact across ``cond.wait()`` (the wait's release pops the entry,
the re-acquire pushes it back).
"""

from __future__ import annotations

import builtins
import os
import socket
import threading
import time
import traceback

_ENV_VAR = "DGREP_LOCKDEP"


def env_lockdep(default: bool = False) -> bool:
    """The ONE parser of DGREP_LOCKDEP: truthy ("1"/"true"/"yes") switches
    the harness on for locks constructed from then on."""
    raw = os.environ.get(_ENV_VAR)
    if raw is None or raw == "":
        return default
    return raw.strip().lower() not in ("0", "false", "no")


# ------------------------------------------------------------------ state
# The harness's own mutex is a RAW lock (never instrumented — it must not
# appear in the graph it maintains).
_state_lock = threading.Lock()
_tls = threading.local()  # .held: list[_TrackedLock], .busy: reentrancy

_active = 0  # activate() nesting count (env_lockdep() counts as one)
_edges: dict[tuple[str, str], dict] = {}  # (held, acquired) -> stacks
_inversions: list[dict] = []
_blocking: list[dict] = []
_patched: dict[str, object] = {}  # original syscalls while installed

_STACK_LIMIT = 16


def _stack() -> list[str]:
    """Compact acquisition stack, reentrancy-guarded: formatting reads
    source via linecache (which calls the possibly-patched open)."""
    _tls.busy = True
    try:
        frames = traceback.extract_stack(limit=_STACK_LIMIT)[:-2]
        return [f"{f.filename.rsplit('/', 1)[-1]}:{f.lineno}:{f.name}"
                for f in frames]
    finally:
        _tls.busy = False


def _held() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _cycle_path(frm: str, to: str) -> list[str] | None:
    """A path frm -> ... -> to through the recorded edges, or None.
    Called under _state_lock."""
    stack = [(frm, [frm])]
    seen = {frm}
    while stack:
        node, path = stack.pop()
        for (a, b) in _edges:
            if a != node or b in seen and b != to:
                continue
            if b == to:
                return path + [b]
            seen.add(b)
            stack.append((b, path + [b]))
    return None


class _TrackedLock:
    """Instrumented threading.Lock stand-in (duck-typed: acquire/release/
    locked/__enter__/__exit__ — everything Condition and `with` need)."""

    def __init__(self, name: str, io_ok: bool = False, rlock: bool = False):
        self.name = name
        self.io_ok = io_ok
        self._l = threading.RLock() if rlock else threading.Lock()
        self._rlock = rlock
        self._depth_by_thread: dict[int, int] = {}

    # -- bookkeeping (called with the lock just acquired / about to drop)
    def _note_acquired(self) -> None:
        if self._rlock:
            me = threading.get_ident()
            with _state_lock:
                d = self._depth_by_thread.get(me, 0) + 1
                self._depth_by_thread[me] = d
            if d > 1:
                return  # reentrant re-acquire: not a new hold
        held = _held()
        # active() not _active: an env-enabled process (DGREP_LOCKDEP=1,
        # no fixture activate()) must record edges too
        if held and active():
            holder = held[-1]
            if holder is not self:
                key = (holder.name, self.name)
                # double-checked: the unlocked membership probe keeps the
                # steady state (edge already known — every acquisition
                # after the first) off the global state lock, or nested
                # acquires process-wide would serialize through it
                if key not in _edges:
                    with _state_lock:
                        if key not in _edges:
                            back = _cycle_path(self.name, holder.name)
                            _edges[key] = {"stack": _stack()}
                            if back is not None:
                                _inversions.append({
                                    "cycle": [holder.name] + back,
                                    "edge": key,
                                    "stack": _edges[key]["stack"],
                                })
        held.append(self)

    def _note_released(self) -> None:
        if self._rlock:
            me = threading.get_ident()
            with _state_lock:
                d = self._depth_by_thread.get(me, 1) - 1
                if d > 0:
                    self._depth_by_thread[me] = d
                    return
                self._depth_by_thread.pop(me, None)
        held = getattr(_tls, "held", None)
        if held:
            # remove by identity (releases are LIFO in practice, but a
            # Condition.wait on an outer lock releases out of order)
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    break

    # -- the Lock surface
    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._l.acquire(blocking, timeout)
        if got:
            self._note_acquired()
        return got

    def release(self) -> None:
        self._note_released()
        self._l.release()

    def locked(self) -> bool:
        if self._rlock:
            # RLock has no locked(); "some thread holds it" is the
            # closest true answer the wrapper can give
            return bool(self._depth_by_thread)
        return self._l.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # diagnostics only
        return f"<TrackedLock {self.name!r} io_ok={self.io_ok}>"


def make_lock(name: str, io_ok: bool = False):
    """A lock for one of the named registries.  Off (the default): a raw
    ``threading.Lock`` — zero overhead, nothing recorded.  On: a tracked
    lock feeding the order graph.  ``io_ok=True`` declares that blocking
    I/O under this lock is the lock's PURPOSE (flush/compile/probe
    serialization) — the blocking-syscall detector skips it, and the
    static ``locked-blocking`` rule reads the same declaration."""
    if _active > 0 or env_lockdep():
        _ensure_patched()
        return _TrackedLock(name, io_ok=io_ok)
    return threading.Lock()


def make_rlock(name: str, io_ok: bool = False):
    """RLock variant of make_lock (reentrant holds count as one)."""
    if _active > 0 or env_lockdep():
        _ensure_patched()
        return _TrackedLock(name, io_ok=io_ok, rlock=True)
    return threading.RLock()


# -------------------------------------------------- blocking-syscall watch
def _non_io_held() -> "_TrackedLock | None":
    if getattr(_tls, "busy", False):
        return None
    # innermost non-io_ok hold wins: the report should name the critical
    # section actually enclosing the syscall
    for lk in reversed(getattr(_tls, "held", ())):
        if not lk.io_ok:
            return lk
    return None


def _record_blocking(call: str) -> None:
    lk = _non_io_held()
    if lk is None:
        return
    with _state_lock:
        _blocking.append({
            "call": call, "lock": lk.name, "stack": _stack(),
        })


def _wrap_syscall(label: str, fn):
    def wrapped(*a, **kw):
        _record_blocking(label)
        return fn(*a, **kw)

    wrapped.__lockdep_original__ = fn
    return wrapped


_SYSCALLS = (
    (os, "fsync"),
    (os, "replace"),
    (os, "rename"),
    (time, "sleep"),
    (builtins, "open"),
    (socket, "create_connection"),
)


def _ensure_patched() -> None:
    with _state_lock:
        if _patched:
            return
        for mod, attr in _SYSCALLS:
            label = f"{mod.__name__}.{attr}"
            orig = getattr(mod, attr)
            _patched[label] = (mod, attr, orig)
            setattr(mod, attr, _wrap_syscall(label, orig))


def _unpatch() -> None:
    with _state_lock:
        for mod, attr, orig in _patched.values():
            setattr(mod, attr, orig)
        _patched.clear()


# ------------------------------------------------------------- public API
def activate() -> None:
    """Switch the harness on for locks constructed from now on (nests)."""
    global _active
    with _state_lock:
        _active += 1
    _ensure_patched()


def deactivate() -> None:
    """Undo one activate().  At zero the syscall patches are removed;
    already-constructed tracked locks keep working (their recording is
    gated per event, and edges from them stay in the report until
    reset())."""
    global _active
    unpatch = False
    with _state_lock:
        _active = max(0, _active - 1)
        unpatch = _active == 0 and not env_lockdep()
    if unpatch:
        _unpatch()


def active() -> bool:
    return _active > 0 or env_lockdep()


def reset() -> None:
    """Drop every recorded edge/finding (test isolation)."""
    with _state_lock:
        _edges.clear()
        _inversions.clear()
        _blocking.clear()


def report() -> dict:
    """{"edges": {...}, "inversions": [...], "blocking": [...]} — the
    suite fixture asserts inversions == [] and blocking == []."""
    with _state_lock:
        return {
            "edges": {f"{a} -> {b}": dict(v) for (a, b), v in _edges.items()},
            "inversions": [dict(i) for i in _inversions],
            "blocking": [dict(b) for b in _blocking],
        }

"""Job configuration — the knobs the reference hardcodes with TODOs.

One dataclass covering exactly the reference's hardcoded constants:
input list (coordinator_launch.go:12-17), grep pattern (application/grep.go:11),
n_reduce=10 (coordinator_launch.go:17), coordinator address
(worker.go:221, coordinator.go:184-193), data roots (coordinator.go:306-309,
worker.go:19), task timeout 10s (coordinator.go:105), plus the TPU-native
knobs (mesh shape, chunking) the reference has no analogue for.
Loadable from JSON with CLI overrides (see runtime/launch.py).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

# Valid JobConfig.store names.  MUST mirror runtime/store.py STORES — a
# literal here (not an import) because constructing a JobConfig must not
# drag in the whole runtime package; test_store_faults pins the two in sync.
STORE_NAMES = frozenset({"posix", "nonatomic"})


@dataclass
class JobConfig:
    # --- What to run -------------------------------------------------------
    input_files: list[str] = field(default_factory=list)
    application: str = "distributed_grep_tpu.apps.grep"
    app_options: dict[str, Any] = field(default_factory=dict)  # e.g. {"pattern": "foo"}
    n_reduce: int = 10  # coordinator_launch.go:17

    # --- Where data lives (replaces /tmp/mr-data + /tmp/mr + SFTP) ---------
    work_dir: str = "/tmp/dgrep"  # shared-FS data plane root
    # Commit semantics for the work dir's blobs (runtime/store.py):
    # "posix" — temp+fsync+rename (the reference's protocol);
    # "nonatomic" — object-store emulation: no rename, visibility via
    # attempt-scoped part files + self-checksummed commit records.
    store: str = "posix"

    # --- Control plane -----------------------------------------------------
    coordinator_host: str = "127.0.0.1"
    coordinator_port: int = 1234  # coordinator.go:193
    rpc_timeout_s: float = 60.0  # client-side long-poll ceiling

    # --- Cross-file batching (runtime/job.plan_map_splits) ------------------
    # Group consecutive small input files (below the engine's
    # device_min_bytes threshold) into multi-file map splits whose packed
    # size fits this many bytes — one map task, and through
    # GrepEngine.scan_batch one packed device dispatch per window, covers
    # many sub-threshold files (the grep -r many-small-files regime).
    # 0/None = one task per file (the reference shape).  The
    # DGREP_BATCH_BYTES env var overrides (0 disables) — see
    # effective_batch_bytes.
    batch_bytes: int | None = None

    # --- Fault tolerance ---------------------------------------------------
    task_timeout_s: float = 10.0  # coordinator.go:105,:114
    sweep_interval_s: float = 1.0  # coordinator.go:122
    journal: bool = True  # durable task-commit journal for coordinator resume
    # durable=False waives the blob store's fsync-before-rename (atomic
    # rename commit unchanged; runtime/store.make_store) — ONLY for
    # ephemeral temp work dirs nobody can resume (the CLI sets it with
    # journal=False; ~0.3 s of fsync per dense 64 MB job on a laptop-class
    # disk).  Resumable and service work dirs must keep the default.
    durable: bool = True

    # --- Observability (utils/spans.py) ------------------------------------
    # Span/event pipeline: workers ship per-task-attempt spans piggybacked
    # on Heartbeat/TaskFinished RPCs; the coordinator persists them as
    # events.jsonl in the work dir (render with `dgrep trace-export`).
    # Off by default — disabled runs add zero RPC payload and write no
    # files.  The DGREP_SPANS env var forces on regardless of this flag.
    spans: bool = False
    # Span job tag; "" derives it from the work dir's basename.
    job_id: str = ""

    # --- Worker resources --------------------------------------------------
    # Reduce-side grouping memory cap: records past this spill to sorted
    # on-disk runs and merge-stream (runtime/extsort.py).  The reference
    # materializes whole partitions in RAM (worker.go:161-162).
    reduce_memory_bytes: int = 128 << 20
    # Where reduce spills land.  None: in-process jobs use
    # <work_dir>/spill; HTTP workers use the system temp dir (the
    # coordinator's path may not exist on their host).  Set explicitly to
    # real disk when the temp dir is RAM-backed tmpfs.
    spill_dir: str | None = None

    # --- Streaming / follow mode (round 17, runtime/follow.py) -------------
    # follow=True turns the job into a STANDING query over live-append
    # inputs: no map/reduce phases — a daemon-side wake loop suffix-scans
    # each input as it grows and streams records to GET /jobs/<id>/stream
    # subscribers; per-file cursors persist in the job workdir
    # (follow.jsonl) so a daemon restart resumes from them.  Both fields
    # ELIDE from to_json at their defaults: a follow-free client/daemon
    # pair exchanges payloads byte-identical to every pre-follow peer.
    follow: bool = False
    follow_poll_s: float | None = None  # wake cadence; None = the
    # DGREP_FOLLOW_POLL_S knob (0.5 s default; env wins either way)

    # --- HA submit dedup (round 18, runtime/lease.py failover) --------------
    # Client-generated idempotency token: the service dedups submits on
    # it, so a client whose POST reply was lost to a failover can re-POST
    # to the promoted daemon and land on the SAME job.  Elides from
    # to_json when empty — token-free submit bodies and registry lines
    # stay byte-identical to every pre-lease peer.
    submit_token: str = ""

    # --- TPU execution -----------------------------------------------------
    backend: str = "auto"  # "cpu" | "tpu" | "auto" — pick the grep map engine
    mesh_shape: tuple[int, ...] = ()  # () = all local devices on one data axis
    mesh_axes: tuple[str, ...] = ("data",)
    chunk_bytes: int = 8 * 1024 * 1024  # per-device scan chunk (HBM-sized shards)

    def __post_init__(self) -> None:
        if self.n_reduce <= 0:
            raise ValueError(f"n_reduce must be positive, got {self.n_reduce}")
        if self.store not in STORE_NAMES:
            raise ValueError(
                f"store must be one of {sorted(STORE_NAMES)}, got {self.store!r}"
            )
        self.mesh_shape = tuple(self.mesh_shape)
        self.mesh_axes = tuple(self.mesh_axes)

    def effective_job_id(self) -> str:
        """The span pipeline's job tag: the explicit job_id, else the work
        dir's basename (stable across coordinator restarts of one job)."""
        return self.job_id or Path(self.work_dir).name

    def effective_batch_bytes(self) -> int:
        """The map-split batching window actually in force: the
        DGREP_BATCH_BYTES env var wins (operator override, 0 disables),
        else this config's batch_bytes; 0 = batching off.  The env parse
        is SHARED with the engine's packing cap (ops/layout
        env_batch_bytes) so the planner and the worker engines can never
        disagree on a malformed override."""
        from distributed_grep_tpu.ops.layout import env_batch_bytes

        return env_batch_bytes(max(0, int(self.batch_bytes or 0)))

    def effective_app_options(self) -> dict:
        """app_options with the top-level mesh knobs merged in (explicit
        app_options win) — the options the runtime actually hands to the
        application's configure() (apps/grep_tpu.py builds its engine mesh
        from them).  Computed at call time on a fresh dict, so later edits
        to the mesh fields are honored and configs never alias options."""
        out = dict(self.app_options)
        if self.mesh_shape:
            out.setdefault("mesh_shape", list(self.mesh_shape))
            out.setdefault("mesh_axes", list(self.mesh_axes))
        bb = self.effective_batch_bytes()
        if bb:
            # the packing window must reach the worker ENGINES too (via
            # grep_tpu's engine_opts) — without this, plan_map_splits
            # would build e.g. 256 MB splits whose engine still flushed
            # every 32 MB default, breaking the one-dispatch-per-window
            # contract.  Apps without engine knobs ignore it (**_ catch-
            # alls / no configure hook).
            out.setdefault("batch_bytes", bb)
        return out

    # --- (De)serialization -------------------------------------------------
    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        # wire-shape pin: the round-17 follow fields elide at their
        # defaults (the rpc._ELIDE_DEFAULTS contract applied to the job
        # config) — submit bodies, registry lines, and /config bootstrap
        # payloads of follow-free jobs stay byte-identical to pre-follow
        # peers, and an old daemon only rejects a config that actually
        # asks for the new behavior.
        if not d.get("follow"):
            d.pop("follow", None)
            d.pop("follow_poll_s", None)
        elif d.get("follow_poll_s") is None:
            d.pop("follow_poll_s", None)
        if not d.get("submit_token"):
            # same contract, round 18: the HA submit-dedup token elides
            # when absent so old payloads stay byte-identical
            d.pop("submit_token", None)
        return json.dumps(d, indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "JobConfig":
        return cls(**json.loads(text))

    @classmethod
    def load(cls, path: str | Path, **overrides: Any) -> "JobConfig":
        cfg = cls.from_json(Path(path).read_text())
        return dataclasses.replace(cfg, **overrides) if overrides else cfg

    @property
    def coordinator_addr(self) -> str:
        return f"http://{self.coordinator_host}:{self.coordinator_port}"

"""Per-job metrics: task timings, retries, bytes scanned, GB/s accounting.

The reference has no metrics at all (SURVEY.md §5).  The north-star target
(>=10 GB/s/chip) makes throughput accounting first-class: every scan records
bytes + seconds, every task records its assign->data-ready->compute->commit
phases, and the job dumps one dict at completion.
"""

from __future__ import annotations

import json
import time
from collections import defaultdict
from contextlib import contextmanager
from dataclasses import dataclass, field

from distributed_grep_tpu.utils import lockdep


def _metrics_lock():
    return lockdep.make_lock("metrics")


@dataclass
class Metrics:
    """Thread-safe counters + timers; one instance per coordinator/worker."""

    counters: dict[str, float] = field(default_factory=lambda: defaultdict(float))
    timings: dict[str, list[float]] = field(default_factory=lambda: defaultdict(list))
    _lock: object = field(default_factory=_metrics_lock, repr=False)

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self.counters[name] += value

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            self.timings[name].append(seconds)

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    def record_scan(self, n_bytes: int, seconds: float) -> None:
        """Throughput accounting for the north-star GB/s metric."""
        with self._lock:
            self.counters["bytes_scanned"] += n_bytes
            self.counters["scan_seconds"] += seconds

    def gbps(self) -> float:
        secs = self.counters.get("scan_seconds", 0.0)
        return (self.counters.get("bytes_scanned", 0.0) / 1e9 / secs) if secs else 0.0

    def piggyback(self) -> dict:
        """Compact counters snapshot for the heartbeat span-pipeline
        piggyback (runtime/rpc.py): every counter plus the computed gbps
        headline — small enough to ship on each stamp, rich enough for
        GET /status per-worker aggregates."""
        with self._lock:
            out = dict(self.counters)
        if out.get("scan_seconds"):
            # 6 digits: tiny jobs (a few KB) must not round to 0.0
            out["gbps"] = round(self.gbps(), 6)
        return out

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "counters": dict(self.counters),
                "timings": {
                    k: {
                        "count": len(v),
                        "total_s": sum(v),
                        "mean_s": sum(v) / len(v),
                        "max_s": max(v),
                    }
                    for k, v in self.timings.items()
                    if v
                },
            }
        if out["counters"].get("scan_seconds"):
            out["throughput_GBps"] = round(self.gbps(), 3)
        return out

    def dump(self) -> str:
        return json.dumps(self.snapshot(), indent=2, sort_keys=True)

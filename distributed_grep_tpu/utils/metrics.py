"""Per-job metrics + the service-wide typed-instrument tier.

The reference has no metrics at all (SURVEY.md §5).  The north-star target
(>=10 GB/s/chip) makes throughput accounting first-class: every scan records
bytes + seconds, every task records its assign->data-ready->compute->commit
phases, and the job dumps one dict at completion (``Metrics`` below — one
instance per coordinator/worker, shipped on the heartbeat piggyback).

Round 15 adds the *process-global* half: typed instruments —
``MetricCounter`` / ``Gauge`` / ``Histogram`` (fixed log-spaced buckets) —
in a named ``MetricsRegistry`` rendered as Prometheus text exposition
(``GET /metrics`` on the service daemon and the one-shot coordinator).
Every exported series name is declared ONCE in ``SERIES`` (the env-knobs
registry pattern; analyze rule ``metrics-registry`` flags undeclared,
kind-mismatched, and stale names).  Instruments are lock-light (one leaf
lock each, built via lockdep.make_lock) and the registry answers
never-touched renders lock-free per instrument (the CorpusCache
``_touched`` convention).  ``RateWindow``/``CounterDeltaTracker`` turn the
monotonic cache counters the workers already piggyback into
rolling-window rates — the live scale/health signal lifetime totals
cannot give.
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time
from collections import defaultdict, deque
from contextlib import contextmanager
from dataclasses import dataclass, field

from distributed_grep_tpu.utils import lockdep


def _metrics_lock():
    return lockdep.make_lock("metrics")


@dataclass
class Metrics:
    """Thread-safe counters + timers; one instance per coordinator/worker."""

    counters: dict[str, float] = field(default_factory=lambda: defaultdict(float))
    timings: dict[str, list[float]] = field(default_factory=lambda: defaultdict(list))
    _lock: object = field(default_factory=_metrics_lock, repr=False)

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self.counters[name] += value

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            self.timings[name].append(seconds)

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    def record_scan(self, n_bytes: int, seconds: float) -> None:
        """Throughput accounting for the north-star GB/s metric."""
        with self._lock:
            self.counters["bytes_scanned"] += n_bytes
            self.counters["scan_seconds"] += seconds

    def gbps(self) -> float:
        secs = self.counters.get("scan_seconds", 0.0)
        return (self.counters.get("bytes_scanned", 0.0) / 1e9 / secs) if secs else 0.0

    def piggyback(self) -> dict:
        """Compact counters snapshot for the heartbeat span-pipeline
        piggyback (runtime/rpc.py): every counter plus the computed gbps
        headline — small enough to ship on each stamp, rich enough for
        GET /status per-worker aggregates."""
        with self._lock:
            out = dict(self.counters)
        if out.get("scan_seconds"):
            # 6 digits: tiny jobs (a few KB) must not round to 0.0
            out["gbps"] = round(self.gbps(), 6)
        return out

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "counters": dict(self.counters),
                "timings": {
                    k: {
                        "count": len(v),
                        "total_s": sum(v),
                        "mean_s": sum(v) / len(v),
                        "max_s": max(v),
                    }
                    for k, v in self.timings.items()
                    if v
                },
            }
        if out["counters"].get("scan_seconds"):
            out["throughput_GBps"] = round(self.gbps(), 3)
        return out

    def dump(self) -> str:
        return json.dumps(self.snapshot(), indent=2, sort_keys=True)


# ======================================================================
# Typed process-global instruments (round 15)
# ======================================================================

# One stable per-process token, piggybacked (spans-on only) alongside the
# engine-cache counters so the service-side delta tracker can attribute
# monotonic counter streams to their SOURCE PROCESS: N in-process worker
# loops share one process's module-global cache counters — summing their
# per-worker-id deltas would multiply every hit by N — and a worker
# reconnecting across a daemon restart gets a FRESH service-allocated id
# while its counters keep counting, which an id-keyed tracker would
# re-baseline as brand-new activity.  A random 48-bit int is exact in a
# float (the piggyback metrics dict is float-valued on the wire).
PROC_TOKEN: float = float(int.from_bytes(os.urandom(6), "big"))

# Fixed log-spaced (x4) latency buckets, 1 ms .. ~262 s: queue waits,
# assign polls, task walls, and whole-job latencies all land inside.
# Literal floats (not computed) so bucket labels render byte-stable.
DEFAULT_BUCKETS: tuple[float, ...] = (
    0.001, 0.004, 0.016, 0.064, 0.256, 1.024,
    4.096, 16.384, 65.536, 262.144,
)

DEFAULT_WINDOW_S = 300.0
_WINDOW_GRANULARITY_S = 10.0


def env_metrics_window_s(default: float = DEFAULT_WINDOW_S) -> float:
    """Rolling-rate window width — the ONE parser of
    DGREP_METRICS_WINDOW_S (malformed or <= 0 keeps the default, the
    env_batch_bytes shrug-off policy)."""
    raw = os.environ.get("DGREP_METRICS_WINDOW_S")
    if raw is None or raw == "":
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v > 0 else default


# The exported-series registry — the metrics twin of analysis/knobs.KNOBS:
# every series name a `counter()`/`gauge()`/`histogram()` call site may
# create, declared exactly once with its kind and help line.  The analyze
# rule `metrics-registry` walks call sites against this table (undeclared
# and kind-mismatched creations flagged; a declared name no call site
# creates is stale).  Doubles as the /metrics HELP text.
SERIES: dict[str, tuple[str, str]] = {
    # job lifecycle (runtime/service.py)
    "dgrep_jobs_submitted_total": ("counter", "Jobs admitted by submit()."),
    "dgrep_jobs_rejected_total": (
        "counter", "Submits rejected by admission control (429s)."),
    "dgrep_jobs_done_total": ("counter", "Jobs finished successfully."),
    "dgrep_jobs_failed_total": ("counter", "Jobs that ended FAILED."),
    "dgrep_jobs_cancelled_total": ("counter", "Jobs that ended CANCELLED."),
    "dgrep_queue_wait_seconds": (
        "histogram", "Submit-to-start queue wait per job."),
    "dgrep_job_run_seconds": (
        "histogram", "Start-to-finish wall per job."),
    "dgrep_job_e2e_seconds": (
        "histogram", "Submit-to-finish end-to-end latency per job."),
    "dgrep_finalize_seconds": (
        "histogram", "Output-listing finalize wall per job."),
    # scheduling (runtime/scheduler.py + the service assign loop)
    "dgrep_assign_poll_seconds": (
        "histogram", "AssignTask long-poll wall until an answer."),
    "dgrep_map_phase_seconds": (
        "histogram", "Scheduler construction to last map commit."),
    "dgrep_reduce_phase_seconds": (
        "histogram", "Map-phase completion to last reduce commit."),
    "dgrep_tasks_requeued_total": (
        "counter", "Tasks re-enqueued by the timeout sweeper."),
    "dgrep_workers_quarantined_total": (
        "counter", "Quarantine episodes entered (WorkerHealth)."),
    # worker task walls (runtime/worker.py; in-process workers land in the
    # daemon's registry, remote workers in their own process's /metrics)
    "dgrep_map_task_seconds": ("histogram", "Whole map-attempt wall."),
    "dgrep_reduce_task_seconds": ("histogram", "Whole reduce-attempt wall."),
    # live scale signal (set at scrape from service state)
    "dgrep_queue_depth": ("gauge", "Jobs queued, awaiting a running slot."),
    "dgrep_jobs_running": ("gauge", "Jobs currently running."),
    "dgrep_workers_attached": ("gauge", "Worker rows in the service table."),
    # peer-to-peer shuffle (round 16, runtime/peer.py): intermediate
    # bytes that transited the DAEMON's data plane — ~0 with peer
    # shuffle on (reducers fetch directly from producers)
    "dgrep_daemon_shuffle_bytes": (
        "gauge", "Relay shuffle bytes through the daemon data plane."),
    # lifetime cache totals (set at scrape from the owning modules,
    # sys.modules-gated — a remote-worker daemon reports zeros)
    "dgrep_model_cache_hits": ("gauge", "Compiled-model cache hits, lifetime."),
    "dgrep_model_cache_misses": (
        "gauge", "Compiled-model cache misses, lifetime."),
    "dgrep_corpus_cache_hits": (
        "gauge", "Device corpus cache hits, lifetime."),
    "dgrep_corpus_cache_misses": (
        "gauge", "Device corpus cache misses, lifetime."),
    "dgrep_corpus_cache_bytes_resident": (
        "gauge", "Device-resident corpus cache bytes."),
    # rolling-window rates (CounterDeltaTracker over the piggybacked
    # counters; window width DGREP_METRICS_WINDOW_S)
    "dgrep_window_model_cache_hits": (
        "gauge", "Model cache hits in the rolling window."),
    "dgrep_window_model_cache_misses": (
        "gauge", "Model cache misses in the rolling window."),
    "dgrep_window_corpus_cache_hits": (
        "gauge", "Corpus cache hits in the rolling window."),
    "dgrep_window_corpus_cache_misses": (
        "gauge", "Corpus cache misses in the rolling window."),
    "dgrep_window_index_shards_pruned": (
        "gauge", "Shards index-pruned in the rolling window."),
    "dgrep_window_index_bytes_skipped": (
        "gauge", "Bytes index-skipped in the rolling window."),
    "dgrep_window_fused_queries": (
        "gauge", "Queries served by fused scans in the rolling window."),
    "dgrep_window_fusion_bytes_saved": (
        "gauge", "Bytes co-tenants did not re-scan in the rolling window."),
    "dgrep_model_cache_hit_ratio": (
        "gauge", "Windowed model-cache hit ratio (hits/(hits+misses))."),
    "dgrep_corpus_cache_hit_ratio": (
        "gauge", "Windowed corpus-cache hit ratio (hits/(hits+misses))."),
    # streaming tier (round 17, runtime/follow.py): set at scrape, and
    # only once the tier has activity — an untouched instrument never
    # renders, so follow-free daemons keep the round-15 exposition bytes
    "dgrep_follow_standing": (
        "gauge", "Standing follow queries currently running."),
    "dgrep_follow_wakes": (
        "gauge", "Follow wakes that scanned appended data, lifetime."),
    "dgrep_follow_suffix_bytes": (
        "gauge", "Appended bytes suffix-scanned by standing queries."),
    "dgrep_stream_dropped_records": (
        "gauge", "Stream records shed oldest-first by bounded buffers."),
    # fleet timeline / HA SLOs (round 19): created LAZILY at their event
    # sites (string-constant names — the metrics-registry rule reads
    # them lexically), so non-HA deployments never render them and the
    # round-15 golden /metrics bytes hold
    "dgrep_daemon_failover_seconds": (
        "histogram", "Lease-stale detection to promoted-and-serving wall."),
    "dgrep_daemon_role": (
        "gauge", "Lease role of this daemon: 1 active, 0 deposed."),
    "dgrep_scale_actions_total": (
        "counter", "Elastic pool grow/drain actions applied."),
    "dgrep_maps_lost_output_total": (
        "counter", "Map tasks revoked after a lost peer shuffle output."),
    # query-result cache (round 20, runtime/result_cache.py): created
    # LAZILY at the planning event site (_stamp_result_plan, string-
    # constant names) — a daemon that never hits the tier never renders
    # them and the round-15 golden /metrics bytes hold
    "dgrep_result_hits_total": (
        "counter", "Jobs answered wholly from the result cache."),
    "dgrep_result_partial_hits_total": (
        "counter", "Jobs answered by incremental re-query (partial hit)."),
    "dgrep_result_splits_reused_total": (
        "counter", "Map splits served from stored results, no scan."),
    "dgrep_result_bytes_unscanned_total": (
        "counter", "Input bytes the result cache kept unscanned."),
}


def _fmt(v: float) -> str:
    """Deterministic Prometheus sample rendering: integral values as
    integers, everything else via repr (shortest round-trip — stable for
    a given value, no locale, no trailing-zero drift)."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class MetricCounter:
    """Monotonic counter.  One leaf lock; never-touched reads are
    lock-free (the `_touched` convention — render skips the lock when
    nothing was ever recorded)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = lockdep.make_lock("metric-series")
        self._v = 0.0
        self._touched = False

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self._v += v
            self._touched = True

    def value(self) -> float:
        if not self._touched:
            return 0.0
        with self._lock:
            return self._v

    def reset(self) -> None:
        with self._lock:
            self._v = 0.0
            self._touched = False

    def render(self) -> list[str]:
        return [f"{self.name} {_fmt(self.value())}"]


class Gauge(MetricCounter):
    """Point-in-time value; set() replaces, inc() adjusts."""

    kind = "gauge"

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)
            self._touched = True


class Histogram:
    """Fixed-bucket histogram (log-spaced defaults).  Cumulative bucket
    counts follow the Prometheus exposition contract; `quantile()` gives
    the /status p50/p95 summary by linear interpolation inside the
    landing bucket."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets))
        self._lock = lockdep.make_lock("metric-series")
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._touched = False

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            self._touched = True

    def snapshot(self) -> tuple[list[int], float, int]:
        if not self._touched:
            return [0] * (len(self.buckets) + 1), 0.0, 0
        with self._lock:
            return list(self._counts), self._sum, self._count

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0
            self._touched = False

    def quantile(self, q: float) -> float | None:
        """Estimated q-quantile (0..1), or None when empty.  Linear
        interpolation between the landing bucket's edges; observations
        past the last finite edge clamp to it (the Prometheus
        histogram_quantile convention)."""
        counts, _sum, count = self.snapshot()
        if count == 0:
            return None
        target = q * count
        cum = 0.0
        for i, c in enumerate(counts):
            cum += c
            if cum >= target and c:
                if i >= len(self.buckets):
                    return self.buckets[-1]
                lo = 0.0 if i == 0 else self.buckets[i - 1]
                hi = self.buckets[i]
                frac = (target - (cum - c)) / c
                return lo + (hi - lo) * frac
        return self.buckets[-1]

    def render(self) -> list[str]:
        counts, total, count = self.snapshot()
        out: list[str] = []
        cum = 0
        for edge, c in zip(self.buckets, counts):
            cum += c
            out.append(
                f'{self.name}_bucket{{le="{_fmt(edge)}"}} {cum}'
            )
        cum += counts[-1]
        out.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
        out.append(f"{self.name}_sum {_fmt(total)}")
        out.append(f"{self.name}_count {count}")
        return out


_KINDS = {"counter": MetricCounter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Named instrument registry.  Instruments are created on first
    access (kind checked against the declaration table) and live for the
    process; `render()` is the byte-stable Prometheus text exposition
    (series sorted by name, sort order and float formatting fixed — the
    analyze --sarif determinism contract, golden-tested)."""

    def __init__(self, series: dict[str, tuple[str, str]] | None = None):
        self._lock = lockdep.make_lock("metric-registry")
        self._instruments: dict[str, object] = {}
        self._series = SERIES if series is None else series

    def _get(self, name: str, kind: str):
        inst = self._instruments.get(name)
        if inst is not None:
            if inst.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {inst.kind}, "
                    f"requested {kind}"
                )
            return inst
        decl = self._series.get(name)
        if decl is not None and decl[0] != kind:
            raise ValueError(
                f"metric {name!r} is declared {decl[0]} in SERIES, "
                f"requested {kind}"
            )
        help_line = decl[1] if decl is not None else ""
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = _KINDS[kind](
                    name, help=help_line
                )
        return inst

    def counter(self, name: str) -> MetricCounter:
        return self._get(name, "counter")

    def gauge(self, name: str) -> Gauge:
        return self._get(name, "gauge")

    def histogram(self, name: str) -> Histogram:
        return self._get(name, "histogram")

    def render(self) -> str:
        with self._lock:
            insts = sorted(self._instruments.items())
        lines: list[str] = []
        for name, inst in insts:
            if inst.help:
                lines.append(f"# HELP {name} {inst.help}")
            lines.append(f"# TYPE {name} {inst.kind}")
            lines.extend(inst.render())
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        """Zero every instrument IN PLACE (test isolation): module-level
        instrument references stay valid — dropping them instead would
        silently detach callers from the rendered registry."""
        with self._lock:
            insts = list(self._instruments.values())
        for inst in insts:
            inst.reset()


_registry = MetricsRegistry()


def metrics_registry() -> MetricsRegistry:
    return _registry


def counter(name: str) -> MetricCounter:
    return _registry.counter(name)


def gauge(name: str) -> Gauge:
    return _registry.gauge(name)


def histogram(name: str) -> Histogram:
    return _registry.histogram(name)


def render_prometheus() -> str:
    """The default registry as Prometheus text exposition."""
    return _registry.render()


def metrics_reset() -> None:
    """Zero the default registry (conftest per-test isolation)."""
    _registry.reset()


# ------------------------------------------------- rolling-window rates
class RateWindow:
    """Per-key rolling sums over coarse time buckets: add() folds a delta
    into the current bucket, total() sums the buckets still inside the
    window.  O(window/granularity) state per key; expired buckets drop on
    the next touch."""

    def __init__(self, window_s: float | None = None,
                 granularity_s: float = _WINDOW_GRANULARITY_S):
        self.window_s = (
            env_metrics_window_s() if window_s is None else float(window_s)
        )
        self.granularity_s = granularity_s
        self._lock = lockdep.make_lock("metric-window")
        self._buckets: dict[str, deque] = {}

    def _bucket(self, now: float) -> float:
        return now - (now % self.granularity_s)

    def add(self, key: str, v: float, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        b = self._bucket(now)
        with self._lock:
            dq = self._buckets.setdefault(key, deque())
            if dq and dq[-1][0] == b:
                dq[-1][1] += v
            else:
                dq.append([b, v])
            floor = now - self.window_s
            while dq and dq[0][0] < floor:
                dq.popleft()

    def total(self, key: str, now: float | None = None) -> float:
        now = time.monotonic() if now is None else now
        floor = now - self.window_s
        with self._lock:
            dq = self._buckets.get(key)
            if not dq:
                return 0.0
            while dq and dq[0][0] < floor:
                dq.popleft()
            return float(sum(v for _, v in dq))

    def reset(self) -> None:
        with self._lock:
            self._buckets.clear()


class CounterDeltaTracker:
    """Monotonic counter streams -> windowed deltas, per SOURCE process.

    Sources report lifetime totals (the engine-cache counters on the
    heartbeat piggyback); the tracker keeps the HIGHEST-seen total per
    (source, name) and folds only the POSITIVE INCREASE into the rolling
    window.  The first report from a source is a BASELINE (delta 0) —
    a worker reconnecting under a fresh service-allocated id, or a daemon
    restart observing a long-lived worker, must not re-count history as
    fresh activity.  A report BELOW the baseline is ignored (the
    baseline is a running max): same-token sources are same-process by
    construction, so a lower reading can only be a stale/out-of-order
    snapshot — two worker loops' heartbeats, or a /metrics scrape racing
    a heartbeat — and lowering the baseline would re-count the gap on
    the next report (double-count); the cost is an undercount on the
    never-observed genuine-reset-behind-a-reused-key case, which is the
    safe direction.  Keying by the worker's PROC_TOKEN (not its service
    id) keeps N same-process worker loops — which all report the SAME
    module-global counters — counted once.  Bounded: least-recently-seen
    sources pruned past MAX_SOURCES.
    """

    MAX_SOURCES = 1024

    def __init__(self, names: tuple[str, ...],
                 window_s: float | None = None):
        self.names = tuple(names)
        self.window = RateWindow(window_s=window_s)
        self._lock = lockdep.make_lock("metric-deltas")
        self._last: dict[object, dict[str, float]] = {}
        self._seen: dict[object, float] = {}

    def observe(self, source: object, counters: dict,
                now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        deltas: list[tuple[str, float]] = []
        with self._lock:
            prev = self._last.get(source)
            fresh = prev is None
            if fresh:
                prev = self._last[source] = {}
            self._seen[source] = now
            for name in self.names:
                cur = counters.get(name)
                if cur is None:
                    continue
                cur = float(cur)
                last = prev.get(name)
                if last is None:
                    prev[name] = cur  # baseline
                elif cur > last:
                    prev[name] = cur
                    deltas.append((name, cur - last))
                # cur <= last: stale/out-of-order snapshot — keep the
                # running-max baseline (see the class docstring)
            if len(self._last) > self.MAX_SOURCES:
                for src in sorted(self._seen, key=self._seen.get)[
                    : len(self._last) - self.MAX_SOURCES
                ]:
                    self._last.pop(src, None)
                    self._seen.pop(src, None)
        for name, d in deltas:
            self.window.add(name, d, now=now)

    def window_totals(self, now: float | None = None) -> dict[str, float]:
        return {
            name: self.window.total(name, now=now) for name in self.names
        }

    def reset(self) -> None:
        with self._lock:
            self._last.clear()
            self._seen.clear()
        self.window.reset()

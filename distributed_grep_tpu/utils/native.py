"""ctypes bindings to libdgrep (native/dgrep.cpp), with Python fallbacks.

Builds the shared library on demand via ``make -C native`` when a compiler
is available; otherwise every entry point degrades to a pure-Python/numpy
implementation with identical semantics, so the framework never hard-depends
on the toolchain.  The FNV-32a hash matches the reference's ``ihash``
(map_reduce/worker.go:13-17) bit-for-bit — intermediate partition layout is
therefore compatible across the native and fallback paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parents[2] / "native"
_LIB_PATH = _NATIVE_DIR / "libdgrep.so"

_lib: ctypes.CDLL | None = None
_load_attempted = False


def _try_load() -> ctypes.CDLL | None:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    override = os.environ.get("DGREP_NATIVE_LIB")
    if override:
        # Explicit build selection (sanitizer builds: libdgrep-asan.so /
        # libdgrep-tsan.so from `make -C native sanitize|tsan`).  No make,
        # no staleness check — and a load failure RAISES, on this call and
        # every later one (_load_attempted stays False): a test that asked
        # for the ASan build must never silently run the Python fallbacks.
        lib = ctypes.CDLL(override)
        _bind(lib)
        _lib = lib
        _load_attempted = True
        return _lib
    _load_attempted = True
    src = _NATIVE_DIR / "dgrep.cpp"
    makefile = _NATIVE_DIR / "Makefile"
    newer_than_lib = [
        p for p in (src, makefile)
        if p.exists() and _LIB_PATH.exists()
        and p.stat().st_mtime > _LIB_PATH.stat().st_mtime
    ]
    stale = not _LIB_PATH.exists() or bool(newer_than_lib)
    if stale:
        try:
            subprocess.run(
                ["make", "-C", str(_NATIVE_DIR)],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except (subprocess.SubprocessError, OSError):
            if not _LIB_PATH.exists():
                return None  # a stale lib still loads; no lib does not
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError:
        return None
    _bind(lib)
    _lib = lib
    return _lib


def _bind(lib: ctypes.CDLL) -> None:
    lib.dgrep_fnv32a.restype = ctypes.c_uint32
    lib.dgrep_fnv32a.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.dgrep_newline_index.restype = ctypes.c_size_t
    lib.dgrep_newline_index.argtypes = [
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_size_t,
    ]
    lib.dgrep_literal_scan.restype = ctypes.c_size_t
    lib.dgrep_literal_scan.argtypes = [
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_size_t,
    ]
    lib.dgrep_dfa_scan.restype = ctypes.c_size_t
    lib.dgrep_dfa_scan.argtypes = [
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint16),
        ctypes.c_char_p,
        ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint32),
    ]
    if hasattr(lib, "dgrep_dfa_scan_mt"):
        lib.dgrep_dfa_scan_mt.restype = ctypes.c_size_t
        lib.dgrep_dfa_scan_mt.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint16),
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_size_t,
            ctypes.c_uint32,
        ]
    if hasattr(lib, "dgrep_gather_ranges"):
        lib.dgrep_gather_ranges.restype = None
        lib.dgrep_gather_ranges.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.dgrep_format_batch.restype = ctypes.c_int64
        lib.dgrep_format_batch.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_uint8,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t,
        ]
        lib.dgrep_merge_display.restype = ctypes.c_int64
        lib.dgrep_merge_display.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint8),
        ]
    if hasattr(lib, "dgrep_build_records"):
        lib.dgrep_unique_lines.restype = ctypes.c_int64
        lib.dgrep_unique_lines.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.dgrep_line_spans.restype = None
        lib.dgrep_line_spans.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.dgrep_build_records.restype = ctypes.c_int64
        lib.dgrep_build_records.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
    if hasattr(lib, "dgrep_confirm_build"):
        lib.dgrep_confirm_build.restype = ctypes.c_void_p
        lib.dgrep_confirm_build.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint32,
            ctypes.c_int,
        ]
        lib.dgrep_confirm_free.restype = None
        lib.dgrep_confirm_free.argtypes = [ctypes.c_void_p]
        lib.dgrep_confirm_scan.restype = None
        lib.dgrep_confirm_scan.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint32,
        ]
    if hasattr(lib, "dgrep_trigram_summary"):
        lib.dgrep_trigram_summary.restype = None
        lib.dgrep_trigram_summary.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t,
        ]


def native_available() -> bool:
    return _try_load() is not None


# --- FNV-32a partition hash (reference ihash, worker.go:13-17) -------------

def fnv32a(key: str | bytes) -> int:
    # surrogateescape: keys embed filenames whose non-UTF8 bytes arrive as
    # lone surrogates — hash the original bytes, don't crash
    data = key.encode("utf-8", "surrogateescape") if isinstance(key, str) else key
    lib = _try_load()
    if lib is not None:
        return lib.dgrep_fnv32a(data, len(data))
    h = 2166136261
    for b in data:
        h ^= b
        h = (h * 16777619) & 0xFFFFFFFF
    return h & 0x7FFFFFFF


def partition(key: str | bytes, n_reduce: int) -> int:
    """ihash(key) % nReduce — the shuffle partitioning (worker.go:89)."""
    return fnv32a(key) % n_reduce


# --- Newline index ---------------------------------------------------------

def newline_index(data: bytes) -> np.ndarray:
    """Byte offsets of every newline, as uint64."""
    lib = _try_load()
    if lib is None:
        return np.flatnonzero(np.frombuffer(data, dtype=np.uint8) == 0x0A).astype(np.uint64)
    cap = max(1024, len(data) // 16)
    while True:
        # np.empty, not a ctypes array: (c_uint64 * cap)() ZEROES the
        # buffer — measured as the wrapper's single biggest cost on a
        # dense 64 MB input (25 ms of memset vs a 30 ms AVX2 scan)
        buf = np.empty(cap, dtype=np.uint64)
        n = lib.dgrep_newline_index(
            data, len(data),
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), cap,
        )
        if n <= cap:
            return buf[:n].copy()
        cap = n


# --- Literal scan (CPU engine / oracle) ------------------------------------

def literal_scan(haystack: bytes, needle: bytes) -> np.ndarray:
    """End offsets (last byte + 1) of all (overlapping) occurrences."""
    if not needle:
        return np.zeros(0, dtype=np.uint64)
    lib = _try_load()
    if lib is None:
        out = []
        start = 0
        while True:
            i = haystack.find(needle, start)
            if i < 0:
                break
            out.append(i + len(needle))
            start = i + 1
        return np.asarray(out, dtype=np.uint64)
    # size the first buffer off the data (one match per ~64 bytes): a
    # match-dense corpus must not pay a SECOND full scan just to learn
    # the count (the old fixed 4096 cap re-ran the whole 64 MB receipt)
    cap = max(4096, len(haystack) >> 6)
    while True:
        buf = np.empty(cap, dtype=np.uint64)
        n = lib.dgrep_literal_scan(
            haystack, len(haystack), needle, len(needle),
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), cap,
        )
        if n <= cap:
            return buf[:n].copy()
        cap = n


# --- DFA scan (CPU engine / oracle for the Pallas kernel) ------------------

def dfa_scan(
    data: bytes,
    table: np.ndarray,  # [n_states, 256] uint16 (or int) next-state table
    accept: np.ndarray,  # [n_states] bool/uint8
    start_state: int = 0,
) -> tuple[np.ndarray, int]:
    """Feed every byte through the DFA; return (accept end-offsets, final state)."""
    table = np.ascontiguousarray(table, dtype=np.uint16)
    accept_u8 = np.ascontiguousarray(accept, dtype=np.uint8)
    lib = _try_load()
    if lib is None:
        tbl = table
        s = start_state
        out = []
        for i, b in enumerate(data):
            s = int(tbl[s, b])
            if accept_u8[s]:
                out.append(i + 1)
        return np.asarray(out, dtype=np.uint64), s
    final = ctypes.c_uint32(0)
    cap = 4096
    while True:
        buf = (ctypes.c_uint64 * cap)()
        n = lib.dgrep_dfa_scan(
            data,
            len(data),
            table.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            accept_u8.tobytes(),
            start_state,
            buf,
            cap,
            ctypes.byref(final),
        )
        if n <= cap:
            return np.ctypeslib.as_array(buf)[:n].copy(), int(final.value)
        cap = n


# --- Literal-set candidate confirm (FDR filter path, models/fdr.py) --------

class ConfirmSet:
    """Batch-confirm FDR candidate end-offsets against a literal set.

    Native path: an L1-resident bloom bitmap rejects absent last-4-byte
    keys, survivors take a hash-table probe + full memcmp
    (native/dgrep.cpp dgrep_confirm_*, ~4 ns/candidate on random offsets,
    ~8.6 ns on FDR-biased candidates) — the cost that lets the FDR tuner run a cheaper device
    filter and accept more candidates (models/fdr.py
    CONFIRM_PS_PER_CANDIDATE).  Fallback: a dict keyed the same way.

    ``patterns`` must be pre-normalized (lowercased when ignore_case);
    ``ignore_case`` controls folding of the *data* bytes at probe time.
    """

    def __init__(self, patterns: list[bytes], ignore_case: bool = False,
                 use_native: bool = True):
        self.ignore_case = bool(ignore_case)
        self._patterns = [bytes(p) for p in patterns]
        self._handle = None
        lib = _try_load() if use_native else None
        self._free = None
        if lib is not None and hasattr(lib, "dgrep_confirm_build"):
            blob = b"".join(self._patterns)
            offs = np.zeros(len(self._patterns) + 1, dtype=np.uint32)
            np.cumsum([len(p) for p in self._patterns], out=offs[1:])
            self._offs = offs  # keep alive
            self._blob = blob
            self._handle = lib.dgrep_confirm_build(
                blob,
                offs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                len(self._patterns),
                1 if ignore_case else 0,
            )
            self._free = lib.dgrep_confirm_free  # bound now: survives shutdown
        if self._handle is None:
            by_key: dict[bytes, list[bytes]] = {}
            shorts: list[bytes] = []
            for p in self._patterns:
                if len(p) < 4:
                    shorts.append(p)
                else:
                    by_key.setdefault(p[-4:], []).append(p)
            self._by_key, self._shorts = by_key, shorts

    def __del__(self):
        if getattr(self, "_handle", None) and getattr(self, "_free", None):
            self._free(self._handle)
            self._handle = None

    def confirm(self, data: bytes, ends: np.ndarray,
                n_threads: int | None = None) -> np.ndarray:
        """Boolean mask: does some pattern truly end at each offset?"""
        ends = np.ascontiguousarray(ends, dtype=np.uint64)
        if ends.size == 0:
            return np.zeros(0, dtype=bool)
        if self._handle is not None:
            lib = _try_load()
            out = np.zeros(ends.size, dtype=np.uint8)
            lib.dgrep_confirm_scan(
                self._handle,
                data,
                len(data),
                ends.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                ends.size,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                n_threads if n_threads is not None else min(8, os.cpu_count() or 1),
            )
            return out.astype(bool)
        hay = data.lower() if self.ignore_case else data
        out_b = np.zeros(ends.size, dtype=bool)
        for i, e in enumerate(ends.tolist()):
            if e > len(hay) or e == 0:
                continue
            hit = False
            for p in self._by_key.get(hay[max(0, e - 4):e], ()):
                if e >= len(p) and hay[e - len(p):e] == p:
                    hit = True
                    break
            if not hit:
                for p in self._shorts:
                    if e >= len(p) and hay[e - len(p):e] == p:
                        hit = True
                        break
            out_b[i] = hit
        return out_b


# --- Columnar merge/print hot loops (round 6) ------------------------------

def gather_ranges_native(
    arr: np.ndarray, starts: np.ndarray, ends: np.ndarray,
    offsets: np.ndarray, total: int,
) -> bytes | None:
    """Native arr[starts[i]:ends[i]] concatenation (the LineBatch slab
    rebuild), or None when libdgrep is unavailable — the caller
    (runtime/columnar.gather_ranges) keeps the numpy fallback.  ``offsets``
    /``total`` are the caller's cumsum (it needs them anyway)."""
    lib = _try_load()
    if lib is None or not hasattr(lib, "dgrep_gather_ranges"):
        return None
    if arr.dtype != np.uint8 or arr.ndim != 1:
        return None  # starts/ends are ELEMENT indices; C indexes bytes
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    ends = np.ascontiguousarray(ends, dtype=np.int64)
    if not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    out = np.empty(int(total), dtype=np.uint8)
    lib.dgrep_gather_ranges(
        arr.ctypes.data_as(ctypes.c_char_p),
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        starts.size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return out.tobytes()


def merge_display_available() -> bool:
    """True when the native display merge exists — callers check BEFORE
    materializing file contents, so a no-native install doesn't read the
    whole output set just to learn it must stream instead."""
    lib = _try_load()
    return lib is not None and hasattr(lib, "dgrep_merge_display")


def format_batch(
    prefix: bytes, linenos: np.ndarray, offsets: np.ndarray, slab: bytes,
    sep: bytes = b"\t",
) -> bytes | None:
    """The mr-out text form of one LineBatch as BYTES —
    ``b"<prefix><N>)<sep><line>\\n"`` per record, byte-identical to
    ``LineBatch.format_lines`` encoded utf-8/surrogateescape.  None when
    libdgrep is unavailable OR the slab is not strictly valid UTF-8 (the
    Python path's utf-8/replace decode is then not the identity; caller
    falls back)."""
    lib = _try_load()
    if lib is None or not hasattr(lib, "dgrep_format_batch"):
        return None
    if len(sep) != 1:
        return None  # C writes exactly one sep byte; fall back otherwise
    n = int(linenos.size)
    if n == 0:
        return b""
    linenos = np.ascontiguousarray(linenos, dtype=np.int64)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    cap = n * (len(prefix) + 23) + len(slab)
    out = np.empty(cap, dtype=np.uint8)
    wrote = lib.dgrep_format_batch(
        prefix,
        len(prefix),
        linenos.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        slab,
        n,
        sep[0],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        cap,
    )
    if wrote < 0:
        return None  # -2: slab needs utf-8/replace; -1: cannot happen (cap)
    return out[:wrote].tobytes()


def merge_display(bufs: list[bytes]) -> bytes | None:
    """K-way merge of pre-sorted mr-out buffers into final display bytes
    (first '\\t' -> ' ' per record, '\\n'-terminated), ordered by
    (path, line) with paths compared as Python str (surrogateescape
    codepoints) and ties broken by buffer order — byte-identical to
    ``JobResult.iter_display_bytes_sorted``.  None when libdgrep is
    unavailable or any line is not grep-key-shaped (caller falls back)."""
    lib = _try_load()
    if lib is None or not hasattr(lib, "dgrep_merge_display"):
        return None
    data = b"".join(bufs)
    off = np.zeros(len(bufs) + 1, dtype=np.int64)
    np.cumsum([len(b) for b in bufs], out=off[1:])
    # + n_bufs: a buffer whose final line lacks '\n' gains one on output
    out = np.empty(max(1, len(data) + len(bufs)), dtype=np.uint8)
    wrote = lib.dgrep_merge_display(
        data,
        off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(bufs),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    if wrote < 0:
        return None
    return out[:wrote].tobytes()


# --- Native map-record pipeline (round 8) ----------------------------------
#
# One C pass from kernel output (matched line numbers + the newline index)
# to the partitioned per-reduce LineBatch arrays — replacing the numpy
# chain make_batch_from_lines -> partitions() -> per-partition select().
# Routed from runtime/columnar.py, which keeps bit-identical numpy
# fallbacks for every entry; DGREP_NATIVE_RECORDS=0 is the debug
# kill-switch (this module is the knob's single owner, analysis/knobs.py).

def env_native_records() -> bool:
    """False when DGREP_NATIVE_RECORDS disables the native record build
    (the numpy fallbacks then serve every call — byte-identical, slower)."""
    return os.environ.get("DGREP_NATIVE_RECORDS", "") not in ("0", "false")


def native_records_available() -> bool:
    """True when the one-pass record build can answer — callers that
    would otherwise pre-compute inputs just to feed it (DeferredBatch's
    span pass) check this FIRST so the fallback path does no wasted
    work."""
    lib = _try_load()
    return (lib is not None and hasattr(lib, "dgrep_build_records")
            and env_native_records())


def unique_lines_native(nl: np.ndarray, ends: np.ndarray) -> np.ndarray | None:
    """Unique 1-based line numbers of sorted match END offsets, or None
    when libdgrep is unavailable (caller keeps the searchsorted+unique
    fallback, ops/lines.unique_match_lines)."""
    lib = _try_load()
    if lib is None or not hasattr(lib, "dgrep_unique_lines"):
        return None
    if not env_native_records():
        return None
    nl = np.ascontiguousarray(nl, dtype=np.uint64)
    ends = np.ascontiguousarray(ends, dtype=np.int64)
    out = np.empty(ends.size, dtype=np.int64)
    n = lib.dgrep_unique_lines(
        nl.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        nl.size,
        ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ends.size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out[:n].copy()


def line_spans_native(
    nl: np.ndarray, linenos: np.ndarray, n_bytes: int
) -> tuple[np.ndarray, np.ndarray] | None:
    """[start, end) byte span per 1-based line (vectorized
    ops/lines.line_span), or None when libdgrep is unavailable."""
    lib = _try_load()
    if lib is None or not hasattr(lib, "dgrep_line_spans"):
        return None
    if not env_native_records():
        return None
    nl = np.ascontiguousarray(nl, dtype=np.uint64)
    linenos = np.ascontiguousarray(linenos, dtype=np.int64)
    starts = np.empty(linenos.size, dtype=np.int64)
    ends = np.empty(linenos.size, dtype=np.int64)
    lib.dgrep_line_spans(
        nl.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        nl.size,
        linenos.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        linenos.size,
        int(n_bytes),
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return starts, ends


def build_records(
    data: np.ndarray, starts: np.ndarray, ends: np.ndarray,
    linenos: np.ndarray, prefix: bytes, n_reduce: int,
) -> dict[int, tuple[np.ndarray, np.ndarray, bytes]] | None:
    """One-pass partitioned record build: line spans of ``data`` in,
    ``{partition: (stored linenos, offsets, slab bytes)}`` out — the
    grouped arrays of each partition's LineBatch, record order preserved
    inside each partition, partition assignment bit-identical to
    ``partition(f"{prefix}{lineno})")`` per record.  None when libdgrep
    is unavailable, DGREP_NATIVE_RECORDS disables it, or the inputs are
    not the grep shape (caller keeps the numpy split path)."""
    lib = _try_load()
    if lib is None or not hasattr(lib, "dgrep_build_records"):
        return None
    if not env_native_records():
        return None
    data = np.asarray(data)
    if data.dtype != np.uint8 or data.ndim != 1:
        return None  # spans are ELEMENT indices; C indexes bytes
    if not data.flags.c_contiguous:
        data = np.ascontiguousarray(data)
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    ends = np.ascontiguousarray(ends, dtype=np.int64)
    linenos = np.ascontiguousarray(linenos, dtype=np.int64)
    n = int(linenos.size)
    if n == 0:
        return {}
    total = int(np.sum(ends - starts))
    out_linenos = np.empty(n, dtype=np.int64)
    out_offsets = np.empty(n + 1, dtype=np.int64)
    out_slab = np.empty(max(1, total), dtype=np.uint8)
    counts = np.zeros(n_reduce, dtype=np.int64)
    nbytes = np.zeros(n_reduce, dtype=np.int64)
    wrote = lib.dgrep_build_records(
        data.ctypes.data_as(ctypes.c_char_p),
        data.size,
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        linenos.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        prefix,
        len(prefix),
        int(n_reduce),
        out_linenos.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        out_offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        out_slab.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        nbytes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if wrote < 0:
        return None  # malformed span: let the numpy path handle it
    out: dict[int, tuple[np.ndarray, np.ndarray, bytes]] = {}
    r0 = 0
    b0 = 0
    for p in range(int(n_reduce)):
        c = int(counts[p])
        nb = int(nbytes[p])
        if c:
            out[p] = (
                out_linenos[r0 : r0 + c].copy(),
                out_offsets[r0 : r0 + c + 1] - b0,
                out_slab[b0 : b0 + nb].tobytes(),
            )
        r0 += c
        b0 += nb
    return out


# Big inputs fan the DFA scan across threads; newline-aligned chunking keeps
# output byte-identical (every state's '\n' transition is the start state —
# the table invariant the device stripes rely on too).
MT_THRESHOLD_BYTES = 1 << 22


def dfa_scan_mt(
    data: bytes,
    table: np.ndarray,
    accept: np.ndarray,
    start_state: int = 0,
    n_threads: int | None = None,
) -> np.ndarray:
    """Multithreaded DFA scan (accept end-offsets only; no final state —
    chunked scans have no single sequential final state)."""
    lib = _try_load()
    if lib is None or not hasattr(lib, "dgrep_dfa_scan_mt"):
        offsets, _ = dfa_scan(data, table, accept, start_state)
        return offsets
    if n_threads is None:
        n_threads = min(8, os.cpu_count() or 1)
    table = np.ascontiguousarray(table, dtype=np.uint16)
    accept_bytes = np.ascontiguousarray(accept, dtype=np.uint8).tobytes()
    # this path only runs on multi-MB inputs: size the first buffer off the
    # data (one match per ~64 bytes) so a match-dense corpus doesn't pay a
    # second full scan just to learn the count
    cap = max(4096, len(data) >> 6)
    while True:
        buf = np.empty(cap, dtype=np.uint64)
        n = lib.dgrep_dfa_scan_mt(
            data,
            len(data),
            table.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            accept_bytes,
            start_state,
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            cap,
            n_threads,
        )
        if n <= cap:
            return buf[:n].copy()
        cap = n


# --- Trigram shard summaries (shard-index tier) ----------------------------
#
# dgrep_trigram_summary ORs the case-folded trigram bloom of `data` into a
# caller-owned byte array — the native build half of the shard index
# (distributed_grep_tpu/index/summary.py owns the format, the bit-identical
# numpy fallback, and the DGREP_INDEX* knobs).  `bloom.size` must be a
# power of two (summary.py enforces it); returns False when libdgrep (or a
# pre-index build of it) is unavailable, and the caller falls back.

def trigram_summary_available() -> bool:
    lib = _try_load()
    return lib is not None and hasattr(lib, "dgrep_trigram_summary")


def trigram_summary_into(data: bytes, bloom: np.ndarray) -> bool:
    """OR `data`'s folded trigram bits into `bloom` (uint8, C-contiguous,
    power-of-two size) via the native pass; False = not available (the
    caller runs the numpy fallback — identical bits)."""
    lib = _try_load()
    if lib is None or not hasattr(lib, "dgrep_trigram_summary"):
        return False
    assert bloom.dtype == np.uint8 and bloom.flags["C_CONTIGUOUS"]
    lib.dgrep_trigram_summary(
        data, len(data),
        bloom.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), bloom.size,
    )
    return True

"""ctypes bindings to libdgrep (native/dgrep.cpp), with Python fallbacks.

Builds the shared library on demand via ``make -C native`` when a compiler
is available; otherwise every entry point degrades to a pure-Python/numpy
implementation with identical semantics, so the framework never hard-depends
on the toolchain.  The FNV-32a hash matches the reference's ``ihash``
(map_reduce/worker.go:13-17) bit-for-bit — intermediate partition layout is
therefore compatible across the native and fallback paths.
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parents[2] / "native"
_LIB_PATH = _NATIVE_DIR / "libdgrep.so"

_lib: ctypes.CDLL | None = None
_load_attempted = False


def _try_load() -> ctypes.CDLL | None:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    if not _LIB_PATH.exists():
        try:
            subprocess.run(
                ["make", "-C", str(_NATIVE_DIR)],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except (subprocess.SubprocessError, OSError):
            return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError:
        return None

    lib.dgrep_fnv32a.restype = ctypes.c_uint32
    lib.dgrep_fnv32a.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.dgrep_newline_index.restype = ctypes.c_size_t
    lib.dgrep_newline_index.argtypes = [
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_size_t,
    ]
    lib.dgrep_literal_scan.restype = ctypes.c_size_t
    lib.dgrep_literal_scan.argtypes = [
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_size_t,
    ]
    lib.dgrep_dfa_scan.restype = ctypes.c_size_t
    lib.dgrep_dfa_scan.argtypes = [
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint16),
        ctypes.c_char_p,
        ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint32),
    ]
    if hasattr(lib, "dgrep_dfa_scan_mt"):
        lib.dgrep_dfa_scan_mt.restype = ctypes.c_size_t
        lib.dgrep_dfa_scan_mt.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint16),
            ctypes.c_char_p,
            ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_size_t,
            ctypes.c_uint32,
        ]
    _lib = lib
    return _lib


def native_available() -> bool:
    return _try_load() is not None


# --- FNV-32a partition hash (reference ihash, worker.go:13-17) -------------

def fnv32a(key: str | bytes) -> int:
    data = key.encode("utf-8") if isinstance(key, str) else key
    lib = _try_load()
    if lib is not None:
        return lib.dgrep_fnv32a(data, len(data))
    h = 2166136261
    for b in data:
        h ^= b
        h = (h * 16777619) & 0xFFFFFFFF
    return h & 0x7FFFFFFF


def partition(key: str | bytes, n_reduce: int) -> int:
    """ihash(key) % nReduce — the shuffle partitioning (worker.go:89)."""
    return fnv32a(key) % n_reduce


# --- Newline index ---------------------------------------------------------

def newline_index(data: bytes) -> np.ndarray:
    """Byte offsets of every newline, as uint64."""
    lib = _try_load()
    if lib is None:
        return np.flatnonzero(np.frombuffer(data, dtype=np.uint8) == 0x0A).astype(np.uint64)
    cap = max(1024, len(data) // 16)
    while True:
        buf = (ctypes.c_uint64 * cap)()
        n = lib.dgrep_newline_index(data, len(data), buf, cap)
        if n <= cap:
            return np.ctypeslib.as_array(buf)[:n].copy()
        cap = n


# --- Literal scan (CPU engine / oracle) ------------------------------------

def literal_scan(haystack: bytes, needle: bytes) -> np.ndarray:
    """End offsets (last byte + 1) of all (overlapping) occurrences."""
    if not needle:
        return np.zeros(0, dtype=np.uint64)
    lib = _try_load()
    if lib is None:
        out = []
        start = 0
        while True:
            i = haystack.find(needle, start)
            if i < 0:
                break
            out.append(i + len(needle))
            start = i + 1
        return np.asarray(out, dtype=np.uint64)
    cap = 4096
    while True:
        buf = (ctypes.c_uint64 * cap)()
        n = lib.dgrep_literal_scan(haystack, len(haystack), needle, len(needle), buf, cap)
        if n <= cap:
            return np.ctypeslib.as_array(buf)[:n].copy()
        cap = n


# --- DFA scan (CPU engine / oracle for the Pallas kernel) ------------------

def dfa_scan(
    data: bytes,
    table: np.ndarray,  # [n_states, 256] uint16 (or int) next-state table
    accept: np.ndarray,  # [n_states] bool/uint8
    start_state: int = 0,
) -> tuple[np.ndarray, int]:
    """Feed every byte through the DFA; return (accept end-offsets, final state)."""
    table = np.ascontiguousarray(table, dtype=np.uint16)
    accept_u8 = np.ascontiguousarray(accept, dtype=np.uint8)
    lib = _try_load()
    if lib is None:
        tbl = table
        s = start_state
        out = []
        for i, b in enumerate(data):
            s = int(tbl[s, b])
            if accept_u8[s]:
                out.append(i + 1)
        return np.asarray(out, dtype=np.uint64), s
    final = ctypes.c_uint32(0)
    cap = 4096
    while True:
        buf = (ctypes.c_uint64 * cap)()
        n = lib.dgrep_dfa_scan(
            data,
            len(data),
            table.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            accept_u8.tobytes(),
            start_state,
            buf,
            cap,
            ctypes.byref(final),
        )
        if n <= cap:
            return np.ctypeslib.as_array(buf)[:n].copy(), int(final.value)
        cap = n


# Big inputs fan the DFA scan across threads; newline-aligned chunking keeps
# output byte-identical (every state's '\n' transition is the start state —
# the table invariant the device stripes rely on too).
MT_THRESHOLD_BYTES = 1 << 22


def dfa_scan_mt(
    data: bytes,
    table: np.ndarray,
    accept: np.ndarray,
    start_state: int = 0,
    n_threads: int | None = None,
) -> np.ndarray:
    """Multithreaded DFA scan (accept end-offsets only; no final state —
    chunked scans have no single sequential final state)."""
    import os

    lib = _try_load()
    if lib is None or not hasattr(lib, "dgrep_dfa_scan_mt"):
        offsets, _ = dfa_scan(data, table, accept, start_state)
        return offsets
    if n_threads is None:
        n_threads = min(8, os.cpu_count() or 1)
    table = np.ascontiguousarray(table, dtype=np.uint16)
    accept_bytes = np.ascontiguousarray(accept, dtype=np.uint8).tobytes()
    # this path only runs on multi-MB inputs: size the first buffer off the
    # data (one match per ~64 bytes) so a match-dense corpus doesn't pay a
    # second full scan just to learn the count
    cap = max(4096, len(data) >> 6)
    while True:
        buf = (ctypes.c_uint64 * cap)()
        n = lib.dgrep_dfa_scan_mt(
            data,
            len(data),
            table.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            accept_bytes,
            start_state,
            buf,
            cap,
            n_threads,
        )
        if n <= cap:
            return np.ctypeslib.as_array(buf)[:n].copy()
        cap = n
